"""TransportSpec: the window/mailbox contract as checked data.

ROADMAP item 1 (tiered transports: device-resident windows, in-mesh
collectives) refactors against ONE seam — the window transport contract —
which until now existed only implicitly, re-derived in four modules
(``native/shm_native.py``, ``native/tcp_transport.py``,
``native/routed_transport.py``, ``sim/transport.py``).  This module makes
it explicit, three ways:

1. **Spec table** (:data:`TRANSPORT_SPEC`): every rule of the contract as
   a :class:`SpecRule` that *pins the constant it governs* — the
   protocol-step tuples, atomicity flags, ordering booleans, and chunk
   geometry in ``shm_native`` / ``tcp_transport``.  This generalizes the
   ad-hoc ``wire_rules.check_spec_parity``: a transport that drifts from
   the contract fails the pin, not a code review.

2. **Executable reference model** (:class:`ReferenceTransport`): the
   contract's observable semantics (slot lifecycle, atomic drain,
   commit-after-payload, epoch quiesce/re-seed, dead-writer drain,
   mass-ledger identity) as a tiny sequential implementation.  The
   conformance harness (``analysis/conformance.py``) drives every real
   transport and this model through identical op schedules and diffs
   observable state after every op.

3. **Capability lint** (``transport.caps-*`` rules): each transport
   declares a :class:`~bluefog_tpu.native.capabilities.TransportCaps`
   record; the lint verifies every declaration is honest against the
   class's actual surface, that composite (routed) capabilities are the
   meet of their legs, and that every adaptive call site — islands'
   scaled-deposit/fused-combine decisions, the progress engine's fusion
   gate, wire-dtype selection, TCP resume — branches on declared
   capabilities, never on transport class identity.

Registered family: ``transport`` (fast, host-only — a few ms).
"""

from __future__ import annotations

import dataclasses
import importlib
import inspect
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

from bluefog_tpu.analysis.engine import Finding, Severity, registry
from bluefog_tpu.native import capabilities as caps_mod
from bluefog_tpu.native.capabilities import CAP_FIELDS, TransportCaps

__all__ = [
    "Pin",
    "SpecRule",
    "TRANSPORT_SPEC",
    "ReferenceTransport",
    "evaluate_spec",
    "declared_transports",
    "check_caps_declared",
    "check_caps_honest",
    "check_caps_call_sites",
]

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# the spec table
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Pin:
    """One constant the contract pins: ``module.attr`` must equal
    ``expected`` (tuples compare exactly; booleans must be identical)."""

    module: str
    attr: str
    expected: object

    def problems(self) -> List[str]:
        try:
            mod = importlib.import_module(self.module)
        except Exception as exc:  # pragma: no cover - import breakage
            return [f"{self.module} failed to import: {exc!r}"]
        if not hasattr(mod, self.attr):
            return [f"{self.module}.{self.attr} is gone (spec pins it)"]
        actual = getattr(mod, self.attr)
        if actual != self.expected:
            return [
                f"{self.module}.{self.attr} = {actual!r} but the spec "
                f"pins {self.expected!r}"
            ]
        return []


@dataclasses.dataclass(frozen=True)
class SpecRule:
    """One rule of the transport contract.

    ``pins`` bind the rule to the constants that encode it in the real
    transports; ``check`` (optional) is an executable verification of the
    rule's semantics — usually against :class:`ReferenceTransport` or a
    live pure-Python surface — returning a list of problem strings."""

    name: str
    doc: str
    pins: Tuple[Pin, ...] = ()
    check: Optional[Callable[[], List[str]]] = None

    def problems(self) -> List[str]:
        out: List[str] = []
        for pin in self.pins:
            out.extend(pin.problems())
        if self.check is not None:
            try:
                out.extend(self.check())
            except Exception as exc:
                out.append(f"executable check raised {exc!r}")
        return out


_SHM = "bluefog_tpu.native.shm_native"
_TCP = "bluefog_tpu.native.tcp_transport"


def _check_drain_orders() -> List[str]:
    """mark_drained must precede the final teardown step in BOTH
    dead-writer drain recipes (a drain that clears the lock/stream before
    the marker exposes a torn payload to a racing reader)."""
    from bluefog_tpu.native import shm_native, tcp_transport

    out = []
    for label, steps, last in (
        ("shm", shm_native.DEAD_WRITER_DRAIN_STEPS, "clear_lock"),
        ("tcp", tcp_transport.TCP_DEAD_WRITER_DRAIN_STEPS, "clear_stream"),
    ):
        if "mark_drained" not in steps or last not in steps:
            out.append(f"{label} drain steps {steps!r} lost "
                       f"mark_drained/{last}")
            continue
        if steps.index("mark_drained") > steps.index(last):
            out.append(f"{label} drain marks drained AFTER {last}: {steps!r}")
    if shm_native.DEAD_WRITER_DRAIN_STEPS[0] != "evenize_chunk_seqs":
        out.append("shm drain must even-ize torn chunk seqlocks first, "
                   f"got {shm_native.DEAD_WRITER_DRAIN_STEPS!r}")
    return out


def _check_chunk_geometry() -> List[str]:
    """Both chunked transports must agree on the configured chunk size
    (a reader drains what a writer streamed — mismatched geometry tears
    the frontier invariant at the seam between tiers)."""
    from bluefog_tpu.native import shm_native, tcp_transport

    out = []
    shm_chunk = shm_native.chunk_bytes()
    tcp_chunk = tcp_transport._chunk_bytes()
    if shm_chunk != tcp_chunk:
        out.append(f"chunk geometry diverged: shm {shm_chunk} B vs "
                   f"tcp {tcp_chunk} B")
    if shm_native.pipeline_depth() < 1:
        out.append("pipeline depth < 1")
    return out


def _check_resume_replay_set() -> List[str]:
    """Session resume may replay only idempotent (read-only) ops — a
    replayed WRITE double-counts a deposit.  Chunked deposits are NOT in
    the set: their replay rule (safe up to the commit frame) lives in
    deposit_chunked itself."""
    from bluefog_tpu.native import tcp_transport as t

    out = []
    expected = frozenset({
        t._OP_READ_EXPOSED, t._OP_PING, t._OP_HEARTBEAT, t._OP_LIVENESS,
        t._OP_CLOCK, t._OP_EPOCH,
    })
    if t._IDEMPOTENT_OPS != expected:
        out.append(f"_IDEMPOTENT_OPS = {sorted(t._IDEMPOTENT_OPS)!r}, spec "
                   f"pins {sorted(expected)!r}")
    for op, label in ((t._OP_WRITE, "WRITE"), (t._OP_CHUNK, "CHUNK"),
                      (t._OP_COMMIT, "COMMIT"), (t._OP_MUTEX_ACQ, "MUTEX")):
        if op in t._IDEMPOTENT_OPS:
            out.append(f"mutating op {label} marked replay-safe")
    return out


def _check_holder_board() -> List[str]:
    """Holder-board semantics: the advisory word is stamped right AFTER a
    raw acquire and cleared conditionally right BEFORE a release (so a
    release racing a break never erases the breaker's view), and a break
    clears unconditionally.  Checked two ways: the pure-Python board is
    exercised live, and the acquire/release wrappers' source must order
    the stamp/clear correctly."""
    import struct as _struct
    import tempfile

    from bluefog_tpu.native import shm_native as sn

    out = []
    # live semantics on a throwaway board
    old = sn._FALLBACK_DIR
    tmp = tempfile.mkdtemp(prefix="bftpu_spec_holders_")
    try:
        sn._FALLBACK_DIR = tmp
        board = sn.HolderBoard("specjob", 4)
        try:
            board.set_holder(1, 2)
            if board.holder(1) != 2:
                out.append("holder word not readable after stamp")
            board.clear(1, holder_rank=3)  # conditional clear by non-holder
            if board.holder(1) != 2:
                out.append("conditional clear by a non-holder erased the "
                           "holder word (release/break race unsafe)")
            board.clear(1, holder_rank=2)
            if board.holder(1) is not None:
                out.append("conditional clear by the holder did not clear")
            board.set_holder(0, 1)
            board.clear(0)  # break path: unconditional
            if board.holder(0) is not None:
                out.append("unconditional (break) clear did not clear")
            # torn/stale words must read as free, never a bogus rank
            _struct.pack_into("<Q", board._seg._mm, 3 * 8, 99)
            if board.holder(3) is not None:
                out.append("out-of-range holder word not treated as free")
        finally:
            board.close(unlink=True)
    finally:
        sn._FALLBACK_DIR = old
    # source ordering: stamp after acquire, clear before release
    src = inspect.getsource(sn._timed_mutex_acquire)
    if src.rfind("acquire(rank, timeout)") > src.find("set_holder("):
        out.append("_timed_mutex_acquire stamps the holder word before "
                   "the raw acquire")
    rel = inspect.getsource(sn.FallbackShmJob.mutex_release)
    if rel.find(".clear(") > rel.find("unlock("):
        out.append("FallbackShmJob.mutex_release clears the holder word "
                   "after the unlock")
    return out


def _check_reference_ledger() -> List[str]:
    """Mass-ledger identity on the reference model: over any op sequence,
    committed deposits == collected + drained + retired-pending + live
    (counts and mass both) — the conservation law every transport's
    ledger telemetry reports against."""
    ref = ReferenceTransport(nranks=2)
    ref.deposit(0, 1, 3.0, 1.0)
    ref.deposit(0, 1, 2.0, 1.0)
    x, p, fresh = ref.collect(0, 1)
    out = []
    if (x, p, fresh) != (5.0, 2.0, 2):
        out.append(f"accumulate+collect returned {(x, p, fresh)!r}, "
                   "expected (5.0, 2.0, 2)")
    ref.deposit(0, 1, 7.0, 1.0)
    ref.drain(0, 1)          # uncollected mass must move to the drained bin
    ref.deposit(1, 0, 1.0, 1.0)
    ref.epoch_switch(1)      # quiesce: live mass retires to pending
    ref.deposit(0, 1, 9.0, 1.0)
    led = ref.ledger()
    if not led["balanced"]:
        out.append(f"ledger identity broken: {led!r}")
    if led["pending"] != 1 or led["drained"] != 1:
        out.append(f"retire/drain accounting off: {led!r}")
    return out


def _check_epoch_quiesce() -> List[str]:
    """Epoch switch quiesces the old epoch (late deposits bounce to the
    refused bucket, never silently commit) and re-seeds the new one (every
    slot starts from version 0 / zero mass)."""
    ref = ReferenceTransport(nranks=2)
    ref.deposit(0, 1, 4.0, 1.0)
    ref.epoch_switch(1)
    out = []
    if ref.version(0, 1) != 0:
        out.append("new epoch inherited old slot state (re-seed skipped)")
    ref.deposit_at_epoch(0, 0, 1, 8.0, 1.0)  # late delivery for epoch 0
    if ref.ledger()["refused"] != 1:
        out.append("late deposit into a retired epoch was not refused")
    x, p, fresh = ref.collect(0, 1)
    if fresh != 0:
        out.append("late deposit into a retired epoch became collectable")
    return out


def _check_dead_writer() -> List[str]:
    """Commit-after-payload makes the dead-writer drain sound: a writer
    death loses only uncommitted mass, and the heal-path force-drain
    conserves every committed deposit in the ledger."""
    ref = ReferenceTransport(nranks=2)
    ref.deposit(0, 1, 3.0, 1.0)          # committed before death
    ref.kill(1)
    ref.deposit(0, 1, 5.0, 1.0)          # dies mid-deposit: zero mass
    out = []
    if ref.version(0, 1) != 1:
        out.append("a dead writer's torn deposit committed mass")
    ref.drain(0, 1)                      # heal-path force drain
    led = ref.ledger()
    if not led["balanced"] or led["drained_x"] != 3.0:
        out.append(f"force drain lost committed mass: {led!r}")
    return out


#: The transport contract.  Each row names the rule, states it, pins the
#: constants that encode it in the real transports, and (where the rule
#: has observable semantics) verifies it executably.
TRANSPORT_SPEC: Tuple[SpecRule, ...] = (
    SpecRule(
        "seqlock-writer-order",
        "whole-slot deposits publish via lock / odd / payload / even / "
        "unlock — the bracket that makes the non-atomic copy safe",
        pins=(Pin(_SHM, "SEQLOCK_WRITER_STEPS",
                  ("acquire_lock", "seq_to_odd", "mutate_payload",
                   "seq_to_even", "release_lock")),),
    ),
    SpecRule(
        "seqlock-reader-order",
        "readers are wait-free: retry-if-odd / copy / retry-if-changed",
        pins=(Pin(_SHM, "SEQLOCK_READER_STEPS",
                  ("read_seq_before_retry_if_odd", "copy_payload",
                   "read_seq_after_retry_if_changed")),),
    ),
    SpecRule(
        "collect-atomicity",
        "collect = read + drain in ONE critical section on every "
        "transport (the push-sum mass-conservation primitive)",
        pins=(Pin(_SHM, "COLLECT_IS_ATOMIC", True),
              Pin(_SHM, "DRAINED_COLLECT_IS_ATOMIC", True),
              Pin(_TCP, "TCP_DRAINED_COLLECT_IS_ATOMIC", True)),
    ),
    SpecRule(
        "chunk-stream-order",
        "chunked deposits bracket each chunk with its own seqlock",
        pins=(Pin(_SHM, "CHUNK_WRITER_STEPS",
                  ("chunk_seq_to_odd", "mutate_chunk", "chunk_seq_to_even")),
              Pin(_SHM, "CHUNK_READER_STEPS",
                  ("read_chunk_seq_before_retry_if_odd", "copy_chunk",
                   "read_chunk_seq_after_retry_if_changed"))),
    ),
    SpecRule(
        "ascending-commit",
        "chunks commit in ascending index order on both chunked "
        "transports (the frontier invariant pipelined consumers rely on)",
        pins=(Pin(_SHM, "CHUNK_COMMIT_IN_ORDER", True),
              Pin(_TCP, "TCP_CHUNK_COMMIT_IN_ORDER", True)),
    ),
    SpecRule(
        "commit-after-payload",
        "version/p advance only after the full payload is written — a "
        "writer that dies mid-deposit committed zero mass",
        pins=(Pin(_SHM, "DEPOSIT_COMMITS_AFTER_PAYLOAD", True),
              Pin(_TCP, "TCP_DEPOSIT_COMMITS_AFTER_PAYLOAD", True)),
        check=_check_dead_writer,
    ),
    SpecRule(
        "dead-writer-drain",
        "the heal-path drain marks drained before tearing down, and "
        "even-izes torn seqlocks first on shm",
        pins=(Pin(_SHM, "DEAD_WRITER_DRAIN_STEPS",
                  ("evenize_chunk_seqs", "mark_drained", "evenize_wseq",
                   "clear_lock")),
              Pin(_TCP, "TCP_DEAD_WRITER_DRAIN_STEPS",
                  ("evenize_wseq", "mark_drained", "clear_stream"))),
        check=_check_drain_orders,
    ),
    SpecRule(
        "barrier-reset-order",
        "sense-reversing barrier: the last arriver resets the arrival "
        "count BEFORE bumping the generation (else: lost wakeup)",
        pins=(Pin(_SHM, "BARRIER_RESET_BEFORE_RELEASE", True),),
    ),
    SpecRule(
        "chunk-geometry-parity",
        "chunk size and pipeline depth agree across chunked transports",
        check=_check_chunk_geometry,
    ),
    SpecRule(
        "resume-idempotence",
        "session resume replays only read-only ops (a replayed deposit "
        "would double-count)",
        check=_check_resume_replay_set,
    ),
    SpecRule(
        "holder-board",
        "the mutex holder word is advisory: stamped after acquire, "
        "cleared conditionally before release, unconditionally on break",
        check=_check_holder_board,
    ),
    SpecRule(
        "mass-ledger-identity",
        "deposits == collected + drained + pending (+ live) at every "
        "observation, in both version counts and mass",
        check=_check_reference_ledger,
    ),
    SpecRule(
        "epoch-quiesce-reseed",
        "retiring an epoch refuses late deliveries and re-seeds every "
        "slot of the next epoch from zero",
        check=_check_epoch_quiesce,
    ),
)


def evaluate_spec(spec: Tuple[SpecRule, ...] = TRANSPORT_SPEC,
                  ) -> Dict[str, List[str]]:
    """Evaluate every spec rule; returns {rule name: problem strings}
    (empty lists for clean rules)."""
    return {rule.name: rule.problems() for rule in spec}


# ---------------------------------------------------------------------------
# the executable reference model
# ---------------------------------------------------------------------------


class _RefSlot:
    __slots__ = ("version", "seen", "x", "p", "drained", "severed")

    def __init__(self) -> None:
        self.version = 0   # committed-deposit count (monotone)
        self.seen = 0      # versions retired by collect/drain
        self.x = 0.0
        self.p = 0.0
        self.drained = 0   # marker: slot reads as zeros iff == version
        self.severed = False  # owner died: slot frozen, mass seized


class ReferenceTransport:
    """Sequential reference implementation of the transport contract.

    One object models one job: ``nranks`` ranks, one mail slot per
    (dst, src) pair per epoch — the same addressing the conformance
    adapters reduce every real transport to.  Payloads are scalars (the
    adapters reduce arrays to a scalar plus a uniformity check).

    Observable surface (what the differential harness compares):

    - ``deposit`` (accumulate) / ``put`` (replace): commit-after-payload
      — in this sequential model a call either fully commits or (writer
      dead / epoch retired) bounces to the refused bucket with ZERO
      observable effect.
    - ``collect``: atomic read+drain; returns ``(x, p, fresh)`` with
      ``fresh`` = number of versions retired (0 on a logically-zero
      slot), exactly :meth:`SimTransport.collect`'s contract.
    - ``read`` / ``version``: non-destructive; a drained slot reads as
      zeros with its version intact (the O(1) marker contract).
    - ``reset`` / ``drain``: wipe without collecting; uncollected
      versions/mass move to the *drained* ledger bin (never vanish).
    - ``epoch_switch``: quiesce + re-seed — live uncollected mass
      retires to the *pending* bin, late deposits into the old epoch are
      refused, the new epoch starts from zero.
    - ``kill``: a dead rank's subsequent deposits bounce (commit-
      after-payload: dying mid-op commits nothing) and its inbound
      slots are severed — uncollected mass moves to the *seized* bin
      and later collects at the corpse read as zeros, matching
      ``SimTransport.kill``'s severing.

    Ledger identity (checked by ``ledger()['balanced']``): committed
    deposits == collected + drained + pending + seized + live, in
    version counts and in mass.
    """

    def __init__(self, nranks: int):
        self.nranks = int(nranks)
        self.epoch = 0
        self._slots: Dict[Tuple[int, int, int], _RefSlot] = {}
        self._retired: set = set()
        self._dead: set = set()
        # ledgers (version counts and mass)
        self.deposits = 0
        self.deposited_x = 0.0
        self.collected = 0
        self.collected_x = 0.0
        self.drained = 0
        self.drained_x = 0.0
        self.pending = 0
        self.pending_x = 0.0
        self.seized = 0
        self.seized_x = 0.0
        self.refused = 0

    # -- helpers -----------------------------------------------------------

    def _slot(self, epoch: int, dst: int, src: int) -> _RefSlot:
        key = (int(epoch), int(dst), int(src))
        s = self._slots.get(key)
        if s is None:
            s = self._slots[key] = _RefSlot()
        return s

    def _live(self, s: _RefSlot) -> bool:
        return s.drained != s.version

    # -- writer side -------------------------------------------------------

    def deposit_at_epoch(self, epoch: int, dst: int, src: int,
                         x: float, p: float) -> None:
        """An accumulate-deposit addressed to an explicit epoch — how the
        harness models a LATE delivery racing an epoch switch."""
        if int(epoch) in self._retired or int(src) in self._dead \
                or int(dst) in self._dead:
            self.refused += 1
            return
        s = self._slot(epoch, dst, src)
        if not self._live(s):
            # accumulate onto a logically-zero slot restarts from zero
            # (the drained-marker contract: degrade to a copy)
            s.x, s.p = float(x), float(p)
        else:
            s.x += float(x)
            s.p += float(p)
        s.version += 1
        self.deposits += 1
        self.deposited_x += float(x)

    def deposit(self, dst: int, src: int, x: float, p: float) -> None:
        self.deposit_at_epoch(self.epoch, dst, src, x, p)

    def put(self, dst: int, src: int, x: float, p: float) -> None:
        """Replace-deposit (win_put): last write wins."""
        if self.epoch in self._retired or int(src) in self._dead \
                or int(dst) in self._dead:
            self.refused += 1
            return
        s = self._slot(self.epoch, dst, src)
        # the mass the put overwrites leaves live circulation via the
        # drained bin (a put over uncollected mass is a deliberate drop);
        # ``seen`` is NOT advanced — the real windows count overwritten
        # versions as fresh at the next collect, so the model must too
        if self._live(s):
            self.drained_x += s.x
        s.x, s.p = float(x), float(p)
        s.version += 1
        s.drained = s.version - 1  # live again
        self.deposits += 1
        self.deposited_x += float(x)

    # -- reader (owner) side ----------------------------------------------

    def collect(self, dst: int, src: int) -> Tuple[float, float, int]:
        s = self._slots.get((self.epoch, int(dst), int(src)))
        if s is None or not self._live(s):
            return 0.0, 0.0, 0
        fresh = s.version - s.seen
        x, p = s.x, s.p
        s.x, s.p = 0.0, 0.0
        s.seen = s.version
        s.drained = s.version
        self.collected += fresh
        self.collected_x += x
        return x, p, fresh

    def read(self, dst: int, src: int) -> Tuple[float, float, int]:
        s = self._slots.get((self.epoch, int(dst), int(src)))
        if s is None:
            return 0.0, 0.0, 0
        if not self._live(s):
            return 0.0, 0.0, s.version
        return s.x, s.p, s.version

    def version(self, dst: int, src: int) -> int:
        s = self._slots.get((self.epoch, int(dst), int(src)))
        return 0 if s is None else s.version

    def reset(self, dst: int, src: int) -> None:
        self.drain(dst, src)

    def drain(self, dst: int, src: int) -> None:
        """force_drain: wipe the slot; uncollected mass is accounted to
        the drained bin (the heal path's conservation obligation)."""
        s = self._slots.get((self.epoch, int(dst), int(src)))
        if s is None:
            return
        if self._live(s):
            self.drained_x += s.x
        self.drained += s.version - s.seen
        s.seen = s.version
        s.x, s.p = 0.0, 0.0
        s.drained = s.version

    # -- epochs + death ----------------------------------------------------

    def epoch_switch(self, new_epoch: int) -> None:
        """Quiesce the current epoch (uncollected mass -> pending bin,
        late deliveries refused from now on) and re-seed the next."""
        for (ep, _dst, _src), s in self._slots.items():
            if ep != self.epoch:
                continue
            if self._live(s):
                self.pending_x += s.x
            self.pending += s.version - s.seen
            s.seen = s.version
            s.x, s.p = 0.0, 0.0
            s.drained = s.version
        self._retired.add(self.epoch)
        self.epoch = int(new_epoch)

    def kill(self, rank: int) -> None:
        """A rank dies: its future deposits bounce, and every inbound
        slot it owned (dst == rank) is severed — uncollected mass moves
        to the *seized* bin (nobody will ever collect it; the heal path
        adopts or writes it off), the version stays visible."""
        g = int(rank)
        self._dead.add(g)
        for (ep, dst, _src), s in self._slots.items():
            if ep != self.epoch or dst != g or s.severed:
                continue
            if self._live(s):
                self.seized_x += s.x
            self.seized += s.version - s.seen
            s.seen = s.version
            s.x, s.p = 0.0, 0.0
            s.drained = s.version
            s.severed = True

    # -- observation -------------------------------------------------------

    def observe(self, dst: int, src: int) -> Tuple[float, float, int]:
        """Canonical observable slot state (what the differential
        harness snapshots): non-destructive read + version."""
        return self.read(dst, src)

    def ledger(self) -> Dict[str, object]:
        live = live_x = 0.0
        for (ep, _d, _s), s in self._slots.items():
            if ep in self._retired:
                continue
            live += s.version - s.seen
            if self._live(s):
                live_x += s.x
        counts_ok = self.deposits == (self.collected + self.drained
                                      + self.pending + self.seized + live)
        mass_ok = abs(self.deposited_x - (self.collected_x + self.drained_x
                                          + self.pending_x + self.seized_x
                                          + live_x)) < 1e-9
        return {
            "deposits": self.deposits,
            "collected": self.collected,
            "drained": self.drained,
            "pending": self.pending,
            "seized": self.seized,
            "live": int(live),
            "refused": self.refused,
            "deposited_x": self.deposited_x,
            "collected_x": self.collected_x,
            "drained_x": self.drained_x,
            "pending_x": self.pending_x,
            "seized_x": self.seized_x,
            "balanced": bool(counts_ok and mass_ok),
        }


# ---------------------------------------------------------------------------
# capability lint
# ---------------------------------------------------------------------------


def declared_transports() -> Dict[str, type]:
    """The registered transport classes, by capability-record name."""
    from bluefog_tpu.native.routed_transport import RoutedWindow
    from bluefog_tpu.native.shm_native import (FallbackShmWindow,
                                               NativeShmWindow)
    from bluefog_tpu.native.tcp_transport import TcpShmWindow
    from bluefog_tpu.sim.transport import SimTransport

    return {
        "shm-native": NativeShmWindow,
        "shm-fallback": FallbackShmWindow,
        "tcp": TcpShmWindow,
        "routed": RoutedWindow,
        "sim": SimTransport,
    }


def check_caps_declared(classes: Optional[Dict[str, type]] = None,
                        ) -> List[str]:
    """Every registered transport carries a well-formed CAPS record whose
    name matches its registration."""
    classes = declared_transports() if classes is None else classes
    out = []
    for name, cls in sorted(classes.items()):
        caps = getattr(cls, "CAPS", None)
        if not isinstance(caps, TransportCaps):
            out.append(f"{cls.__name__} declares no TransportCaps record")
            continue
        if caps.name != name:
            out.append(f"{cls.__name__}.CAPS.name = {caps.name!r}, "
                       f"registered as {name!r}")
        for field in CAP_FIELDS:
            if not isinstance(getattr(caps, field), bool):
                out.append(f"{cls.__name__}.CAPS.{field} is not a bool")
    return out


#: zero-copy collect is a structural property the lint cannot derive from
#: a signature; the expected values are pinned here and cross-checked
#: against the drain-atomicity constants of each module.
_ZERO_COPY_EXPECTED = {
    "shm-native": True,    # O(1) drained marker
    "shm-fallback": False,  # memset drain under lockf
    "tcp": True,           # collect swaps the slot buffer
    "routed": False,       # meet: the fallback leg may be in play
    "sim": True,           # collect IS the drain
}

#: same treatment for chunked streaming: the fallback window carries the
#: chunk *attributes* for interface parity but streams nothing, so a
#: signature probe cannot distinguish the claims — pin them.
_CHUNKED_EXPECTED = {
    "shm-native": True,
    "shm-fallback": False,  # whole-slot lockf writes
    "tcp": True,
    "routed": False,        # meet: the fallback leg may be in play
    "sim": False,           # virtual wire delivers whole payloads
}


def check_caps_honest(classes: Optional[Dict[str, type]] = None,
                      ) -> List[str]:
    """Each capability claim must match the class's actual surface:
    ``fused_scale`` ⇔ ``supports_scale`` + a ``scale`` kwarg on write,
    ``fused_accumulate`` ⇔ an ``accumulate`` kwarg (or an accumulating
    deposit), ``fused_combine`` ⇔ ``combine()``, ``chunked_streaming`` /
    ``wire_quantization`` / ``resume`` ⇔ the protocol constants and
    machinery of the defining module, and the routed record must be the
    meet of its legs."""
    classes = declared_transports() if classes is None else classes
    out = []
    for name, cls in sorted(classes.items()):
        caps = getattr(cls, "CAPS", None)
        if not isinstance(caps, TransportCaps):
            continue  # caps-declared already fires
        mod = inspect.getmodule(cls)
        mod_src = inspect.getsource(mod) if mod else ""
        write = getattr(cls, "write", None)
        if write is not None:
            params = inspect.signature(write).parameters
            has_scale = ("scale" in params
                         and getattr(cls, "supports_scale", False))
            if caps.fused_scale != has_scale:
                out.append(f"{name}: fused_scale={caps.fused_scale} but "
                           f"write scale kwarg/supports_scale say "
                           f"{has_scale}")
            if caps.fused_accumulate != ("accumulate" in params):
                out.append(f"{name}: fused_accumulate claim does not match "
                           "write()'s accumulate kwarg")
        elif caps.fused_scale:
            out.append(f"{name}: fused_scale without a write()")
        if caps.fused_combine != callable(getattr(cls, "combine", None)):
            out.append(f"{name}: fused_combine={caps.fused_combine} but "
                       f"combine() {'exists' if not caps.fused_combine else 'is missing'}")
        expected_chunked = _CHUNKED_EXPECTED.get(name)
        if expected_chunked is not None \
                and caps.chunked_streaming != expected_chunked:
            out.append(f"{name}: chunked_streaming={caps.chunked_streaming},"
                       f" pinned expectation is {expected_chunked}")
        if caps.chunked_streaming and name != "routed" \
                and "CHUNK_COMMIT_IN_ORDER" not in mod_src:
            out.append(f"{name}: claims chunked_streaming but its module "
                       "pins no ascending-commit constant")
        quant = "wire_codec" in getattr(mod, "__dict__", {})
        if name != "routed" and caps.wire_quantization != quant:
            out.append(f"{name}: wire_quantization={caps.wire_quantization} "
                       f"but module {'imports' if quant else 'never imports'}"
                       " wire_codec")
        resume = "_IDEMPOTENT_OPS" in getattr(mod, "__dict__", {})
        if name != "routed" and caps.resume != resume:
            out.append(f"{name}: resume={caps.resume} but module "
                       f"{'has' if resume else 'lacks'} a replay rule set")
        expected_zc = _ZERO_COPY_EXPECTED.get(name)
        if expected_zc is not None and caps.zero_copy_collect != expected_zc:
            out.append(f"{name}: zero_copy_collect={caps.zero_copy_collect},"
                       f" pinned expectation is {expected_zc}")
        if caps.device_resident or caps.in_mesh_collective:
            out.append(f"{name}: claims a future tier capability no "
                       "transport provides yet")
    # composite honesty: routed's static record is the meet of its
    # possible legs (it upgrades per instance, never past its legs)
    routed = classes.get("routed")
    if routed is not None and isinstance(getattr(routed, "CAPS", None),
                                         TransportCaps):
        native = classes["shm-native"].CAPS
        fallback = classes["shm-fallback"].CAPS
        tcp = classes["tcp"].CAPS
        floor = caps_mod.meet(caps_mod.meet(native, fallback, "shm"),
                              tcp, "routed")
        if routed.CAPS != floor:
            out.append("routed CAPS is not the meet of its legs: "
                       f"{routed.CAPS} != {floor}")
    return out


def _read_source(rel: str) -> str:
    with open(os.path.join(_REPO, rel), "r", encoding="utf-8") as f:
        return f.read()


#: every adaptive call site the lint covers: (file, what must hold).
#: Each entry is (relative path, [(description, predicate over source)]).
def _call_site_checks() -> List[Tuple[str, str, Callable[[str], bool]]]:
    probe = re.compile(r"getattr\([^)]*[\"']supports_scale[\"']")
    dual = re.compile(r"getattr\([^)]*[\"']put_dual[\"']")
    fused = re.compile(r"getattr\([^)]*[\"']update_fused[\"']")
    fuse_gate = re.compile(r"getattr\([^)]*[\"']fuse[\"']")
    classes = re.compile(
        r"\b(NativeShmWindow|TcpShmWindow|FallbackShmWindow|RoutedWindow)\b")
    return [
        ("bluefog_tpu/islands.py",
         "scaled deposits gate on the supports_scale capability probe",
         lambda s: bool(probe.search(s))),
        ("bluefog_tpu/islands.py",
         "dual-publish deposits probe put_dual, never assume it",
         lambda s: bool(dual.search(s))),
        ("bluefog_tpu/islands.py",
         "fused read sweeps probe update_fused, never assume it",
         lambda s: bool(fused.search(s))),
        ("bluefog_tpu/islands.py",
         "no transport-class identity checks (capabilities only)",
         lambda s: not classes.search(s)),
        ("bluefog_tpu/progress/engine.py",
         "accumulate fusion gates on the backend's declared fuse hook",
         lambda s: bool(fuse_gate.search(s))),
        ("bluefog_tpu/progress/engine.py",
         "no transport-class identity checks (capabilities only)",
         lambda s: not classes.search(s)),
        ("bluefog_tpu/native/wire_codec.py",
         "wire-dtype selection reads BFTPU_WIRE_DTYPE here and only here",
         lambda s: "BFTPU_WIRE_DTYPE" in s),
        ("bluefog_tpu/native/routed_transport.py",
         "tier selection routes purely by host equality (_same_host)",
         lambda s: "_same_host" in s and not re.search(
             r"isinstance\([^)]*(Native|Tcp|Fallback)", s)),
    ]


def check_caps_call_sites() -> List[str]:
    """Static pass over every adaptive call site: engine fusion, islands'
    scaled/fused deposits, wire-dtype selection, resume, and routed tier
    selection must rely only on declared capabilities."""
    out = []
    for rel, desc, pred in _call_site_checks():
        try:
            src = _read_source(rel)
        except OSError as exc:
            out.append(f"{rel}: unreadable ({exc})")
            continue
        if not pred(src):
            out.append(f"{rel}: {desc} — violated")
    # the wire-dtype env knob must have exactly one runtime reader
    # (wire_codec); any other runtime module reading it bypasses the
    # wire_quantization capability
    for root in ("bluefog_tpu/native", "bluefog_tpu/progress"):
        for dirpath, _dirs, files in os.walk(os.path.join(_REPO, root)):
            for fn in files:
                if not fn.endswith(".py") or fn == "wire_codec.py":
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), _REPO)
                src = _read_source(rel)
                if re.search(r"environ[^\n]*BFTPU_WIRE_DTYPE", src):
                    out.append(f"{rel}: reads BFTPU_WIRE_DTYPE directly "
                               "(only wire_codec may)")
    # resume machinery stays inside the transport that declares it
    for rel in ("bluefog_tpu/islands.py", "bluefog_tpu/progress/engine.py"):
        if "_IDEMPOTENT_OPS" in _read_source(rel):
            out.append(f"{rel}: touches the TCP replay rule set directly")
    return out


# ---------------------------------------------------------------------------
# registered rules
# ---------------------------------------------------------------------------


def _spec_rule_runner(report) -> None:
    for rule in TRANSPORT_SPEC:
        report.subjects_checked += 1
        for problem in rule.problems():
            report.add(Finding("transport.spec", f"spec:{rule.name}",
                               problem))
    report.metric("transport.spec_rules", float(len(TRANSPORT_SPEC)))


registry.register(  # direct registration keeps the callable reusable
    __import__("bluefog_tpu.analysis.engine",
               fromlist=["Rule"]).Rule(
        name="transport.spec",
        family="transport",
        doc="every rule of the window/mailbox contract holds: pinned "
            "constants unchanged, executable semantics verified",
        run=_spec_rule_runner,
    ))


@registry.rule("transport.caps-declared", "transport",
               "every registered transport declares a TransportCaps record")
def _rule_caps_declared(report) -> None:
    classes = declared_transports()
    report.subjects_checked += len(classes)
    for problem in check_caps_declared(classes):
        report.add(Finding("transport.caps-declared", "capability records",
                           problem))


@registry.rule("transport.caps-honest", "transport",
               "capability claims match each transport's actual surface; "
               "routed == meet of its legs")
def _rule_caps_honest(report) -> None:
    classes = declared_transports()
    report.subjects_checked += len(classes) * len(CAP_FIELDS)
    for problem in check_caps_honest(classes):
        report.add(Finding("transport.caps-honest", "capability records",
                           problem))


@registry.rule("transport.caps-call-sites", "transport",
               "engine fusion / scaled deposits / wire dtype / resume / "
               "routing branch only on declared capabilities")
def _rule_caps_call_sites(report) -> None:
    report.subjects_checked += len(_call_site_checks())
    for problem in check_caps_call_sites():
        report.add(Finding("transport.caps-call-sites", "call sites",
                           problem))
