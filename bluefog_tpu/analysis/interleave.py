"""Unified interleaving explorer: one little language for the protocol
state machines, one exhaustive checker, one happens-before race scan.

The seqlock, chunk-ring and drained-collect models in
:mod:`.seqlock_model` are three hand-rolled variations on the same
pattern: processes as lists of step closures, shared words, a DFS over
every interleaving.  This module factors the pattern into a declarative
**op language** — a process is a list of :class:`Op` rows (``acquire`` /
``release`` / ``rd`` / ``rdf`` / ``w`` / ``rmw`` / ``guard`` /
``branch`` / ``chk`` plus :class:`Label` jump targets) — compiled down
to the *same* :class:`~bluefog_tpu.analysis.seqlock_model.Model` the
legacy explorer runs, so one engine (``explore``) checks everything.

On top of the compiled form the module adds what the legacy models never
had: a **vector-clock race scan** (:func:`race_scan`).  Ops declare
which shared vars they read/write, and the spec classifies vars as
*sync* (lock words, seqlock sequence words, the packed serve header) or
*data* (payload words).  Over seeded random linearizations the scan
maintains one vector clock per process and per sync var (write =
release-join, read = acquire-join) and flags any **committed**
observation of a data var whose producing write is not happens-before
ordered — speculative seqlock-style copies are held pending and only
checked when the bracket validates (``chk(commits=True)``), exactly the
retroactive justification a real seqlock provides.  A torn-window bug
the interleaving verdict sees as "torn snapshot" the race scan
independently sees as "no happens-before edge": two detectors, one spec.

The three legacy protocols are re-expressed in the language
(:func:`seqlock_spec`, :func:`chunk_ring_spec`, :func:`drain_spec`) and
a **subsumption rule** asserts verdict parity with the legacy models on
the healthy builds AND every seeded-bug variant, so the unified explorer
provably covers what the old ones did (the legacy rules stay registered;
this family fences them).  Two new machines extend the coverage: the
async progress-engine submit queue (:func:`progress_queue_spec`:
exactly-once, order-preserving, nothing executes while parked) and the
serving double-buffer under a publisher death matrix
(:func:`serve_death_spec`: a completed read only ever returns a
committed version's canonical bytes, at every death point).

Registered family: ``interleave``.  Runtime: a few seconds (small
explicit-state bounds + pinned-seed scans).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional, Tuple

from bluefog_tpu.analysis.engine import Finding, Report, registry
from bluefog_tpu.analysis.seqlock_model import (
    Model,
    chunk_ring_model,
    drained_collect_model,
    explore,
    seqlock_model,
)
from bluefog_tpu.native.shm_native import (
    CHUNK_WRITER_STEPS,
    SEQLOCK_WRITER_STEPS,
)

__all__ = [
    "Op",
    "Label",
    "Proc",
    "ProtoSpec",
    "compile_spec",
    "verdict",
    "race_scan",
    "rd_when",
    "seqlock_spec",
    "chunk_ring_spec",
    "drain_spec",
    "progress_queue_spec",
    "serve_death_spec",
    "selftest_interleave",
]


# ---------------------------------------------------------------------------
# the op language
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Op:
    kind: str                 # acquire|acquire_when|release|rd|rdf|w|rmw|guard|branch|chk
    doc: str = ""             # step name (asserted against the spec tuples)
    var: Optional[str] = None          # acquire/release/rd/w target
    reg: Optional[str] = None          # rd/rdf destination register
    val: object = None                 # w value (constant or fn(sh, rg))
    fn: Optional[Callable] = None      # rdf/rmw/guard/branch/chk semantics
    reads: Tuple[str, ...] = ()        # shared vars read (race bookkeeping)
    reads_fn: Optional[Callable] = None   # dynamic actual-read set
    goto: Optional[str] = None         # branch target label
    reset: bool = False                # branch: clear registers on jump
    spec: bool = False                 # rd/rdf: speculative (validated later)
    commits: bool = False              # chk: success commits pending reads


class Label:
    """Jump target marker inside a process's op list."""

    def __init__(self, name: str):
        self.name = name


def acquire(var: str, doc: str = "") -> Op:
    return Op("acquire", doc=doc, var=var, reads=(var,))


def release(var: str, doc: str = "") -> Op:
    return Op("release", doc=doc, var=var)


def rd(reg: str, var: str, spec: bool = False, doc: str = "") -> Op:
    return Op("rd", doc=doc, var=var, reg=reg, reads=(var,), spec=spec)


def rd_when(reg: str, var: str, fn: Callable,
            reads: Tuple[str, ...] = (), doc: str = "") -> Op:
    """Atomic guarded read: blocks until ``fn(sh, rg)`` holds, then
    reads ``var`` in the SAME step — the seqlock reader's
    spin-while-odd-then-record, whose atomicity is what keeps an odd
    sequence value out of the bracket."""
    return Op("rd_when", doc=doc, var=var, reg=reg, fn=fn,
              reads=tuple(reads) + (var,))


def rdf(reg: str, fn: Callable, reads: Tuple[str, ...] = (),
        reads_fn: Optional[Callable] = None, spec: bool = False,
        doc: str = "") -> Op:
    return Op("rdf", doc=doc, reg=reg, fn=fn, reads=reads,
              reads_fn=reads_fn, spec=spec)


def w(var: str, val, doc: str = "", reads: Tuple[str, ...] = ()) -> Op:
    return Op("w", doc=doc, var=var, val=val, reads=reads)


def rmw(fn: Callable, reads: Tuple[str, ...] = (), doc: str = "") -> Op:
    return Op("rmw", doc=doc, fn=fn, reads=reads)


def guard(fn: Callable, reads: Tuple[str, ...] = (), doc: str = "") -> Op:
    return Op("guard", doc=doc, fn=fn, reads=reads)


def acquire_when(fn: Callable, var: str = "lock",
                 reads: Tuple[str, ...] = (), doc: str = "") -> Op:
    """Blocking conditional lock acquire: proceeds (taking ``var``) only
    when ``fn(sh, rg)`` holds and the lock is free — the coarsened
    test-and-set the real engines do under their mutex."""
    return Op("acquire_when", doc=doc, var=var, fn=fn,
              reads=tuple(reads) + (var,))


def branch(fn: Callable, goto: str, reads: Tuple[str, ...] = (),
           reset: bool = False, doc: str = "") -> Op:
    return Op("branch", doc=doc, fn=fn, goto=goto, reads=reads, reset=reset)


def chk(fn: Callable, reads: Tuple[str, ...] = (), commits: bool = False,
        doc: str = "") -> Op:
    return Op("chk", doc=doc, fn=fn, reads=reads, commits=commits)


@dataclasses.dataclass
class Proc:
    ops: List[object]           # Op | Label
    dying: bool = False         # every op also offers a die-in-place successor


@dataclasses.dataclass
class ProtoSpec:
    name: str
    shared: Dict
    procs: List[Proc]
    sync: Tuple[str, ...] = ()   # release/acquire vars for the race scan
    data: Tuple[str, ...] = ()   # payload vars the race scan guards
    final: Optional[Callable[[Dict], Optional[str]]] = None


def _resolve(proc: Proc) -> Tuple[List[Op], Dict[str, int]]:
    ops: List[Op] = []
    labels: Dict[str, int] = {}
    for item in proc.ops:
        if isinstance(item, Label):
            labels[item.name] = len(ops)
        else:
            ops.append(item)
    return ops, labels


def _value(val, sh, rg):
    return val(sh, rg) if callable(val) else val


def _step_for(op: Op, pc: int, labels: Dict[str, int], dying: bool
              ) -> Callable:
    """Compile one Op into a legacy-explorer step function."""
    nxt = pc + 1

    def successors(sh, rg):
        if op.kind == "acquire":
            if sh[op.var]:
                return []
            return [(dict(sh, **{op.var: 1}), rg, nxt)]
        if op.kind == "acquire_when":
            if sh[op.var] or not op.fn(sh, rg):
                return []
            return [(dict(sh, **{op.var: 1}), rg, nxt)]
        if op.kind == "release":
            return [(dict(sh, **{op.var: 0}), rg, nxt)]
        if op.kind == "rd":
            return [(sh, dict(rg, **{op.reg: sh[op.var]}), nxt)]
        if op.kind == "rd_when":
            if not op.fn(sh, rg):
                return []
            return [(sh, dict(rg, **{op.reg: sh[op.var]}), nxt)]
        if op.kind == "rdf":
            return [(sh, dict(rg, **{op.reg: op.fn(sh, rg)}), nxt)]
        if op.kind == "w":
            return [(dict(sh, **{op.var: _value(op.val, sh, rg)}), rg, nxt)]
        if op.kind == "rmw":
            return [(dict(sh, **op.fn(sh, rg)), rg, nxt)]
        if op.kind == "guard":
            return [(sh, rg, nxt)] if op.fn(sh, rg) else []
        if op.kind == "branch":
            if op.fn(sh, rg):
                return [(sh, {} if op.reset else rg, labels[op.goto])]
            return [(sh, rg, nxt)]
        if op.kind == "chk":
            msg = op.fn(sh, rg)
            if msg:
                return [(dict(sh, _bad=msg), rg, nxt)]
            return [(sh, rg, nxt)]
        raise ValueError(f"unknown op kind {op.kind!r}")

    if not dying:
        return successors

    def with_death(sh, rg):
        succ = list(successors(sh, rg))
        succ.append((dict(sh, dead=1), rg, 10_000))  # SIGKILL in place
        return succ

    return with_death


def compile_spec(spec: ProtoSpec) -> Model:
    """Compile the declarative spec to the legacy explorer's Model — the
    one engine both generations of models run on."""
    programs = []
    for proc in spec.procs:
        ops, labels = _resolve(proc)
        programs.append([_step_for(op, i, labels, proc.dying)
                         for i, op in enumerate(ops)])
    return Model(name=spec.name, shared=dict(spec.shared),
                 programs=programs, final_check=spec.final)


def verdict(spec: ProtoSpec) -> List[str]:
    """Exhaustively explore the compiled spec; returns violations."""
    return explore(compile_spec(spec))


def _collapsed_docs(ops: List[object]) -> Tuple[str, ...]:
    """The op-doc sequence with repeats collapsed — compared against the
    implementation's pinned step tuples so specs cannot silently drift."""
    out: List[str] = []
    for item in ops:
        if isinstance(item, Label) or not item.doc:
            continue
        if not out or out[-1] != item.doc:
            out.append(item.doc)
    return tuple(out)


# ---------------------------------------------------------------------------
# vector-clock race scan
# ---------------------------------------------------------------------------


def _join(a: Tuple[int, ...], b: Tuple[int, ...]) -> Tuple[int, ...]:
    return tuple(max(x, y) for x, y in zip(a, b))


def _hb(earlier: Optional[Tuple[int, ...]], later: Tuple[int, ...]) -> bool:
    return earlier is None or all(x <= y for x, y in zip(earlier, later))


def race_scan(spec: ProtoSpec, seeds: Tuple[int, ...] = tuple(range(20)),
              max_steps: int = 4000) -> List[str]:
    """Happens-before race check over seeded random linearizations.

    Sync vars carry release/acquire clocks; every non-speculative read
    (and every write) of a data var must be happens-after the var's last
    write; speculative reads go to a pending set that is checked when a
    ``chk(commits=True)`` succeeds and discarded when a resetting branch
    retries.  Returns deduplicated race/violation messages."""
    races: List[str] = []
    seen = set()

    def flag(msg: str) -> None:
        if msg not in seen:
            seen.add(msg)
            races.append(f"{spec.name}: {msg}")

    nprocs = len(spec.procs)
    resolved = [_resolve(p) for p in spec.procs]
    sync, data = set(spec.sync), set(spec.data)

    for seed in seeds:
        rng = random.Random(seed)
        sh = dict(spec.shared)
        pcs = [0] * nprocs
        regs: List[Dict] = [{} for _ in range(nprocs)]
        vc = [tuple(1 if j == i else 0 for j in range(nprocs))
              for i in range(nprocs)]
        var_clock: Dict[str, Tuple[int, ...]] = {}
        last_write: Dict[str, Tuple[Optional[Tuple[int, ...]], int]] = {}
        pending: List[List[Tuple[str, Optional[Tuple[int, ...]], int]]] = \
            [[] for _ in range(nprocs)]

        for _ in range(max_steps):
            enabled = []
            for i in range(nprocs):
                ops, _labels = resolved[i]
                if pcs[i] >= len(ops):
                    continue
                op = ops[pcs[i]]
                if op.kind in ("guard", "rd_when") and not op.fn(sh, regs[i]):
                    continue
                if op.kind == "acquire" and sh[op.var]:
                    continue
                if op.kind == "acquire_when" and (
                        sh[op.var] or not op.fn(sh, regs[i])):
                    continue
                enabled.append(i)
            if not enabled:
                break
            i = rng.choice(enabled)
            ops, labels = resolved[i]
            op = ops[pcs[i]]
            rg = regs[i]
            vc[i] = tuple(c + (1 if j == i else 0)
                          for j, c in enumerate(vc[i]))

            reads = (op.reads_fn(sh, rg) if op.reads_fn is not None
                     else op.reads)
            # acquire-join every sync var FIRST: within one op the
            # synchronization precedes the data observation (an
            # acquire_when guard evaluates under the lock it takes)
            for v in reads:
                if v in sync:
                    vc[i] = _join(vc[i], var_clock.get(v, vc[i]))
            for v in reads:
                if v in data and not op.spec:
                    wvc, wproc = last_write.get(v, (None, -1))
                    if wproc not in (-1, i) and not _hb(wvc, vc[i]):
                        flag(f"race: process {i} reads data var {v!r} "
                             f"concurrently with process {wproc}'s write "
                             f"(no happens-before edge)")
            if op.spec:
                for v in reads:
                    if v in data:
                        wvc, wproc = last_write.get(v, (None, -1))
                        pending[i].append((v, wvc, wproc))

            # execute with the compiled semantics
            if op.kind in ("acquire", "acquire_when"):
                sh[op.var] = 1
                var_clock[op.var] = _join(
                    var_clock.get(op.var, vc[i]), vc[i])
                pcs[i] += 1
            elif op.kind == "release":
                sh[op.var] = 0
                var_clock[op.var] = _join(
                    var_clock.get(op.var, vc[i]), vc[i])
                pcs[i] += 1
            elif op.kind in ("rd", "rd_when"):
                rg[op.reg] = sh[op.var]
                pcs[i] += 1
            elif op.kind == "rdf":
                rg[op.reg] = op.fn(sh, rg)
                pcs[i] += 1
            elif op.kind in ("w", "rmw"):
                updates = ({op.var: _value(op.val, sh, rg)}
                           if op.kind == "w" else op.fn(sh, rg))
                for v, nv in updates.items():
                    sh[v] = nv
                    if v in sync:
                        var_clock[v] = _join(var_clock.get(v, vc[i]), vc[i])
                    elif v in data:
                        wvc, wproc = last_write.get(v, (None, -1))
                        if wproc not in (-1, i) and not _hb(wvc, vc[i]):
                            flag(f"race: processes {wproc} and {i} write "
                                 f"data var {v!r} concurrently")
                        last_write[v] = (vc[i], i)
                pcs[i] += 1
            elif op.kind == "guard":
                pcs[i] += 1
            elif op.kind == "branch":
                if op.fn(sh, rg):
                    if op.reset:
                        regs[i] = {}
                        pending[i].clear()
                    pcs[i] = labels[op.goto]
                else:
                    pcs[i] += 1
            elif op.kind == "chk":
                msg = op.fn(sh, rg)
                if msg:
                    flag(msg)
                elif op.commits:
                    for v, wvc, wproc in pending[i]:
                        if wproc not in (-1, i) and not _hb(wvc, vc[i]):
                            flag(f"race: process {i} COMMITTED a "
                                 f"speculative read of {v!r} whose "
                                 f"producing write (process {wproc}) it "
                                 f"does not happen-after")
                    pending[i].clear()
                pcs[i] += 1
    return races


# ---------------------------------------------------------------------------
# the three legacy protocols, re-expressed in the language
# ---------------------------------------------------------------------------


def seqlock_spec(bug: Optional[str] = None, deposits: int = 2,
                 words: int = 2) -> ProtoSpec:
    """The mailbox slot seqlock: locked writer with odd/even publish, one
    wait-free bracketed reader.  ``bug`` in {"early_publish", "no_odd",
    "no_validate"} builds the seeded-bug variants (each must fire)."""
    shared = {"lock": 0, "seq": 0}
    shared.update({f"w{k}": 0 for k in range(words)})

    wops: List[object] = []
    for dep in range(deposits):
        v = dep + 1
        body: List[Op] = [acquire("lock", doc="acquire_lock")]
        bump = rmw(lambda sh, rg: {"seq": sh["seq"] + 1}, reads=("seq",))
        if bug != "no_odd":
            body.append(dataclasses.replace(bump, doc="seq_to_odd"))
        payload = [w(f"w{k}", v, doc="mutate_payload") for k in range(words)]
        publish = [dataclasses.replace(bump, doc="seq_to_even")]
        body += (publish + payload if bug == "early_publish"
                 else payload + publish)
        body.append(w("lock", 0, doc="release_lock"))
        wops += body
    if bug is None:
        per_dep = wops[:len(wops) // deposits]
        assert _collapsed_docs(per_dep) == SEQLOCK_WRITER_STEPS, (
            "unified seqlock spec drifted from "
            "shm_native.SEQLOCK_WRITER_STEPS")

    rops: List[object] = [
        Label("retry"),
        rd_when("before", "seq", lambda sh, rg: sh["seq"] % 2 == 0,
                doc="read_seq_before_retry_if_odd"),
    ]
    rops += [rd(f"r{k}", f"w{k}", spec=True, doc="copy_payload")
             for k in range(words)]
    if bug != "no_validate":
        rops.append(branch(lambda sh, rg: sh["seq"] != rg["before"],
                           goto="retry", reads=("seq",), reset=True,
                           doc="read_seq_after_retry_if_changed"))

    def torn(sh, rg, words=words):
        vals = {rg[f"r{k}"] for k in range(words)}
        if len(vals) > 1:
            return f"torn read: completed snapshot mixes {sorted(vals)}"
        return None

    rops.append(chk(torn, commits=True))
    return ProtoSpec(name=f"u-seqlock[{bug or 'healthy'}]", shared=shared,
                     procs=[Proc(wops), Proc(rops)],
                     sync=("lock", "seq"),
                     data=tuple(f"w{k}" for k in range(words)))


def chunk_ring_spec(bug: Optional[str] = None, nchunks: int = 2,
                    deposits: int = 2, words: int = 2,
                    frontier: bool = False) -> ProtoSpec:
    """The v2 chunk ring: per-chunk seqlocks committed in ascending
    order.  ``bug`` in {"no_fence", "descending"}; ``frontier=True``
    swaps the bracketed per-chunk reader for the pipelined
    commit-frontier consumer (the one that needs the ascending order)."""
    shared: Dict = {}
    for c in range(nchunks):
        shared[f"cs{c}"] = 0
        shared.update({f"c{c}w{k}": 0 for k in range(words)})

    wops: List[object] = []
    for dep in range(deposits):
        v = dep + 1
        order = (range(nchunks - 1, -1, -1) if bug == "descending"
                 else range(nchunks))
        for c in order:
            bump = rmw(lambda sh, rg, c=c: {f"cs{c}": sh[f"cs{c}"] + 1},
                       reads=(f"cs{c}",))
            mutate = [w(f"c{c}w{k}", v, doc="mutate_chunk")
                      for k in range(words)]
            publish = [dataclasses.replace(bump, doc="chunk_seq_to_even")]
            body = [dataclasses.replace(bump, doc="chunk_seq_to_odd")]
            body += (publish + mutate if bug == "no_fence"
                     else mutate + publish)
            wops += body
    if bug is None:
        per_chunk = wops[:len(wops) // (deposits * nchunks)]
        assert _collapsed_docs(per_chunk) == CHUNK_WRITER_STEPS, (
            "unified chunk spec drifted from shm_native.CHUNK_WRITER_STEPS")

    rops: List[object] = []
    if frontier:
        last = nchunks - 1

        def at_frontier(sh, rg, last=last):
            s = sh[f"cs{last}"]
            return s % 2 == 0 and s >= 2

        rops.append(rd_when("dlast", f"cs{last}", at_frontier))
        for c in range(nchunks):
            def ordered(sh, rg, c=c, words=words, last=last):
                d = rg["dlast"] // 2
                lo = min(sh[f"c{c}w{k}"] for k in range(words))
                if lo < d:
                    return (f"commit frontier violated: chunk {last} shows "
                            f"episode {d} committed but chunk {c} still "
                            f"carries episode {lo}")
                return None

            rops.append(chk(ordered,
                            reads=tuple(f"c{c}w{k}" for k in range(words))))
    else:
        for c in range(nchunks):
            lbl = f"retry{c}"
            rops.append(Label(lbl))
            rops.append(rd_when("before", f"cs{c}",
                                lambda sh, rg, c=c: sh[f"cs{c}"] % 2 == 0))
            rops += [rd(f"r{k}", f"c{c}w{k}", spec=True)
                     for k in range(words)]
            rops.append(branch(
                lambda sh, rg, c=c: sh[f"cs{c}"] != rg["before"],
                goto=lbl, reads=(f"cs{c}",), reset=True))

            def torn(sh, rg, c=c, words=words):
                vals = {rg[f"r{k}"] for k in range(words)}
                if len(vals) > 1:
                    return (f"torn chunk {c}: completed bracket mixes "
                            f"episodes {sorted(vals)}")
                return None

            rops.append(chk(torn, commits=True))
    return ProtoSpec(
        name=f"u-chunk-ring[{bug or 'healthy'}"
             f"{'+frontier' if frontier else ''}]",
        shared=shared, procs=[Proc(wops), Proc(rops)],
        sync=tuple(f"cs{c}" for c in range(nchunks)),
        data=tuple(f"c{c}w{k}" for c in range(nchunks)
                   for k in range(words)))


def drain_spec(bug: Optional[str] = None, deposits: int = 2) -> ProtoSpec:
    """The v2 O(1) drained-marker collect racing an accumulating writer;
    final mass conservation.  ``bug="lockfree_sample"`` samples the
    logical mass outside the critical section (the seeded bug)."""
    shared = {"lock": 0, "m": 0, "version": 0, "drained": 0, "collected": 0}

    def logical(sh) -> int:
        return 0 if sh["drained"] == sh["version"] else sh["m"]

    wops: List[object] = []
    for _dep in range(deposits):
        wops += [
            acquire("lock"),
            rmw(lambda sh, rg: {"m": logical(sh) + 1,
                                "version": sh["version"] + 1},
                reads=("m", "version", "drained")),
            release("lock"),
        ]

    cops: List[object]
    if bug == "lockfree_sample":
        cops = [
            rdf("got", lambda sh, rg: logical(sh),
                reads=("m", "version", "drained")),
            acquire("lock"),
            rmw(lambda sh, rg: {"collected": sh["collected"] + rg["got"],
                                "drained": sh["version"]},
                reads=("version",)),
            release("lock"),
        ]
    else:
        cops = [
            acquire("lock"),
            rmw(lambda sh, rg: {"collected": sh["collected"] + logical(sh),
                                "drained": sh["version"]},
                reads=("m", "version", "drained")),
            release("lock"),
        ]

    def conserved(sh) -> Optional[str]:
        if sh["collected"] + logical(sh) != deposits:
            return (f"lost deposit: {deposits} deposited but "
                    f"collected={sh['collected']} + "
                    f"logical-remaining={logical(sh)}")
        return None

    return ProtoSpec(name=f"u-drain[{bug or 'healthy'}]", shared=shared,
                     procs=[Proc(wops), Proc(cops)],
                     sync=("lock",), data=("m", "version", "drained"),
                     final=conserved)


# ---------------------------------------------------------------------------
# new coverage: the progress-engine queue and the serve death matrix
# ---------------------------------------------------------------------------


def progress_queue_spec(bug: Optional[str] = None,
                        handles: int = 3) -> ProtoSpec:
    """The async progress engine's submit queue at small bounds: one
    submitter enqueuing ``handles`` handles, one worker executing them,
    one quiescer parking the engine mid-stream.

    Invariants (the engine contract the progress family lints on
    traces, here proved over every interleaving): every handle executes
    exactly once, in submit order, and NOTHING executes while parked.
    ``bug`` in {"runs_while_parked", "double_execute"}."""
    shared = {"lock": 0, "parked": 0, "head": 0, "tail": 0, "snap": 0}
    shared.update({f"q{k}": 0 for k in range(handles)})
    shared.update({f"done{h}": 0 for h in range(1, handles + 1)})

    sops: List[object] = []
    for h in range(1, handles + 1):
        sops += [
            acquire("lock"),
            rmw(lambda sh, rg, h=h: {f"q{sh['tail']}": h,
                                     "tail": sh["tail"] + 1},
                reads=("tail",), doc="enqueue"),
            release("lock"),
        ]

    wops: List[object] = []
    for it in range(handles):
        def runnable(sh, rg, bug=bug):
            if sh["head"] >= sh["tail"]:
                return False
            return bug == "runs_while_parked" or sh["parked"] == 0

        skip_bump = bug == "double_execute" and it == 0
        wops += [
            acquire_when(runnable, reads=("head", "tail", "parked"),
                         doc="claim"),
            rdf("h", lambda sh, rg: sh[f"q{sh['head']}"],
                reads=("head",) + tuple(f"q{k}" for k in range(handles))),
            chk(lambda sh, rg: None if rg["h"] == rg.get("last", 0) + 1
                else (f"out-of-order execution: handle {rg['h']} ran "
                      f"after {rg.get('last', 0)}"),
                doc="order"),
            rmw(lambda sh, rg, skip=skip_bump: {
                    f"done{rg['h']}": sh[f"done{rg['h']}"] + 1,
                    "head": sh["head"] + (0 if skip else 1),
                    "ran_parked": max(sh.get("ran_parked", 0),
                                      sh["parked"])},
                reads=("head", "parked"), doc="execute"),
            rdf("last", lambda sh, rg: rg["h"]),
            release("lock"),
        ]
    shared["ran_parked"] = 0

    qops: List[object] = [
        acquire("lock"),
        rmw(lambda sh, rg: {"parked": 1,
                            "snap": sum(sh[f"done{h}"]
                                        for h in range(1, handles + 1))},
            reads=("parked",) + tuple(f"done{h}"
                                      for h in range(1, handles + 1)),
            doc="park"),
        release("lock"),
        acquire("lock"),
        chk(lambda sh, rg: None
            if sum(sh[f"done{h}"] for h in range(1, handles + 1))
            == sh["snap"] and not sh["ran_parked"]
            else "handle executed while the engine was parked",
            doc="quiesce-check"),
        rmw(lambda sh, rg: {"parked": 0}, doc="unpark"),
        release("lock"),
    ]

    def final(sh) -> Optional[str]:
        for h in range(1, handles + 1):
            if sh[f"done{h}"] != 1:
                return (f"handle {h} executed {sh[f'done{h}']} time(s) — "
                        "exactly-once broken")
        if sh["ran_parked"]:
            return "handle executed while the engine was parked"
        return None

    return ProtoSpec(name=f"u-progress-queue[{bug or 'healthy'}]",
                     shared=shared,
                     procs=[Proc(sops), Proc(wops), Proc(qops)],
                     sync=("lock",),
                     data=tuple(f"q{k}" for k in range(handles))
                     + ("head", "tail"),
                     final=final)


def serve_death_spec(bug: Optional[str] = None,
                     rounds: int = 2) -> ProtoSpec:
    """The serving double-buffer under a publisher death matrix.

    ``hdr`` packs (version, active-index) as ``version * 10 + idx`` —
    the single seq_cst word the real region flips.  The publisher writes
    the INACTIVE buffer's canonical bytes (modeled as ``100 + version``)
    and then flips hdr in one step; it may DIE at any op (SIGKILL, no
    cleanup).  The reader brackets its copy with two hdr reads.  A
    completed read must return the canonical bytes of the version its
    bracket pinned — at every death point.  ``bug="flip_before_payload"``
    publishes the flip before the payload lands (the torn-publish bug)."""
    shared = {"hdr": 0, "b0": 100, "b1": 0}

    pops: List[object] = []
    for _r in range(rounds):
        plan = [
            rdf("idx", lambda sh, rg: 1 - sh["hdr"] % 10, reads=("hdr",),
                doc="pick_inactive"),
            rdf("nv", lambda sh, rg: sh["hdr"] // 10 + 1, reads=("hdr",)),
            rmw(lambda sh, rg: {f"b{rg['idx']}": 100 + rg["nv"]},
                doc="write_payload"),
            rmw(lambda sh, rg: {"hdr": rg["nv"] * 10 + rg["idx"]},
                reads=("hdr",), doc="flip"),
        ]
        if bug == "flip_before_payload":
            plan[2], plan[3] = plan[3], plan[2]
        pops += plan

    rops: List[object] = [
        Label("retry"),
        rd("h1", "hdr"),
        rdf("x", lambda sh, rg: sh[f"b{rg['h1'] % 10}"],
            reads=("b0", "b1"),
            reads_fn=lambda sh, rg: (f"b{rg['h1'] % 10}",),
            spec=True, doc="copy_active"),
        branch(lambda sh, rg: sh["hdr"] != rg["h1"], goto="retry",
               reads=("hdr",), reset=True, doc="revalidate"),
        chk(lambda sh, rg: None if rg["x"] == 100 + rg["h1"] // 10
            else (f"completed read returned {rg['x']} for committed "
                  f"version {rg['h1'] // 10} (canonical "
                  f"{100 + rg['h1'] // 10}) — uncommitted/torn bytes "
                  "served"),
            commits=True, doc="canonical"),
    ]

    return ProtoSpec(name=f"u-serve-death[{bug or 'healthy'}]",
                     shared=shared,
                     procs=[Proc(pops, dying=True), Proc(rops)],
                     sync=("hdr",), data=("b0", "b1"))


# ---------------------------------------------------------------------------
# subsumption matrix + registered rules
# ---------------------------------------------------------------------------

#: (label, legacy model factory, unified spec factory, must_fire) — the
#: unified explorer must agree with the legacy model on every row, clean
#: AND seeded-bug builds both.
SUBSUMPTION: Tuple[Tuple[str, Callable[[], Model],
                         Callable[[], ProtoSpec], bool], ...] = (
    ("seqlock healthy", lambda: seqlock_model(),
     lambda: seqlock_spec(), False),
    ("seqlock early-publish", lambda: seqlock_model(early_publish=True),
     lambda: seqlock_spec("early_publish"), True),
    ("seqlock no-odd-phase", lambda: seqlock_model(odd_phase=False),
     lambda: seqlock_spec("no_odd"), True),
    ("seqlock no-validate",
     lambda: seqlock_model(reader_checks_after=False),
     lambda: seqlock_spec("no_validate"), True),
    ("chunk-ring healthy", lambda: chunk_ring_model(),
     lambda: chunk_ring_spec(), False),
    ("chunk-ring no-fence", lambda: chunk_ring_model(commit_fence=False),
     lambda: chunk_ring_spec("no_fence"), True),
    ("chunk-ring descending",
     lambda: chunk_ring_model(in_order_commit=False, words=1,
                              frontier_reader=True),
     lambda: chunk_ring_spec("descending", words=1, frontier=True), True),
    ("chunk-ring frontier healthy",
     lambda: chunk_ring_model(words=1, frontier_reader=True),
     lambda: chunk_ring_spec(words=1, frontier=True), False),
    ("drained-collect healthy", lambda: drained_collect_model(),
     lambda: drain_spec(), False),
    ("drained-collect lock-free sample",
     lambda: drained_collect_model(atomic_collect=False),
     lambda: drain_spec("lockfree_sample"), True),
)


@registry.rule("interleave.unified-explorer", "interleave",
               "every protocol spec written in the unified op language "
               "explores clean: seqlock, chunk ring (both readers), "
               "drained collect, progress queue, serve death matrix")
def _run_unified(report: Report) -> None:
    healthy = (
        seqlock_spec(),
        chunk_ring_spec(),
        chunk_ring_spec(words=1, frontier=True),
        drain_spec(),
        progress_queue_spec(),
        serve_death_spec(),
    )
    for spec in healthy:
        report.subjects_checked += 1
        for msg in verdict(spec):
            report.add(Finding("interleave.unified-explorer", spec.name,
                               msg))


@registry.rule("interleave.subsumes-legacy", "interleave",
               "the unified explorer's verdict matches the three legacy "
               "models on healthy AND seeded-bug builds — the old "
               "checkers are provably subsumed")
def _run_subsumption(report: Report) -> None:
    for label, legacy_fn, unified_fn, must_fire in SUBSUMPTION:
        report.subjects_checked += 1
        legacy_fired = bool(explore(legacy_fn()))
        unified_fired = bool(verdict(unified_fn()))
        if legacy_fired != unified_fired:
            report.add(Finding(
                "interleave.subsumes-legacy", label,
                f"verdict split: legacy model "
                f"{'fires' if legacy_fired else 'is clean'} but the "
                f"unified spec "
                f"{'fires' if unified_fired else 'is clean'}"))
        if unified_fired != must_fire:
            report.add(Finding(
                "interleave.subsumes-legacy", label,
                f"expected the unified spec to "
                f"{'fire' if must_fire else 'stay clean'} but it "
                f"{'fired' if unified_fired else 'stayed clean'}"))


@registry.rule("interleave.race-scan", "interleave",
               "the vector-clock happens-before scan: healthy specs are "
               "race-free over pinned seeds, and the planted "
               "early-publish bug IS caught (the scan has teeth)")
def _run_race_scan(report: Report) -> None:
    for spec in (seqlock_spec(), chunk_ring_spec(), drain_spec(),
                 progress_queue_spec(), serve_death_spec()):
        report.subjects_checked += 1
        for msg in race_scan(spec):
            report.add(Finding("interleave.race-scan", spec.name,
                               f"unexpected race in a healthy spec: "
                               f"{msg}"))
    report.subjects_checked += 1
    planted = race_scan(seqlock_spec("early_publish"))
    if not planted:
        report.add(Finding(
            "interleave.race-scan", "u-seqlock[early_publish]",
            "planted early-publish bug produced NO race/violation — "
            "the happens-before scan lost its teeth"))


def selftest_interleave() -> List[Tuple[str, bool, str]]:
    """The --self-test arm: every seeded-bug spec must make the unified
    explorer fire; the healthy builds must stay clean."""
    rows: List[Tuple[str, bool, str]] = []
    for label, _legacy_fn, unified_fn, must_fire in SUBSUMPTION:
        fired = bool(verdict(unified_fn()))
        ok = fired == must_fire
        rows.append((f"unified {label}", ok,
                     ("fires" if fired else "clean")
                     + ("" if ok else " — UNEXPECTED")))
    for bug in ("runs_while_parked", "double_execute"):
        fired = bool(verdict(progress_queue_spec(bug)))
        rows.append((f"progress-queue {bug}", fired,
                     "caught" if fired else "NOT caught"))
    fired = bool(verdict(serve_death_spec("flip_before_payload")))
    rows.append(("serve flip-before-payload", fired,
                 "caught" if fired else "NOT caught"))
    fired = bool(race_scan(seqlock_spec("early_publish")))
    rows.append(("race-scan early-publish", fired,
                 "caught" if fired else "NOT caught"))
    return rows
