"""Generative conformance harness: every transport vs the reference model.

``transport_spec.ReferenceTransport`` states what a window transport must
*do*; this module checks the real ones actually do it.  Every registered
transport — native shm, fallback shm, chunked TCP, the legacy
whole-payload TCP arm, and ``SimTransport`` — is wrapped in a small
adapter exposing one op vocabulary (deposit / put / collect / read /
version / drain / reset / epoch-switch / kill), then driven through the
same randomized-but-seeded op schedules as the reference model, with the
observable state (op results + every slot's version) differentially
compared after **every op**.  A divergence is shrunk with the same ddmin
the sim campaigns use (``sim/campaign.shrink_schedule``'s algorithm) to a
1-minimal repro schedule before it is reported.

Vocabulary boundaries (each op runs on every arm that can represent it):

- core (all five transports + reference): deposit / collect / version;
- window (shm native, shm fallback, both TCP arms + reference): adds
  put / read / drain / reset — sim's mailbox has no replace or
  owner-side drain op;
- epoch/death (sim + reference): adds epoch-switch (quiesce + re-seed,
  mapped to ``SimTransport.retire_epoch`` per owner) and mid-schedule
  writer death (``kill``) — real windows have no epochs (the islands
  layer re-creates segments per epoch) and live death is exercised by
  the np=2 chaos e2e in ``tests/test_conformance.py``.

The TCP arms run two REAL ranks of one job in-process (the runtime is
keyed by ``(job, rank)``), so deposits genuinely cross the loopback wire
— chunked arm with a 2-chunk geometry, legacy arm with
``BFTPU_TCP_CHUNKED=0``.

Registered family: ``conformance``.  Runtime: the shm rules are
milliseconds; the TCP rule pays two runtime handshakes (~1 s).
"""

from __future__ import annotations

import os
import random
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.analysis.engine import Finding, Severity, registry
from bluefog_tpu.analysis.transport_spec import ReferenceTransport

__all__ = [
    "gen_schedule",
    "run_schedule",
    "shrink_ops",
    "differential",
    "ARM_FACTORIES",
    "CORE_ARMS",
    "WINDOW_ARMS",
    "FAMILY_MAP",
    "families_for_paths",
    "selftest_conformance",
]

_NRANKS = 2
_SHAPE = (4,)
_DTYPE = np.float32
_PAIRS = tuple((d, s) for d in range(_NRANKS) for s in range(_NRANKS))

_job_counter = [0]


def _fresh_job(tag: str) -> str:
    _job_counter[0] += 1
    return f"conf_{tag}_{os.getpid()}_{_job_counter[0]}"


# ---------------------------------------------------------------------------
# op schedules
# ---------------------------------------------------------------------------


def gen_schedule(seed: int, nops: int, *, puts: bool = False,
                 drains: bool = False, epochs: bool = False,
                 kills: bool = False) -> List[Tuple]:
    """One seeded op schedule over the 2-rank job.  ``puts``/``drains``
    add the window-only vocabulary; ``epochs``/``kills`` the sim-side
    one.  Deterministic in ``seed``; payload values are small integers
    (exact in f32 and f64, so cross-precision comparison is bitwise)."""
    rng = random.Random(seed)
    ops: List[Tuple] = []
    killed = False
    for _ in range(nops):
        d, s = rng.randrange(_NRANKS), rng.randrange(_NRANKS)
        x = float(rng.randint(1, 9))
        p = rng.choice((0.5, 1.0, 1.5))
        r = rng.random()
        if epochs and r < 0.08:
            ops.append(("epoch",))
        elif kills and not killed and r < 0.14:
            ops.append(("kill", rng.randrange(_NRANKS)))
            killed = True
        elif r < 0.48:
            ops.append(("deposit", d, s, x, p))
        elif puts and r < 0.58:
            ops.append(("put", d, s, x, p))
        elif drains and r < 0.66:
            ops.append((rng.choice(("drain", "reset")), d, s))
        elif r < 0.86:
            ops.append(("collect", d, s))
        elif puts and r < 0.93:
            ops.append(("read", d, s))
        else:
            ops.append(("version", d, s))
    return ops


# ---------------------------------------------------------------------------
# adapters
# ---------------------------------------------------------------------------


class RefAdapter:
    """The reference model behind the common adapter surface."""

    name = "reference"

    def __init__(self) -> None:
        self.ref = ReferenceTransport(_NRANKS)

    def apply(self, op: Tuple):
        kind = op[0]
        if kind == "deposit":
            _, d, s, x, p = op
            self.ref.deposit(d, s, x, p)
            return None
        if kind == "put":
            _, d, s, x, p = op
            self.ref.put(d, s, x, p)
            return None
        if kind == "collect":
            return ("collect",) + self.ref.collect(op[1], op[2])
        if kind == "read":
            return ("read",) + self.ref.read(op[1], op[2])
        if kind == "version":
            return ("version", self.ref.version(op[1], op[2]))
        if kind in ("drain", "reset"):
            self.ref.drain(op[1], op[2])
            return None
        if kind == "epoch":
            self.ref.epoch_switch(self.ref.epoch + 1)
            return None
        if kind == "kill":
            self.ref.kill(op[1])
            return None
        raise ValueError(f"unknown op {op!r}")

    def snapshot(self) -> Tuple:
        return tuple(self.ref.version(d, s) for d, s in _PAIRS)

    def ledger(self) -> Optional[dict]:
        return self.ref.ledger()

    def close(self) -> None:
        pass


class SimAdapter:
    """``SimTransport`` on a virtual event loop; deliveries settle
    (zero latency, drained queue) before any observation — the harness
    checks the *quiescent-state* contract, the sim's own invariant rules
    cover in-flight accounting."""

    name = "sim"

    def __init__(self) -> None:
        from bluefog_tpu.sim.events import EventLoop, VirtualClock
        from bluefog_tpu.sim.transport import SimTransport

        self.loop = EventLoop()
        self.t = SimTransport(self.loop, VirtualClock(self.loop))
        self.epoch = 0

    def _settle(self) -> None:
        self.loop.run_until(self.loop.now)

    def apply(self, op: Tuple):
        kind = op[0]
        if kind == "deposit":
            _, d, s, x, p = op
            self.t.deposit(self.epoch, s, d, x, p, 0.0)
            self._settle()
            return None
        if kind == "collect":
            return ("collect",) + self.t.collect(self.epoch, op[1], op[2])
        if kind == "version":
            return ("version",
                    self.t.read_version(self.epoch, op[1], op[2]))
        if kind == "epoch":
            for dst in range(_NRANKS):
                self.t.retire_epoch(dst, self.epoch, range(_NRANKS))
            self.epoch += 1
            return None
        if kind == "kill":
            self.t.kill(op[1])
            return None
        raise ValueError(f"sim arm cannot represent {op!r}")

    def snapshot(self) -> Tuple:
        return tuple(self.t.read_version(self.epoch, d, s)
                     for d, s in _PAIRS)

    def ledger(self) -> Optional[dict]:
        led = self.t.ledger(include=range(_NRANKS))
        return {"deposits": led["deposits"], "collected": led["collected"],
                "pending": led["pending"], "balanced": led["balanced"]}

    def close(self) -> None:
        pass


class _WindowAdapter:
    """Common driver for the window transports: one window object per
    rank of a 2-rank job, mail slot index == writer rank (maxd = 2), and
    per-slot ``seen`` counters turning raw versions into the fresh-count
    contract ``collect`` promises."""

    def __init__(self) -> None:
        self.wins = self._make_windows()  # rank -> window
        self.seen: Dict[Tuple[int, int], int] = {p: 0 for p in _PAIRS}

    # subclasses provide the windows and may wrap writes (env scoping)
    def _make_windows(self):
        raise NotImplementedError

    def _write(self, src: int, dst: int, array, p: float,
               accumulate: bool) -> None:
        self.wins[src].write(dst, slot=src, array=array, p=p,
                             accumulate=accumulate)

    @staticmethod
    def _scalar(a: np.ndarray):
        flat = np.asarray(a).reshape(-1)
        if flat.size and not np.all(flat == flat[0]):
            return ("TORN", tuple(float(v) for v in flat))
        return float(flat[0]) if flat.size else 0.0

    def apply(self, op: Tuple):
        kind = op[0]
        if kind in ("deposit", "put"):
            _, d, s, x, p = op
            arr = np.full(_SHAPE, x, _DTYPE)
            self._write(s, d, arr, p, accumulate=(kind == "deposit"))
            return None
        if kind == "collect":
            _, d, s = op
            a, p, ver = self.wins[d].read(s, collect=True, src=s)
            fresh = ver - self.seen[(d, s)]
            self.seen[(d, s)] = ver
            x = self._scalar(a)
            if fresh <= 0 or p == 0.0:
                # logically-zero slot: the window reports its version,
                # the fresh-count contract reports nothing retired
                return ("collect", 0.0, 0.0, 0)
            return ("collect", x, float(p), int(fresh))
        if kind == "read":
            _, d, s = op
            a, p, ver = self.wins[d].read(s, collect=False, src=s)
            return ("read", self._scalar(a), float(p), int(ver))
        if kind == "version":
            _, d, s = op
            return ("version", int(self.wins[d].read_version(s, src=s)))
        if kind in ("drain", "reset"):
            _, d, s = op
            if kind == "drain":
                self.wins[d].force_drain(s, src=s)
            else:
                self.wins[d].reset(s, src=s)
            # the drain retires the slot's uncollected versions
            self.seen[(d, s)] = int(self.wins[d].read_version(s, src=s))
            return None
        raise ValueError(f"window arm cannot represent {op!r}")

    def snapshot(self) -> Tuple:
        return tuple(int(self.wins[d].read_version(s, src=s))
                     for d, s in _PAIRS)

    def ledger(self) -> Optional[dict]:
        return None

    def close(self) -> None:
        for rank in sorted(self.wins, reverse=True):
            try:
                self.wins[rank].close(unlink=(rank == 0))
            except Exception:
                pass


class NativeShmAdapter(_WindowAdapter):
    name = "shm-native"

    def _make_windows(self):
        from bluefog_tpu.native.shm_native import NativeShmWindow

        job = _fresh_job("shm")
        # chunk=8 bytes -> the 16-byte payload streams as 2 chunks, so
        # the chunk ring genuinely runs even at this tiny size
        return {r: NativeShmWindow(job, "conf", r, _NRANKS, _NRANKS,
                                   _SHAPE, _DTYPE, chunk=8)
                for r in range(_NRANKS)}


class FallbackShmAdapter(_WindowAdapter):
    name = "shm-fallback"

    def _make_windows(self):
        from bluefog_tpu.native.shm_native import FallbackShmWindow

        job = _fresh_job("fb")
        return {r: FallbackShmWindow(job, "conf", r, _NRANKS, _NRANKS,
                                     _SHAPE, _DTYPE)
                for r in range(_NRANKS)}


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TcpAdapter(_WindowAdapter):
    """Two real TCP ranks in one process (runtime keyed by (job, rank));
    rank 0 hosts the coordinator, deposits cross the loopback wire.
    ``chunked=False`` pins the legacy whole-payload ``_OP_WRITE`` arm."""

    def __init__(self, chunked: bool = True):
        self.chunked = chunked
        self.name = "tcp-chunked" if chunked else "tcp-legacy"
        super().__init__()

    def _make_windows(self):
        from bluefog_tpu.native import tcp_transport as tt

        self._tt = tt
        self.job = _fresh_job("tcp")
        coord = f"127.0.0.1:{_free_port()}"
        built: Dict[int, object] = {}
        errors: List[BaseException] = []

        def _build(rank: int) -> None:
            try:
                # construct OUTSIDE the class lock: both ranks' runtimes
                # must come up concurrently (registration blocks on the
                # full table), then publish under the lock
                rt = tt._JobRuntime(self.job, rank, _NRANKS, coord)
                with tt._JobRuntime._cls_lock:
                    tt._JobRuntime._by_key[(self.job, rank)] = rt
                built[rank] = rt
            except BaseException as exc:  # surfaced to the caller
                errors.append(exc)

        threads = [threading.Thread(target=_build, args=(r,), daemon=True)
                   for r in range(_NRANKS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        if errors or len(built) != _NRANKS:
            raise RuntimeError(f"tcp pair bring-up failed: {errors}")
        return {r: tt.TcpShmWindow(self.job, "conf", r, _NRANKS, _NRANKS,
                                   _SHAPE, _DTYPE, coord)
                for r in range(_NRANKS)}

    def _write(self, src, dst, array, p, accumulate):
        # the arm is selected per write: tcp_chunked()/chunk geometry
        # are env-driven reads at deposit time (single-threaded driver)
        saved = {k: os.environ.get(k)
                 for k in ("BFTPU_TCP_CHUNKED", "BLUEFOG_SHM_CHUNK_BYTES")}
        os.environ["BFTPU_TCP_CHUNKED"] = "1" if self.chunked else "0"
        os.environ["BLUEFOG_SHM_CHUNK_BYTES"] = "8"  # 2-chunk streams
        try:
            super()._write(src, dst, array, p, accumulate)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def close(self) -> None:
        super().close()
        for r in range(_NRANKS):
            try:
                self._tt._JobRuntime.drop(self.job, r)
            except Exception:
                pass


#: arm name -> zero-arg factory.  CORE arms accept the core vocabulary;
#: WINDOW arms additionally accept put/read/drain/reset.
ARM_FACTORIES: Dict[str, Callable[[], object]] = {
    "reference": RefAdapter,
    "sim": SimAdapter,
    "shm-native": NativeShmAdapter,
    "shm-fallback": FallbackShmAdapter,
    "tcp-chunked": lambda: TcpAdapter(chunked=True),
    "tcp-legacy": lambda: TcpAdapter(chunked=False),
}
CORE_ARMS = ("reference", "sim", "shm-native", "shm-fallback",
             "tcp-chunked", "tcp-legacy")
WINDOW_ARMS = ("reference", "shm-native", "shm-fallback", "tcp-chunked",
               "tcp-legacy")


def _shm_native_available() -> bool:
    from bluefog_tpu.native import get_lib
    from bluefog_tpu.native.shm_native import _force_fallback

    return get_lib() is not None and not _force_fallback()


# ---------------------------------------------------------------------------
# the differential driver + ddmin shrink
# ---------------------------------------------------------------------------


def run_schedule(arms: Dict[str, object], schedule: Sequence[Tuple],
                 *, compare_ledgers: bool = False) -> Optional[dict]:
    """Drive every arm through ``schedule``; after EVERY op compare the
    op result and the full version snapshot across arms.  Returns None
    (conformant) or a divergence record ``{step, op, field, values}``."""
    names = sorted(arms)
    for i, op in enumerate(schedule):
        results = {}
        for name in names:
            try:
                results[name] = arms[name].apply(op)
            except Exception as exc:
                results[name] = ("EXCEPTION", type(exc).__name__, str(exc))
        if len(set(map(repr, results.values()))) > 1:
            return {"step": i, "op": op, "field": "result",
                    "values": results}
        snaps = {name: arms[name].snapshot() for name in names}
        if len(set(snaps.values())) > 1:
            return {"step": i, "op": op, "field": "versions",
                    "values": snaps}
    if compare_ledgers:
        ledgers = {n: arms[n].ledger() for n in names}
        ledgers = {n: v for n, v in ledgers.items() if v is not None}
        keys = set().union(*(set(v) for v in ledgers.values())) \
            if ledgers else set()
        common = [k for k in sorted(keys)
                  if all(k in v for v in ledgers.values())]
        vals = {n: tuple(v[k] for k in common) for n, v in ledgers.items()}
        if len(set(vals.values())) > 1:
            return {"step": len(schedule), "op": ("ledger",),
                    "field": "ledger", "values": ledgers}
        for n, v in ledgers.items():
            if v.get("balanced") is False:
                return {"step": len(schedule), "op": ("ledger",),
                        "field": "ledger", "values": {n: v}}
    return None


def differential(arm_names: Sequence[str], schedule: Sequence[Tuple],
                 *, compare_ledgers: bool = False,
                 factories: Optional[Dict[str, Callable]] = None,
                 ) -> Optional[dict]:
    """Build fresh arms, run the schedule, tear down.  The re-runnable
    unit ddmin shrinks over."""
    factories = ARM_FACTORIES if factories is None else factories
    arms = {}
    try:
        for name in arm_names:
            arms[name] = factories[name]()
        return run_schedule(arms, schedule,
                            compare_ledgers=compare_ledgers)
    finally:
        for a in arms.values():
            try:
                a.close()
            except Exception:
                pass


def shrink_ops(schedule: Sequence[Tuple],
               reproduces: Callable[[Sequence[Tuple]], bool],
               ) -> Tuple[List[Tuple], int]:
    """ddmin over op schedules (same algorithm as
    ``sim/campaign.shrink_schedule``, on ops instead of fault events):
    repeatedly try dropping chunks (subsets and complements) while the
    divergence still reproduces; returns ``(1-minimal schedule, runs)``.
    """
    current = list(schedule)
    runs = 0
    if not current:
        return current, runs
    granularity = 2
    while len(current) >= 1:
        chunk = max(1, len(current) // granularity)
        pieces = [current[i:i + chunk]
                  for i in range(0, len(current), chunk)]
        reduced = False
        for idx in range(len(pieces)):
            candidate = [op for j, p in enumerate(pieces) for op in p
                         if j != idx]
            runs += 1
            if reproduces(candidate):
                current = candidate
                granularity = max(granularity - 1, 2)
                reduced = True
                break
        if not reduced:
            if granularity >= len(current):
                break
            granularity = min(len(current), granularity * 2)
    # final 1-minimality pass: no single op is droppable
    for i in range(len(current) - 1, -1, -1):
        candidate = current[:i] + current[i + 1:]
        runs += 1
        if reproduces(candidate):
            current = candidate
    return current, runs


def _report_divergence(report, rule: str, arm_names: Sequence[str],
                       seed: int, schedule: List[Tuple], div: dict,
                       *, compare_ledgers: bool = False) -> None:
    """Shrink a divergent schedule to its 1-minimal repro and file it."""
    def _reproduces(sub: Sequence[Tuple]) -> bool:
        try:
            return differential(arm_names, sub,
                                compare_ledgers=compare_ledgers) is not None
        except Exception:
            return False

    minimal, runs = shrink_ops(schedule, _reproduces)
    report.add(Finding(
        rule, f"seed={seed}",
        f"transports diverge on {div['field']} after {div['op']!r} "
        f"(step {div['step']}): {div['values']!r}; 1-minimal repro "
        f"({runs} shrink runs): {minimal!r}"))


# ---------------------------------------------------------------------------
# seeded mutants (the self-test / fixture corpus)
# ---------------------------------------------------------------------------


class ReorderingRefAdapter(RefAdapter):
    """Seeded bug: commits deposits OUT OF ORDER — each deposit is
    buffered and the backlog is flushed last-in-first-out only when a
    non-deposit op arrives.  An intervening collect observes the slot
    empty; the differential must catch it (ascending-commit violation
    made observable)."""

    name = "mutant-out-of-order-commit"

    def __init__(self) -> None:
        super().__init__()
        self._backlog: List[Tuple] = []

    def apply(self, op: Tuple):
        if op[0] == "deposit":
            self._backlog.append(op)
            return None
        for held in reversed(self._backlog):
            super().apply(held)
        self._backlog.clear()
        return super().apply(op)


class LossyDrainReference(ReferenceTransport):
    """Seeded bug: force_drain discards the slot's uncollected mass
    without accounting it — the mass-ledger identity must break."""

    def drain(self, dst: int, src: int) -> None:
        s = self._slots.get((self.epoch, int(dst), int(src)))
        if s is None:
            return
        s.seen = s.version
        s.x, s.p = 0.0, 0.0
        s.drained = s.version  # wiped; never credited to any bin


class StaleReseedReference(ReferenceTransport):
    """Seeded bug: an epoch switch retires the ledger but SKIPS the
    re-seed — the new epoch inherits the old epoch's slot state."""

    def epoch_switch(self, new_epoch: int) -> None:
        carried = {k: s for k, s in self._slots.items()
                   if k[0] == self.epoch}
        super().epoch_switch(new_epoch)
        for (_ep, dst, src), s in carried.items():
            self._slots[(self.epoch, dst, src)] = s


class StaleReseedAdapter(RefAdapter):
    name = "mutant-epoch-reseed-skipped"

    def __init__(self) -> None:
        self.ref = StaleReseedReference(_NRANKS)


class _OverclaimedTransport:
    """Seeded bug for the capability lint: claims a fused scale (and a
    future tier) its write() cannot deliver."""

    from bluefog_tpu.native.capabilities import TransportCaps as _TC

    CAPS = _TC(name="overclaimed", fused_accumulate=True, fused_scale=True,
               fused_combine=True, zero_copy_collect=False,
               chunked_streaming=False, wire_quantization=False,
               resume=False, device_resident=True)

    def write(self, dst, slot, array, p=1.0, accumulate=False):
        pass  # no scale kwarg, no supports_scale attr


#: pinned repro schedules for the mutants (found by the generator, frozen
#: so the self-test replays them bit-identically)
MUTANT_PINS: Dict[str, List[Tuple]] = {
    "out-of-order-commit": [("deposit", 0, 1, 3.0, 1.0),
                            ("deposit", 0, 1, 2.0, 0.5),
                            ("collect", 0, 1)],
    "epoch-reseed-skipped": [("deposit", 1, 0, 4.0, 1.0),
                             ("epoch",),
                             ("version", 1, 0)],
}


def mutant_out_of_order_findings() -> List[Finding]:
    """Differential vs the reordering mutant + ddmin down to the minimal
    repro; ≥1 finding iff the harness catches the seeded bug."""
    factories = dict(ARM_FACTORIES)
    factories["mutant"] = ReorderingRefAdapter
    arms = ("reference", "mutant")
    schedule = gen_schedule(7, 40)
    div = differential(arms, schedule, factories=factories)
    if div is None:
        return []
    minimal, _runs = shrink_ops(
        schedule,
        lambda sub: differential(arms, sub,
                                 factories=factories) is not None)
    return [Finding("conformance.differential",
                    "mutant:out-of-order-commit",
                    f"out-of-order commit diverges at {div['op']!r}; "
                    f"minimal repro: {minimal!r}")]


def mutant_reseed_findings() -> List[Finding]:
    factories = dict(ARM_FACTORIES)
    factories["mutant"] = StaleReseedAdapter
    div = differential(("reference", "mutant"),
                       MUTANT_PINS["epoch-reseed-skipped"],
                       factories=factories)
    if div is None:
        return []
    return [Finding("conformance.differential",
                    "mutant:epoch-reseed-skipped",
                    f"skipped re-seed leaks old-epoch state: {div['op']!r} "
                    f"at step {div['step']}")]


def mutant_lossy_drain_findings() -> List[Finding]:
    ref = LossyDrainReference(_NRANKS)
    ref.deposit(0, 1, 5.0, 1.0)
    ref.drain(0, 1)
    led = ref.ledger()
    if led["balanced"]:
        return []
    return [Finding("conformance.ledger", "mutant:drain-loses-mass",
                    f"drain dropped committed mass from the ledger: {led!r}")]


def mutant_overclaim_findings() -> List[Finding]:
    from bluefog_tpu.analysis.transport_spec import check_caps_honest

    problems = check_caps_honest({"overclaimed": _OverclaimedTransport})
    return [Finding("transport.caps-honest", "mutant:capability-overclaim",
                    p) for p in problems]


# ---------------------------------------------------------------------------
# registered rules
# ---------------------------------------------------------------------------

#: pinned seeds per rule — frozen so CI runs are reproducible; bumping a
#: seed is a reviewed change, not noise
SHM_SEEDS = (11, 12, 13, 14)
TCP_SEEDS = (21, 22)
EPOCH_SEEDS = (31, 32, 33, 34, 35, 36)


@registry.rule("conformance.differential-shm", "conformance",
               "shm windows (native + fallback) match the reference model "
               "and SimTransport on pinned op schedules")
def _rule_differential_shm(report) -> None:
    native = _shm_native_available()
    for seed in SHM_SEEDS:
        # core pass: every in-process transport speaks this vocabulary
        arms = ["reference", "sim", "shm-fallback"]
        if native:
            arms.append("shm-native")
        schedule = gen_schedule(seed, 60)
        report.subjects_checked += 1
        div = differential(arms, schedule)
        if div is not None:
            _report_divergence(report, "conformance.differential-shm",
                               arms, seed, schedule, div)
        # window pass: puts/reads/drains (sim cannot represent these)
        arms = ["reference", "shm-fallback"] + (["shm-native"] if native
                                                else [])
        schedule = gen_schedule(seed, 60, puts=True, drains=True)
        report.subjects_checked += 1
        div = differential(arms, schedule)
        if div is not None:
            _report_divergence(report, "conformance.differential-shm",
                               arms, seed, schedule, div)
    if not native:
        report.add(Finding("conformance.differential-shm", "arms",
                           "native shm library unavailable: native arm "
                           "skipped (fallback arm still checked)",
                           Severity.WARNING))


@registry.rule("conformance.differential-tcp", "conformance",
               "both TCP arms (chunked + legacy) match the reference model "
               "across a real loopback wire on pinned op schedules")
def _rule_differential_tcp(report) -> None:
    for seed in TCP_SEEDS:
        arms = ("reference", "tcp-chunked", "tcp-legacy")
        schedule = gen_schedule(seed, 30, puts=True, drains=True)
        report.subjects_checked += 1
        try:
            div = differential(arms, schedule)
        except Exception as exc:
            report.add(Finding("conformance.differential-tcp",
                               f"seed={seed}",
                               f"tcp harness failed to run: {exc!r}"))
            continue
        if div is not None:
            _report_divergence(report, "conformance.differential-tcp",
                               arms, seed, schedule, div)


@registry.rule("conformance.epoch-death", "conformance",
               "epoch quiesce/re-seed and writer death: SimTransport "
               "matches the reference model, ledgers settle balanced")
def _rule_epoch_death(report) -> None:
    for seed in EPOCH_SEEDS:
        kills = seed % 2 == 0  # half the corpus exercises writer death
        schedule = gen_schedule(seed, 50, epochs=True, kills=kills)
        # final quiesce so the count ledgers are comparable (live == 0);
        # ledgers only compare on kill-free runs — death settlement
        # (adoption/write-off) is the sim fleet's own rule family
        schedule = schedule + [("epoch",)]
        arms = ("reference", "sim")
        report.subjects_checked += 1
        div = differential(arms, schedule, compare_ledgers=not kills)
        if div is not None:
            _report_divergence(report, "conformance.epoch-death", arms,
                               seed, schedule, div,
                               compare_ledgers=not kills)


@registry.rule("conformance.shrinker", "conformance",
               "the ddmin shrink reduces a planted divergence to its "
               "1-minimal repro schedule")
def _rule_shrinker(report) -> None:
    factories = dict(ARM_FACTORIES)
    factories["mutant"] = ReorderingRefAdapter
    arms = ("reference", "mutant")
    noise = gen_schedule(99, 24)
    schedule = noise + MUTANT_PINS["out-of-order-commit"]
    report.subjects_checked += 1

    def _reproduces(sub):
        return differential(arms, sub, factories=factories) is not None

    if not _reproduces(schedule):
        report.add(Finding("conformance.shrinker", "planted mutant",
                           "planted out-of-order-commit mutant did not "
                           "diverge — the harness lost its teeth"))
        return
    minimal, runs = shrink_ops(schedule, _reproduces)
    report.metric("conformance.shrink_runs", float(runs))
    report.metric("conformance.shrunk_len", float(len(minimal)))
    if len(minimal) > 3:
        report.add(Finding("conformance.shrinker", "planted mutant",
                           f"ddmin left a non-minimal repro of "
                           f"{len(minimal)} ops: {minimal!r}"))
    if not _reproduces(minimal):
        report.add(Finding("conformance.shrinker", "planted mutant",
                           "shrunk schedule no longer reproduces"))


# ---------------------------------------------------------------------------
# --changed-only support + self-test arm
# ---------------------------------------------------------------------------

#: transport/runtime source file -> the rule families that gate it (the
#: pre-commit mapping behind ``--changed-only``)
FAMILY_MAP: Dict[str, Tuple[str, ...]] = {
    "bluefog_tpu/native/shm_native.py": ("protocol", "resilience",
                                         "transport", "conformance",
                                         "interleave"),
    "bluefog_tpu/native/tcp_transport.py": ("wire", "transport",
                                            "conformance", "interleave"),
    "bluefog_tpu/native/wire_codec.py": ("wire", "transport"),
    "bluefog_tpu/native/routed_transport.py": ("transport", "conformance"),
    "bluefog_tpu/native/capabilities.py": ("transport",),
    "bluefog_tpu/sim/transport.py": ("sim", "partition", "serve",
                                     "transport", "conformance"),
    "bluefog_tpu/progress/engine.py": ("progress", "transport",
                                       "interleave"),
    "bluefog_tpu/islands.py": ("protocol", "transport", "wire"),
    "bluefog_tpu/serving/region.py": ("serve", "interleave"),
    # the snapshot distribution plane: tree math, delta codec and the
    # feed protocol are all gated by the distrib family (the codec
    # additionally by wire — deltas ride the wire_codec chunks)
    "bluefog_tpu/serve/distrib/__init__.py": ("distrib",),
    "bluefog_tpu/serve/distrib/tree.py": ("distrib",),
    "bluefog_tpu/serve/distrib/delta.py": ("distrib", "wire"),
    "bluefog_tpu/serve/distrib/feed.py": ("distrib", "wire"),
    "bluefog_tpu/serve/distrib/sub.py": ("distrib", "serve"),
    "bluefog_tpu/analysis/distrib_rules.py": ("distrib",),
    # the fleet monitor: the alert engine and its sim twin are gated by
    # the monitor family; the scraper and store additionally by
    # introspect (they ride the statuspage seqlock protocol) and the
    # report joiner by telemetry (it joins the journal schema)
    "bluefog_tpu/monitor/rules.py": ("monitor",),
    "bluefog_tpu/monitor/scraper.py": ("monitor", "introspect"),
    "bluefog_tpu/monitor/store.py": ("monitor", "introspect"),
    "bluefog_tpu/monitor/tail.py": ("monitor", "telemetry"),
    "bluefog_tpu/monitor/report.py": ("monitor", "telemetry"),
}


def families_for_paths(paths: Sequence[str]) -> List[str]:
    """Map touched files to the families that must re-run.  Unknown
    files under analysis/ select their own family by module name; any
    other unknown file selects everything (safe default)."""
    out = set()
    for raw in paths:
        rel = os.path.normpath(raw).replace(os.sep, "/")
        rel = rel.lstrip("./")
        if rel in FAMILY_MAP:
            out.update(FAMILY_MAP[rel])
            continue
        if rel.startswith("bluefog_tpu/analysis/"):
            stem = os.path.basename(rel)
            for fam in registry.families():
                if stem.startswith(fam.replace("-", "_")):
                    out.add(fam)
                    break
            else:
                return sorted(registry.families())
            continue
        return sorted(registry.families())
    return sorted(out)


def selftest_conformance() -> List[Tuple[str, bool, str]]:
    """The --self-test arm: the live differential corpus must be clean
    AND every seeded conformance mutant must be caught.  Returns
    ``(label, ok, detail)`` rows."""
    from bluefog_tpu.analysis.engine import Report

    rows: List[Tuple[str, bool, str]] = []
    report = Report()
    registry.run(families=["conformance"], report=report)
    clean = [f for f in report.findings if f.severity == Severity.ERROR]
    rows.append(("conformance corpus", not clean,
                 f"{report.subjects_checked} schedules, "
                 f"{len(clean)} divergence(s)"))
    for label, fn in (
            ("mutant out-of-order-commit", mutant_out_of_order_findings),
            ("mutant drain-loses-mass", mutant_lossy_drain_findings),
            ("mutant epoch-reseed-skipped", mutant_reseed_findings),
            ("mutant capability-overclaim", mutant_overclaim_findings)):
        caught = bool(fn())
        rows.append((label, caught,
                     "caught" if caught else "NOT caught"))
    return rows
