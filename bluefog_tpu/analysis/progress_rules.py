"""Rule family: the async progress engine's queue state machine.

:mod:`bluefog_tpu.progress` promises three invariants (engine.py module
docstring) that nothing in the type system enforces:

- **queue-state-machine** — every submitted op resolves its handle
  exactly once, no op executes while the engine is quiesced, and a
  quiesce/resume cycle loses nothing: the parked queue replays intact.
  Checked by driving a REAL manual-mode :class:`ProgressEngine` through
  every bounded interleaving of submit/step/quiesce/resume (exhaustive
  at small bounds, the seqlock-model playbook).
- **handle-lifecycle** — a :class:`WinHandle` resolves at most once and
  is only observed (``result``) after it resolved.  Checked as a trace
  lint over handle event sequences.
- **fusion-order** — coalescing preserves per-window submission order:
  a batch is a CONTIGUOUS run of queue-front ops sharing kind, window,
  and weights, within the byte budget; ``update`` never fuses.  Checked
  against the batches a real engine actually pops (the recording
  backend's ``fuse`` concatenates op tags, so each execute call exposes
  its batch composition).

The fixture corpus seeds the matching bugs: a quiesce that drops the
queue, a handle completed twice, a fuser that reorders across windows.
"""

from __future__ import annotations

import itertools
from typing import Any, List, Optional, Sequence, Tuple

from bluefog_tpu.analysis.engine import Finding, Report, Severity, registry
from bluefog_tpu.progress import ProgressEngine

__all__ = ["run_schedule", "check_schedule", "check_handle_events",
           "check_batches", "schedule_corpus", "FUSION_STREAMS"]

_SM = "progress.queue-state-machine"
_HL = "progress.handle-lifecycle"
_FO = "progress.fusion-order"


class _RecordingBackend:
    """Backend whose ``fuse`` concatenates op tags: every ``execute``
    call then records exactly which submitted ops the engine coalesced,
    and in what order."""

    def __init__(self):
        self.batches: List[Tuple[str, str, Tuple[int, ...]]] = []
        self.parked = False           # driver-maintained quiesce mirror
        self.parked_executes = 0

    def execute(self, kind, window, payload, weights, kwargs):
        if self.parked:
            self.parked_executes += 1
        if kind == "update":
            seqs = (int(kwargs.get("seq", -1)),)
        else:
            seqs = tuple(payload) if isinstance(payload, tuple) else ()
        self.batches.append((kind, window, seqs))
        return ("ok", kind, window)

    def fuse(self, kind, window, payloads):
        out: Tuple[int, ...] = ()
        for p in payloads:
            out = out + tuple(p)
        return out


def run_schedule(schedule: Sequence[Any],
                 engine_cls=ProgressEngine,
                 fusion_bytes: int = 1 << 20):
    """Drive one manual-mode engine through ``schedule`` then drain.

    Schedule atoms: ``("put"|"accumulate"|"update", window)`` submits,
    ``"step"`` processes one batch, ``"quiesce"``/``"resume"`` park and
    unpark.  Returns ``(backend, submissions, handles, crashes)`` where
    ``submissions`` is ``[(seq, kind, window, weights, nbytes)]``.
    """
    be = _RecordingBackend()
    eng = engine_cls(be, start_worker=False, queue_depth=64,
                     fusion_bytes=fusion_bytes)
    submissions: List[Tuple[int, str, str, Any, int]] = []
    handles = []
    crashes: List[str] = []
    seq = 0
    for act in schedule:
        try:
            if act == "step":
                eng.step()
            elif act == "quiesce":
                eng.quiesce()
                be.parked = True
            elif act == "resume":
                eng.resume()
                be.parked = False
            else:
                kind, window = act
                if kind == "update":
                    h = eng.submit("update", window, seq=seq)
                    submissions.append((seq, kind, window, None, 0))
                else:
                    h = eng.submit(kind, window, payload=(seq,),
                                   nbytes=8)
                    submissions.append((seq, kind, window, None, 8))
                handles.append((seq, h))
                seq += 1
        except Exception as e:  # noqa: BLE001 - a crash IS a finding
            crashes.append(f"{act!r}: {e!r}")
    eng.resume()
    be.parked = False
    try:
        while eng.step():
            pass
        eng.stop()
    except Exception as e:  # noqa: BLE001
        crashes.append(f"drain: {e!r}")
    return be, submissions, handles, crashes


def check_schedule(schedule: Sequence[Any], subject: str = "schedule",
                   engine_cls=ProgressEngine) -> List[Finding]:
    """Model-check one interleaving against the state-machine contract."""
    be, submissions, handles, crashes = run_schedule(
        schedule, engine_cls=engine_cls)
    findings: List[Finding] = []

    def add(msg: str, severity: str = Severity.ERROR) -> None:
        findings.append(Finding(_SM, subject, msg, severity))

    for c in crashes:
        add(f"engine raised on the caller thread: {c}")
    if be.parked_executes:
        add(f"{be.parked_executes} op(s) executed while quiesced — the "
            "park must gate execution until resume")
    for seq, h in handles:
        if not h.done():
            add(f"op {seq} submitted but its handle never resolved "
                "after a full drain (lost across quiesce/resume?)")
        elif h.exception() is not None:
            add(f"op {seq} failed spuriously: {h.exception()!r}")
    executed = [s for _, _, seqs in be.batches for s in seqs]
    if sorted(executed) != list(range(len(submissions))):
        add(f"executed op set {sorted(executed)} != submitted "
            f"{list(range(len(submissions)))} (dropped or duplicated)")
    else:
        per_window: dict = {}
        for kind, window, seqs in be.batches:
            per_window.setdefault(window, []).extend(seqs)
        for window, seqs in per_window.items():
            if seqs != sorted(seqs):
                add(f"window {window!r} executed out of submission "
                    f"order: {seqs}")
    return findings


def schedule_corpus(length: int = 4) -> List[Tuple[Any, ...]]:
    """Every schedule of ``length`` atoms over the two-window alphabet —
    exhaustive at this bound, the same playbook as the seqlock models."""
    alphabet = (("put", "a"), ("put", "b"), ("update", "a"),
                "step", "quiesce", "resume")
    return list(itertools.product(alphabet, repeat=length))


def check_handle_events(events: Sequence[Tuple[str, str]],
                        subject: str = "events") -> List[Finding]:
    """Lint one handle event trace: ``(handle_id, action)`` with actions
    ``create`` / ``complete`` / ``fail`` / ``result``."""
    findings: List[Finding] = []
    state: dict = {}  # id -> "pending" | "resolved"

    def add(msg: str, severity: str = Severity.ERROR) -> None:
        findings.append(Finding(_HL, subject, msg, severity))

    for i, (hid, action) in enumerate(events):
        if action == "create":
            if hid in state:
                add(f"event {i}: handle {hid!r} created twice",
                    Severity.WARNING)
            state[hid] = "pending"
        elif action in ("complete", "fail"):
            if state.get(hid) == "resolved":
                add(f"event {i}: {action} on already-resolved handle "
                    f"{hid!r} — resolution must happen exactly once")
            elif hid not in state:
                add(f"event {i}: {action} on unknown handle {hid!r}")
            state[hid] = "resolved"
        elif action == "result":
            if state.get(hid) != "resolved":
                add(f"event {i}: result() returned on handle {hid!r} "
                    "before it resolved")
        else:
            add(f"event {i}: unknown action {action!r}", Severity.WARNING)
    return findings


def check_batches(submissions: Sequence[Tuple[int, str, str, Any, int]],
                  batches: Sequence[Tuple[str, str, Tuple[int, ...]]],
                  budget: int, subject: str = "batches") -> List[Finding]:
    """Verify a batch partition against the fusion-order contract."""
    findings: List[Finding] = []

    def add(msg: str) -> None:
        findings.append(Finding(_FO, subject, msg, Severity.ERROR))

    by_seq = {s[0]: s for s in submissions}
    flat = [s for _, _, seqs in batches for s in seqs]
    if sorted(flat) != sorted(by_seq):
        add(f"batches {flat} are not a partition of the submissions "
            f"{sorted(by_seq)}")
        return findings
    if flat != sorted(flat):
        add(f"global execution order {flat} reorders submissions — a "
            "batch may only take a CONTIGUOUS run off the queue front")
    for kind, window, seqs in batches:
        for s in seqs:
            _, k, w, _, _ = by_seq[s]
            if (k, w) != (kind, window):
                add(f"op {s} ({k}:{w}) landed in a {kind}:{window} "
                    "batch — fusion must not mix kinds or windows")
        weights = {repr(by_seq[s][3]) for s in seqs}
        if len(weights) > 1:
            add(f"batch {seqs} mixes weight maps {weights} — a fused "
                "deposit would apply one map to all of them")
        if kind == "update" and len(seqs) > 1:
            add(f"update batch {seqs} fused — combines are never "
                "coalesced")
        if len(seqs) > 1:
            total = sum(by_seq[s][4] for s in seqs)
            if total > budget:
                add(f"batch {seqs} totals {total} bytes over the "
                    f"{budget}-byte fusion budget")
    return findings


#: canonical op streams the fusion rule replays through a real engine:
#: (label, schedule, fusion_bytes)
FUSION_STREAMS = [
    ("same-window-run",
     [("put", "a"), ("put", "a"), ("put", "a"), "step", "step"], 1 << 20),
    ("window-switch-cuts",
     [("put", "a"), ("put", "b"), ("put", "a"), "step", "step", "step"],
     1 << 20),
    ("update-never-fuses",
     [("put", "a"), ("update", "a"), ("update", "a"), "step", "step",
      "step"], 1 << 20),
    ("budget-cuts",
     [("put", "a"), ("put", "a"), ("put", "a"), "step", "step"], 12),
    ("accumulate-run",
     [("accumulate", "a"), ("accumulate", "a"), "step"], 1 << 20),
]


@registry.rule(_SM, "progress",
               "exhaustive submit/step/quiesce/resume interleavings on a "
               "real manual-mode engine: nothing lost, nothing doubled")
def _run_state_machine(report: Report) -> None:
    for schedule in schedule_corpus(length=4):
        report.subjects_checked += 1
        report.extend(check_schedule(schedule,
                                     subject="sched" + repr(schedule)))


@registry.rule(_HL, "progress",
               "handle event traces from the canonical engine paths "
               "resolve exactly once, observed only after resolution")
def _run_handle_lifecycle(report: Report) -> None:
    canonical = {
        "submit-execute-result": [("h0", "create"), ("h0", "complete"),
                                  ("h0", "result")],
        "submit-fail": [("h0", "create"), ("h0", "fail")],
        "two-handles-interleaved": [("h0", "create"), ("h1", "create"),
                                    ("h1", "complete"), ("h0", "complete"),
                                    ("h0", "result"), ("h1", "result")],
        "completed-factory": [("h0", "create"), ("h0", "complete"),
                              ("h0", "result"), ("h0", "result")],
    }
    for label, events in canonical.items():
        report.subjects_checked += 1
        report.extend(check_handle_events(events, subject=label))


@registry.rule(_FO, "progress",
               "the batches a real engine pops preserve per-window "
               "submission order, compatibility, and the byte budget")
def _run_fusion_order(report: Report) -> None:
    for label, schedule, budget in FUSION_STREAMS:
        report.subjects_checked += 1
        be, submissions, _, crashes = run_schedule(schedule,
                                                   fusion_bytes=budget)
        for c in crashes:
            report.add(Finding(_FO, label, f"engine crashed: {c}"))
        report.extend(check_batches(submissions, be.batches, budget,
                                    subject=label))
