"""Rule family: the live introspection plane (status pages, holder words,
critical-path feed).

Three invariants the ``bftpu-top`` plane leans on, checked the same way
the adaptive family checks demotions — by DRIVING the real artifacts
(a real :class:`~bluefog_tpu.introspect.statuspage.StatusPage` writer,
a real :class:`~bluefog_tpu.resilience.adaptive.AdaptivePolicy`) and
linting what comes out:

- **status-page** — every page an external reader accepts must be
  schema/version-exact, settled (even seq), self-consistent (rank in
  range, edge records legal, ledger balance arithmetic intact).  A page
  that fails here would make ``bftpu-top`` lie about a running job.
- **holder-word** — a mutex holder word must name a live member: a rank
  outside the membership (or in the dead set) holding a word means the
  clear-on-release / clear-on-break path was skipped, and every future
  mutex wait would be blamed on a ghost.
- **critical-path-feed** — the blame counters feeding
  :meth:`AdaptivePolicy.corroborated` are cumulative: a snapshot
  sequence where any rank's count decreases means the feed was reset or
  raced, silently re-arming demotion for ranks the trace had cleared.

Pure ``check_*`` helpers (artifact in, findings out) so the fixture
corpus and the CLI share one implementation.
"""

from __future__ import annotations

import tempfile
from typing import Dict, List, Mapping, Sequence, Set

from bluefog_tpu.analysis.engine import Finding, Report, registry

_RULE_PAGE = "introspect.status-page"
_RULE_HOLDER = "introspect.holder-word"
_RULE_FEED = "introspect.critical-path-feed"


# ---------------------------------------------------------------------------
# status pages
# ---------------------------------------------------------------------------


def check_status_page(page: Mapping[str, object],
                      label: str) -> List[Finding]:
    """Structural lint of one decoded status page (the dict shape
    ``read_status_page`` returns and ``bftpu-top --json`` re-emits)."""
    from bluefog_tpu.introspect.statuspage import (
        EDGE_STATE_NAMES, MAX_EDGES, STATUS_SCHEMA, STATUS_VERSION)

    out: List[Finding] = []

    def bad(msg: str) -> None:
        out.append(Finding(_RULE_PAGE, label, msg))

    if page.get("schema") != STATUS_SCHEMA:
        bad(f"schema {page.get('schema')!r} != {STATUS_SCHEMA!r}")
    if page.get("version") != STATUS_VERSION:
        bad(f"version {page.get('version')!r} != {STATUS_VERSION}")
    seq = page.get("seq")
    if not isinstance(seq, int) or seq % 2 != 0:
        bad(f"seq {seq!r} is not even: the page was accepted mid-write")

    rank, nranks = page.get("rank"), page.get("nranks")
    if not (isinstance(rank, int) and isinstance(nranks, int)
            and 0 <= rank < max(nranks, 1)):
        bad(f"rank {rank!r} outside [0, nranks={nranks!r})")

    edges = page.get("edges") or []
    if len(edges) > MAX_EDGES:
        bad(f"{len(edges)} edge records exceed MAX_EDGES={MAX_EDGES}")
    legal_states = set(EDGE_STATE_NAMES.values())
    for e in edges:
        peer, state = e.get("peer"), e.get("state")
        if state not in legal_states:
            bad(f"edge peer={peer!r} has unknown state {state!r}")
        if not (isinstance(peer, int) and 0 <= peer) or peer == rank:
            bad(f"edge peer {peer!r} is not a valid remote rank")
        if not (float(e.get("deadline_s", 0.0)) >= 0.0):
            bad(f"edge peer={peer!r} deadline "
                f"{e.get('deadline_s')!r} is negative")

    led = page.get("ledger") or {}
    for k in ("deposits", "collected", "drained", "pending"):
        if float(led.get(k, 0.0)) < 0.0:
            bad(f"ledger {k} {led.get(k)!r} is negative")
    want = (float(led.get("deposits", 0.0)) - float(led.get("collected", 0.0))
            - float(led.get("drained", 0.0)))
    if abs(float(led.get("balance", 0.0)) - want) > 1e-9:
        bad(f"ledger balance {led.get('balance')!r} != "
            f"deposits - collected - drained = {want}")
    return out


def check_page_sequence(pages: Sequence[Mapping[str, object]],
                        label: str) -> List[Finding]:
    """Republishes from one rank: step, op_id, and epoch never go
    backward (each publish overwrites the whole page in place)."""
    out: List[Finding] = []
    for field in ("step", "op_id", "epoch"):
        prev = None
        for p in pages:
            cur = p.get(field)
            if prev is not None and isinstance(cur, int) and cur < prev:
                out.append(Finding(
                    _RULE_PAGE, label,
                    f"{field} went backward across republishes: "
                    f"{prev} -> {cur}"))
            if isinstance(cur, int):
                prev = cur
    return out


@registry.rule(
    _RULE_PAGE, "introspect",
    "Drive a real StatusPage writer through publish/read cycles (edges, "
    "ledger, epoch bump, in-place republish) and lint every page an "
    "external reader would accept: schema/version exact, seq even, rank "
    "and edge records in range, ledger balance arithmetic intact, "
    "step/op_id/epoch monotone.")
def _run_status_pages(report: Report) -> None:
    from bluefog_tpu.introspect import statuspage as sp
    from bluefog_tpu.native import shm_native

    with tempfile.TemporaryDirectory(prefix="bftpu_introspect_") as td:
        saved = shm_native._FALLBACK_DIR
        shm_native._FALLBACK_DIR = td
        try:
            job = "analysis-sp"
            for rank in range(2):
                page = sp.StatusPage(job, rank)
                seen: List[Dict[str, object]] = []
                try:
                    for step in range(1, 4):
                        epoch = 1 if step == 3 else 0
                        page.publish(
                            nranks=2, step=step, epoch=epoch, op_id=step,
                            last_op=f"win_update:g{step}",
                            ledger={"deposits": 4.0 * step,
                                    "collected": 3.0 * step,
                                    "drained": 0.5 * step,
                                    "pending": 0.5 * step},
                            edges=[(1 - rank, 1 if step == 2 else 0, 0.2)])
                        decoded = sp.read_status_page(
                            sp.status_page_path(job, rank))
                        seen.append(decoded)
                        report.subjects_checked += 1
                        report.extend(check_status_page(
                            decoded, f"{job}/r{rank}@step{step}"))
                finally:
                    page.close(unlink=True)
                report.extend(check_page_sequence(seen, f"{job}/r{rank}"))
            report.metric("introspect.pages_checked", 6)
        finally:
            shm_native._FALLBACK_DIR = saved


# ---------------------------------------------------------------------------
# holder words
# ---------------------------------------------------------------------------


def check_holder_words(holders: Mapping[int, int],
                       members: Set[int], dead: Set[int],
                       label: str) -> List[Finding]:
    """Every holder word must name a live member.  ``holders`` maps
    mutex rank -> holder rank (the decoded, 0-based view a
    ``HolderBoard.snapshot``/``collect`` exposes)."""
    out: List[Finding] = []
    for mutex_rank, holder in sorted(holders.items()):
        if holder in dead:
            out.append(Finding(
                _RULE_HOLDER, label,
                f"mutex {mutex_rank} held by DEAD rank {holder}: the "
                f"break/heal path must clear the word so waits stop "
                f"blaming a ghost"))
        elif holder not in members:
            out.append(Finding(
                _RULE_HOLDER, label,
                f"mutex {mutex_rank} held by rank {holder} outside the "
                f"membership {sorted(members)}: stale word survived a "
                f"release or epoch switch"))
    return out


@registry.rule(
    _RULE_HOLDER, "introspect",
    "Drive a real HolderBoard through the acquire/release/break "
    "lifecycle and audit the words after each step: a set word names "
    "the acquirer, release clears it, mutex_break (the heal path for a "
    "dead holder) clears it — no ghost holders at any point.")
def _run_holder_lifecycle(report: Report) -> None:
    from bluefog_tpu.native.shm_native import HolderBoard

    with tempfile.TemporaryDirectory(prefix="bftpu_introspect_") as td:
        from bluefog_tpu.native import shm_native
        saved = shm_native._FALLBACK_DIR
        shm_native._FALLBACK_DIR = td
        try:
            members = {0, 1, 2, 3}
            board = HolderBoard("analysis-hb", 4)
            try:
                report.subjects_checked += 1
                # acquire: rank 2 takes rank 0's window mutex
                board.set_holder(0, 2)
                snap = board.snapshot()
                report.extend(check_holder_words(
                    snap, members, set(), "analysis-hb[held]"))
                if snap.get(0) != 2:
                    report.add(Finding(
                        _RULE_HOLDER, "analysis-hb[held]",
                        f"acquire did not publish the holder: {snap}"))
                # conditional release by the right rank clears the word
                board.clear(0, 2)
                if 0 in board.snapshot():
                    report.add(Finding(
                        _RULE_HOLDER, "analysis-hb[released]",
                        "release by the holder left the word set"))
                # a raced conditional clear by a NON-holder is a no-op
                board.set_holder(1, 3)
                board.clear(1, 0)
                if board.snapshot().get(1) != 3:
                    report.add(Finding(
                        _RULE_HOLDER, "analysis-hb[raced-clear]",
                        "conditional clear by a non-holder clobbered "
                        "another rank's word"))
                # heal: rank 3 died holding mutex 1; break clears
                # unconditionally, after which the audit must be clean
                report.extend(check_holder_words(
                    board.snapshot(), members, set(), "analysis-hb[pre]"))
                board.clear(1)
                report.extend(check_holder_words(
                    board.snapshot(), members - {3}, {3},
                    "analysis-hb[healed]"))
            finally:
                board.close(unlink=True)
        finally:
            shm_native._FALLBACK_DIR = saved


# ---------------------------------------------------------------------------
# critical-path feed
# ---------------------------------------------------------------------------


def check_blame_monotone(snapshots: Sequence[Mapping[int, int]],
                         label: str) -> List[Finding]:
    """The per-rank critical-path blame counts are cumulative: across a
    snapshot sequence every rank's count must be non-negative and
    non-decreasing."""
    out: List[Finding] = []
    prev: Dict[int, int] = {}
    for i, snap in enumerate(snapshots):
        for rank, n in sorted(snap.items()):
            if n < 0:
                out.append(Finding(
                    _RULE_FEED, label,
                    f"snapshot {i}: rank {rank} blame count {n} < 0"))
            if n < prev.get(rank, 0):
                out.append(Finding(
                    _RULE_FEED, label,
                    f"rank {rank} blame count went backward "
                    f"({prev[rank]} -> {n} at snapshot {i}): the feed "
                    f"was reset mid-run and corroboration is unsound"))
        for rank, n in snap.items():
            prev[rank] = max(prev.get(rank, 0), int(n))
    return out


@registry.rule(
    _RULE_FEED, "introspect",
    "Drive a real AdaptivePolicy's critical-path feed (note_round_blame "
    "increments, feed_critical_path max-merges) and check the contract "
    "corroborated() relies on: counts only ever grow, the gate is open "
    "when no live trace feed exists and closed for unblamed peers when "
    "one does.")
def _run_critical_path_feed(report: Report) -> None:
    from bluefog_tpu.resilience.adaptive import AdaptivePolicy

    pol = AdaptivePolicy()
    report.subjects_checked += 1

    snaps: List[Dict[int, int]] = [dict(pol._cp_blame)]
    pol.note_round_blame(3)
    snaps.append(dict(pol._cp_blame))
    pol.note_round_blame(3)
    pol.note_round_blame(1)
    snaps.append(dict(pol._cp_blame))
    # a merge reporting LOWER totals than already observed must not
    # roll the counters back (max-merge)
    pol.feed_critical_path({3: 1, 2: 5})
    snaps.append(dict(pol._cp_blame))
    report.extend(check_blame_monotone(snaps, "adaptive-policy@4"))

    # gate semantics: without a live feed every peer is corroborated;
    # with one, only blamed peers are
    if not pol.corroborated(0):
        report.add(Finding(
            _RULE_FEED, "adaptive-policy@4",
            "corroborated() closed with no live trace feed: demotion "
            "would deadlock whenever tracing is off"))
    pol.set_live_feed(True)
    if pol.corroborated(0):
        report.add(Finding(
            _RULE_FEED, "adaptive-policy@4",
            "corroborated() open for a peer the live critical path "
            "never blamed"))
    if not (pol.corroborated(3) and pol.corroborated(2)):
        report.add(Finding(
            _RULE_FEED, "adaptive-policy@4",
            "corroborated() closed for a blamed peer: the feed is not "
            "reaching the gate"))
    report.metric("introspect.blame_snapshots", len(snaps))
