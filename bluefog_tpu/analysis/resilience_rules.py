"""Rule family 5: static verification of healed (post-failure) topologies.

When ranks die, :func:`bluefog_tpu.resilience.healing.heal_topology`
rebuilds the gossip over the survivors.  A healed topology is exactly as
load-bearing as a fresh one — every invariant the plan family checks on
the named corpus must hold on the healed artifacts too, or the surviving
job silently diverges:

- the dead ranks are fully EXCISED: no survivor, no node, no scheduled
  edge references them (a dead rank left in the plan deposits into a
  drained slot forever — its neighbors average in zeros);
- the survivor mixing matrix is doubly stochastic (row AND column sums
  1): Metropolis–Hastings over the symmetrized induced subgraph — the
  condition under which degraded gossip still converges to the exact
  survivor average;
- the spectral gap stays strictly positive: the ring-reconnect step must
  have restored connectivity whenever the excision cut the graph;
- the recompiled plan covers the healed edge set exactly, with valid
  permutation classes and consistent slot bookkeeping — the plan rules,
  re-run on the healed subject.

The corpus is every named topology x sizes 4..16 x a spread of dead-rank
sets (first rank, last rank, an interior pair, and — where it exists —
the star's center, the excision that forces a reconnect).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.resilience.healing import HealedTopology, heal_topology

from bluefog_tpu.analysis import plan_rules
from bluefog_tpu.analysis.engine import Finding, Report, registry

__all__ = [
    "HEALED_SIZES",
    "dead_sets",
    "check_dead_excised",
    "check_healed",
]

HEALED_SIZES: Tuple[int, ...] = tuple(range(4, 17))


def dead_sets(size: int) -> List[Tuple[int, ...]]:
    """The dead-rank sets exercised per (topology, size): single deaths
    at both id extremes, an interior pair, and near-majority loss."""
    out = [(0,), (size - 1,)]
    if size > 3:
        out.append((1, 2))
    if size > 5:
        out.append(tuple(range(1, size - 2)))  # 3 survivors
    return out


def check_dead_excised(healed: HealedTopology,
                       label: str = "healed") -> List[Finding]:
    """Every trace of the dead ranks must be gone from the healed
    artifacts: survivors, topology nodes, and plan edges (mapped back to
    global ids via ``to_global``)."""
    out: List[Finding] = []
    dead = set(healed.dead)
    leaked = dead & set(healed.survivors)
    if leaked:
        out.append(Finding(
            "resilience.dead-excised", label,
            f"dead rank(s) {sorted(leaked)} still listed as survivors — "
            "the healed gossip would keep scheduling a corpse"))
    if healed.plan.size != len(healed.survivors):
        out.append(Finding(
            "resilience.dead-excised", label,
            f"healed plan has size {healed.plan.size} but there are "
            f"{len(healed.survivors)} survivors"))
    to_global = healed.to_global
    bad_edges = []
    for cls in healed.plan.classes:
        for s, d in cls.perm:
            for local in (s, d):
                if 0 <= local < len(to_global) \
                        and to_global[local] in dead:
                    bad_edges.append((s, d))
    if bad_edges:
        out.append(Finding(
            "resilience.dead-excised", label,
            f"scheduled edge(s) {sorted(set(bad_edges))[:6]} map to dead "
            "global rank(s) — survivors would win_put into force-drained "
            "slots forever"))
    mapped = {to_global[i] for i in range(len(to_global))}
    if mapped & dead:
        out.append(Finding(
            "resilience.dead-excised", label,
            f"to_global maps local ids onto dead rank(s) "
            f"{sorted(mapped & dead)}"))
    return out


def check_healed(healed: HealedTopology, label: str = "healed",
                 report: Optional[Report] = None) -> Report:
    """All resilience + plan rules on one healed topology; the healed W
    must be doubly stochastic and mixing, the plan valid over the healed
    edge set, the dead ranks fully excised."""
    report = report if report is not None else Report()
    report.subjects_checked += 1
    report.extend(check_dead_excised(healed, label))
    plan, topo = healed.plan, healed.topology
    report.extend(plan_rules.check_classes_are_permutations(plan, label))
    report.extend(plan_rules.check_edge_cover(plan, topo, label))
    report.extend(plan_rules.check_slot_consistency(plan, label))
    # expect_column=True: the healing contract is DOUBLY stochastic
    report.extend(plan_rules.check_mixing_stochastic(
        plan, label, expect_column=True))
    findings, gap = plan_rules.check_spectral_gap(plan, label)
    report.extend(findings)
    report.metric(f"resilience.spectral_gap/{label}", round(gap, 6))
    return report


def iter_healed_corpus(sizes: Sequence[int] = HEALED_SIZES
                       ) -> Iterable[Tuple[str, HealedTopology]]:
    for name, ctor in plan_rules.CORPUS_TOPOLOGIES.items():
        for n in sizes:
            topo = ctor(n)
            for dead in dead_sets(n):
                label = f"{name}@{n}-dead{list(dead)}"
                yield label, heal_topology(topo, dead)


@registry.rule("resilience.healed-corpus", "resilience",
               "every named topology x sizes 4..16 x dead-rank sets: the "
               "healed survivor topology is doubly stochastic, mixing, "
               "fully excises the dead, and recompiles to a valid plan")
def _run_healed_corpus(report: Report) -> None:
    worst = {}
    for label, healed in iter_healed_corpus():
        report.subjects_checked += 1
        report.extend(check_dead_excised(healed, label))
        plan, topo = healed.plan, healed.topology
        report.extend(plan_rules.check_classes_are_permutations(plan, label))
        report.extend(plan_rules.check_edge_cover(plan, topo, label))
        report.extend(plan_rules.check_slot_consistency(plan, label))
        report.extend(plan_rules.check_mixing_stochastic(
            plan, label, expect_column=True))
        findings, gap = plan_rules.check_spectral_gap(plan, label)
        report.extend(findings)
        fam = label.split("@")[0]
        worst[fam] = min(worst.get(fam, 1.0), gap)
    for fam, gap in sorted(worst.items()):
        report.metric(f"resilience.min_healed_spectral_gap/{fam}",
                      round(gap, 6))


@registry.rule("resilience.degraded-weights", "resilience",
               "self-weight renormalization of combine rows: dropping "
               "dead neighbors conserves the row total for uniform, "
               "convex, and push-sum (all-ones) rows")
def _run_degraded_weights(report: Report) -> None:
    from bluefog_tpu.resilience.degraded import renormalize_weights
    rng = np.random.default_rng(7)
    for n in (2, 4, 8):
        for trial in range(8):
            w = rng.dirichlet(np.ones(n + 1))
            sw, nw = float(w[0]), {i: float(w[i + 1]) for i in range(n)}
            dead = set(int(i) for i in
                       rng.choice(n, size=rng.integers(0, n + 1),
                                  replace=False))
            sw2, nw2 = renormalize_weights(sw, nw, dead)
            label = f"dirichlet@{n} trial {trial} dead={sorted(dead)}"
            report.subjects_checked += 1
            total = sw2 + sum(nw2.values())
            if abs(total - 1.0) > 1e-9:
                report.add(Finding(
                    "resilience.degraded-weights", label,
                    f"renormalized row sums to {total!r}, expected 1"))
            if set(nw2) & dead:
                report.add(Finding(
                    "resilience.degraded-weights", label,
                    f"dead neighbor(s) {sorted(set(nw2) & dead)} survive "
                    "renormalization"))
            if any(v < -1e-12 for v in nw2.values()) or sw2 < -1e-12:
                report.add(Finding(
                    "resilience.degraded-weights", label,
                    "negative weight after renormalization"))
