"""Rule family 5: static verification of healed (post-failure) topologies.

When ranks die, :func:`bluefog_tpu.resilience.healing.heal_topology`
rebuilds the gossip over the survivors.  A healed topology is exactly as
load-bearing as a fresh one — every invariant the plan family checks on
the named corpus must hold on the healed artifacts too, or the surviving
job silently diverges:

- the dead ranks are fully EXCISED: no survivor, no node, no scheduled
  edge references them (a dead rank left in the plan deposits into a
  drained slot forever — its neighbors average in zeros);
- the survivor mixing matrix is doubly stochastic (row AND column sums
  1): Metropolis–Hastings over the symmetrized induced subgraph — the
  condition under which degraded gossip still converges to the exact
  survivor average;
- the spectral gap stays strictly positive: the ring-reconnect step must
  have restored connectivity whenever the excision cut the graph;
- the recompiled plan covers the healed edge set exactly, with valid
  permutation classes and consistent slot bookkeeping — the plan rules,
  re-run on the healed subject.

The corpus is every named topology x sizes 4..16 x a spread of dead-rank
sets (first rank, last rank, an interior pair, and — where it exists —
the star's center, the excision that forces a reconnect).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import networkx as nx
import numpy as np

from bluefog_tpu.resilience.healing import (
    HealedTopology, grow_topology, heal_topology)

from bluefog_tpu.analysis import plan_rules
from bluefog_tpu.analysis.engine import Finding, Report, registry

__all__ = [
    "HEALED_SIZES",
    "dead_sets",
    "check_dead_excised",
    "check_healed",
    "check_grown",
    "check_membership_epochs",
    "iter_elastic_corpus",
]

HEALED_SIZES: Tuple[int, ...] = tuple(range(4, 17))


def dead_sets(size: int) -> List[Tuple[int, ...]]:
    """The dead-rank sets exercised per (topology, size): single deaths
    at both id extremes, an interior pair, and near-majority loss."""
    out = [(0,), (size - 1,)]
    if size > 3:
        out.append((1, 2))
    if size > 5:
        out.append(tuple(range(1, size - 2)))  # 3 survivors
    return out


def check_dead_excised(healed: HealedTopology,
                       label: str = "healed") -> List[Finding]:
    """Every trace of the dead ranks must be gone from the healed
    artifacts: survivors, topology nodes, and plan edges (mapped back to
    global ids via ``to_global``)."""
    out: List[Finding] = []
    dead = set(healed.dead)
    leaked = dead & set(healed.survivors)
    if leaked:
        out.append(Finding(
            "resilience.dead-excised", label,
            f"dead rank(s) {sorted(leaked)} still listed as survivors — "
            "the healed gossip would keep scheduling a corpse"))
    if healed.plan.size != len(healed.survivors):
        out.append(Finding(
            "resilience.dead-excised", label,
            f"healed plan has size {healed.plan.size} but there are "
            f"{len(healed.survivors)} survivors"))
    to_global = healed.to_global
    bad_edges = []
    for cls in healed.plan.classes:
        for s, d in cls.perm:
            for local in (s, d):
                if 0 <= local < len(to_global) \
                        and to_global[local] in dead:
                    bad_edges.append((s, d))
    if bad_edges:
        out.append(Finding(
            "resilience.dead-excised", label,
            f"scheduled edge(s) {sorted(set(bad_edges))[:6]} map to dead "
            "global rank(s) — survivors would win_put into force-drained "
            "slots forever"))
    mapped = {to_global[i] for i in range(len(to_global))}
    if mapped & dead:
        out.append(Finding(
            "resilience.dead-excised", label,
            f"to_global maps local ids onto dead rank(s) "
            f"{sorted(mapped & dead)}"))
    return out


def check_healed(healed: HealedTopology, label: str = "healed",
                 report: Optional[Report] = None) -> Report:
    """All resilience + plan rules on one healed topology; the healed W
    must be doubly stochastic and mixing, the plan valid over the healed
    edge set, the dead ranks fully excised."""
    report = report if report is not None else Report()
    report.subjects_checked += 1
    report.extend(check_dead_excised(healed, label))
    plan, topo = healed.plan, healed.topology
    report.extend(plan_rules.check_classes_are_permutations(plan, label))
    report.extend(plan_rules.check_edge_cover(plan, topo, label))
    report.extend(plan_rules.check_slot_consistency(plan, label))
    # expect_column=True: the healing contract is DOUBLY stochastic
    report.extend(plan_rules.check_mixing_stochastic(
        plan, label, expect_column=True))
    findings, gap = plan_rules.check_spectral_gap(plan, label)
    report.extend(findings)
    report.metric(f"resilience.spectral_gap/{label}", round(gap, 6))
    return report


def iter_healed_corpus(sizes: Sequence[int] = HEALED_SIZES
                       ) -> Iterable[Tuple[str, HealedTopology]]:
    for name, ctor in plan_rules.CORPUS_TOPOLOGIES.items():
        for n in sizes:
            topo = ctor(n)
            for dead in dead_sets(n):
                label = f"{name}@{n}-dead{list(dead)}"
                yield label, heal_topology(topo, dead)


@registry.rule("resilience.healed-corpus", "resilience",
               "every named topology x sizes 4..16 x dead-rank sets: the "
               "healed survivor topology is doubly stochastic, mixing, "
               "fully excises the dead, and recompiles to a valid plan")
def _run_healed_corpus(report: Report) -> None:
    worst = {}
    for label, healed in iter_healed_corpus():
        report.subjects_checked += 1
        report.extend(check_dead_excised(healed, label))
        plan, topo = healed.plan, healed.topology
        report.extend(plan_rules.check_classes_are_permutations(plan, label))
        report.extend(plan_rules.check_edge_cover(plan, topo, label))
        report.extend(plan_rules.check_slot_consistency(plan, label))
        report.extend(plan_rules.check_mixing_stochastic(
            plan, label, expect_column=True))
        findings, gap = plan_rules.check_spectral_gap(plan, label)
        report.extend(findings)
        fam = label.split("@")[0]
        worst[fam] = min(worst.get(fam, 1.0), gap)
    for fam, gap in sorted(worst.items()):
        report.metric(f"resilience.min_healed_spectral_gap/{fam}",
                      round(gap, 6))


# ---------------------------------------------------------------------------
# grow-side healing (elastic membership): the shrink/grow/shrink corpus
# ---------------------------------------------------------------------------


def check_grown(grown: HealedTopology, label: str = "grown",
                report: Optional[Report] = None) -> Report:
    """All plan + excision rules on one GROWN topology (the output of
    :func:`grow_topology`): the joiners are present under fresh global
    ranks, no dead rank reappears, and the grown W is doubly stochastic
    and mixing — admission must not cost the job its convergence
    guarantee."""
    report = report if report is not None else Report()
    report.subjects_checked += 1
    mapped = set(grown.to_global)
    missing = set(grown.joined) - mapped
    if missing:
        report.add(Finding(
            "resilience.grown-corpus", label,
            f"joiner(s) {sorted(missing)} granted but absent from the "
            "grown topology — the new rank would gossip with nobody"))
    revived = set(grown.dead) & mapped
    if revived:
        report.add(Finding(
            "resilience.grown-corpus", label,
            f"dead rank(s) {sorted(revived)} reappear in the grown view "
            "— a corpse's global rank must never be reissued (stale "
            "deposits would be double-counted under the new member)"))
    if grown.plan.size != len(grown.to_global):
        report.add(Finding(
            "resilience.grown-corpus", label,
            f"grown plan has size {grown.plan.size} but the view maps "
            f"{len(grown.to_global)} members"))
    plan, topo = grown.plan, grown.topology
    report.extend(plan_rules.check_classes_are_permutations(plan, label))
    report.extend(plan_rules.check_edge_cover(plan, topo, label))
    report.extend(plan_rules.check_slot_consistency(plan, label))
    report.extend(plan_rules.check_mixing_stochastic(
        plan, label, expect_column=True))
    findings, gap = plan_rules.check_spectral_gap(plan, label)
    report.extend(findings)
    report.metric(f"resilience.grown_spectral_gap/{label}", round(gap, 6))
    return report


def _global_graph(h: HealedTopology) -> nx.DiGraph:
    """A healed/grown topology relabeled back to GLOBAL ranks — the form
    the next membership transition consumes."""
    return nx.relabel_nodes(h.topology, dict(enumerate(h.to_global)),
                            copy=True)


def iter_elastic_corpus(sizes: Sequence[int] = HEALED_SIZES
                        ) -> Iterable[Tuple[str, str, HealedTopology]]:
    """The shrink -> grow -> shrink corpus: every named topology x sizes
    4..16 goes through a death (heal), an admission under fresh global
    ranks (grow), and a second death in the grown view (heal again) —
    the full elastic life cycle, yielding ``(label, stage, artifact)``
    with stage one of ``shrink``/``grow``/``reshrink``."""
    for name, ctor in plan_rules.CORPUS_TOPOLOGIES.items():
        for n in sizes:
            topo = ctor(n)
            for dead in ((0,), (1, 2)):
                label = f"{name}@{n}-dead{list(dead)}"
                healed = heal_topology(topo, dead)
                yield label, "shrink", healed
                fresh = (n, n + 1)
                grown = grow_topology(_global_graph(healed), fresh)
                yield f"{label}+join{list(fresh)}", "grow", grown
                # second shrink: kill one ORIGINAL survivor of the grown
                # view (never a joiner — their death is the same path)
                victim = grown.to_global[0]
                reshrunk = heal_topology(_global_graph(grown), [victim])
                yield (f"{label}+join{list(fresh)}-dead[{victim}]",
                       "reshrink", reshrunk)


@registry.rule("resilience.grown-corpus", "resilience",
               "shrink/grow/shrink over every named topology x sizes "
               "4..16: healed, grown (fresh joiners), and re-healed "
               "views all stay doubly stochastic, mixing, and free of "
               "revived corpses")
def _run_elastic_corpus(report: Report) -> None:
    worst = {}
    for label, stage, art in iter_elastic_corpus():
        if stage == "grow":
            check_grown(art, label, report)
        else:
            report.subjects_checked += 1
            report.extend(check_dead_excised(art, label))
            report.extend(plan_rules.check_mixing_stochastic(
                art.plan, label, expect_column=True))
        _, gap = plan_rules.check_spectral_gap(art.plan, label)
        fam = label.split("@")[0]
        worst[fam] = min(worst.get(fam, 1.0), gap)
    for fam, gap in sorted(worst.items()):
        report.metric(f"resilience.min_elastic_spectral_gap/{fam}",
                      round(gap, 6))


# ---------------------------------------------------------------------------
# membership epochs: the epoch_switch journal audit
# ---------------------------------------------------------------------------


def check_membership_epochs(events: Sequence[dict],
                            label: str = "journal") -> List[Finding]:
    """Audit ``epoch_switch`` journal events (one per member per switch,
    emitted AT the round barrier with the four cumulative mass-ledger
    counters):

    - per switch, the merged ledger balances — ``sum(deposits) ==
      sum(collected + drained + pending)`` across every member of the
      new view: no committed chunk from epoch ``e`` is consumed under
      view ``e+1`` without having been drained or retired as pending at
      the cut;
    - per member, epochs advance by exactly one (``old_epoch + 1 ==
      new_epoch``) — a skipped epoch means a member gossiped against a
      stale membership view;
    - a member entering from nowhere (``old_epoch is None``) must be in
      the record's ``joined`` list: only granted joiners materialize.
    """
    out: List[Finding] = []
    switches: dict = {}
    for ev in events:
        if ev.get("event") != "epoch_switch":
            continue
        switches.setdefault(int(ev["new_epoch"]), []).append(ev)
    for epoch, evs in sorted(switches.items()):
        dep = sum(float(e.get("deposits", 0)) for e in evs)
        acc = sum(float(e.get("collected", 0)) + float(e.get("drained", 0))
                  + float(e.get("pending", 0)) for e in evs)
        if abs(dep - acc) > 1e-9:
            out.append(Finding(
                "resilience.membership-epoch", f"{label}@epoch{epoch}",
                f"mass ledger does not balance at the epoch-{epoch} "
                f"switch: deposits={dep:g} != collected+drained+pending="
                f"{acc:g} — committed mass crossed the membership "
                "barrier unaccounted (lost, or double-counted under the "
                "new view)"))
        for e in evs:
            old = e.get("old_epoch")
            g = e.get("global_rank")
            if old is None:
                if g not in e.get("joined", []):
                    out.append(Finding(
                        "resilience.membership-epoch",
                        f"{label}@epoch{epoch}",
                        f"rank {g} entered epoch {epoch} from nowhere "
                        "but is not in the granted joiner list"))
            elif int(old) + 1 != epoch:
                out.append(Finding(
                    "resilience.membership-epoch", f"{label}@epoch{epoch}",
                    f"rank {g} switched {old} -> {epoch}: members must "
                    "step one epoch at a time (a skipped view means "
                    "gossip against a stale membership)"))
    return out


def _synthetic_epoch_journal() -> List[dict]:
    """A healthy two-switch journal: 3 members admit rank 4 (epoch 1),
    then all 4 admit rank 5 (epoch 2), every cut balanced."""
    events = []
    for r, (dep, col, drn, pnd) in zip(
            (0, 2, 3), ((40, 30, 6, 4), (38, 34, 2, 2), (22, 16, 4, 2))):
        events.append({"event": "epoch_switch", "old_epoch": 0,
                       "new_epoch": 1, "global_rank": r, "joined": [4],
                       "deposits": dep, "collected": col,
                       "drained": drn, "pending": pnd})
    events.append({"event": "epoch_switch", "old_epoch": None,
                   "new_epoch": 1, "global_rank": 4, "joined": [4],
                   "deposits": 0, "collected": 0, "drained": 0,
                   "pending": 0})
    for r in (0, 2, 3, 4):
        events.append({"event": "epoch_switch", "old_epoch": 1,
                       "new_epoch": 2, "global_rank": r, "joined": [5],
                       "deposits": 50 + r, "collected": 48 + r,
                       "drained": 1, "pending": 1})
    events.append({"event": "epoch_switch", "old_epoch": None,
                   "new_epoch": 2, "global_rank": 5, "joined": [5],
                   "deposits": 0, "collected": 0, "drained": 0,
                   "pending": 0})
    return events


@registry.rule("resilience.membership-epoch", "resilience",
               "epoch_switch journal audit: the merged mass ledger "
               "balances at every membership switch, members step one "
               "epoch at a time, and only granted joiners materialize")
def _run_membership_epochs(report: Report) -> None:
    events = _synthetic_epoch_journal()
    report.subjects_checked += len(
        {e["new_epoch"] for e in events})
    report.extend(check_membership_epochs(events, "synthetic"))


@registry.rule("resilience.degraded-weights", "resilience",
               "self-weight renormalization of combine rows: dropping "
               "dead neighbors conserves the row total for uniform, "
               "convex, and push-sum (all-ones) rows")
def _run_degraded_weights(report: Report) -> None:
    from bluefog_tpu.resilience.degraded import renormalize_weights
    rng = np.random.default_rng(7)
    for n in (2, 4, 8):
        for trial in range(8):
            w = rng.dirichlet(np.ones(n + 1))
            sw, nw = float(w[0]), {i: float(w[i + 1]) for i in range(n)}
            dead = set(int(i) for i in
                       rng.choice(n, size=rng.integers(0, n + 1),
                                  replace=False))
            sw2, nw2 = renormalize_weights(sw, nw, dead)
            label = f"dirichlet@{n} trial {trial} dead={sorted(dead)}"
            report.subjects_checked += 1
            total = sw2 + sum(nw2.values())
            if abs(total - 1.0) > 1e-9:
                report.add(Finding(
                    "resilience.degraded-weights", label,
                    f"renormalized row sums to {total!r}, expected 1"))
            if set(nw2) & dead:
                report.add(Finding(
                    "resilience.degraded-weights", label,
                    f"dead neighbor(s) {sorted(set(nw2) & dead)} survive "
                    "renormalization"))
            if any(v < -1e-12 for v in nw2.values()) or sw2 < -1e-12:
                report.add(Finding(
                    "resilience.degraded-weights", label,
                    "negative weight after renormalization"))
