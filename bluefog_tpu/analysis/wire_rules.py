"""Rule family: the ONE wire protocol, model-checked for both carriers.

PR "one wire protocol everywhere" ported the shm v2 chunk state machine
to the TCP transport: chunked deposits streamed under a credit window,
ascending chunk commits, version/mass advancing only at the commit
frame, a drained-marker collect, and a dead-writer drain run by the
disconnect handler.  :mod:`seqlock_model` already proves the shm side;
this family proves the properties that are NEW on the socket carrier —
and pins both transports to one shared protocol spec so they cannot
drift apart silently.

Models (same explicit-state explorer as :mod:`seqlock_model`):

- **chunk stream integrity** — a commit that checks only the chunk
  COUNT accepts a stream where one chunk was duplicated and another
  lost (the out-of-order/duplication race a multiplexed carrier can
  produce); the ascending-index check (``TCP_CHUNK_COMMIT_IN_ORDER``)
  refuses such a stream before it can commit a hole.
- **credit window liveness** — the server must ack EVERY chunk frame
  (the sender's flow-control credit); a receiver that acks only at
  commit deadlocks any deposit with more chunks than the window
  (sender blocked on a credit, receiver blocked on the commit frame).
- **error-feedback residual conservation** —
  ``sum(delivered) + residual == sum(inputs)`` at every step; the
  residual must survive edge DEMOTION (a paused edge flushes the carry
  on its next deposit) — zeroing it there silently destroys value mass
  that the quantizer had borrowed.
- **mid-stream writer death** — the disconnect drain
  (``TCP_DEAD_WRITER_DRAIN_STEPS``) conserves committed mass and never
  strands a reader waiting on an odd ``wseq``; committing at stream
  OPEN instead of at the commit frame (the seeded bug) lets a torn
  deposit become visible.
- **spec parity** — the TCP protocol constants must equal shm_native's
  and both transports must share one chunk geometry.

Seeded-bug variants feed the fixture corpus (``--self-test``): each
must make its checker fire.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from bluefog_tpu.analysis.engine import Finding, Report, registry
from bluefog_tpu.analysis.seqlock_model import (
    Model,
    _s,
    check_model,
)

__all__ = [
    "chunk_stream_model",
    "credit_window_model",
    "residual_feedback_model",
    "stream_death_model",
    "check_spec_parity",
]


# ---------------------------------------------------------------------------
# model 1: chunk stream integrity (ascending commit vs count-only commit)
# ---------------------------------------------------------------------------


def chunk_stream_model(nchunks: int = 3, writer_in_order: bool = True,
                       enforce_order: Optional[bool] = None) -> Model:
    """A writer streams ``nchunks`` chunk frames through a FIFO and then
    commits; the server applies each frame into the slot.

    ``writer_in_order=False`` seeds the duplication race: the writer
    emits chunk 0 twice and never emits chunk 1 — the chunk COUNT still
    matches, so a server that validates only the count commits a slot
    with a hole (stale bytes where chunk 1 should be).  The ascending
    check (``enforce_order``, the implementation's
    ``TCP_CHUNK_COMMIT_IN_ORDER`` behaviour: expected-index mismatch
    drops the connection) refuses the stream before commit, so the
    deposit dies with zero mass instead of committing torn.
    """
    if enforce_order is None:
        from bluefog_tpu.native.tcp_transport import TCP_CHUNK_COMMIT_IN_ORDER
        enforce_order = TCP_CHUNK_COMMIT_IN_ORDER

    idxs = list(range(nchunks))
    if not writer_in_order:
        idxs[1] = idxs[0]  # duplicate chunk 0, lose chunk 1 — count intact

    shared = {"q": (), "slot": (0,) * nchunks, "refused": 0,
              "committed": 0, "commit_sent": 0}

    writer: List[Callable] = []
    for i, idx in enumerate(idxs):
        def send(sh, rg, idx=idx, nxt=i + 1):
            return _s(sh, rg, nxt, q=sh["q"] + (idx,))
        writer.append(send)

    def send_commit(sh, rg, nxt=len(idxs) + 1):
        return _s(sh, rg, nxt, commit_sent=1)
    writer.append(send_commit)

    server: List[Callable] = []
    for i in range(nchunks):
        def apply_chunk(sh, rg, expected=i, nxt=i + 1):
            if sh["refused"]:
                return [(sh, rg, nchunks + 1)]  # stream dropped
            if not sh["q"]:
                return []  # nothing arrived yet
            idx, rest = sh["q"][0], sh["q"][1:]
            if enforce_order and idx != expected:
                # the ascending check: drop the stream, never commit
                return _s(sh, rg, nchunks + 1, q=rest, refused=1)
            slot = list(sh["slot"])
            slot[idx] = idx + 1  # chunk idx's payload value
            return _s(sh, rg, nxt, q=rest, slot=tuple(slot))
        server.append(apply_chunk)

    def apply_commit(sh, rg, nxt=nchunks + 1):
        if sh["refused"]:
            return [(sh, rg, nxt)]
        if not sh["commit_sent"]:
            return []
        return _s(sh, rg, nxt, committed=1)
    server.append(apply_commit)

    def complete(sh) -> Optional[str]:
        if sh["committed"] and any(w == 0 for w in sh["slot"]):
            holes = [i for i, w in enumerate(sh["slot"]) if w == 0]
            return (f"deposit committed with hole(s) at chunk {holes} — "
                    "a duplicated/reordered stream passed the count-only "
                    "commit check (ascending chunk commit required)")
        return None

    return Model(name="chunk-stream", shared=shared,
                 programs=[writer, server], final_check=complete)


# ---------------------------------------------------------------------------
# model 2: credit-window liveness (per-chunk acks vs ack-at-commit)
# ---------------------------------------------------------------------------


def credit_window_model(nchunks: int = 3, window: int = 1,
                        ack_per_chunk: bool = True) -> Model:
    """The pipelined sender keeps at most ``window`` unacked chunk
    frames outstanding; the server processes frames and (correctly)
    acks each one — the flow-control credit.

    ``ack_per_chunk=False`` seeds the deadlock: a server that acks only
    at commit starves the sender of credits once
    ``nchunks > window`` — the sender blocks waiting for an ack before
    chunk ``window``+1, the server blocks waiting for the commit frame,
    and the explorer's deadlock detector fires (lost wakeup shape).
    """
    shared = {"sent": 0, "acked": 0, "delivered": 0,
              "commit_sent": 0, "committed": 0}

    sender: List[Callable] = []
    for i in range(nchunks):
        def send_chunk(sh, rg, nxt=i + 1):
            if sh["sent"] - sh["acked"] >= window:
                return []  # out of credit: wait for one ack
            return _s(sh, rg, nxt, sent=sh["sent"] + 1)
        sender.append(send_chunk)

    def send_commit(sh, rg, nxt=nchunks + 1):
        return _s(sh, rg, nxt, commit_sent=1)
    sender.append(send_commit)

    def drain_acks(sh, rg, nxt=nchunks + 2):
        if sh["acked"] < sh["sent"] or not sh["committed"]:
            return []  # collect every credit + the commit ack
        return [(sh, rg, nxt)]
    sender.append(drain_acks)

    server: List[Callable] = []
    for i in range(nchunks):
        def recv_chunk(sh, rg, nxt=i + 1):
            if sh["delivered"] >= sh["sent"]:
                return []  # frame not here yet
            upd = {"delivered": sh["delivered"] + 1}
            if ack_per_chunk:
                upd["acked"] = sh["acked"] + 1
            return _s(sh, rg, nxt, **upd)
        server.append(recv_chunk)

    def recv_commit(sh, rg, nxt=nchunks + 1):
        if not sh["commit_sent"]:
            return []
        upd = {"committed": 1}
        if not ack_per_chunk:
            upd["acked"] = sh["delivered"]  # the deferred bulk ack
        return _s(sh, rg, nxt, **upd)
    server.append(recv_commit)

    def done(sh) -> Optional[str]:
        if not sh["committed"]:
            return "deposit never committed"
        return None

    return Model(name="credit-window", shared=shared,
                 programs=[sender, server], final_check=done)


# ---------------------------------------------------------------------------
# model 3: error-feedback residual conservation across demotion
# ---------------------------------------------------------------------------


def residual_feedback_model(rounds: int = 3,
                            drop_on_demote: bool = False) -> Model:
    """Integer miniature of the EF quantizer: each round folds the
    residual into the outgoing value, ships ``floor((x+r)/Q)*Q`` down
    the wire, and carries the remainder.  The invariant —
    ``delivered + residual == inputs`` — is checked at EVERY step, over
    every interleaving with an adaptive-topology DEMOTE event.

    ``drop_on_demote=True`` seeds the bug this family exists to catch:
    zeroing the per-edge residual when the edge is demoted.  Demotion
    merely PAUSES an edge (the peer is alive; promotion resumes it), so
    the carry must survive and flush on the next deposit — dropping it
    silently destroys the value mass the quantizer had borrowed.
    """
    Q, X = 2, 3  # quantum and per-round input: 3 = 2 + carry 1
    shared = {"r": 0, "inputs": 0, "delivered": 0, "demoted": 0}

    sender: List[Callable] = []
    for i in range(rounds):
        def send_round(sh, rg, nxt=i + 1):
            buf = X + sh["r"]
            q = (buf // Q) * Q
            sh2 = dict(sh, inputs=sh["inputs"] + X,
                       delivered=sh["delivered"] + q, r=buf - q)
            if sh2["delivered"] + sh2["r"] != sh2["inputs"]:
                sh2["_bad"] = (
                    f"error-feedback residual lost: delivered="
                    f"{sh2['delivered']} + residual={sh2['r']} != "
                    f"inputs={sh2['inputs']}")
            return [(sh2, rg, nxt)]
        sender.append(send_round)

    def demote(sh, rg):
        # the adaptive layer may demote the edge between ANY two rounds
        upd = {"demoted": 1}
        if drop_on_demote:
            upd["r"] = 0  # seeded bug: the carry dies with the demotion
        return _s(sh, rg, 1, **upd)

    def conserved(sh) -> Optional[str]:
        if sh["delivered"] + sh["r"] != sh["inputs"]:
            return (f"error-feedback residual lost across demotion: "
                    f"delivered={sh['delivered']} + residual={sh['r']} "
                    f"!= inputs={sh['inputs']} — the residual must "
                    "survive demote (the edge is paused, not dead)")
        return None

    return Model(name="residual-feedback", shared=shared,
                 programs=[sender, [demote]], final_check=conserved)


# ---------------------------------------------------------------------------
# model 4: mid-stream writer death (the disconnect drain)
# ---------------------------------------------------------------------------


def stream_death_model(nchunks: int = 2,
                       commits_after_payload: Optional[bool] = None,
                       drain_evenizes: bool = True) -> Model:
    """A TCP writer streams ``nchunks`` chunk frames then commits, and
    may DIE (SIGKILL — connection drops, no cleanup) at any step.  The
    owner reads (waiting while ``wseq`` is odd) and, on death, the
    disconnect handler runs ``TCP_DEAD_WRITER_DRAIN_STEPS``.

    Properties over every death point and interleaving:

    - **no unbacked mass** (``commits_after_payload=False`` seeds the
      bug): the version/mass must advance only at the commit frame,
      after every chunk landed — committing at stream OPEN lets the
      owner collect a deposit whose payload never fully arrived;
    - **no lost committed mass**: collected + wiped + logical ==
      committed, with the drain charging in-transit mass to the dead
      rank's ledger;
    - **no stranded reader** (``drain_evenizes=False`` seeds the bug):
      the drain must make ``wseq`` even again, or a reader waiting out
      the stream spins forever — the deadlock detector fires.
    """
    if commits_after_payload is None:
        from bluefog_tpu.native.tcp_transport import (
            TCP_DEPOSIT_COMMITS_AFTER_PAYLOAD,
        )
        commits_after_payload = TCP_DEPOSIT_COMMITS_AFTER_PAYLOAD

    # chunk-granular accounting: paid counts chunks written, committed/m
    # count chunks made visible (a whole deposit = nchunks units)
    shared = {"wseq_odd": 0, "m": 0, "version": 0, "drained": 0,
              "dead": 0, "wdone": 0, "paid": 0, "committed": 0,
              "collected": 0, "wiped": 0}

    def logical(sh) -> int:
        return 0 if sh["drained"] == sh["version"] else sh["m"]

    def dying(step):
        def wrapped(sh, rg):
            succ = list(step(sh, rg))
            succ.extend(_s(sh, rg, 10_000, dead=1))
            return succ
        return wrapped

    writer: List[Callable] = []

    def w_open(sh, rg, nxt=1):
        return _s(sh, rg, nxt, wseq_odd=1,
                  # seeded bug: visibility granted at stream open
                  **({} if commits_after_payload
                     else {"m": nchunks, "version": sh["version"] + 1,
                           "committed": sh["committed"] + nchunks}))
    writer.append(dying(w_open))

    for i in range(nchunks):
        def w_chunk(sh, rg, nxt=i + 2):
            return _s(sh, rg, nxt, paid=sh["paid"] + 1)
        writer.append(dying(w_chunk))

    def w_commit(sh, rg, nxt=nchunks + 2):
        upd = {"wseq_odd": 0}
        if commits_after_payload:
            upd.update(m=nchunks, version=sh["version"] + 1,
                       committed=sh["committed"] + nchunks)
        return _s(sh, rg, nxt, **upd)
    writer.append(dying(w_commit))

    def w_linger(sh, rg, nxt=nchunks + 3):
        # the writer may still die AFTER the commit (connection drops
        # later) — the drain must then conserve the committed deposit
        return _s(sh, rg, nxt, wdone=1)
    writer.append(dying(w_linger))

    # the reader: _await_settled blocks while the stream is open; it
    # relies on the DRAINER (a separate actor — the server's disconnect
    # handler, not the reader itself) to evenize wseq on writer death
    def o_collect(sh, rg, nxt=1):
        if sh["wseq_odd"]:
            return []  # a drain that forgot to evenize strands us HERE
        return _s(sh, rg, nxt, collected=sh["collected"] + logical(sh),
                  drained=sh["version"])

    def d_drain(sh, rg, nxt=1):
        if sh["dead"]:
            # the disconnect handler: 1. evenize_wseq  2. mark_drained
            # (wipe accounted)  3. clear_stream (stream key dropped)
            upd = {"drained": sh["version"],
                   "wiped": sh["wiped"] + logical(sh)}
            if drain_evenizes:
                upd["wseq_odd"] = 0
            return _s(sh, rg, nxt, **upd)
        if sh["wdone"]:
            return [(sh, rg, nxt)]  # writer exited cleanly: nothing to do
        return []  # connection still up: wait for EOF or clean close

    owner = [o_collect]
    drainer = [d_drain]

    def conserved(sh) -> Optional[str]:
        if sh["committed"] > sh["paid"]:
            return (f"unbacked mass: {sh['committed']} chunk-unit(s) "
                    f"visible but only {sh['paid']} chunk(s) landed — "
                    "the deposit must commit at the COMMIT frame, after "
                    "the payload")
        if sh["collected"] + sh["wiped"] + logical(sh) != sh["committed"]:
            return (f"lost deposit: committed={sh['committed']} but "
                    f"collected={sh['collected']} + wiped={sh['wiped']} "
                    f"+ logical={logical(sh)}")
        return None

    return Model(name="stream-death", shared=shared,
                 programs=[writer, owner, drainer], final_check=conserved)


# ---------------------------------------------------------------------------
# spec parity: one protocol, two carriers
# ---------------------------------------------------------------------------


def check_spec_parity(report: Optional[Report] = None,
                      rule: str = "wire.spec-parity") -> Report:
    """The TCP transport's protocol constants must equal shm_native's,
    the dead-writer drain must mark-drained before clearing in BOTH,
    and the two carriers must share one chunk geometry."""
    from bluefog_tpu.native import shm_native, tcp_transport

    report = report if report is not None else Report()
    report.subjects_checked += 1
    pairs = [
        ("CHUNK_COMMIT_IN_ORDER",
         tcp_transport.TCP_CHUNK_COMMIT_IN_ORDER,
         shm_native.CHUNK_COMMIT_IN_ORDER),
        ("DEPOSIT_COMMITS_AFTER_PAYLOAD",
         tcp_transport.TCP_DEPOSIT_COMMITS_AFTER_PAYLOAD,
         shm_native.DEPOSIT_COMMITS_AFTER_PAYLOAD),
        ("DRAINED_COLLECT_IS_ATOMIC",
         tcp_transport.TCP_DRAINED_COLLECT_IS_ATOMIC,
         shm_native.DRAINED_COLLECT_IS_ATOMIC),
    ]
    for name, tcp_v, shm_v in pairs:
        if tcp_v != shm_v:
            report.add(Finding(
                rule, "tcp-vs-shm",
                f"protocol constant drift: TCP_{name}={tcp_v} but "
                f"shm {name}={shm_v} — one wire protocol, two carriers"))
    for steps, clear in (
            (tcp_transport.TCP_DEAD_WRITER_DRAIN_STEPS, "clear_stream"),
            (shm_native.DEAD_WRITER_DRAIN_STEPS, "clear_lock")):
        if "mark_drained" not in steps or clear not in steps \
                or steps.index("mark_drained") > steps.index(clear):
            report.add(Finding(
                rule, "drain-order",
                f"dead-writer drain {steps} must mark_drained before "
                f"{clear} — nobody may slip into a half-drained slot"))
    if tcp_transport._chunk_bytes() != shm_native.chunk_bytes():
        report.add(Finding(
            rule, "chunk-geometry",
            "TCP and shm disagree on chunk size — the stream framing "
            "must follow BLUEFOG_SHM_CHUNK_BYTES on both carriers"))
    return report


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------


@registry.rule("wire.chunk-stream-order", "wire",
               "the ascending chunk-commit check refuses a "
               "duplicated/reordered stream before it can commit a hole")
def _run_chunk_stream(report: Report) -> None:
    for nchunks in (2, 3):
        check_model(chunk_stream_model(nchunks=nchunks), report,
                    rule="wire.chunk-stream-order")
    # the enforcing server must also neutralize a buggy writer: refused
    # streams never commit (zero findings = the check works)
    check_model(chunk_stream_model(nchunks=3, writer_in_order=False,
                                   enforce_order=True),
                report, rule="wire.chunk-stream-order")


@registry.rule("wire.credit-window", "wire",
               "per-chunk acks keep the pipelined sender live for every "
               "deposit size vs window setting")
def _run_credit_window(report: Report) -> None:
    for nchunks, window in ((2, 1), (3, 1), (3, 2), (2, 4)):
        check_model(credit_window_model(nchunks=nchunks, window=window),
                    report, rule="wire.credit-window")


@registry.rule("wire.residual-conservation", "wire",
               "the error-feedback residual conserves value mass at "
               "every step, across edge demotion")
def _run_residual(report: Report) -> None:
    for rounds in (2, 3, 4):
        check_model(residual_feedback_model(rounds=rounds), report,
                    rule="wire.residual-conservation")


@registry.rule("wire.stream-death-drain", "wire",
               "a TCP writer dying mid-chunk-stream: the disconnect "
               "drain conserves committed mass and frees waiting readers")
def _run_stream_death(report: Report) -> None:
    for nchunks in (1, 2, 3):
        check_model(stream_death_model(nchunks=nchunks), report,
                    rule="wire.stream-death-drain")


@registry.rule("wire.spec-parity", "wire",
               "TCP and shm expose identical protocol spec constants "
               "and one chunk geometry")
def _run_spec_parity(report: Report) -> None:
    check_spec_parity(report, rule="wire.spec-parity")
