"""CLI: ``python -m bluefog_tpu.analysis`` — exit 0 iff no errors.

Modes:

- default: run every registered rule family over the default corpus and
  print a summary (``--families plan protocol`` to subset, ``--no-hlo``
  to skip the compile-heavy family — the fast CI gate);
- ``--fixture NAME``: lint one seeded-bug fixture; exits NONZERO when it
  (correctly) fires — CI uses this to prove the verifier catches what it
  claims to catch;
- ``--self-test``: run every fixture and fail unless each yields at
  least one finding;
- ``--list``: enumerate rules and fixtures;
- ``--json``: machine-readable report.

The 8-device CPU mesh is forced before jax initializes (same trick as
tests/conftest.py) so the hlo family works on any host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_cpu_mesh() -> None:
    # must run before jax picks a backend; harmless if already configured
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.analysis",
        description="static verifier: plans, topologies, HLO contracts, "
                    "shm-mailbox protocol")
    p.add_argument("--families", nargs="*", default=None,
                   help="rule families to run (default: all)")
    p.add_argument("--no-hlo", action="store_true",
                   help="skip the compile-heavy hlo family (fast CI gate)")
    p.add_argument("--fixture", default=None,
                   help="lint one seeded-bug fixture; exits nonzero when "
                        "the rule fires (it must)")
    p.add_argument("--self-test", action="store_true",
                   help="check every fixture yields >= 1 finding")
    p.add_argument("--list", action="store_true", dest="list_rules",
                   help="list registered rules and fixtures")
    p.add_argument("--json", action="store_true", help="emit a JSON report")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    _force_cpu_mesh()

    from bluefog_tpu import analysis
    from bluefog_tpu.analysis import fixtures

    if args.list_rules:
        for rule in analysis.registry.select():
            print(f"{rule.name:<36s} [{rule.family}] {rule.doc}")
        print()
        for name in fixtures.FIXTURES:
            print(f"fixture: {name}")
        return 0

    if args.fixture is not None:
        if args.fixture not in fixtures.FIXTURES:
            p.error(f"unknown fixture {args.fixture!r}; see --list")
        findings = fixtures.run_fixture(args.fixture)
        for f in findings:
            print(f)
        print(f"{args.fixture}: {len(findings)} finding(s)")
        # a seeded bug MUST be caught: nonzero exit = the rule fired
        return 1 if findings else 0

    if args.self_test:
        dead = []
        for name in fixtures.FIXTURES:
            findings = fixtures.run_fixture(name)
            status = f"fires ({len(findings)})" if findings else "SILENT"
            print(f"  {name:<36s} {status}")
            if not findings:
                dead.append(name)
        if dead:
            print(f"self-test FAILED: rule(s) never fired for {dead}")
            return 1
        # sim arm: the acceptance-size pinned campaigns must run clean
        # (the default corpus only runs the small ones)
        from bluefog_tpu.analysis import sim_rules

        dirty = []
        for label, res, findings in sim_rules.selftest_campaigns():
            ok = not findings
            print(f"  {label:<36s} "
                  f"{'clean' if ok else 'VIOLATED'} "
                  f"(events={res.events}, digest={res.digest[:12]})")
            for f in findings:
                print(f"    {f}")
            if not ok:
                dirty.append(label)
        if dirty:
            print(f"self-test FAILED: campaign(s) violated invariants "
                  f"{dirty}")
            return 1
        # partition arm: acceptance-size partition campaigns must
        # orphan the minority, merge it back, and replay bit-identically
        from bluefog_tpu.analysis import partition_rules

        torn = []
        for label, res, findings in (
                partition_rules.selftest_partition_campaigns()):
            ok = not findings
            print(f"  {label:<36s} "
                  f"{'clean' if ok else 'VIOLATED'} "
                  f"(events={res.events}, digest={res.digest[:12]})")
            for f in findings:
                print(f"    {f}")
            if not ok:
                torn.append(label)
        if torn:
            print(f"self-test FAILED: partition campaign(s) failed "
                  f"{torn}")
            return 1
        # serve arm: acceptance-size serve campaigns under chaos must
        # publish monotone, converge replicas, and replay bit-identically
        from bluefog_tpu.analysis import serve_rules

        stale = []
        for label, res, findings in (
                serve_rules.selftest_serve_campaigns()):
            ok = not findings
            print(f"  {label:<36s} "
                  f"{'clean' if ok else 'VIOLATED'} "
                  f"(events={res.events}, digest={res.digest[:12]})")
            for f in findings:
                print(f"    {f}")
            if not ok:
                stale.append(label)
        if stale:
            print(f"self-test FAILED: serve campaign(s) failed {stale}")
            return 1
        # lab arm: every claim the frozen sweep artifact makes must
        # re-derive from its own raw data (python -m bluefog_tpu.lab
        # --check runs the same checks standalone)
        from bluefog_tpu.analysis.engine import Severity
        from bluefog_tpu.analysis.lab_rules import check_artifact
        from bluefog_tpu.lab.recommend import (default_artifact_path,
                                               load_artifact)

        try:
            art = load_artifact()
        except (OSError, ValueError) as e:
            print(f"self-test FAILED: frozen lab artifact unreadable "
                  f"({default_artifact_path()}): {e}")
            return 1
        lab_findings = check_artifact(
            art, label="LAB_" + str(art.get("version")))
        lab_errors = [f for f in lab_findings
                      if f.severity == Severity.ERROR]
        ncells = len(art.get("cells") or ())
        print(f"  {'lab artifact LAB_' + str(art.get('version')):<36s} "
              f"{'clean' if not lab_errors else 'VIOLATED'} "
              f"(cells={ncells}, "
              f"spearman={art.get('spearman_rate_vs_gap'):.3f})")
        for f in lab_errors:
            print(f"    {f}")
        if lab_errors:
            print("self-test FAILED: frozen lab artifact fails its own "
                  "checks")
            return 1
        print(f"self-test OK: all {len(fixtures.FIXTURES)} seeded bugs "
              f"caught, {len(sim_rules.SELFTEST_PINS)} pinned campaigns "
              f"+ {len(partition_rules.PARTITION_PINS)} partition "
              f"+ {len(serve_rules.SERVE_PINS)} serve campaigns clean, "
              f"lab artifact verified ({ncells} cells)")
        return 0

    families = args.families
    if args.no_hlo:
        families = [f for f in (families or analysis.registry.families())
                    if f != "hlo"]
    report = analysis.run(families=families, verbose=args.verbose)
    if args.json:
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
    else:
        for f in report.findings:
            print(f)
        for name, value in sorted(report.metrics.items()):
            print(f"  metric {name} = {value}")
        print(report.summary())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
