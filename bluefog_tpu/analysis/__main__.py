"""CLI: ``python -m bluefog_tpu.analysis`` — exit 0 iff no errors.

Modes:

- default: run every registered rule family over the default corpus and
  print a summary (``--families plan protocol`` to subset, ``--no-hlo``
  to skip the compile-heavy family — the fast CI gate);
- ``--fixture NAME``: lint one seeded-bug fixture; exits NONZERO when it
  (correctly) fires — CI uses this to prove the verifier catches what it
  claims to catch;
- ``--self-test``: run every fixture and fail unless each yields at
  least one finding;
- ``--family NAME``: run exactly one rule family (``--list-families``
  shows every family with its documented runtime);
- ``--changed-only FILE [FILE ...]``: map touched source files to the
  rule families that gate them (``conformance.FAMILY_MAP``) and run only
  those — the pre-commit mode;
- ``--list``: enumerate rules and fixtures;
- ``--json``: machine-readable report.

The 8-device CPU mesh is forced before jax initializes (same trick as
tests/conftest.py) so the hlo family works on any host.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _force_cpu_mesh() -> None:
    # must run before jax picks a backend; harmless if already configured
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.analysis",
        description="static verifier: plans, topologies, HLO contracts, "
                    "shm-mailbox protocol")
    p.add_argument("--families", nargs="*", default=None,
                   help="rule families to run (default: all)")
    p.add_argument("--family", default=None,
                   help="run exactly one rule family (see --list-families)")
    p.add_argument("--list-families", action="store_true",
                   dest="list_families",
                   help="list rule families with rule counts and the "
                        "documented runtime of each")
    p.add_argument("--changed-only", nargs="+", default=None,
                   metavar="FILE",
                   help="run only the families gating these touched "
                        "source files (the pre-commit mode)")
    p.add_argument("--no-hlo", action="store_true",
                   help="skip the compile-heavy hlo family (fast CI gate)")
    p.add_argument("--fixture", default=None,
                   help="lint one seeded-bug fixture; exits nonzero when "
                        "the rule fires (it must)")
    p.add_argument("--self-test", action="store_true",
                   help="check every fixture yields >= 1 finding")
    p.add_argument("--list", action="store_true", dest="list_rules",
                   help="list registered rules and fixtures")
    p.add_argument("--json", action="store_true", help="emit a JSON report")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    _force_cpu_mesh()

    from bluefog_tpu import analysis
    from bluefog_tpu.analysis import fixtures

    if args.list_rules:
        for rule in analysis.registry.select():
            print(f"{rule.name:<36s} [{rule.family}] {rule.doc}")
        print()
        for name in fixtures.FIXTURES:
            print(f"fixture: {name}")
        return 0

    if args.list_families:
        # rough wall-clock on the CI container, measured once and kept
        # honest by the CLI timing test in tests/test_analysis.py
        runtime = {
            "plan": "~5 s (topology sweeps 2..64)",
            "hlo": "~60-120 s (jit+lower the HLO corpus — the slow one)",
            "protocol": "~2 s (exhaustive interleavings, small bounds)",
            "resilience": "~5 s (healed-topology sweeps + drain model)",
            "telemetry": "~1 s", "trace": "~1 s", "adaptive": "~5 s",
            "epoch": "<1 s", "progress": "~2 s",
            "wire": "~3 s (chunk-stream + credit-window models)",
            "introspect": "~2 s", "sim": "~10 s (pinned fault campaigns)",
            "partition": "~10 s (pinned partition campaigns)",
            "serve": "~10 s (pinned serve campaigns + buffer model)",
            "slo": "~10 s (pinned traffic campaigns + latency "
                   "sampler pins)",
            "monitor": "~15 s (monitored seeded-bug + clean-twin "
                       "campaigns)",
            "distrib": "~15 s (pinned tree campaigns + exhaustive "
                       "kill/delta models)",
            "lab": "~5 s (frozen sweep artifact re-derivation)",
            "transport": "<1 s (spec table pins + capability lint)",
            "conformance": "~5 s (differential transports vs reference; "
                           "includes two live TCP rank pairs)",
            "interleave": "~2 s (unified explorer + race scan)",
        }
        rules_by_family = {}
        for rule in analysis.registry.select():
            rules_by_family.setdefault(rule.family, []).append(rule.name)
        for fam in sorted(rules_by_family):
            n = len(rules_by_family[fam])
            print(f"{fam:<12s} {n:>2d} rule(s)  "
                  f"{runtime.get(fam, '(unmeasured)')}")
        return 0

    if args.fixture is not None:
        if args.fixture not in fixtures.FIXTURES:
            p.error(f"unknown fixture {args.fixture!r}; see --list")
        findings = fixtures.run_fixture(args.fixture)
        for f in findings:
            print(f)
        print(f"{args.fixture}: {len(findings)} finding(s)")
        # a seeded bug MUST be caught: nonzero exit = the rule fired
        return 1 if findings else 0

    if args.self_test:
        dead = []
        for name in fixtures.FIXTURES:
            findings = fixtures.run_fixture(name)
            status = f"fires ({len(findings)})" if findings else "SILENT"
            print(f"  {name:<36s} {status}")
            if not findings:
                dead.append(name)
        if dead:
            print(f"self-test FAILED: rule(s) never fired for {dead}")
            return 1
        # sim arm: the acceptance-size pinned campaigns must run clean
        # (the default corpus only runs the small ones)
        from bluefog_tpu.analysis import sim_rules

        dirty = []
        for label, res, findings in sim_rules.selftest_campaigns():
            ok = not findings
            print(f"  {label:<36s} "
                  f"{'clean' if ok else 'VIOLATED'} "
                  f"(events={res.events}, digest={res.digest[:12]})")
            for f in findings:
                print(f"    {f}")
            if not ok:
                dirty.append(label)
        if dirty:
            print(f"self-test FAILED: campaign(s) violated invariants "
                  f"{dirty}")
            return 1
        # partition arm: acceptance-size partition campaigns must
        # orphan the minority, merge it back, and replay bit-identically
        from bluefog_tpu.analysis import partition_rules

        torn = []
        for label, res, findings in (
                partition_rules.selftest_partition_campaigns()):
            ok = not findings
            print(f"  {label:<36s} "
                  f"{'clean' if ok else 'VIOLATED'} "
                  f"(events={res.events}, digest={res.digest[:12]})")
            for f in findings:
                print(f"    {f}")
            if not ok:
                torn.append(label)
        if torn:
            print(f"self-test FAILED: partition campaign(s) failed "
                  f"{torn}")
            return 1
        # serve arm: acceptance-size serve campaigns under chaos must
        # publish monotone, converge replicas, and replay bit-identically
        from bluefog_tpu.analysis import serve_rules

        stale = []
        for label, res, findings in (
                serve_rules.selftest_serve_campaigns()):
            ok = not findings
            print(f"  {label:<36s} "
                  f"{'clean' if ok else 'VIOLATED'} "
                  f"(events={res.events}, digest={res.digest[:12]})")
            for f in findings:
                print(f"    {f}")
            if not ok:
                stale.append(label)
        if stale:
            print(f"self-test FAILED: serve campaign(s) failed {stale}")
            return 1
        # slo arm: Poisson load over >= 64 virtual replicas under
        # relay kills and publish churn — zero unattributed request
        # violations, nonzero excused traffic, bit-identical replays
        from bluefog_tpu.analysis import slo_rules

        unattributed = []
        for label, res, findings in (
                slo_rules.selftest_slo_campaigns()):
            ok = not findings
            arr = res.final.get("arrivals") or {}
            print(f"  {label:<36s} "
                  f"{'clean' if ok else 'VIOLATED'} "
                  f"(served={arr.get('served')}, "
                  f"attributed={arr.get('attributed')}, "
                  f"digest={res.digest[:12]})")
            for f in findings:
                print(f"    {f}")
            if not ok:
                unattributed.append(label)
        if unattributed:
            print(f"self-test FAILED: traffic campaign(s) failed "
                  f"{unattributed}")
            return 1
        # monitor arm: the acceptance-size clean campaigns, monitored —
        # zero alerts, digest and alert list bit-identical on replay
        from bluefog_tpu.analysis import monitor_rules

        alarmed = []
        for label, res, findings in (
                monitor_rules.selftest_monitor_campaigns()):
            ok = not findings
            mon = res.final.get("monitor") or {}
            print(f"  {label:<36s} "
                  f"{'clean' if ok else 'VIOLATED'} "
                  f"(samples={mon.get('samples')}, "
                  f"alerts={len(mon.get('alerts', ()))}, "
                  f"digest={res.digest[:12]})")
            for f in findings:
                print(f"    {f}")
            if not ok:
                alarmed.append(label)
        if alarmed:
            print(f"self-test FAILED: monitored campaign(s) failed "
                  f"{alarmed}")
            return 1
        # distrib arm: acceptance-size distribution-tree campaigns
        # (relay kills + join storm mid-rollout at >= 64 ranks) must
        # re-parent cleanly, converge, and replay bit-identically
        from bluefog_tpu.analysis import distrib_rules

        stalled = []
        for label, res, findings in (
                distrib_rules.selftest_distrib_campaigns()):
            ok = not findings
            print(f"  {label:<36s} "
                  f"{'clean' if ok else 'VIOLATED'} "
                  f"(events={res.events}, digest={res.digest[:12]})")
            for f in findings:
                print(f"    {f}")
            if not ok:
                stalled.append(label)
        if stalled:
            print(f"self-test FAILED: distrib campaign(s) failed "
                  f"{stalled}")
            return 1
        # lab arm: every claim the frozen sweep artifact makes must
        # re-derive from its own raw data (python -m bluefog_tpu.lab
        # --check runs the same checks standalone)
        from bluefog_tpu.analysis.engine import Severity
        from bluefog_tpu.analysis.lab_rules import check_artifact
        from bluefog_tpu.lab.recommend import (default_artifact_path,
                                               load_artifact)

        try:
            art = load_artifact()
        except (OSError, ValueError) as e:
            print(f"self-test FAILED: frozen lab artifact unreadable "
                  f"({default_artifact_path()}): {e}")
            return 1
        lab_findings = check_artifact(
            art, label="LAB_" + str(art.get("version")))
        lab_errors = [f for f in lab_findings
                      if f.severity == Severity.ERROR]
        ncells = len(art.get("cells") or ())
        print(f"  {'lab artifact LAB_' + str(art.get('version')):<36s} "
              f"{'clean' if not lab_errors else 'VIOLATED'} "
              f"(cells={ncells}, "
              f"spearman={art.get('spearman_rate_vs_gap'):.3f})")
        for f in lab_errors:
            print(f"    {f}")
        if lab_errors:
            print("self-test FAILED: frozen lab artifact fails its own "
                  "checks")
            return 1
        # conformance arm: the live differential corpus must run clean
        # and every seeded transport mutant must be caught
        from bluefog_tpu.analysis import conformance

        broken = []
        for label, ok, detail in conformance.selftest_conformance():
            print(f"  {label:<36s} {'ok' if ok else 'FAILED'} ({detail})")
            if not ok:
                broken.append(label)
        if broken:
            print(f"self-test FAILED: conformance arm(s) failed {broken}")
            return 1
        # interleave arm: the unified explorer must agree with the
        # legacy models and catch every seeded protocol bug
        from bluefog_tpu.analysis import interleave

        split = []
        for label, ok, detail in interleave.selftest_interleave():
            print(f"  {label:<40s} {'ok' if ok else 'FAILED'} ({detail})")
            if not ok:
                split.append(label)
        if split:
            print(f"self-test FAILED: interleave arm(s) failed {split}")
            return 1
        print(f"self-test OK: all {len(fixtures.FIXTURES)} seeded bugs "
              f"caught, {len(sim_rules.SELFTEST_PINS)} pinned campaigns "
              f"+ {len(partition_rules.PARTITION_PINS)} partition "
              f"+ {len(serve_rules.SERVE_PINS)} serve "
              f"+ {len(slo_rules.SLO_PINS)} traffic "
              f"+ {len(monitor_rules.MONITOR_PINS)} monitored "
              f"+ {len(distrib_rules.DISTRIB_PINS)} distrib campaigns "
              f"clean, "
              f"lab artifact verified ({ncells} cells), transports "
              f"conformant, unified explorer subsumes the legacy models")
        return 0

    families = args.families
    if args.family is not None:
        if args.family not in analysis.registry.families():
            p.error(f"unknown family {args.family!r}; see --list-families")
        families = [args.family]
    if args.changed_only is not None:
        from bluefog_tpu.analysis.conformance import families_for_paths

        families = families_for_paths(args.changed_only)
        print(f"changed-only: {len(args.changed_only)} file(s) -> "
              f"families {families}")
    if args.no_hlo:
        families = [f for f in (families or analysis.registry.families())
                    if f != "hlo"]
    report = analysis.run(families=families, verbose=args.verbose)
    if args.json:
        print(json.dumps(report.to_dict(), indent=2, sort_keys=True))
    else:
        for f in report.findings:
            print(f)
        for name, value in sorted(report.metrics.items()):
            print(f"  metric {name} = {value}")
        print(report.summary())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
