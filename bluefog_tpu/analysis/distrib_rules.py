"""Rule family: the snapshot distribution plane as a verifier.

The distribution plane (:mod:`bluefog_tpu.serve.distrib`) argues three
properties hold under arbitrary relay death and subscriber churn:

1. the fan-out **tree stays a tree** — connected, acyclic, and
   degree-capped at ``BFTPU_DISTRIB_FANOUT`` — across any sequence of
   relay deaths and greedy re-parents (the publisher is the root of
   last resort, allowed to run hot);
2. **delta application is complete** — the dirty map composed with
   delta-apply reproduces the full canonical snapshot bit for bit, for
   every codec (f32 | bf16 | int8), every lag inside the horizon, and
   degrades to a full resync beyond it; the commit CRC makes an
   incomplete delta un-installable;
3. the distributed **version is monotone under relay death** — a
   re-parented subtree converges back to the committed head without
   ever serving a version it already moved past.

The rules run the REAL code three ways: exhaustive kill/re-parent
sequences against the production tree math
(:mod:`bluefog_tpu.serve.distrib.tree` — the same functions the feed
coordinator calls), the real ``DeltaEncoder``/``ChunkStore`` pair over
seeded update streams, and pinned distribution-tree sim campaigns
(relay kills + a join storm mid-rollout at acceptance size) audited by
the standing invariants after every event.
"""

from __future__ import annotations

import itertools
import math
import os
from typing import List, Optional, Tuple

import numpy as np

from bluefog_tpu.analysis.engine import Finding, Report, registry
from bluefog_tpu.analysis.sim_rules import campaign_findings

__all__ = [
    "distrib_campaign",
    "stale_delta_findings",
    "selftest_distrib_campaigns",
    "DISTRIB_PINS",
]

#: ``--self-test`` pinned distribution campaigns: (ranks, rounds, seed,
#: scenario).  ``relay-storm`` is the acceptance campaign: >= 64 ranks,
#: two relay kills plus a join storm mid-rollout, every standing
#: invariant (tree-validity, staleness SLO, serve monotone/committed)
#: audited after every event.
DISTRIB_PINS: Tuple[Tuple[int, int, int, str], ...] = (
    (32, 40, 7, "clean"),
    (32, 40, 13, "relay-kill"),
    (64, 40, 11, "relay-storm"),
)


def distrib_campaign(ranks: int, rounds: int, seed: int,
                     schedule=None, **kw):
    """One distribution-tree campaign: publisher analog every 4
    rounds, 8 tree-fed replicas at fanout 4, staleness SLO armed."""
    from bluefog_tpu.sim.campaign import SimConfig, run_campaign
    from bluefog_tpu.sim.schedule import FaultSchedule

    kw.setdefault("quiesce_rounds", max(10, rounds // 2))
    kw.setdefault("serve_every", 4)
    kw.setdefault("serve_replicas", 8)
    kw.setdefault("distrib_fanout", 4)
    kw.setdefault("distrib_slo", 6)
    cfg = SimConfig(ranks=ranks, rounds=rounds, seed=seed, **kw)
    sched = schedule if schedule is not None else FaultSchedule()
    return cfg, sched, run_campaign(cfg, sched)


def _storm_schedule(rounds: int, seed: int):
    """Two relay kills (the interior relay, then a post-storm parent)
    with one respawn — the mid-rollout chaos the acceptance criteria
    name."""
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    return FaultSchedule([
        # replica 0 is the interior relay of the heap placement
        # (feeds 4..7); step here is the swap ordinal, ~serve_every
        # rounds apiece, so ordinal 2 lands mid-rollout
        Fault(kind="serve_kill", step=2, rank=0, stop=rounds - 10),
        # replica 1 picks up join-storm leaves, then dies under them
        Fault(kind="serve_kill", step=4, rank=1),
    ], seed=seed)


def _depth_bound(replicas: int, fanout: int) -> int:
    """The acceptance depth bound: ``floor(log_fanout R) + 1`` (+1 of
    slack under churn — greedy repair is near- but not exactly
    optimal)."""
    return int(math.floor(math.log(max(2, replicas), max(2, fanout)))) + 2


def _distrib_path_findings(res, label: str,
                           expect_reparents: int = 0,
                           expect_joins: int = 0) -> List[Finding]:
    """Non-vacuity + convergence + final-tree audit over a campaign."""
    from bluefog_tpu.serve.distrib import tree as _tree

    out: List[Finding] = []
    sv = res.final.get("serve") or {}
    dv = sv.get("distrib") or {}
    if not dv:
        out.append(Finding(
            "distrib.version-monotone", label,
            "no distribution-tree state in the campaign result — the "
            "tree model never armed"))
        return out
    parents, fanout = dv["parents"], dv["fanout"]
    err = _tree.tree_valid(parents, fanout)
    if err:
        out.append(Finding(
            "distrib.tree-validity", label,
            f"final parent map is not a valid tree: {err}"))
    bound = _depth_bound(len(parents), fanout)
    if dv["depth"] > bound:
        out.append(Finding(
            "distrib.tree-validity", label,
            f"final tree depth {dv['depth']} exceeds the "
            f"log_{fanout}(R)+1 bound ({bound}) for {len(parents)} "
            "replicas — repair is not keeping the tree shallow"))
    if dv["reparents"] < expect_reparents:
        out.append(Finding(
            "distrib.version-monotone", label,
            f"only {dv['reparents']} re-parent(s), expected >= "
            f"{expect_reparents} — the relay-death path passed "
            "vacuously"))
    joins = len([e for e in res.event_log if e[1] == "distrib_join"])
    if joins < expect_joins:
        out.append(Finding(
            "distrib.version-monotone", label,
            f"only {joins} distrib_join event(s), expected >= "
            f"{expect_joins} — the join storm never landed"))
    for i, rep in sorted((sv.get("replicas") or {}).items()):
        if rep.get("killed"):
            continue
        if rep.get("version") != sv.get("published"):
            out.append(Finding(
                "distrib.version-monotone", label,
                f"replica {i} quiesced at version {rep.get('version')}"
                f", committed head is {sv.get('published')} — its feed "
                "path never converged"))
    return out


# ---------------------------------------------------------------------------
# rule 1: tree validity under exhaustive kill/re-parent sequences
# ---------------------------------------------------------------------------


@registry.rule("distrib.tree-validity", "distrib",
               "exhaustive kill/re-parent sequences over the "
               "production tree math (every 1- and 2-node death order "
               "at several sizes): the repaired map stays connected, "
               "acyclic, and degree-capped, at logarithmic depth — "
               "and dropping the degree cap is caught")
def _run_tree_validity(report: Report) -> None:
    from bluefog_tpu.serve.distrib import tree as _tree

    # canonical placement: valid, capped, logarithmic at every size
    report.subjects_checked += 1
    for fanout in (2, 3, 4):
        for n in (1, 2, 5, 16, 33, 64):
            parents = {k: _tree.parent_of(k, fanout) for k in range(n)}
            err = _tree.tree_valid(parents, fanout,
                                   root_cap=fanout)
            if err:
                report.add(Finding(
                    "distrib.tree-validity", f"heap[n={n},f={fanout}]",
                    f"canonical placement invalid: {err}"))
            depth = _tree.tree_depth(parents)
            bound = _depth_bound(n, fanout) - 1  # no churn: exact bound
            if depth > bound:
                report.add(Finding(
                    "distrib.tree-validity", f"heap[n={n},f={fanout}]",
                    f"canonical depth {depth} > log_{fanout}({n})+1 "
                    f"= {bound}"))

    # exhaust every ordered death pair (and every single death) at
    # n=13/f=3 and n=9/f=2; after each reassign the map must still be
    # a valid tree and every survivor must keep a path to the publisher
    for n, fanout in ((13, 3), (9, 2)):
        report.subjects_checked += 1
        base = {k: _tree.parent_of(k, fanout) for k in range(n)}
        checked = 0
        for seq in itertools.chain(
                ((k,) for k in range(n)),
                itertools.permutations(range(n), 2)):
            parents = dict(base)
            for dead in seq:
                if dead not in parents:
                    continue  # died as a leaf of an earlier death
                parents = _tree.reassign(parents, dead, fanout)
                err = _tree.tree_valid(parents, fanout)
                checked += 1
                if err:
                    report.add(Finding(
                        "distrib.tree-validity",
                        f"kill-seq[n={n},f={fanout},seq={seq}]",
                        f"after killing {dead}: {err}"))
                    break
        report.metrics[f"distrib.kill-states/n={n}"] = float(checked)

    # sensitivity: the degree_cap=False knob (the seeded bug) must
    # produce an overload the validator catches, or the cap check is
    # vacuous
    report.subjects_checked += 1
    base = {k: _tree.parent_of(k, 3) for k in range(13)}
    broken = _tree.reassign(base, 1, 3, degree_cap=False)
    if _tree.tree_valid(broken, 3) is None:
        report.add(Finding(
            "distrib.tree-validity", "kill-seq[no-degree-cap]",
            "re-parenting with the degree cap dropped produced a tree "
            "the validator accepts — the fan-out bound is not actually "
            "checked"))


# ---------------------------------------------------------------------------
# rule 2: delta completeness (dirty map ∘ delta-apply ≡ full snapshot)
# ---------------------------------------------------------------------------


class _ChunkDroppingStore:
    """The seeded-bug wrapper: a feed whose delta silently drops one
    dirty chunk (the bug the commit CRC exists to catch)."""

    def __init__(self, store):
        self._store = store

    def delta_since(self, have: int, horizon: Optional[int] = None):
        full, items, meta = self._store.delta_since(have, horizon)
        if not full and len(items) > 1:
            items = items[1:]
        return full, items, meta


def _env_patched(**kv):
    """Context manager: patch env keys, restore on exit."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        saved = {k: os.environ.get(k) for k in kv}
        try:
            for k, v in kv.items():
                os.environ[k] = str(v)
            yield
        finally:
            for k, old in saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
    return cm()


def _update_stream(rng: np.random.RandomState, shape, versions: int,
                   nchunks: int, per: int):
    """Seeded sparse update stream: each version dirties a small
    random subset of chunks (the steady-state a delta plane exists
    for)."""
    arrs = []
    x = rng.standard_normal(shape).astype(np.float32)
    for _ in range(versions):
        dirty = rng.choice(nchunks, size=max(1, nchunks // 4),
                           replace=False)
        x = x.copy()
        flat = x.reshape(-1)
        for i in dirty:
            seg = flat[i * per:(i + 1) * per]
            seg += rng.standard_normal(seg.shape).astype(
                np.float32) * 0.01
        arrs.append(x)
    return arrs


def _dropped_chunk_stream():
    """Publisher + lag-2 subscriber with a chunk-dropping feed between
    them: v2 dirties chunks {0,1}, v3 dirties {2,3}, the feed drops
    one of the four dirty chunks from the delta."""
    from bluefog_tpu.serve.distrib.delta import ChunkStore, DeltaEncoder

    per = 1024 // 4  # 1 KiB chunks of f32
    nchunks = 6
    enc = DeltaEncoder()
    sub = ChunkStore()
    x = np.arange(nchunks * per, dtype=np.float32)
    enc.publish(1, 0, 1, x)
    full, items, meta = enc.store.delta_since(0)
    sub.install(meta, dict(items), full=full)
    for v, dirty in ((2, (0, 1)), (3, (2, 3))):
        x = x.copy()
        for i in dirty:
            x[i * per:(i + 1) * per] += float(v)
        enc.publish(v, 0, v, x)
    bad = _ChunkDroppingStore(enc.store)
    return enc, sub, bad


def stale_delta_findings() -> List[Finding]:
    """The seeded-bug probe (shared with the fixture corpus): a feed
    that silently drops a dirty chunk from its delta.  The
    completeness audit applies the delta with the runtime CRC gate
    bypassed, so the audit itself must notice the divergent bytes —
    dirty-map ∘ delta-apply no longer equals the full snapshot."""
    out: List[Finding] = []
    with _env_patched(BFTPU_WIRE_DTYPE="bf16", BFTPU_DISTRIB_CHUNK_KB=1):
        enc, sub, bad = _dropped_chunk_stream()
        full, items, meta = bad.delta_since(sub.version)
        got = sub.install(meta, dict(items), full=full, verify=False)
        _, want = enc.store.decode()
        if not np.array_equal(got, want):
            out.append(Finding(
                "distrib.delta-completeness", "fixture[dropped-chunk]",
                f"a delta missing a dirty chunk installed bytes "
                f"differing from the canonical v{meta.version} "
                "snapshot — the dirty map and the applied delta do "
                "not compose to the full snapshot"))
    return out


@registry.rule("distrib.delta-completeness", "distrib",
               "dirty-map deltas composed over seeded update streams "
               "reproduce the full canonical snapshot bit for bit at "
               "every codec (f32/bf16/int8) and every lag; beyond the "
               "horizon the feed degrades to a full resync; a delta "
               "missing a dirty chunk is un-installable (commit CRC)")
def _run_delta_completeness(report: Report) -> None:
    from bluefog_tpu.serve.distrib.delta import ChunkStore, DeltaEncoder

    horizon = 4
    nchunks = 6
    per = 1024 // 4
    for wire in ("f32", "bf16", "int8"):
        report.subjects_checked += 1
        label = f"delta[{wire},chunks={nchunks}]"
        with _env_patched(BFTPU_WIRE_DTYPE=wire,
                          BFTPU_DISTRIB_CHUNK_KB=1):
            rng = np.random.RandomState(11)
            enc = DeltaEncoder()
            arrs = _update_stream(rng, (nchunks * per,), 10, nchunks,
                                  per)
            # subscribers at lag 1 / lag 3 / past-horizon, all
            # applying deltas (or resyncs) against their own stores
            subs = {1: ChunkStore(), 3: ChunkStore(), 99: ChunkStore()}
            delta_chunks = 0
            fulls = {k: 0 for k in subs}
            for v, arr in enumerate(arrs, start=1):
                enc.publish(v, 0, v, arr)
                for lag, sub in subs.items():
                    if lag == 99:
                        # installs v1, then sleeps far past the
                        # horizon and wakes at the head
                        if v not in (1, len(arrs)):
                            continue
                    elif v % lag:
                        continue  # this subscriber polls every `lag`
                    full, items, meta = enc.store.delta_since(
                        sub.version, horizon)
                    fulls[lag] += bool(full)
                    if not full:
                        delta_chunks += len(items)
                    got = sub.install(meta, dict(items), full=full)
                    _, want = enc.store.decode()
                    if not np.array_equal(got, want):
                        report.add(Finding(
                            "distrib.delta-completeness", label,
                            f"subscriber at lag {lag} applied "
                            f"{'a full resync' if full else 'a delta'}"
                            f" to v{v} and holds bytes differing from "
                            "the canonical snapshot"))
            if fulls[99] != 2:
                report.add(Finding(
                    "distrib.delta-completeness", label,
                    f"a subscriber {len(arrs) - 1} versions behind "
                    f"took {fulls[99]} full resync(s), expected "
                    "exactly 2 (the bootstrap plus one past-horizon "
                    "degrade) — the horizon path is broken"))
            if fulls[1] > 1 or delta_chunks == 0:
                report.add(Finding(
                    "distrib.delta-completeness", label,
                    f"steady-state subscribers took {fulls[1]} extra "
                    f"full resync(s) and {delta_chunks} delta chunks "
                    "— the dirty map is not producing deltas"))

    # the exhaustive window: EVERY pair of dirty subsets over a
    # 3-chunk buffer (two publishes after the seed generation); the
    # lag-1 delta must reproduce the full snapshot in all 49 cases
    report.subjects_checked += 1
    with _env_patched(BFTPU_WIRE_DTYPE="f32", BFTPU_DISTRIB_CHUNK_KB=1):
        n3, cases = 3, 0
        for s1 in _subsets(n3):
            for s2 in _subsets(n3):
                enc = DeltaEncoder()
                sub = ChunkStore()
                base = np.arange(n3 * per, dtype=np.float32)
                enc.publish(1, 0, 1, base)
                full, items, meta = enc.store.delta_since(0)
                sub.install(meta, dict(items), full=full)
                x = base
                for v, dirty in ((2, s1), (3, s2)):
                    x = x.copy()
                    for i in dirty:
                        x[i * per:(i + 1) * per] += float(v)
                    enc.publish(v, 0, v, x)
                    full, items, meta = enc.store.delta_since(
                        sub.version, horizon)
                    got = sub.install(meta, dict(items), full=full)
                    if full or not np.array_equal(got, x):
                        report.add(Finding(
                            "distrib.delta-completeness",
                            f"exhaustive[s1={s1},s2={s2}]",
                            f"lag-1 delta at v{v} "
                            f"{'degraded to a full resync' if full else 'produced wrong bytes'}"))
                    want_sent = {i for i, _c in items}
                    if not set(dirty) <= want_sent:
                        report.add(Finding(
                            "distrib.delta-completeness",
                            f"exhaustive[s1={s1},s2={s2}]",
                            f"delta at v{v} omitted dirty chunk(s) "
                            f"{sorted(set(dirty) - want_sent)}"))
                cases += 1
        report.metrics["distrib.exhaustive-delta-cases"] = float(cases)

    # sensitivity: the chunk-dropping feed must (a) be visible to the
    # bypassed-CRC audit and (b) be REFUSED by the runtime CRC gate —
    # a gate that admits the torn generation is the finding here
    report.subjects_checked += 1
    if not stale_delta_findings():
        report.add(Finding(
            "distrib.delta-completeness", "delta[dropped-chunk]",
            "a delta with a dirty chunk dropped produced NO byte "
            "divergence — the completeness audit is not sensitive to "
            "the bug it exists to catch"))
    with _env_patched(BFTPU_WIRE_DTYPE="bf16", BFTPU_DISTRIB_CHUNK_KB=1):
        _enc, sub, bad = _dropped_chunk_stream()
        full, items, meta = bad.delta_since(sub.version)
        try:
            sub.install(meta, dict(items), full=full)
        except ValueError:
            pass  # the commit CRC refused the flip, as designed
        else:
            report.add(Finding(
                "distrib.delta-completeness", "delta[dropped-chunk]",
                "the staged-install CRC gate ADMITTED a delta missing "
                "a dirty chunk — a subscriber would serve bytes that "
                "match no committed snapshot"))


def _subsets(n: int):
    for r in range(n + 1):
        yield from itertools.combinations(range(n), r)


# ---------------------------------------------------------------------------
# rule 3: version monotone under relay death (pinned campaigns)
# ---------------------------------------------------------------------------


@registry.rule("distrib.version-monotone", "distrib",
               "pinned distribution-tree campaigns — clean, interior "
               "relay killed mid-fan-out and respawned, join storm "
               "mid-rollout — keep every standing invariant silent "
               "(tree-validity, staleness SLO, serve monotone and "
               "committed) while the subtree re-parents and converges "
               "back to the committed head")
def _run_version_monotone(report: Report) -> None:
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    cases = [
        ("clean", None, 0, 0, {}),
        ("relay-kill",
         FaultSchedule([Fault(kind="serve_kill", step=2, rank=0,
                              stop=16)]),
         3, 1, {}),
        ("join-storm", None, 0, 4,
         {"distrib_join_round": 8, "distrib_join_n": 4}),
    ]
    for name, sched, want_rep, want_join, extra in cases:
        label = f"distrib[n=16,seed=3,{name}]"
        report.subjects_checked += 1
        _cfg, _sched, res = distrib_campaign(16, 24, 3, schedule=sched,
                                             **extra)
        report.extend(campaign_findings(res, label))
        report.extend(_distrib_path_findings(
            res, label, expect_reparents=want_rep,
            expect_joins=want_join))
        report.metrics[f"distrib.reparents/{label}"] = float(
            (res.final["serve"].get("distrib") or {}).get(
                "reparents", -1))


def selftest_distrib_campaigns():
    """The ``--self-test`` arm: acceptance-size distribution campaigns
    under relay chaos, clean + non-vacuous + bit-identical on a second
    run.  Returns ``(label, result, findings)`` triples."""
    from bluefog_tpu.sim.campaign import run_campaign
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    out = []
    for ranks, rounds, seed, kind in DISTRIB_PINS:
        extra = {}
        if kind == "relay-kill":
            sched = FaultSchedule([Fault(kind="serve_kill", step=2,
                                         rank=0, stop=rounds - 10)],
                                  seed=seed)
            want_rep, want_join = 3, 1
        elif kind == "relay-storm":
            sched = _storm_schedule(rounds, seed)
            extra = {"distrib_join_round": 8, "distrib_join_n": 4}
            want_rep, want_join = 4, 4
        else:
            sched = FaultSchedule(seed=seed)
            want_rep, want_join = 0, 0
        cfg, sched, res = distrib_campaign(ranks, rounds, seed,
                                           schedule=sched, **extra)
        label = f"distrib[n={ranks},seed={seed},{kind}]"
        findings = campaign_findings(res, label)
        findings.extend(_distrib_path_findings(
            res, label, expect_reparents=want_rep,
            expect_joins=want_join))
        again = run_campaign(cfg, sched)
        if again.digest != res.digest:
            findings.append(Finding(
                "distrib.version-monotone", label,
                f"same-seed distribution campaign diverged: "
                f"{res.digest[:16]} != {again.digest[:16]}"))
        out.append((label, res, findings))
    return out
