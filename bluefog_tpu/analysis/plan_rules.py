"""Rule family 1: static verification of comm plans and topologies.

Verifies, entirely on the host and without touching a device, the
invariants the gossip runtime's correctness rests on:

- every ``CommPlan`` shift class is a valid permutation (each rank at
  most once as source and at most once as destination) — the precondition
  for lowering a class to one ``lax.ppermute``;
- the classes jointly cover the topology's (non-self) edge set exactly —
  a dropped edge silently biases the average toward the remaining
  neighbors, a duplicated one double-counts a neighbor;
- the reconstructed mixing matrix is row-stochastic (decentralized
  averaging's convergence condition, arXiv:2111.04287 §2) and — for
  every constructor in this library — column-stochastic, which is what
  preserves the global average exactly;
- the spectral gap ``1 - |λ₂(W)|`` is strictly positive (gossip actually
  mixes) and is reported per topology as a metric;
- the per-class slot/mask bookkeeping (``slot_index``, ``recv_mask``,
  ``send_mask``) is self-consistent with the in-neighbor lists that
  drive ``neighbor_allgather`` output placement.

The default corpus is every named constructor × every size in
``DEFAULT_SIZES`` (2..64), plus one step of each dynamic one-peer
generator — the shapes the HLO contracts and benchmarks deploy.
"""

from __future__ import annotations

import functools
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import networkx as nx
import numpy as np

from bluefog_tpu import topology_util as tu
from bluefog_tpu.core.plan import CommPlan, compile_plan, plan_from_neighbor_lists

from bluefog_tpu.analysis.engine import Finding, Report, Severity, registry

__all__ = [
    "CORPUS_TOPOLOGIES",
    "DEFAULT_SIZES",
    "check_classes_are_permutations",
    "check_edge_cover",
    "check_slot_consistency",
    "check_mixing_stochastic",
    "check_spectral_gap",
    "check_plan",
    "spectral_gap",
]

_TOL = 1e-9

#: Named corpus: label -> constructor(size).  Every constructor here
#: produces a doubly stochastic mixing matrix (uniform weights on regular
#: graphs; Metropolis–Hastings on the irregular ones), so the column
#: check applies corpus-wide.
CORPUS_TOPOLOGIES = {
    "exp2": tu.ExponentialTwoGraph,
    "sym_exp4": tu.SymmetricExponentialGraph,
    "ring": tu.RingGraph,
    "ring_uni": lambda n: tu.RingGraph(n, connect_style=1),
    "star": tu.StarGraph,
    "mesh2d": tu.MeshGrid2DGraph,
    "full": tu.FullyConnectedGraph,
}

DEFAULT_SIZES: Tuple[int, ...] = tuple(range(2, 65))


# ---------------------------------------------------------------------------
# per-subject checks (pure; tests call these directly)
# ---------------------------------------------------------------------------


def check_classes_are_permutations(plan: CommPlan,
                                   label: str = "plan") -> List[Finding]:
    """Each shift class must be a permutation fragment: within one class a
    rank appears at most once as source and at most once as destination,
    and every rank index is in range.  (Self-edges are permitted — the
    loopback bench plan uses one — but must still be unique.)"""
    out: List[Finding] = []
    for c, cls in enumerate(plan.classes):
        srcs = [s for s, _ in cls.perm]
        dsts = [d for _, d in cls.perm]
        subject = f"{label} class {c}"
        for kind, ranks in (("source", srcs), ("destination", dsts)):
            dup = {r for r in ranks if ranks.count(r) > 1}
            if dup:
                out.append(Finding(
                    "plan.class-permutation", subject,
                    f"rank(s) {sorted(dup)} appear more than once as "
                    f"{kind} — the class cannot lower to one ppermute"))
        bad = [(s, d) for s, d in cls.perm
               if not (0 <= s < plan.size and 0 <= d < plan.size)]
        if bad:
            out.append(Finding(
                "plan.class-permutation", subject,
                f"edge(s) {bad} reference ranks outside 0..{plan.size - 1}"))
    return out


def _topology_edges(topo: nx.DiGraph) -> List[Tuple[int, int]]:
    return sorted((int(u), int(v)) for u, v in topo.edges if u != v)


def check_edge_cover(plan: CommPlan, topo: nx.DiGraph,
                     label: str = "plan") -> List[Finding]:
    """The union of class perms must equal the topology's non-self edge
    set exactly — each edge in exactly one class."""
    out: List[Finding] = []
    plan_edges: List[Tuple[int, int]] = []
    for cls in plan.classes:
        plan_edges.extend(cls.perm)
    plan_sorted = sorted(plan_edges)
    dup = sorted({e for e in plan_sorted if plan_edges.count(e) > 1})
    if dup:
        out.append(Finding(
            "plan.edge-cover", label,
            f"edge(s) {dup[:6]} appear in more than one class — the value "
            "would be combined twice"))
    topo_edges = _topology_edges(topo)
    missing = sorted(set(topo_edges) - set(plan_sorted))
    extra = sorted(set(plan_sorted) - set(topo_edges))
    if missing:
        out.append(Finding(
            "plan.edge-cover", label,
            f"{len(missing)} topology edge(s) not scheduled by any class "
            f"(first: {missing[:6]}) — those neighbors never transfer"))
    if extra:
        out.append(Finding(
            "plan.edge-cover", label,
            f"{len(extra)} scheduled edge(s) not in the topology "
            f"(first: {extra[:6]})"))
    return out


def check_slot_consistency(plan: CommPlan,
                           label: str = "plan") -> List[Finding]:
    """recv_mask/send_mask/slot_index must agree with the class perms and
    with the ascending in-neighbor slot convention."""
    out: List[Finding] = []
    for c, cls in enumerate(plan.classes):
        subject = f"{label} class {c}"
        recv_of = {d: s for s, d in cls.perm}
        send_set = {s for s, _ in cls.perm}
        for r in range(plan.size):
            recv_expected = 1 if r in recv_of else 0
            if cls.recv_mask[r] != recv_expected:
                out.append(Finding(
                    "plan.slot-consistency", subject,
                    f"recv_mask[{r}] = {cls.recv_mask[r]} but the class "
                    f"{'delivers' if recv_expected else 'does not deliver'} "
                    f"to rank {r}"))
            send_expected = 1.0 if r in send_set else 0.0
            if float(cls.send_mask[r]) != send_expected:
                out.append(Finding(
                    "plan.slot-consistency", subject,
                    f"send_mask[{r}] = {cls.send_mask[r]}, expected "
                    f"{send_expected}"))
            if r in recv_of:
                nbrs = plan.in_neighbors[r]
                src = recv_of[r]
                want = nbrs.index(src) if src in nbrs else None
                if want is None or cls.slot_index[r] != want:
                    out.append(Finding(
                        "plan.slot-consistency", subject,
                        f"slot_index[{r}] = {cls.slot_index[r]} but source "
                        f"{src} sits at position {want} of in-neighbors "
                        f"{nbrs} — allgather output placement would "
                        "scramble"))
            elif cls.slot_index[r] != -1:
                out.append(Finding(
                    "plan.slot-consistency", subject,
                    f"slot_index[{r}] = {cls.slot_index[r]} for a rank that "
                    "receives nothing (expected -1)"))
            if cls.recv_mask[r] == 0 and cls.recv_weights[r] != 0.0:
                out.append(Finding(
                    "plan.slot-consistency", subject,
                    f"recv_weights[{r}] = {cls.recv_weights[r]} but "
                    "recv_mask is 0 — a masked rank must carry zero weight"))
    for d in range(plan.size):
        if plan.in_degrees[d] != len(plan.in_neighbors[d]):
            out.append(Finding(
                "plan.slot-consistency", f"{label} rank {d}",
                f"in_degrees[{d}] = {plan.in_degrees[d]} != "
                f"len(in_neighbors) = {len(plan.in_neighbors[d])}"))
        if plan.out_degrees[d] != len(plan.out_neighbors[d]):
            out.append(Finding(
                "plan.slot-consistency", f"{label} rank {d}",
                f"out_degrees[{d}] = {plan.out_degrees[d]} != "
                f"len(out_neighbors) = {len(plan.out_neighbors[d])}"))
    return out


def check_mixing_stochastic(plan: CommPlan, label: str = "plan",
                            expect_column: bool = True,
                            tol: float = _TOL) -> List[Finding]:
    """Rows of the reconstructed W must sum to 1 (convergence to *a*
    consensus); columns too when the constructor promises it (convergence
    to the *average*); entries must be non-negative.

    The numeric core is shared with the fleet simulator's continuous
    invariant audit (``sim.invariants.stochastic_violations``) — one
    implementation of the property, checked offline on plans and online
    on campaign topologies."""
    from bluefog_tpu.sim.invariants import stochastic_violations

    return [Finding("plan.mixing-stochastic", label, msg)
            for msg in stochastic_violations(
                plan.mixing_matrix(), expect_column=expect_column,
                tol=tol)]


def spectral_gap(W: np.ndarray) -> float:
    """``1 - |λ₂|`` of the mixing matrix: the per-step contraction rate of
    the consensus error for doubly stochastic W."""
    if W.shape[0] < 2:
        return 1.0
    mods = np.sort(np.abs(np.linalg.eigvals(W)))[::-1]
    return float(1.0 - mods[1])


def check_spectral_gap(plan: CommPlan, label: str = "plan",
                       min_gap: float = 1e-9) -> Tuple[List[Finding], float]:
    """Returns (findings, gap).  A zero gap on a connected topology means
    the chain does not mix (e.g. a periodic W) — an error; the gap value
    itself is the reported metric."""
    gap = spectral_gap(plan.mixing_matrix())
    out: List[Finding] = []
    if plan.size > 1 and gap <= min_gap:
        out.append(Finding(
            "plan.spectral-gap", label,
            f"spectral gap {gap:.3e} <= {min_gap:.0e} — gossip on this "
            "plan never contracts the consensus error"))
    return out, gap


def check_plan(plan: CommPlan, topo: Optional[nx.DiGraph] = None,
               label: str = "plan", expect_column: bool = True,
               report: Optional[Report] = None) -> Report:
    """Run every plan rule on one subject; returns the (shared) report."""
    report = report if report is not None else Report()
    report.subjects_checked += 1
    report.extend(check_classes_are_permutations(plan, label))
    if topo is not None:
        report.extend(check_edge_cover(plan, topo, label))
    report.extend(check_slot_consistency(plan, label))
    report.extend(check_mixing_stochastic(plan, label,
                                          expect_column=expect_column))
    findings, gap = check_spectral_gap(plan, label)
    report.extend(findings)
    report.metric(f"plan.spectral_gap/{label}", round(gap, 6))
    return report


# ---------------------------------------------------------------------------
# default corpus + registration
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _corpus_subject(name: str, size: int):
    topo = CORPUS_TOPOLOGIES[name](size)
    return topo, compile_plan(topo)


def iter_corpus(sizes: Sequence[int] = DEFAULT_SIZES):
    for name in CORPUS_TOPOLOGIES:
        for n in sizes:
            topo, plan = _corpus_subject(name, n)
            yield f"{name}@{n}", topo, plan


@registry.rule("plan.corpus", "plan",
               "all plan/topology rules over every named constructor x "
               "sizes 2..64")
def _run_corpus(report: Report) -> None:
    worst: Dict[str, float] = {}
    for label, topo, plan in iter_corpus():
        report.subjects_checked += 1
        report.extend(check_classes_are_permutations(plan, label))
        report.extend(check_edge_cover(plan, topo, label))
        report.extend(check_slot_consistency(plan, label))
        report.extend(check_mixing_stochastic(plan, label))
        findings, gap = check_spectral_gap(plan, label)
        report.extend(findings)
        fam = label.split("@")[0]
        worst[fam] = min(worst.get(fam, 1.0), gap)
    for fam, gap in sorted(worst.items()):
        report.metric(f"plan.min_spectral_gap/{fam}", round(gap, 6))


@registry.rule("plan.dynamic-one-peer", "plan",
               "each dynamic one-peer generator step is a single "
               "permutation class")
def _run_dynamic(report: Report) -> None:
    for n in (2, 4, 8, 16, 32, 64):
        gens = [tu.GetDynamicOnePeerSendRecvRanks(n, r) for r in range(n)]
        for step in range(max(1, n.bit_length() - 1)):
            pairs = [next(g) for g in gens]
            src_ranks = [recv for _, recv in pairs]
            plan = plan_from_neighbor_lists(n, src_ranks)
            label = f"one_peer@{n} step {step}"
            report.subjects_checked += 1
            report.extend(check_classes_are_permutations(plan, label))
            report.extend(check_mixing_stochastic(plan, label))
            if len(plan.classes) != 1:
                report.add(Finding(
                    "plan.dynamic-one-peer", label,
                    f"{len(plan.classes)} shift classes (expected 1): a "
                    "one-peer step must lower to exactly one ppermute"))
