"""Rule family 6: telemetry snapshots, counter laws, and env-var lint.

The telemetry layer (:mod:`bluefog_tpu.telemetry`) is itself an artifact
worth verifying: a snapshot that drifts off schema breaks the merge CLI,
a counter that ever DECREASES means some code path overwrote instead of
accumulated, and an unbalanced mailbox ledger means deposits were lost
(or double-counted) somewhere between a writer's ``win_put`` and a
reader's collect/drain.  Three laws, one lint:

- **schema** — every per-rank snapshot carries the
  ``bftpu-telemetry-snapshot/1`` tag and well-formed counter / gauge /
  histogram entries (counts array one longer than the bucket edges,
  non-negative counter values);
- **monotone** — across a time-ordered snapshot sequence from one rank,
  no counter value decreases (counters only ``inc``/``add``; a
  regression means a reset or a raced overwrite);
- **conservation** — over a quiescent job's merged corpus,
  ``deposits == collected + drained + pending`` (the mailbox mass
  ledger telescopes: every slot's monotone version count is retired
  exactly once, into exactly one of the three sinks);
- **env lint** — every ``BFTPU_*`` / ``BLUEFOG_*`` env var referenced
  anywhere under ``bluefog_tpu/`` is documented in README.md or
  ``docs/*.md`` (an undocumented knob is an unfindable knob).

The registered rules drive a synthetic in-memory corpus (no files, no
jax); the ``check_*`` helpers are pure and are what the fixtures and
the merge CLI's ``--check`` call directly.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterable, List, Sequence, Set, Tuple

from bluefog_tpu.telemetry.registry import (
    LEDGER_COLLECTED,
    LEDGER_DEPOSITS,
    LEDGER_DRAINED,
    LEDGER_PENDING,
    SNAPSHOT_SCHEMA,
    Registry as TelemetryRegistry,
)
from bluefog_tpu.telemetry.merge import ledger_balance, merge_snapshots

from bluefog_tpu.analysis.engine import Finding, Report, registry

__all__ = [
    "ENV_VAR_RE",
    "check_snapshot_schema",
    "check_counters_monotone",
    "check_conservation",
    "check_snapshot_corpus",
    "scan_env_vars",
    "documented_vars",
    "check_env_documented",
]

#: The namespaced env-var shape this repo uses for all its knobs.
ENV_VAR_RE = re.compile(r"\b(?:BFTPU|BLUEFOG)_[A-Z][A-Z0-9_]*")


# ---------------------------------------------------------------------------
# snapshot schema
# ---------------------------------------------------------------------------


def _entry_errors(entry: object, kind: str) -> List[str]:
    if not isinstance(entry, dict):
        return [f"{kind} entry is not an object: {entry!r}"]
    errs = []
    if not isinstance(entry.get("name"), str) or not entry.get("name"):
        errs.append(f"{kind} entry missing a name: {entry!r}")
    labels = entry.get("labels")
    if labels is not None and not isinstance(labels, dict):
        errs.append(f"{kind} {entry.get('name')!r} labels not a mapping")
    if kind in ("counter", "gauge"):
        v = entry.get("value")
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            errs.append(f"{kind} {entry.get('name')!r} value not numeric")
        elif kind == "counter" and v < 0:
            errs.append(f"counter {entry.get('name')!r} is negative ({v}) "
                        "— counters only accumulate")
    if kind == "histogram":
        buckets = entry.get("buckets")
        counts = entry.get("counts")
        if not isinstance(buckets, list) or not isinstance(counts, list):
            errs.append(f"histogram {entry.get('name')!r} missing "
                        "buckets/counts arrays")
        elif len(counts) != len(buckets) + 1:
            errs.append(
                f"histogram {entry.get('name')!r} has {len(counts)} counts "
                f"for {len(buckets)} bucket edges (want edges+1: the last "
                "count is the overflow bucket)")
        if not isinstance(entry.get("sum"), (int, float)):
            errs.append(f"histogram {entry.get('name')!r} missing sum")
    return errs


def check_snapshot_schema(snap: dict, label: str = "snapshot"
                          ) -> List[Finding]:
    """One per-rank snapshot dict against the v1 schema."""
    out: List[Finding] = []

    def err(msg: str):
        out.append(Finding("telemetry.snapshot-schema", label, msg))

    if not isinstance(snap, dict):
        err(f"snapshot is not an object: {type(snap).__name__}")
        return out
    if snap.get("schema") != SNAPSHOT_SCHEMA:
        err(f"schema tag is {snap.get('schema')!r}, want "
            f"{SNAPSHOT_SCHEMA!r} — the merge CLI would skip this file")
    if not isinstance(snap.get("rank"), int):
        err(f"rank is {snap.get('rank')!r}, want an int")
    for kind, key in (("counter", "counters"), ("gauge", "gauges"),
                      ("histogram", "histograms")):
        entries = snap.get(key, [])
        if not isinstance(entries, list):
            err(f"{key} is not a list")
            continue
        for entry in entries:
            for msg in _entry_errors(entry, kind):
                err(msg)
    return out


# ---------------------------------------------------------------------------
# counter monotonicity across a snapshot sequence
# ---------------------------------------------------------------------------


def _counter_map(snap: dict) -> Dict[Tuple, float]:
    out: Dict[Tuple, float] = {}
    for c in snap.get("counters", []):
        labels = c.get("labels") or {}
        key = (c["name"], tuple(sorted((k, str(v))
                                       for k, v in labels.items())))
        out[key] = float(c["value"])
    return out


def check_counters_monotone(snaps: Sequence[dict],
                            label: str = "snapshot-sequence"
                            ) -> List[Finding]:
    """Time-ordered snapshots from ONE rank: no counter may decrease."""
    out: List[Finding] = []
    prev: Dict[Tuple, float] = {}
    for i, snap in enumerate(snaps):
        cur = _counter_map(snap)
        for key, v in cur.items():
            was = prev.get(key)
            if was is not None and v < was:
                name, labels = key
                out.append(Finding(
                    "telemetry.counter-monotone", label,
                    f"counter {name!r} {dict(labels)} regressed "
                    f"{was} -> {v} between snapshots {i - 1} and {i} — "
                    "some code path overwrote instead of accumulating"))
        prev = cur
    return out


# ---------------------------------------------------------------------------
# mailbox-ledger conservation
# ---------------------------------------------------------------------------


def check_conservation(snaps: Sequence[dict], label: str = "job"
                       ) -> List[Finding]:
    """Merged ledger identity over a quiescent job's snapshot corpus:
    ``deposits == collected + drained + pending``.  Only meaningful when
    the corpus carries ledger counters at all (a job with telemetry on
    but no window traffic trivially balances at 0 == 0)."""
    merged = merge_snapshots(list(snaps))
    bal = ledger_balance(merged)
    if bal["balanced"]:
        return []
    return [Finding(
        "telemetry.conservation", label,
        f"mailbox ledger does not balance: deposits={bal['deposits']:g} "
        f"!= collected={bal['collected']:g} + drained={bal['drained']:g} "
        f"+ pending={bal['pending']:g} — a deposit was lost or retired "
        "twice between win_put and collect/drain")]


def check_snapshot_corpus(snaps: Sequence[dict]) -> List[Finding]:
    """Everything the merge CLI's ``--check`` verifies on a corpus:
    per-snapshot schema + cross-rank conservation."""
    out: List[Finding] = []
    for snap in snaps:
        r = snap.get("rank", "?") if isinstance(snap, dict) else "?"
        out.extend(check_snapshot_schema(snap, label=f"rank {r}"))
    if not out:  # schema-broken snapshots would make the merge nonsense
        out.extend(check_conservation(snaps))
    return out


# ---------------------------------------------------------------------------
# env-var lint
# ---------------------------------------------------------------------------


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))  # .../bluefog_tpu/analysis
    return os.path.dirname(os.path.dirname(here))


def scan_env_vars(root: str = None) -> Dict[str, List[str]]:
    """Every ``BFTPU_*``/``BLUEFOG_*`` name referenced in the package
    sources, mapped to the files that mention it."""
    root = _repo_root() if root is None else root
    pkg = os.path.join(root, "bluefog_tpu")
    out: Dict[str, List[str]] = {}
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    text = f.read()
            except OSError:
                continue
            rel = os.path.relpath(path, root)
            for var in set(ENV_VAR_RE.findall(text)):
                out.setdefault(var, []).append(rel)
    return out


def documented_vars(root: str = None) -> Set[str]:
    """Env vars mentioned anywhere in README.md or docs/*.md."""
    root = _repo_root() if root is None else root
    docs = [os.path.join(root, "README.md")]
    docdir = os.path.join(root, "docs")
    if os.path.isdir(docdir):
        docs.extend(os.path.join(docdir, f) for f in sorted(os.listdir(docdir))
                    if f.endswith(".md"))
    seen: Set[str] = set()
    for path in docs:
        try:
            with open(path, "r", encoding="utf-8") as f:
                seen.update(ENV_VAR_RE.findall(f.read()))
        except OSError:
            continue
    return seen


#: Names the regex matches that are not actually env knobs (prefixes of
#: messages, identifiers in comments about the naming scheme itself).
_ENV_LINT_ALLOW: Set[str] = set()


def check_env_documented(used: Dict[str, List[str]], documented: Set[str],
                         label: str = "bluefog_tpu") -> List[Finding]:
    """Every referenced env var must appear in the docs."""
    out: List[Finding] = []
    for var in sorted(used):
        if var in documented or var in _ENV_LINT_ALLOW:
            continue
        files = ", ".join(sorted(set(used[var]))[:3])
        out.append(Finding(
            "telemetry.env-documented", label,
            f"env var {var} is referenced ({files}) but documented "
            "nowhere in README.md or docs/*.md — every knob needs a "
            "findable description (docs/OBSERVABILITY.md keeps the "
            "index)"))
    return out


# ---------------------------------------------------------------------------
# registered rules: synthetic in-memory corpus + the real source tree
# ---------------------------------------------------------------------------


def _synthetic_corpus(nranks: int = 4) -> List[dict]:
    """An in-memory 4-rank ring-gossip job: every rank deposits into its
    two neighbors each of 3 rounds; the last round's deposits are still
    un-collected at "teardown" and get probed into the pending sink."""
    snaps = []
    for r in range(nranks):
        reg = TelemetryRegistry(out_dir=None, rank=r, job="synthetic")
        rounds, degree = 3, 2
        reg.counter(LEDGER_DEPOSITS).add(rounds * degree)
        reg.counter(LEDGER_COLLECTED).add((rounds - 1) * degree)
        reg.counter(LEDGER_PENDING).add(degree)
        reg.counter("win.edge_ops", op="win_put",
                    src=r, dst=(r + 1) % nranks).add(rounds)
        reg.gauge("optim.k").set(2)
        h = reg.histogram("win.op_s", op="win_put")
        for v in (1e-5, 2e-5, 1e-4):
            h.observe(v)
        snaps.append(reg.snapshot())
    return snaps


@registry.rule("telemetry.snapshot-schema", family="telemetry",
               doc="per-rank snapshots conform to the v1 schema")
def _rule_snapshot_schema(report: Report) -> None:
    for snap in _synthetic_corpus():
        report.subjects_checked += 1
        report.extend(check_snapshot_schema(
            snap, label=f"synthetic rank {snap['rank']}"))


@registry.rule("telemetry.counter-monotone", family="telemetry",
               doc="counters never decrease across a snapshot sequence")
def _rule_counter_monotone(report: Report) -> None:
    reg = TelemetryRegistry(out_dir=None, rank=0, job="synthetic")
    seq = []
    for _ in range(4):
        reg.counter("tcp.round_trips").add(5)
        reg.counter(LEDGER_DEPOSITS).inc()
        seq.append(reg.snapshot())
    report.subjects_checked += 1
    report.extend(check_counters_monotone(seq, label="synthetic rank 0"))


@registry.rule("telemetry.conservation", family="telemetry",
               doc="merged mailbox ledger balances on a quiescent corpus")
def _rule_conservation(report: Report) -> None:
    report.subjects_checked += 1
    report.extend(check_conservation(_synthetic_corpus(),
                                     label="synthetic 4-rank job"))


@registry.rule("telemetry.env-documented", family="telemetry",
               doc="every BFTPU_*/BLUEFOG_* env var referenced in the "
                   "package is documented in README.md or docs/*.md")
def _rule_env_documented(report: Report) -> None:
    used = scan_env_vars()
    report.subjects_checked += len(used)
    report.metric("telemetry.env_vars_referenced", float(len(used)))
    report.extend(check_env_documented(used, documented_vars()))
