"""bluefog_tpu.analysis — static verifier for the gossip runtime.

Four rule families over the seed's load-bearing artifacts, one shared
currency (:class:`~bluefog_tpu.analysis.engine.Finding`), three
consumers (CLI, pytest, CI):

- **plan** (:mod:`.plan_rules`) — every named topology x size 2..64:
  shift classes are permutations, classes cover the edge set exactly,
  the mixing matrix is doubly stochastic, the spectral gap is positive;
- **hlo** (:mod:`.hlo_rules`, :mod:`.hlo_corpus`) — declarative lint of
  post-partitioner HLO: collective budgets, no full-axis all-gather in
  FSDP programs, no replicated large buffers;
- **protocol** (:mod:`.seqlock_model`, :mod:`.epoch_rules`) — exhaustive
  interleaving check of the shm-mailbox seqlock/collect/barrier at small
  bounds, plus the window-op epoch-ordering lint;
- **resilience** (:mod:`.resilience_rules`, plus the dead-writer-drain
  model in :mod:`.seqlock_model`) — healed survivor topologies stay
  doubly stochastic and mixing with the dead fully excised, degraded
  combine rows conserve mass, and the force-drain of a dead writer's
  slot loses no committed deposit at any death point;
- **telemetry** (:mod:`.telemetry_rules`) — snapshot schema, counter
  monotonicity, the mailbox-ledger conservation identity
  (deposits == collected + drained + pending on a quiescent job), and
  the env-var lint (every BFTPU_*/BLUEFOG_* knob documented);
- **trace** (:mod:`.trace_rules`) — distributed-trace buffers: per-rank
  span nesting, cross-rank flow-endpoint resolution, and clock blocks
  within the min-RTT estimator's own error bound;
- **adaptive** (:mod:`.adaptive_rules`) — demoted (straggler-capped)
  topologies stay doubly stochastic and mixing with the straggler
  retained at degree one, restores round-trip to the pre-demotion W,
  and the driven EdgeHealth machine admits no demote/promote cycle
  shorter than the hysteresis floor;
- **progress** (:mod:`.progress_rules`) — the async progress engine:
  exhaustive submit/step/quiesce/resume interleavings on a real
  manual-mode engine (exactly-once handles, order-preserving fusion,
  nothing executes while parked), handle-lifecycle trace lint, and the
  fusion-batch contiguity/budget contract;
- **wire** (:mod:`.wire_rules`) — the one wire protocol shared by both
  carriers: ascending chunk-stream commit integrity, credit-window
  liveness of the pipelined TCP framing, error-feedback residual
  conservation across demotion, mid-stream writer death vs the
  disconnect drain, and TCP/shm protocol-spec parity;
- **introspect** (:mod:`.introspect_rules`) — the live introspection
  plane: status pages read back schema-exact, settled, and
  ledger-consistent; mutex holder words always name a live member and
  clear on release/heal; the critical-path blame feed gating adaptive
  demotion stays monotone;
- **sim** (:mod:`.sim_rules`) — the deterministic fleet simulator as a
  verifier: pinned-seed fault campaigns over the real protocol state
  machines finish clean (mass conserved, ledger balanced, consensus at
  quiesce), the same seed replays bit-identically, and a seeded
  invariant bug shrinks to its minimal schedule;
- **partition** (:mod:`.partition_rules`) — partition tolerance: the
  production quorum module's strict-majority arithmetic is pinned
  (even splits have NO quorum on either side), pinned-seed partition
  campaigns ORPHAN exactly the minority and merge every orphan back
  to consensus with a balanced ledger, and the seeded ``split_brain``
  bug is caught by the single-lineage invariant and ddmin-shrinks to
  the partition fault alone;
- **serve** (:mod:`.serve_rules`) — the serving plane: pinned serve
  campaigns (replica killed mid-swap and respawned, publisher killed
  mid-payload and mid-flip) publish strictly monotone versions with
  replicas converging to the committed head, the publish fence is
  pinned against the production quorum arithmetic with an
  orphaned-publisher campaign showing the handoff, and an exhaustive
  double-buffer interleaving model proves a completed read only ever
  returns a committed version's canonical bytes;
- **distrib** (:mod:`.distrib_rules`) — the snapshot distribution
  plane: exhaustive kill/re-parent sequences over the production
  fan-out tree math stay connected, acyclic and degree-capped at
  logarithmic depth, dirty-map deltas compose to the full canonical
  snapshot bit for bit at every codec and lag (degrading to a full
  resync past the horizon, with incomplete deltas un-installable),
  and pinned distribution campaigns (interior relay killed mid-fan-out,
  join storm mid-rollout) keep the tree-validity and staleness-SLO
  standing invariants silent while subtrees re-parent and converge;
- **monitor** (:mod:`.monitor_rules`) — the fleet monitor's sim twin:
  every seeded runtime-fault campaign raises exactly its matching
  alert (mass leak, demotion-cap bypass, split brain, silent SLO
  stall), the clean twins raise zero alerts with the campaign digest
  bit-identical monitor-on vs monitor-off, and the alert engine's
  gap-closing coalesces a sustained breach into one fully-accounted
  window;
- **slo** (:mod:`.slo_rules`) — the serve traffic observatory: pinned
  Poisson-load campaigns serve every admitted request within the SLO
  or excuse it with an overlapping fault window (replica kill,
  publisher death, publish churn), the seeded drain-skip and
  send-re-anchor bugs are caught by the request-SLO and open-loop
  invariants, and the trace-fitted per-edge latency sampler honors
  its measured anchors deterministically;
- **lab** (:mod:`.lab_rules`) — the convergence observatory's frozen
  sweep artifact: schema-valid, cell fits refittable from their own
  series, scaling laws non-increasing in fleet size, measured rates
  rank-correlated with spectral gaps, every cell sim-oracle clean,
  and the stored recommendation map consistent with recomputation;
- **transport** (:mod:`.transport_spec`) — the machine-readable window/
  mailbox contract: an executable spec table pinning every protocol
  constant (seqlock brackets, ascending chunk commit, drain-marker
  semantics, dead-writer drain order, mass-ledger identity, epoch
  quiesce/re-seed, holder-board stamps), a sequential
  ``ReferenceTransport`` implementing the contract, and the capability
  lint — every transport declares a :class:`TransportCaps` record, the
  declarations are honest against the implementations, and every call
  site relies only on declared capabilities;
- **conformance** (:mod:`.conformance`) — the generative differential
  harness: native shm, fallback shm, chunked TCP, legacy TCP and
  ``SimTransport`` all driven through the same pinned-seed op schedules
  as the reference model, observable state compared after every op,
  divergences ddmin-shrunk to 1-minimal repro schedules;
- **interleave** (:mod:`.interleave`) — the unified interleaving
  explorer: protocol state machines written in one little language,
  exhaustively explored with a vector-clock happens-before race check;
  re-expresses (and cross-checks against) the seqlock, chunk-ring and
  drain models and extends to the progress-engine queue and the serve
  double-buffer;
- the **fixture corpus** (:mod:`.fixtures`) — seeded bugs proving every
  rule fires.

Run ``python -m bluefog_tpu.analysis`` for the CLI (docs/ANALYSIS.md).

Importing this package registers every rule; importing it does NOT
touch a jax backend — only *running* the hlo family compiles programs.
"""

from bluefog_tpu.analysis.engine import (  # noqa: F401
    Finding,
    Report,
    Rule,
    Registry,
    Severity,
    registry,
)

# importing the family modules populates ``registry``
from bluefog_tpu.analysis import (  # noqa: F401
    adaptive_rules,
    conformance,
    distrib_rules,
    epoch_rules,
    fixtures,
    hlo_corpus,
    hlo_rules,
    interleave,
    introspect_rules,
    lab_rules,
    monitor_rules,
    partition_rules,
    plan_rules,
    progress_rules,
    resilience_rules,
    seqlock_model,
    serve_rules,
    sim_rules,
    slo_rules,
    telemetry_rules,
    trace_rules,
    transport_spec,
    wire_rules,
)

__all__ = [
    "Finding",
    "Report",
    "Rule",
    "Registry",
    "Severity",
    "registry",
    "run",
]


def run(families=None, verbose: bool = False) -> Report:
    """Run the registered rules (all families by default); see
    :meth:`Registry.run`."""
    return registry.run(families=families, verbose=verbose)
