"""Rule family: the fleet monitor's sim twin as a verifier.

The live monitor (:mod:`bluefog_tpu.monitor`) is a passive scraper over
the shm status pages feeding a declarative alert engine.  Its sim twin
(``SimConfig(monitor=True)``) samples the SAME rule engine on the
virtual clock — same series, same gap-closed windows — so the alerting
contract can be checked deterministically, campaign after campaign:

1. **alert completeness** — every seeded-bug campaign raises exactly
   the matching alert: ``mass_leak`` -> ``mass_imbalance``,
   ``cap_bypass`` -> ``demote_storm``, ``split_brain`` ->
   ``epoch_fork``, ``slo_silent_violation`` -> ``request_slo`` — and
   nothing else (an alert plane that also fires on the wrong rule is
   noise, not signal);
2. **false-alarm freedom** — the clean twins of those campaigns (same
   faults, kills, heals, partitions and Poisson load, no seeded bug)
   raise ZERO alerts: a kill/heal transient, an orphaned minority that
   merges back, or served-on-time traffic must never alarm;
3. **window coalescing** — a sustained breach produces ONE gap-closed
   alert window (not one per sample), separated breaches produce one
   window each, and every closed window carries its accounting
   (samples, worst, t0/t1) — flapping alerts are a seeded defect the
   corpus proves we catch.

Arming the monitor never perturbs the campaign: alert windows ride the
final dict, NOT the event log, so the digest is bit-identical with the
twin on or off — ``selftest_monitor_campaigns`` pins that identity at
the acceptance sizes (N=64/128/256).
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from bluefog_tpu.analysis.engine import Finding, Report, registry
from bluefog_tpu.analysis.sim_rules import SELFTEST_PINS, campaign_findings

__all__ = [
    "monitor_findings",
    "monitored_campaign",
    "selftest_monitor_campaigns",
    "MONITOR_PINS",
]

#: ``--self-test`` pinned clean campaigns (ranks, rounds, seed) — the
#: acceptance sizes, monitored; must raise zero alerts bit-identically.
MONITOR_PINS: Tuple[Tuple[int, int, int], ...] = SELFTEST_PINS


def monitored_campaign(ranks: int, rounds: int, seed: int,
                       schedule=None, **kw):
    """One monitored campaign: the sim twin armed, everything else per
    the sim family's defaults (``schedule=None`` = the canonical
    kill/heal schedule for the seed — the clean twins must see real
    churn and stay quiet)."""
    from bluefog_tpu.sim.campaign import SimConfig, run_campaign

    kw.setdefault("quiesce_rounds", max(10, rounds // 2))
    kw["monitor"] = True
    cfg = SimConfig(ranks=ranks, rounds=rounds, seed=seed, **kw)
    res = run_campaign(cfg, schedule)
    return cfg, res.schedule, res


def monitor_findings(res, label: str, expect: Sequence[str] = (),
                     max_windows_per_rule: int = 3) -> List[Finding]:
    """Audit a monitored campaign's alert windows against the expected
    alert set: every expected rule fired, nothing unexpected fired, no
    rule flapped (more than ``max_windows_per_rule`` windows), and the
    twin actually sampled (non-vacuity)."""
    out: List[Finding] = []
    mon = res.final.get("monitor")
    if mon is None:
        out.append(Finding(
            "monitor.alert-completeness", label,
            "no monitor accounting in the campaign result — the sim "
            "twin never armed"))
        return out
    if not mon["samples"]:
        out.append(Finding(
            "monitor.alert-completeness", label,
            "the monitor twin took ZERO samples — every alert check "
            "below would pass vacuously"))
    fired = {}
    per_subject = {}
    for w in mon["alerts"]:
        fired[w["rule"]] = fired.get(w["rule"], 0) + 1
        k = (w["rule"], w["subject"])
        per_subject[k] = per_subject.get(k, 0) + 1
    for want in expect:
        if want not in fired:
            out.append(Finding(
                "monitor.alert-completeness", label,
                f"seeded defect raised no {want!r} alert "
                f"(got {sorted(fired)}) — the monitor is silent on "
                "the incident it exists to catch"))
    extra = sorted(set(fired) - set(expect))
    if extra:
        out.append(Finding(
            "monitor.false-alarm-free", label,
            f"unexpected alert(s) {extra} fired "
            f"({sum(fired[r] for r in extra)} window(s)) — a monitor "
            "that alarms on healthy behavior trains operators to "
            "ignore it"))
    # flapping is per (rule, subject): N replicas each opening one
    # window is attribution, one replica opening N is noise
    flapping = sorted(k for k, n in per_subject.items()
                      if n > max_windows_per_rule)
    if flapping:
        out.append(Finding(
            "monitor.window-coalescing", label,
            f"rule/subject pair(s) {flapping} opened "
            f"{[per_subject[k] for k in flapping]} windows — a "
            f"sustained breach must coalesce into one gap-closed "
            f"window, not flap once per sample"))
    return out


@registry.rule("monitor.alert-completeness", "monitor",
               "every seeded-bug campaign raises exactly its matching "
               "alert — mass_leak->mass_imbalance, "
               "cap_bypass->demote_storm, split_brain->epoch_fork, "
               "slo_silent_violation->request_slo — and nothing else")
def _run_alert_completeness(report: Report) -> None:
    from bluefog_tpu.analysis import partition_rules, slo_rules
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    # mass_leak: a 1e-3 multiplicative combine leak -> mass_imbalance
    label = "monitor[mass_leak]"
    report.subjects_checked += 1
    _c, _s, res = monitored_campaign(16, 20, 3,
                                     debug_bugs=("mass_leak",))
    report.extend(monitor_findings(res, label,
                                   expect=("mass_imbalance",)))
    # cap_bypass: the adaptive step demotes a majority -> demote_storm
    label = "monitor[cap_bypass]"
    report.subjects_checked += 1
    sched = FaultSchedule(
        [Fault(kind="slow", step=3 + i, rank=i, duration_s=1.0, stop=35)
         for i in range(5)], seed=5)
    _c, _s, res = monitored_campaign(
        8, 40, 5, schedule=sched, quiesce_rounds=20, faults=("slow",),
        debug_bugs=("cap_bypass",))
    report.extend(monitor_findings(res, label,
                                   expect=("demote_storm",)))
    # split_brain: the quorum fence seeded out -> epoch_fork
    label = "monitor[split_brain]"
    report.subjects_checked += 1
    _c, _s, res = partition_rules.partition_campaign(
        16, 30, 3, (6, 11), debug_bugs=("split_brain",), monitor=True)
    report.extend(monitor_findings(res, label, expect=("epoch_fork",)))
    # slo_silent_violation: a drain that skips polls -> request_slo
    label = "monitor[slo_silent_violation]"
    report.subjects_checked += 1
    _c, _s, res = slo_rules.slo_campaign(
        16, 24, 3, debug_bugs=("slo_silent_violation",), monitor=True)
    report.extend(monitor_findings(res, label, expect=("request_slo",)))


@registry.rule("monitor.false-alarm-free", "monitor",
               "the clean twins of the seeded-bug campaigns — kills, "
               "heals, partitions, Poisson load, no bug — raise zero "
               "alerts, and arming the twin leaves the campaign digest "
               "bit-identical")
def _run_false_alarm_free(report: Report) -> None:
    from bluefog_tpu.analysis import partition_rules, slo_rules
    from bluefog_tpu.sim.campaign import SimConfig, run_campaign

    # clean base campaign (kills + heals happen; nothing may alarm)
    label = "monitor[clean]"
    report.subjects_checked += 1
    cfg, _s, res = monitored_campaign(16, 20, 3)
    report.extend(campaign_findings(res, label))
    report.extend(monitor_findings(res, label, expect=()))
    # the digest must not know the monitor exists
    off = run_campaign(
        SimConfig.from_dict({**cfg.to_dict(), "monitor": False}))
    if off.digest != res.digest:
        report.add(Finding(
            "monitor.false-alarm-free", label,
            f"arming the monitor twin changed the campaign digest: "
            f"{res.digest[:16]} != {off.digest[:16]} — the observer "
            "is perturbing the observed"))
    # clean partition: minority orphans and merges back, no alarm
    label = "monitor[clean-partition]"
    report.subjects_checked += 1
    _c, _s, res = partition_rules.partition_campaign(
        16, 30, 3, (6, 11), monitor=True)
    report.extend(campaign_findings(res, label))
    report.extend(monitor_findings(res, label, expect=()))
    # clean traffic: every request served inside the SLO, no alarm
    label = "monitor[clean-slo]"
    report.subjects_checked += 1
    _c, _s, res = slo_rules.slo_campaign(16, 24, 3, monitor=True)
    report.extend(campaign_findings(res, label))
    report.extend(monitor_findings(res, label, expect=()))


@registry.rule("monitor.window-coalescing", "monitor",
               "the alert engine's gap-closing: a sustained breach is "
               "ONE window with full accounting, separated breaches "
               "are one window each, recovery closes the lamp")
def _run_window_coalescing(report: Report) -> None:
    from bluefog_tpu.monitor.rules import (ALERT_STATE_FIRING,
                                           ALERT_STATE_OK, AlertEngine,
                                           AlertRule)

    label = "engine[sustained+separated]"
    report.subjects_checked += 1
    rule = AlertRule("hot", "temp", "gt", 1.0, "synthetic")
    eng = AlertEngine(rules=(rule,), gap_s=2.5)
    # 10 samples at cadence 1.0: breach over t=2..6, clean elsewhere
    for t in range(10):
        v = 5.0 if 2 <= t <= 6 else 0.0
        eng.feed(float(t), [("temp", "fleet", v)], wall=100.0 + t)
        if t == 4 and eng.state != ALERT_STATE_FIRING:
            report.add(Finding(
                "monitor.window-coalescing", label,
                f"engine state {eng.state} mid-breach — the lamp "
                "never lit"))
    eng.close()
    if eng.state != ALERT_STATE_OK:
        report.add(Finding(
            "monitor.window-coalescing", label,
            f"engine state {eng.state} after recovery + close — the "
            "lamp never cleared"))
    if len(eng.windows) != 1:
        report.add(Finding(
            "monitor.window-coalescing", label,
            f"one sustained 5-sample breach produced "
            f"{len(eng.windows)} window(s), want exactly 1"))
    else:
        w = eng.windows[0]
        if (w["samples"] != 5 or w["worst"] != 5.0
                or w["t0_mono"] != 2.0 or w["t1_mono"] != 6.0
                or w["t0_wall"] != 102.0 or w["t1_wall"] != 106.0):
            report.add(Finding(
                "monitor.window-coalescing", label,
                f"window accounting wrong: {w} (want samples=5, "
                "worst=5.0, t0/t1 mono 2..6, wall 102..106)"))
    # two breaches separated by more than the gap -> two windows
    eng2 = AlertEngine(rules=(rule,), gap_s=2.5)
    for t in range(12):
        v = 5.0 if t in (1, 2, 9, 10) else 0.0
        eng2.feed(float(t), [("temp", "fleet", v)])
    eng2.close()
    if len(eng2.windows) != 2:
        report.add(Finding(
            "monitor.window-coalescing", label,
            f"two breaches 7 s apart (gap 2.5 s) produced "
            f"{len(eng2.windows)} window(s), want exactly 2"))


def selftest_monitor_campaigns():
    """The ``--self-test`` arm: the acceptance-size clean campaigns
    (N=64/128/256), monitored — zero alerts, and both the digest and
    the alert list bit-identical on a second run.  Returns ``(label,
    result, findings)`` triples."""
    from bluefog_tpu.analysis.sim_rules import _config
    from bluefog_tpu.sim.campaign import run_campaign

    out = []
    for ranks, rounds, seed in MONITOR_PINS:
        cfg = _config(ranks, rounds, seed, quiesce_rounds=40,
                      monitor=True)
        res = run_campaign(cfg)
        label = f"monitor[n={ranks},seed={seed}]"
        findings = campaign_findings(res, label)
        findings.extend(monitor_findings(res, label, expect=()))
        again = run_campaign(cfg)
        if again.digest != res.digest:
            findings.append(Finding(
                "monitor.false-alarm-free", label,
                f"same-seed monitored campaign diverged: "
                f"{res.digest[:16]} != {again.digest[:16]}"))
        a1 = res.final.get("monitor", {}).get("alerts")
        a2 = again.final.get("monitor", {}).get("alerts")
        if a1 != a2:
            findings.append(Finding(
                "monitor.false-alarm-free", label,
                f"same-seed alert windows diverged: {a1} != {a2}"))
        out.append((label, res, findings))
    return out
