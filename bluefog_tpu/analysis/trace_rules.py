"""Rule family 7: distributed-trace buffers, flows, and clocks.

The tracing layer (:mod:`bluefog_tpu.tracing`) is the next artifact
worth verifying: a span buffer whose spans interleave without nesting
means two timing contexts raced on one rank (the merge would draw
overlapping boxes on one track and the critical-path walk would pick
nonsense predecessors), a consume whose ``(origin, op_id)`` identity no
producer ever emitted means a trace-context word was corrupted (or a
stale slot was re-consumed past the ``_trace_seen`` guard), and a clock
block that violates the min-RTT estimator's own arithmetic means the
offset applied at merge time is not the one the estimator produced.
Three laws:

- **nesting** — per rank, spans are properly nested or disjoint: for
  any two spans A, B either A contains B, B contains A, or they do not
  overlap (spans all come from paired ``begin``/``end`` on one control
  thread, so partial overlap is structurally impossible unless a token
  was dropped or reused);
- **flow endpoints** — every ``consume`` entry's flow identity resolves
  to an ``emit`` on the buffer of its claimed origin rank, and every
  ``emit``'s destination is a rank that exists in the corpus;
- **clock bounds** — each buffer's clock block obeys the estimator's
  identity (``err_s == best_rtt_s / 2``, both non-negative, a nonzero
  offset implies at least one sample), and no resolved flow completes
  before its producer *began* by more than the two endpoints' combined
  error bound (causality survives alignment).

The registered rules drive a synthetic in-memory 2-rank corpus (no
files, no processes); the ``check_*`` helpers are pure and are what the
fixtures and the merge CLI's ``--check`` call directly.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from bluefog_tpu.tracing.merge import _aligned_spans, flow_index

from bluefog_tpu.analysis.engine import Finding, Report, Severity, registry

__all__ = [
    "check_span_nesting",
    "check_flow_endpoints",
    "check_clock_offsets",
    "check_trace_corpus",
]

#: err_s is rtt/2 by construction; allow fp slop plus rounding in the
#: JSON round-trip.
_CLOCK_IDENTITY_TOL_S = 1e-9


# ---------------------------------------------------------------------------
# span nesting
# ---------------------------------------------------------------------------


def check_span_nesting(trace: Dict, label: str = "trace") -> List[Finding]:
    """Per-rank spans must be properly nested or disjoint.

    Sweep in start order with a stack of open intervals: a span that
    starts inside the top of stack but ends after it PARTIALLY overlaps
    — the broken-token signature."""
    out: List[Finding] = []
    spans = [s for s in trace.get("spans", ())
             if s.get("ph") != "i" and "t0" in s and "t1" in s]
    spans.sort(key=lambda s: (s["t0"], -s["t1"]))
    stack: List[Dict] = []
    for s in spans:
        while stack and stack[-1]["t1"] <= s["t0"]:
            stack.pop()
        if stack and s["t1"] > stack[-1]["t1"]:
            top = stack[-1]
            out.append(Finding(
                "trace.span-nesting", label,
                f"span {s.get('name')!r} [{s['t0']}, {s['t1']}] partially "
                f"overlaps {top.get('name')!r} [{top['t0']}, {top['t1']}] "
                "on one rank — begin/end tokens crossed (a span token was "
                "dropped, reused, or ended out of order)"))
            continue
        stack.append(s)
    return out


# ---------------------------------------------------------------------------
# flow endpoints
# ---------------------------------------------------------------------------


def check_flow_endpoints(traces: Sequence[Dict], label: str = "corpus"
                         ) -> List[Finding]:
    """Every consume resolves to an emit; every emit targets a known
    rank.  A corpus missing some rank's buffer (it died before writing)
    legitimately has dangling flows — those demote to warnings; a
    dangling flow whose ORIGIN buffer is present is an error (the
    context word was corrupted in the mailbox or mis-unpacked)."""
    out: List[Finding] = []
    spans, _ = _aligned_spans(traces)
    producers, flows = flow_index(spans)
    ranks = {int(t.get("rank", -1)) for t in traces}
    for fl in flows:
        if fl["producer"] is not None:
            continue
        ident = f"({fl['origin']}:{fl['op_id']})"
        if fl["origin"] in ranks:
            out.append(Finding(
                "trace.flow-endpoints", label,
                f"rank {fl['dst']} consumed flow {ident} but rank "
                f"{fl['origin']}'s buffer (present in the corpus) never "
                "emitted it — the trace-context word was corrupted in "
                "the mailbox or unpacked wrong"))
        else:
            out.append(Finding(
                "trace.flow-endpoints", label,
                f"rank {fl['dst']} consumed flow {ident} from rank "
                f"{fl['origin']}, whose buffer is missing from the "
                "corpus (rank died before writing?)",
                severity=Severity.WARNING))
    for s in spans:
        for e in s["emit"]:
            dst = int(e.get("dst", -1))
            if dst not in ranks:
                out.append(Finding(
                    "trace.flow-endpoints", label,
                    f"rank {s['rank']} emitted op {e.get('op_id')} to "
                    f"rank {dst}, which is not in the corpus "
                    f"(ranks {sorted(ranks)})",
                    severity=Severity.WARNING))
    return out


# ---------------------------------------------------------------------------
# clock offsets
# ---------------------------------------------------------------------------


def check_clock_offsets(traces: Sequence[Dict], label: str = "corpus"
                        ) -> List[Finding]:
    """Per-buffer estimator arithmetic + corpus-level causality."""
    out: List[Finding] = []
    for t in traces:
        r = t.get("rank", "?")
        clk = t.get("clock") or {}
        err = float(clk.get("err_s", 0.0))
        rtt = clk.get("best_rtt_s")
        samples = int(clk.get("samples", 0))
        offset = float(clk.get("offset_s", 0.0))
        if err < 0:
            out.append(Finding(
                "trace.clock-offsets", f"{label} rank {r}",
                f"clock err_s is negative ({err:g}) — rtt/2 cannot be"))
        if rtt is not None and abs(err - float(rtt) / 2.0) > \
                _CLOCK_IDENTITY_TOL_S:
            out.append(Finding(
                "trace.clock-offsets", f"{label} rank {r}",
                f"clock err_s={err:g} is not best_rtt_s/2={float(rtt)/2:g}"
                " — the offset in this buffer did not come from the "
                "min-RTT estimator"))
        if offset != 0.0 and samples < 1:
            out.append(Finding(
                "trace.clock-offsets", f"{label} rank {r}",
                f"nonzero clock offset ({offset:g}s) with zero samples — "
                "an offset was applied that no probe ever measured"))
    # causality: a resolved flow's consumer cannot COMPLETE before its
    # producer BEGAN by more than the two endpoints' combined error
    # bound.  (Producer END is not a bound: on an acked transport the
    # deposit lands remotely before the ack closes the producer span,
    # so consumers legitimately finish first.)
    spans, _ = _aligned_spans(traces)
    _, flows = flow_index(spans)
    for fl in flows:
        p, c = fl["producer"], fl["consumer"]
        if p is None:
            continue
        slack_us = p["err_us"] + c["err_us"] + 1.0
        lag_us = p["t0_us"] - c["t1_us"]
        if lag_us > slack_us:
            out.append(Finding(
                "trace.clock-offsets", label,
                f"flow ({fl['origin']}:{fl['op_id']}) "
                f"{p['rank']}->{c['rank']} completes {lag_us:.1f}us "
                f"BEFORE its producer began (allowed clock slack "
                f"{slack_us:.1f}us) — the applied offsets exceed the "
                "estimator's error bound"))
    return out


def check_trace_corpus(traces: Sequence[Dict]) -> List[Finding]:
    """Everything the merge CLI's ``--check`` verifies: per-buffer span
    nesting + corpus-wide flow resolution and clock bounds."""
    out: List[Finding] = []
    for t in traces:
        out.extend(check_span_nesting(
            t, label=f"rank {t.get('rank', '?')}"))
    out.extend(check_flow_endpoints(traces))
    out.extend(check_clock_offsets(traces))
    return out


# ---------------------------------------------------------------------------
# registered rules: synthetic in-memory 2-rank gossip corpus
# ---------------------------------------------------------------------------


def _synthetic_traces() -> List[Dict]:
    """Two ranks, two rounds of put→update with resolved flows, nested
    timeline sub-spans, and clock blocks straight off the estimator."""
    from bluefog_tpu.tracing.tracer import TRACE_SCHEMA

    def clock(offset: float, rtt: float, samples: int) -> Dict:
        return {"offset_s": offset, "err_s": rtt / 2.0,
                "best_rtt_s": rtt if samples else None,
                "samples": samples}

    us = 1000  # ns per µs keeps the numbers readable

    def buf(rank: int, peer: int, base: int, clk: Dict) -> Dict:
        spans = []
        for rnd in range(2):
            t = base + rnd * 100 * us
            op = rnd + 1
            spans.append({"name": "win_put", "win": "w", "round": rnd,
                          "t0": t, "t1": t + 30 * us,
                          "emit": [{"dst": peer, "op_id": op}]})
            spans.append({"name": "win_update", "win": "w", "round": rnd,
                          "t0": t + 40 * us, "t1": t + 90 * us,
                          "consume": [{"src": peer, "origin": peer,
                                       "op_id": op, "round": rnd}]})
        return {"schema": TRACE_SCHEMA, "job": "synthetic", "rank": rank,
                "nranks": 2, "rounds": 2, "clock": clk,
                "anchor": {"wall_s": 0.0, "mono_ns": base},
                "dropped": 0, "spans": spans}

    return [buf(0, 1, 10 * us, clock(0.0, 0.0, 0)),
            buf(1, 0, 12 * us, clock(2e-6, 8e-6, 3))]


@registry.rule("trace.span-nesting", family="trace",
               doc="per-rank spans are properly nested or disjoint")
def _rule_span_nesting(report: Report) -> None:
    for t in _synthetic_traces():
        report.subjects_checked += 1
        report.extend(check_span_nesting(
            t, label=f"synthetic rank {t['rank']}"))


@registry.rule("trace.flow-endpoints", family="trace",
               doc="every consumed flow resolves to an emit on its "
                   "origin rank's buffer")
def _rule_flow_endpoints(report: Report) -> None:
    report.subjects_checked += 1
    report.extend(check_flow_endpoints(_synthetic_traces(),
                                       label="synthetic 2-rank corpus"))


@registry.rule("trace.clock-offsets", family="trace",
               doc="clock blocks obey the min-RTT estimator identity and "
                   "aligned flows stay causal within the error bound")
def _rule_clock_offsets(report: Report) -> None:
    report.subjects_checked += 1
    report.extend(check_clock_offsets(_synthetic_traces(),
                                      label="synthetic 2-rank corpus"))
