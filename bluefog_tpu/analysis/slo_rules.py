"""Rule family: request-level SLO attribution as a verifier.

The serve traffic observatory (:mod:`bluefog_tpu.serve.loadgen`)
argues two request-level properties hold under arbitrary serving-plane
chaos:

1. **every SLO violation has a cause** — an admitted request that
   misses the latency SLO (or is served beyond the staleness SLO)
   always overlaps an injected fault window (replica kill, publisher
   death, publish churn, tree re-parent); a violation with no window
   is a silent serve-path stall;
2. **latency is charged open-loop** — from the SCHEDULED send instant
   of the arrival process, never re-anchored to when the server got
   around to the request (coordinated omission, the measurement bug
   the real load generator exists to avoid).

These rules run the sim's traffic model (``SimConfig(arrivals=...)``)
against pinned chaos campaigns and check the claims non-vacuously:

- **request-attributed** — clean, replica-kill and publisher-kill
  campaigns under Poisson load finish with zero request violations,
  requests actually flowed, and the kill campaigns excused a nonzero
  number of requests via their fault windows (the attribution path is
  exercised, not just silent);
- **omission-sensitivity** — the two seeded traffic bugs are CAUGHT:
  a drain that skips polls (``slo_silent_violation``) trips the
  request-SLO invariant and a drain that re-anchors send times
  (``loadgen_omission``) trips the open-loop invariant — a campaign
  that stays clean with either bug armed is not checking anything;
- **trace-latency** — the empirical per-edge latency sampler
  (:mod:`bluefog_tpu.sim.latency`) honors its anchors: quantiles are
  monotone, the median and p99 round-trip from a synthesized
  critical-path report, and arming the table leaves the campaign
  digest deterministic.
"""

from __future__ import annotations

from typing import List, Tuple

from bluefog_tpu.analysis.engine import Finding, Report, registry
from bluefog_tpu.analysis.sim_rules import campaign_findings

__all__ = [
    "slo_campaign",
    "selftest_slo_campaigns",
    "SLO_PINS",
]

#: ``--self-test`` pinned traffic campaigns: (ranks, rounds, seed,
#: fault kind or None) — Poisson load over >= 64 virtual replicas
#: (the acceptance size), with relay kills and publish churn.
SLO_PINS: Tuple[Tuple[int, int, int, object], ...] = (
    (16, 40, 7, None),
    (16, 40, 7, "serve_kill"),
    (16, 40, 11, "serve_pub_kill"),
)


def slo_campaign(ranks: int, rounds: int, seed: int,
                 schedule=None, **kw):
    """One traffic-enabled campaign: publisher analog every 4 rounds,
    Poisson arrivals at every replica, request SLO armed at its
    default (2x the round period)."""
    from bluefog_tpu.sim.campaign import SimConfig, run_campaign
    from bluefog_tpu.sim.schedule import FaultSchedule

    kw.setdefault("quiesce_rounds", max(10, rounds // 2))
    kw.setdefault("serve_every", 4)
    kw.setdefault("serve_replicas", 4)
    kw.setdefault("arrivals", "poisson")
    kw.setdefault("arrival_rate", 3.0)
    cfg = SimConfig(ranks=ranks, rounds=rounds, seed=seed, **kw)
    sched = schedule if schedule is not None else FaultSchedule()
    return cfg, sched, run_campaign(cfg, sched)


def _slo_path_findings(res, label: str,
                       expect_attributed: bool = False
                       ) -> List[Finding]:
    """Non-vacuity over the campaign's arrivals accounting."""
    out: List[Finding] = []
    arr = res.final.get("arrivals")
    if not arr:
        out.append(Finding(
            "slo.request-attributed", label,
            "no arrivals accounting in the campaign result — the "
            "traffic model never armed"))
        return out
    if not arr["admitted"]:
        out.append(Finding(
            "slo.request-attributed", label,
            "zero requests admitted — the arrival process is not "
            "running"))
    if arr["violations"]:
        out.append(Finding(
            "slo.request-attributed", label,
            f"{arr['violations']} request(s) violated an SLO with no "
            "fault window to attribute them to"))
    if expect_attributed and not arr["attributed"]:
        out.append(Finding(
            "slo.request-attributed", label,
            "a chaos campaign excused ZERO requests — the fault "
            "windows never overlapped any traffic, so the "
            "attribution path passed vacuously"))
    if not any(e[1] == "serve_requests" for e in res.event_log):
        out.append(Finding(
            "slo.request-attributed", label,
            "no serve_requests event in the log — replicas never "
            "drained their arrival queues"))
    return out


@registry.rule("slo.request-attributed", "slo",
               "pinned Poisson-load campaigns — clean, replica killed "
               "mid-load and respawned, publisher killed mid-publish — "
               "serve every admitted request within the SLO or excuse "
               "it with an overlapping fault window; the kill "
               "campaigns must actually excuse traffic")
def _run_request_attributed(report: Report) -> None:
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    cases = [
        ("clean", None, False),
        ("replica-kill",
         FaultSchedule([Fault(kind="serve_kill", step=2, rank=0,
                              stop=16)]), True),
        ("pub-kill-flip",
         FaultSchedule([Fault(kind="serve_pub_kill", step=2, rank=-1,
                              group="flip")]), False),
    ]
    for name, sched, expect_att in cases:
        label = f"slo[n=16,seed=3,{name}]"
        report.subjects_checked += 1
        _cfg, _sched, res = slo_campaign(
            16, 24, 3, schedule=sched, request_staleness_slo=3)
        report.extend(campaign_findings(res, label))
        report.extend(_slo_path_findings(res, label,
                                         expect_attributed=expect_att))
        arr = res.final.get("arrivals") or {}
        report.metrics[f"slo.requests/{label}"] = float(
            arr.get("served", 0))


@registry.rule("slo.omission-sensitivity", "slo",
               "the two seeded traffic bugs are caught: a drain that "
               "skips polls trips the request SLO, a drain that "
               "re-anchors send times trips the open-loop invariant — "
               "the attribution machinery is sensitive to what it "
               "verifies")
def _run_omission_sensitivity(report: Report) -> None:
    for bug, want in (("slo_silent_violation", "request-slo"),
                      ("loadgen_omission", "open-loop")):
        label = f"slo[n=16,seed=3,bug={bug}]"
        report.subjects_checked += 1
        _cfg, _sched, res = slo_campaign(16, 24, 3, debug_bugs=(bug,))
        names = {v["name"] for v in res.violations}
        if want not in names:
            report.add(Finding(
                "slo.omission-sensitivity", label,
                f"seeded bug {bug!r} produced no {want!r} violation "
                f"(got {sorted(names)}) — the invariant is not "
                "sensitive to the defect it exists to catch"))


@registry.rule("slo.trace-latency", "slo",
               "the trace-fitted per-edge latency sampler honors its "
               "anchors: quantiles monotone, median and p99 "
               "round-trip from a critical-path report, campaign "
               "digest deterministic with the table armed")
def _run_trace_latency(report: Report) -> None:
    import json
    import os
    import tempfile

    from bluefog_tpu.sim.latency import EmpiricalLatency, \
        load_trace_latency

    label = "trace-latency[2 edges]"
    report.subjects_checked += 1
    doc = {"rounds": 4, "stragglers": {"edge_latency": {
        "0->1": {"n": 40, "p50_us": 3000.0, "p99_us": 15000.0},
        "1->2": {"n": 38, "p50_us": 5000.0, "p99_us": 30000.0}}}}
    fd, path = tempfile.mkstemp(suffix=".json")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        table = load_trace_latency(path)
    finally:
        os.unlink(path)
    model = EmpiricalLatency(table)
    if len(model) != 2:
        report.add(Finding("slo.trace-latency", label,
                           f"loaded {len(model)} edge(s), expected 2"))
    for (u, v), (p50, p99) in (((0, 1), (0.003, 0.015)),
                               ((1, 2), (0.005, 0.030))):
        got50 = model.quantile(u, v, 0.5)
        got99 = model.quantile(u, v, 0.99)
        if abs(got50 - p50) > 1e-12 or abs(got99 - p99) > 1e-12:
            report.add(Finding(
                "slo.trace-latency", label,
                f"edge {u}->{v} anchors did not round-trip: "
                f"quantile(0.5)={got50} want {p50}, "
                f"quantile(0.99)={got99} want {p99}"))
        qs = [model.quantile(u, v, q / 20.0) for q in range(21)]
        if any(b < a for a, b in zip(qs, qs[1:])):
            report.add(Finding(
                "slo.trace-latency", label,
                f"edge {u}->{v} quantile function is not monotone: "
                f"{qs}"))
    # digest determinism with the table armed
    _cfg, _sched, r1 = slo_campaign(8, 16, 5, latency_table=table)
    _cfg, _sched, r2 = slo_campaign(8, 16, 5, latency_table=table)
    if r1.digest != r2.digest:
        report.add(Finding(
            "slo.trace-latency", label,
            f"same-seed campaign with the latency table armed "
            f"diverged: {r1.digest[:16]} != {r2.digest[:16]}"))


def selftest_slo_campaigns():
    """The ``--self-test`` arm: Poisson load over >= 64 virtual
    replicas under relay kills and publish churn — zero unattributed
    violations, nonzero excused traffic on the chaos pins, and
    bit-identical on a second run.  Returns ``(label, result,
    findings)`` triples."""
    from bluefog_tpu.sim.campaign import run_campaign
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    out = []
    for ranks, rounds, seed, kind in SLO_PINS:
        kw = {"serve_replicas": 64, "distrib_fanout": 4,
              "request_staleness_slo": 4, "arrival_rate": 1.5}
        if kind == "serve_kill":
            # rank 0 is a relay in the fanout-4 tree: its death
            # orphans a subtree mid-load
            sched = FaultSchedule([Fault(kind="serve_kill", step=2,
                                         rank=0, stop=rounds - 10)],
                                  seed=seed)
        elif kind == "serve_pub_kill":
            sched = FaultSchedule([Fault(kind="serve_pub_kill", step=2,
                                         rank=-1, group="flip")],
                                  seed=seed)
        else:
            sched = FaultSchedule(seed=seed)
        cfg, sched, res = slo_campaign(ranks, rounds, seed,
                                       schedule=sched, **kw)
        label = f"slo[n={ranks},seed={seed},{kind or 'clean'}]"
        findings = campaign_findings(res, label)
        findings.extend(_slo_path_findings(
            res, label, expect_attributed=(kind == "serve_kill")))
        again = run_campaign(cfg, sched)
        if again.digest != res.digest:
            findings.append(Finding(
                "slo.request-attributed", label,
                f"same-seed traffic campaign diverged: "
                f"{res.digest[:16]} != {again.digest[:16]}"))
        out.append((label, res, findings))
    return out
