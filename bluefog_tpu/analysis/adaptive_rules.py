"""Rule family: adaptive topology (gray-failure demotion) artifacts.

The adaptive control loop (resilience/adaptive.py) swaps topologies at
runtime: a straggler is demoted to one anchor edge via
:func:`~bluefog_tpu.resilience.healing.demote_topology`, and promoted
back when its edge turns clean.  Every W it can produce is exactly as
load-bearing as a fresh one, and the state machine that produces them
has its own invariant — hysteresis — that no runtime test can pin down
as tightly as a driven simulation.  Three rule groups:

- **demoted corpus** — every named topology x sizes 4..16 x straggler
  sets: the demoted W is doubly stochastic with a positive spectral
  gap, the straggler is STILL a member (demotion is not death — excising
  it would orphan its pending mass), its gossip degree is capped at one
  anchor edge, and the recompiled plan passes every plan rule;
- **restore round-trip** — demote then promote (empty remaining
  straggler set) reproduces the symmetrized original edge set, so a
  recovered rank returns to the exact pre-demotion gossip;
- **hysteresis** — drive the real :class:`~bluefog_tpu.resilience.
  detector.EdgeHealth` machine through adversarial flapping schedules on
  a fake clock and audit the transition log: no two non-DEAD transitions
  for one peer closer than the configured floor (so no demote/promote
  cycle can be shorter), only legal arcs, DEAD absorbing.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, Optional, Sequence, Tuple

import networkx as nx
import numpy as np

from bluefog_tpu.resilience.detector import (
    EDGE_ALIVE, EDGE_DEAD, EDGE_SUSPECT, EdgeHealth)
from bluefog_tpu.resilience.healing import (
    HealedTopology, demote_topology, heal_topology)

from bluefog_tpu.analysis import plan_rules
from bluefog_tpu.analysis.engine import Finding, Report, registry

__all__ = [
    "DEMOTED_SIZES",
    "straggler_sets",
    "check_straggler_member",
    "check_straggler_capped",
    "check_demoted",
    "check_hysteresis",
    "iter_demoted_corpus",
]

DEMOTED_SIZES: Tuple[int, ...] = tuple(range(4, 17))

_LEGAL_ARCS = {
    (EDGE_ALIVE, EDGE_SUSPECT),
    (EDGE_SUSPECT, EDGE_ALIVE),
    (EDGE_ALIVE, EDGE_DEAD),
    (EDGE_SUSPECT, EDGE_DEAD),
}


def straggler_sets(size: int) -> List[Tuple[int, ...]]:
    """The straggler sets exercised per (topology, size): single
    stragglers at both id extremes, an interior pair, and near-majority
    demotion (all but two — at least one healthy anchor must remain)."""
    out = [(0,), (size - 1,)]
    if size > 4:
        out.append((1, 2))
    if size > 5:
        out.append(tuple(range(1, size - 1)))  # 2 healthy members
    return out


def check_straggler_member(demoted: HealedTopology,
                           label: str = "demoted") -> List[Finding]:
    """Demotion is NOT death: every straggler must still be a member of
    the view (mapped, present in the topology, scheduled by the plan) —
    excising it would strand the mass pending in its slots."""
    out: List[Finding] = []
    strag = set(demoted.demoted)
    missing = strag - set(demoted.survivors)
    if missing:
        out.append(Finding(
            "adaptive.demoted-corpus", label,
            f"demoted rank(s) {sorted(missing)} dropped from the member "
            "set — demotion must keep the straggler in the view (its "
            "pending slot mass has nowhere to drain otherwise)"))
    mapped = set(demoted.to_global)
    if strag - mapped:
        out.append(Finding(
            "adaptive.demoted-corpus", label,
            f"demoted rank(s) {sorted(strag - mapped)} absent from "
            "to_global — the straggler has no local id to gossip under"))
    if demoted.dead:
        out.append(Finding(
            "adaptive.demoted-corpus", label,
            f"demotion declared rank(s) {sorted(demoted.dead)} dead — "
            "the whole point of the gray-failure path is that it never "
            "does"))
    tag = tuple(demoted.topology.graph.get("demoted_from", ()))
    if tag != tuple(sorted(strag)):
        out.append(Finding(
            "adaptive.demoted-corpus", label,
            f"topology demoted_from tag {tag} disagrees with the record "
            f"{tuple(sorted(strag))} — epoch observers would re-derive "
            "a different graph"))
    return out


def check_straggler_capped(demoted: HealedTopology,
                           label: str = "demoted") -> List[Finding]:
    """Each straggler's gossip degree is capped at ONE anchor edge
    (bidirectional), and the anchor is a healthy member wherever one is
    adjacent — the straggler must sit on nobody's critical path."""
    out: List[Finding] = []
    strag = set(demoted.demoted)
    to_local = demoted.to_local
    for s in sorted(strag):
        if s not in to_local:
            continue  # check_straggler_member already flagged it
        v = to_local[s]
        succ = {u for u in demoted.topology.successors(v) if u != v}
        pred = {u for u in demoted.topology.predecessors(v) if u != v}
        nbrs = succ | pred
        if len(nbrs) > 1:
            glb = sorted(demoted.to_global[u] for u in nbrs)
            out.append(Finding(
                "adaptive.demoted-corpus", label,
                f"straggler {s} keeps {len(nbrs)} neighbors {glb} — the "
                "demotion contract caps it to one anchor edge"))
        if succ != pred:
            out.append(Finding(
                "adaptive.demoted-corpus", label,
                f"straggler {s}'s anchor edge is one-directional "
                f"(out={sorted(succ)}, in={sorted(pred)}) — an "
                "asymmetric edge breaks the MH doubly-stochastic "
                "construction"))
        if len(nbrs) == 1:
            anchor = demoted.to_global[next(iter(nbrs))]
            if anchor in strag:
                healthy_adj = False  # anchored to a fellow straggler:
                # only legal when no healthy member was reachable, which
                # the construction never produces (it falls back to the
                # lowest healthy member) — flag unconditionally
                if not healthy_adj:
                    out.append(Finding(
                        "adaptive.demoted-corpus", label,
                        f"straggler {s} anchored to fellow straggler "
                        f"{anchor} — two demoted ranks gossiping only "
                        "with each other partition off the fleet"))
    return out


def check_demoted(demoted: HealedTopology, label: str = "demoted",
                  report: Optional[Report] = None) -> Report:
    """All adaptive + plan rules on one demoted topology: straggler
    retained and capped, W doubly stochastic, spectral gap positive,
    plan valid over the demoted edge set."""
    report = report if report is not None else Report()
    report.subjects_checked += 1
    report.extend(check_straggler_member(demoted, label))
    report.extend(check_straggler_capped(demoted, label))
    plan, topo = demoted.plan, demoted.topology
    report.extend(plan_rules.check_classes_are_permutations(plan, label))
    report.extend(plan_rules.check_edge_cover(plan, topo, label))
    report.extend(plan_rules.check_slot_consistency(plan, label))
    report.extend(plan_rules.check_mixing_stochastic(
        plan, label, expect_column=True))
    findings, gap = plan_rules.check_spectral_gap(plan, label)
    report.extend(findings)
    report.metric(f"adaptive.spectral_gap/{label}", round(gap, 6))
    return report


def iter_demoted_corpus(sizes: Sequence[int] = DEMOTED_SIZES
                        ) -> Iterable[Tuple[str, HealedTopology]]:
    for name, ctor in plan_rules.CORPUS_TOPOLOGIES.items():
        for n in sizes:
            topo = ctor(n)
            for strag in straggler_sets(n):
                label = f"{name}@{n}-slow{list(strag)}"
                yield label, demote_topology(topo, strag)


@registry.rule("adaptive.demoted-corpus", "adaptive",
               "every named topology x sizes 4..16 x straggler sets: "
               "the demoted W is doubly stochastic and mixing, the "
               "straggler stays a member with degree capped at one "
               "anchor edge, and the recompiled plan is valid")
def _run_demoted_corpus(report: Report) -> None:
    worst = {}
    for label, demoted in iter_demoted_corpus():
        report.subjects_checked += 1
        report.extend(check_straggler_member(demoted, label))
        report.extend(check_straggler_capped(demoted, label))
        plan, topo = demoted.plan, demoted.topology
        report.extend(plan_rules.check_classes_are_permutations(plan, label))
        report.extend(plan_rules.check_edge_cover(plan, topo, label))
        report.extend(plan_rules.check_slot_consistency(plan, label))
        report.extend(plan_rules.check_mixing_stochastic(
            plan, label, expect_column=True))
        findings, gap = plan_rules.check_spectral_gap(plan, label)
        report.extend(findings)
        fam = label.split("@")[0]
        worst[fam] = min(worst.get(fam, 1.0), gap)
    for fam, gap in sorted(worst.items()):
        report.metric(f"adaptive.min_demoted_spectral_gap/{fam}",
                      round(gap, 6))


@registry.rule("adaptive.restore-roundtrip", "adaptive",
               "demote then promote reproduces the symmetrized original "
               "edge set and mixing matrix — a recovered straggler "
               "returns to the exact pre-demotion gossip")
def _run_restore_roundtrip(report: Report) -> None:
    for name, ctor in plan_rules.CORPUS_TOPOLOGIES.items():
        for n in (4, 8, 12):
            topo = ctor(n)
            label = f"{name}@{n}-roundtrip"
            report.subjects_checked += 1
            # the restore path the runtime takes: promote with an empty
            # remaining straggler set == heal with an empty dead set,
            # applied to the SAME base graph the demotion captured
            restored = heal_topology(topo, [])
            baseline = heal_topology(topo, [])
            if (set(restored.topology.edges)
                    != set(baseline.topology.edges)):
                report.add(Finding(
                    "adaptive.restore-roundtrip", label,
                    "restore is not deterministic: two restores of the "
                    "same base graph disagree on the edge set"))
            demoted = demote_topology(topo, [n - 1])
            v = baseline.to_local[n - 1]
            base_deg = len({u for u in baseline.topology.successors(v)
                            if u != v})
            if base_deg > 1 and set(demoted.topology.edges) \
                    == set(baseline.topology.edges):
                report.add(Finding(
                    "adaptive.restore-roundtrip", label,
                    "demotion was a no-op: the demoted edge set equals "
                    "the baseline (the straggler's degree was never "
                    "capped)"))
            W_r = restored.plan.mixing_matrix()
            W_b = baseline.plan.mixing_matrix()
            if not np.allclose(W_r, W_b, atol=1e-12):
                report.add(Finding(
                    "adaptive.restore-roundtrip", label,
                    "restored mixing matrix differs from the "
                    "pre-demotion W — promotion must fully undo the "
                    "demotion, not approximate it"))


def check_hysteresis(transitions: Sequence[dict], floor_s: float,
                     label: str = "edge-health") -> List[Finding]:
    """Audit an EdgeHealth transition log ``[{t, peer, frm, to}, ...]``:

    - per peer, consecutive transitions not involving DEAD are at least
      ``floor_s`` apart — the hysteresis guarantee that bounds how fast
      a flapping rank can thrash demote/promote epochs.  Transitions
      tagged ``adopted`` (a fleet promote verdict mirrored into a
      machine that was starved of observations) are exempt as the
      SECOND of a pair: their floor was paid at the anchor whose
      evidence produced the verdict, and absolving restarts the local
      floor clock, so the NEXT local transition is still gated;
    - only legal arcs (ALIVE<->SUSPECT, anything->DEAD);
    - DEAD is absorbing: nothing transitions out of it.
    """
    out: List[Finding] = []
    by_peer: dict = {}
    for ev in transitions:
        by_peer.setdefault(ev["peer"], []).append(ev)
    for peer, evs in sorted(by_peer.items()):
        evs = sorted(evs, key=lambda e: float(e["t"]))
        prev = None
        for ev in evs:
            frm, to = ev["frm"], ev["to"]
            if (frm, to) not in _LEGAL_ARCS:
                out.append(Finding(
                    "adaptive.hysteresis", label,
                    f"peer {peer}: illegal transition {frm} -> {to} at "
                    f"t={ev['t']:g}"
                    + (" (DEAD must be absorbing)"
                       if frm == EDGE_DEAD else "")))
            if (prev is not None and to != EDGE_DEAD
                    and prev["to"] != EDGE_DEAD
                    and not ev.get("adopted")):
                gap = float(ev["t"]) - float(prev["t"])
                if gap < floor_s - 1e-12:
                    out.append(Finding(
                        "adaptive.hysteresis", label,
                        f"peer {peer}: transitions {gap:g}s apart "
                        f"({prev['frm']}->{prev['to']} then {frm}->{to})"
                        f" — under the {floor_s:g}s hysteresis floor, a "
                        "flapping rank could thrash membership epochs"))
            prev = ev
    return out


def _drive_flapping(misses: int, clean: int, floor_s: float,
                    tick_s: float, rounds: int) -> EdgeHealth:
    """Adversarial schedule: alternate bursts of misses and cleans as
    fast as the observation cadence allows, for several peers at
    staggered phases — the workload most likely to violate the floor."""
    now = [0.0]
    eh = EdgeHealth(misses=misses, clean=clean, floor_s=floor_s,
                    clock=lambda: now[0])
    for step in range(rounds):
        for peer in (1, 2, 3):
            phase = (step + peer) % (2 * misses)
            if phase < misses:
                eh.note_miss(peer)
            else:
                eh.note_clean(peer)
        now[0] += tick_s
    eh.note_dead(3)
    # post-death observations must not resurrect peer 3
    for _ in range(clean + 1):
        eh.note_clean(3)
        now[0] += tick_s
    return eh


@registry.rule("adaptive.hysteresis", "adaptive",
               "the EdgeHealth machine, driven through adversarial "
               "flapping schedules on a fake clock, admits no "
               "demote/promote cycle shorter than the configured floor, "
               "takes only legal arcs, and keeps DEAD absorbing")
def _run_hysteresis(report: Report) -> None:
    for misses, clean, floor_s, tick_s in (
            (3, 5, 1.0, 0.05),   # defaults, fast flapping
            (1, 1, 0.5, 0.01),   # hair-trigger thresholds
            (2, 3, 2.0, 0.3),    # slow cadence, long floor
    ):
        label = (f"flap[m={misses},c={clean},floor={floor_s:g},"
                 f"tick={tick_s:g}]")
        report.subjects_checked += 1
        eh = _drive_flapping(misses, clean, floor_s, tick_s, rounds=400)
        log = eh.transitions()
        report.extend(check_hysteresis(log, floor_s, label))
        if not any(e["to"] == EDGE_SUSPECT for e in log):
            report.add(Finding(
                "adaptive.hysteresis", label,
                "the adversarial schedule never tripped ALIVE->SUSPECT "
                "— the machine under test is not reacting to misses, "
                "so the floor was never actually exercised"))
        if eh.state(3) != EDGE_DEAD:
            report.add(Finding(
                "adaptive.hysteresis", label,
                f"peer 3 is {eh.state(3)!r} after a death declaration "
                "followed by clean observations — DEAD must absorb"))
