"""Rule family 4: seeded-bug fixture corpus.

Every rule family must be shown to FIRE, not just to pass — a verifier
that has never caught anything proves nothing.  Each fixture here is a
deliberately-broken artifact (a mutated plan, a tampered mixing weight,
an HLO program with an injected all-gather, a protocol variant with a
dropped fence, an ill-ordered window trace) paired with the rule set
that must flag it.  ``run_fixture`` returns the findings; the CLI's
``--fixture``/``--self-test`` modes and tests/test_analysis.py both
demand a non-empty result for every name in :data:`FIXTURES`.

Fixtures are built by *mutating real seed artifacts* (``compile_plan``
output, the corpus topologies) rather than hand-writing broken objects,
so a representation change that silently disarms a rule breaks the
fixture too.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List

from bluefog_tpu import topology_util as tu
from bluefog_tpu.core.plan import compile_plan, plan_from_neighbor_lists

from bluefog_tpu.resilience.healing import demote_topology, heal_topology

from bluefog_tpu.analysis import (
    adaptive_rules,
    epoch_rules,
    hlo_rules,
    introspect_rules,
    plan_rules,
    progress_rules,
    resilience_rules,
    seqlock_model,
    telemetry_rules,
    trace_rules,
    wire_rules,
)
from bluefog_tpu.analysis.engine import Finding

__all__ = ["FIXTURES", "run_fixture"]


def _seed_plan(size: int = 8):
    topo = tu.ExponentialTwoGraph(size)
    return topo, compile_plan(topo)


# ---------------------------------------------------------------------------
# plan fixtures: mutate a freshly compiled exp2@8 plan
# ---------------------------------------------------------------------------


def _plan_duplicate_destination() -> List[Finding]:
    """Two class edges aimed at the same destination rank — not a
    permutation, so one ppermute cannot realize the class."""
    topo, plan = _seed_plan()
    cls = plan.classes[0]
    (s0, d0), (s1, d1) = cls.perm[0], cls.perm[1]
    bad = dataclasses.replace(cls, perm=((s0, d0), (s1, d0)) + cls.perm[2:])
    mutated = dataclasses.replace(plan, classes=(bad,) + plan.classes[1:])
    return plan_rules.check_classes_are_permutations(mutated, "exp2@8[dup-dst]")


def _plan_dropped_edge() -> List[Finding]:
    """One scheduled edge removed: that neighbor never transfers and the
    class cover no longer matches the topology."""
    topo, plan = _seed_plan()
    cls = plan.classes[0]
    bad = dataclasses.replace(cls, perm=cls.perm[1:])
    mutated = dataclasses.replace(plan, classes=(bad,) + plan.classes[1:])
    return plan_rules.check_edge_cover(mutated, topo, "exp2@8[dropped-edge]")


def _plan_tampered_weights() -> List[Finding]:
    """One receive weight doubled: W rows stop summing to 1."""
    topo, plan = _seed_plan()
    cls = plan.classes[0]
    rw = list(cls.recv_weights)
    idx = next(i for i, w in enumerate(rw) if w != 0.0)
    rw[idx] *= 2.0
    bad = dataclasses.replace(cls, recv_weights=tuple(rw))
    mutated = dataclasses.replace(plan, classes=(bad,) + plan.classes[1:])
    return plan_rules.check_mixing_stochastic(mutated, "exp2@8[tampered-w]")


def _plan_inconsistent_slots() -> List[Finding]:
    """slot_index pointed at the wrong in-neighbor position: allgather
    output placement would scramble."""
    topo, plan = _seed_plan()
    cls = plan.classes[0]
    si = list(cls.slot_index)
    recv = next(r for r in range(plan.size) if cls.recv_mask[r])
    si[recv] = (si[recv] + 1) % max(plan.in_degrees[recv], 1) \
        if plan.in_degrees[recv] > 1 else -1
    bad = dataclasses.replace(cls, slot_index=tuple(si))
    mutated = dataclasses.replace(plan, classes=(bad,) + plan.classes[1:])
    return plan_rules.check_slot_consistency(mutated, "exp2@8[bad-slot]")


def _plan_disconnected() -> List[Finding]:
    """Two disjoint 4-cliques spelled as one 8-rank plan: W is block
    diagonal, the second eigenvalue is 1, the spectral gap is zero."""
    src_ranks = [[s for s in range((r // 4) * 4, (r // 4) * 4 + 4) if s != r]
                 for r in range(8)]
    plan = plan_from_neighbor_lists(8, src_ranks)
    findings, _gap = plan_rules.check_spectral_gap(plan, "two-cliques@8")
    return findings


# ---------------------------------------------------------------------------
# HLO fixtures: real compiled text with an injected violation
# ---------------------------------------------------------------------------

# A post-partitioner-shaped module for a gossip step whose contract is
# "collective-permute only".  The all-gather on the second line is the
# injected bug: it re-materializes the full 8-way axis (and at f32
# [8,4096,4096] it is also a 512 MB replicated buffer).
_INJECTED_ALL_GATHER_HLO = """\
HloModule jit_gossip_step, is_scheduled=true

ENTRY %main.42 (param.0: f32[4096,4096]) -> f32[4096,4096] {
  %param.0 = f32[4096,4096]{1,0} parameter(0)
  %all-gather.1 = f32[8,4096,4096]{2,1,0} all-gather(%param.0), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %slice.2 = f32[1,4096,4096]{2,1,0} slice(%all-gather.1), slice={[0:1], [0:4096], [0:4096]}
  %reshape.3 = f32[4096,4096]{1,0} reshape(%slice.2)
  %collective-permute.4 = f32[4096,4096]{1,0} collective-permute(%reshape.3), source_target_pairs={{0,1},{1,2},{2,3},{3,4},{4,5},{5,6},{6,7},{7,0}}
  ROOT %add.5 = f32[4096,4096]{1,0} add(%reshape.3, %collective-permute.4)
}
"""


def _hlo_injected_all_gather() -> List[Finding]:
    rules = [
        hlo_rules.CollectiveBudget({"collective-permute": 1},
                                   subject="gossip_step[injected-ag]"),
        hlo_rules.NoFullAxisAllGather(axis_size=8,
                                      subject="gossip_step[injected-ag]"),
    ]
    return hlo_rules.check_program(_INJECTED_ALL_GATHER_HLO, rules)


def _hlo_replicated_large_buffer() -> List[Finding]:
    rules = [hlo_rules.NoReplicatedLargeBuffer(
        max_bytes=64 * 2 ** 20, subject="gossip_step[512MB-gather]")]
    return hlo_rules.check_program(_INJECTED_ALL_GATHER_HLO, rules)


# ---------------------------------------------------------------------------
# resilience fixtures: botched healings + broken drain protocols
# ---------------------------------------------------------------------------


def _healed_dead_not_excised() -> List[Finding]:
    """A healing that declares rank 2 dead but forgot to excise it: the
    survivor set (and hence the plan) still schedules the corpse."""
    healed = heal_topology(tu.ExponentialTwoGraph(8), dead=[3])
    lied = dataclasses.replace(healed, dead=(2,))
    return resilience_rules.check_dead_excised(lied, "exp2@8[corpse-kept]")


def _healed_not_doubly_stochastic() -> List[Finding]:
    """A healed plan whose Metropolis–Hastings re-weighting was skipped
    for one edge (weight doubled): the survivor W stops being
    stochastic, so degraded gossip drifts off the survivor average."""
    healed = heal_topology(tu.ExponentialTwoGraph(8), dead=[3])
    cls = healed.plan.classes[0]
    rw = list(cls.recv_weights)
    idx = next(i for i, w in enumerate(rw) if w != 0.0)
    rw[idx] *= 2.0
    bad = dataclasses.replace(cls, recv_weights=tuple(rw))
    mutated = dataclasses.replace(healed.plan,
                                  classes=(bad,) + healed.plan.classes[1:])
    return plan_rules.check_mixing_stochastic(
        mutated, "exp2@8-dead[3][skipped-mh]", expect_column=True)


def _grown_reuses_dead_rank() -> List[Finding]:
    """A grown membership view that handed a joiner the CORPSE's global
    rank: stale deposits addressed to the dead rank would be consumed by
    the new member — double-counted mass."""
    from bluefog_tpu.resilience.healing import grow_topology
    import networkx as nx

    healed = heal_topology(tu.ExponentialTwoGraph(8), dead=[3])
    G = nx.relabel_nodes(healed.topology,
                         dict(enumerate(healed.to_global)), copy=True)
    grown = grow_topology(G, [8])
    # lie: the view claims rank 8 (a mapped member) is ALSO dead — the
    # reissued-corpse signature check_grown exists to catch
    lied = dataclasses.replace(grown, dead=(8,))
    return resilience_rules.check_grown(
        lied, "exp2@8[joiner-reuses-corpse]").findings


def _grown_not_doubly_stochastic() -> List[Finding]:
    """A grown plan whose Metropolis–Hastings re-weighting skipped one
    spliced-in edge (weight doubled): the grown W stops being doubly
    stochastic, so post-admission gossip drifts off the consensus the
    joiner was onboarded at."""
    from bluefog_tpu.resilience.healing import grow_topology

    grown = grow_topology(tu.ExponentialTwoGraph(8), [8, 9])
    cls = grown.plan.classes[0]
    rw = list(cls.recv_weights)
    idx = next(i for i, w in enumerate(rw) if w != 0.0)
    rw[idx] *= 2.0
    bad = dataclasses.replace(cls, recv_weights=tuple(rw))
    mutated = dataclasses.replace(grown.plan,
                                  classes=(bad,) + grown.plan.classes[1:])
    return plan_rules.check_mixing_stochastic(
        mutated, "exp2@8+join[8,9][skipped-mh]", expect_column=True)


def _epoch_switch_unbalanced_ledger() -> List[Finding]:
    """An epoch_switch journal where one member's switch-point counters
    lost a deposit (retired neither collected, drained, nor pending):
    mass crossed the membership barrier unaccounted."""
    events = resilience_rules._synthetic_epoch_journal()
    ev = next(e for e in events if e["new_epoch"] == 1
              and e["old_epoch"] is not None)
    ev["pending"] -= 2  # two deposits vanish at the cut
    return resilience_rules.check_membership_epochs(
        events, "fixture[unbalanced-switch]")


# ---------------------------------------------------------------------------
# adaptive fixtures: botched demotions + a flapping schedule under the floor
# ---------------------------------------------------------------------------


def _demoted_straggler_excised() -> List[Finding]:
    """A demotion that dropped the straggler from the member set — the
    death-by-another-name bug: its pending slot mass has nowhere to
    drain and every neighbor averages in a vanished rank."""
    demoted = demote_topology(tu.ExponentialTwoGraph(8), [3])
    lied = dataclasses.replace(
        demoted, survivors=tuple(r for r in demoted.survivors if r != 3))
    return adaptive_rules.check_straggler_member(
        lied, "exp2@8-slow[3][straggler-excised]")


def _demoted_degree_cap_violated() -> List[Finding]:
    """A demotion that forgot to cut one of the straggler's edges: the
    straggler keeps two neighbors, so it still sits on a second rank's
    critical path and the convoy persists."""
    demoted = demote_topology(tu.ExponentialTwoGraph(8), [3])
    H = demoted.topology.copy()
    v = demoted.to_local[3]
    extra = next(u for u in H.nodes
                 if u != v and not H.has_edge(v, u))
    H.add_edge(v, extra)
    H.add_edge(extra, v)
    lied = dataclasses.replace(demoted, topology=H)
    return adaptive_rules.check_straggler_capped(
        lied, "exp2@8-slow[3][degree-2]")


def _demoted_not_doubly_stochastic() -> List[Finding]:
    """A demoted plan whose Metropolis–Hastings re-weighting was skipped
    for one edge (weight doubled): the adaptively produced W stops being
    doubly stochastic, so gossip under it drifts off the average."""
    demoted = demote_topology(tu.ExponentialTwoGraph(8), [3])
    cls = demoted.plan.classes[0]
    rw = list(cls.recv_weights)
    idx = next(i for i, w in enumerate(rw) if w != 0.0)
    rw[idx] *= 2.0
    bad = dataclasses.replace(cls, recv_weights=tuple(rw))
    mutated = dataclasses.replace(
        demoted.plan, classes=(bad,) + demoted.plan.classes[1:])
    return plan_rules.check_mixing_stochastic(
        mutated, "exp2@8-slow[3][skipped-mh]", expect_column=True)


def _adaptive_flap_below_floor() -> List[Finding]:
    """A transition log where one peer demotes and promotes 0.2 s apart
    under a 1 s hysteresis floor — the epoch-thrash signature the floor
    exists to forbid."""
    log = [
        {"t": 0.0, "peer": 3, "frm": "alive", "to": "suspect"},
        {"t": 0.2, "peer": 3, "frm": "suspect", "to": "alive"},
        {"t": 1.5, "peer": 3, "frm": "alive", "to": "suspect"},
    ]
    return adaptive_rules.check_hysteresis(
        log, floor_s=1.0, label="fixture[flap-0.2s]")


# ---------------------------------------------------------------------------
# protocol fixtures: broken seqlock/collect/barrier variants + bad traces
# ---------------------------------------------------------------------------


def _model_fixture(model) -> List[Finding]:
    return seqlock_model.check_model(model).findings


# ---------------------------------------------------------------------------
# telemetry fixtures: mutate real in-memory Registry snapshots
# ---------------------------------------------------------------------------


def _telemetry_counter_regression() -> List[Finding]:
    """A snapshot sequence where a counter value goes BACKWARD (the bug a
    raced read-modify-write or an accidental reset would produce)."""
    from bluefog_tpu.telemetry.registry import Registry as TReg

    reg = TReg(out_dir=None, rank=0, job="fixture")
    reg.counter("tcp.round_trips").add(10)
    first = reg.snapshot()
    second = reg.snapshot()
    for c in second["counters"]:
        if c["name"] == "tcp.round_trips":
            c["value"] = 3.0  # regressed
    return telemetry_rules.check_counters_monotone(
        [first, second], label="fixture[regressed-counter]")


def _telemetry_snapshot_bad_schema() -> List[Finding]:
    """A real snapshot with its schema tag clobbered and a histogram
    counts array truncated (missing the overflow bucket)."""
    from bluefog_tpu.telemetry.registry import Registry as TReg

    reg = TReg(out_dir=None, rank=0, job="fixture")
    reg.histogram("win.op_s", op="win_put").observe(1e-4)
    snap = reg.snapshot()
    snap["schema"] = "bftpu-telemetry-snapshot/999"
    snap["histograms"][0]["counts"] = snap["histograms"][0]["counts"][:-1]
    return telemetry_rules.check_snapshot_schema(
        snap, label="fixture[bad-schema]")


def _telemetry_conservation_broken() -> List[Finding]:
    """A 2-rank corpus where one deposit was never retired into any sink
    — the lost-mass signature the ledger identity exists to catch."""
    from bluefog_tpu.telemetry.registry import (
        LEDGER_COLLECTED, LEDGER_DEPOSITS, Registry as TReg)

    snaps = []
    for r in range(2):
        reg = TReg(out_dir=None, rank=r, job="fixture")
        reg.counter(LEDGER_DEPOSITS).add(4)
        reg.counter(LEDGER_COLLECTED).add(3 if r else 4)  # rank 1 lost one
        snaps.append(reg.snapshot())
    return telemetry_rules.check_conservation(
        snaps, label="fixture[lost-deposit]")


def _envlint_undocumented_var() -> List[Finding]:
    """A referenced env knob that appears in no doc — the lint must name
    the var and the files using it."""
    # name assembled at runtime so the env lint's source scan (which
    # reads THIS file) never sees the seeded knob as a real reference
    var = "BFTPU_" + "SEEDED_UNDOCUMENTED_KNOB"
    return telemetry_rules.check_env_documented(
        {var: ["bluefog_tpu/fake.py"]},
        documented=set(), label="fixture[undocumented-var]")


def _trace_fixture_corpus() -> List[dict]:
    """The trace family's healthy synthetic corpus, re-used by mutation
    (same rationale as the plan fixtures: break the REAL shape so a
    schema change that disarms a rule breaks the fixture too)."""
    return trace_rules._synthetic_traces()


def _trace_unbalanced_nesting() -> List[Finding]:
    """A buffer where one span's end crossed another's — the signature
    of a dropped/reused begin token (two timing contexts raced)."""
    t = _trace_fixture_corpus()[0]
    # stretch the first win_put so it ends INSIDE the following
    # win_update: partial overlap, neither nested nor disjoint
    put = next(s for s in t["spans"] if s["name"] == "win_put")
    upd = next(s for s in t["spans"] if s["name"] == "win_update")
    put["t1"] = (upd["t0"] + upd["t1"]) // 2
    return trace_rules.check_span_nesting(
        t, label="fixture[crossed-spans]")


def _trace_dangling_flow() -> List[Finding]:
    """A consume whose flow identity no present buffer ever emitted —
    the corrupted-context-word signature (origin rank IS in the corpus,
    so this must be an error, not a missing-buffer warning)."""
    corpus = _trace_fixture_corpus()
    for s in corpus[1]["spans"]:
        for c in s.get("consume", ()):
            c["op_id"] += 1000  # no such emit anywhere
    return [f for f in trace_rules.check_flow_endpoints(
        corpus, label="fixture[dangling-flow]")
        if f.severity == "error"]


def _trace_clock_skew() -> List[Finding]:
    """A buffer whose applied clock offset is far outside what its own
    estimator state allows: flows complete before their producers by
    much more than the combined error bound."""
    corpus = _trace_fixture_corpus()
    # claim a huge NEGATIVE offset with a tiny rtt: rank 1's spans slide
    # 5 ms earlier while the error bound stays at rtt/2 = 4 µs
    corpus[1]["clock"] = {"offset_s": -5e-3, "err_s": 4e-6,
                          "best_rtt_s": 8e-6, "samples": 3}
    return trace_rules.check_clock_offsets(
        corpus, label="fixture[clock-skew]")


# ---------------------------------------------------------------------------
# introspect fixtures: a real status page / holder board / blame feed,
# each broken the way its failure mode would break it
# ---------------------------------------------------------------------------


def _introspect_torn_page() -> List[Finding]:
    """A REAL published status page decoded, then presented the way a
    reader racing a stuck writer would see it: odd seq, clobbered
    version, and a balance that stopped matching its own totals."""
    import tempfile

    from bluefog_tpu.introspect import statuspage as sp
    from bluefog_tpu.native import shm_native

    with tempfile.TemporaryDirectory(prefix="bftpu_fixture_") as td:
        saved = shm_native._FALLBACK_DIR
        shm_native._FALLBACK_DIR = td
        try:
            page = sp.StatusPage("fixture", 0)
            try:
                page.publish(nranks=2, step=7, epoch=0, op_id=7,
                             last_op="win_update:g",
                             ledger={"deposits": 4.0, "collected": 3.0,
                                     "drained": 1.0, "pending": 0.0},
                             edges=[(1, 0, 0.2)])
                decoded = sp.read_status_page(sp.status_page_path(
                    "fixture", 0))
            finally:
                page.close(unlink=True)
        finally:
            shm_native._FALLBACK_DIR = saved
    decoded["seq"] = 7                  # accepted mid-write
    decoded["version"] = 99             # foreign layout
    decoded["ledger"]["balance"] = 3.5  # 4 - 3 - 1 == 0, not 3.5
    return introspect_rules.check_status_page(decoded, "fixture[torn-page]")


def _introspect_ghost_holder() -> List[Finding]:
    """A real holder board where the holding rank died and the heal path
    never ran mutex_break: the word keeps blaming a ghost."""
    import tempfile

    from bluefog_tpu.native import shm_native
    from bluefog_tpu.native.shm_native import HolderBoard

    with tempfile.TemporaryDirectory(prefix="bftpu_fixture_") as td:
        saved = shm_native._FALLBACK_DIR
        shm_native._FALLBACK_DIR = td
        try:
            board = HolderBoard("fixture-hb", 4)
            try:
                board.set_holder(1, 3)  # rank 3 acquires, then dies
                snap = board.snapshot()
            finally:
                board.close(unlink=True)
        finally:
            shm_native._FALLBACK_DIR = saved
    return introspect_rules.check_holder_words(
        snap, members={0, 1, 2}, dead={3}, label="fixture[ghost-holder]")


def _introspect_blame_regression() -> List[Finding]:
    """A real AdaptivePolicy blame feed reset mid-run (the bug a raced
    re-init or an epoch switch dropping the dict would produce): the
    snapshot sequence goes backward."""
    from bluefog_tpu.resilience.adaptive import AdaptivePolicy

    pol = AdaptivePolicy()
    pol.note_round_blame(3)
    pol.note_round_blame(3)
    first = dict(pol._cp_blame)
    pol._cp_blame.clear()  # seeded bug: feed reset mid-run
    pol.note_round_blame(3)
    second = dict(pol._cp_blame)
    return introspect_rules.check_blame_monotone(
        [first, second], "fixture[blame-regression]")


# ---------------------------------------------------------------------------
# progress fixtures: a broken engine variant + seeded bad traces
# ---------------------------------------------------------------------------


def _progress_queue_drops_on_quiesce() -> List[Finding]:
    """A quiesce that clears the queue instead of parking it (the
    classic shutdown/epoch-switch confusion): the parked op's handle
    never resolves after resume + drain, and the state-machine check
    on the REAL engine class must notice the loss."""
    from bluefog_tpu.progress import ProgressEngine

    class Droppy(ProgressEngine):
        def quiesce(self, timeout: float = 60.0) -> int:
            with self._cv:
                self._q.clear()
            return super().quiesce(timeout)

    return progress_rules.check_schedule(
        [("put", "w"), "quiesce", "resume", "step"],
        subject="fixture[queue-drops-on-quiesce]", engine_cls=Droppy)


def _progress_handle_double_complete() -> List[Finding]:
    """A worker that resolves the same handle on the requeue path AND
    the success path — the exactly-once lifecycle lint must flag the
    second resolution."""
    return progress_rules.check_handle_events(
        [("h0", "create"), ("h0", "complete"), ("h0", "complete"),
         ("h0", "result")],
        subject="fixture[handle-double-complete]")


def _progress_fusion_reorders() -> List[Finding]:
    """A fuser that coalesced two same-window puts ACROSS an interleaved
    other-window put: the combined deposit stream no longer replays in
    submission order."""
    subs = [(0, "put", "a", None, 8), (1, "put", "b", None, 8),
            (2, "put", "a", None, 8)]
    batches = [("put", "a", (0, 2)), ("put", "b", (1,))]
    return progress_rules.check_batches(
        subs, batches, budget=1 << 20,
        subject="fixture[fusion-reorders]")


def _sim_mass_leak() -> List[Finding]:
    """A full campaign with a seeded 1e-3 multiplicative leak in the
    combine path: the continuous mass audit must flag it (and nothing
    else can — the leak never touches the count ledger)."""
    from bluefog_tpu.analysis import sim_rules
    from bluefog_tpu.sim.campaign import SimConfig, run_campaign

    cfg = SimConfig(ranks=16, rounds=20, seed=3, quiesce_rounds=10,
                    debug_bugs=("mass_leak",))
    res = run_campaign(cfg)
    return sim_rules.campaign_findings(res, "fixture[sim-mass-leak]")


def _sim_cap_bypass() -> List[Finding]:
    """A campaign whose adaptive step ignores the minority-demotion
    cap, on a hand-written schedule slowing 5 of 8 ranks: with the cap
    bypassed the fleet demotes a majority, which the standing
    invariant must flag (the same schedule without the seeded bug runs
    clean — the cap is what protects it)."""
    from bluefog_tpu.analysis import sim_rules
    from bluefog_tpu.sim.campaign import SimConfig, run_campaign
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    cfg = SimConfig(ranks=8, rounds=40, seed=5, quiesce_rounds=20,
                    faults=("slow",), debug_bugs=("cap_bypass",))
    sched = FaultSchedule(
        [Fault(kind="slow", step=3 + i, rank=i, duration_s=1.0, stop=35)
         for i in range(5)], seed=5)
    res = run_campaign(cfg, sched)
    return sim_rules.campaign_findings(res, "fixture[sim-cap-bypass]")


def _sim_split_brain() -> List[Finding]:
    """A partition campaign with the quorum fence seeded out
    (``split_brain``): both sides of the cut heal the other out and
    commit under diverged membership, which the single-lineage
    standing invariant must flag (the identical campaign WITH the
    fence runs clean — partition_rules pins that side)."""
    from bluefog_tpu.analysis import partition_rules, sim_rules

    _cfg, _sched, res = partition_rules.partition_campaign(
        16, 30, 3, (6, 11), debug_bugs=("split_brain",))
    return sim_rules.campaign_findings(res, "fixture[sim-split-brain]")


def _serve_version_reset() -> List[Finding]:
    """A serve campaign whose publisher handoff forgets the region
    header's persisted version word and restarts at 1
    (``serve_version_reset``): the serve-monotone standing invariant
    must flag it at the publisher."""
    from bluefog_tpu.analysis import serve_rules, sim_rules

    _cfg, _sched, res = serve_rules.serve_campaign(
        16, 24, 3, debug_bugs=("serve_version_reset",))
    return sim_rules.campaign_findings(res,
                                       "fixture[serve-version-reset]")


def _serve_torn_swap() -> List[Finding]:
    """A serve campaign whose replica swap mixes old and new buffer
    bytes instead of flipping one whole generation (``serve_torn``):
    the serve-committed standing invariant must flag bytes that match
    no committed snapshot."""
    from bluefog_tpu.analysis import serve_rules, sim_rules

    _cfg, _sched, res = serve_rules.serve_campaign(
        16, 24, 3, debug_bugs=("serve_torn",))
    return sim_rules.campaign_findings(res, "fixture[serve-torn-swap]")


def _serve_torn_read_model() -> List[Finding]:
    """The double-buffer interleaving model with both seqlocks dropped:
    a reader racing the buffer-reuse publish completes with a torn mix
    of two generations, which the model must surface."""
    from bluefog_tpu.analysis import serve_rules

    res = serve_rules.torn_read_model(buffer_seqlock=False,
                                      header_seqlock=False)
    return [Finding("serve.torn-read-model",
                    "fixture[serve-no-seqlock]", msg)
            for msg in res["findings"]]


# ---------------------------------------------------------------------------
# distrib fixtures: seeded distribution-plane bugs the standing
# invariants (and the delta-completeness audit) must catch
# ---------------------------------------------------------------------------


def _distrib_degree_overflow() -> List[Finding]:
    """A distribution campaign whose tree repair ignores the fan-out
    cap (``distrib_degree_overflow``): a relay death dumps every
    orphan onto the shallowest relay, and the tree-validity standing
    invariant must flag the overloaded node."""
    from bluefog_tpu.analysis import distrib_rules, sim_rules
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    _cfg, _sched, res = distrib_rules.distrib_campaign(
        16, 24, 3, serve_replicas=13, distrib_fanout=3, distrib_slo=0,
        schedule=FaultSchedule([Fault(kind="serve_kill", step=2,
                                      rank=1)]),
        debug_bugs=("distrib_degree_overflow",))
    return sim_rules.campaign_findings(
        res, "fixture[distrib-degree-overflow]")


def _distrib_stalled_subtree() -> List[Finding]:
    """A distribution campaign where a dead relay's children never
    re-parent (``distrib_stall``): the orphaned subtree stops adopting
    versions while the publisher keeps committing, and the
    staleness-SLO standing invariant must flag the growing lag."""
    from bluefog_tpu.analysis import distrib_rules, sim_rules
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    _cfg, _sched, res = distrib_rules.distrib_campaign(
        16, 40, 3, distrib_slo=4,
        schedule=FaultSchedule([Fault(kind="serve_kill", step=2,
                                      rank=0)]),
        debug_bugs=("distrib_stall",))
    return sim_rules.campaign_findings(
        res, "fixture[distrib-stalled-subtree]")


def _distrib_version_regress() -> List[Finding]:
    """A distribution campaign whose publisher handoff restarts the
    version word at 1 (``serve_version_reset`` with the tree armed):
    the serve-monotone standing invariant must flag the regression
    before it propagates down the tree."""
    from bluefog_tpu.analysis import distrib_rules, sim_rules

    _cfg, _sched, res = distrib_rules.distrib_campaign(
        16, 24, 3, debug_bugs=("serve_version_reset",))
    return sim_rules.campaign_findings(
        res, "fixture[distrib-version-regress]")


def _distrib_stale_delta() -> List[Finding]:
    """A feed that silently drops a dirty chunk from its delta: the
    delta-completeness audit (CRC gate bypassed, so the audit itself
    must notice) flags bytes that no longer compose to the full
    canonical snapshot."""
    from bluefog_tpu.analysis import distrib_rules

    return distrib_rules.stale_delta_findings()


# ---------------------------------------------------------------------------
# lab fixtures: mutate the REAL frozen sweep artifact (same rationale as
# the plan fixtures — a schema change that disarms a rule breaks these)
# ---------------------------------------------------------------------------


def _lab_artifact() -> dict:
    import copy

    from bluefog_tpu.lab.recommend import load_artifact

    return copy.deepcopy(load_artifact())


def _lab_corrupted_fit() -> List[Finding]:
    """A scaling law whose exponent was clobbered to claim contraction
    rates GROWING with fleet size — physically impossible for every
    corpus topology (gaps are non-increasing in n) and no longer the
    law the measured cells refit to."""
    from bluefog_tpu.analysis import lab_rules

    art = _lab_artifact()
    topo = sorted(art["fits"])[0]
    art["fits"][topo]["b"] = 0.5  # rates grow ~ n^0.5: impossible
    return lab_rules.check_fit_monotonicity(
        art, f"LAB[{topo}-growing-law]")


def _lab_tampered_rate() -> List[Finding]:
    """A cell's headline rate hand-edited away from what its own stored
    series refits to — the tampered-number signature the raw-data-in-
    artifact design exists to catch."""
    from bluefog_tpu.analysis import lab_rules

    art = _lab_artifact()
    cell = art["cells"][0]
    cell["rate"] = min(1.0, float(cell["rate"]) * 0.5 + 0.25)
    return lab_rules.check_cell_refit(art, "LAB[tampered-rate]")


def _lab_recommendation_contradicts_corpus() -> List[Finding]:
    """A stored recommendation swapped to a topology the measured
    corpus does not pick — recomputing ``lab.recommend`` over the same
    artifact must contradict it (the determinism contract behind
    BFTPU_LAB_AUTO_TOPOLOGY)."""
    from bluefog_tpu.analysis import lab_rules
    from bluefog_tpu.lab.recommend import TOPOLOGIES

    art = _lab_artifact()
    key = sorted(art["recommended"])[0]
    stored = art["recommended"][key]
    stored["topology"] = next(t for t in sorted(TOPOLOGIES)
                              if t != stored["topology"])
    return lab_rules.check_recommendation_consistency(
        art, "LAB[swapped-recommendation]")


def _conformance_out_of_order_commit() -> List[Finding]:
    """A transport that buffers deposits and commits them LIFO: the
    ascending-commit contract breaks and the differential harness must
    shrink the divergence to its minimal repro."""
    from bluefog_tpu.analysis import conformance

    return conformance.mutant_out_of_order_findings()


def _conformance_capability_overclaim() -> List[Finding]:
    """A transport whose CAPS record claims a fused scale (and a future
    device-resident tier) its ``write`` cannot deliver: the capability
    honesty lint must refuse the declaration."""
    from bluefog_tpu.analysis import conformance

    return conformance.mutant_overclaim_findings()


def _conformance_drain_loses_mass() -> List[Finding]:
    """A force-drain that wipes committed mass without crediting any
    ledger bin: the reference mass identity must break."""
    from bluefog_tpu.analysis import conformance

    return conformance.mutant_lossy_drain_findings()


def _conformance_epoch_reseed_skipped() -> List[Finding]:
    """An epoch switch that retires the ledger but carries the old
    epoch's slot state into the new one: the differential against the
    reference re-seed must diverge on the first version observation."""
    from bluefog_tpu.analysis import conformance

    return conformance.mutant_reseed_findings()


def _slo_silent_violation() -> List[Finding]:
    """A traffic campaign whose replica drain only runs every third
    poll (``slo_silent_violation``): requests queue past the latency
    SLO with no fault window to blame, and the request-SLO standing
    invariant must flag the silent stall."""
    from bluefog_tpu.analysis import sim_rules, slo_rules

    _cfg, _sched, res = slo_rules.slo_campaign(
        16, 24, 3, debug_bugs=("slo_silent_violation",))
    return sim_rules.campaign_findings(
        res, "fixture[slo-silent-violation]")


def _omission_biased_loadgen() -> List[Finding]:
    """A traffic campaign whose drain re-anchors each request's send
    time to the drain instant (``loadgen_omission``): queueing delay
    vanishes from the measurement — coordinated omission — and the
    open-loop standing invariant must flag it."""
    from bluefog_tpu.analysis import sim_rules, slo_rules

    _cfg, _sched, res = slo_rules.slo_campaign(
        16, 24, 3, debug_bugs=("loadgen_omission",))
    return sim_rules.campaign_findings(
        res, "fixture[omission-biased-loadgen]")


def _monitor_silent_alert() -> List[Finding]:
    """A monitored mass-leak campaign whose monitor scrapes but never
    feeds its alert engine (``mon_silent``): the leak runs to quiesce
    with no alert fired, and the alert-completeness audit must flag
    the silence."""
    from bluefog_tpu.analysis import monitor_rules

    _cfg, _sched, res = monitor_rules.monitored_campaign(
        16, 20, 3, debug_bugs=("mass_leak", "mon_silent"))
    return monitor_rules.monitor_findings(
        res, "fixture[monitor-silent-alert]",
        expect=("mass_imbalance",))


def _monitor_flapping_alert() -> List[Finding]:
    """A monitored mass-leak campaign whose engine gap-close is set to
    a hundredth of the sample cadence (``mon_flap``): one sustained
    breach opens a fresh window at every sample, and the
    window-coalescing audit must flag the flapping."""
    from bluefog_tpu.analysis import monitor_rules

    _cfg, _sched, res = monitor_rules.monitored_campaign(
        16, 20, 3, debug_bugs=("mass_leak", "mon_flap"))
    return monitor_rules.monitor_findings(
        res, "fixture[monitor-flapping-alert]",
        expect=("mass_imbalance",))


def _monitor_false_alarm() -> List[Finding]:
    """A CLEAN campaign watched by a naive fork detector that alarms
    on ANY membership-view divergence (``mon_naive_fork``): the normal
    kill/heal adoption transient raises a spurious ``epoch_fork``,
    which the false-alarm-free audit must flag."""
    from bluefog_tpu.analysis import monitor_rules

    _cfg, _sched, res = monitor_rules.monitored_campaign(
        16, 20, 3, debug_bugs=("mon_naive_fork",))
    return monitor_rules.monitor_findings(
        res, "fixture[monitor-false-alarm]", expect=())


FIXTURES: Dict[str, Callable[[], List[Finding]]] = {
    # plan family
    "plan-duplicate-destination": _plan_duplicate_destination,
    "plan-dropped-edge": _plan_dropped_edge,
    "plan-tampered-weights": _plan_tampered_weights,
    "plan-inconsistent-slots": _plan_inconsistent_slots,
    "plan-disconnected-zero-gap": _plan_disconnected,
    # hlo family
    "hlo-injected-all-gather": _hlo_injected_all_gather,
    "hlo-replicated-large-buffer": _hlo_replicated_large_buffer,
    # protocol family: each drops one ingredient of the real protocol
    "seqlock-skip-odd-phase": lambda: _model_fixture(
        seqlock_model.seqlock_model(1, 2, odd_phase=False)),
    "seqlock-publish-before-payload": lambda: _model_fixture(
        seqlock_model.seqlock_model(1, 2, early_publish=True)),
    "seqlock-no-writer-lock": lambda: _model_fixture(
        seqlock_model.seqlock_model(2, 1, use_lock=False)),
    "collect-split-critical-section": lambda: _model_fixture(
        seqlock_model.collect_model(2, atomic_collect=False)),
    "barrier-release-before-reset": lambda: _model_fixture(
        seqlock_model.barrier_model(2, 2, reset_before_release=False)),
    # protocol v2 (chunk-ring) family: each drops one ingredient of
    # slot_deposit / the drained-marker drain
    "chunk-ring-missing-commit-fence": lambda: _model_fixture(
        seqlock_model.chunk_ring_model(2, 2, commit_fence=False)),
    "chunk-ring-reordered-commit": lambda: _model_fixture(
        seqlock_model.chunk_ring_model(2, 1, words=1,
                                       in_order_commit=False,
                                       frontier_reader=True)),
    "chunk-drained-split-collect": lambda: _model_fixture(
        seqlock_model.drained_collect_model(2, atomic_collect=False)),
    # resilience family: botched healings + broken dead-writer drains
    "healed-dead-rank-not-excised": _healed_dead_not_excised,
    "healed-not-doubly-stochastic": _healed_not_doubly_stochastic,
    "grown-reuses-dead-rank": _grown_reuses_dead_rank,
    "grown-not-doubly-stochastic": _grown_not_doubly_stochastic,
    "epoch-switch-unbalanced-ledger": _epoch_switch_unbalanced_ledger,
    # adaptive family: botched demotions + a sub-floor flapping schedule
    "adaptive-straggler-excised": _demoted_straggler_excised,
    "adaptive-degree-cap-violated": _demoted_degree_cap_violated,
    "adaptive-demoted-not-doubly-stochastic": _demoted_not_doubly_stochastic,
    "adaptive-flap-below-floor": _adaptive_flap_below_floor,
    "dead-writer-lost-mass-drain": lambda: _model_fixture(
        seqlock_model.dead_writer_drain_model(deposits=2,
                                              account_wiped=False)),
    "dead-writer-early-commit": lambda: _model_fixture(
        seqlock_model.dead_writer_drain_model(deposits=2,
                                              commits_after_payload=False)),
    # wire family: the one wire protocol with one ingredient dropped
    "wire-reordered-chunk-stream": lambda: _model_fixture(
        wire_rules.chunk_stream_model(nchunks=3, writer_in_order=False,
                                      enforce_order=False)),
    "wire-credit-window-deadlock": lambda: _model_fixture(
        wire_rules.credit_window_model(nchunks=3, window=1,
                                       ack_per_chunk=False)),
    "wire-residual-dropped-on-demote": lambda: _model_fixture(
        wire_rules.residual_feedback_model(rounds=3, drop_on_demote=True)),
    "wire-commit-at-stream-open": lambda: _model_fixture(
        wire_rules.stream_death_model(nchunks=2,
                                      commits_after_payload=False)),
    "wire-drain-strands-reader": lambda: _model_fixture(
        wire_rules.stream_death_model(nchunks=2, drain_evenizes=False)),
    # telemetry family: broken snapshots, regressed counters, lost mass
    "telemetry-counter-regression": _telemetry_counter_regression,
    "telemetry-snapshot-bad-schema": _telemetry_snapshot_bad_schema,
    "telemetry-conservation-broken": _telemetry_conservation_broken,
    "envlint-undocumented-var": _envlint_undocumented_var,
    # introspect family: torn/foreign page, ghost holder, reset feed
    "introspect-torn-page": _introspect_torn_page,
    "introspect-ghost-holder": _introspect_ghost_holder,
    "introspect-blame-regression": _introspect_blame_regression,
    # sim family: seeded invariant bugs a full campaign must catch
    "sim-mass-leak": _sim_mass_leak,
    "sim-cap-bypass": _sim_cap_bypass,
    "sim-split-brain": _sim_split_brain,
    # serve family: a forgetful publisher handoff, a torn replica
    # swap, and the double-buffer model with its seqlocks dropped
    "serve-version-reset": _serve_version_reset,
    "serve-torn-swap": _serve_torn_swap,
    "serve-torn-read-model": _serve_torn_read_model,
    # slo family: a drain that skips polls (silent SLO hole) and a
    # drain that re-anchors send times (coordinated omission)
    "slo-silent-violation": _slo_silent_violation,
    "omission-biased-loadgen": _omission_biased_loadgen,
    # monitor family: a silent monitor, a flapping monitor, and a
    # false-alarming fork detector
    "monitor-silent-alert": _monitor_silent_alert,
    "monitor-flapping-alert": _monitor_flapping_alert,
    "monitor-false-alarm": _monitor_false_alarm,
    # distrib family: an uncapped tree repair, a stalled orphan
    # subtree, a regressing publisher handoff, a dirty chunk dropped
    # from a delta
    "distrib-degree-overflow": _distrib_degree_overflow,
    "distrib-stalled-subtree": _distrib_stalled_subtree,
    "distrib-version-regress": _distrib_version_regress,
    "distrib-stale-delta": _distrib_stale_delta,
    # lab family: tampered sweep artifacts the observatory must reject
    "lab-corrupted-fit": _lab_corrupted_fit,
    "lab-tampered-rate": _lab_tampered_rate,
    "lab-recommendation-contradicts-corpus":
        _lab_recommendation_contradicts_corpus,
    # trace family: crossed spans, corrupted flow identity, clock skew
    "trace-unbalanced-nesting": _trace_unbalanced_nesting,
    "trace-dangling-flow": _trace_dangling_flow,
    "trace-clock-skew": _trace_clock_skew,
    # progress family: dropped queue, double resolution, reordered fuse
    "progress-queue-drops-on-quiesce": _progress_queue_drops_on_quiesce,
    "progress-handle-double-complete": _progress_handle_double_complete,
    "progress-fusion-reorders": _progress_fusion_reorders,
    # epoch family: ill-ordered window traces
    "epoch-use-after-free": lambda: epoch_rules.check_trace(
        [("win_create", "w"), ("win_put", "w"), ("win_free", "w"),
         ("win_update", "w")], subject="use-after-free"),
    "epoch-get-clobbers-put": lambda: epoch_rules.check_trace(
        [("win_create", "w"), ("win_put", "w"), ("win_get", "w"),
         ("win_update", "w")], subject="get-clobbers-put"),
    # conformance family: transport mutants the differential harness,
    # the mass ledger, and the capability lint must each catch
    "conformance-out-of-order-commit": _conformance_out_of_order_commit,
    "conformance-capability-overclaim": _conformance_capability_overclaim,
    "conformance-drain-loses-mass": _conformance_drain_loses_mass,
    "conformance-epoch-reseed-skipped": _conformance_epoch_reseed_skipped,
}


def run_fixture(name: str) -> List[Finding]:
    """Build and lint one seeded-bug fixture; MUST return >= 1 finding."""
    return FIXTURES[name]()
