"""Rule family 12: the convergence observatory's measured artifacts.

The lab's claims are only useful if they are *checkable*: a sweep
artifact (``LAB_rNN.json``, :mod:`bluefog_tpu.lab.sweep`) asserts
measured contraction rates, fitted scaling laws, a rate-vs-gap rank
correlation, sim-oracle agreement, and a recommendation map — every
one of which can silently rot (a re-run with a broken combine path, a
hand-edited artifact, a recommender change that contradicts the frozen
corpus).  These rules re-derive each claim from the artifact's own raw
data:

- **schema** — the artifact is structurally what ``lab.recommend``
  will deserialize: schema id, version, provenance stamp, per-cell
  fields in range (rates/rhos in [0, 1], r² ≤ 1, gaps in (0, 1]);
- **refit** — each cell's stored (rho, rate) matches re-fitting the
  cell's own stored series with the shared fit code;
- **fit-monotonicity** — no fitted scaling law claims rates that GROW
  with fleet size (every corpus topology's gap is non-increasing in
  ``n``), and each law reproduces the measured cells it was fit from;
- **rate-vs-gap** — the measured rates rank-correlate with the static
  spectral-gap predictions (Spearman ≥ 0.8: the paper's ordering,
  observed), and the stored correlation matches recomputation;
- **oracle** — every cell's sim diff is within the artifact's own
  tolerance and no cell is flagged divergent;
- **recommendation-consistency** — every stored recommendation equals
  ``lab.recommend`` recomputed over the same artifact (determinism:
  the opt-in islands default must match the frozen corpus).

Check helpers are pure over a loaded artifact dict (tests and
``python -m bluefog_tpu.lab check`` call them directly); the
registered rules bind them to the frozen package artifact.
"""

from __future__ import annotations

import re
from typing import List, Optional

from bluefog_tpu.analysis.engine import Finding, Report, Severity, registry

__all__ = [
    "check_artifact_schema",
    "check_cell_refit",
    "check_fit_monotonicity",
    "check_rate_vs_gap",
    "check_oracle_clean",
    "check_recommendation_consistency",
    "check_artifact",
    "MIN_SPEARMAN",
]

#: Acceptance floor for the measured-vs-predicted rank correlation.
MIN_SPEARMAN = 0.8

_CELL_FIELDS = ("topology", "n", "payload_bytes", "rounds", "seed",
                "rate", "rho", "r2", "points", "gap", "series",
                "sim_ok", "sim_rate", "sim_rho", "abs_diff", "diverged")

_PROVENANCE_FIELDS = ("git_sha", "date", "host")


def _cell_label(c: dict) -> str:
    return f"{c.get('topology', '?')}@{c.get('n', '?')}"


def check_artifact_schema(art: dict, label: str = "artifact"
                          ) -> List[Finding]:
    """Structural contract of a lab artifact."""
    from bluefog_tpu.lab.recommend import ARTIFACT_SCHEMA, TOPOLOGIES

    out: List[Finding] = []

    def bad(subject: str, msg: str) -> None:
        out.append(Finding(rule="lab.artifact-schema",
                           subject=subject, message=msg))

    if art.get("schema") != ARTIFACT_SCHEMA:
        bad(label, f"schema {art.get('schema')!r} != {ARTIFACT_SCHEMA!r}")
    if not re.fullmatch(r"r\d{2,}", str(art.get("version", ""))):
        bad(label, f"version {art.get('version')!r} is not rNN")
    prov = art.get("provenance") or {}
    for k in _PROVENANCE_FIELDS:
        if not prov.get(k):
            bad(label, f"provenance missing {k!r}")
    cells = art.get("cells") or []
    if not cells:
        bad(label, "no sweep cells")
    for c in cells:
        sub = f"{label}:{_cell_label(c)}"
        missing = [k for k in _CELL_FIELDS if k not in c]
        if missing:
            bad(sub, f"cell missing fields {missing}")
            continue
        if c["topology"] not in TOPOLOGIES:
            bad(sub, f"unknown topology {c['topology']!r}")
        if not (0.0 <= float(c["rate"]) <= 1.0):
            bad(sub, f"rate {c['rate']} outside [0, 1]")
        if not (0.0 <= float(c["rho"]) <= 1.0):
            bad(sub, f"rho {c['rho']} outside [0, 1]")
        if float(c["r2"]) > 1.0 + 1e-9:
            bad(sub, f"r2 {c['r2']} > 1")
        if not (0.0 < float(c["gap"]) <= 1.0 + 1e-9):
            bad(sub, f"spectral gap {c['gap']} outside (0, 1]")
        if int(c["n"]) < 2:
            bad(sub, f"n {c['n']} < 2")
    for topo, fit in (art.get("fits") or {}).items():
        if not all(k in fit for k in ("a", "b")):
            bad(f"{label}:fit[{topo}]", f"fit missing a/b: {fit}")
    return out


def check_cell_refit(art: dict, label: str = "artifact",
                     tol: float = 1e-9) -> List[Finding]:
    """Each cell's stored fit must match re-fitting its stored series
    with the shared fit code — the artifact carries its own raw data
    precisely so a tampered headline number is catchable."""
    from bluefog_tpu.lab.fit import NOISE_FLOOR, fit_contraction

    out: List[Finding] = []
    for c in art.get("cells") or []:
        series = [(int(t), float(e)) for t, e in c.get("series") or []]
        if not series:
            out.append(Finding(
                rule="lab.cell-refit", subject=f"{label}:{_cell_label(c)}",
                message="cell has no stored series to refit"))
            continue
        peak = max((e for _, e in series), default=0.0)
        fit = fit_contraction(series,
                              floor=max(NOISE_FLOOR, peak * 1e-5))
        for k in ("rho", "rate"):
            if abs(fit[k] - float(c[k])) > tol:
                out.append(Finding(
                    rule="lab.cell-refit",
                    subject=f"{label}:{_cell_label(c)}",
                    message=f"stored {k} {c[k]:.6g} != refit "
                            f"{fit[k]:.6g} from the cell's own series"))
    return out


def check_fit_monotonicity(art: dict, label: str = "artifact",
                           grow_tol: float = 0.05,
                           refit_tol: float = 1e-9) -> List[Finding]:
    """Scaling laws must not claim contraction rates growing with n
    (every corpus topology's gap is non-increasing in fleet size), and
    each stored law must match re-fitting the measured cells."""
    from bluefog_tpu.lab.fit import fit_power_law

    out: List[Finding] = []
    cells = art.get("cells") or []
    for topo, fit in sorted((art.get("fits") or {}).items()):
        sub = f"{label}:fit[{topo}]"
        b = float(fit.get("b", 0.0))
        if b > grow_tol:
            out.append(Finding(
                rule="lab.fit-monotonicity", subject=sub,
                message=f"law exponent b={b:.4f} claims rates GROWING "
                        f"with n (tolerance {grow_tol})"))
        mine = [c for c in cells if c["topology"] == topo]
        if not mine:
            out.append(Finding(
                rule="lab.fit-monotonicity", subject=sub,
                message="fit has no measured cells backing it"))
            continue
        refit = fit_power_law([c["n"] for c in mine],
                              [c["rate"] for c in mine])
        if (abs(refit["a"] - float(fit.get("a", 0.0))) > refit_tol
                or abs(refit["b"] - b) > refit_tol):
            out.append(Finding(
                rule="lab.fit-monotonicity", subject=sub,
                message=f"stored law (a={fit.get('a'):.6g}, b={b:.6g}) "
                        f"!= refit (a={refit['a']:.6g}, "
                        f"b={refit['b']:.6g}) from the measured cells"))
    return out


def check_rate_vs_gap(art: dict, label: str = "artifact",
                      min_corr: float = MIN_SPEARMAN) -> List[Finding]:
    """Measured rates must rank-correlate with the spectral-gap
    predictions, and the stored correlation must be honest."""
    from bluefog_tpu.lab.fit import spearman

    out: List[Finding] = []
    cells = art.get("cells") or []
    if len(cells) < 3:
        return [Finding(rule="lab.rate-vs-gap", subject=label,
                        message=f"only {len(cells)} cells — too few to "
                                f"rank-correlate")]
    corr = spearman([float(c["gap"]) for c in cells],
                    [float(c["rate"]) for c in cells])
    stored = art.get("spearman_rate_vs_gap")
    if stored is None or abs(float(stored) - corr) > 1e-9:
        out.append(Finding(
            rule="lab.rate-vs-gap", subject=label,
            message=f"stored spearman {stored!r} != recomputed "
                    f"{corr:.4f}"))
    if corr < min_corr:
        out.append(Finding(
            rule="lab.rate-vs-gap", subject=label,
            message=f"measured rates vs spectral gaps: spearman "
                    f"{corr:.3f} < {min_corr} — the fleet does not "
                    f"reproduce the predicted topology ordering"))
    return out


def check_oracle_clean(art: dict, label: str = "artifact"
                       ) -> List[Finding]:
    """Every cell must agree with its sim replay within the artifact's
    own tolerance, with the sim run itself invariant-clean."""
    out: List[Finding] = []
    tol = float((art.get("params") or {}).get("tol", 0.0) or 0.0)
    for c in art.get("cells") or []:
        sub = f"{label}:{_cell_label(c)}"
        if not c.get("sim_ok", False):
            out.append(Finding(
                rule="lab.oracle", subject=sub,
                message="sim replay violated fleet invariants"))
        if c.get("diverged"):
            out.append(Finding(
                rule="lab.oracle", subject=sub,
                message=f"measured rate {c.get('rate'):.4f} vs sim "
                        f"{c.get('sim_rate'):.4f}: |diff| "
                        f"{c.get('abs_diff'):.4f} > tol {tol}"))
        elif tol and abs(float(c["rate"]) - float(c["sim_rate"])) > tol:
            out.append(Finding(
                rule="lab.oracle", subject=sub,
                message=f"cell not flagged but |rate - sim_rate| = "
                        f"{abs(float(c['rate']) - float(c['sim_rate'])):.4f}"
                        f" > tol {tol}"))
    if not art.get("oracle_clean", False) and not out:
        out.append(Finding(
            rule="lab.oracle", subject=label,
            message="oracle_clean is false but no cell is divergent"))
    return out


def check_recommendation_consistency(art: dict, label: str = "artifact"
                                     ) -> List[Finding]:
    """Every stored recommendation must equal ``lab.recommend``
    recomputed over this same artifact — the determinism contract
    behind using it as an islands launch default."""
    from bluefog_tpu.lab.recommend import recommend

    out: List[Finding] = []
    recs = art.get("recommended") or {}
    if not recs:
        return [Finding(rule="lab.recommendation-consistency",
                        subject=label,
                        message="artifact stores no recommendation map")]
    for key, stored in sorted(recs.items()):
        try:
            n_s, pb_s = key.split(":")
            fresh = recommend(int(n_s), int(pb_s), artifact=art)
        except (ValueError, KeyError) as e:
            out.append(Finding(
                rule="lab.recommendation-consistency",
                subject=f"{label}:{key}",
                message=f"recompute failed: {e}"))
            continue
        if fresh["topology"] != stored.get("topology"):
            out.append(Finding(
                rule="lab.recommendation-consistency",
                subject=f"{label}:{key}",
                message=f"stored recommendation "
                        f"{stored.get('topology')!r} contradicts the "
                        f"measured corpus (recompute: "
                        f"{fresh['topology']!r})"))
        elif abs(float(stored.get("score", -1.0)) - fresh["score"]) > 1e-9:
            out.append(Finding(
                rule="lab.recommendation-consistency",
                subject=f"{label}:{key}",
                message=f"stored score {stored.get('score')} != "
                        f"recomputed {fresh['score']:.6g}"))
    return out


def check_artifact(art: dict, label: str = "artifact") -> List[Finding]:
    """All lab checks over one loaded artifact (what ``python -m
    bluefog_tpu.lab check`` and the registered rules run)."""
    out = check_artifact_schema(art, label)
    if any(f.severity == Severity.ERROR for f in out):
        # structurally broken: the semantic checks would only cascade
        return out
    out += check_cell_refit(art, label)
    out += check_fit_monotonicity(art, label)
    out += check_rate_vs_gap(art, label)
    out += check_oracle_clean(art, label)
    out += check_recommendation_consistency(art, label)
    return out


# ---------------------------------------------------------------------------
# registered rules over the frozen package artifact
# ---------------------------------------------------------------------------


def _frozen_artifact() -> Optional[dict]:
    from bluefog_tpu.lab.recommend import load_artifact

    try:
        return load_artifact()
    except (OSError, ValueError):
        return None


def _run_over_frozen(report: Report, check, rule_name: str) -> None:
    from bluefog_tpu.lab.recommend import default_artifact_path

    art = _frozen_artifact()
    if art is None:
        report.add(Finding(
            rule=rule_name, subject=default_artifact_path(),
            message="frozen lab artifact missing or unreadable",
            severity=Severity.ERROR))
        return
    report.subjects_checked += len(art.get("cells") or ())
    report.extend(check(art, label="LAB_" + str(art.get("version"))))


@registry.rule("lab.artifact-schema", "lab",
               "frozen sweep artifact is structurally valid")
def rule_artifact_schema(report: Report) -> None:
    _run_over_frozen(report, check_artifact_schema, "lab.artifact-schema")


@registry.rule("lab.cell-refit", "lab",
               "stored cell fits match refitting their own series")
def rule_cell_refit(report: Report) -> None:
    _run_over_frozen(report, check_cell_refit, "lab.cell-refit")


@registry.rule("lab.fit-monotonicity", "lab",
               "scaling laws honest and non-increasing in fleet size")
def rule_fit_monotonicity(report: Report) -> None:
    _run_over_frozen(report, check_fit_monotonicity,
                     "lab.fit-monotonicity")


@registry.rule("lab.rate-vs-gap", "lab",
               "measured rates rank-correlate with spectral gaps")
def rule_rate_vs_gap(report: Report) -> None:
    art = _frozen_artifact()
    if art is not None:
        corr = art.get("spearman_rate_vs_gap")
        if isinstance(corr, (int, float)):
            report.metric("lab.spearman_rate_vs_gap", float(corr))
    _run_over_frozen(report, check_rate_vs_gap, "lab.rate-vs-gap")


@registry.rule("lab.oracle", "lab",
               "every sweep cell agrees with its sim replay")
def rule_oracle(report: Report) -> None:
    _run_over_frozen(report, check_oracle_clean, "lab.oracle")


@registry.rule("lab.recommendation-consistency", "lab",
               "stored recommendations match recomputation")
def rule_recommendation_consistency(report: Report) -> None:
    _run_over_frozen(report, check_recommendation_consistency,
                     "lab.recommendation-consistency")
