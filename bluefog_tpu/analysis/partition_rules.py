"""Rule family: partition tolerance as a verifier.

The quorum fence (:mod:`bluefog_tpu.resilience.quorum`) argues that a
network partition can never fork the membership-epoch lineage: the
side that cannot account for a strict majority of the current epoch
ORPHANs — parks its rounds, touches neither the board nor the shared
ledgers — and merges back through the join machinery when the cut
heals.  These rules turn that argument into checks, on the same
no-subprocess seeded-campaign plan as :mod:`.sim_rules`:

- **quorum-floor** — the strict-majority arithmetic is pinned against
  the production :func:`~bluefog_tpu.resilience.quorum.majority_floor`
  /``quorum_met`` pair: exact floors for small fleets, the even-split
  property (neither half of an even fleet has quorum), and the
  1-member trivial quorum;
- **campaign-clean** — pinned-seed partition campaigns finish with
  zero violations AND actually exercised the path (orphans entered and
  merged — a partition window shorter than the failure timeout would
  pass vacuously);
- **split-brain-caught** — with the ``split_brain`` seeded bug (the
  fence skipped), both sides heal and the ``single-lineage`` standing
  invariant fires, and ddmin shrinks the schedule to the partition
  fault alone.

The partition acceptance campaigns (N=64/128) ride the CLI's
``--self-test`` arm via :func:`selftest_partition_campaigns`.
"""

from __future__ import annotations

from typing import List, Tuple

from bluefog_tpu.analysis.engine import Finding, Report, registry
from bluefog_tpu.analysis.sim_rules import campaign_findings

__all__ = [
    "partition_campaign",
    "selftest_partition_campaigns",
    "PARTITION_PINS",
]

#: ``--self-test`` pinned partition campaigns:
#: (ranks, rounds, seed, minority) — the acceptance sizes.
PARTITION_PINS: Tuple[Tuple[int, int, int, Tuple[int, ...]], ...] = (
    (64, 40, 7, (9, 23, 55)),
    (128, 40, 11, (3, 64, 77, 101)),
)

#: pinned strict-majority floors: total members -> minimum live count
#: that may commit a heal/demote (floor(n/2) + 1; 1-member epochs have
#: trivial quorum)
_FLOORS = {1: 1, 2: 2, 3: 2, 4: 3, 5: 3, 6: 4, 7: 4, 8: 5, 9: 5,
           64: 33, 128: 65}


def partition_campaign(ranks: int, rounds: int, seed: int,
                       minority, start: int = 5, stop: int = 14,
                       **kw):
    """One partition campaign: ``minority`` cut from the rest between
    rounds ``start`` and ``stop`` (long enough to span the sim's 1 s
    failure timeout at the 0.2 s round period)."""
    from bluefog_tpu.sim.campaign import SimConfig, run_campaign
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    kw.setdefault("quiesce_rounds", max(20, rounds))
    cfg = SimConfig(ranks=ranks, rounds=rounds, seed=seed,
                    faults=("partition",), **kw)
    sched = FaultSchedule([Fault.partition([minority], start, stop)],
                          seed=seed)
    return cfg, sched, run_campaign(cfg, sched)


def _path_findings(res, label: str, minority_n: int) -> List[Finding]:
    """Non-vacuity: the campaign must have actually orphaned the
    minority and merged every orphan back."""
    out: List[Finding] = []
    kinds = [e[1] for e in res.event_log]
    orphans = kinds.count("orphan")
    merged = kinds.count("merge_enter")
    if orphans != minority_n:
        out.append(Finding(
            "partition.campaign-clean", label,
            f"{orphans} rank(s) ORPHANed, expected the full minority "
            f"of {minority_n} — the quorum fence did not engage"))
    if merged != orphans:
        out.append(Finding(
            "partition.campaign-clean", label,
            f"{merged} of {orphans} orphan(s) merged back after the "
            "heal — the merge path stranded a rank"))
    led = res.final.get("ledger") or {}
    if not led.get("balanced"):
        out.append(Finding("partition.campaign-clean", label,
                           f"count ledger unbalanced after merge: {led}"))
    return out


@registry.rule("partition.quorum-floor", "partition",
               "the strict-majority floor and quorum verdicts of the "
               "production quorum module match the pinned arithmetic "
               "(even splits have NO quorum on either side)")
def _run_quorum_floor(report: Report) -> None:
    from bluefog_tpu.resilience.quorum import majority_floor, quorum_met

    report.subjects_checked += 1
    for total, floor in sorted(_FLOORS.items()):
        got = majority_floor(total)
        if got != floor:
            report.add(Finding(
                "partition.quorum-floor", f"total={total}",
                f"majority_floor({total}) = {got}, pinned {floor}"))
        if not quorum_met(floor, total) or quorum_met(floor - 1, total):
            report.add(Finding(
                "partition.quorum-floor", f"total={total}",
                f"quorum_met is not a strict threshold at the floor "
                f"({floor} of {total})"))
    for even in (2, 4, 8, 64):
        if quorum_met(even // 2, even):
            report.add(Finding(
                "partition.quorum-floor", f"total={even}",
                f"an even {even}-member fleet grants quorum to a "
                f"half of {even // 2} — both sides of an even split "
                "would heal"))


@registry.rule("partition.campaign-clean", "partition",
               "a pinned-seed partition campaign ORPHANs exactly the "
               "minority, keeps a single epoch lineage, merges every "
               "orphan back on heal, and quiesces to consensus with a "
               "balanced ledger")
def _run_partition_clean(report: Report) -> None:
    for ranks, rounds, seed, minority in ((16, 30, 3, (6, 11)),):
        _cfg, _sched, res = partition_campaign(ranks, rounds, seed,
                                               minority)
        label = f"partition[n={ranks},seed={seed},cut={len(minority)}]"
        report.subjects_checked += 1
        report.extend(campaign_findings(res, label))
        report.extend(_path_findings(res, label, len(minority)))
        report.metrics[f"partition.events/{label}"] = float(res.events)


@registry.rule("partition.split-brain-caught", "partition",
               "with the quorum fence seeded out (split_brain), both "
               "partition sides heal and the single-lineage standing "
               "invariant fires, shrinking to the partition fault alone")
def _run_split_brain_caught(report: Report) -> None:
    from bluefog_tpu.sim.campaign import run_campaign, shrink_schedule
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    label = "partition[n=16,seed=3,bug=split_brain]"
    report.subjects_checked += 1
    cfg, sched, res = partition_campaign(
        16, 30, 3, (6, 11), debug_bugs=("split_brain",))
    names = {v["name"] for v in res.violations}
    if "single-lineage" not in names:
        report.add(Finding(
            "partition.split-brain-caught", label,
            f"the seeded split_brain bug was NOT caught (violations: "
            f"{sorted(names)}) — the single-lineage invariant is not "
            "auditing"))
        return
    noisy = FaultSchedule(
        list(sched.faults)
        + [Fault(kind="kill", step=3, rank=1),
           Fault(kind="slow", step=4, rank=2, duration_s=0.9, stop=12)],
        seed=cfg.seed)
    minimal, viol, _runs = shrink_schedule(cfg, noisy,
                                           target="single-lineage")
    if viol is None or viol["name"] != "single-lineage":
        report.add(Finding(
            "partition.split-brain-caught", label,
            f"shrinker lost the violation (got {viol!r})"))
        return
    kinds = [f.kind for f in minimal]
    if kinds != ["partition"]:
        report.add(Finding(
            "partition.split-brain-caught", label,
            f"minimal schedule is {kinds}, expected the partition "
            "fault alone — the violation needs no other fault"))


def selftest_partition_campaigns():
    """The ``--self-test`` arm: acceptance-size partition campaigns
    (N=64/128) must come back clean, non-vacuous, and bit-identical on
    a second run.  Returns ``(label, result, findings)`` triples."""
    from bluefog_tpu.sim.campaign import run_campaign

    out = []
    for ranks, rounds, seed, minority in PARTITION_PINS:
        # merged orphans re-enter with fresh unit weight and need a
        # full mixing time at acceptance scale — quiesce longer than
        # the small-campaign default
        cfg, sched, res = partition_campaign(ranks, rounds, seed,
                                             minority,
                                             quiesce_rounds=60)
        label = f"partition[n={ranks},rounds={rounds},seed={seed}]"
        findings = campaign_findings(res, label)
        findings.extend(_path_findings(res, label, len(minority)))
        again = run_campaign(cfg, sched)
        if again.digest != res.digest:
            findings.append(Finding(
                "partition.campaign-clean", label,
                f"same-seed partition campaign diverged: "
                f"{res.digest[:16]} != {again.digest[:16]}"))
        out.append((label, res, findings))
    return out
