"""Rule family 3b: epoch-ordering lint for window-op sequences.

MPI RMA imposes epoch discipline (ops only inside access/exposure
epochs); the mailbox emulation in ``windows.py`` is looser — there is no
fence call — but it still has a real ordering contract, and violating it
corrupts data silently rather than raising:

- an op on a never-created (or already-freed) window raises at runtime,
  but only at the first op — a trace lint catches it in review/CI;
- ``win_get`` and ``win_put``/``win_accumulate`` deposit into the SAME
  mailbox slots, so both in one epoch (between combines) means the later
  one silently overwrites the earlier's deposits before ``win_update``
  ever reads them;
- a plain ``win_put`` after ``win_accumulate`` in one epoch silently
  discards the accumulated partial sums the same way.

``check_trace`` lints a ``(op, window_name)`` event list — either canned
(the fixture corpus) or recorded from a live run via
``windows.record_win_ops()``, which is how tests/test_analysis.py lints
the real push-sum idiom end to end.  Epochs are delimited by the combine
ops (``win_update`` / ``win_put_update`` / ``win_update_then_collect``)
and by ``win_create``.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from bluefog_tpu.analysis.engine import Finding, Report, Severity, registry

__all__ = ["check_trace", "CANONICAL_TRACES"]

Trace = Sequence[Tuple[str, str]]

_CREATE = "win_create"
_FREE = "win_free"
_PUTS = frozenset({"win_put", "win_put_update"})
_ACCS = frozenset({"win_accumulate"})
_GETS = frozenset({"win_get"})
# combine reads the mailbox and (with reset / collect) drains it: a new
# epoch starts after it.  win_put_update is both a deposit in the old
# epoch and the combine that closes it.
_COMBINES = frozenset({"win_update", "win_put_update",
                       "win_update_then_collect"})
_KNOWN = (_PUTS | _ACCS | _GETS | _COMBINES
          | {_CREATE, _FREE, "win_set_exposed"})

_RULE = "protocol.win-epoch"


def check_trace(trace: Trace, subject: str = "trace") -> List[Finding]:
    """Lint one window-op event sequence.  Returns findings for:

    use-before-create / use-after-free (ERROR), duplicate create
    (WARNING), free of an unknown window (WARNING), get+put in one epoch
    (ERROR: the later op overwrites the earlier's slot deposits), and
    put-after-accumulate in one epoch (WARNING: discards partial sums).
    """
    findings: List[Finding] = []
    live = set()
    ever = set()
    # per-window deposits since the last epoch boundary
    epoch: dict = {}

    def add(msg: str, severity: Severity = Severity.ERROR) -> None:
        findings.append(Finding(_RULE, subject, msg, severity))

    for i, (op, name) in enumerate(trace):
        if op not in _KNOWN:
            add(f"event {i}: unknown op {op!r}", Severity.WARNING)
            continue
        if op == _CREATE:
            if name in live:
                add(f"event {i}: win_create({name!r}) on a live window "
                    "(silently returns False; free it first)",
                    Severity.WARNING)
            live.add(name)
            ever.add(name)
            epoch[name] = set()
            continue
        if op == _FREE:
            if name == "*":
                live.clear()
                epoch.clear()
            elif name in live:
                live.discard(name)
                epoch.pop(name, None)
            else:
                add(f"event {i}: win_free({name!r}) on an unknown window",
                    Severity.WARNING)
            continue
        if name not in live:
            kind = "freed" if name in ever else "never-created"
            add(f"event {i}: {op}({name!r}) on a {kind} window")
            continue
        dep = epoch.setdefault(name, set())
        if op in _GETS and (dep & (_PUTS | _ACCS)):
            add(f"event {i}: win_get({name!r}) in an epoch that already "
                "deposited via put/accumulate — the get overwrites those "
                "slot deposits before any combine reads them")
        elif op in (_PUTS | _ACCS) and (dep & _GETS):
            add(f"event {i}: {op}({name!r}) in an epoch that already "
                "deposited via win_get — the put overwrites the pulled "
                "slot values before any combine reads them")
        elif op in _PUTS and (dep & _ACCS):
            add(f"event {i}: {op}({name!r}) after win_accumulate in the "
                "same epoch — the plain put discards the accumulated "
                "partial sums", Severity.WARNING)
        if op in _COMBINES:
            # win_update_then_collect also logs its inner win_update;
            # clearing here makes that second boundary a no-op.
            epoch[name] = set()
        else:
            dep.add(op)
    return findings


# Known-good idioms from the optimizer / push-sum code paths; the
# registered rule proves the lint accepts every one of them (the fixture
# corpus proves it rejects the seeded-bug traces).
CANONICAL_TRACES = {
    "pushsum-loop": [
        ("win_create", "w"),
        ("win_accumulate", "w"),
        ("win_update_then_collect", "w"), ("win_update", "w"),
        ("win_set_exposed", "w"),
        ("win_accumulate", "w"),
        ("win_update_then_collect", "w"), ("win_update", "w"),
        ("win_free", "w"),
    ],
    "put-optimizer-loop": [
        ("win_create", "w"),
        ("win_put_update", "w"),
        ("win_put_update", "w"),
        ("win_free", "*"),
    ],
    "get-then-average": [
        ("win_create", "w"),
        ("win_get", "w"),
        ("win_update", "w"),
        ("win_get", "w"),
        ("win_update", "w"),
        ("win_free", "w"),
    ],
    "two-windows-interleaved": [
        ("win_create", "a"), ("win_create", "b"),
        ("win_put", "a"), ("win_get", "b"),
        ("win_update", "a"), ("win_update", "b"),
        ("win_free", "*"),
    ],
}


@registry.rule(_RULE, "protocol",
               "canonical window-op idioms pass the epoch-ordering lint")
def _run_epoch(report: Report) -> None:
    for label, trace in CANONICAL_TRACES.items():
        report.subjects_checked += 1
        report.extend(check_trace(trace, subject=label))
