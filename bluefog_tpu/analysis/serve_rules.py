"""Rule family: the serving plane as a verifier.

The serving fleet (:mod:`bluefog_tpu.serve`) argues three properties
hold under arbitrary publisher/replica death:

1. the committed snapshot **version is strictly monotone** — the
   region header persists it, so a successor publisher continues past
   the highest committed version instead of restarting at 1, and a
   replica hot-swap never flips backward;
2. publication is **quorum-fenced** — ``islands.serve_publish`` runs
   the same strict-majority gate as membership commits, so an ORPHAN
   minority can never publish weights the majority lineage diverged
   from;
3. the double-buffer seqlock makes **torn reads impossible** — a
   reader either observes a whole committed snapshot or retries;
   served bytes always equal SOME committed version.

These rules turn the argument into checks on the sim-campaign plan of
:mod:`.partition_rules` plus one exhaustive interleaving model:

- **version-monotone** — pinned serve campaigns (clean, replica kill
  mid-swap + respawn, publisher kill mid-payload and mid-flip) finish
  with zero violations and non-vacuously: versions in the event log
  strictly increase across the publisher handoff, replicas converge
  to the committed head, the kill paths actually fired;
- **fence-requires-quorum** — the publish gate is pinned against the
  production :func:`~bluefog_tpu.resilience.quorum.quorum_met`
  arithmetic, and a partition campaign that cuts the publisher into
  the minority shows it FENCED (``serve_fenced``), never publishing
  while orphaned, with the majority's successor continuing monotone;
- **torn-read-model** — an exhaustive interleaving model of the
  double-buffer protocol (two publishes racing one reader, every
  atomic-step placement): a completed read only ever returns a
  committed ``(version, payload)`` pair; dropping the seqlocks or the
  reader's re-read bracket produces the torn accepts the fixture
  corpus pins.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from bluefog_tpu.analysis.engine import Finding, Report, registry
from bluefog_tpu.analysis.sim_rules import campaign_findings

__all__ = [
    "serve_campaign",
    "torn_read_model",
    "selftest_serve_campaigns",
    "SERVE_PINS",
]

#: ``--self-test`` pinned serve campaigns: (ranks, rounds, seed,
#: fault kind or None) — chaos under serving at a modest acceptance
#: size (the np=4 process-level e2e lives in tests/).
SERVE_PINS: Tuple[Tuple[int, int, int, object], ...] = (
    (32, 40, 7, None),
    (32, 40, 7, "serve_kill"),
    (32, 40, 11, "serve_pub_kill"),
)


def serve_campaign(ranks: int, rounds: int, seed: int,
                   schedule=None, **kw):
    """One serve-enabled campaign: publisher analog every 4 rounds,
    two hot-swap replicas, default no rank faults."""
    from bluefog_tpu.sim.campaign import SimConfig, run_campaign
    from bluefog_tpu.sim.schedule import FaultSchedule

    kw.setdefault("quiesce_rounds", max(10, rounds // 2))
    kw.setdefault("serve_every", 4)
    kw.setdefault("serve_replicas", 2)
    cfg = SimConfig(ranks=ranks, rounds=rounds, seed=seed, **kw)
    sched = schedule if schedule is not None else FaultSchedule()
    return cfg, sched, run_campaign(cfg, sched)


def _publish_versions(res) -> List[int]:
    return [dict(e[3])["version"] for e in res.event_log
            if e[1] == "serve_publish"]


def _serve_path_findings(res, label: str,
                         expect_publishes: int = 3) -> List[Finding]:
    """Non-vacuity + monotonicity over the campaign's event log."""
    out: List[Finding] = []
    vers = _publish_versions(res)
    if len(vers) < expect_publishes:
        out.append(Finding(
            "serve.version-monotone", label,
            f"only {len(vers)} snapshot(s) published, expected >= "
            f"{expect_publishes} — the publisher path is not running"))
    if any(b <= a for a, b in zip(vers, vers[1:])):
        out.append(Finding(
            "serve.version-monotone", label,
            f"published versions not strictly increasing: {vers}"))
    sv = res.final.get("serve") or {}
    reps = sv.get("replicas") or {}
    if not reps:
        out.append(Finding(
            "serve.version-monotone", label,
            "no replica state in the campaign result — replicas never "
            "ran"))
    for i, rep in sorted(reps.items()):
        if rep.get("killed"):
            continue  # killed without a respawn round scheduled
        if rep.get("version") != sv.get("published"):
            out.append(Finding(
                "serve.version-monotone", label,
                f"replica {i} quiesced at version {rep.get('version')}"
                f", committed head is {sv.get('published')} — the "
                "hot-swap loop stalled"))
        if not rep.get("steps"):
            out.append(Finding(
                "serve.version-monotone", label,
                f"replica {i} served zero steps"))
    return out


@registry.rule("serve.version-monotone", "serve",
               "pinned serve campaigns — clean, replica killed "
               "mid-swap and respawned, publisher killed mid-payload "
               "and mid-flip — publish strictly increasing versions, "
               "replicas converge to the committed head, and the "
               "standing serve invariants stay silent")
def _run_version_monotone(report: Report) -> None:
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    cases = [
        ("clean", None, ()),
        ("replica-kill",
         FaultSchedule([Fault(kind="serve_kill", step=2, rank=0,
                              stop=16)]),
         ("serve_replica_kill", "serve_replica_join")),
        ("pub-kill-payload",
         FaultSchedule([Fault(kind="serve_pub_kill", step=2, rank=-1,
                              group="payload")]),
         ("serve_pub_kill",)),
        ("pub-kill-flip",
         FaultSchedule([Fault(kind="serve_pub_kill", step=2, rank=-1,
                              group="flip")]),
         ("serve_pub_kill",)),
    ]
    for name, sched, need_events in cases:
        label = f"serve[n=16,seed=3,{name}]"
        report.subjects_checked += 1
        _cfg, _sched, res = serve_campaign(16, 24, 3, schedule=sched)
        report.extend(campaign_findings(res, label))
        report.extend(_serve_path_findings(res, label))
        kinds = {e[1] for e in res.event_log}
        for ev in need_events:
            if ev not in kinds:
                report.add(Finding(
                    "serve.version-monotone", label,
                    f"scheduled fault never fired: no {ev!r} event — "
                    "the chaos path passed vacuously"))
        if name == "pub-kill-payload":
            # mid-payload death must NOT commit: one publish ordinal
            # is swallowed, yet versions stay gap-free and monotone
            # (the torn standby buffer is simply overwritten)
            vers = _publish_versions(res)
            if vers != sorted(set(vers)) or (
                    vers and vers != list(range(1, len(vers) + 1))):
                report.add(Finding(
                    "serve.version-monotone", label,
                    f"versions after a mid-payload publisher death "
                    f"are {vers} — expected a gap-free monotone "
                    "sequence (nothing committed at the torn ordinal)"))
        report.metrics[f"serve.publishes/{label}"] = float(
            len(_publish_versions(res)))


@registry.rule("serve.fence-requires-quorum", "serve",
               "the publish gate matches the production quorum_met "
               "arithmetic, and a partition that cuts the publisher "
               "into the minority fences it (serve_fenced, no publish "
               "while orphaned) while the majority successor "
               "continues strictly monotone")
def _run_fence_requires_quorum(report: Report) -> None:
    from bluefog_tpu.resilience.quorum import majority_floor, quorum_met
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    # the arithmetic pin: serve_publish commits iff quorum_met — a
    # fence that admitted one member below the strict-majority floor
    # would let an orphaned minority publish diverged weights
    report.subjects_checked += 1
    for total in (1, 2, 3, 4, 5, 8, 9, 64):
        floor = majority_floor(total)
        if not quorum_met(floor, total) or quorum_met(floor - 1, total):
            report.add(Finding(
                "serve.fence-requires-quorum", f"total={total}",
                f"quorum_met is not a strict threshold at the floor "
                f"({floor} of {total}) — the publish fence inherits "
                "the defect"))

    # the campaign pin: ranks 0..2 (the publisher among them) cut from
    # a 5-strong majority; serve_every=1 so the denial round publishes
    label = "serve[n=8,seed=3,publisher-orphaned]"
    report.subjects_checked += 1
    sched = FaultSchedule([Fault.partition([(0, 1, 2)], 5, 14)], seed=3)
    _cfg, _sched, res = serve_campaign(
        8, 24, 3, schedule=sched, serve_every=1, serve_replicas=1,
        quiesce_rounds=30)
    report.extend(campaign_findings(res, label))
    fenced = [e for e in res.event_log if e[1] == "serve_fenced"]
    if not fenced:
        report.add(Finding(
            "serve.fence-requires-quorum", label,
            "the orphaned publisher was never fenced (no serve_fenced "
            "event) — the quorum gate did not engage"))
    orphan_t = {e[2]: e[0] for e in res.event_log if e[1] == "orphan"}
    for e in res.event_log:
        if e[1] == "serve_publish" and e[2] in orphan_t \
                and e[0] >= orphan_t[e[2]]:
            report.add(Finding(
                "serve.fence-requires-quorum", label,
                f"rank {e[2]} published at t={e[0]} AFTER entering "
                f"ORPHAN at t={orphan_t[e[2]]} — a minority published "
                "weights the majority lineage diverged from"))
    vers = _publish_versions(res)
    if any(b <= a for a, b in zip(vers, vers[1:])):
        report.add(Finding(
            "serve.fence-requires-quorum", label,
            f"versions not monotone across the publisher handoff: "
            f"{vers}"))
    pubs_by_rank = sorted({e[2] for e in res.event_log
                           if e[1] == "serve_publish"})
    if len(pubs_by_rank) < 2:
        report.add(Finding(
            "serve.fence-requires-quorum", label,
            f"publisher never handed off (publishing ranks: "
            f"{pubs_by_rank}) — the fence path passed vacuously"))


# ---------------------------------------------------------------------------
# the double-buffer torn-read model
# ---------------------------------------------------------------------------

#: canonical payload per version: version v serves (10v, 10v + 1)
_PAYLOAD = {v: (10 * v, 10 * v + 1) for v in (1, 2, 3)}


def _writer_ops(version: int, buf: int, *, buffer_seqlock: bool,
                header_seqlock: bool) -> List:
    """One publish as a list of atomic mutations of the region state
    (mirrors ``SnapshotRegion.publish``: standby buffer under its own
    seqlock, then the header flip under the head seqlock)."""
    w0, w1 = _PAYLOAD[version]
    ops = []
    if buffer_seqlock:
        ops.append(lambda st: st["bufs"][buf].__setitem__(
            "seq", st["bufs"][buf]["seq"] + 1))
    ops.append(lambda st: st["bufs"][buf].__setitem__("w0", w0))
    ops.append(lambda st: st["bufs"][buf].__setitem__("w1", w1))
    ops.append(lambda st: st["bufs"][buf].__setitem__("ver", version))
    if buffer_seqlock:
        ops.append(lambda st: st["bufs"][buf].__setitem__(
            "seq", st["bufs"][buf]["seq"] + 1))
    if header_seqlock:
        ops.append(lambda st: st["head"].__setitem__(
            "seq", st["head"]["seq"] + 1))

    def flip(st):
        st["head"]["active"] = buf
        st["head"]["version"] = version
        st["committed"] = version
    ops.append(flip)
    if header_seqlock:
        ops.append(lambda st: st["head"].__setitem__(
            "seq", st["head"]["seq"] + 1))
    return ops


def torn_read_model(*, buffer_seqlock: bool = True,
                    header_seqlock: bool = True,
                    reader_rechecks: bool = True) -> Dict:
    """Exhaustively interleave one reader attempt against two
    publishes (v2 into the standby buffer, then v3 overwriting v1's
    old buffer — the reuse that makes tearing possible at all).

    Every atomic-step placement of the reader is explored, including
    "writer died here" (all remaining reader steps run against the
    frozen state).  A completed read must return a ``(version,
    payload)`` pair where the version was committed at accept time and
    the payload is that version's canonical bytes.  The knobs produce
    the seeded-bug variants: ``buffer_seqlock=False`` +
    ``header_seqlock=False`` drops the seqlocks, ``reader_rechecks=
    False`` drops the reader's re-read bracket.
    """
    base = {
        "bufs": [{"seq": 0, "ver": 1,
                  "w0": _PAYLOAD[1][0], "w1": _PAYLOAD[1][1]},
                 {"seq": 0, "ver": 0, "w0": 0, "w1": 0}],
        "head": {"seq": 0, "active": 0, "version": 1},
        "committed": 1,
    }
    wops = (_writer_ops(2, 1, buffer_seqlock=buffer_seqlock,
                        header_seqlock=header_seqlock)
            + _writer_ops(3, 0, buffer_seqlock=buffer_seqlock,
                          header_seqlock=header_seqlock))

    def state_at(wpos: int) -> dict:
        import copy

        st = copy.deepcopy(base)
        for op in wops[:wpos]:
            op(st)
        return st

    states = [state_at(k) for k in range(len(wops) + 1)]

    # reader attempt as a PC machine over registers; each step reads
    # the writer-state at its own placement position.  Returns None
    # (retry/abort) or the accepted (version, payload, committed-at).
    def step(pc: int, regs: tuple, wpos: int):
        st = states[wpos]
        h, b = st["head"], st["bufs"]
        if pc == 0:
            if h["seq"] & 1:
                return None
            return (regs + (h["seq"],), 1)            # h1
        if pc == 1:
            return (regs + (h["active"], h["version"]), 2)  # a, hv
        if pc == 2:
            s = b[regs[1]]["seq"]
            if s & 1:
                return None
            return (regs + (s,), 3)                   # b1
        if pc == 3:
            return (regs + (b[regs[1]]["w0"],), 4)    # r0
        if pc == 4:
            return (regs + (b[regs[1]]["w1"],), 5)    # r1
        if pc == 5:
            if b[regs[1]]["ver"] != regs[2]:
                return None
            if not reader_rechecks:
                return (regs, 8)
            return (regs, 6)
        if pc == 6:
            if b[regs[1]]["seq"] != regs[3]:
                return None
            return (regs, 7)
        if pc == 7:
            if h["seq"] != regs[0]:
                return None
            return (regs, 8)
        raise AssertionError(pc)

    findings: List[str] = []
    accepts = 0
    seen = set()
    stack = [(0, (), 0)]
    while stack:
        pc, regs, wpos = stack.pop()
        key = (pc, regs, wpos)
        if key in seen:
            continue
        seen.add(key)
        if pc == 8:
            accepts += 1
            _h1, _a, hv, _b1, r0, r1 = regs[:6]
            committed_now = states[wpos]["committed"]
            want = _PAYLOAD.get(hv)
            if hv > committed_now or (r0, r1) != want:
                if len(findings) < 8:
                    findings.append(
                        f"torn accept at writer step {wpos}: version "
                        f"{hv} payload ({r0}, {r1}) — committed head "
                        f"is {committed_now}, canonical payload "
                        f"{want}")
            continue
        # advance the writer first (or let it die here: the reader
        # step at the same wpos covers the frozen-state case)
        if wpos < len(wops):
            stack.append((pc, regs, wpos + 1))
        nxt = step(pc, regs, wpos)
        if nxt is not None:
            stack.append((nxt[1], nxt[0], wpos))
    if accepts == 0:
        findings.append("the model never completed a read — the "
                        "protocol model is vacuous")
    return {"name": "serve-double-buffer", "accepts": accepts,
            "states": len(seen), "findings": findings}


@registry.rule("serve.torn-read-model", "serve",
               "exhaustive interleavings of one reader against two "
               "publishes (with buffer reuse and writer death at "
               "every step): a completed read only ever returns a "
               "committed version's canonical bytes")
def _run_torn_read_model(report: Report) -> None:
    report.subjects_checked += 1
    res = torn_read_model()
    for msg in res["findings"]:
        report.add(Finding("serve.torn-read-model",
                           "double-buffer[2 publishes]", msg))
    report.metrics["serve.model-states"] = float(res["states"])
    # the knobs must matter: a model that stays clean with the
    # seqlocks dropped is not actually checking the bracket
    broken = torn_read_model(buffer_seqlock=False, header_seqlock=False)
    if not broken["findings"]:
        report.add(Finding(
            "serve.torn-read-model", "double-buffer[no-seqlock]",
            "dropping both seqlocks produced NO torn accept — the "
            "model is not sensitive to the protection it verifies"))


def selftest_serve_campaigns():
    """The ``--self-test`` arm: acceptance-size serve campaigns under
    chaos, clean + non-vacuous + bit-identical on a second run.
    Returns ``(label, result, findings)`` triples."""
    from bluefog_tpu.sim.campaign import run_campaign
    from bluefog_tpu.sim.schedule import Fault, FaultSchedule

    out = []
    for ranks, rounds, seed, kind in SERVE_PINS:
        if kind == "serve_kill":
            sched = FaultSchedule([Fault(kind="serve_kill", step=3,
                                         rank=1, stop=rounds - 10)],
                                  seed=seed)
        elif kind == "serve_pub_kill":
            sched = FaultSchedule([Fault(kind="serve_pub_kill", step=2,
                                         rank=-1, group="payload")],
                                  seed=seed)
        else:
            sched = FaultSchedule(seed=seed)
        cfg, sched, res = serve_campaign(ranks, rounds, seed,
                                         schedule=sched)
        label = f"serve[n={ranks},seed={seed},{kind or 'clean'}]"
        findings = campaign_findings(res, label)
        findings.extend(_serve_path_findings(res, label))
        again = run_campaign(cfg, sched)
        if again.digest != res.digest:
            findings.append(Finding(
                "serve.version-monotone", label,
                f"same-seed serve campaign diverged: "
                f"{res.digest[:16]} != {again.digest[:16]}"))
        out.append((label, res, findings))
    return out
