"""Rule family 2: declarative lint rules over post-partitioner HLO text.

The HLO contract tests (tests/test_hlo_contract*.py) pin each program's
collective inventory by hand; these rules make the same checks
declarative objects that the tests, the CLI, and CI share — one
semantics, three consumers.  Each rule's ``check(text)`` returns
:class:`~bluefog_tpu.analysis.engine.Finding`s over the parsed
instruction stream (``common/hlo_inspect.iter_ops``):

- :class:`CollectiveBudget` — exact (or max) per-opcode collective
  counts, unlisted collectives pinned to zero in exact mode.  The
  O(deg)-gossip story is exactly "collective-permute == #shift classes,
  everything else zero".
- :class:`NoFullAxisAllGather` — no ``all-gather`` result may carry a
  given axis extent in its leading dims; with the stacked-layer count it
  is the "FSDP programs must not re-materialize full parameters" rule
  (the scan-stacked 8B memory story).
- :class:`NoReplicatedLargeBuffer` — no all-gather/broadcast result may
  exceed a byte threshold; catches GSPMD resolutions that replicate a
  big buffer even when the opcode budget still balances.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence

from bluefog_tpu.common.hlo_inspect import (
    COLLECTIVES,
    collective_counts,
    iter_ops,
)

from bluefog_tpu.analysis.engine import Finding, Severity

__all__ = [
    "CollectiveBudget",
    "NoFullAxisAllGather",
    "NoReplicatedLargeBuffer",
    "check_program",
    "assert_clean",
]


@dataclasses.dataclass(frozen=True)
class CollectiveBudget:
    """Per-program collective-count budget.

    ``exact=True`` (the contract-test mode): every listed opcode must
    appear exactly its budgeted number of times and every *unlisted*
    collective exactly zero times.  ``exact=False``: budgets are upper
    bounds and unlisted collectives are unconstrained.
    """

    budgets: Mapping[str, int]
    exact: bool = True
    subject: str = "program"

    def __post_init__(self):
        unknown = set(self.budgets) - set(COLLECTIVES)
        if unknown:
            raise ValueError(
                f"unknown collective opcode(s) {sorted(unknown)}; known: "
                f"{list(COLLECTIVES)}")

    def check_counts(self, counts: Mapping[str, int]) -> List[Finding]:
        out: List[Finding] = []
        for op in COLLECTIVES:
            have = counts.get(op, 0)
            want = self.budgets.get(op, 0 if self.exact else None)
            if want is None:
                continue
            bad = have != want if self.exact else have > want
            if bad:
                rel = "expected exactly" if self.exact else "budget"
                out.append(Finding(
                    "hlo.collective-budget", self.subject,
                    f"{have} x {op} ({rel} {want}); full inventory "
                    f"{dict(counts)}"))
        return out

    def check(self, compiled_text: str) -> List[Finding]:
        return self.check_counts(collective_counts(compiled_text))


@dataclasses.dataclass(frozen=True)
class NoFullAxisAllGather:
    """No all-gather result may carry ``axis_size`` as either of its two
    leading result dims.  With ``axis_size=num_layers`` on a scan-stacked
    FSDP program this is the "no full-parameter re-materialization" rule:
    a gather whose output is ``[layers, ...]`` has reassembled the whole
    stacked leaf outside the layer loop."""

    axis_size: int
    subject: str = "program"

    def check(self, compiled_text: str) -> List[Finding]:
        out: List[Finding] = []
        for op in iter_ops(compiled_text):
            if op.opcode != "all-gather":
                continue
            for _, dims in op.shapes:
                if dims[:1] == (self.axis_size,) or dims[1:2] == (self.axis_size,):
                    out.append(Finding(
                        "hlo.full-axis-all-gather", self.subject,
                        f"all-gather result carries the full axis extent "
                        f"{self.axis_size}: {op.line.strip()[:160]}"))
                    break
        return out


@dataclasses.dataclass(frozen=True)
class NoReplicatedLargeBuffer:
    """No all-gather or broadcast result may exceed ``max_bytes``.

    The opcode budget can balance while a single gather blows the memory
    story (the r5 8B campaign's dominators were exactly this shape);
    byte-bounding the replicating opcodes catches it structurally.
    """

    max_bytes: int
    opcodes: Sequence[str] = ("all-gather", "broadcast")
    subject: str = "program"

    def check(self, compiled_text: str) -> List[Finding]:
        out: List[Finding] = []
        for op in iter_ops(compiled_text):
            if op.opcode not in self.opcodes:
                continue
            nbytes = op.result_bytes()
            if nbytes > self.max_bytes:
                out.append(Finding(
                    "hlo.replicated-large-buffer", self.subject,
                    f"{op.opcode} result is {nbytes / 1e6:.1f} MB "
                    f"(> {self.max_bytes / 1e6:.1f} MB): "
                    f"{op.line.strip()[:160]}"))
        return out


def check_program(compiled_text: str, rules: Sequence) -> List[Finding]:
    """Run a rule set over one compiled program's text."""
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(compiled_text))
    return findings


def assert_clean(compiled_text: str, rules: Sequence) -> None:
    """pytest integration: raise AssertionError listing every finding.

    The HLO contract tests call this instead of hand-rolled count
    asserts, so a test failure and a CLI violation print the same rule
    ids and messages."""
    findings = check_program(compiled_text, rules)
    errors = [f for f in findings if f.severity == Severity.ERROR]
    if errors:
        raise AssertionError(
            "HLO contract violated:\n" + "\n".join(f"  {f}" for f in errors))
