"""Decentralized SPMD train-step builder — the idiomatic TPU path.

This is the flagship composition the whole framework exists for (SURVEY.md
§7 stage 3/6): a single jitted SPMD program in which every rank computes its
local forward/backward on its batch shard and the decentralized optimizer's
gossip (``ppermute`` rounds) is scheduled by XLA *inside* the step —
overlapping communication with compute exactly where the reference relied on
its background thread + per-parameter hooks (SURVEY.md §3.3).

Works on any mesh: flat ``(bf_nodes,)`` for rank-level gossip, factored
``(bf_machines, bf_local)`` for hierarchical.  BatchNorm state stays local
per rank (data-parallel semantics, like the reference); only parameters are
communicated.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from bluefog_tpu.core.basics import LOCAL_AXIS, MACHINES_AXIS, NODES_AXIS
from bluefog_tpu.core.plan import CommPlan
from bluefog_tpu.optim import (
    CommunicationType,
    adapt_then_combine_spmd,
    adapt_with_combine_spmd,
    gradient_allreduce_spmd,
    make_spmd_comm_fn,
)
from bluefog_tpu.telemetry import registry as _telemetry
from bluefog_tpu.timeline import timeline_context

__all__ = [
    "apply_accepts_labels",
    "make_decentralized_train_step",
    "make_lm_loss_fns",
    "replicate_for_mesh",
]


def apply_accepts_labels(apply_fn: Callable) -> bool:
    """True when ``apply_fn`` declares a ``labels`` parameter — the contract
    marker by which train-step builders (here and in ``parallel/zero.py``)
    thread the true targets through to a model that computes its own loss
    (the chunked LM head).  Wrappers around such an apply_fn must preserve
    the ``labels`` parameter or targets silently revert to inputs-as-labels.
    """
    import inspect

    try:
        return "labels" in inspect.signature(apply_fn).parameters
    except (TypeError, ValueError):
        return False


def softmax_cross_entropy(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def make_lm_loss_fns(model):
    """(apply_fn, loss_fn) for LM pretraining with a ``LlamaLM``-style
    model where inputs are their own labels.  The chunked-vs-full choice
    is read off ``model.head_chunks`` — the one place it is configured.

    With ``head_chunks > 1`` the model computes the chunked scalar loss
    itself (``apply(variables, ids, labels=ids)`` — the full
    ``[B, T, vocab]`` logits never materialize) and ``loss_fn`` is the
    identity; otherwise the model returns logits and ``loss_fn`` is the
    standard shifted cross-entropy.  One definition shared by
    ``benchmarks/llama.py`` and ``examples/jax_llama_pretrain.py`` so the
    chunked-loss contract cannot drift between them.
    """
    if getattr(model, "head_chunks", 0) > 1:
        # labels flow through apply (train-step builders detect the
        # ``labels`` parameter and pass them) so masked/instruction-tuning
        # targets are honored, not silently replaced by inputs-as-labels
        # (r3 advisor finding); bare 2-arg calls keep the ids-as-labels
        # LM-pretraining default
        def apply_fn(variables, ids, labels=None):
            return model.apply(variables, ids, labels=ids if labels is None else labels)

        def loss_fn(out, labels):
            return out

    else:

        def apply_fn(variables, ids):
            return model.apply(variables, ids)

        def loss_fn(logits, labels):
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], labels[:, 1:]
            ).mean()

    return apply_fn, loss_fn


def make_decentralized_train_step(
    apply_fn: Callable,
    base_optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    communication_type: CommunicationType = CommunicationType.neighbor_allreduce,
    plan: Optional[CommPlan] = None,
    machine_plan: Optional[CommPlan] = None,
    mode: str = "atc",
    loss_fn: Callable = softmax_cross_entropy,
    has_batch_stats: bool = False,
    num_steps_per_communication: int = 1,
    donate: bool = True,
    steps_per_call: int = 1,
    comm_fuse: bool = False,
):
    """Build ``(init_fn, step_fn)`` for decentralized training on ``mesh``.

    Data layout: every array is *rank-major sharded* — params/opt_state/
    batch leading axis is the global rank axis.  ``step_fn(train_state,
    batch, labels) -> (train_state, metrics)`` with ``train_state =
    (params, batch_stats, opt_state)``.

    The returned functions are jit-compiled once per shape; inside, each
    rank's loss/grad runs on its shard and the optimizer transform carries
    the gossip.

    ``steps_per_call=k`` fuses k FULL training steps (forward, backward,
    optimizer, gossip) into one compiled program; ``batch``/``labels`` then
    carry a leading sub-step axis ``[k, ranks, B, ...]`` and the returned
    loss/acc are the last sub-step's.  On platforms with a fixed per-dispatch
    cost (the tunneled TPU measures ~3.5 ms/call) this amortizes it — ~8%
    ResNet-50 throughput at k=2 — at the price of k× compile time.

    ``comm_fuse`` forwards to the gossip's fusion buffer (one ppermute per
    shift class per dtype group instead of per leaf) — a measured knob,
    see :func:`bluefog_tpu.optim.make_spmd_comm_fn`.
    """
    apply_takes_labels = apply_accepts_labels(apply_fn)

    axes = mesh.axis_names
    if set(axes) == {MACHINES_AXIS, LOCAL_AXIS}:
        spec = P((MACHINES_AXIS, LOCAL_AXIS))
        axis_name = (MACHINES_AXIS, LOCAL_AXIS)
    else:
        spec = P(NODES_AXIS)
        axis_name = NODES_AXIS

    if communication_type == CommunicationType.allreduce:
        if comm_fuse:
            # this branch never reaches make_spmd_comm_fn's guard, so it
            # must raise itself — a silently dropped flag poisons A/Bs
            raise ValueError(
                "comm_fuse=True is only implemented for "
                "neighbor_allreduce, not CommunicationType.allreduce"
            )
        tx = gradient_allreduce_spmd(
            base_optimizer, axis_name, num_steps_per_communication
        )
    else:
        comm_fn = make_spmd_comm_fn(communication_type, plan, machine_plan,
                                    fuse=comm_fuse)
        builder = {"atc": adapt_then_combine_spmd, "awc": adapt_with_combine_spmd}[mode]
        tx = builder(base_optimizer, comm_fn, num_steps_per_communication)

    def local_step(params, batch_stats, opt_state, batch, labels):
        # strip the local rank-major axis (length 1 per device)
        p = jax.tree_util.tree_map(lambda a: a[0], params)
        bs = jax.tree_util.tree_map(lambda a: a[0], batch_stats)
        os_ = jax.tree_util.tree_map(
            lambda a: a[0] if a.ndim >= 1 and a.shape[0] == 1 else a, opt_state
        )
        x, y = batch[0], labels[0]

        if has_batch_stats:

            def loss_of(p_):
                logits, mut = apply_fn(
                    {"params": p_, "batch_stats": bs}, x, mutable=["batch_stats"]
                )
                return loss_fn(logits, y), (logits, mut["batch_stats"])

            (loss, (logits, new_bs)), grads = jax.value_and_grad(
                loss_of, has_aux=True
            )(p)
        else:

            def loss_of(p_):
                if apply_takes_labels:
                    logits = apply_fn({"params": p_}, x, labels=y)
                else:
                    logits = apply_fn({"params": p_}, x)
                return loss_fn(logits, y), logits

            (loss, logits), grads = jax.value_and_grad(loss_of, has_aux=True)(p)
            new_bs = bs

        updates, new_os = tx.update(grads, os_, p)
        new_p = optax.apply_updates(p, updates)
        if logits.ndim >= 2:
            acc = jnp.mean((jnp.argmax(logits, -1) == y).astype(jnp.float32))
        else:
            # apply_fn returned a scalar loss directly (e.g. the chunked
            # LM head, where full logits never exist) — NaN marks the
            # accuracy "not computed" rather than a measured 0%
            acc = jnp.full_like(loss, jnp.nan)
        # re-attach the rank-major axis
        expand = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
        new_os_out = jax.tree_util.tree_map(
            lambda new, old: new[None] if old.ndim >= 1 and old.shape[0] == 1 else new,
            new_os,
            opt_state,
        )
        return (
            expand(new_p),
            expand(new_bs),
            new_os_out,
            expand(loss),
            expand(acc),
        )

    if steps_per_call > 1:
        # k fused steps per dispatch: batch/labels gain a leading sub-step
        # axis, consumed by a python-unrolled loop (lax.scan over a body
        # this size has crashed remote-compile services; unroll is safe)
        def body(params, batch_stats, opt_state, batch, labels):
            for i in range(steps_per_call):
                params, batch_stats, opt_state, loss, acc = local_step(
                    params, batch_stats, opt_state, batch[i], labels[i]
                )
            return params, batch_stats, opt_state, loss, acc

        data_spec = P(None, *spec)

        def _check_substep_axis(batch):
            lead = {a.shape[0] for a in jax.tree_util.tree_leaves(batch)}
            if lead != {steps_per_call}:
                raise ValueError(
                    f"steps_per_call={steps_per_call} needs batch/labels "
                    f"with a leading [{steps_per_call}] sub-step axis; got "
                    f"leading dims {sorted(lead)}"
                )
    else:
        body = local_step
        data_spec = spec

    def _opt_state_spec(opt_state, example_leaf_count):
        del example_leaf_count
        return jax.tree_util.tree_map(
            lambda a: spec if getattr(a, "ndim", 0) >= 1 else P(), opt_state
        )

    def init_fn(params, batch_stats=None):
        """params/batch_stats: rank-major pytrees.  Returns opt_state."""
        p_local = jax.tree_util.tree_map(lambda a: a[0], params)
        os_local = tx.init(p_local)
        n = mesh.devices.size
        # broadcast rank-major leaves across ranks; scalars replicated
        return jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a[None], (n,) + a.shape)
            if getattr(a, "ndim", 0) >= 1
            else a,
            os_local,
        )

    compiled = {}

    def step_fn(params, batch_stats, opt_state, batch, labels):
        if steps_per_call > 1:
            # a [ranks, B, ...] batch here would silently shard the RANK
            # axis as the sub-step axis and train on wrong slices
            _check_substep_axis((batch, labels))
        key = jax.tree_util.tree_structure(opt_state)
        if key not in compiled:
            os_spec = _opt_state_spec(opt_state, None)
            compiled[key] = jax.jit(
                jax.shard_map(
                    body,
                    mesh=mesh,
                    in_specs=(spec, spec, os_spec, data_spec, data_spec),
                    out_specs=(spec, spec, os_spec, spec, spec),
                ),
                donate_argnums=(0, 1, 2) if donate else (),
            )
        reg = _telemetry.get_registry()
        if reg.enabled:
            # one host call may run several fused sub-steps
            reg.counter("train.steps").add(max(1, int(steps_per_call)))
        # step-level span: jitted training records no per-op host spans, so
        # this is where BLUEFOG_TIMELINE traces come from (the reference's
        # per-tensor spans are a background-thread artifact; dispatch of the
        # whole fused step is the honest TPU equivalent)
        with timeline_context("train_step"):
            return compiled[key](params, batch_stats, opt_state, batch, labels)

    return init_fn, step_fn


def replicate_for_mesh(tree, n: int):
    """Replicate a single-rank pytree into rank-major layout [n, ...]."""
    return jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (n,) + a.shape), tree
    )
