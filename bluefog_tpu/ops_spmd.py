"""SPMD collective primitives — the compute core, used inside ``shard_map``.

TPU-native sibling of the reference's controller execution layer
(``MPIController::NeighborAllreduce`` / ``NCCLController::NeighborAllreduce``
in ``bluefog/common/{mpi,nccl}_controller.cc`` [U], SURVEY.md §3.2): where the
reference drains a queue on a background thread, negotiates order and issues
``MPI_Neighbor_allgather``/grouped ``ncclSend/Recv`` plus a local weighted
combine, here each op is a pure traced function — one ``lax.ppermute`` per
shift class of the compiled :class:`~bluefog_tpu.core.plan.CommPlan`, fused
by XLA with the weighted FMA combine, latency-hidden by XLA's async
collective scheduling.

Every function takes the mesh axis name(s) explicitly and works on arbitrary
pytrees.  They are usable directly inside user ``jit``/``shard_map`` code
(the idiomatic TPU path) and are wrapped by :mod:`bluefog_tpu.ops` for the
eager rank-major veneer.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bluefog_tpu.core.plan import CommPlan, PermClass

__all__ = [
    "allreduce",
    "broadcast",
    "allgather",
    "neighbor_allreduce",
    "neighbor_allgather",
    "hierarchical_neighbor_allreduce",
    "pairwise_gossip",
]


def _weight_dtype(x: jnp.ndarray) -> jnp.dtype:
    return x.dtype if jnp.issubdtype(x.dtype, jnp.inexact) else jnp.float32


def allreduce(x, axis_name: str, *, average: bool = True):
    """Global (p)sum/(p)mean over ``axis_name`` (reference ``bf.allreduce``,
    default average=True [U])."""
    op = lax.pmean if average else lax.psum
    return jax.tree_util.tree_map(lambda a: op(a, axis_name), x)


def broadcast(x, root_rank: int, axis_name: str):
    """Every rank gets ``root_rank``'s value (reference ``bf.broadcast`` [U]).

    Lowered as a masked psum — the XLA-native broadcast over a mesh axis.
    """

    def bcast(a):
        idx = lax.axis_index(axis_name)
        wdt = _weight_dtype(a)
        masked = jnp.where(idx == root_rank, a, jnp.zeros_like(a)).astype(wdt)
        return lax.psum(masked, axis_name).astype(a.dtype)

    return jax.tree_util.tree_map(bcast, x)


def allgather(x, axis_name: str):
    """Concatenate every rank's tensor along a new leading axis
    (reference ``bf.allgather`` concatenates along axis 0 [U]; reshape the
    leading two axes to recover exactly that layout)."""
    return jax.tree_util.tree_map(
        lambda a: lax.all_gather(a, axis_name, axis=0, tiled=False), x
    )


def _class_arrays(cls: PermClass, wdt):
    rw = jnp.asarray(cls.recv_weights, dtype=wdt)
    return rw


def neighbor_allreduce(
    x,
    plan: CommPlan,
    axis_name: str,
    *,
    self_weight: Optional[float] = None,
    average_dtype=None,
    fuse: bool = False,
    rank_index=None,
):
    """Weighted neighbor averaging: ``out_d = w_dd * x_d + sum_{s in N_in(d)}
    w_ds * x_s`` — the reference's hot path (SURVEY.md §3.2).

    One ``ppermute`` per shift class; the per-rank weights ride as trace-time
    constant vectors indexed by ``axis_index`` so a single compiled program
    serves every rank (SPMD).  ``self_weight`` overrides the plan's per-rank
    self weights uniformly.

    ``rank_index`` optionally supplies this rank's index along
    ``axis_name`` as a traced scalar (e.g. the caller's shard of a
    mesh-sharded iota).  Inside a PARTIALLY-manual ``shard_map`` (some
    mesh axes still auto) ``lax.axis_index`` lowers to a
    ``partition-id`` instruction, which the SPMD partitioner rejects
    on some backends (CPU raises UNIMPLEMENTED); a sharded-iota
    operand is the partitioner-friendly spelling of the same value.

    ``fuse=True`` packs same-dtype leaves into ONE flat buffer before
    permuting — the reference's fusion buffer (``BLUEFOG_FUSION_THRESHOLD``,
    ``operations.cc`` [U]) realized on the SPMD path: exactly one ppermute
    per (shift class, dtype group) regardless of pytree width, GUARANTEED
    rather than left to XLA's collective combiner (which merges same-shaped
    permutes but leaves odd-shaped scalars — e.g. a push-sum weight —
    riding their own collective).  Exact: the weighted combine is linear
    and the per-edge weights are leaf-independent.  Output leaves are in
    their accumulation dtype, same as the unfused path.
    """

    def nar(a):
        wdt = average_dtype or _weight_dtype(a)
        idx = lax.axis_index(axis_name) if rank_index is None else rank_index
        if self_weight is None:
            sw = jnp.asarray(plan.self_weights, dtype=wdt)[idx]
        else:
            sw = jnp.asarray(self_weight, dtype=wdt)
        acc = a.astype(wdt) * sw
        # permute in the NARROWER of storage/average dtype: bf16 params with
        # fp32 accumulation send 2 bytes/elem over ICI (the neighbor's exact
        # stored value either way), and an explicit narrow average_dtype
        # still shrinks the wire for wide params
        wire = a if a.dtype.itemsize <= jnp.dtype(wdt).itemsize else a.astype(wdt)
        for cls in plan.classes:
            recvd = lax.ppermute(wire, axis_name, cls.perm).astype(wdt)
            w = jnp.asarray(cls.recv_weights, dtype=wdt)[idx]
            acc = acc + w * recvd
        return acc

    leaves, treedef = jax.tree_util.tree_flatten(x)
    if fuse and len(leaves) > 1:
        groups = {}  # dtype -> leaf positions, insertion-ordered
        for i, leaf in enumerate(leaves):
            groups.setdefault(jnp.dtype(leaf.dtype), []).append(i)
        out = [None] * len(leaves)
        for idxs in groups.values():
            mixed = nar(jnp.concatenate([leaves[i].ravel() for i in idxs]))
            off = 0
            for i in idxs:
                n = leaves[i].size
                out[i] = mixed[off:off + n].reshape(leaves[i].shape)
                off += n
        return jax.tree_util.tree_unflatten(treedef, out)
    return jax.tree_util.tree_map(nar, x)


def neighbor_allgather(x, plan: CommPlan, axis_name: str):
    """Gather in-neighbor tensors, stacked on a new leading axis ordered by
    ascending source rank (reference ``bf.neighbor_allgather`` concatenation
    order [U]).

    SPMD requires static shapes, so the output leading dim is the *max*
    in-degree; ranks with smaller in-degree have zero-padded trailing slots
    (plan.in_degrees gives the valid count — exact for regular topologies,
    which all built-in constructors are).
    """
    maxd = plan.max_in_degree

    def nag(a):
        idx = lax.axis_index(axis_name)
        out = jnp.zeros((maxd,) + a.shape, dtype=a.dtype)
        for cls in plan.classes:
            recvd = lax.ppermute(a, axis_name, cls.perm)
            slot = jnp.asarray(cls.slot_index)[idx]
            valid = jnp.asarray(cls.recv_mask)[idx].astype(bool)
            updated = lax.dynamic_update_index_in_dim(
                out, recvd, jnp.maximum(slot, 0), axis=0
            )
            out = jnp.where(valid, updated, out)
        return out

    return jax.tree_util.tree_map(nag, x)


def hierarchical_neighbor_allreduce(
    x,
    machine_plan: CommPlan,
    machines_axis: str,
    local_axis: str,
    *,
    self_weight: Optional[float] = None,
):
    """Intra-machine average -> machine-level gossip -> (implicit) local
    broadcast (reference ``bf.hierarchical_neighbor_allreduce``: local
    allreduce, cross-machine neighbor exchange, local bcast — SURVEY.md
    §2.1 NCCL-controller row [U]).

    On the factored ``(machines, local)`` mesh the local pmean already leaves
    every local rank with the machine value, so the machine-level gossip
    runs replicated across the local axis and no final broadcast is needed.
    """

    def hnar(a):
        wdt = _weight_dtype(a)
        local_avg = lax.pmean(a.astype(wdt), local_axis)
        return neighbor_allreduce(
            local_avg, machine_plan, machines_axis, self_weight=self_weight
        )

    return jax.tree_util.tree_map(hnar, x)


def pairwise_gossip(
    x,
    send_to: Tuple[Tuple[int, int], ...],
    size: int,
    axis_name: str,
    *,
    self_weight: float = 0.5,
    peer_weight: float = 0.5,
):
    """One-peer dynamic gossip step: a single ``ppermute`` along the given
    (src, dst) pairs plus weighted combine — the lowering of the reference's
    dynamic one-peer topologies (``GetDynamicOnePeerSendRecvRanks`` [U]).

    Ranks that receive nothing this step keep their value (weight 1)."""
    recv_ranks = {d for _, d in send_to}
    mask_host = [1.0 if d in recv_ranks else 0.0 for d in range(size)]

    def g(a):
        wdt = _weight_dtype(a)
        recvd = lax.ppermute(a, axis_name, send_to).astype(wdt)
        idx = lax.axis_index(axis_name)
        mask = jnp.asarray(mask_host, dtype=wdt)[idx]
        keep = self_weight + (1.0 - mask) * peer_weight
        return keep * a.astype(wdt) + (mask * peer_weight) * recvd

    return jax.tree_util.tree_map(g, x)
