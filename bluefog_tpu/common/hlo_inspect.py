"""Compiled-program inspection helpers: collective inventory and memory.

The HLO perf contracts (tests/test_hlo_contract*.py) and the memory
contracts (tests/test_memory_contract.py) both pin properties of the
POST-PARTITIONER program — the strongest multi-chip evidence obtainable
without multi-chip hardware, and a tripwire against GSPMD/scheduler
regressions on jax upgrades.  The reference's analogue is asserting which
MPI calls a collective op issues (``mpi_controller.cc`` [U]); here the
"calls" are XLA collective opcodes and the buffer assignment.
"""

from __future__ import annotations

import dataclasses
import re
from collections import Counter
from typing import Iterator, Tuple

COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# opcode sits after `=` and the (possibly tuple) result type
_OP_RE = re.compile(r"=\s*(?:\([^)]*\)|[^\s(]+)\s+([a-z][a-z0-9\-]*)\(")

# one instruction line: result name `=` result type(s) opcode `(`
_LINE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\([^)]*\)|[^\s(]+)\s+"
    r"([a-z][a-z0-9\-]*)\(")

# a typed shape inside a result type, e.g. ``bf16[6,64,128]{2,1,0}``
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1,
}


@dataclasses.dataclass(frozen=True)
class HloOp:
    """One parsed HLO instruction: opcode (``-start`` forms normalized to
    the base opcode, ``-done`` forms dropped by :func:`iter_ops`'s
    collective filter), its result shapes, and the raw line."""

    opcode: str
    shapes: Tuple[Tuple[str, Tuple[int, ...]], ...]  # (dtype, dims) per result
    line: str

    def result_bytes(self) -> int:
        """Total bytes across result shapes (0 for unknown dtypes)."""
        total = 0
        for dtype, dims in self.shapes:
            n = 1
            for d in dims:
                n *= d
            total += n * _DTYPE_BYTES.get(dtype, 0)
        return total


def iter_ops(compiled_text: str) -> Iterator[HloOp]:
    """Parse every instruction line of ``compiled.as_text()`` into an
    :class:`HloOp`.  Async ``-done`` instructions are skipped and
    ``-start`` opcodes are normalized, mirroring :func:`collective_counts`
    so shape-aware rules and the counter can never disagree on what is
    one logical op."""
    for line in compiled_text.splitlines():
        m = _LINE_RE.match(line)
        if not m:
            continue
        op = m.group(2)
        if op.endswith("-done"):
            continue
        if op.endswith("-start"):
            op = op[: -len("-start")]
        shapes = tuple(
            (dt, tuple(int(x) for x in dims.split(",") if x))
            for dt, dims in _SHAPE_RE.findall(m.group(1))
        )
        yield HloOp(opcode=op, shapes=shapes, line=line)


def collective_ops(compiled_text: str) -> list:
    """The :data:`COLLECTIVES` subset of :func:`iter_ops`."""
    return [op for op in iter_ops(compiled_text) if op.opcode in COLLECTIVES]


def collective_counts(compiled_text: str) -> Counter:
    """Count collective opcodes in ``compiled.as_text()``.

    ``-start`` forms count once; ``-done`` forms are ignored (async
    collectives appear as a start/done pair for one logical op).
    """
    counts = Counter()
    for m in _OP_RE.finditer(compiled_text):
        op = m.group(1)
        if op.endswith("-done"):
            continue
        if op.endswith("-start"):
            op = op[: -len("-start")]
        if op in COLLECTIVES:
            counts[op] += 1
    return counts


def memory_bytes(compiled) -> dict:
    """Per-DEVICE byte accounting from XLA's buffer assignment.

    The SPMD module is the per-device program, so these numbers are what
    one chip's HBM must hold: ``arguments`` (live inputs), ``outputs``,
    ``aliased`` (donated in/out pairs, counted once), ``temps`` (peak
    intermediate liveness under the chosen schedule), and
    ``live_peak_upper_bound = arguments + outputs - aliased + temps``.
    """
    ma = compiled.memory_analysis()
    live = (ma.argument_size_in_bytes + ma.output_size_in_bytes
            - ma.alias_size_in_bytes + ma.temp_size_in_bytes)
    return {
        "arguments": ma.argument_size_in_bytes,
        "outputs": ma.output_size_in_bytes,
        "aliased": ma.alias_size_in_bytes,
        "temps": ma.temp_size_in_bytes,
        "live_peak_upper_bound": live,
    }
