"""Compiled-program inspection helpers: collective inventory and memory.

The HLO perf contracts (tests/test_hlo_contract*.py) and the memory
contracts (tests/test_memory_contract.py) both pin properties of the
POST-PARTITIONER program — the strongest multi-chip evidence obtainable
without multi-chip hardware, and a tripwire against GSPMD/scheduler
regressions on jax upgrades.  The reference's analogue is asserting which
MPI calls a collective op issues (``mpi_controller.cc`` [U]); here the
"calls" are XLA collective opcodes and the buffer assignment.
"""

from __future__ import annotations

import re
from collections import Counter

COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)

# opcode sits after `=` and the (possibly tuple) result type
_OP_RE = re.compile(r"=\s*(?:\([^)]*\)|[^\s(]+)\s+([a-z][a-z0-9\-]*)\(")


def collective_counts(compiled_text: str) -> Counter:
    """Count collective opcodes in ``compiled.as_text()``.

    ``-start`` forms count once; ``-done`` forms are ignored (async
    collectives appear as a start/done pair for one logical op).
    """
    counts = Counter()
    for m in _OP_RE.finditer(compiled_text):
        op = m.group(1)
        if op.endswith("-done"):
            continue
        if op.endswith("-start"):
            op = op[: -len("-start")]
        if op in COLLECTIVES:
            counts[op] += 1
    return counts


def memory_bytes(compiled) -> dict:
    """Per-DEVICE byte accounting from XLA's buffer assignment.

    The SPMD module is the per-device program, so these numbers are what
    one chip's HBM must hold: ``arguments`` (live inputs), ``outputs``,
    ``aliased`` (donated in/out pairs, counted once), ``temps`` (peak
    intermediate liveness under the chosen schedule), and
    ``live_peak_upper_bound = arguments + outputs - aliased + temps``.
    """
    ma = compiled.memory_analysis()
    live = (ma.argument_size_in_bytes + ma.output_size_in_bytes
            - ma.alias_size_in_bytes + ma.temp_size_in_bytes)
    return {
        "arguments": ma.argument_size_in_bytes,
        "outputs": ma.output_size_in_bytes,
        "aliased": ma.alias_size_in_bytes,
        "temps": ma.temp_size_in_bytes,
        "live_peak_upper_bound": live,
    }
