"""Logging shim: the reference's C++ ``BFLOG``/``BLUEFOG_LOG_LEVEL`` macros
(``bluefog/common/logging.h`` [U], SURVEY.md §5.5) mapped onto stdlib logging."""

from __future__ import annotations

import logging
import os

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logger = logging.getLogger("bluefog_tpu")
if not logger.handlers:
    _h = logging.StreamHandler()
    _h.setFormatter(
        logging.Formatter("[%(asctime)s %(levelname)s bluefog_tpu] %(message)s")
    )
    logger.addHandler(_h)
logger.setLevel(
    _LEVELS.get(os.environ.get("BLUEFOG_LOG_LEVEL", "warn").lower(), logging.WARNING)
)
