"""Environment-variable configuration, mirroring the reference's env surface.

The reference has no config files — behaviour is driven by ``BLUEFOG_*`` env
vars (SURVEY.md §5.6: ``BLUEFOG_LOG_LEVEL``, ``BLUEFOG_TIMELINE``,
``BLUEFOG_FUSION_THRESHOLD``, ``BLUEFOG_CYCLE_TIME``).  We keep the same
names.  Fusion/cycle knobs are accepted-but-inert: XLA fuses and schedules
collectives itself, so they exist only so reference-era launch scripts do
not break (a warning is logged when they are set to non-defaults).  The
*capability* the fusion buffer provided — one exchange for many tensors —
is an explicit API here instead of a byte threshold: pass a pytree to
``win_create`` (one packed window) or use the fused optimizer modes.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    try:
        return int(v) if v is not None else default
    except ValueError:
        return default


@dataclasses.dataclass
class Config:
    log_level: str = "warn"
    timeline_path: Optional[str] = None
    # Inert-on-TPU knobs kept for launch-script parity (see module docstring).
    fusion_threshold: int = 64 * 1024 * 1024
    cycle_time_ms: float = 0.0
    # Window-op staleness bound (steps a rank may run ahead before the
    # mailbox exchange synchronizes); ours, not the reference's.
    win_staleness_bound: int = 1

    @classmethod
    def from_env(cls) -> "Config":
        return cls(
            log_level=os.environ.get("BLUEFOG_LOG_LEVEL", "warn").lower(),
            timeline_path=os.environ.get("BLUEFOG_TIMELINE") or None,
            fusion_threshold=_env_int("BLUEFOG_FUSION_THRESHOLD", 64 * 1024 * 1024),
            cycle_time_ms=float(os.environ.get("BLUEFOG_CYCLE_TIME", "0") or 0),
            win_staleness_bound=_env_int("BLUEFOG_WIN_STALENESS_BOUND", 1),
        )
