"""Per-rank live status pages + the job trace-control word.

The status page is the read side of the live introspection plane
(docs/OBSERVABILITY.md "Live introspection"): every island rank keeps one
small versioned mmap struct next to its shm segments
(``bf_<job>_status_r<rank>``) and republishes it once per window op —
current step/round, membership epoch, last op + op_id, per-edge
:mod:`EdgeHealth <bluefog_tpu.resilience.detector>` state and deadline,
and the mass-ledger totals.  The page is seqlock'd exactly like the
mailbox slots (seq → odd, payload, seq → even), so an external reader
(``bftpu-top``) NEVER blocks or perturbs the writer: it just retries a
torn bracket.  Pages ride the ``seg_name`` prefix, so
:func:`bluefog_tpu.native.shm_native.unlink_all` reclaims them.

The trace-control word (``bf_<job>_tracectl``) is the write side: a
(generation, mode) pair published by atomic rename — the same idiom as
the membership-epoch word — that lets ``bftpu-top trace on|off`` flip
``BFTPU_TRACING`` inside running ranks without a restart.
"""

from __future__ import annotations

import glob
import math
import os
import struct
import time
from typing import Dict, List, Optional, Tuple

from bluefog_tpu.native import shm_native

STATUS_SCHEMA = "bftpu-statuspage/8"
STATUS_MAGIC = 0x42465350  # "BFSP"
STATUS_VERSION = 8

#: Page layout: header (magic u32, version u32, seq u64), fixed block,
#: then up to MAX_EDGES edge records; the whole page is padded to
#: PAGE_BYTES so the file size is stable across republishes.
#: v2 appends the progress-engine view (queue depth + in-flight op) to
#: the fixed block; v3 appends the convergence-probe word (consensus
#: error + probe round); v4 appends the flags word (bit 0 = ORPHAN:
#: this rank lost membership quorum and quiesced — see
#: docs/RESILIENCE.md "Orphan quiesce"); v5 appends the serving plane
#: (serve_version + serve_lag — the snapshot version a publisher last
#: committed / a replica currently serves, and how many committed
#: versions the replica trails; -1/-1 = not part of the serve plane,
#: see docs/SERVING.md); v6 appends the distribution tree
#: (distrib_slot + distrib_parent — this replica's slot in the fan-out
#: tree and the slot it feeds from, -1 parent = the publisher itself;
#: slot -1 = not attached through the distribution plane, see
#: docs/SERVING.md "Cross-host distribution"); v7 appends the
#: request-level serve telemetry (qps + p50_ms + p99_ms over the
#: replica's rolling request window, and slo_state: -1 = no SLO armed
#: or no traffic yet, 0 = inside the BFTPU_SERVE_SLO_MS objective,
#: 1 = currently violating — see docs/SERVING.md "Measuring serve
#: latency under churn"); v8 appends the fleet-monitor alert lamp
#: (alert_state: -1 = no monitor attached / no samples yet, 0 =
#: sampled and quiet, 1 = an alert window is open, plus the 16-byte
#: last-alert rule name — written only by the monitor's own page at
#: MONITOR_RANK, every worker page reads -1/"" — see
#: docs/OBSERVABILITY.md "Fleet monitor").  Readers still decode
#: v1..v7 pages from live older writers.
_HEAD = struct.Struct("<IIQ")                 # magic, version, seq
_FIXED_V1 = struct.Struct("<iiiiQQQdd16sdddd")  # rank, nranks, pid, n_edges,
#                                                 step, epoch, op_id,
#                                                 wall_ts, mono_ts, last_op,
#                                                 ledger dep/col/drn/pend
_FIXED_V2 = struct.Struct("<iiiiQQQdd16sddddi16s")  # ... + qdepth, inflight
_FIXED_V3 = struct.Struct("<iiiiQQQdd16sddddi16sdq")  # ... + conv_err,
#                                                         conv_round
_FIXED_V4 = struct.Struct("<iiiiQQQdd16sddddi16sdqi")  # ... + flags
_FIXED_V5 = struct.Struct("<iiiiQQQdd16sddddi16sdqiqq")  # ... +
#                                               serve_version, serve_lag
_FIXED_V6 = struct.Struct("<iiiiQQQdd16sddddi16sdqiqqii")  # ... +
#                                               distrib_slot, distrib_parent
_FIXED_V7 = struct.Struct("<iiiiQQQdd16sddddi16sdqiqqiidddi")  # ... +
#                                               qps, p50_ms, p99_ms,
#                                               slo_state
_FIXED = struct.Struct("<iiiiQQQdd16sddddi16sdqiqqiidddii16s")  # ... +
#                                               alert_state, last_alert
_EDGE = struct.Struct("<iid")                 # peer_global, state, deadline_s
MAX_EDGES = 32
PAGE_BYTES = 1024
#: flags-word bits (v4)
FLAG_ORPHAN = 1
assert _HEAD.size + _FIXED.size + MAX_EDGES * _EDGE.size <= PAGE_BYTES

#: EdgeHealth state codes as written into edge records (3 = demoted is
#: an islands-level overlay the detector itself does not track).
EDGE_STATE_NAMES = {0: "alive", 1: "suspect", 2: "dead", 3: "demoted"}

_LEDGER_KEYS = ("deposits", "collected", "drained", "pending")


class TornPageError(RuntimeError):
    """A status page stayed torn (odd/moving seq) across every retry."""


def status_page_path(job: str, rank: int) -> str:
    return os.path.join(
        shm_native._FALLBACK_DIR,
        shm_native.seg_name(job, f"status_r{int(rank)}")[1:])


class StatusPage:
    """The writer: owned by one rank, republished once per window op.

    ``publish`` is a few ``pack_into`` calls on an mmap — no locks, no
    syscalls — which is what keeps the always-on plane under the < 2%
    ``statuspage_overhead_pct`` bench gate."""

    def __init__(self, job: str, rank: int):
        self.job = str(job)
        self.rank = int(rank)
        self._seg = shm_native._FallbackSegment(
            status_page_path(job, rank), PAGE_BYTES)
        self._seq = 0
        _HEAD.pack_into(self._seg._mm, 0, STATUS_MAGIC, STATUS_VERSION, 0)

    def publish(self, *, nranks: int, step: int, epoch: int, op_id: int,
                last_op: str = "", ledger: Optional[Dict[str, float]] = None,
                edges=(), qdepth: int = -1, inflight: str = "",
                conv_err: float = -1.0, conv_round: int = -1,
                flags: int = 0, serve_version: int = -1,
                serve_lag: int = -1, distrib_slot: int = -1,
                distrib_parent: int = -1, qps: float = -1.0,
                p50_ms: float = -1.0, p99_ms: float = -1.0,
                slo_state: int = -1, alert_state: int = -1,
                last_alert: str = "") -> None:
        """Seqlocked single-writer update of the whole page.

        ``edges`` is an iterable of ``(peer_global, state_code,
        deadline_s)`` tuples (truncated at MAX_EDGES); ``ledger`` maps
        the ``_LEDGER_KEYS`` to mass totals (missing keys read 0.0);
        ``qdepth``/``inflight`` mirror the rank's progress engine
        (-1 = no engine running); ``conv_err``/``conv_round`` mirror
        the convergence probe (round -1 = probe off); ``flags`` is the
        v4 bit set (``FLAG_ORPHAN`` = quorum lost, rank quiesced);
        ``serve_version``/``serve_lag`` are the v5 serving plane
        (-1 = this rank neither publishes nor serves snapshots);
        ``distrib_slot``/``distrib_parent`` are the v6 distribution
        tree (slot -1 = not attached through the distribution plane,
        parent -1 = fed straight by the publisher);
        ``qps``/``p50_ms``/``p99_ms``/``slo_state`` are the v7
        request-level serve telemetry (-1 = no request traffic
        observed; slo_state 0 = within the latency SLO, 1 =
        violating); ``alert_state``/``last_alert`` are the v8 fleet-
        monitor lamp (-1 = this page is not a monitor / no samples
        yet; only the monitor daemon's page at MONITOR_RANK writes
        them)."""
        mm = self._seg._mm
        led = ledger or {}
        ed = list(edges)[:MAX_EDGES]
        self._seq += 1  # -> odd: readers retry from here on
        struct.pack_into("<Q", mm, 8, self._seq)
        _FIXED.pack_into(
            mm, _HEAD.size,
            self.rank, int(nranks), os.getpid(), len(ed),
            int(step) & 0xFFFFFFFFFFFFFFFF,
            int(epoch) & 0xFFFFFFFFFFFFFFFF,
            int(op_id) & 0xFFFFFFFFFFFFFFFF,
            time.time(), time.monotonic(),
            str(last_op).encode("utf-8", "replace")[:16],
            float(led.get("deposits", 0.0)), float(led.get("collected", 0.0)),
            float(led.get("drained", 0.0)), float(led.get("pending", 0.0)),
            int(qdepth),
            str(inflight).encode("utf-8", "replace")[:16],
            float(conv_err), int(conv_round), int(flags),
            int(serve_version), int(serve_lag),
            int(distrib_slot), int(distrib_parent),
            float(qps), float(p50_ms), float(p99_ms), int(slo_state),
            int(alert_state),
            str(last_alert).encode("utf-8", "replace")[:16])
        off = _HEAD.size + _FIXED.size
        for peer, state, deadline in ed:
            _EDGE.pack_into(mm, off, int(peer), int(state), float(deadline))
            off += _EDGE.size
        self._seq += 1  # -> even: page consistent again
        struct.pack_into("<Q", mm, 8, self._seq)

    def close(self, unlink: bool = False) -> None:
        self._seg.close(unlink)


def _decode(buf: bytes) -> Dict[str, object]:
    magic, version, seq = _HEAD.unpack_from(buf, 0)
    if magic != STATUS_MAGIC:
        raise ValueError(f"not a status page (magic 0x{magic:08x})")
    if version not in (1, 2, 3, 4, 5, 6, 7, STATUS_VERSION):
        raise ValueError(f"unsupported status-page version {version}")
    if version == 1:
        # a live v1 writer (mid-upgrade fleet): no progress-engine block
        (rank, nranks, pid, n_edges, step, epoch, op_id, wall_ts, mono_ts,
         last_op, dep, col, drn, pend) = _FIXED_V1.unpack_from(
            buf, _HEAD.size)
        qdepth, inflight = -1, b""
        conv_err, conv_round = -1.0, -1
        flags = 0
        serve_version, serve_lag = -1, -1
        distrib_slot, distrib_parent = -1, -1
        qps, p50_ms, p99_ms, slo_state = -1.0, -1.0, -1.0, -1
        alert_state, last_alert = -1, b""
        fixed_size = _FIXED_V1.size
    elif version == 2:
        # a live v2 writer: progress block, no convergence word
        (rank, nranks, pid, n_edges, step, epoch, op_id, wall_ts, mono_ts,
         last_op, dep, col, drn, pend, qdepth, inflight) = \
            _FIXED_V2.unpack_from(buf, _HEAD.size)
        conv_err, conv_round = -1.0, -1
        flags = 0
        serve_version, serve_lag = -1, -1
        distrib_slot, distrib_parent = -1, -1
        qps, p50_ms, p99_ms, slo_state = -1.0, -1.0, -1.0, -1
        alert_state, last_alert = -1, b""
        fixed_size = _FIXED_V2.size
    elif version == 3:
        # a live v3 writer: convergence word, no flags word
        (rank, nranks, pid, n_edges, step, epoch, op_id, wall_ts, mono_ts,
         last_op, dep, col, drn, pend, qdepth, inflight,
         conv_err, conv_round) = _FIXED_V3.unpack_from(buf, _HEAD.size)
        flags = 0
        serve_version, serve_lag = -1, -1
        distrib_slot, distrib_parent = -1, -1
        qps, p50_ms, p99_ms, slo_state = -1.0, -1.0, -1.0, -1
        alert_state, last_alert = -1, b""
        fixed_size = _FIXED_V3.size
    elif version == 4:
        # a live v4 writer: flags word, no serving plane
        (rank, nranks, pid, n_edges, step, epoch, op_id, wall_ts, mono_ts,
         last_op, dep, col, drn, pend, qdepth, inflight,
         conv_err, conv_round, flags) = _FIXED_V4.unpack_from(
            buf, _HEAD.size)
        serve_version, serve_lag = -1, -1
        distrib_slot, distrib_parent = -1, -1
        qps, p50_ms, p99_ms, slo_state = -1.0, -1.0, -1.0, -1
        alert_state, last_alert = -1, b""
        fixed_size = _FIXED_V4.size
    elif version == 5:
        # a live v5 writer: serving plane, no distribution tree
        (rank, nranks, pid, n_edges, step, epoch, op_id, wall_ts, mono_ts,
         last_op, dep, col, drn, pend, qdepth, inflight,
         conv_err, conv_round, flags,
         serve_version, serve_lag) = _FIXED_V5.unpack_from(
            buf, _HEAD.size)
        distrib_slot, distrib_parent = -1, -1
        qps, p50_ms, p99_ms, slo_state = -1.0, -1.0, -1.0, -1
        alert_state, last_alert = -1, b""
        fixed_size = _FIXED_V5.size
    elif version == 6:
        # a live v6 writer: distribution tree, no request telemetry
        (rank, nranks, pid, n_edges, step, epoch, op_id, wall_ts, mono_ts,
         last_op, dep, col, drn, pend, qdepth, inflight,
         conv_err, conv_round, flags,
         serve_version, serve_lag,
         distrib_slot, distrib_parent) = _FIXED_V6.unpack_from(
            buf, _HEAD.size)
        qps, p50_ms, p99_ms, slo_state = -1.0, -1.0, -1.0, -1
        alert_state, last_alert = -1, b""
        fixed_size = _FIXED_V6.size
    elif version == 7:
        # a live v7 writer: request telemetry, no alert lamp
        (rank, nranks, pid, n_edges, step, epoch, op_id, wall_ts, mono_ts,
         last_op, dep, col, drn, pend, qdepth, inflight,
         conv_err, conv_round, flags,
         serve_version, serve_lag,
         distrib_slot, distrib_parent,
         qps, p50_ms, p99_ms, slo_state) = _FIXED_V7.unpack_from(
            buf, _HEAD.size)
        alert_state, last_alert = -1, b""
        fixed_size = _FIXED_V7.size
    else:
        (rank, nranks, pid, n_edges, step, epoch, op_id, wall_ts, mono_ts,
         last_op, dep, col, drn, pend, qdepth, inflight,
         conv_err, conv_round, flags,
         serve_version, serve_lag,
         distrib_slot, distrib_parent,
         qps, p50_ms, p99_ms, slo_state,
         alert_state, last_alert) = _FIXED.unpack_from(buf, _HEAD.size)
        fixed_size = _FIXED.size
    edges: List[Dict[str, object]] = []
    off = _HEAD.size + fixed_size
    for _ in range(max(0, min(n_edges, MAX_EDGES))):
        peer, state, deadline = _EDGE.unpack_from(buf, off)
        off += _EDGE.size
        edges.append({
            "peer": peer,
            "state": EDGE_STATE_NAMES.get(state, str(state)),
            "deadline_s": deadline,
        })
    return {
        "schema": STATUS_SCHEMA,
        "version": version,
        "seq": seq,
        "rank": rank,
        "nranks": nranks,
        "pid": pid,
        "step": step,
        "epoch": epoch,
        "op_id": op_id,
        "last_op": last_op.split(b"\0", 1)[0].decode("utf-8", "replace"),
        "wall_ts": wall_ts,
        "mono_ts": mono_ts,
        "ledger": {
            "deposits": dep, "collected": col,
            "drained": drn, "pending": pend,
            "balance": dep - col - drn,
        },
        "progress": {
            "qdepth": int(qdepth),
            "inflight": inflight.split(b"\0", 1)[0].decode(
                "utf-8", "replace"),
        },
        # the convergence probe's word (bluefog_tpu.lab): err is the
        # debiased consensus-error sample at probe round `round`;
        # round < 0 = probe off (or a pre-v3 writer), err NaN = the
        # probe's first round (a difference needs a predecessor)
        "conv": {
            # non-finite (a NaN first-round sample) sanitized to -1.0 so
            # collect()'s payload stays strict-JSON serializable
            "err": float(conv_err) if math.isfinite(conv_err) else -1.0,
            "round": int(conv_round),
        },
        "flags": int(flags),
        # quorum-lost quiesce (docs/RESILIENCE.md "Orphan quiesce")
        "orphan": bool(int(flags) & FLAG_ORPHAN),
        # the serving plane (docs/SERVING.md): a publisher's last
        # committed version (lag 0) or a replica's served version and
        # trail; version < 0 = this rank is not part of the serve plane
        "serve": {
            "version": int(serve_version),
            "lag": int(serve_lag),
            # v7 request telemetry over the replica's rolling window:
            # qps/p50/p99 read -1.0 while no request traffic has been
            # observed; slo_state -1 = no SLO armed (or no traffic),
            # 0 = within BFTPU_SERVE_SLO_MS, 1 = currently violating.
            # Non-finite values sanitized so collect() stays strict-JSON.
            "qps": float(qps) if math.isfinite(qps) else -1.0,
            "p50_ms": float(p50_ms) if math.isfinite(p50_ms) else -1.0,
            "p99_ms": float(p99_ms) if math.isfinite(p99_ms) else -1.0,
            "slo_state": int(slo_state),
        },
        # the distribution tree (docs/SERVING.md "Cross-host
        # distribution"): slot -1 = not attached through the distrib
        # plane; parent -1 = fed straight by the publisher
        "distrib": {
            "slot": int(distrib_slot),
            "parent": int(distrib_parent),
        },
        # the fleet-monitor lamp (v8, docs/OBSERVABILITY.md "Fleet
        # monitor"): only the monitor daemon's own page (MONITOR_RANK)
        # writes it; state -1 = not a monitor page (or a pre-v8
        # writer), 0 = sampled and quiet, 1 = an alert window is open,
        # last = the most recent alert's rule name
        "alert": {
            "state": int(alert_state),
            "last": last_alert.split(b"\0", 1)[0].decode(
                "utf-8", "replace"),
        },
        "edges": edges,
    }


def read_status_page(path: str, retries: int = 8) -> Dict[str, object]:
    """Seqlock reader: two whole-page reads bracketing one seq — accept
    the first buffer iff both seqs are the same even number; otherwise a
    write was in flight, so retry.  Raises :class:`TornPageError` when
    the page never settles (a stuck mid-write writer) and ``ValueError``
    on a bad magic/version."""
    last = None
    for _ in range(max(1, retries)):
        with open(path, "rb") as f:
            buf1 = f.read(PAGE_BYTES)
        if len(buf1) < _HEAD.size + _FIXED.size:
            raise ValueError(f"truncated status page {path}")
        seq1 = struct.unpack_from("<Q", buf1, 8)[0]
        if seq1 % 2 == 0:
            with open(path, "rb") as f:
                buf2 = f.read(PAGE_BYTES)
            seq2 = struct.unpack_from("<Q", buf2, 8)[0]
            if seq1 == seq2:
                return _decode(buf1)
        last = seq1
        time.sleep(0.001)
    raise TornPageError(f"status page {path} torn across retries "
                        f"(last seq {last})")


def find_status_pages(job: str) -> Dict[int, str]:
    """``{rank: path}`` of every status page the job has published (both
    the shm dir and any configured fallback dir are searched)."""
    prefix = shm_native.seg_name(job, "status_r")[1:]
    out: Dict[int, str] = {}
    for d in {"/dev/shm", shm_native._FALLBACK_DIR}:
        for path in glob.glob(os.path.join(d, prefix + "*")):
            tail = os.path.basename(path)[len(prefix):]
            if tail.isdigit():
                out[int(tail)] = path
    return out


def read_fleet(job: str) -> Dict[int, Dict[str, object]]:
    """Every readable status page of the job; unreadable/torn pages map
    to ``{"error": ...}`` instead of failing the whole attach."""
    fleet: Dict[int, Dict[str, object]] = {}
    for rank, path in sorted(find_status_pages(job).items()):
        try:
            fleet[rank] = read_status_page(path)
        except (OSError, ValueError, TornPageError) as e:
            fleet[rank] = {"error": f"{type(e).__name__}: {e}"}
    return fleet


def _read_holder_words(job: str) -> Dict[int, int]:
    """``{mutex_rank: holder_rank}`` straight from the job's holder-board
    segment, read-only (no segment is created when none exists)."""
    path = os.path.join(shm_native._FALLBACK_DIR,
                        shm_native.seg_name(job, "holders")[1:])
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return {}
    n = len(raw) // 8
    out: Dict[int, int] = {}
    for r in range(n):
        word = struct.unpack_from("<Q", raw, r * 8)[0]
        if 0 < word <= n:
            out[r] = int(word) - 1
    return out


def collect(job: str) -> Dict[str, object]:
    """One schema-valid fleet snapshot: the merged status pages plus the
    current epoch's lock holders and the suspect summary — the payload
    behind ``bftpu-top --once --json``."""
    from bluefog_tpu.resilience.join import epoch_job

    fleet = read_fleet(job)
    epoch = max((int(p.get("epoch", 0)) for p in fleet.values()
                 if "error" not in p), default=0)
    # mutexes live in the CURRENT epoch's job segment; ranks mid-switch
    # may still hold base-epoch locks, so merge both boards (epoch wins)
    holders = _read_holder_words(job)
    holders.update(_read_holder_words(epoch_job(job, epoch)))
    suspects = sorted({e["peer"] for p in fleet.values()
                       for e in p.get("edges", ())
                       if e.get("state") == "suspect"})
    orphans = sorted(r for r, p in fleet.items() if p.get("orphan"))
    # the serving plane: every rank that publishes/serves snapshots
    # (training publishers report lag 0; replicas their actual trail)
    serve = {}
    for r, p in sorted(fleet.items()):
        if "error" in p or p.get("serve", {}).get("version", -1) < 0:
            continue
        ent = dict(p["serve"])
        d = p.get("distrib", {})
        if d.get("slot", -1) >= 0:
            # attached through the distribution tree: report its slot
            # and the slot it feeds from (-1 = the publisher)
            ent["slot"] = int(d["slot"])
            ent["parent"] = int(d["parent"])
        serve[str(r)] = ent
    # the fleet-monitor lamp (v8): a page with alert_state >= 0 IS a
    # monitor page (worker pages always read -1); step counts scrapes
    # and op_id counts rule firings on the monitor's own page
    monitor = {}
    for r, p in sorted(fleet.items()):
        if "error" in p or p.get("alert", {}).get("state", -1) < 0:
            continue
        monitor[str(r)] = {
            "state": int(p["alert"]["state"]),
            "last": p["alert"]["last"],
            "scrapes": int(p.get("step", 0)),
            "firings": int(p.get("op_id", 0)),
        }
    return {
        "schema": "bftpu-top/1",
        "job": job,
        "wall_ts": time.time(),
        "epoch": epoch,
        "ranks": {str(r): p for r, p in fleet.items()},
        "holders": {str(m): h for m, h in sorted(holders.items())},
        "suspects": suspects,
        "orphans": orphans,
        "serve": serve,
        "serve_published": max(
            (int(v["version"]) for v in serve.values()), default=-1),
        "monitor": monitor,
    }


# ---------------------------------------------------------------------------
# runtime trace toggle: the tracectl word
# ---------------------------------------------------------------------------

TRACE_DEFAULT = 0  # whatever BFTPU_TRACING said at launch
TRACE_OFF = 1
TRACE_ON = 2
_CTL = struct.Struct("<QQ")  # generation, mode


def _tracectl_path(job: str) -> str:
    return os.path.join(shm_native._FALLBACK_DIR,
                        shm_native.seg_name(job, "tracectl")[1:])


def read_trace_control(job: str) -> Tuple[int, int]:
    """``(generation, mode)`` of the job's trace-control word (``(0,
    TRACE_DEFAULT)`` when never published)."""
    try:
        with open(_tracectl_path(job), "rb") as f:
            raw = f.read(_CTL.size)
    except OSError:
        return (0, TRACE_DEFAULT)
    if len(raw) != _CTL.size:
        return (0, TRACE_DEFAULT)
    return _CTL.unpack(raw)


def publish_trace_control(job: str, mode: int) -> int:
    """Atomically publish a new trace mode (generation bump makes the
    publish observable even when the mode repeats); returns the new
    generation."""
    gen = read_trace_control(job)[0] + 1
    path = _tracectl_path(job)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(_CTL.pack(gen, int(mode)))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return gen


class TraceControl:
    """Worker-side poller: each rank checks the word at most every
    ``interval`` seconds (amortized to ~nothing against a window op) and
    applies a generation change by rebuilding the process tracer —
    ``bftpu-top trace on`` therefore takes effect within one gossip
    round, no restart."""

    def __init__(self, job: str, rank: int, nranks: int,
                 interval: float = 0.2):
        self.job = str(job)
        self.rank = int(rank)
        self.nranks = int(nranks)
        self._interval = float(interval)
        # attach-time state is history, not a command: only generations
        # published AFTER we start polling are applied
        self._gen = read_trace_control(job)[0]
        self._next_poll = 0.0

    def poll(self) -> None:
        now = time.monotonic()
        if now < self._next_poll:
            return
        self._next_poll = now + self._interval
        gen, mode = read_trace_control(self.job)
        if gen == self._gen:
            return
        self._gen = gen
        self._apply(mode)

    def _apply(self, mode: int) -> None:
        from bluefog_tpu.tracing import tracer as _tracing

        if mode == TRACE_ON:
            if _tracing.tracing_dir() is None:
                os.environ["BFTPU_TRACING"] = "1"
            _tracing.reset()
            _tracing.get_tracer().set_identity(
                self.rank, self.nranks, self.job)
        elif mode == TRACE_OFF:
            t = _tracing.get_tracer()
            if t.enabled:
                t.write_buffer()  # don't lose spans gathered while on
            os.environ["BFTPU_TRACING"] = "0"
            _tracing.reset()
