"""Live introspection plane: status pages, lock-holder attribution, and
the ``bftpu-top`` fleet view.

Three pieces (docs/OBSERVABILITY.md "Live introspection"):

- :class:`StatusPage` — a per-rank seqlock'd mmap struct each island
  rank republishes once per window op (step, epoch, last op, edge
  health, mass-ledger totals); readers never block writers.
- The mutex **holder board**
  (:class:`bluefog_tpu.native.shm_native.HolderBoard`) — an acquire-time
  holder word per job mutex, so mutex waits attribute to the rank that
  actually holds the lock instead of the window owner.
- ``bftpu-top`` (``python -m bluefog_tpu.introspect --job JOB``, or
  ``bftpu-run --attach JOB top``) — attaches through the status pages +
  the launcher control socket and renders a refreshing fleet view, with
  ``trace on|off`` verbs that flip ``BFTPU_TRACING`` in running ranks.
"""

from bluefog_tpu.introspect.statuspage import (  # noqa: F401
    EDGE_STATE_NAMES,
    MAX_EDGES,
    PAGE_BYTES,
    STATUS_SCHEMA,
    TRACE_DEFAULT,
    TRACE_OFF,
    TRACE_ON,
    StatusPage,
    TornPageError,
    TraceControl,
    collect,
    find_status_pages,
    publish_trace_control,
    read_fleet,
    read_status_page,
    read_trace_control,
    status_page_path,
)
from bluefog_tpu.native.shm_native import (  # noqa: F401
    HolderBoard,
    statuspage_enabled,
)
