"""``bftpu-top``: live fleet view over the shm status pages.

    python -m bluefog_tpu.introspect --job JOB            # refreshing view
    python -m bluefog_tpu.introspect --job JOB --once --json
    python -m bluefog_tpu.introspect --job JOB --trace on
    bftpu-run --attach JOB top [--once --json]            # same thing

Reads are passive (seqlock readers over the per-rank pages + the holder
board): attaching never blocks or perturbs the run.  The launcher
control socket, when present, contributes supervisor state (live pids,
pending scale); the pages alone are enough for jobs spawned in-process.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time
from typing import Dict, Optional

from bluefog_tpu.introspect import statuspage as sp

_EDGE_CHAR = {"alive": ".", "suspect": "S", "dead": "D", "demoted": "d"}


def _launcher_state(job: str) -> Optional[dict]:
    """Best-effort ``top`` query against the launcher control socket."""
    from bluefog_tpu.run.launcher import control_sock_path

    path = control_sock_path(job)
    if not os.path.exists(path):
        return None
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(1.0)
        s.connect(path)
        s.sendall((json.dumps({"cmd": "top"}) + "\n").encode())
        line = s.makefile("r").readline()
        s.close()
        rep = json.loads(line)
        return rep if rep.get("ok") else None
    except (OSError, ValueError):
        return None


def snapshot(job: str) -> dict:
    """One merged fleet snapshot: status pages + holders + launcher."""
    snap = sp.collect(job)
    launcher = _launcher_state(job)
    if launcher is not None:
        snap["launcher"] = {k: launcher[k] for k in
                            ("live", "joiners", "pending_scale")
                            if k in launcher}
    return snap


def _rates(prev: Dict[int, tuple], snap: dict) -> Dict[int, float]:
    """Per-rank step/s between two snapshots (NaN-free: absent = 0)."""
    rates: Dict[int, float] = {}
    for rs, page in snap["ranks"].items():
        if "error" in page:
            continue
        r = int(rs)
        cur = (page["step"], page["mono_ts"])
        if r in prev:
            dstep = cur[0] - prev[r][0]
            dt = cur[1] - prev[r][1]
            if dt > 0:
                rates[r] = dstep / dt
        prev[r] = cur
    return rates


def render(snap: dict, rates: Dict[int, float]) -> str:
    """The fleet view as plain text (one frame of the live display)."""
    lines = []
    ranks = sorted(int(r) for r in snap["ranks"])
    holders = {int(m): h for m, h in snap.get("holders", {}).items()}
    held_by = {}  # holder rank -> [mutexes]
    for m, h in holders.items():
        held_by.setdefault(h, []).append(m)
    lines.append(f"bftpu-top — job {snap['job']}  epoch {snap['epoch']}  "
                 f"{time.strftime('%H:%M:%S', time.localtime(snap['wall_ts']))}")
    la = snap.get("launcher")
    if la:
        lines.append(f"launcher: live={len(la.get('live', []))} "
                     f"joiners={la.get('joiners', 0)} "
                     f"pending_scale={la.get('pending_scale', 0)}")
    lines.append("")
    lines.append(f"{'RANK':>4} {'STEP':>8} {'STEP/S':>7} {'EPOCH':>5} "
                 f"{'LAST OP':<12} {'BALANCE':>10} {'CONV':>9} "
                 f"{'SERVE':>9} {'QPS':>7} {'P99MS':>7} {'SLO':>4} "
                 f"{'QUEUE':<14} {'HOLDS':<8} EDGES")
    for r in ranks:
        page = snap["ranks"][str(r)]
        if "error" in page:
            lines.append(f"{r:>4} {'—':>8} {page['error']}")
            continue
        rate = rates.get(r)
        edges = " ".join(
            f"{e['peer']}:{_EDGE_CHAR.get(e['state'], '?')}"
            for e in page["edges"])
        holds = ",".join(f"m{m}" for m in sorted(held_by.get(r, []))) or "-"
        # the progress-engine view (statuspage v2): queue depth plus the
        # op the worker is landing right now; "-" = no engine running
        prog = page.get("progress", {})
        qd = prog.get("qdepth", -1)
        queue = "-" if qd < 0 else (
            f"{qd}" + (f">{prog['inflight']}" if prog.get("inflight")
                       else ""))
        # convergence probe (statuspage v3): debiased consensus-error
        # sample; "—" = probe off (or pre-v3 writer / first round)
        conv = page.get("conv", {})
        cerr, cround = conv.get("err", -1.0), conv.get("round", -1)
        conv_s = f"{cerr:.1e}" if cround >= 0 and cerr >= 0.0 else "—"
        # serving plane (statuspage v5): the snapshot version this rank
        # publishes/serves; replicas append their lag ("v3+2" = serving
        # v3, 2 committed versions behind); "—" = not a serve rank.
        # A distribution-tree replica (v6) appends its slot and feed
        # edge: "v3 s4<1" = slot 4 fed by slot 1, "<P" = publisher-fed
        sv = page.get("serve", {})
        sver, slag = sv.get("version", -1), sv.get("lag", -1)
        serve_s = "—" if sver < 0 else (
            f"v{sver}" + (f"+{slag}" if slag > 0 else ""))
        dv = page.get("distrib", {})
        if sver >= 0 and dv.get("slot", -1) >= 0:
            par = dv.get("parent", -1)
            serve_s += f" s{dv['slot']}<" + (
                "P" if par < 0 else str(par))
        # request plane (statuspage v7): rolling-window QPS + p99 and
        # the SLO lamp (— = no SLO armed / no traffic, ok = inside the
        # objective, VIOL = in an open violation window)
        qps, p99 = sv.get("qps", -1.0), sv.get("p99_ms", -1.0)
        qps_s = f"{qps:.1f}" if qps >= 0 else "—"
        p99_s = f"{p99:.2f}" if p99 >= 0 else "—"
        slo_s = {0: "ok", 1: "VIOL"}.get(sv.get("slo_state", -1), "—")
        # an ORPHAN rank quiesced on quorum loss — the page freezes at
        # the denial, so the state outranks whatever op came last
        last_op = "ORPHAN" if page.get("orphan") else page["last_op"]
        lines.append(
            f"{r:>4} {page['step']:>8} "
            f"{('%.1f' % rate) if rate is not None else '—':>7} "
            f"{page['epoch']:>5} {last_op:<12} "
            f"{page['ledger']['balance']:>10.3g} {conv_s:>9} "
            f"{serve_s:>9} {qps_s:>7} {p99_s:>7} {slo_s:>4} "
            f"{queue:<14} {holds:<8} {edges}")
    if snap.get("serve"):
        lines.append("")
        # tree replicas append "slot<parent" ("<P" = publisher-fed),
        # so one line shows the whole distribution fan-out
        lines.append(
            f"serving: committed v{snap.get('serve_published', -1)}; " +
            ", ".join(
                f"r{r} v{v['version']} lag {max(0, v['lag'])}" + (
                    f" s{v['slot']}<" + ("P" if v.get("parent", -1) < 0
                                         else str(v["parent"]))
                    if v.get("slot", -1) >= 0 else "") + (
                    f" {v['qps']:.0f}/s p99 {v['p99_ms']:.1f}ms"
                    if v.get("qps", -1.0) >= 0 else "")
                for r, v in sorted(snap["serve"].items(),
                                   key=lambda kv: int(kv[0]))))
    if snap.get("monitor"):
        lines.append("")
        # the fleet-monitor lamp (statuspage v8): quiet/FIRING plus the
        # last alert's rule name — one glance answers "is it alarming?"
        lines.append("monitor: " + ", ".join(
            (f"r{r} FIRING [{m['last']}]" if m["state"] == 1 else
             f"r{r} quiet" + (f" (last {m['last']})" if m["last"] else ""))
            + f" scrapes {m['scrapes']} firings {m['firings']}"
            for r, m in sorted(snap["monitor"].items(),
                               key=lambda kv: int(kv[0]))))
    if snap.get("orphans"):
        lines.append("")
        lines.append(f"ORPHANED (quorum lost, quiesced): "
                     f"{', '.join(str(o) for o in snap['orphans'])}")
    if snap.get("suspects"):
        lines.append("")
        lines.append(f"straggler suspects: "
                     f"{', '.join(str(s) for s in snap['suspects'])}")
    if holders:
        lines.append(f"lock holders: " + ", ".join(
            f"mutex {m} held by rank {h}" for m, h in sorted(holders.items())))
    lines.append("")
    lines.append("edges: .=alive S=suspect D=dead d=demoted "
                 "(as seen by the row's rank)")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bftpu-top",
        description="Live fleet view of a running islands job "
        "(status pages + lock holders + straggler suspects).")
    parser.add_argument("--job", required=True,
                        help="island job name (BLUEFOG_ISLAND_JOB)")
    parser.add_argument("--once", action="store_true",
                        help="print one snapshot and exit")
    parser.add_argument("--json", action="store_true",
                        help="emit machine-readable JSON instead of the "
                        "table (schema bftpu-top/1)")
    parser.add_argument("--interval", type=float, default=1.0,
                        help="refresh interval in seconds (live mode)")
    parser.add_argument("--trace", choices=("on", "off", "default"),
                        default=None,
                        help="publish the runtime trace-control word "
                        "(flips BFTPU_TRACING in running ranks) and exit")
    args = parser.parse_args(argv)

    if args.trace is not None:
        mode = {"on": sp.TRACE_ON, "off": sp.TRACE_OFF,
                "default": sp.TRACE_DEFAULT}[args.trace]
        gen = sp.publish_trace_control(args.job, mode)
        print(json.dumps({"ok": True, "job": args.job, "mode": args.trace,
                          "generation": gen}))
        return 0

    snap = snapshot(args.job)
    if not snap["ranks"]:
        print(f"bftpu-top: no status pages for job {args.job!r} — is the "
              f"run up (and BFTPU_STATUSPAGE not 0)?", file=sys.stderr)
        if args.once and args.json:
            print(json.dumps(snap, indent=2))
        return 1

    if args.once:
        print(json.dumps(snap, indent=2) if args.json
              else render(snap, {}))
        return 0

    prev: Dict[int, tuple] = {}
    _rates(prev, snap)  # seed the rate baseline
    try:
        while True:
            time.sleep(max(0.1, args.interval))
            snap = snapshot(args.job)
            rates = _rates(prev, snap)
            if args.json:
                print(json.dumps(snap))
            else:
                # clear + home, then one frame — plain ANSI, no curses dep
                sys.stdout.write("\x1b[2J\x1b[H" + render(snap, rates) + "\n")
                sys.stdout.flush()
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
