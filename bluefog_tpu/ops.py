"""User-facing collective ops — the eager, rank-major veneer.

TPU-native sibling of the reference's ``bluefog/torch/mpi_ops.py`` [U]
(SURVEY.md §2.2): same verbs (``allreduce``, ``broadcast``, ``allgather``,
``neighbor_allgather``, ``neighbor_allreduce``,
``hierarchical_neighbor_allreduce``, ``barrier``) with blocking and
``_nonblocking`` variants, static-topology weights from the installed graph
and dynamic per-call neighbor sets.

Programming model difference, by design: the reference is one process per
rank, so each call site passes *its own* rank's weights.  JAX is
single-controller SPMD, so eager arrays are **rank-major** — leading axis =
rank, sharded over the mesh — and dynamic arguments are per-rank sequences
(index r holds what rank r would have passed upstream).  Scalars broadcast
to all ranks.  The "nonblocking" variants return a :class:`Handle` backed by
JAX's async dispatch — the transfer is already in flight when the call
returns, exactly the overlap the reference's background thread provided
(SURVEY.md §3.2 TPU mapping).

For code *inside* ``jit``/``shard_map`` (the idiomatic TPU path), use
:mod:`bluefog_tpu.ops_spmd` directly.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from bluefog_tpu import ops_spmd, topology_util
from bluefog_tpu.common.logging_util import logger
from bluefog_tpu.core import basics
from bluefog_tpu.core.basics import LOCAL_AXIS, MACHINES_AXIS, NODES_AXIS
from bluefog_tpu.core.plan import CommPlan, plan_from_neighbor_lists
from bluefog_tpu.timeline import timeline_context

__all__ = [
    "Handle",
    "device_sync",
    "allreduce",
    "allreduce_nonblocking",
    "broadcast",
    "broadcast_nonblocking",
    "allgather",
    "allgather_nonblocking",
    "neighbor_allgather",
    "neighbor_allgather_nonblocking",
    "neighbor_allreduce",
    "neighbor_allreduce_nonblocking",
    "hierarchical_neighbor_allreduce",
    "hierarchical_neighbor_allreduce_nonblocking",
    "barrier",
    "poll",
    "synchronize",
    "wait",
]


def device_sync(tree):
    """Block until every array leaf of ``tree`` is materialized on device,
    and return ``tree``.

    ``jax.block_until_ready`` alone is NOT a trustworthy barrier on every
    platform: on the tunneled TPU plugin used by the benchmark driver it
    returns immediately (measured in ``bench.py``), and the platform
    self-reports as plain ``tpu`` so it cannot be special-cased.  Completion
    is therefore *proven* by round-tripping to the host one scalar DERIVED
    from every leaf — data dependency forces the fetch to wait for the real
    computation.  The transfer is a single f32, so the extra cost on honest
    platforms is one host round-trip.  Set ``BLUEFOG_FETCH_SYNC=0`` to fall
    back to bare ``block_until_ready``.
    """
    jax.block_until_ready(tree)
    if os.environ.get("BLUEFOG_FETCH_SYNC", "1") != "0":
        # multi-process: eager ops reject non-fully-addressable arrays, so
        # probe this process's first shard instead — it lives on a local
        # device whose execution stream ordered after the real computation
        leaves = []
        for l in jax.tree_util.tree_leaves(tree):
            if not (isinstance(l, jax.Array) and l.size):
                continue
            if not l.is_fully_addressable:
                shards = l.addressable_shards
                if not shards:
                    continue
                l = shards[0].data
            leaves.append(jnp.ravel(l)[:1].astype(jnp.float32))
        if leaves:
            probe = jnp.concatenate(leaves)
            np.asarray(probe)  # the host round-trip that proves completion
    return tree


_POLL_BLOCK_WARNED = False


class Handle:
    """Nonblocking-op result (the reference's integer handle +
    ``HandleManager``, ``bluefog/torch/handle_manager.h`` [U]).

    JAX dispatch is asynchronous: by the time a Handle exists the collective
    is already enqueued on device.  ``poll`` asks the runtime whether the
    output buffers are materialized; ``wait`` blocks and returns the value.
    """

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def poll(self) -> bool:
        """True once the result buffers are materialized.

        MAY BLOCK on platforms whose arrays lack an async ``is_ready``
        query (e.g. the tunneled TPU plugin): there the only truthful
        answer requires a ``device_sync`` round-trip, so a reference-style
        "poll and do useful work meanwhile" loop degrades to a wait.  On
        standard jax.Array platforms it is a non-blocking probe.
        """
        leaves = jax.tree_util.tree_leaves(self._value)
        if all(hasattr(leaf, "is_ready") for leaf in leaves):
            return all(leaf.is_ready() for leaf in leaves)
        # No async readiness query on this platform: claiming True would
        # make reference-style poll loops spin-claim readiness falsely
        # (round-1 verdict weak #3).  Prove readiness instead — poll may
        # block briefly, but what it returns is the truth.
        global _POLL_BLOCK_WARNED
        if not _POLL_BLOCK_WARNED:
            _POLL_BLOCK_WARNED = True
            logger.warning(
                "Handle.poll: this platform's arrays have no async is_ready "
                "query; poll degrades to a blocking wait, so poll-and-work "
                "loops serialize here.  (Warned once per process.)"
            )
        device_sync(self._value)
        return True

    def wait(self):
        return device_sync(self._value)


def poll(handle: Handle) -> bool:
    """Reference ``bf.poll(handle)`` [U].  May block where the platform
    has no async readiness query (see :meth:`Handle.poll`)."""
    return handle.poll()


def synchronize(handle: Handle):
    """Reference ``bf.synchronize(handle)`` [U] — block and return output."""
    return handle.wait()


wait = synchronize


def _ctx():
    return basics.context()


def _jit_cached(key, builder):
    return _ctx().jit_cache(key, builder)


def _rank_major(fn, *, out_specs=P(NODES_AXIS)):
    mesh = _ctx().mesh
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=P(NODES_AXIS), out_specs=out_specs)
    )


def _as_tree(x):
    # single-process: a plain transfer; multi-process: assembles a global
    # rank-major array from process-local rows (basics.to_rank_major_global)
    return basics.to_rank_major_global(x)


# --------------------------------------------------------------------------
# Global collectives
# --------------------------------------------------------------------------


def allreduce(x, average: bool = True, name: Optional[str] = None):
    """Global average (default) or sum across all ranks; rank-major in/out
    (reference ``bf.allreduce(tensor, average=True)`` [U])."""
    del name
    with timeline_context("allreduce"):
        f = _jit_cached(
            ("allreduce", bool(average)),
            lambda: _rank_major(
                functools.partial(ops_spmd.allreduce, axis_name=NODES_AXIS, average=average)
            ),
        )
        return f(_as_tree(x))


def allreduce_nonblocking(x, average: bool = True, name: Optional[str] = None) -> Handle:
    return Handle(allreduce(x, average=average, name=name))


def broadcast(x, root_rank: int = 0, name: Optional[str] = None):
    """All ranks receive ``root_rank``'s slice (reference ``bf.broadcast`` [U])."""
    del name
    with timeline_context("broadcast"):
        f = _jit_cached(
            ("broadcast", int(root_rank)),
            lambda: _rank_major(
                functools.partial(
                    ops_spmd.broadcast, root_rank=int(root_rank), axis_name=NODES_AXIS
                )
            ),
        )
        return f(_as_tree(x))


def broadcast_nonblocking(x, root_rank: int = 0, name: Optional[str] = None) -> Handle:
    return Handle(broadcast(x, root_rank=root_rank, name=name))


def allgather(x, name: Optional[str] = None):
    """Every rank receives the concatenation (along the per-rank axis 0) of
    all ranks' tensors: rank-major input ``[size, n0, ...]`` -> output
    ``[size, size*n0, ...]`` (reference ``bf.allgather`` [U])."""
    del name
    with timeline_context("allgather"):

        def spmd(t):
            def per_leaf(a):
                g = jax.lax.all_gather(a, NODES_AXIS, axis=0, tiled=True)
                # leading rank axis for rank-major out_specs; concatenate the
                # gathered per-rank blocks INSIDE the traced fn (an eager
                # reshape would reject non-addressable multi-host arrays)
                return g.reshape((1, g.shape[0] * g.shape[1]) + g.shape[2:])

            return jax.tree_util.tree_map(per_leaf, t)

        f = _jit_cached(("allgather",), lambda: _rank_major(spmd))
        return f(_as_tree(x))


def allgather_nonblocking(x, name: Optional[str] = None) -> Handle:
    return Handle(allgather(x, name=name))


def barrier():
    """Block until all in-flight device work is complete (reference
    ``bf.barrier`` [U]).  Executes a trivial psum over the mesh and waits."""
    f = _jit_cached(
        ("barrier",),
        lambda: _rank_major(
            functools.partial(ops_spmd.allreduce, axis_name=NODES_AXIS, average=False)
        ),
    )
    device_sync(f(jnp.zeros((_ctx().size, 1))))


# --------------------------------------------------------------------------
# Neighbor collectives (static + dynamic topology)
# --------------------------------------------------------------------------

WeightsArg = Union[None, Sequence[Dict[int, float]]]


def _resolve_src_lists(
    size: int,
    src_arg,
    dst_arg,
    src_name: str,
    dst_name: str,
) -> list:
    """Shared edge-set resolution for the dynamic-topology paths: per-rank
    source lists from ``src_arg`` (each entry iterates source ranks) and/or
    ``dst_arg`` (each entry iterates destination ranks).  Giving both
    cross-validates that they describe the same edge set."""
    if src_arg is None and dst_arg is None:
        raise ValueError(f"dynamic path needs {src_name} and/or {dst_name}")
    for nm, arg in ((src_name, src_arg), (dst_name, dst_arg)):
        if arg is not None and len(arg) != size:
            raise ValueError(
                f"{nm} must be a length-{size} sequence (one entry per rank)"
            )
    src_lists = None
    if src_arg is not None:
        src_lists = [sorted(int(s) for s in src_arg[d]) for d in range(size)]
    if dst_arg is not None:
        inferred = topology_util.InferSourceFromDestinationRanks(
            [sorted(int(d) for d in dst_arg[s]) for s in range(size)]
        )
        if src_lists is None:
            src_lists = inferred
        elif src_lists != [sorted(x) for x in inferred]:
            raise ValueError(
                f"{src_name} and {dst_name} describe different edge sets"
            )
    return src_lists


def _dynamic_plan(
    size: int,
    self_weight,
    src_weights: WeightsArg,
    dst_weights: WeightsArg,
) -> CommPlan:
    """Translate the reference's dynamic-topology arguments into a CommPlan.

    Effective weight of edge s->d: ``src_weights[d][s] * dst_weights[s][d]``
    (receiver-side weight times sender-side scale — the reference applies
    dst scaling at the sender and src weighting at the receiver, SURVEY.md
    §3.2/§2.2 [U]); either side defaults to 1 when not given.
    """
    src_lists = _resolve_src_lists(
        size, src_weights, dst_weights, "src_weights", "dst_weights"
    )
    eff = []
    for d in range(size):
        wd = {}
        for s in src_lists[d]:
            w = 1.0
            if src_weights is not None:
                w *= float(src_weights[d][s])
            if dst_weights is not None:
                w *= float(dst_weights[s][d])
            wd[s] = w
        eff.append(wd)
    if self_weight is None:
        self_w = [1.0 - sum(eff[d].values()) for d in range(size)]
    elif np.isscalar(self_weight):
        self_w = [float(self_weight)] * size
    else:
        self_w = [float(w) for w in self_weight]
        if len(self_w) != size:
            raise ValueError(f"self_weight must be scalar or length-{size}")
    return plan_from_neighbor_lists(size, src_lists, src_weights=eff, self_weights=self_w)


def neighbor_allreduce(
    x,
    self_weight=None,
    src_weights: WeightsArg = None,
    dst_weights: WeightsArg = None,
    name: Optional[str] = None,
):
    """Weighted neighbor averaging — the reference's hot path
    (``bf.neighbor_allreduce``, SURVEY.md §3.2 [U]).

    Static mode (no weight args): weights come from the installed topology
    (``set_topology``), self weight = 1 - sum(in-weights).

    Dynamic mode: per-rank ``src_weights``/``dst_weights`` sequences of
    ``{rank: weight}`` dicts define this call's edge set (the reference's
    per-call dynamic topology).  ``self_weight`` may be a scalar (all ranks)
    or per-rank sequence; default keeps row-stochasticity.
    """
    del name
    ctx = _ctx()
    with timeline_context("neighbor_allreduce"):
        if src_weights is None and dst_weights is None and self_weight is None:
            plan = ctx.plan
        elif src_weights is None and dst_weights is None:
            sw = (
                float(self_weight)
                if np.isscalar(self_weight)
                else tuple(float(w) for w in self_weight)
            )
            plan = ctx.plan_for(ctx.topology, self_weight=sw)
        else:
            plan = _dynamic_plan(ctx.size, self_weight, src_weights, dst_weights)
        f = _jit_cached(
            ("neighbor_allreduce", plan),
            lambda: _rank_major(
                functools.partial(
                    ops_spmd.neighbor_allreduce, plan=plan, axis_name=NODES_AXIS
                )
            ),
        )
        return f(_as_tree(x))


def neighbor_allreduce_nonblocking(
    x,
    self_weight=None,
    src_weights: WeightsArg = None,
    dst_weights: WeightsArg = None,
    name: Optional[str] = None,
) -> Handle:
    return Handle(
        neighbor_allreduce(
            x,
            self_weight=self_weight,
            src_weights=src_weights,
            dst_weights=dst_weights,
            name=name,
        )
    )


RanksArg = Union[None, Sequence[Sequence[int]]]


def _dynamic_gather_plan(size: int, src_ranks: RanksArg, dst_ranks: RanksArg) -> CommPlan:
    """Per-call neighbor sets for ``neighbor_allgather`` (the reference's
    dynamic ``src_ranks=``/``dst_ranks=`` variant in
    ``bluefog/torch/mpi_ops.py`` [U]).  Rank-major like ``_dynamic_plan``:
    ``src_ranks[d]`` lists the ranks d receives from; ``dst_ranks[s]`` lists
    the ranks s sends to.  Giving both cross-validates the edge sets.
    """
    src_lists = _resolve_src_lists(
        size, src_ranks, dst_ranks, "src_ranks", "dst_ranks"
    )
    return plan_from_neighbor_lists(size, src_lists)


def neighbor_allgather(
    x,
    src_ranks: RanksArg = None,
    dst_ranks: RanksArg = None,
    name: Optional[str] = None,
):
    """Concatenate in-neighbor tensors (ascending source rank) per rank:
    rank-major ``[size, n0, ...]`` -> ``[size, D*n0, ...]`` for in-degree-D
    regular topologies (reference ``bf.neighbor_allgather`` [U]).

    Irregular topologies return ``[size, maxD, n0, ...]`` zero-padded
    (static SPMD shapes cannot be ragged); valid counts are
    ``context().plan.in_degrees``.

    Dynamic mode (``src_ranks``/``dst_ranks``): per-rank neighbor lists
    define this call's edge set instead of the installed topology, mirroring
    the dynamic-topology ``neighbor_allreduce`` path.
    """
    del name
    ctx = _ctx()
    if src_ranks is None and dst_ranks is None:
        plan = ctx.plan
    else:
        plan = _dynamic_gather_plan(ctx.size, src_ranks, dst_ranks)
    with timeline_context("neighbor_allgather"):

        def spmd(t):
            y = ops_spmd.neighbor_allgather(t, plan=plan, axis_name=NODES_AXIS)

            def finish(a):
                a = jnp.moveaxis(a, 1, 0)  # per-shard [1, D, n0, ...]
                if plan.is_regular:
                    # concatenate neighbor blocks INSIDE the traced fn
                    # (same multi-host rule as allgather above)
                    a = a.reshape((1, a.shape[1] * a.shape[2]) + a.shape[3:])
                return a

            return jax.tree_util.tree_map(finish, y)

        f = _jit_cached(("neighbor_allgather", plan), lambda: _rank_major(spmd))
        return f(_as_tree(x))


def neighbor_allgather_nonblocking(
    x,
    src_ranks: RanksArg = None,
    dst_ranks: RanksArg = None,
    name: Optional[str] = None,
) -> Handle:
    return Handle(
        neighbor_allgather(x, src_ranks=src_ranks, dst_ranks=dst_ranks, name=name)
    )


def hierarchical_neighbor_allreduce(
    x,
    self_weight: Optional[float] = None,
    name: Optional[str] = None,
):
    """Intra-machine average -> machine-level gossip on the machine topology
    -> implicit local broadcast (reference
    ``bf.hierarchical_neighbor_allreduce`` [U]).  Rank-major in/out; all
    ranks of a machine end with identical values.
    """
    del name
    ctx = _ctx()
    if ctx.machine_topology is None:
        raise RuntimeError(
            "no machine topology; call set_machine_topology() (machine_size="
            f"{ctx.machine_size_})"
        )
    mplan = ctx.machine_plan
    with timeline_context("hierarchical_neighbor_allreduce"):

        def build():
            def spmd(t):
                return ops_spmd.hierarchical_neighbor_allreduce(
                    t,
                    machine_plan=mplan,
                    machines_axis=MACHINES_AXIS,
                    local_axis=LOCAL_AXIS,
                    self_weight=self_weight,
                )

            mesh = ctx.hier_mesh
            return jax.jit(
                jax.shard_map(
                    spmd,
                    mesh=mesh,
                    in_specs=P((MACHINES_AXIS, LOCAL_AXIS)),
                    out_specs=P((MACHINES_AXIS, LOCAL_AXIS)),
                )
            )

        f = _jit_cached(
            ("hierarchical_neighbor_allreduce", mplan, self_weight), build
        )
        return f(_as_tree(x))


def hierarchical_neighbor_allreduce_nonblocking(
    x, self_weight: Optional[float] = None, name: Optional[str] = None
) -> Handle:
    return Handle(hierarchical_neighbor_allreduce(x, self_weight=self_weight, name=name))
