"""TCP mailbox transport — the islands' cross-host (DCN) path.

Same window model and interface as the shared-memory transport
(:mod:`bluefog_tpu.native.shm_native`), carried over sockets so island
processes can live on DIFFERENT hosts: the deployment where each TPU pod
host runs one island and gossips parameters asynchronously over the
data-center network, exactly the role the reference's CUDA-aware MPI RMA
plays between its GPU nodes (``MPI_Win_create``/``MPI_Put`` over
IB/Ethernet, ``bluefog/common/mpi_controller.cc`` [U]; SURVEY.md §2.4).

Topology of responsibility (the passive-target model, unchanged):

- every rank runs a small **mailbox server thread** that OWNS that rank's
  state: its mail slots (one per in-neighbor per window), its exposed
  tensor, its mutex, and — on rank 0 — the job barrier;
- ``write``/``read_exposed`` are requests to the *destination's* server —
  the receiver's application code never participates (one-sided);
- ``read``/``collect``/``expose``/``reset`` touch only the local server's
  store (an in-process dict guarded by a lock) — no network;
- rendezvous: rank 0 additionally serves a registry where every rank posts
  its ``host:port`` and fetches the full table, so only ONE address
  (``BLUEFOG_ISLAND_COORD``) must be known up front — the analogue of
  ``bfrun``'s host list [U].

Wire format: 40-byte fixed header ``(op, win_id, slot, mode, nbytes, p,
trace)`` + raw payload bytes, over persistent connections (one per peer,
created lazily).  ``trace`` is the u64 trace-context word
(:func:`bluefog_tpu.tracing.pack_ctx`; 0 = tracing off) that lets the
merge CLI draw a flow arrow from the depositing span on the writer to
the collecting span on the owner.  No external dependencies.

One wire protocol (the v2 chunk state machine, ported from shm)
----------------------------------------------------------------

Window deposits default to the CHUNKED framing (``BFTPU_TCP_CHUNKED``):
the sender splits the payload into ``shm_native.chunk_bytes()``-sized
chunks — the SAME geometry the shm mailbox uses — and streams one
``_OP_CHUNK`` frame per chunk (header+payload in one scatter-gather
``sendmsg``), pipelined under a credit window
(``BFTPU_TCP_WINDOW_CHUNKS`` frames in flight before one ack is
collected — windowed credit, not stop-and-wait), then seals the deposit
with an ``_OP_COMMIT`` frame.  The server commits chunks in ascending
order into the mail slot and advances the slot version and push-sum
mass ONLY at the commit frame (``TCP_DEPOSIT_COMMITS_AFTER_PAYLOAD``) —
so a writer that dies mid-stream committed exactly zero mass, and the
disconnect handler's drain (``TCP_DEAD_WRITER_DRAIN_STEPS``) restores
the slot to the logical-zero drained state readers expect, just like
shm's dead-writer drain.  Chunk frames may carry bf16/int8-quantized
values (``BFTPU_WIRE_DTYPE``; per-chunk wire code in ``mode``, scale in
``p``, element offset in ``trace``) with an error-feedback residual
held per edge on the sender — see :mod:`bluefog_tpu.native.wire_codec`.
Both transports are model-checked from one shared protocol spec by
:mod:`bluefog_tpu.analysis.wire_rules`.
"""

from __future__ import annotations

import os
import random
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from bluefog_tpu.common.logging_util import logger
from bluefog_tpu.native import capabilities as _caps
from bluefog_tpu.native import wire_codec
from bluefog_tpu.resilience.detector import PeerTimeoutError
from bluefog_tpu.telemetry import registry as _telemetry
from bluefog_tpu.tracing import tracer as _tracing

# ops
_OP_WRITE = 1          # deposit into (my) mail slot: mode 0 put, 1 accumulate
_OP_READ_EXPOSED = 2   # return my exposed tensor
_OP_MUTEX_ACQ = 3
_OP_MUTEX_REL = 4
_OP_BARRIER = 5        # rank-0 only
_OP_REGISTER = 6       # rank-0 only: register rank -> addr, get table when full
_OP_PING = 7
_OP_BARRIER_T = 8      # rank-0 only: timed barrier, timeout rides in p
_OP_HEARTBEAT = 9      # rank-0 only: renew rank `slot`'s lease
_OP_LIVENESS = 10      # rank-0 only: age of rank `slot`'s lease (in p)
_OP_CLOCK = 11         # rank-0 only: coordinator's monotonic clock (in p)
_OP_JOIN_RANK = 12     # rank-0 only: grant a fresh global rank (in slot)
_OP_EPOCH = 13         # rank-0 only: membership-epoch word (read/publish)
_OP_CHUNK = 14         # one chunk of a streaming deposit: mode packs
                       # (chunk_idx << 8) | (wire_code << 1) | accumulate,
                       # p carries the per-chunk quantization scale and
                       # trace the element offset; acked per frame (credit)
_OP_COMMIT = 15        # seal a chunk stream: mode packs (nchunks << 1) |
                       # accumulate, p the EXACT push-sum mass, trace the
                       # trace-context word; version/mass advance HERE

#: human-readable op names: PeerTimeoutError context + telemetry labels
_OP_NAMES = {
    _OP_WRITE: "write", _OP_READ_EXPOSED: "read_exposed",
    _OP_MUTEX_ACQ: "mutex_acquire", _OP_MUTEX_REL: "mutex_release",
    _OP_BARRIER: "barrier", _OP_REGISTER: "register", _OP_PING: "ping",
    _OP_BARRIER_T: "barrier_timed", _OP_HEARTBEAT: "heartbeat",
    _OP_LIVENESS: "liveness", _OP_CLOCK: "clock",
    _OP_JOIN_RANK: "join_rank", _OP_EPOCH: "epoch",
    _OP_CHUNK: "chunk", _OP_COMMIT: "commit",
}

#: ops a mid-exchange disconnect may safely REPLAY on a fresh
#: connection: pure reads of server state.  Mutation ops (write,
#: mutex, barrier, join_rank) stay one-shot — the server may have
#: applied the lost request, and re-sending would double-apply.
#: Chunked deposits get their own replay rule in ``deposit_chunked``
#: (safe up to the commit frame, which is where state advances).
_IDEMPOTENT_OPS = frozenset({
    _OP_READ_EXPOSED, _OP_PING, _OP_HEARTBEAT, _OP_LIVENESS,
    _OP_CLOCK, _OP_EPOCH,
})

# op, win_id, slot, mode, nbytes, p, trace — the trace word is LAST so
# pre-trace header fields keep their offsets on the wire
_HDR = struct.Struct("<iiiiqdQ")

# -- protocol spec constants ---------------------------------------------
# Model-checked against shm_native's spec by bluefog_tpu.analysis.
# wire_rules: ONE wire protocol, two carriers.
TCP_CHUNK_COMMIT_IN_ORDER = True
TCP_DEPOSIT_COMMITS_AFTER_PAYLOAD = True
TCP_DRAINED_COLLECT_IS_ATOMIC = True
#: the disconnect-handler drain for a writer that died mid-stream, in
#: order: make the slot seq even so readers stop spinning, mark it
#: logically drained (reads as zeros, mass 0), then clear the stream
#: registration — mark_drained MUST precede the clear, same invariant
#: as shm's DEAD_WRITER_DRAIN_STEPS
TCP_DEAD_WRITER_DRAIN_STEPS = ("evenize_wseq", "mark_drained",
                               "clear_stream")


def peer_timeout_s() -> Optional[float]:
    """Deadline for any single request/response round trip to a peer
    (``BFTPU_PEER_TIMEOUT_S``; <= 0 disables, restoring unbounded waits).
    The default is generous: mutex and barrier waits legitimately block
    while other ranks compute — the deadline exists to unstick survivors
    from a DEAD peer, not to police slow ones."""
    try:
        t = float(os.environ.get("BFTPU_PEER_TIMEOUT_S", "120"))
    except ValueError:
        t = 120.0
    return t if t > 0 else None


def tcp_retries() -> int:
    """Session-resume attempts after a DISCONNECT-class failure
    (``BFTPU_TCP_RETRIES``, default 3; 0 restores the old one-shot
    behavior where the next request reconnects but the failing one
    raises).  Only connection drops are retried — a connected peer
    that stays silent is the failure detector's business and still
    surfaces as :class:`PeerTimeoutError` after one deadline."""
    try:
        n = int(os.environ.get("BFTPU_TCP_RETRIES", "3"))
    except ValueError:
        n = 3
    return max(n, 0)


def tcp_backoff_s() -> float:
    """Base of the bounded full-jitter reconnect backoff
    (``BFTPU_TCP_BACKOFF_S``, default 0.05): retry ``k`` sleeps
    ``uniform(0, min(2.0, base * 2**k))`` seconds."""
    try:
        b = float(os.environ.get("BFTPU_TCP_BACKOFF_S", "0.05"))
    except ValueError:
        b = 0.05
    return max(b, 0.0)


#: RNG behind the reconnect jitter — module-level so tests can pin it
#: (``tcp_transport._jitter_rng = random.Random(seed)``) and so every
#: connection in the process shares one stream
_jitter_rng = random.Random()


def tcp_chunked() -> bool:
    """Chunked pipelined framing for window deposits
    (``BFTPU_TCP_CHUNKED``; default on, ``0`` restores the legacy
    whole-payload acked write — kept for A/B benches)."""
    return os.environ.get("BFTPU_TCP_CHUNKED", "1") != "0"


def window_chunks() -> int:
    """Sender credit window: chunk frames in flight before one ack is
    collected (``BFTPU_TCP_WINDOW_CHUNKS``, default 32; 1 degenerates
    to stop-and-wait)."""
    try:
        w = int(os.environ.get("BFTPU_TCP_WINDOW_CHUNKS", "32"))
    except ValueError:
        w = 32
    return max(w, 1)


def _chunk_bytes() -> int:
    # ONE chunk geometry for both transports: the shm setting
    # (BLUEFOG_SHM_CHUNK_BYTES) governs the TCP stream too (lazy import:
    # shm_native imports this module for transport selection)
    from bluefog_tpu.native import shm_native
    return shm_native.chunk_bytes()


def _chunk_kill_after(src_rank: int) -> int:
    """Chaos hook: ``BFTPU_CHAOS_KILL_CHUNK="<rank>:<n>"`` makes rank
    ``<rank>`` (-1 = any) SIGKILL itself after streaming ``<n>`` chunk
    frames of a deposit — the deterministic mid-stream death the
    drain-path tests need (an external signal cannot time it).  Returns
    -1 when no schedule matches."""
    spec = os.environ.get("BFTPU_CHAOS_KILL_CHUNK")
    if not spec:
        return -1
    try:
        kr, kn = spec.split(":")
        if int(kr) in (src_rank, -1):
            return int(kn)
    except ValueError:
        pass
    return -1


def _chunk_drop_after() -> int:
    """Chaos hook: ``BFTPU_CHAOS_DROP_CHUNK="<n>"`` makes the RECEIVING
    server drop the connection after accepting ``<n>`` chunk frames of
    one stream, ONE TIME per server — the deterministic mid-stream
    disconnect the session-resume tests need (a real link flap cannot
    be timed).  The writer sees ConnectionError with the commit unsent,
    so the bounded-backoff retry must replay the stream from chunk 0
    and lose nothing.  Returns -1 when unset."""
    spec = os.environ.get("BFTPU_CHAOS_DROP_CHUNK")
    if not spec:
        return -1
    try:
        return int(spec)
    except ValueError:
        return -1


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    # preallocate + recv_into: a `buf += chunk` loop would copy O(n²/chunk)
    # bytes (measured 20x slowdown on multi-MB window payloads)
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return buf  # bytearray: frombuffer/slice-assign/decode all accept it


class _BufReader:
    """Buffered frame reader for a server connection: one ``recv_into``
    syscall fetches MANY queued 40-byte chunk headers and acks at once
    (the pipelined framing makes back-to-back small frames the common
    case, and per-frame ``recv`` syscalls were the dominant per-chunk
    cost).  Large payloads bypass the buffer — and ``read_into`` lands
    them straight in caller memory (the mail slot), eliminating the
    per-deposit staging allocation + copy of the legacy path."""

    __slots__ = ("sock", "_buf", "_lo", "_hi")

    def __init__(self, sock: socket.socket, bufsize: int = 1 << 16):
        self.sock = sock
        self._buf = memoryview(bytearray(bufsize))
        self._lo = 0
        self._hi = 0

    def read_exact(self, n: int):
        """n bytes as a bytes-like; small reads are served from the
        buffer, which refills with bulk ``recv_into`` calls that sweep
        up every queued frame the kernel already holds."""
        avail = self._hi - self._lo
        if avail < n <= len(self._buf):
            if avail:  # compact the tail to the front before refilling
                self._buf[:avail] = self._buf[self._lo:self._hi]
            self._lo, self._hi = 0, avail
            while self._hi < n:
                r = self.sock.recv_into(self._buf[self._hi:],
                                        len(self._buf) - self._hi)
                if r == 0:
                    raise ConnectionError("peer closed")
                self._hi += r
            avail = self._hi
        if avail >= n:
            out = bytes(self._buf[self._lo:self._lo + n])
            self._lo += n
            return out
        out = bytearray(n)
        self.read_into(memoryview(out))
        return out

    def read_into(self, dest) -> None:
        """Fill ``dest`` (a writable memoryview) — buffered remainder
        first, then straight ``recv_into`` the destination: payload
        bytes cross exactly once from kernel to their final resting
        place."""
        n = len(dest)
        avail = self._hi - self._lo
        take = min(avail, n)
        if take:
            dest[:take] = self._buf[self._lo:self._lo + take]
            self._lo += take
        got = take
        while got < n:
            r = self.sock.recv_into(dest[got:], n - got)
            if r == 0:
                raise ConnectionError("peer closed")
            got += r


def _send_frame(sock, hdr, payload=b""):
    """One frame in (at most) one syscall: scatter-gather ``sendmsg``
    coalesces header+payload — no concat copy, no back-to-back
    ``sendall`` pair; partial sends finish with zero-copy memoryview
    slices.  Header-only frames (control ops, acks) ship as a single
    ``sendall``."""
    if not payload:
        sock.sendall(hdr)
        return
    sent = sock.sendmsg([hdr, memoryview(payload)])
    hl = len(hdr)
    if sent < hl:
        sock.sendall(memoryview(hdr)[sent:])
        sent = hl
    if sent < hl + len(payload):
        sock.sendall(memoryview(payload)[sent - hl:])


def _send_iov(sock, bufs):
    """MANY frames in one scatter-gather syscall: the pipelined chunk
    stream pays one ``sendmsg`` per credit half-window instead of one
    per chunk.  Partial sends resume with zero-copy memoryview slices."""
    total = sum(len(b) for b in bufs)
    sent = sock.sendmsg(bufs)
    while sent < total:
        i = 0
        while sent >= len(bufs[i]):
            sent -= len(bufs[i])
            i += 1
        bufs = [memoryview(bufs[i])[sent:]] + list(bufs[i + 1:])
        total = sum(len(b) for b in bufs)
        sent = sock.sendmsg(bufs)


def _drain_acks(sock, k):
    """Collect ``k`` header-only acks in bulk ``recv`` calls (the server
    writes them back-to-back, so one syscall typically sweeps them all).
    A server-side protocol error closes the connection, which surfaces
    here as ConnectionError."""
    if k > 0:
        _recv_exact(sock, _HDR.size * k)


def _send_msg(sock, op, win_id=0, slot=0, mode=0, p=0.0, payload=b"",
              trace=0):
    _send_frame(
        sock, _HDR.pack(op, win_id, slot, mode, len(payload), p, trace),
        payload,
    )


# the per-chunk credit ack, precomputed once: the hottest server->client
# frame, sent once per chunk of every deposit
_ACK_CHUNK = _HDR.pack(_OP_CHUNK, 0, 0, 0, 0, 0.0, 0)


def _recv_msg(sock):
    # trace rides LAST in the tuple so existing payload/mode indexing
    # ([5], [3], ...) is unchanged
    op, win_id, slot, mode, nbytes, p, trace = _HDR.unpack(
        _recv_exact(sock, _HDR.size))
    payload = _recv_exact(sock, nbytes) if nbytes else b""
    return op, win_id, slot, mode, p, payload, trace


class _Slot:
    __slots__ = ("data", "p", "version", "trace", "wseq", "drained")

    def __init__(self, nbytes: int):
        self.data = bytearray(nbytes)
        self.p = 0.0
        self.version = 0
        self.trace = 0  # trace-context word of the last deposit
        # chunk-stream seq: even = settled, odd = a deposit is streaming
        # into the slot (readers wait on the server's store_cond)
        self.wseq = 0
        # drained marker, the shm v2 trick: drained == version means the
        # slot is LOGICALLY zero (mass 0) without touching the payload
        # bytes — collect is one comparison + two stores, O(1)
        self.drained = 0


class _WinStore:
    """One window's rank-local state, owned by the server thread."""

    def __init__(self, maxd: int, nbytes: int, dtype):
        self.nbytes = nbytes
        self.dtype = np.dtype(dtype)
        self.mail = [_Slot(nbytes) for _ in range(max(maxd, 1))]
        self.exposed = _Slot(nbytes)


class _Server:
    """Per-rank mailbox server: owns this rank's slots/exposed/mutex (and
    the barrier + registry on rank 0).  Thread-per-connection; handlers are
    short critical sections under one lock (mutex/barrier waits use
    conditions so they never hold it)."""

    def __init__(self, rank: int, nranks: int, host: str, port: int = 0):
        self.rank = rank
        self.nranks = nranks
        self.lock = threading.Lock()
        self.windows: Dict[int, _WinStore] = {}
        # chunk-stream completion/drain notifications for readers of a
        # mid-stream slot (wraps the SAME lock as the store)
        self.store_cond = threading.Condition(self.lock)
        # open chunk streams: (win_id, slot) -> state.  Exactly one
        # writer owns a mailbox slot by construction, so the key needs
        # no writer component; the owning connection is recorded so a
        # disconnect can drain exactly its own torn streams.
        self.streams: Dict[Tuple[int, int], dict] = {}
        # mutex (this rank's): the CONNECTION holding it, or None — owner
        # tracking lets a dead holder's disconnect release the lock
        self.mutex_cond = threading.Condition()
        self.mutex_owner = None
        # barrier state (rank 0 only)
        self.bar_cond = threading.Condition()
        self.bar_count = 0
        self.bar_gen = 0
        # registry (rank 0 only)
        self.reg_cond = threading.Condition()
        self.registry: Dict[int, str] = {}
        # liveness leases (rank-0 coordinator only): rank -> last-renewal
        # stamp on THIS server's monotonic clock.  Ranks heartbeat the
        # coordinator, survivors query lease AGE (clock-transportable,
        # unlike the raw stamp) — the tcp analogue of the shm transport's
        # per-rank liveness words.
        self.lease_lock = threading.Lock()
        self.leases: Dict[int, float] = {}
        # elastic-membership rendezvous (rank-0 coordinator only): the
        # monotone fresh-rank counter (seeded past the launch world — a
        # dead rank's id is never reissued) and the membership-epoch
        # word.  The multi-host analogue of the shm membership board
        # (resilience/join.py) for deployments where joiner and members
        # share no filesystem.
        self.join_lock = threading.Lock()
        self.next_join_rank = nranks
        self.membership_epoch = 0
        # one-shot latch for the BFTPU_CHAOS_DROP_CHUNK disconnect hook
        self._chaos_dropped = False
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(nranks * 4 + 8)
        self.port = self.sock.getsockname()[1]
        self._stop = False
        self.thread = threading.Thread(target=self._accept_loop, daemon=True)
        self.thread.start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _handle_chunk(self, conn, rd, win_id, slot, mode, p, nbytes,
                      trace):
        """One ``_OP_CHUNK`` frame: open the stream on chunk 0, commit
        the chunk into the mail slot in ascending order
        (``TCP_CHUNK_COMMIT_IN_ORDER``), ack it (the sender's credit).
        Any protocol violation drops the connection — the writer sees
        ConnectionError instead of a corrupted slot.

        Validation is header-driven so a RAW put chunk can be received
        STRAIGHT into the mail slot (``rd.read_into``) — payload bytes
        cross kernel→slot exactly once, with no staging buffer.  The
        reception happens outside the server lock: the stream state
        machine already serializes the slot (one writer per slot by
        construction) and readers wait out the odd ``wseq``."""
        idx = mode >> 8
        code = (mode >> 1) & 0x3
        acc = mode & 1
        with self.lock:
            w = self.windows[win_id]
            s = w.mail[slot]
            key = (win_id, slot)
            st = self.streams.get(key)
            if st is None:
                if idx != 0:
                    logger.error(
                        "rank %d mailbox: chunk stream %d[%d] opened at "
                        "chunk %d — dropping connection",
                        self.rank, win_id, slot, idx,
                    )
                    raise ConnectionError("chunk stream opened mid-sequence")
                fresh = s.drained == s.version
                if acc and fresh:
                    # accumulating onto a LOGICALLY zero slot: the bytes
                    # may still hold the drained deposit — swap in a
                    # zeroed buffer (calloc; no memset of the old one)
                    s.data = bytearray(w.nbytes)
                st = self.streams[key] = {
                    "conn": conn, "next": 0, "acc": acc,
                    "fresh": fresh, "elems": 0,
                }
                s.wseq += 1  # odd: a deposit is streaming into the slot
            if st["conn"] is not conn or st["next"] != idx \
                    or st["acc"] != acc:
                logger.error(
                    "rank %d mailbox: chunk %d to %d[%d] violates stream "
                    "order (expected %d) — dropping connection",
                    self.rank, idx, win_id, slot, st["next"],
                )
                raise ConnectionError("out-of-order chunk commit")
            item = w.dtype.itemsize
            if code == wire_codec.WIRE_RAW:
                cnt = nbytes // item
                endbyte = int(trace) * item + nbytes
            elif code == wire_codec.WIRE_BF16:
                cnt = nbytes // 2
                endbyte = (int(trace) + cnt) * item
            else:
                cnt = nbytes
                endbyte = (int(trace) + cnt) * item
            off = int(trace)  # element offset rides the trace field
            if endbyte > w.nbytes:
                raise ConnectionError("chunk overruns window")
            st["next"] = idx + 1
            st["elems"] += cnt
            drop_n = _chunk_drop_after()
            if drop_n >= 0 and idx + 1 >= drop_n \
                    and not self._chaos_dropped:
                # one-shot chaos disconnect: the stream dies UNCOMMITTED
                # (the disconnect drain restores the slot), the writer's
                # session resume replays it from chunk 0
                self._chaos_dropped = True
                raise ConnectionError("chaos: scheduled mid-stream drop")
            do_acc = acc and not st["fresh"]
            dest = (memoryview(s.data)[off * item:off * item + nbytes]
                    if code == wire_codec.WIRE_RAW and not do_acc else None)
        if dest is not None:
            rd.read_into(dest)  # zero-copy commit: kernel -> slot
        else:
            payload = rd.read_exact(nbytes)
            decoded = wire_codec.decode_chunk(payload, code, p, w.dtype,
                                              cnt)
            with self.lock:
                region = np.frombuffer(s.data, w.dtype, count=cnt,
                                       offset=off * item)
                if do_acc:
                    region += decoded
                else:
                    region[:] = decoded
        conn.sendall(_ACK_CHUNK)

    def _handle_commit(self, conn, win_id, slot, mode, p, trace):
        """The ``_OP_COMMIT`` frame: version and push-sum mass advance
        ONLY here, after every chunk landed
        (``TCP_DEPOSIT_COMMITS_AFTER_PAYLOAD``) — a writer that dies
        mid-stream committed zero mass, which is what makes the
        disconnect drain sound."""
        nchunks = mode >> 1
        acc = mode & 1
        with self.lock:
            w = self.windows[win_id]
            s = w.mail[slot]
            st = self.streams.pop((win_id, slot), None)
            if st is None or st["conn"] is not conn \
                    or st["next"] != nchunks \
                    or st["elems"] * w.dtype.itemsize != w.nbytes:
                logger.error(
                    "rank %d mailbox: commit of %d[%d] without a complete "
                    "stream (%s) — dropping connection",
                    self.rank, win_id, slot,
                    "no stream" if st is None else
                    f"{st['next']}/{nchunks} chunks, {st['elems']} elems",
                )
                raise ConnectionError("commit without a complete stream")
            if acc and not st["fresh"]:
                s.p += p
            else:
                s.p = p
            s.version += 1
            s.wseq += 1  # even again: the deposit is settled
            if trace:
                s.trace = trace
            self.store_cond.notify_all()
        _send_msg(conn, _OP_COMMIT)

    def _drain_conn_streams(self, conn):
        """Disconnect drain (``TCP_DEAD_WRITER_DRAIN_STEPS``): any slot
        the dying connection left mid-stream is restored to the
        logical-zero drained state — evenize the seq so readers stop
        waiting, mark drained, clear the stream registration.  The torn
        deposit committed zero mass (version unchanged), so heal-time
        ledger accounting sees it as drained pending."""
        reg = _telemetry.get_registry()
        with self.lock:
            for key, st in list(self.streams.items()):
                if st["conn"] is not conn:
                    continue
                w = self.windows.get(key[0])
                if w is not None:
                    s = w.mail[key[1]]
                    s.wseq += 1            # 1. evenize_wseq
                    s.drained = s.version  # 2. mark_drained (reads zeros)
                    s.p = 0.0
                del self.streams[key]      # 3. clear_stream
                self.store_cond.notify_all()
                if reg.enabled:
                    reg.counter("tcp.mid_stream_drains").inc()
                    reg.journal("tcp_mid_stream_drain", win_id=key[0],
                                slot=key[1], rank=self.rank)

    def _serve_conn(self, conn):
        rd = _BufReader(conn)
        try:
            while True:
                op, win_id, slot, mode, nbytes, p, trace = _HDR.unpack(
                    rd.read_exact(_HDR.size))
                if op == _OP_CHUNK:
                    # payload handled inside (zero-copy into the slot)
                    self._handle_chunk(conn, rd, win_id, slot, mode, p,
                                       nbytes, trace)
                    continue
                payload = rd.read_exact(nbytes) if nbytes else b""
                if op == _OP_COMMIT:
                    self._handle_commit(conn, win_id, slot, mode, p, trace)
                elif op == _OP_WRITE:
                    with self.lock:
                        w = self.windows[win_id]
                        s = w.mail[slot]
                        if len(payload) != w.nbytes:
                            # log, then drop the faulty request AND the
                            # connection: the writer sees ConnectionError at
                            # the ack instead of corrupting the slot (a
                            # bytearray slice-assign would silently RESIZE it)
                            logger.error(
                                "rank %d mailbox: win write to %d[%d]: "
                                "payload %dB != window %dB — dropping "
                                "connection", self.rank, win_id, slot,
                                len(payload), w.nbytes,
                            )
                            raise ConnectionError("size mismatch")
                        if mode == 1 and w.dtype.kind == "f" \
                                and s.drained != s.version:
                            a = np.frombuffer(bytes(s.data), w.dtype) + \
                                np.frombuffer(payload, w.dtype)
                            s.data[:] = a.tobytes()
                            s.p += p
                        else:
                            # put — or accumulate onto a logically-zero
                            # (drained) slot, which is just a put
                            s.data[:] = payload
                            s.p = p
                        s.version += 1
                        if trace:
                            s.trace = trace
                    _send_msg(conn, op)  # ack → MPI_Win_flush semantics
                elif op == _OP_READ_EXPOSED:
                    with self.lock:
                        w = self.windows[win_id]
                        s = w.exposed
                        data, pv = bytes(s.data), s.p
                        ver = s.version
                    _send_msg(conn, op, win_id, ver, 0, pv, data)
                elif op == _OP_MUTEX_ACQ:
                    with self.mutex_cond:
                        while self.mutex_owner is not None:
                            self.mutex_cond.wait()
                        self.mutex_owner = conn
                    _send_msg(conn, op)
                elif op == _OP_MUTEX_REL:
                    with self.mutex_cond:
                        if self.mutex_owner is conn:
                            self.mutex_owner = None
                            self.mutex_cond.notify()
                    _send_msg(conn, op)
                elif op == _OP_BARRIER:
                    with self.bar_cond:
                        gen = self.bar_gen
                        self.bar_count += 1
                        if self.bar_count == self.nranks:
                            self.bar_count = 0
                            self.bar_gen += 1
                            self.bar_cond.notify_all()
                        else:
                            while self.bar_gen == gen:
                                self.bar_cond.wait()
                    _send_msg(conn, op)
                elif op == _OP_REGISTER:
                    r = slot
                    addr = payload.decode()
                    with self.reg_cond:
                        self.registry[r] = addr
                        if len(self.registry) == self.nranks:
                            self.reg_cond.notify_all()
                        else:
                            while len(self.registry) < self.nranks:
                                self.reg_cond.wait()
                        table = "\n".join(
                            f"{k} {v}" for k, v in sorted(self.registry.items())
                        ).encode()
                    _send_msg(conn, op, payload=table)
                elif op == _OP_BARRIER_T:
                    # timed barrier: the COORDINATOR owns the retraction
                    # (client-side socket timeouts cannot un-arrive), so a
                    # timed-out rank leaves the count exactly as if it had
                    # never arrived and a later barrier is unharmed
                    timed_out = 0
                    with self.bar_cond:
                        gen = self.bar_gen
                        self.bar_count += 1
                        if self.bar_count == self.nranks:
                            self.bar_count = 0
                            self.bar_gen += 1
                            self.bar_cond.notify_all()
                        else:
                            deadline = time.monotonic() + max(0.0, p)
                            while self.bar_gen == gen:
                                left = deadline - time.monotonic()
                                if left <= 0:
                                    break
                                self.bar_cond.wait(left)
                            if self.bar_gen == gen:
                                self.bar_count -= 1  # retract arrival
                                timed_out = 1
                    _send_msg(conn, op, mode=timed_out)
                elif op == _OP_HEARTBEAT:
                    with self.lease_lock:
                        self.leases[slot] = time.monotonic()
                    _send_msg(conn, op)
                elif op == _OP_LIVENESS:
                    with self.lease_lock:
                        stamp = self.leases.get(slot, 0.0)
                    age = (time.monotonic() - stamp) if stamp > 0 else -1.0
                    _send_msg(conn, op, p=age)
                elif op == _OP_CLOCK:
                    # coordinator clock read for the min-RTT offset
                    # estimator (bluefog_tpu.tracing.clock): reply as
                    # late as possible so queueing before the read only
                    # widens the client's RTT bound, never biases it
                    _send_msg(conn, op, p=time.monotonic())
                elif op == _OP_JOIN_RANK:
                    with self.join_lock:
                        granted = self.next_join_rank
                        self.next_join_rank += 1
                    _send_msg(conn, op, slot=granted)
                elif op == _OP_EPOCH:
                    # mode 1 publishes (monotone, like
                    # shm_native.publish_membership_epoch), mode 0 reads;
                    # either way the reply carries the current word
                    with self.join_lock:
                        if mode == 1 and slot > self.membership_epoch:
                            self.membership_epoch = slot
                        e = self.membership_epoch
                    _send_msg(conn, op, slot=e)
                elif op == _OP_PING:
                    _send_msg(conn, op)
                else:
                    raise ValueError(f"bad op {op}")
        except (ConnectionError, OSError):
            pass
        finally:
            # a dying holder must not leave the mutex locked forever
            with self.mutex_cond:
                if self.mutex_owner is conn:
                    self.mutex_owner = None
                    self.mutex_cond.notify()
            # ... nor its slot torn: drain any stream it left mid-flight
            self._drain_conn_streams(conn)
            conn.close()

    def stop(self):
        self._stop = True
        # shutdown() wakes a thread blocked in accept() (close() alone
        # does not on Linux — the zombie thread would keep accepting on
        # the fd number once the kernel reuses it for a later listener)
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.thread.join(timeout=5.0)


class _Peers:
    """Lazy persistent client connections, one per destination rank.
    One request/response at a time per peer (guarded by a lock) — the
    caller is single-threaded in practice, the lock makes it safe anyway."""

    def __init__(self, table: Dict[int, str]):
        self.table = table
        self.conns: Dict[int, socket.socket] = {}
        self.locks: Dict[int, threading.Lock] = {}

    def _connect(self, rank: int) -> socket.socket:
        """Get-or-create the persistent connection (caller holds the
        per-peer lock)."""
        conn = self.conns.get(rank)
        if conn is None:
            host, port = self.table[rank].rsplit(":", 1)
            conn = socket.create_connection((host, int(port)), timeout=60)
            # a bounded deadline replaces the old unbounded wait: a
            # request to a DEAD peer must eventually surface as a
            # PeerTimeoutError naming the rank, not a silent hang
            conn.settimeout(peer_timeout_s())
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self.conns[rank] = conn
        return conn

    def _evict(self, rank: int, conn) -> None:
        # a half-done exchange leaves the stream unusable (a late reply
        # would be mis-paired with the next request) — drop the socket so
        # the NEXT request reconnects instead of failing forever
        self.conns.pop(rank, None)
        try:
            conn.close()
        except OSError:
            pass

    def _backoff(self, rank: int, attempt: int, opname: str) -> None:
        """One bounded full-jitter backoff step before a reconnect.

        Sampling ``uniform(0, min(cap, base * 2**attempt))`` instead of
        sleeping the deterministic bound decorrelates a fleet that lost
        the same peer at the same instant (publisher restart → every
        replica reconnecting in lockstep, a thundering herd)."""
        delay = _jitter_rng.uniform(
            0.0, min(tcp_backoff_s() * (2 ** attempt), 2.0))
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.histogram("tcp.retry_backoff_s", op=opname).observe(delay)
            reg.journal("tcp_retry", peer_rank=rank, op=opname,
                        attempt=attempt + 1, backoff_s=delay)
        if delay > 0:
            time.sleep(delay)

    def _note_reconnect(self, opname: str) -> None:
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("tcp.reconnects", op=opname).inc()

    def _timeout_error(self, rank: int, opname: str) -> PeerTimeoutError:
        reg = _telemetry.get_registry()
        addr = self.table.get(rank)
        if reg.enabled:
            reg.counter("tcp.timeouts", op=opname).inc()
            reg.journal("peer_timeout", peer_rank=rank, addr=addr,
                        op=opname, deadline_s=peer_timeout_s())
        tr = _tracing.get_tracer()
        if tr.enabled:
            tr.instant(f"peer_timeout:{opname}", aux=rank)
            tr.dump_flight(f"PeerTimeoutError:{opname}:r{rank}")
        return PeerTimeoutError(
            f"rank {rank} ({addr}) did not respond to op "
            f"{opname} within {peer_timeout_s()}s (set "
            f"BFTPU_PEER_TIMEOUT_S to adjust)",
            rank=rank, addr=addr, op=opname)

    def request(self, rank: int, op, win_id=0, slot=0, mode=0, p=0.0,
                payload=b"", trace=0):
        reg = _telemetry.get_registry()
        opname = _OP_NAMES.get(op, str(op))
        t0 = time.perf_counter_ns() if reg.enabled else 0
        lock = self.locks.setdefault(rank, threading.Lock())
        with lock:
            attempt = 0
            while True:
                conn = None
                try:
                    conn = self._connect(rank)
                    if attempt:
                        self._note_reconnect(opname)
                    _send_msg(conn, op, win_id, slot, mode, p, payload,
                              trace=trace)
                    reply = _recv_msg(conn)
                    break
                except socket.timeout as e:
                    # deliberately NOT retried: the peer is connected
                    # but silent — reconnecting can't help, and the
                    # failure detector owns this verdict
                    self._evict(rank, conn)
                    raise self._timeout_error(rank, opname) from e
                except (ConnectionError, OSError):
                    if conn is not None:
                        self._evict(rank, conn)
                    # a failure INSIDE _connect (conn is None) never
                    # reached the server, so any op may retry it; a
                    # mid-exchange drop replays only idempotent ops
                    replayable = conn is None or op in _IDEMPOTENT_OPS
                    if not replayable or attempt >= tcp_retries():
                        raise
                    self._backoff(rank, attempt, opname)
                    attempt += 1
        if reg.enabled:
            reg.counter("tcp.round_trips", op=opname).inc()
            reg.counter("tcp.acks").inc()
            reg.counter("tcp.bytes_sent").add(_HDR.size + len(payload))
            reg.counter("tcp.bytes_received").add(_HDR.size + len(reply[5]))
            reg.histogram("tcp.rtt_s", op=opname).observe(
                (time.perf_counter_ns() - t0) / 1e9)
        return reply

    def deposit_chunked(self, rank: int, win_id: int, slot: int,
                        arr: np.ndarray, p: float, accumulate: bool,
                        trace: int, residual: Optional[np.ndarray] = None,
                        src_rank: int = -1) -> None:
        """Stream ONE window deposit as pipelined chunk frames + a commit.

        The sender runs ahead of the acks under a credit window
        (``BFTPU_TCP_WINDOW_CHUNKS``): it collects one ack per chunk
        frame only once that many are outstanding, then sends the
        ``_OP_COMMIT`` frame carrying the exact mass ``p`` and drains
        the remaining credits — the whole deposit costs ~one RTT
        instead of one per payload byte window.

        ``residual`` (same dtype/size as ``arr``, flattened) enables
        error-feedback quantization: the carry is folded into the
        outgoing values and re-settled per chunk against what the wire
        actually delivered, so ``sum(delivered) + residual`` always
        equals ``sum(inputs)`` — mass conservation at the value level.
        """
        reg = _telemetry.get_registry()
        t0 = time.perf_counter_ns() if reg.enabled else 0
        code = wire_codec.wire_code() if arr.dtype.kind == "f" \
            else wire_codec.WIRE_RAW
        buf = arr.ravel() if residual is None else arr.ravel() + residual
        elems = max(_chunk_bytes() // arr.dtype.itemsize, 1)
        total = buf.size
        nchunks = (total + elems - 1) // elems
        credit = window_chunks()
        acc = 1 if accumulate else 0
        kill_after = _chunk_kill_after(src_rank)
        wire_bytes = 0
        lock = self.locks.setdefault(rank, threading.Lock())
        with lock:
            attempt = 0
            while True:
                conn = None
                commit_sent = False
                wire_bytes = 0
                try:
                    conn = self._connect(rank)
                    if attempt:
                        self._note_reconnect("write_chunked")
                    # frames coalesce into half-credit-window sendmsg
                    # iovecs (one syscall apiece), acks drain in matching
                    # bulk recvs; the chaos kill path flushes per frame so
                    # the "die after n chunk frames" schedule stays exact
                    batch = max(credit // 2, 1) if kill_after < 0 else 1
                    outstanding = 0
                    pend = 0
                    iov = []
                    for idx in range(nchunks):
                        lo = idx * elems
                        hi = min(lo + elems, total)
                        view = buf[lo:hi]
                        code_i, payload, scale = wire_codec.encode_chunk(
                            view, code)
                        iov.append(_HDR.pack(
                            _OP_CHUNK, win_id, slot,
                            (idx << 8) | (code_i << 1) | acc,
                            len(payload), scale, lo))
                        if payload:
                            iov.append(payload)
                        pend += 1
                        wire_bytes += _HDR.size + len(payload)
                        if residual is not None:
                            # pure function of `buf` (encode is
                            # deterministic), so a stream REPLAY after a
                            # disconnect rewrites the same residuals —
                            # no pre-attempt snapshot needed
                            if code_i == wire_codec.WIRE_RAW:
                                residual[lo:hi] = 0  # wire was exact
                            else:
                                residual[lo:hi] = \
                                    view - wire_codec.decode_chunk(
                                        payload, code_i, scale,
                                        arr.dtype, hi - lo)
                        if pend >= batch:
                            over = outstanding + pend - credit
                            if over > 0:  # honor the credit window FIRST
                                _drain_acks(conn, over)
                                outstanding -= over
                            _send_iov(conn, iov)
                            iov = []
                            outstanding += pend
                            pend = 0
                        if kill_after >= 0 and idx + 1 >= kill_after:
                            from bluefog_tpu.resilience.chaos import \
                                kill_self
                            kill_self()
                    if pend:
                        over = outstanding + pend - credit
                        if over > 0:
                            _drain_acks(conn, over)
                            outstanding -= over
                        _send_iov(conn, iov)
                        outstanding += pend
                    # point of no replay: once any commit-frame byte may
                    # be on the wire the server MAY have advanced the
                    # slot version and mass — re-sending would
                    # double-commit, so failures past here raise
                    commit_sent = True
                    _send_msg(conn, _OP_COMMIT, win_id, slot,
                              (nchunks << 1) | acc, float(p), trace=trace)
                    wire_bytes += _HDR.size
                    _drain_acks(conn, outstanding + 1)
                    break
                except socket.timeout as e:
                    self._evict(rank, conn)
                    raise self._timeout_error(rank, "write_chunked") from e
                except (ConnectionError, OSError):
                    if conn is not None:
                        self._evict(rank, conn)
                    # an UNCOMMITTED stream is replay-safe: the server
                    # advances version/mass only at _OP_COMMIT
                    # (TCP_DEPOSIT_COMMITS_AFTER_PAYLOAD) and its
                    # disconnect handler drained the torn stream, so the
                    # retry re-opens chunk 0 against a clean slot
                    if commit_sent or attempt >= tcp_retries():
                        raise
                    self._backoff(rank, attempt, "write_chunked")
                    attempt += 1
        if reg.enabled:
            reg.counter("tcp.round_trips", op="write_chunked").inc()
            reg.counter("tcp.acks").add(nchunks + 1)
            reg.counter("tcp.chunks_sent").add(nchunks)
            reg.counter("tcp.bytes_sent").add(wire_bytes)
            reg.counter("tcp.bytes_received").add(_HDR.size * (nchunks + 1))
            # raw vs wire payload volume: the measured compression ratio
            # (bench.py wire_compression_ratio) is wire/raw
            reg.counter("tcp.raw_payload_bytes").add(arr.nbytes)
            reg.counter("tcp.wire_payload_bytes").add(wire_bytes)
            reg.histogram("tcp.rtt_s", op="write_chunked").observe(
                (time.perf_counter_ns() - t0) / 1e9)

    def close(self):
        for c in self.conns.values():
            try:
                c.close()
            except OSError:
                pass
        self.conns.clear()


class _JobRuntime:
    """Shared per-process runtime: server + peer table (created once, used
    by the job handle and every window)."""

    _by_key: Dict[Tuple[str, int], "_JobRuntime"] = {}
    _cls_lock = threading.Lock()

    def __init__(self, job: str, rank: int, nranks: int, coord: str):
        self.job = job
        self.rank = rank
        self.nranks = nranks
        host = os.environ.get("BLUEFOG_ISLAND_HOST", "127.0.0.1")
        self.server = _Server(rank, nranks, host)
        self._win_ids: Dict[str, int] = {}
        self._next_win = 0
        chost, cport = coord.rsplit(":", 1)
        if rank == 0:
            # rank 0 additionally runs the coordinator (rendezvous +
            # barrier) on the well-known port
            self._coord_server = _Server(rank, nranks, chost, int(cport))
        else:
            self._coord_server = None
        # register with the coordinator (retry while rank 0 comes up)
        my_addr = f"{host}:{self.server.port}"
        deadline = time.time() + 60
        while True:
            try:
                coord_conn = socket.create_connection(
                    (chost, int(cport)), timeout=5
                )
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)
        # registration/barrier replies wait on OTHER ranks, but never
        # forever: a dead sibling must surface as PeerTimeoutError(-1)
        # within the configured deadline, not hang the job
        coord_conn.settimeout(peer_timeout_s())
        coord_conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send_msg(coord_conn, _OP_REGISTER, slot=rank, payload=my_addr.encode())
        table_raw = _recv_msg(coord_conn)[5]
        self._coord_conn = coord_conn  # kept open: barrier rides on it
        self._coord_addr = (chost, int(cport))
        # leases ride a SEPARATE lazily-created coordinator connection: the
        # heartbeat thread must keep renewing while the main thread blocks
        # inside a barrier on _coord_conn
        self._lease_conn: Optional[socket.socket] = None
        self._lease_lock = threading.Lock()
        table = {}
        for line in table_raw.decode().splitlines():
            k, v = line.split()
            table[int(k)] = v
        self.peers = _Peers(table)

    @classmethod
    def get(cls, job: str, rank: int, nranks: int, coord: str) -> "_JobRuntime":
        with cls._cls_lock:
            key = (job, rank)
            rt = cls._by_key.get(key)
            if rt is None:
                rt = cls(job, rank, nranks, coord)
                cls._by_key[key] = rt
            return rt

    @classmethod
    def drop(cls, job: str, rank: int):
        with cls._cls_lock:
            rt = cls._by_key.pop((job, rank), None)
        if rt is not None:
            rt.peers.close()
            try:
                rt._coord_conn.close()
            except OSError:
                pass
            if rt._lease_conn is not None:
                try:
                    rt._lease_conn.close()
                except OSError:
                    pass
            rt.server.stop()
            if rt._coord_server is not None:
                rt._coord_server.stop()

    def win_id(self, name: str) -> int:
        # window ids must agree across ranks: windows are created
        # collectively in the same order (enforced by the create barrier),
        # so a per-process counter stays in sync
        if name not in self._win_ids:
            self._win_ids[name] = self._next_win
            self._next_win += 1
        return self._win_ids[name]

    def barrier(self, timeout: Optional[float] = None):
        with self.peers.locks.setdefault(-1, threading.Lock()):
            mode = 0
            try:
                if timeout is None:
                    _send_msg(self._coord_conn, _OP_BARRIER)
                    _recv_msg(self._coord_conn)
                else:
                    # the coordinator owns the timed wait AND the arrival
                    # retraction; the socket deadline only covers the
                    # round trip on top of it
                    old = self._coord_conn.gettimeout()
                    self._coord_conn.settimeout(float(timeout) + 30.0)
                    try:
                        _send_msg(self._coord_conn, _OP_BARRIER_T,
                                  p=float(timeout))
                        mode = _recv_msg(self._coord_conn)[3]
                    finally:
                        self._coord_conn.settimeout(old)
            except socket.timeout as e:
                # NB socket.timeout IS TimeoutError (py3.10): only socket
                # waits happen inside this try, so the clause is unambiguous
                addr = "%s:%s" % self._coord_addr
                reg = _telemetry.get_registry()
                if reg.enabled:
                    reg.counter("tcp.timeouts", op="barrier").inc()
                    reg.journal("peer_timeout", peer_rank=0, addr=addr,
                                op="barrier")
                tr = _tracing.get_tracer()
                if tr.enabled:
                    tr.instant("peer_timeout:barrier", aux=0)
                    tr.dump_flight("PeerTimeoutError:barrier")
                raise PeerTimeoutError(
                    "coordinator (rank 0) did not answer the barrier "
                    f"within its deadline ({addr})",
                    rank=-1, addr=addr, op="barrier") from e
            if mode:
                raise TimeoutError(
                    f"barrier timed out after {timeout}s (rank {self.rank})")

    def _lease_request(self, op: int, rank: int) -> float:
        """One heartbeat/liveness round trip to the coordinator (own
        connection + lock: must work while barrier blocks _coord_conn)."""
        with self._lease_lock:
            conn = self._lease_conn
            if conn is None:
                conn = socket.create_connection(self._coord_addr, timeout=5)
                conn.settimeout(peer_timeout_s())
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._lease_conn = conn
            try:
                _send_msg(conn, op, slot=rank)
                return _recv_msg(conn)[4]
            except (socket.timeout, ConnectionError, OSError):
                self._lease_conn = None
                try:
                    conn.close()
                except OSError:
                    pass
                raise


class TcpShmJob:
    """Job handle with the shm-job interface (barrier + mutexes)."""

    def __init__(self, job: str, rank: int, nranks: int, coord: str):
        self.rt = _JobRuntime.get(job, rank, nranks, coord)
        self.job = job
        self.rank = rank

    def barrier(self, timeout: Optional[float] = None) -> None:
        self.rt.barrier(timeout=timeout)

    def mutex_acquire(self, rank: int) -> None:
        self.rt.peers.request(rank, _OP_MUTEX_ACQ)

    def mutex_release(self, rank: int) -> None:
        self.rt.peers.request(rank, _OP_MUTEX_REL)

    # -- liveness leases (coordinator-mediated; see FailureDetector) -------
    def heartbeat(self) -> None:
        """Renew my lease at the rank-0 coordinator."""
        self.rt._lease_request(_OP_HEARTBEAT, self.rank)

    def liveness(self, rank: int) -> float:
        """Last lease renewal of ``rank``, mapped onto MY monotonic clock
        (0.0 = never renewed).  The coordinator reports lease AGE — ages
        transport across hosts; raw stamps do not."""
        age = self.rt._lease_request(_OP_LIVENESS, rank)
        if age < 0:
            return 0.0
        return max(0.0, time.monotonic() - age)

    def clock_probe(self) -> Tuple[float, float, float]:
        """One NTP-style exchange with the rank-0 coordinator: returns
        ``(t0, remote, t1)`` — local send time, the coordinator's
        monotonic clock, local receive time — for
        :class:`bluefog_tpu.tracing.ClockEstimator`.  Rides the lease
        connection, which works while a barrier blocks the main one."""
        t0 = time.monotonic()
        remote = self.rt._lease_request(_OP_CLOCK, self.rank)
        return t0, remote, time.monotonic()

    def close(self, unlink: bool = False) -> None:
        del unlink
        _JobRuntime.drop(self.job, self.rank)


class TcpShmWindow:
    """Window handle with the shm-window interface over the TCP runtime."""

    #: no fused scale: ``write`` has no ``scale`` kwarg — islands
    #: pre-multiplies before a TCP deposit (capability-linted).
    supports_scale = False

    CAPS = _caps.TransportCaps(
        name="tcp",
        fused_accumulate=True,
        fused_scale=False,       # == supports_scale
        fused_combine=False,     # no combine()/update_fused()
        zero_copy_collect=True,  # collect swaps the slot buffer, O(1)
        chunked_streaming=True,  # deposit_chunked + credit window
        wire_quantization=True,  # BFTPU_WIRE_DTYPE + EF residual
        resume=True,             # session resume replays _IDEMPOTENT_OPS
    )

    def __init__(self, job: str, name: str, rank: int, nranks: int,
                 maxd: int, shape, dtype, coord: str):
        self.rt = _JobRuntime.get(job, rank, nranks, coord)
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.nbytes = int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize
        self._id = self.rt.win_id(name)
        with self.rt.server.lock:
            self.rt.server.windows[self._id] = _WinStore(
                maxd, self.nbytes, self.dtype
            )
        # trace words staged by trace_stamp, consumed (popped) by the
        # immediately-following write() — same-thread call pattern
        self._trace_out: Dict[Tuple[int, int], int] = {}
        # error-feedback residuals, one per (dst, slot) out-edge, created
        # lazily when a quantized wire dtype is configured — the carry
        # survives edge demotion (flushed on the next deposit) and only
        # dies with the window (or the peer)
        self._residual: Dict[Tuple[int, int], np.ndarray] = {}

    # -- local (owner-side) ops --------------------------------------------
    def _store(self) -> _WinStore:
        return self.rt.server.windows[self._id]

    def _await_settled(self, s: _Slot) -> None:
        """Wait out a mid-flight chunk stream (``wseq`` odd) before a
        payload read/reset — the commit or the dead-writer drain
        notifies.  Caller holds ``store_cond`` (== the server lock, which
        ``wait`` releases while blocked, so chunk frames keep landing)."""
        if not s.wseq & 1:
            return
        deadline = time.monotonic() + (peer_timeout_s() or 120.0)
        while s.wseq & 1:
            left = deadline - time.monotonic()
            if left <= 0:
                raise RuntimeError(
                    "mid-stream deposit never settled (writer alive but "
                    "stalled past BFTPU_PEER_TIMEOUT_S)")
            self.rt.server.store_cond.wait(min(left, 0.2))

    def trace_stamp(self, dst: int, slot: int, word: int,
                    writer=None) -> None:
        """Stage the trace-context word for the next write to (dst,
        slot); it rides the frame header of that write."""
        del writer
        self._trace_out[(int(dst), int(slot))] = int(word)

    def trace_peek(self, slot: int, src=None) -> int:
        del src
        with self.rt.server.lock:
            return self._store().mail[slot].trace

    def read(self, slot: int, collect: bool = False, src=None):
        del src
        srv = self.rt.server
        with srv.store_cond:
            s = self._store().mail[slot]
            self._await_settled(s)
            if s.drained == s.version:
                # logically zero: the drained marker spares both the
                # payload copy here and the memset on collect
                a = np.zeros(self.shape, self.dtype)
                p = 0.0
            elif collect:
                # collect takes the buffer itself (the slot is drained
                # anyway) and swaps in a fresh zeroed one — O(1), no
                # payload copy at all
                raw = s.data
                s.data = bytearray(self.nbytes)
                a = np.frombuffer(raw, self.dtype).reshape(self.shape)
                p = s.p
            else:
                a = np.frombuffer(s.data, self.dtype).reshape(
                    self.shape).copy()
                p = s.p
            ver = s.version
            if collect:
                # collect == read + drain in ONE critical section
                # (TCP_DRAINED_COLLECT_IS_ATOMIC)
                s.drained = s.version
                s.p = 0.0
        return a, p, ver

    def read_version(self, slot: int, src=None) -> int:
        # metadata-only: no _await_settled — a mid-stream slot reports
        # its pre-stream version (the stream commits later, by design)
        del src
        with self.rt.server.lock:
            return self._store().mail[slot].version

    def reset(self, slot: int, src=None) -> None:
        del src
        srv = self.rt.server
        with srv.store_cond:
            s = self._store().mail[slot]
            self._await_settled(s)
            s.drained = s.version
            s.p = 0.0

    def force_drain(self, slot: int, src=None) -> None:
        """Owner-side drain of a possibly-torn mail slot: the heal-path
        hook islands' dead-writer accounting calls on every transport
        (shm grew it in v2; this is the TCP twin).  Safe on a settled
        slot (just drops pending mass); on a mid-stream slot it applies
        ``TCP_DEAD_WRITER_DRAIN_STEPS`` without waiting for the
        disconnect handler."""
        del src
        srv = self.rt.server
        with srv.store_cond:
            s = self._store().mail[slot]
            if s.wseq & 1:
                s.wseq += 1            # 1. evenize_wseq
            s.drained = s.version      # 2. mark_drained
            s.p = 0.0
            srv.streams.pop((self._id, slot), None)  # 3. clear_stream
            srv.store_cond.notify_all()
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("tcp.force_drains").inc()

    def expose(self, array, p: float = 1.0) -> None:
        a = np.ascontiguousarray(np.asarray(array, self.dtype))
        if a.nbytes != self.nbytes:
            raise ValueError(
                f"expose payload has {a.nbytes} bytes but window "
                f"expects {self.nbytes} (shape {self.shape})"
            )
        try:
            src = a.view(np.uint8).data  # zero-copy byte view
        except (TypeError, ValueError):
            src = a.tobytes()
        with self.rt.server.lock:
            s = self._store().exposed
            s.data[:] = src  # single copy into the slot
            s.p = float(p)
            s.version += 1

    # -- remote (one-sided) ops --------------------------------------------
    def write(self, dst: int, slot: int, array, p: float = 1.0,
              accumulate: bool = False, writer=None) -> None:
        del writer
        if accumulate and self.dtype.kind != "f":
            raise TypeError(f"accumulate unsupported for dtype {self.dtype}")
        a = np.ascontiguousarray(np.asarray(array, self.dtype))
        if a.nbytes != self.nbytes:
            raise ValueError(
                f"win_put payload has {a.nbytes} bytes but window "
                f"expects {self.nbytes} (shape {self.shape})"
            )
        trace = self._trace_out.pop((int(dst), int(slot)), 0)
        if dst == self.rt.rank:
            # local fast path, same semantics (incl. the drained marker:
            # accumulate onto a logically-zero slot is a put)
            try:
                src = a.view(np.uint8).data  # zero-copy byte view
            except (TypeError, ValueError):
                src = a.tobytes()
            with self.rt.server.lock:
                s = self._store().mail[slot]
                if accumulate and s.drained != s.version:
                    # in-place: frombuffer on the bytearray is writable
                    cur = np.frombuffer(s.data, self.dtype)
                    cur += a.ravel()
                    s.p += float(p)
                else:
                    s.data[:] = src
                    s.p = float(p)
                s.version += 1
                if trace:
                    s.trace = trace
            return
        if tcp_chunked() and a.size:
            residual = None
            if self.dtype.kind == "f" \
                    and wire_codec.wire_code() != wire_codec.WIRE_RAW:
                key = (int(dst), int(slot))
                residual = self._residual.get(key)
                if residual is None:
                    residual = self._residual[key] = np.zeros(
                        a.size, self.dtype)
            self.rt.peers.deposit_chunked(
                dst, self._id, slot, a, float(p), accumulate, trace,
                residual=residual, src_rank=self.rt.rank)
            return
        try:
            # zero-copy byte view; the uint8 reinterpret also covers
            # ml_dtypes (bf16) arrays whose native buffers can't export
            payload = a.view(np.uint8).data
        except (TypeError, ValueError):
            payload = a.tobytes()
        self.rt.peers.request(
            dst, _OP_WRITE, self._id, slot, 1 if accumulate else 0,
            float(p), payload, trace=trace,
        )

    def read_exposed(self, src: int):
        if src == self.rt.rank:
            with self.rt.server.lock:
                s = self._store().exposed
                a = np.frombuffer(s.data, self.dtype).reshape(self.shape)
                return a.copy(), s.p, s.version
        _, _, ver, _, p, payload, _ = self.rt.peers.request(
            src, _OP_READ_EXPOSED, self._id
        )
        a = np.frombuffer(payload, self.dtype).reshape(self.shape)
        return a.copy(), p, ver

    def close(self, unlink: bool = False) -> None:
        del unlink
        self._residual.clear()
        with self.rt.server.lock:
            self.rt.server.windows.pop(self._id, None)

    def unlink_segments(self) -> None:
        pass  # in-memory store, freed at close
