"""TCP mailbox transport — the islands' cross-host (DCN) path.

Same window model and interface as the shared-memory transport
(:mod:`bluefog_tpu.native.shm_native`), carried over sockets so island
processes can live on DIFFERENT hosts: the deployment where each TPU pod
host runs one island and gossips parameters asynchronously over the
data-center network, exactly the role the reference's CUDA-aware MPI RMA
plays between its GPU nodes (``MPI_Win_create``/``MPI_Put`` over
IB/Ethernet, ``bluefog/common/mpi_controller.cc`` [U]; SURVEY.md §2.4).

Topology of responsibility (the passive-target model, unchanged):

- every rank runs a small **mailbox server thread** that OWNS that rank's
  state: its mail slots (one per in-neighbor per window), its exposed
  tensor, its mutex, and — on rank 0 — the job barrier;
- ``write``/``read_exposed`` are requests to the *destination's* server —
  the receiver's application code never participates (one-sided);
- ``read``/``collect``/``expose``/``reset`` touch only the local server's
  store (an in-process dict guarded by a lock) — no network;
- rendezvous: rank 0 additionally serves a registry where every rank posts
  its ``host:port`` and fetches the full table, so only ONE address
  (``BLUEFOG_ISLAND_COORD``) must be known up front — the analogue of
  ``bfrun``'s host list [U].

Wire format: 40-byte fixed header ``(op, win_id, slot, mode, nbytes, p,
trace)`` + raw payload bytes, over persistent connections (one per peer,
created lazily).  ``trace`` is the u64 trace-context word
(:func:`bluefog_tpu.tracing.pack_ctx`; 0 = tracing off) that lets the
merge CLI draw a flow arrow from the depositing span on the writer to
the collecting span on the owner.  No external dependencies.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from bluefog_tpu.common.logging_util import logger
from bluefog_tpu.resilience.detector import PeerTimeoutError
from bluefog_tpu.telemetry import registry as _telemetry
from bluefog_tpu.tracing import tracer as _tracing

# ops
_OP_WRITE = 1          # deposit into (my) mail slot: mode 0 put, 1 accumulate
_OP_READ_EXPOSED = 2   # return my exposed tensor
_OP_MUTEX_ACQ = 3
_OP_MUTEX_REL = 4
_OP_BARRIER = 5        # rank-0 only
_OP_REGISTER = 6       # rank-0 only: register rank -> addr, get table when full
_OP_PING = 7
_OP_BARRIER_T = 8      # rank-0 only: timed barrier, timeout rides in p
_OP_HEARTBEAT = 9      # rank-0 only: renew rank `slot`'s lease
_OP_LIVENESS = 10      # rank-0 only: age of rank `slot`'s lease (in p)
_OP_CLOCK = 11         # rank-0 only: coordinator's monotonic clock (in p)
_OP_JOIN_RANK = 12     # rank-0 only: grant a fresh global rank (in slot)
_OP_EPOCH = 13         # rank-0 only: membership-epoch word (read/publish)

#: human-readable op names: PeerTimeoutError context + telemetry labels
_OP_NAMES = {
    _OP_WRITE: "write", _OP_READ_EXPOSED: "read_exposed",
    _OP_MUTEX_ACQ: "mutex_acquire", _OP_MUTEX_REL: "mutex_release",
    _OP_BARRIER: "barrier", _OP_REGISTER: "register", _OP_PING: "ping",
    _OP_BARRIER_T: "barrier_timed", _OP_HEARTBEAT: "heartbeat",
    _OP_LIVENESS: "liveness", _OP_CLOCK: "clock",
    _OP_JOIN_RANK: "join_rank", _OP_EPOCH: "epoch",
}

# op, win_id, slot, mode, nbytes, p, trace — the trace word is LAST so
# pre-trace header fields keep their offsets on the wire
_HDR = struct.Struct("<iiiiqdQ")


def peer_timeout_s() -> Optional[float]:
    """Deadline for any single request/response round trip to a peer
    (``BFTPU_PEER_TIMEOUT_S``; <= 0 disables, restoring unbounded waits).
    The default is generous: mutex and barrier waits legitimately block
    while other ranks compute — the deadline exists to unstick survivors
    from a DEAD peer, not to police slow ones."""
    try:
        t = float(os.environ.get("BFTPU_PEER_TIMEOUT_S", "120"))
    except ValueError:
        t = 120.0
    return t if t > 0 else None


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    # preallocate + recv_into: a `buf += chunk` loop would copy O(n²/chunk)
    # bytes (measured 20x slowdown on multi-MB window payloads)
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed")
        got += r
    return buf  # bytearray: frombuffer/slice-assign/decode all accept it


def _send_msg(sock, op, win_id=0, slot=0, mode=0, p=0.0, payload=b"",
              trace=0):
    hdr = _HDR.pack(op, win_id, slot, mode, len(payload), p, trace)
    if not payload:
        sock.sendall(hdr)
        return
    # scatter-gather: no header+payload concat copy; finish partial sends
    # with zero-copy memoryview slices
    sent = sock.sendmsg([hdr, memoryview(payload)])
    hl = len(hdr)
    if sent < hl:
        sock.sendall(memoryview(hdr)[sent:])
        sent = hl
    if sent < hl + len(payload):
        sock.sendall(memoryview(payload)[sent - hl:])


def _recv_msg(sock):
    # trace rides LAST in the tuple so existing payload/mode indexing
    # ([5], [3], ...) is unchanged
    op, win_id, slot, mode, nbytes, p, trace = _HDR.unpack(
        _recv_exact(sock, _HDR.size))
    payload = _recv_exact(sock, nbytes) if nbytes else b""
    return op, win_id, slot, mode, p, payload, trace


class _Slot:
    __slots__ = ("data", "p", "version", "trace")

    def __init__(self, nbytes: int):
        self.data = bytearray(nbytes)
        self.p = 0.0
        self.version = 0
        self.trace = 0  # trace-context word of the last deposit


class _WinStore:
    """One window's rank-local state, owned by the server thread."""

    def __init__(self, maxd: int, nbytes: int, dtype):
        self.nbytes = nbytes
        self.dtype = np.dtype(dtype)
        self.mail = [_Slot(nbytes) for _ in range(max(maxd, 1))]
        self.exposed = _Slot(nbytes)


class _Server:
    """Per-rank mailbox server: owns this rank's slots/exposed/mutex (and
    the barrier + registry on rank 0).  Thread-per-connection; handlers are
    short critical sections under one lock (mutex/barrier waits use
    conditions so they never hold it)."""

    def __init__(self, rank: int, nranks: int, host: str, port: int = 0):
        self.rank = rank
        self.nranks = nranks
        self.lock = threading.Lock()
        self.windows: Dict[int, _WinStore] = {}
        # mutex (this rank's): the CONNECTION holding it, or None — owner
        # tracking lets a dead holder's disconnect release the lock
        self.mutex_cond = threading.Condition()
        self.mutex_owner = None
        # barrier state (rank 0 only)
        self.bar_cond = threading.Condition()
        self.bar_count = 0
        self.bar_gen = 0
        # registry (rank 0 only)
        self.reg_cond = threading.Condition()
        self.registry: Dict[int, str] = {}
        # liveness leases (rank-0 coordinator only): rank -> last-renewal
        # stamp on THIS server's monotonic clock.  Ranks heartbeat the
        # coordinator, survivors query lease AGE (clock-transportable,
        # unlike the raw stamp) — the tcp analogue of the shm transport's
        # per-rank liveness words.
        self.lease_lock = threading.Lock()
        self.leases: Dict[int, float] = {}
        # elastic-membership rendezvous (rank-0 coordinator only): the
        # monotone fresh-rank counter (seeded past the launch world — a
        # dead rank's id is never reissued) and the membership-epoch
        # word.  The multi-host analogue of the shm membership board
        # (resilience/join.py) for deployments where joiner and members
        # share no filesystem.
        self.join_lock = threading.Lock()
        self.next_join_rank = nranks
        self.membership_epoch = 0
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(nranks * 4 + 8)
        self.port = self.sock.getsockname()[1]
        self._stop = False
        self.thread = threading.Thread(target=self._accept_loop, daemon=True)
        self.thread.start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn):
        try:
            while True:
                op, win_id, slot, mode, p, payload, trace = _recv_msg(conn)
                if op == _OP_WRITE:
                    with self.lock:
                        w = self.windows[win_id]
                        s = w.mail[slot]
                        if len(payload) != w.nbytes:
                            # log, then drop the faulty request AND the
                            # connection: the writer sees ConnectionError at
                            # the ack instead of corrupting the slot (a
                            # bytearray slice-assign would silently RESIZE it)
                            logger.error(
                                "rank %d mailbox: win write to %d[%d]: "
                                "payload %dB != window %dB — dropping "
                                "connection", self.rank, win_id, slot,
                                len(payload), w.nbytes,
                            )
                            raise ConnectionError("size mismatch")
                        if mode == 1 and w.dtype.kind == "f":
                            a = np.frombuffer(bytes(s.data), w.dtype) + \
                                np.frombuffer(payload, w.dtype)
                            s.data[:] = a.tobytes()
                            s.p += p
                        else:
                            s.data[:] = payload
                            s.p = p
                        s.version += 1
                        if trace:
                            s.trace = trace
                    _send_msg(conn, op)  # ack → MPI_Win_flush semantics
                elif op == _OP_READ_EXPOSED:
                    with self.lock:
                        w = self.windows[win_id]
                        s = w.exposed
                        data, pv = bytes(s.data), s.p
                        ver = s.version
                    _send_msg(conn, op, win_id, ver, 0, pv, data)
                elif op == _OP_MUTEX_ACQ:
                    with self.mutex_cond:
                        while self.mutex_owner is not None:
                            self.mutex_cond.wait()
                        self.mutex_owner = conn
                    _send_msg(conn, op)
                elif op == _OP_MUTEX_REL:
                    with self.mutex_cond:
                        if self.mutex_owner is conn:
                            self.mutex_owner = None
                            self.mutex_cond.notify()
                    _send_msg(conn, op)
                elif op == _OP_BARRIER:
                    with self.bar_cond:
                        gen = self.bar_gen
                        self.bar_count += 1
                        if self.bar_count == self.nranks:
                            self.bar_count = 0
                            self.bar_gen += 1
                            self.bar_cond.notify_all()
                        else:
                            while self.bar_gen == gen:
                                self.bar_cond.wait()
                    _send_msg(conn, op)
                elif op == _OP_REGISTER:
                    r = slot
                    addr = payload.decode()
                    with self.reg_cond:
                        self.registry[r] = addr
                        if len(self.registry) == self.nranks:
                            self.reg_cond.notify_all()
                        else:
                            while len(self.registry) < self.nranks:
                                self.reg_cond.wait()
                        table = "\n".join(
                            f"{k} {v}" for k, v in sorted(self.registry.items())
                        ).encode()
                    _send_msg(conn, op, payload=table)
                elif op == _OP_BARRIER_T:
                    # timed barrier: the COORDINATOR owns the retraction
                    # (client-side socket timeouts cannot un-arrive), so a
                    # timed-out rank leaves the count exactly as if it had
                    # never arrived and a later barrier is unharmed
                    timed_out = 0
                    with self.bar_cond:
                        gen = self.bar_gen
                        self.bar_count += 1
                        if self.bar_count == self.nranks:
                            self.bar_count = 0
                            self.bar_gen += 1
                            self.bar_cond.notify_all()
                        else:
                            deadline = time.monotonic() + max(0.0, p)
                            while self.bar_gen == gen:
                                left = deadline - time.monotonic()
                                if left <= 0:
                                    break
                                self.bar_cond.wait(left)
                            if self.bar_gen == gen:
                                self.bar_count -= 1  # retract arrival
                                timed_out = 1
                    _send_msg(conn, op, mode=timed_out)
                elif op == _OP_HEARTBEAT:
                    with self.lease_lock:
                        self.leases[slot] = time.monotonic()
                    _send_msg(conn, op)
                elif op == _OP_LIVENESS:
                    with self.lease_lock:
                        stamp = self.leases.get(slot, 0.0)
                    age = (time.monotonic() - stamp) if stamp > 0 else -1.0
                    _send_msg(conn, op, p=age)
                elif op == _OP_CLOCK:
                    # coordinator clock read for the min-RTT offset
                    # estimator (bluefog_tpu.tracing.clock): reply as
                    # late as possible so queueing before the read only
                    # widens the client's RTT bound, never biases it
                    _send_msg(conn, op, p=time.monotonic())
                elif op == _OP_JOIN_RANK:
                    with self.join_lock:
                        granted = self.next_join_rank
                        self.next_join_rank += 1
                    _send_msg(conn, op, slot=granted)
                elif op == _OP_EPOCH:
                    # mode 1 publishes (monotone, like
                    # shm_native.publish_membership_epoch), mode 0 reads;
                    # either way the reply carries the current word
                    with self.join_lock:
                        if mode == 1 and slot > self.membership_epoch:
                            self.membership_epoch = slot
                        e = self.membership_epoch
                    _send_msg(conn, op, slot=e)
                elif op == _OP_PING:
                    _send_msg(conn, op)
                else:
                    raise ValueError(f"bad op {op}")
        except (ConnectionError, OSError):
            pass
        finally:
            # a dying holder must not leave the mutex locked forever
            with self.mutex_cond:
                if self.mutex_owner is conn:
                    self.mutex_owner = None
                    self.mutex_cond.notify()
            conn.close()

    def stop(self):
        self._stop = True
        # shutdown() wakes a thread blocked in accept() (close() alone
        # does not on Linux — the zombie thread would keep accepting on
        # the fd number once the kernel reuses it for a later listener)
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
        self.thread.join(timeout=5.0)


class _Peers:
    """Lazy persistent client connections, one per destination rank.
    One request/response at a time per peer (guarded by a lock) — the
    caller is single-threaded in practice, the lock makes it safe anyway."""

    def __init__(self, table: Dict[int, str]):
        self.table = table
        self.conns: Dict[int, socket.socket] = {}
        self.locks: Dict[int, threading.Lock] = {}

    def request(self, rank: int, op, win_id=0, slot=0, mode=0, p=0.0,
                payload=b"", trace=0):
        reg = _telemetry.get_registry()
        opname = _OP_NAMES.get(op, str(op))
        t0 = time.perf_counter_ns() if reg.enabled else 0
        lock = self.locks.setdefault(rank, threading.Lock())
        with lock:
            conn = self.conns.get(rank)
            if conn is None:
                host, port = self.table[rank].rsplit(":", 1)
                conn = socket.create_connection((host, int(port)), timeout=60)
                # a bounded deadline replaces the old unbounded wait: a
                # request to a DEAD peer must eventually surface as a
                # PeerTimeoutError naming the rank, not a silent hang
                conn.settimeout(peer_timeout_s())
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self.conns[rank] = conn
            try:
                _send_msg(conn, op, win_id, slot, mode, p, payload,
                          trace=trace)
                reply = _recv_msg(conn)
            except socket.timeout as e:
                # half-done exchange: the stream is unusable (a late reply
                # would be mis-paired with the next request) — evict it
                self.conns.pop(rank, None)
                try:
                    conn.close()
                except OSError:
                    pass
                addr = self.table.get(rank)
                if reg.enabled:
                    reg.counter("tcp.timeouts", op=opname).inc()
                    reg.journal("peer_timeout", peer_rank=rank, addr=addr,
                                op=opname, deadline_s=peer_timeout_s())
                tr = _tracing.get_tracer()
                if tr.enabled:
                    tr.instant(f"peer_timeout:{opname}", aux=rank)
                    tr.dump_flight(f"PeerTimeoutError:{opname}:r{rank}")
                raise PeerTimeoutError(
                    f"rank {rank} ({addr}) did not respond to op "
                    f"{opname} within {peer_timeout_s()}s (set "
                    f"BFTPU_PEER_TIMEOUT_S to adjust)",
                    rank=rank, addr=addr, op=opname) from e
            except (ConnectionError, OSError):
                # evict the dead socket so the NEXT request reconnects
                # instead of failing forever on a cached corpse
                self.conns.pop(rank, None)
                try:
                    conn.close()
                except OSError:
                    pass
                raise
        if reg.enabled:
            reg.counter("tcp.round_trips", op=opname).inc()
            reg.counter("tcp.acks").inc()
            reg.counter("tcp.bytes_sent").add(_HDR.size + len(payload))
            reg.counter("tcp.bytes_received").add(_HDR.size + len(reply[5]))
            reg.histogram("tcp.rtt_s", op=opname).observe(
                (time.perf_counter_ns() - t0) / 1e9)
        return reply

    def close(self):
        for c in self.conns.values():
            try:
                c.close()
            except OSError:
                pass
        self.conns.clear()


class _JobRuntime:
    """Shared per-process runtime: server + peer table (created once, used
    by the job handle and every window)."""

    _by_key: Dict[Tuple[str, int], "_JobRuntime"] = {}
    _cls_lock = threading.Lock()

    def __init__(self, job: str, rank: int, nranks: int, coord: str):
        self.job = job
        self.rank = rank
        self.nranks = nranks
        host = os.environ.get("BLUEFOG_ISLAND_HOST", "127.0.0.1")
        self.server = _Server(rank, nranks, host)
        self._win_ids: Dict[str, int] = {}
        self._next_win = 0
        chost, cport = coord.rsplit(":", 1)
        if rank == 0:
            # rank 0 additionally runs the coordinator (rendezvous +
            # barrier) on the well-known port
            self._coord_server = _Server(rank, nranks, chost, int(cport))
        else:
            self._coord_server = None
        # register with the coordinator (retry while rank 0 comes up)
        my_addr = f"{host}:{self.server.port}"
        deadline = time.time() + 60
        while True:
            try:
                coord_conn = socket.create_connection(
                    (chost, int(cport)), timeout=5
                )
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.05)
        # registration/barrier replies wait on OTHER ranks, but never
        # forever: a dead sibling must surface as PeerTimeoutError(-1)
        # within the configured deadline, not hang the job
        coord_conn.settimeout(peer_timeout_s())
        coord_conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        _send_msg(coord_conn, _OP_REGISTER, slot=rank, payload=my_addr.encode())
        table_raw = _recv_msg(coord_conn)[5]
        self._coord_conn = coord_conn  # kept open: barrier rides on it
        self._coord_addr = (chost, int(cport))
        # leases ride a SEPARATE lazily-created coordinator connection: the
        # heartbeat thread must keep renewing while the main thread blocks
        # inside a barrier on _coord_conn
        self._lease_conn: Optional[socket.socket] = None
        self._lease_lock = threading.Lock()
        table = {}
        for line in table_raw.decode().splitlines():
            k, v = line.split()
            table[int(k)] = v
        self.peers = _Peers(table)

    @classmethod
    def get(cls, job: str, rank: int, nranks: int, coord: str) -> "_JobRuntime":
        with cls._cls_lock:
            key = (job, rank)
            rt = cls._by_key.get(key)
            if rt is None:
                rt = cls(job, rank, nranks, coord)
                cls._by_key[key] = rt
            return rt

    @classmethod
    def drop(cls, job: str, rank: int):
        with cls._cls_lock:
            rt = cls._by_key.pop((job, rank), None)
        if rt is not None:
            rt.peers.close()
            try:
                rt._coord_conn.close()
            except OSError:
                pass
            if rt._lease_conn is not None:
                try:
                    rt._lease_conn.close()
                except OSError:
                    pass
            rt.server.stop()
            if rt._coord_server is not None:
                rt._coord_server.stop()

    def win_id(self, name: str) -> int:
        # window ids must agree across ranks: windows are created
        # collectively in the same order (enforced by the create barrier),
        # so a per-process counter stays in sync
        if name not in self._win_ids:
            self._win_ids[name] = self._next_win
            self._next_win += 1
        return self._win_ids[name]

    def barrier(self, timeout: Optional[float] = None):
        with self.peers.locks.setdefault(-1, threading.Lock()):
            mode = 0
            try:
                if timeout is None:
                    _send_msg(self._coord_conn, _OP_BARRIER)
                    _recv_msg(self._coord_conn)
                else:
                    # the coordinator owns the timed wait AND the arrival
                    # retraction; the socket deadline only covers the
                    # round trip on top of it
                    old = self._coord_conn.gettimeout()
                    self._coord_conn.settimeout(float(timeout) + 30.0)
                    try:
                        _send_msg(self._coord_conn, _OP_BARRIER_T,
                                  p=float(timeout))
                        mode = _recv_msg(self._coord_conn)[3]
                    finally:
                        self._coord_conn.settimeout(old)
            except socket.timeout as e:
                # NB socket.timeout IS TimeoutError (py3.10): only socket
                # waits happen inside this try, so the clause is unambiguous
                addr = "%s:%s" % self._coord_addr
                reg = _telemetry.get_registry()
                if reg.enabled:
                    reg.counter("tcp.timeouts", op="barrier").inc()
                    reg.journal("peer_timeout", peer_rank=0, addr=addr,
                                op="barrier")
                tr = _tracing.get_tracer()
                if tr.enabled:
                    tr.instant("peer_timeout:barrier", aux=0)
                    tr.dump_flight("PeerTimeoutError:barrier")
                raise PeerTimeoutError(
                    "coordinator (rank 0) did not answer the barrier "
                    f"within its deadline ({addr})",
                    rank=-1, addr=addr, op="barrier") from e
            if mode:
                raise TimeoutError(
                    f"barrier timed out after {timeout}s (rank {self.rank})")

    def _lease_request(self, op: int, rank: int) -> float:
        """One heartbeat/liveness round trip to the coordinator (own
        connection + lock: must work while barrier blocks _coord_conn)."""
        with self._lease_lock:
            conn = self._lease_conn
            if conn is None:
                conn = socket.create_connection(self._coord_addr, timeout=5)
                conn.settimeout(peer_timeout_s())
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._lease_conn = conn
            try:
                _send_msg(conn, op, slot=rank)
                return _recv_msg(conn)[4]
            except (socket.timeout, ConnectionError, OSError):
                self._lease_conn = None
                try:
                    conn.close()
                except OSError:
                    pass
                raise


class TcpShmJob:
    """Job handle with the shm-job interface (barrier + mutexes)."""

    def __init__(self, job: str, rank: int, nranks: int, coord: str):
        self.rt = _JobRuntime.get(job, rank, nranks, coord)
        self.job = job
        self.rank = rank

    def barrier(self, timeout: Optional[float] = None) -> None:
        self.rt.barrier(timeout=timeout)

    def mutex_acquire(self, rank: int) -> None:
        self.rt.peers.request(rank, _OP_MUTEX_ACQ)

    def mutex_release(self, rank: int) -> None:
        self.rt.peers.request(rank, _OP_MUTEX_REL)

    # -- liveness leases (coordinator-mediated; see FailureDetector) -------
    def heartbeat(self) -> None:
        """Renew my lease at the rank-0 coordinator."""
        self.rt._lease_request(_OP_HEARTBEAT, self.rank)

    def liveness(self, rank: int) -> float:
        """Last lease renewal of ``rank``, mapped onto MY monotonic clock
        (0.0 = never renewed).  The coordinator reports lease AGE — ages
        transport across hosts; raw stamps do not."""
        age = self.rt._lease_request(_OP_LIVENESS, rank)
        if age < 0:
            return 0.0
        return max(0.0, time.monotonic() - age)

    def clock_probe(self) -> Tuple[float, float, float]:
        """One NTP-style exchange with the rank-0 coordinator: returns
        ``(t0, remote, t1)`` — local send time, the coordinator's
        monotonic clock, local receive time — for
        :class:`bluefog_tpu.tracing.ClockEstimator`.  Rides the lease
        connection, which works while a barrier blocks the main one."""
        t0 = time.monotonic()
        remote = self.rt._lease_request(_OP_CLOCK, self.rank)
        return t0, remote, time.monotonic()

    def close(self, unlink: bool = False) -> None:
        del unlink
        _JobRuntime.drop(self.job, self.rank)


class TcpShmWindow:
    """Window handle with the shm-window interface over the TCP runtime."""

    def __init__(self, job: str, name: str, rank: int, nranks: int,
                 maxd: int, shape, dtype, coord: str):
        self.rt = _JobRuntime.get(job, rank, nranks, coord)
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.nbytes = int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize
        self._id = self.rt.win_id(name)
        with self.rt.server.lock:
            self.rt.server.windows[self._id] = _WinStore(
                maxd, self.nbytes, self.dtype
            )
        # trace words staged by trace_stamp, consumed (popped) by the
        # immediately-following write() — same-thread call pattern
        self._trace_out: Dict[Tuple[int, int], int] = {}

    # -- local (owner-side) ops --------------------------------------------
    def _store(self) -> _WinStore:
        return self.rt.server.windows[self._id]

    def trace_stamp(self, dst: int, slot: int, word: int,
                    writer=None) -> None:
        """Stage the trace-context word for the next write to (dst,
        slot); it rides the frame header of that write."""
        del writer
        self._trace_out[(int(dst), int(slot))] = int(word)

    def trace_peek(self, slot: int, src=None) -> int:
        del src
        with self.rt.server.lock:
            return self._store().mail[slot].trace

    def read(self, slot: int, collect: bool = False, src=None):
        del src
        with self.rt.server.lock:
            s = self._store().mail[slot]
            a = np.frombuffer(bytes(s.data), self.dtype).reshape(self.shape)
            p, ver = s.p, s.version
            if collect:
                s.data[:] = b"\x00" * self.nbytes
                s.p = 0.0
        return a.copy(), p, ver

    def read_version(self, slot: int, src=None) -> int:
        del src
        with self.rt.server.lock:
            return self._store().mail[slot].version

    def reset(self, slot: int, src=None) -> None:
        del src
        with self.rt.server.lock:
            s = self._store().mail[slot]
            s.data[:] = b"\x00" * self.nbytes
            s.p = 0.0

    def expose(self, array, p: float = 1.0) -> None:
        a = np.ascontiguousarray(np.asarray(array, self.dtype))
        if a.nbytes != self.nbytes:
            raise ValueError(
                f"expose payload has {a.nbytes} bytes but window "
                f"expects {self.nbytes} (shape {self.shape})"
            )
        with self.rt.server.lock:
            s = self._store().exposed
            s.data[:] = a.tobytes()
            s.p = float(p)
            s.version += 1

    # -- remote (one-sided) ops --------------------------------------------
    def write(self, dst: int, slot: int, array, p: float = 1.0,
              accumulate: bool = False, writer=None) -> None:
        del writer
        if accumulate and self.dtype.kind != "f":
            raise TypeError(f"accumulate unsupported for dtype {self.dtype}")
        a = np.ascontiguousarray(np.asarray(array, self.dtype))
        if a.nbytes != self.nbytes:
            raise ValueError(
                f"win_put payload has {a.nbytes} bytes but window "
                f"expects {self.nbytes} (shape {self.shape})"
            )
        trace = self._trace_out.pop((int(dst), int(slot)), 0)
        if dst == self.rt.rank:
            # local fast path, same semantics
            with self.rt.server.lock:
                s = self._store().mail[slot]
                if accumulate:
                    cur = np.frombuffer(bytes(s.data), self.dtype)
                    s.data[:] = (cur + a.ravel()).tobytes()
                    s.p += float(p)
                else:
                    s.data[:] = a.tobytes()
                    s.p = float(p)
                s.version += 1
                if trace:
                    s.trace = trace
            return
        try:
            # zero-copy byte view; the uint8 reinterpret also covers
            # ml_dtypes (bf16) arrays whose native buffers can't export
            payload = a.view(np.uint8).data
        except (TypeError, ValueError):
            payload = a.tobytes()
        self.rt.peers.request(
            dst, _OP_WRITE, self._id, slot, 1 if accumulate else 0,
            float(p), payload, trace=trace,
        )

    def read_exposed(self, src: int):
        if src == self.rt.rank:
            with self.rt.server.lock:
                s = self._store().exposed
                a = np.frombuffer(bytes(s.data), self.dtype).reshape(self.shape)
                return a.copy(), s.p, s.version
        _, _, ver, _, p, payload, _ = self.rt.peers.request(
            src, _OP_READ_EXPOSED, self._id
        )
        a = np.frombuffer(payload, self.dtype).reshape(self.shape)
        return a.copy(), p, ver

    def close(self, unlink: bool = False) -> None:
        del unlink
        with self.rt.server.lock:
            self.rt.server.windows.pop(self._id, None)

    def unlink_segments(self) -> None:
        pass  # in-memory store, freed at close
