// Native data-loading pipeline: worker threads fill a bounded ring of
// pre-allocated host buffers (synthetic xorshift data or slices of a
// binary file), overlapping batch production with device compute — the
// TPU-native sibling of the reference's reliance on torch DataLoader
// worker processes [U] (SURVEY.md: IO belongs to the native runtime).
//
// C ABI (ctypes, see data_native.py):
//   bf_loader_create(batch_bytes, depth, workers, mode, seed, path) -> handle
//       mode 0: synthetic float32 in [0,1); mode 1: wrap-around slices of
//       the file at `path`.
//   bf_loader_next(handle) -> const uint8_t*   (blocks until a batch is ready)
//   bf_loader_release(handle, ptr)             (return the buffer to the pool)
//   bf_loader_stats(handle, uint64 out[3])     (produced, consumed, stalls)
//   bf_loader_destroy(handle)
//
// Batch content is a pure function of (seed, batch_index); with one worker
// batches arrive in index order, with several the order is unspecified
// (exactly torch DataLoader's worker semantics).

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Buffer {
  std::vector<uint8_t> data;
  uint64_t index = 0;
};

struct Loader {
  uint64_t batch_bytes = 0;
  int mode = 0;
  uint64_t seed = 0;
  int fd = -1;                // mode 1: dataset file, read via pread
  uint64_t file_batches = 0;  // whole batches in the file
  std::vector<Buffer*> pool;  // free buffers
  std::queue<Buffer*> ready;
  std::unordered_map<const uint8_t*, Buffer*> by_ptr;
  std::mutex mu;
  std::condition_variable cv_free, cv_ready;
  std::vector<std::thread> workers;
  bool stop = false;  // guarded by mu
  std::atomic<uint64_t> produced{0}, consumed{0}, stalls{0};
  uint64_t next_index = 0;  // guarded by mu
};

uint64_t splitmix(uint64_t& s) {
  s += 0x9e3779b97f4a7c15ULL;
  uint64_t z = s;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

void fill(Loader* L, Buffer* b) {
  if (L->mode == 0) {
    uint64_t s = L->seed ^ (b->index * 0x9e3779b97f4a7c15ULL + 1);
    float* f = reinterpret_cast<float*>(b->data.data());
    size_t n = L->batch_bytes / sizeof(float);
    for (size_t i = 0; i < n; ++i)
      f[i] = static_cast<float>(splitmix(s) >> 40) * (1.0f / 16777216.0f);
  } else {
    // wrap on whole batches so offsets stay batch- (and element-) aligned;
    // a trailing partial batch is dropped, as dataset epochs usually do.
    // pread: O(batch) memory, thread-safe on a shared fd.
    off_t off = static_cast<off_t>((b->index % L->file_batches) *
                                   L->batch_bytes);
    size_t done = 0;
    while (done < L->batch_bytes) {
      ssize_t r = pread(L->fd, b->data.data() + done, L->batch_bytes - done,
                        off + static_cast<off_t>(done));
      if (r <= 0) {  // IO error: surface as an obviously-poisoned batch
        std::memset(b->data.data() + done, 0xFF, L->batch_bytes - done);
        break;
      }
      done += static_cast<size_t>(r);
    }
  }
}

void worker_loop(Loader* L) {
  for (;;) {
    Buffer* b = nullptr;
    {
      std::unique_lock<std::mutex> lk(L->mu);
      L->cv_free.wait(lk, [&] { return L->stop || !L->pool.empty(); });
      if (L->stop) return;
      b = L->pool.back();
      L->pool.pop_back();
      b->index = L->next_index++;
    }
    fill(L, b);
    {
      std::lock_guard<std::mutex> lk(L->mu);
      L->produced.fetch_add(1);  // before push: stats never show consumed>produced
      L->ready.push(b);
    }
    L->cv_ready.notify_one();
  }
}

}  // namespace

extern "C" {

void* bf_loader_create(int64_t batch_bytes, int64_t depth, int64_t workers,
                       int64_t mode, uint64_t seed, const char* path) {
  if (batch_bytes <= 0 || depth <= 0 || workers <= 0) return nullptr;
  auto* L = new Loader();
  L->batch_bytes = static_cast<uint64_t>(batch_bytes);
  L->mode = static_cast<int>(mode);
  L->seed = seed;
  if (mode == 1) {
    L->fd = open(path ? path : "", O_RDONLY);
    struct stat st;
    if (L->fd < 0 || fstat(L->fd, &st) != 0 ||
        static_cast<uint64_t>(st.st_size) < L->batch_bytes) {
      if (L->fd >= 0) close(L->fd);
      delete L;
      return nullptr;
    }
    L->file_batches = static_cast<uint64_t>(st.st_size) / L->batch_bytes;
  }
  for (int64_t i = 0; i < depth; ++i) {
    auto* b = new Buffer();
    b->data.resize(L->batch_bytes);
    L->by_ptr[b->data.data()] = b;
    L->pool.push_back(b);
  }
  for (int64_t i = 0; i < workers; ++i)
    L->workers.emplace_back(worker_loop, L);
  return L;
}

const uint8_t* bf_loader_next(void* h) {
  auto* L = static_cast<Loader*>(h);
  Buffer* b = nullptr;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    if (L->ready.empty()) L->stalls.fetch_add(1);
    L->cv_ready.wait(lk, [&] { return L->stop || !L->ready.empty(); });
    if (L->ready.empty()) return nullptr;  // loader shut down
    b = L->ready.front();
    L->ready.pop();
  }
  return b->data.data();
}

void bf_loader_release(void* h, const uint8_t* ptr) {
  auto* L = static_cast<Loader*>(h);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    auto it = L->by_ptr.find(ptr);
    if (it == L->by_ptr.end()) return;
    L->pool.push_back(it->second);
  }
  L->consumed.fetch_add(1);
  L->cv_free.notify_one();
}

void bf_loader_stats(void* h, uint64_t out[3]) {
  auto* L = static_cast<Loader*>(h);
  out[0] = L->produced.load();
  out[1] = L->consumed.load();
  out[2] = L->stalls.load();
}

void bf_loader_destroy(void* h) {
  auto* L = static_cast<Loader*>(h);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop = true;
  }
  L->cv_free.notify_all();
  L->cv_ready.notify_all();  // wake any consumer blocked in next()
  for (auto& t : L->workers) t.join();
  for (auto& kv : L->by_ptr) delete kv.second;
  if (L->fd >= 0) close(L->fd);
  delete L;
}

}  // extern "C"
