"""ctypes wrapper over the native plan compiler (plan_compiler.cc)."""

from __future__ import annotations

import ctypes
from typing import Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.native import get_lib


def compile_edge_classes(
    size: int, edges: Sequence[Tuple[int, int]]
) -> Optional[Tuple[np.ndarray, np.ndarray, int]]:
    """(class_of_edge, slot_of_edge, n_classes) via the native library, or
    None when it is unavailable.  Raises ValueError on invalid edges (the
    same conditions plan.py checks)."""
    lib = get_lib()
    if lib is None:
        return None
    n = len(edges)
    srcs = np.ascontiguousarray([e[0] for e in edges], dtype=np.int64)
    dsts = np.ascontiguousarray([e[1] for e in edges], dtype=np.int64)
    cls = np.zeros(n, dtype=np.int64)
    slot = np.zeros(n, dtype=np.int64)
    as_ptr = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
    n_classes = lib.bf_plan_compile(
        size, n, as_ptr(srcs), as_ptr(dsts), as_ptr(cls), as_ptr(slot)
    )
    if n_classes < 0:
        raise ValueError("invalid edge list (self-edge, duplicate, or out of range)")
    return cls, slot, int(n_classes)
