"""Native (C++) components, loaded via ctypes.

Siblings of the reference's C++ runtime layer (SURVEY.md §2.1).  Everything
here is optional: each consumer has a pure-Python fallback, and the shared
library is built on demand from the in-tree sources (`make` in this
directory) — mirroring the reference's build-on-install extension without
requiring pybind11 (absent in this environment).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libbluefog_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_attempted = False


def build(force: bool = False) -> bool:
    """Compile the shared library in-tree.  Returns True on success."""
    if os.path.exists(_LIB_PATH) and not force:
        return True
    try:
        subprocess.run(
            ["make", "-C", _DIR, "-s"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, building it on first use; None if unavailable."""
    global _lib, _build_attempted
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB_PATH):
            if _build_attempted:
                return None
            _build_attempted = True
            if not build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        try:
            _declare_abi(lib)
        except AttributeError:
            # stale .so from before a symbol was added: rebuild once
            if not build(force=True):
                return None
            try:
                lib = ctypes.CDLL(_LIB_PATH)
                _declare_abi(lib)
            except (OSError, AttributeError):
                return None
        _lib = lib
        return _lib


def _declare_abi(lib: ctypes.CDLL) -> None:
        # timeline ABI
        lib.bf_timeline_create.restype = ctypes.c_void_p
        lib.bf_timeline_create.argtypes = [ctypes.c_char_p]
        lib.bf_timeline_record.restype = None
        lib.bf_timeline_record.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_int64,
        ]
        lib.bf_timeline_counter.restype = None
        lib.bf_timeline_counter.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_double,
            ctypes.c_double,
        ]
        lib.bf_timeline_flush.restype = None
        lib.bf_timeline_flush.argtypes = [ctypes.c_void_p]
        lib.bf_timeline_destroy.restype = None
        lib.bf_timeline_destroy.argtypes = [ctypes.c_void_p]
        # plan compiler ABI
        lib.bf_plan_compile.restype = ctypes.c_int64
        lib.bf_plan_compile.argtypes = [
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        # data loader ABI
        lib.bf_loader_create.restype = ctypes.c_void_p
        lib.bf_loader_create.argtypes = [
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_uint64,
            ctypes.c_char_p,
        ]
        lib.bf_loader_next.restype = ctypes.c_void_p
        lib.bf_loader_next.argtypes = [ctypes.c_void_p]
        lib.bf_loader_release.restype = None
        lib.bf_loader_release.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.bf_loader_stats.restype = None
        lib.bf_loader_stats.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.bf_loader_destroy.restype = None
        lib.bf_loader_destroy.argtypes = [ctypes.c_void_p]
        # shm mailbox ABI (async island window transport, protocol v2:
        # chunk-ring seqlocks, drained markers, fused scale/combine).
        # Declaring the version sentinel FIRST makes loading a stale v1 .so
        # raise AttributeError here, which get_lib() answers with a forced
        # rebuild — the ABI below is not call-compatible with v1.
        lib.bf_shm_abi_version.restype = ctypes.c_int32
        lib.bf_shm_abi_version.argtypes = []
        lib.bf_shm_job_create.restype = ctypes.c_void_p
        lib.bf_shm_job_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.bf_shm_job_barrier.restype = None
        lib.bf_shm_job_barrier.argtypes = [ctypes.c_void_p]
        lib.bf_shm_job_barrier_timeout.restype = ctypes.c_int32
        lib.bf_shm_job_barrier_timeout.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.bf_shm_job_heartbeat.restype = None
        lib.bf_shm_job_heartbeat.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bf_shm_job_liveness.restype = ctypes.c_int64
        lib.bf_shm_job_liveness.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bf_shm_monotonic_ms.restype = ctypes.c_int64
        lib.bf_shm_monotonic_ms.argtypes = []
        lib.bf_shm_job_mutex_acquire.restype = None
        lib.bf_shm_job_mutex_acquire.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bf_shm_job_mutex_acquire_timeout.restype = ctypes.c_int32
        lib.bf_shm_job_mutex_acquire_timeout.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.bf_shm_job_mutex_break.restype = None
        lib.bf_shm_job_mutex_break.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bf_shm_job_mutex_release.restype = None
        lib.bf_shm_job_mutex_release.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bf_shm_job_destroy.restype = None
        lib.bf_shm_job_destroy.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.bf_shm_win_create.restype = ctypes.c_void_p
        lib.bf_shm_win_create.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int64,  # chunk_bytes
        ]
        lib.bf_shm_win_write.restype = None
        lib.bf_shm_win_write.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_double, ctypes.c_int32,
            ctypes.c_double,  # scale
        ]
        lib.bf_shm_win_read.restype = ctypes.c_int64
        lib.bf_shm_win_read.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int32,
        ]
        lib.bf_shm_win_combine.restype = ctypes.c_int64
        lib.bf_shm_win_combine.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_double, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.bf_shm_win_probe.restype = ctypes.c_int32
        lib.bf_shm_win_probe.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.bf_shm_win_put_dual.restype = None
        lib.bf_shm_win_put_dual.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_double, ctypes.c_int32,
            ctypes.c_double, ctypes.c_double,
        ]
        lib.bf_shm_win_update_fused.restype = ctypes.c_double
        lib.bf_shm_win_update_fused.argtypes = [
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
            ctypes.c_void_p, ctypes.c_double, ctypes.c_double,
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ]
        lib.bf_shm_win_exposed_offset.restype = ctypes.c_int64
        lib.bf_shm_win_exposed_offset.argtypes = [ctypes.c_void_p]
        lib.bf_shm_win_reset.restype = None
        lib.bf_shm_win_reset.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bf_shm_win_force_drain.restype = None
        lib.bf_shm_win_force_drain.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.bf_shm_win_expose.restype = None
        lib.bf_shm_win_expose.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_double,
        ]
        lib.bf_shm_win_read_exposed.restype = ctypes.c_int64
        lib.bf_shm_win_read_exposed.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.bf_shm_win_destroy.restype = None
        lib.bf_shm_win_destroy.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.bf_shm_unlink.restype = None
        lib.bf_shm_unlink.argtypes = [ctypes.c_char_p]
        # layout optimizer ABI
        lib.bf_layout_anneal.restype = ctypes.c_double
        lib.bf_layout_anneal.argtypes = [
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64,
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64),
        ]
