"""ctypes wrapper over the native data-loading pipeline (data_loader.cc).

Worker threads in C++ fill a bounded ring of host buffers ahead of the
training loop, so batch production overlaps device compute — the
TPU-native sibling of the reference's torch-DataLoader worker processes
[U].  Two access styles:

- ``next()``: returns an owned numpy copy (simple, always safe).
- ``next_view()``: context manager yielding a zero-copy numpy view of the
  ring buffer; the buffer returns to the pool on exit, so the view must
  not escape (device_put/np.array it first).

Batch content is a pure function of ``(seed, batch_index)``; with
``workers=1`` batches arrive in index order, with more the order is
unspecified (the reference's DataLoader semantics).
"""

from __future__ import annotations

import contextlib
import ctypes
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.native import get_lib

__all__ = ["NativeDataLoader"]


class NativeDataLoader:
    def __init__(
        self,
        batch_shape: Sequence[int],
        dtype=np.float32,
        *,
        depth: int = 4,
        workers: int = 2,
        seed: int = 0,
        path: Optional[str] = None,
    ):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.batch_shape = tuple(int(s) for s in batch_shape)
        self.dtype = np.dtype(dtype)
        self._nbytes = int(np.prod(self.batch_shape)) * self.dtype.itemsize
        self._h = lib.bf_loader_create(
            self._nbytes, int(depth), int(workers),
            1 if path else 0, int(seed),
            path.encode() if path else None,
        )
        if not self._h:
            raise RuntimeError(
                "could not create native loader (bad args or unreadable path)"
            )

    @contextlib.contextmanager
    def next_view(self) -> Iterator[np.ndarray]:
        ptr = self._lib.bf_loader_next(self._h)
        if not ptr:
            raise RuntimeError("loader was shut down")
        try:
            raw = np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)),
                shape=(self._nbytes,),
            )
            yield raw.view(self.dtype).reshape(self.batch_shape)
        finally:
            self._lib.bf_loader_release(self._h, ptr)

    def next(self) -> np.ndarray:
        with self.next_view() as v:
            return v.copy()

    def stats(self) -> Tuple[int, int, int]:
        """(produced, consumed, stalls) — stalls counts consumer waits."""
        out = (ctypes.c_uint64 * 3)()
        self._lib.bf_loader_stats(self._h, out)
        return int(out[0]), int(out[1]), int(out[2])

    def close(self) -> None:
        h, self._h = getattr(self, "_h", None), None
        if h:
            self._lib.bf_loader_destroy(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
