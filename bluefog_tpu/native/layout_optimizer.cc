// Torus layout optimizer: simulated-annealing search for the rank->chip
// assignment minimizing the weighted ICI hop cost of a gossip topology.
//
// TPU-native sibling of the reference's reliance on MPI rank reordering
// (MPI_Dist_graph_create_adjacent's reorder flag + mpirun placement,
// bluefog/common/mpi_context.cc [U], SURVEY.md §2.4): there the MPI library
// may permute ranks to fit the physical network; here we own the search.
// The snake heuristic (parallel/ici_map.py) is the starting point; this
// annealer improves irregular topologies (exp-2, 2-D mesh on non-square
// tori) where no closed-form embedding exists.  Cost model: sum over
// directed edges of weight * torus-Manhattan hops — link-bandwidth use of
// one gossip round (ici_map.plan_hop_cost's total).
//
// C API (ctypes-friendly, no exceptions across the boundary).

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <random>
#include <vector>

namespace {

inline int64_t hop(const int64_t* a, const int64_t* b, const int64_t* shape,
                   int64_t nd) {
  int64_t d = 0;
  for (int64_t i = 0; i < nd; ++i) {
    int64_t x = std::llabs(a[i] - b[i]);
    d += std::min(x, shape[i] - x);
  }
  return d;
}

}  // namespace

extern "C" {

// n ranks live on n candidate positions (coords: n*nd row-major, a
// permutation of torus cells or any subset of them); m directed edges
// (esrc/edst rank ids, ew weights).  assign[r] (in/out) is the position
// index of rank r — seeded with the caller's initial assignment (e.g. the
// snake order), overwritten with the best found.  Returns the best cost,
// or -1.0 on invalid input.
double bf_layout_anneal(int64_t n, int64_t nd, const int64_t* coords,
                        const int64_t* shape, int64_t m, const int64_t* esrc,
                        const int64_t* edst, const double* ew, int64_t iters,
                        uint64_t seed, int64_t* assign) {
  if (n <= 0 || nd <= 0 || m < 0 || iters < 0) return -1.0;
  std::vector<char> seen(static_cast<size_t>(n), 0);
  for (int64_t r = 0; r < n; ++r) {
    if (assign[r] < 0 || assign[r] >= n || seen[assign[r]]) return -1.0;
    seen[assign[r]] = 1;
  }
  for (int64_t e = 0; e < m; ++e) {
    if (esrc[e] < 0 || esrc[e] >= n || edst[e] < 0 || edst[e] >= n ||
        esrc[e] == edst[e])
      return -1.0;
  }

  // incidence lists so a swap's delta touches only local edges
  std::vector<std::vector<int64_t>> inc(static_cast<size_t>(n));
  for (int64_t e = 0; e < m; ++e) {
    inc[esrc[e]].push_back(e);
    if (edst[e] != esrc[e]) inc[edst[e]].push_back(e);
  }

  std::vector<int64_t> pos(assign, assign + n);
  auto edge_cost = [&](int64_t e) {
    return ew[e] * static_cast<double>(hop(coords + pos[esrc[e]] * nd,
                                           coords + pos[edst[e]] * nd, shape,
                                           nd));
  };
  double cost = 0.0;
  for (int64_t e = 0; e < m; ++e) cost += edge_cost(e);

  std::vector<int64_t> best(pos);
  double best_cost = cost;
  if (n < 2 || m == 0 || iters == 0) return best_cost;

  std::mt19937_64 rng(seed);
  std::uniform_int_distribution<int64_t> pick(0, n - 1);
  std::uniform_real_distribution<double> unit(0.0, 1.0);

  // geometric cooling from the mean edge cost down to ~1e-3 of it
  double t0 = std::max(cost / std::max<int64_t>(m, 1), 1e-9);
  double t_end = t0 * 1e-3;
  double decay = std::pow(t_end / t0, 1.0 / static_cast<double>(iters));
  double temp = t0;

  for (int64_t it = 0; it < iters; ++it, temp *= decay) {
    int64_t r1 = pick(rng);
    int64_t r2 = pick(rng);
    if (r1 == r2) continue;
    double before = 0.0;
    for (int64_t e : inc[r1]) before += edge_cost(e);
    for (int64_t e : inc[r2])
      if (esrc[e] != r1 && edst[e] != r1) before += edge_cost(e);
    std::swap(pos[r1], pos[r2]);
    double after = 0.0;
    for (int64_t e : inc[r1]) after += edge_cost(e);
    for (int64_t e : inc[r2])
      if (esrc[e] != r1 && edst[e] != r1) after += edge_cost(e);
    double delta = after - before;
    if (delta <= 0.0 || unit(rng) < std::exp(-delta / temp)) {
      cost += delta;
      if (cost < best_cost) {
        best_cost = cost;
        best = pos;
      }
    } else {
      std::swap(pos[r1], pos[r2]);  // reject
    }
  }
  for (int64_t r = 0; r < n; ++r) assign[r] = best[r];
  return best_cost;
}

}  // extern "C"
