"""Quantized wire codec for the chunked transport framing.

The classic decentralized-SGD bandwidth lever (Deep Gradient
Compression / EF-SGD; the upstream BlueFog paper's DCN story): gossip
tolerates aggressive per-edge quantization of the *values* as long as
(a) the quantization error is fed back into the next deposit (the
error-feedback residual, held per edge on the SENDER) and (b) the
push-sum mass ``p`` rides exact — only payload bytes are compressed,
so the telemetry mass ledger stays balanced by construction.

Wire dtypes (``BFTPU_WIRE_DTYPE``):

- ``f32`` (default) — raw window-dtype bytes, no compression (the name
  is historical: for f64 windows the raw path ships f64);
- ``bf16`` — round-to-nearest-even truncation of the f32 view to the
  high 16 bits (2 bytes/element; exact for bf16-representable values);
- ``int8`` — per-chunk max-abs scaling to [-127, 127] (1 byte/element;
  the scale rides the chunk frame header as an f64, computed in f64 so
  denormal and near-``FLT_MAX`` chunks neither overflow nor divide by
  zero).

A chunk whose values are not all finite is shipped RAW regardless of
the configured dtype (bf16 truncation can turn a NaN into an Inf and
an int8 max-abs scale of Inf would poison every element) — the
per-chunk wire code in the frame header makes mixed streams legal.

Conservation contract (model-checked by ``analysis/wire_rules.py``,
unit-tested in ``tests/test_wire.py``)::

    sum(inputs) == sum(delivered) + residual      -- at every step

which is exactly ``fold``/``settle`` below: ``buf = x + r`` is encoded,
and ``r' = buf - decode(encode(buf))``.  The residual must survive edge
demotion (a paused edge flushes it on the next deposit); it is dropped
only when the peer is declared dead (the edge no longer exists).
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

__all__ = [
    "WIRE_RAW",
    "WIRE_BF16",
    "WIRE_INT8",
    "WIRE_CODES",
    "WIRE_NAMES",
    "wire_dtype",
    "wire_code",
    "encode_chunk",
    "decode_chunk",
]

# per-chunk wire codes, carried in the chunk frame header so every
# chunk of a stream may pick its own representation
WIRE_RAW = 0    # window-dtype bytes, scale unused
WIRE_BF16 = 1   # u16 high half of the f32 bits, scale unused
WIRE_INT8 = 2   # int8 with per-chunk f64 scale in the header

WIRE_CODES = {"f32": WIRE_RAW, "bf16": WIRE_BF16, "int8": WIRE_INT8}
WIRE_NAMES = {v: k for k, v in WIRE_CODES.items()}


def wire_dtype() -> str:
    """Configured wire dtype (``BFTPU_WIRE_DTYPE``: f32 | bf16 | int8;
    unknown values fall back to f32 so a typo degrades to correctness,
    not corruption)."""
    v = os.environ.get("BFTPU_WIRE_DTYPE", "f32").strip().lower()
    return v if v in WIRE_CODES else "f32"


def wire_code() -> int:
    return WIRE_CODES[wire_dtype()]


def _bf16_pack(xf: np.ndarray) -> np.ndarray:
    """f32 -> u16 high halves, round-to-nearest-even (the +0x7FFF + lsb
    carry trick; uint32 addition wraps are impossible for finite inputs
    because the exponent field never carries past the sign bit for
    |x| < 2**128 after rounding — non-finite chunks never reach here)."""
    u = np.ascontiguousarray(xf, np.float32).view(np.uint32)
    return ((u + 0x7FFF + ((u >> 16) & 1)) >> 16).astype(np.uint16)


def _bf16_unpack(payload, count: int) -> np.ndarray:
    u = np.frombuffer(payload, np.uint16, count=count).astype(np.uint32)
    return (u << 16).view(np.float32)


def encode_chunk(x: np.ndarray, code: int) -> Tuple[int, bytes, float]:
    """Encode ONE contiguous 1-D chunk of window-dtype values.

    Returns ``(code_used, payload, scale)``; ``code_used`` may downgrade
    to :data:`WIRE_RAW` (non-float window dtype, or a non-finite chunk).
    """
    if code != WIRE_RAW and x.dtype.kind == "f":
        xf = x.astype(np.float32, copy=False)
        if np.isfinite(xf).all():
            if code == WIRE_BF16:
                return WIRE_BF16, _bf16_pack(xf).tobytes(), 1.0
            # int8: max-abs scale in f64 — a denormal-f32 max would
            # round to zero as f32 and divide-by-zero; a near-FLT_MAX
            # max stays finite in f64
            m = float(np.max(np.abs(x)))
            if m == 0.0:
                return WIRE_INT8, b"\x00" * x.size, 0.0
            scale = m / 127.0
            q = np.clip(np.rint(x.astype(np.float64) / scale), -127, 127)
            return WIRE_INT8, q.astype(np.int8).tobytes(), scale
    return WIRE_RAW, _raw_bytes(x), 1.0


def _raw_bytes(x: np.ndarray):
    try:
        # zero-copy byte view (covers ml_dtypes arrays whose native
        # buffers can't export — same trick as the legacy write path)
        return np.ascontiguousarray(x).view(np.uint8).data
    except (TypeError, ValueError):
        return x.tobytes()


def decode_chunk(payload, code: int, scale: float, dtype,
                 count: int) -> np.ndarray:
    """Decode one chunk back to ``count`` window-dtype elements."""
    dtype = np.dtype(dtype)
    if code == WIRE_RAW:
        return np.frombuffer(payload, dtype, count=count)
    if code == WIRE_BF16:
        return _bf16_unpack(payload, count).astype(dtype, copy=False)
    if code == WIRE_INT8:
        q = np.frombuffer(payload, np.int8, count=count)
        return (q.astype(np.float64) * scale).astype(dtype, copy=False)
    raise ValueError(f"bad wire code {code}")
