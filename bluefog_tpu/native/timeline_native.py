"""ctypes wrapper over the native timeline writer (timeline.cc)."""

from __future__ import annotations

from bluefog_tpu.native import get_lib


class NativeTimelineWriter:
    """Thread-safe chrome-trace writer with a C++ background flush thread
    (sibling of the reference's ``TimelineWriter`` [U]).

    Raises RuntimeError if the native library is unavailable — callers
    (``bluefog_tpu.timeline``) fall back to the pure-Python writer.
    """

    def __init__(self, path: str):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = lib.bf_timeline_create(path.encode())
        if not self._h:
            raise RuntimeError(f"could not create native timeline at {path!r}")

    def record(self, name: str, start_us: float, dur_us: float, tid: int = 0):
        self._lib.bf_timeline_record(
            self._h, name.encode(), float(start_us), float(dur_us), int(tid)
        )

    def counter(self, name: str, ts_us: float, value: float):
        self._lib.bf_timeline_counter(
            self._h, name.encode(), float(ts_us), float(value)
        )

    def flush(self):
        self._lib.bf_timeline_flush(self._h)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                self._lib.bf_timeline_destroy(h)
            except Exception:
                pass
            self._h = None
