"""Hierarchical island transport: shared memory intra-host, TCP inter-host.

The deployment shape of the reference's hierarchical design (SURVEY.md
§2.4: NCCL/shared-memory fast path inside a machine, network transport
between machines — ``hierarchical_neighbor_allreduce``'s premise) applied
to the island mailbox: ranks on the SAME host exchange deposits through the
native seqlock shm mailbox (:mod:`shm_native`), ranks on different hosts
through the TCP mailbox (:mod:`tcp_transport`) — one window, routed per
edge by a rank→host map.

Routing rule (everything else follows from it):

- a window's slot ``(owner d, in-neighbor s)`` lives in the transport
  matching the (s, d) pair: shm iff ``host(s) == host(d)``;
- ``write``: the writer picks the transport by comparing its host with the
  destination's;
- ``read``/``collect``/``read_version``: the OWNER picks per slot the same
  way — it knows every in-neighbor's host from the map, so it reads each
  slot from the transport the writer used (the islands layer passes the
  in-neighbor rank via ``src``);
- ``expose``/``read_exposed``: exposed tensors are published to BOTH
  transports (cheap: one local shm write + one local TCP-store write), so
  any reader uses its natural path;
- ``barrier``/``mutex``: global coordination rides TCP (the only transport
  every rank shares).

The rank→host map comes from ``BLUEFOG_ISLAND_HOSTMAP`` — either
``"0,0,1,1"`` (host index per rank, comma-separated) or
``"r:h,r:h,..."`` pairs.  Single-machine tests simulate multiple hosts by
assigning fake host indices: same-"host" pairs genuinely use shm,
cross-"host" pairs genuinely use TCP loopback.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from bluefog_tpu.native import capabilities as _caps


def parse_hostmap(raw: str, nranks: int) -> List[str]:
    """``"0,0,1,1"`` or ``"0:a,1:a,2:b"`` → host label per rank."""
    raw = raw.strip()
    if ":" in raw:
        out = [""] * nranks
        for item in raw.split(","):
            r, h = item.split(":")
            idx = int(r)
            if not 0 <= idx < nranks:
                raise ValueError(
                    f"hostmap rank {idx} out of range [0, {nranks}): {raw!r}"
                )
            out[idx] = h.strip()
        if any(h == "" for h in out):
            raise ValueError(f"hostmap missing ranks: {raw!r}")
        return out
    parts = [p.strip() for p in raw.split(",")]
    if len(parts) != nranks:
        raise ValueError(
            f"hostmap has {len(parts)} entries for {nranks} ranks: {raw!r}"
        )
    return parts


class RoutedJob:
    """Job handle: a thin TCP wrapper — global coordination (barrier,
    mutexes, rendezvous) always rides TCP, the only transport every rank
    shares.  Windows create their own per-host shm segments; there is no
    job-scope shm state."""

    def __init__(self, job: str, rank: int, nranks: int, hosts: List[str],
                 coord: str):
        from bluefog_tpu.native.tcp_transport import TcpShmJob

        self.hosts = hosts
        self.rank = rank
        self.tcp = TcpShmJob(job, rank, nranks, coord)

    def barrier(self) -> None:
        self.tcp.barrier()

    def mutex_acquire(self, rank: int) -> None:
        self.tcp.mutex_acquire(rank)

    def mutex_release(self, rank: int) -> None:
        self.tcp.mutex_release(rank)

    def close(self, unlink: bool = False) -> None:
        self.tcp.close(unlink)


class RoutedWindow:
    """One window over both transports, routed per (writer, owner) edge.

    The islands layer addresses mailbox slots by (owner, slot-index) and
    knows the writer rank for every slot; this class only needs the hosts
    of the two endpoints, passed as ``src``/``dst`` rank arguments.
    """

    #: static floor: what routed may claim before knowing which shm leg
    #: (native or fallback) an instance gets — the meet over every
    #: possible leg pair.  __init__ upgrades to the actual legs' meet.
    CAPS = None  # filled in below the class (needs the leg classes)

    def __init__(self, job: str, name: str, rank: int, nranks: int,
                 maxd: int, shape, dtype, hosts: List[str], coord: str):
        from bluefog_tpu.native.shm_native import make_shm_window
        from bluefog_tpu.native.tcp_transport import TcpShmWindow

        self.hosts = hosts
        self.rank = rank
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.tcp = TcpShmWindow(job, name, rank, nranks, maxd, shape, dtype,
                                coord)
        local = [r for r in range(nranks) if hosts[r] == hosts[rank]]
        if len(local) > 1:
            li = {r: i for i, r in enumerate(local)}
            self.shm = make_shm_window(
                f"{job}_h{hosts[rank]}", name, li[rank], len(local), maxd,
                shape, dtype,
            )
            self._local_index = li
            # caller-facing capabilities: a routed edge may take either
            # leg, so only the meet of the two is honest
            self.CAPS = _caps.meet(type(self.shm).CAPS, type(self.tcp).CAPS,
                                   "routed")
        else:
            self.shm = None
            self._local_index = {}
            self.CAPS = _caps.meet(type(self.tcp).CAPS,
                                   type(self.tcp).CAPS, "routed")

    def _same_host(self, a: int, b: int) -> bool:
        return self.hosts[a] == self.hosts[b]

    def _shm_dst(self, dst: int) -> int:
        return self._local_index[dst]

    # -- mailbox ------------------------------------------------------------
    def write(self, dst: int, slot: int, array, p: float = 1.0,
              accumulate: bool = False, writer: Optional[int] = None) -> None:
        # a slot's canonical transport is set by the (writer-of-record,
        # owner) pair; `writer` defaults to self (win_put) but win_get's
        # self-deposit passes the pulled in-neighbor so deposit and read
        # agree on which transport holds the slot
        w = self.rank if writer is None else writer
        if self.shm is not None and self._same_host(w, dst):
            self.shm.write(self._shm_dst(dst), slot, array, p, accumulate)
        else:
            self.tcp.write(dst, slot, array, p, accumulate)

    def trace_stamp(self, dst: int, slot: int, word: int,
                    writer: Optional[int] = None) -> None:
        # must route exactly like the write it annotates, so the word
        # lands beside the slot the consumer will actually read
        w = self.rank if writer is None else writer
        if self.shm is not None and self._same_host(w, dst):
            self.shm.trace_stamp(self._shm_dst(dst), slot, word)
        else:
            self.tcp.trace_stamp(dst, slot, word)

    def trace_peek(self, slot: int, src: Optional[int] = None) -> int:
        if src is not None and self.shm is not None \
                and self._same_host(self.rank, src):
            return self.shm.trace_peek(slot)
        return self.tcp.trace_peek(slot)

    def read(self, slot: int, collect: bool = False, src: Optional[int] = None):
        if src is not None and self.shm is not None \
                and self._same_host(self.rank, src):
            return self.shm.read(slot, collect)
        return self.tcp.read(slot, collect)

    def read_version(self, slot: int, src: Optional[int] = None) -> int:
        if src is not None and self.shm is not None \
                and self._same_host(self.rank, src):
            return self.shm.read_version(slot)
        return self.tcp.read_version(slot)

    def reset(self, slot: int, src: Optional[int] = None) -> None:
        if src is not None and self.shm is not None \
                and self._same_host(self.rank, src):
            self.shm.reset(slot)
        else:
            self.tcp.reset(slot)

    def force_drain(self, slot: int, src: Optional[int] = None) -> None:
        # heal-path dead-writer drain, routed like read(): the slot lives
        # in the transport the (dead) writer used
        if src is not None and self.shm is not None \
                and self._same_host(self.rank, src):
            drain = getattr(self.shm, "force_drain", None)
            if drain is not None:
                drain(slot, src=src)
        else:
            self.tcp.force_drain(slot, src=src)

    # -- exposed ------------------------------------------------------------
    def expose(self, array, p: float = 1.0) -> None:
        # publish to both transports so any reader uses its natural path
        if self.shm is not None:
            self.shm.expose(array, p)
        self.tcp.expose(array, p)

    def read_exposed(self, src: int):
        if self.shm is not None and self._same_host(self.rank, src):
            return self.shm.read_exposed(self._local_index[src])
        return self.tcp.read_exposed(src)

    def close(self, unlink: bool = False) -> None:
        if self.shm is not None:
            self.shm.close(unlink)
        self.tcp.close(unlink)

    def unlink_segments(self) -> None:
        # each host group's segment-rank-0 unlinks that host's segment
        if self.shm is not None:
            self.shm.unlink_segments()


def _static_floor_caps() -> "_caps.TransportCaps":
    from bluefog_tpu.native.shm_native import (FallbackShmWindow,
                                               NativeShmWindow)
    from bluefog_tpu.native.tcp_transport import TcpShmWindow

    shm_floor = _caps.meet(NativeShmWindow.CAPS, FallbackShmWindow.CAPS,
                           "shm")
    return _caps.meet(shm_floor, TcpShmWindow.CAPS, "routed")


RoutedWindow.CAPS = _static_floor_caps()
# a routed window never fuses a scale factor (the TCP leg cannot)
RoutedWindow.supports_scale = False
