"""Shared-memory mailbox veneer (shm_mailbox.cc) + pure-Python fallback.

Process-to-process transport for the asynchronous island window ops
(:mod:`bluefog_tpu.islands`) — the TPU-native sibling of the reference's
passive-target MPI RMA windows (``MPI_Win_create/Put/Accumulate/lock`` in
``bluefog/common/mpi_controller.cc`` [U]).  The native path is a chunked
seqlock mailbox in POSIX shm (protocol v2): each slot's payload is divided
into ``chunk_bytes`` chunks, each guarded by its own seqlock and committed
in ascending order, so a pipelined consumer can chase the commit frontier;
collect/reset drain via an O(1) ``drained`` version marker instead of a
zeroing pass; deposits fuse an optional ``scale`` into the copy loop and
``combine`` fuses the reader-side ``acc += weight * payload`` — the three
sequential payload traversals of the v1 protocol collapse into ~one.  The
fallback implements the same interface over an mmap'd file with
``fcntl.lockf`` byte-range locks — slower, zero native deps, used when the
.so is absent.

Both paths share slot geometry: per window, ``nranks`` exposed slots (the
owner-published tensor ``win_get`` reads) followed by ``nranks × maxd``
mailbox slots (slot ``(d, k)`` = last deposit from d's k-th in-neighbor).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import re
import struct
import time
from typing import Optional, Tuple

import numpy as np

from bluefog_tpu.native import get_lib
from bluefog_tpu.native import capabilities as _caps
from bluefog_tpu.telemetry import registry as _telemetry

_DTYPE_CODES = {np.dtype(np.float32): 1, np.dtype(np.float64): 2}


#: A mutex wait is "contended" (worth a per-holder counter + trace
#: instant) past this many nanoseconds; uncontended acquires stay on the
#: aggregate counters only, so the hot path adds no label lookups.
_CONTENDED_WAIT_NS = 1_000_000


def _timed_mutex_acquire(acquire, rank: int, timeout: Optional[float],
                         holders=None, me: int = -1):
    """Run a transport's raw mutex acquire under telemetry timing: total
    wall nanoseconds spent waiting (``shm.mutex_wait_ns``), acquire count,
    and timeout count — the contention signals docs/OBSERVABILITY.md
    points at when win_mutex latency climbs.

    With a ``holders`` board (:class:`HolderBoard`) the wait additionally
    attributes to the *current holder* — the rank whose release we are
    actually waiting on, which under lock-all gossip is usually NOT the
    window owner ``rank``: the holder word is sampled at wait start, a
    contended wait bumps ``shm.mutex_wait_by_holder{holder=..}`` and
    emits a ``mutex_wait`` trace instant carrying the holder rank, and
    the board is stamped with ``me`` after a successful acquire.
    Returns the holder rank observed at wait start (None when free,
    unknown, or it was us)."""
    observed = None
    if holders is not None:
        h = holders.holder(rank)
        if h is not None and h != me:
            observed = h
    reg = _telemetry.get_registry()
    if not reg.enabled and holders is None:
        acquire(rank, timeout)
        return None
    t0 = time.perf_counter_ns()
    try:
        acquire(rank, timeout)
    except TimeoutError:
        if reg.enabled:
            reg.counter("shm.mutex_timeouts").inc()
        raise
    finally:
        wait_ns = time.perf_counter_ns() - t0
        if reg.enabled:
            reg.counter("shm.mutex_wait_ns").add(wait_ns)
            reg.counter("shm.mutex_acquires").inc()
        if observed is not None and wait_ns >= _CONTENDED_WAIT_NS:
            if reg.enabled:
                reg.counter("shm.mutex_wait_by_holder",
                            holder=observed).inc()
            from bluefog_tpu.tracing import tracer as _tracing

            tr = _tracing.get_tracer()
            if tr.enabled:
                tr.instant("mutex_wait", aux=int(observed))
    if holders is not None:
        holders.set_holder(rank, me)
    return observed


def _deposit_counters(obj, reg):
    """Memoized (deposits, chunk_commits) counter pair for a window object.
    Handle lookup costs ~1.5µs each; deposits ride every win op, so the
    write paths cache the live handles on the window, invalidating when
    telemetry is reset to a different registry."""
    cache = getattr(obj, "_tel_cache", None)
    if cache is None or cache[0] is not reg:
        obj._tel_cache = cache = (
            reg, reg.counter("shm.deposits"), reg.counter("shm.chunk_commits"))
    return cache

# ---------------------------------------------------------------------------
# protocol specification (model-checked)
# ---------------------------------------------------------------------------
#
# The seqlock step orders below are the ground truth the static verifier's
# exhaustive interleaving model (bluefog_tpu/analysis/seqlock_model.py)
# mirrors; the model asserts its generated programs match these tuples, so
# a protocol change in shm_mailbox.cc must update BOTH this spec and the
# model — the checker cannot silently drift from the implementation.

#: slot_write() in shm_mailbox.cc: spinlock, seq -> odd, mutate payload,
#: seq -> even (release), unlock.  The odd phase is what makes concurrent
#: plain readers retry instead of copying a half-written payload.
SEQLOCK_WRITER_STEPS = (
    "acquire_lock",
    "seq_to_odd",
    "mutate_payload",
    "seq_to_even",
    "release_lock",
)

#: slot_read() in shm_mailbox.cc: wait-free w.r.t. writers — no lock;
#: retry until the same even seq brackets the whole copy.
SEQLOCK_READER_STEPS = (
    "read_seq_before_retry_if_odd",
    "copy_payload",
    "read_seq_after_retry_if_changed",
)

#: bf_shm_win_read(collect=1): the read AND the drain happen inside ONE
#: critical section — the push-sum mass-conservation primitive (a deposit
#: can never land between the read and the drain marker).
COLLECT_IS_ATOMIC = True

#: slot_deposit() in shm_mailbox.cc, per chunk: chunk_seq -> odd, mutate
#: the chunk, release-fence, chunk_seq -> even.  The release fence before
#: the even publish is what makes an even chunk_seq imply the chunk bytes
#: are globally visible — the verifier's chunk-ring model seeds a variant
#: with the fence dropped and must catch it.
CHUNK_WRITER_STEPS = (
    "chunk_seq_to_odd",
    "mutate_chunk",
    "chunk_seq_to_even",
)

#: Per-chunk consumer bracket (the pipelined drain): same retry discipline
#: as the whole-slot reader, applied to one chunk_seq.
CHUNK_READER_STEPS = (
    "read_chunk_seq_before_retry_if_odd",
    "copy_chunk",
    "read_chunk_seq_after_retry_if_changed",
)

#: slot_deposit() commits chunks in ASCENDING index order: observing chunk
#: c committed at episode E implies every chunk < c is committed at >= E
#: (the frontier invariant a pipelined consumer relies on).  The model
#: checks the reversed-order variant loses this ("reordered chunk commit").
CHUNK_COMMIT_IN_ORDER = True

#: collect/reset drain by storing ``drained = version`` (an O(1) marker;
#: a drained slot READS as zeros by contract) in the same critical section
#: as the copy-out — no memset pass, and still no window for a concurrent
#: accumulate to be marked drained without having been read (model-checked
#: "no lost deposit").
DRAINED_COLLECT_IS_ATOMIC = True

#: Chunk size of the v2 transport.  64 KiB x pipeline_depth 4 keeps the
#: probe ring L2-resident on common parts, which is where the measured
#: pipelined bandwidth peaks (see benchmarks/gossip_bandwidth.py's sweep).
DEFAULT_CHUNK_BYTES = 64 * 1024
DEFAULT_PIPELINE_DEPTH = 4


def chunk_bytes() -> int:
    """Configured chunk size (``BLUEFOG_SHM_CHUNK_BYTES`` or the default)."""
    try:
        v = int(os.environ.get("BLUEFOG_SHM_CHUNK_BYTES", ""))
    except ValueError:
        return DEFAULT_CHUNK_BYTES
    return v if v > 0 else DEFAULT_CHUNK_BYTES


def pipeline_depth() -> int:
    """Ring depth for the pipelined self-edge probe
    (``BLUEFOG_SHM_PIPELINE_DEPTH`` or the default)."""
    try:
        v = int(os.environ.get("BLUEFOG_SHM_PIPELINE_DEPTH", ""))
    except ValueError:
        return DEFAULT_PIPELINE_DEPTH
    return v if v > 0 else DEFAULT_PIPELINE_DEPTH

#: bf_shm_job_barrier(): sense-reversing — the last arriver must reset
#: ``arrived`` BEFORE bumping ``generation``; the opposite order loses the
#: arrival of a rank that races into the next episode (model-checked
#: lost-wakeup).
BARRIER_RESET_BEFORE_RELEASE = True

#: slot_deposit() advances ``p``/``version`` only AFTER every chunk write,
#: under the slot lock.  This ordering is what makes the dead-writer drain
#: sound: a writer that dies mid-deposit has committed ZERO mass, so
#: bf_shm_win_force_drain() can discard the torn payload and store
#: ``drained = version`` without losing any deposited mass (model-checked:
#: dead_writer_drain_model — the commit-before-payload variant must lose
#: mass and is a seeded-bug fixture).
DEPOSIT_COMMITS_AFTER_PAYLOAD = True

#: bf_shm_win_force_drain() (dead-writer recovery): even-ize the torn
#: chunk seqlocks, store the drained marker, advance ``wseq`` past any
#: torn bracket, clear the lock LAST.  Only legal once the failure
#: detector has established the slot's (single) writer is gone.
DEAD_WRITER_DRAIN_STEPS = (
    "evenize_chunk_seqs",
    "mark_drained",
    "evenize_wseq",
    "clear_lock",
)


def seg_name(job: str, suffix: str) -> str:
    """Sanitized POSIX shm object name (leading slash, [A-Za-z0-9_.-])."""
    raw = f"bf_{job}_{suffix}"
    return "/" + re.sub(r"[^A-Za-z0-9_.-]", "_", raw)[:250]


def _as_contiguous(array, dtype) -> np.ndarray:
    a = np.asarray(array, dtype=dtype)
    return np.ascontiguousarray(a)


# ---------------------------------------------------------------------------
# native path
# ---------------------------------------------------------------------------


class NativeShmJob:
    """Job-scope segment: sense-reversing barrier + per-rank mutexes +
    per-rank heartbeat words (the shm leg of the failure detector)."""

    def __init__(self, job: str, rank: int, nranks: int):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.rank = int(rank)
        self.nranks = int(nranks)
        self._name = seg_name(job, "job")
        self._h = lib.bf_shm_job_create(self._name.encode(), rank, nranks)
        if not self._h:
            raise RuntimeError(f"could not create shm job segment {self._name}")
        self._holders = _maybe_holder_board(job, nranks)
        #: holder rank observed at the start of the last mutex_acquire wait
        #: (None = lock was free / board off) — islands' deadline acquire
        #: reads this to blame the *holder* instead of the window owner.
        self.last_wait_holder = None

    def barrier(self, timeout: Optional[float] = None) -> None:
        """Sense-reversing barrier.  With ``timeout`` (seconds) the wait is
        bounded: on expiry the arrival is retracted (later episodes stay
        consistent) and TimeoutError is raised."""
        if timeout is None:
            self._lib.bf_shm_job_barrier(self._h)
            return
        rc = self._lib.bf_shm_job_barrier_timeout(
            self._h, int(timeout * 1000.0))
        if rc != 0:
            raise TimeoutError(
                f"shm barrier timed out after {timeout:.3f}s "
                f"(rank {self.rank} of {self.nranks})")

    def heartbeat(self) -> None:
        """Stamp my liveness word with CLOCK_MONOTONIC milliseconds."""
        self._lib.bf_shm_job_heartbeat(self._h, 0)

    def liveness(self, rank: int) -> float:
        """A rank's last heartbeat stamp in seconds on the same
        system-wide monotonic clock as :func:`time.monotonic` (0.0 if it
        never beat)."""
        return self._lib.bf_shm_job_liveness(self._h, int(rank)) / 1000.0

    def mutex_acquire(self, rank: int,
                      timeout: Optional[float] = None) -> None:
        self.last_wait_holder = _timed_mutex_acquire(
            self._mutex_acquire_raw, rank, timeout,
            holders=self._holders, me=self.rank)

    def _mutex_acquire_raw(self, rank: int,
                           timeout: Optional[float]) -> None:
        if timeout is None:
            self._lib.bf_shm_job_mutex_acquire(self._h, int(rank))
            return
        rc = self._lib.bf_shm_job_mutex_acquire_timeout(
            self._h, int(rank), int(timeout * 1000.0))
        if rc != 0:
            raise TimeoutError(
                f"shm mutex {rank} not acquired within {timeout:.3f}s")

    def mutex_break(self, rank: int) -> None:
        """Forcibly release a mutex whose holder the failure detector has
        declared dead."""
        if self._holders is not None:
            self._holders.clear(int(rank))  # unconditional: holder is dead
        self._lib.bf_shm_job_mutex_break(self._h, int(rank))

    def mutex_release(self, rank: int) -> None:
        if self._holders is not None:
            # clear BEFORE the release: once the lock is free a nonzero
            # word must never name us (conditional — a racing break wins)
            self._holders.clear(int(rank), self.rank)
        self._lib.bf_shm_job_mutex_release(self._h, int(rank))

    def mutex_holder(self, rank: int) -> Optional[int]:
        """Advisory current holder of a job mutex (None when free or the
        holder board is off)."""
        return None if self._holders is None else self._holders.holder(rank)

    def close(self, unlink: bool = False) -> None:
        if self._h:
            self._lib.bf_shm_job_destroy(self._h, 1 if unlink else 0)
            self._h = None
        if self._holders is not None:
            self._holders.close(unlink)
            self._holders = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeShmWindow:
    """One named window: exposed slots + per-in-neighbor mailbox slots.

    Protocol v2: payloads stream through per-chunk seqlocks (ascending
    commit order), ``write`` fuses a ``scale`` factor into the deposit
    pass, ``combine`` fuses the weighted read-side accumulation, and
    collect/reset drain via the O(1) ``drained`` marker.
    """

    #: islands.py keys off this to route scaled deposits / fused combines
    #: through the transport instead of staging temporaries.
    supports_scale = True

    CAPS = _caps.TransportCaps(
        name="shm-native",
        fused_accumulate=True,
        fused_scale=True,       # == supports_scale
        fused_combine=True,     # combine() / update_fused()
        zero_copy_collect=True,  # O(1) drained-marker drain
        chunked_streaming=True,  # per-chunk seqlock ring
        wire_quantization=False,  # same-host memcpy, nothing to quantize
        resume=False,            # shared memory has no sessions to resume
    )

    def __init__(self, job: str, name: str, rank: int, nranks: int,
                 maxd: int, shape: Tuple[int, ...], dtype,
                 chunk: Optional[int] = None):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.rank = rank
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.nbytes = int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize
        self._code = _DTYPE_CODES.get(self.dtype, 0)
        self.chunk_bytes = int(chunk) if chunk else chunk_bytes()
        self.nchunks = max(1, -(-self.nbytes // self.chunk_bytes))
        self.pipeline_depth = min(pipeline_depth(), self.nchunks)
        self._name = seg_name(job, f"win_{name}")
        self._h = lib.bf_shm_win_create(
            self._name.encode(), rank, nranks, max(maxd, 1), self.nbytes,
            self._code, self.chunk_bytes,
        )
        if not self._h:
            raise RuntimeError(f"could not create shm window {self._name}")
        self._exposed_view: Optional[np.ndarray] = None
        self._trace = _maybe_trace_sidecar(job, name, rank, nranks,
                                           max(maxd, 1))

    def trace_stamp(self, dst: int, slot: int, word: int,
                    writer=None) -> None:
        del writer  # single-transport: routing is the RoutedWindow's job
        if self._trace is not None:
            self._trace.stamp(dst, slot, word)

    def trace_peek(self, slot: int, src=None) -> int:
        del src
        return self._trace.peek(slot) if self._trace is not None else 0

    def write(self, dst: int, slot: int, array, p: float = 1.0,
              accumulate: bool = False, writer=None,
              scale: float = 1.0) -> None:
        del writer  # single-transport: routing is the RoutedWindow's job
        if self._code == 0:
            if accumulate:
                raise TypeError(
                    f"accumulate unsupported for dtype {self.dtype}")
            if scale != 1.0:
                raise TypeError(f"scale unsupported for dtype {self.dtype}")
        a = _as_contiguous(array, self.dtype)
        if a.nbytes != self.nbytes:
            raise ValueError(
                f"win_put payload has {a.nbytes} bytes but window "
                f"{self._name} expects {self.nbytes} (shape {self.shape})"
            )
        self._lib.bf_shm_win_write(
            self._h, int(dst), int(slot),
            a.ctypes.data_as(ctypes.c_void_p), float(p),
            1 if accumulate else 0, float(scale),
        )
        reg = _telemetry.get_registry()
        if reg.enabled:
            _, dep, com = _deposit_counters(self, reg)
            dep.inc()
            com.add(self.nchunks)

    def read(self, slot: int, collect: bool = False, src=None, out=None):
        del src
        if out is None:
            out = np.empty(self.shape, dtype=self.dtype)
        elif (out.dtype != self.dtype or out.nbytes != self.nbytes
              or not out.flags["C_CONTIGUOUS"]):
            raise ValueError(
                f"read out= must be C-contiguous {self.dtype} of "
                f"{self.nbytes} bytes"
            )
        p = ctypes.c_double(0.0)
        version = self._lib.bf_shm_win_read(
            self._h, int(slot), out.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(p), 1 if collect else 0,
        )
        if collect:
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.counter("shm.marker_drains").inc()
        return out, p.value, int(version)

    def combine(self, slot: int, acc: np.ndarray, weight: float = 1.0,
                collect: bool = False, src=None):
        """Fused ``acc += weight * slot_payload`` in one native pass under
        the slot lock (a drained slot contributes nothing).  ``collect``
        drains in the same critical section — atomic with concurrent
        accumulating writers.  Returns ``(p, version)``."""
        del src
        if self._code == 0:
            raise TypeError(f"combine unsupported for dtype {self.dtype}")
        if (acc.dtype != self.dtype or acc.nbytes != self.nbytes
                or not acc.flags["C_CONTIGUOUS"]):
            raise ValueError(
                f"combine acc must be C-contiguous {self.dtype} of "
                f"{self.nbytes} bytes"
            )
        p = ctypes.c_double(0.0)
        version = self._lib.bf_shm_win_combine(
            self._h, int(slot), acc.ctypes.data_as(ctypes.c_void_p),
            float(weight), 1 if collect else 0, ctypes.byref(p),
        )
        if collect:
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.counter("shm.marker_drains").inc()
        return p.value, int(version)

    def put_dual(self, dst: int, slot: int, array, p: float = 1.0,
                 accumulate: bool = False, scale: float = 1.0,
                 expose_p: float = 1.0) -> None:
        """Fused expose + deposit: one read of ``array`` feeds both my
        exposed slot and the mailbox slot at ``(dst, slot)``,
        chunk-interleaved (the win_put fast path — replaces two full
        payload passes with one)."""
        if self._code == 0:
            raise TypeError(f"put_dual unsupported for dtype {self.dtype}")
        a = _as_contiguous(array, self.dtype)
        if a.nbytes != self.nbytes:
            raise ValueError(
                f"put_dual payload has {a.nbytes} bytes but window "
                f"{self._name} expects {self.nbytes}"
            )
        self._lib.bf_shm_win_put_dual(
            self._h, int(dst), int(slot),
            a.ctypes.data_as(ctypes.c_void_p), float(p),
            1 if accumulate else 0, float(scale), float(expose_p),
        )
        reg = _telemetry.get_registry()
        if reg.enabled:
            _, dep, com = _deposit_counters(self, reg)
            dep.inc()
            # both legs of the fused pass commit chunk-by-chunk
            com.add(2 * self.nchunks)

    def update_fused(self, slots, weights, self_data: np.ndarray,
                     self_weight: float, self_p: float,
                     out: Optional[np.ndarray],
                     collect: bool = False, expose: int = 0) -> float:
        """Whole win_update in one native sweep:
        ``out = self_weight * self_data + Σ weights[i] * slot_i`` with the
        per-chunk partial cache-resident across sub-passes, optional atomic
        drain of every slot, and optional chunk-interleaved republish of
        ``out`` as the exposed tensor (``expose``: 0 off, 1 with
        p = self_p, 2 with p = the combined mass).  ``out=None`` selects
        the in-place form: the destination is the exposed payload itself
        (read back through :meth:`exposed_view`), which drops the separate
        result buffer AND the republish copy — ``expose`` is then implied
        (forced to 1 if 0).  Returns the combined mass."""
        if self._code == 0:
            raise TypeError(
                f"update_fused unsupported for dtype {self.dtype}")
        checks = [("self_data", self_data)]
        if out is not None:
            checks.append(("out", out))
        for name, a in checks:
            if (a.dtype != self.dtype or a.nbytes != self.nbytes
                    or not a.flags["C_CONTIGUOUS"]):
                raise ValueError(
                    f"update_fused {name} must be C-contiguous "
                    f"{self.dtype} of {self.nbytes} bytes"
                )
        n = len(slots)
        if n != len(weights) or n > 64:
            raise ValueError("update_fused: bad slots/weights")
        c_slots = (ctypes.c_int64 * n)(*[int(s) for s in slots])
        c_w = (ctypes.c_double * n)(*[float(w) for w in weights])
        out_ptr = (None if out is None
                   else out.ctypes.data_as(ctypes.c_void_p))
        p_acc = float(self._lib.bf_shm_win_update_fused(
            self._h, n, c_slots, c_w,
            self_data.ctypes.data_as(ctypes.c_void_p), float(self_weight),
            float(self_p), out_ptr,
            1 if collect else 0, int(expose),
        ))
        if collect and n:
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.counter("shm.marker_drains").add(n)
        return p_acc

    def exposed_view(self) -> np.ndarray:
        """A numpy view of my exposed payload, backed by an INDEPENDENT
        ``mmap`` of the same shm pages (MAP_SHARED ⇒ coherent with the
        native mapping).  Because the view owns its own mapping, arrays
        returned to callers stay readable after :meth:`close` unmaps the
        native segment — the pages live until the last mapping drops.
        Combined with ``update_fused(out=None)`` this makes the island
        ``self_tensor`` the window buffer itself, the reference's
        win_update semantics, with zero extra copies."""
        if self._exposed_view is None:
            off = int(self._lib.bf_shm_win_exposed_offset(self._h))
            page = mmap.PAGESIZE
            base = off & ~(page - 1)
            delta = off - base
            fd = os.open("/dev/shm" + self._name, os.O_RDWR)
            try:
                mm = mmap.mmap(fd, delta + self.nbytes, offset=base)
            finally:
                os.close(fd)
            flat = np.frombuffer(
                mm, dtype=self.dtype,
                count=self.nbytes // self.dtype.itemsize, offset=delta)
            self._exposed_view = flat.reshape(self.shape)
        return self._exposed_view

    def probe(self, src: np.ndarray, dst: np.ndarray, slot: int = 0,
              ring_depth: Optional[int] = None) -> None:
        """Pipelined self-edge streaming pass: ``src`` flows to ``dst``
        through a bounded cache-resident ring of ``ring_depth`` chunk
        slots of my own mailbox ``slot``, with the full per-chunk seqlock
        protocol on both legs.  One call = one complete payload roundtrip
        (the protocol-ceiling benchmark primitive); the slot is left
        drained."""
        for a in (src, dst):
            if a.nbytes != self.nbytes or not a.flags["C_CONTIGUOUS"]:
                raise ValueError(
                    f"probe buffers must be C-contiguous, {self.nbytes} bytes"
                )
        depth = int(ring_depth) if ring_depth else self.pipeline_depth
        rc = self._lib.bf_shm_win_probe(
            self._h, int(slot), src.ctypes.data_as(ctypes.c_void_p),
            dst.ctypes.data_as(ctypes.c_void_p), depth,
        )
        if rc != 0:
            raise RuntimeError("probe reader bracket failed")

    def read_version(self, slot: int, src=None) -> int:
        del src
        # metadata-only probe: NULL out pointer skips the payload copy
        return int(self._lib.bf_shm_win_read(self._h, int(slot), None, None, 0))

    def reset(self, slot: int, src=None) -> None:
        del src
        self._lib.bf_shm_win_reset(self._h, int(slot))

    def force_drain(self, slot: int, src=None) -> None:
        """Dead-writer recovery on my mailbox ``slot``: force a consistent
        drained state even if the writer died mid-deposit (lock held,
        odd seqlocks).  Only call after the failure detector has declared
        the slot's writer dead — see DEAD_WRITER_DRAIN_STEPS."""
        del src
        self._lib.bf_shm_win_force_drain(self._h, int(slot))
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("shm.force_drains").inc()

    def expose(self, array, p: float = 1.0) -> None:
        a = _as_contiguous(array, self.dtype)
        if a.nbytes != self.nbytes:
            raise ValueError(
                f"expose payload has {a.nbytes} bytes but window "
                f"{self._name} expects {self.nbytes} (shape {self.shape})"
            )
        self._lib.bf_shm_win_expose(
            self._h, a.ctypes.data_as(ctypes.c_void_p), float(p)
        )

    def read_exposed(self, src: int):
        out = np.empty(self.shape, dtype=self.dtype)
        p = ctypes.c_double(0.0)
        version = self._lib.bf_shm_win_read_exposed(
            self._h, int(src), out.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(p),
        )
        return out, p.value, int(version)

    def close(self, unlink: bool = False) -> None:
        if self._h:
            self._lib.bf_shm_win_destroy(self._h, 1 if unlink else 0)
            self._h = None
        if self._trace is not None:
            self._trace.close(unlink)
            self._trace = None

    def unlink_segments(self) -> None:
        """Name-based unlink by the designated (segment-rank-0) rank —
        the collective win_free teardown (call after close, between
        barriers)."""
        if self.rank == 0:
            _unlink_name(self._name)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _unlink_name(name: str) -> None:
    lib = get_lib()
    if lib is not None:
        try:
            lib.bf_shm_unlink(name.encode())
        except Exception:
            pass
    for d in {"/dev/shm", _FALLBACK_DIR}:
        try:
            os.unlink(os.path.join(d, name[1:]))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Python mirror of the chunk-ring slot protocol (tests / fault injection)
# ---------------------------------------------------------------------------


class ChunkRingMirror:
    """In-process mirror of one chunk-ring slot's state machine.

    Replays the exact v2 protocol steps (``CHUNK_WRITER_STEPS`` /
    ``CHUNK_READER_STEPS``) over numpy state so tests can freeze a writer
    MID-DEPOSIT — something the native path never exposes — and assert the
    reader-side retry discipline: a bracketed read must refuse to return
    while ``wseq`` is odd or changes across the copy.  Byte-level chunk
    math mirrors the native layout (last chunk may be short).
    """

    def __init__(self, nbytes: int, chunk: Optional[int] = None):
        self.nbytes = int(nbytes)
        self.chunk_bytes = int(chunk) if chunk else chunk_bytes()
        self.nchunks = max(1, -(-self.nbytes // self.chunk_bytes))
        self.payload = np.zeros(self.nbytes, dtype=np.uint8)
        self.chunk_seq = np.zeros(self.nchunks, dtype=np.uint64)
        self.wseq = 0
        self.version = 0
        self.drained = 0
        self.p = 0.0
        self._pending = None  # (data, p, next_chunk) of a frozen deposit

    def _chunk_slice(self, c: int) -> slice:
        lo = c * self.chunk_bytes
        return slice(lo, min(lo + self.chunk_bytes, self.nbytes))

    def _commit_chunk(self, data: bytes, c: int) -> None:
        sl = self._chunk_slice(c)
        self.chunk_seq[c] += 1  # odd: chunk in flux
        self.payload[sl] = np.frombuffer(data[sl], dtype=np.uint8)
        self.chunk_seq[c] += 1  # even: committed (release in native code)

    def write(self, data: bytes, p: float = 1.0) -> None:
        """Full deposit: ascending in-order chunk commits under odd wseq."""
        assert self._pending is None, "complete the torn write first"
        assert len(data) == self.nbytes
        self.wseq += 1
        for c in range(self.nchunks):
            self._commit_chunk(data, c)
        self.version += 1
        self.p = p
        self.wseq += 1

    def begin_torn_write(self, data: bytes, p: float = 1.0,
                         tear_at: int = 0) -> None:
        """Start a deposit and FREEZE it mid-protocol: chunks before
        ``tear_at`` are committed, chunk ``tear_at`` is left odd with only
        half its bytes stored, and ``wseq`` stays odd — the state a reader
        observes when a writer is preempted mid-copy."""
        assert self._pending is None
        assert len(data) == self.nbytes
        assert 0 <= tear_at < self.nchunks
        self.wseq += 1
        for c in range(tear_at):
            self._commit_chunk(data, c)
        sl = self._chunk_slice(tear_at)
        half = sl.start + max(1, (sl.stop - sl.start) // 2)
        self.chunk_seq[tear_at] += 1  # odd, and it stays odd
        self.payload[sl.start:half] = np.frombuffer(
            data[sl.start:half], dtype=np.uint8)
        self._pending = (data, p, tear_at)

    def complete_write(self) -> None:
        """Finish the frozen deposit (writer resumes and publishes)."""
        assert self._pending is not None
        data, p, tear_at = self._pending
        sl = self._chunk_slice(tear_at)
        self.payload[sl] = np.frombuffer(data[sl], dtype=np.uint8)
        self.chunk_seq[tear_at] += 1  # even
        for c in range(tear_at + 1, self.nchunks):
            self._commit_chunk(data, c)
        self.version += 1
        self.p = p
        self.wseq += 1
        self._pending = None

    def force_drain(self) -> None:
        """Dead-writer recovery (mirrors ``bf_shm_win_force_drain``):
        discard any frozen mid-deposit state, even-ize the torn chunk
        seqlocks and ``wseq``, and store the drained marker.  Because
        ``version``/``p`` only advance AFTER every chunk commit
        (DEPOSIT_COMMITS_AFTER_PAYLOAD), the torn deposit had committed
        zero mass — the post-drain slot reads as logical zero and the
        committed-mass ledger is conserved."""
        self._pending = None
        for c in range(self.nchunks):
            if int(self.chunk_seq[c]) & 1:
                self.chunk_seq[c] += 1
        self.drained = self.version
        self.p = 0.0
        if self.wseq & 1:
            self.wseq += 1

    def read(self, retries: int = 64):
        """Whole-slot bracketed read: retry while ``wseq`` is odd or moves
        across the copy.  Raises TimeoutError once the retry budget is
        exhausted (a frozen torn writer never publishes)."""
        for attempt in range(retries):
            before = self.wseq
            if before & 1:
                continue
            out = self.payload.copy()
            empty = self.drained == self.version
            p = 0.0 if empty else self.p
            if self.wseq == before:
                if empty:
                    out[:] = 0
                if attempt:
                    reg = _telemetry.get_registry()
                    if reg.enabled:
                        reg.counter("shm.seqlock_retries").add(attempt)
                return bytes(out), p, self.version
        raise TimeoutError("reader retry budget exhausted (torn writer)")

    def read_chunk(self, c: int, retries: int = 64) -> bytes:
        """Per-chunk bracketed read (the pipelined consumer's unit)."""
        sl = self._chunk_slice(c)
        for attempt in range(retries):
            before = int(self.chunk_seq[c])
            if before & 1:
                continue
            out = bytes(self.payload[sl])
            if int(self.chunk_seq[c]) == before:
                if attempt:
                    reg = _telemetry.get_registry()
                    if reg.enabled:
                        reg.counter("shm.seqlock_retries").add(attempt)
                return out
        raise TimeoutError(
            f"chunk {c} retry budget exhausted (torn writer)")


# ---------------------------------------------------------------------------
# pure-Python fallback (mmap + fcntl byte-range locks)
# ---------------------------------------------------------------------------

_FALLBACK_DIR = os.environ.get("BLUEFOG_SHM_DIR", "/dev/shm")


class _FallbackSegment:
    """mmap'd file; every slot guarded by an exclusive lockf range.

    Creation needs no handshake: all ranks ftruncate to the same size
    (idempotent, zero-fills) and zeros are a valid initial state.
    """

    def __init__(self, path: str, nbytes: int):
        self.path = path
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        os.ftruncate(self._fd, nbytes)
        self._mm = mmap.mmap(self._fd, nbytes)

    def lock(self, start: int, length: int):
        import fcntl

        fcntl.lockf(self._fd, fcntl.LOCK_EX, length, start)

    def unlock(self, start: int, length: int):
        import fcntl

        fcntl.lockf(self._fd, fcntl.LOCK_UN, length, start)

    def close(self, unlink: bool = False):
        if self._mm is not None:
            self._mm.close()
            os.close(self._fd)
            self._mm = None
            if unlink:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass


class TraceSidecar:
    """One aligned u64 trace-context word per (dst, mailbox-slot) pair,
    in an mmap segment that rides NEXT TO a window (``trace_<name>``)
    rather than inside it — the native chunk-ring C struct is not
    extensible without recompiling, and the fallback layout stays wire-
    compatible.  Writes are single 8-byte aligned ``pack_into`` calls
    (atomic in practice on x86/ARM64); the word is advisory — a torn or
    stale read costs one flow arrow in the merged trace, never
    correctness — so no locks are taken.  Created only when
    ``BFTPU_TRACING`` is on; the ``seg_name`` prefix means
    :func:`unlink_all` reclaims it with the window segments."""

    def __init__(self, job: str, name: str, rank: int, nranks: int,
                 maxd: int):
        self.rank = int(rank)
        self.maxd = int(maxd)
        path = os.path.join(_FALLBACK_DIR, seg_name(job, f"trace_{name}")[1:])
        self._seg = _FallbackSegment(path, nranks * self.maxd * 8)

    def stamp(self, dst: int, slot: int, word: int) -> None:
        struct.pack_into("<Q", self._seg._mm,
                         (int(dst) * self.maxd + int(slot)) * 8,
                         word & 0xFFFFFFFFFFFFFFFF)

    def peek(self, slot: int) -> int:
        return struct.unpack_from(
            "<Q", self._seg._mm, (self.rank * self.maxd + int(slot)) * 8)[0]

    def close(self, unlink: bool = False) -> None:
        self._seg.close(unlink)


def _maybe_trace_sidecar(job: str, name: str, rank: int, nranks: int,
                         maxd: int):
    """A window's trace sidecar when tracing is enabled, else None (the
    window's trace_stamp/trace_peek become no-ops).

    Also created (it is a tiny segment) when the introspection plane is
    on, so flipping ``BFTPU_TRACING`` at runtime via ``bftpu-top`` finds
    the flow-arrow words already wired — windows are built once at
    win_create and cannot grow a sidecar later."""
    from bluefog_tpu.tracing.tracer import tracing_dir

    if tracing_dir() is None and not statuspage_enabled():
        return None
    try:
        return TraceSidecar(job, name, rank, nranks, maxd)
    except OSError:
        return None


def statuspage_enabled() -> bool:
    """Whether the live-introspection plane (per-rank status pages + the
    mutex holder board) is on.  Default ON — the point of the plane is
    that a job is attachable *before* anyone knew it would misbehave;
    ``BFTPU_STATUSPAGE=0`` opts out (bench.py gates the cost < 2%)."""
    return os.environ.get("BFTPU_STATUSPAGE", "1") not in ("0", "", "false")


class HolderBoard:
    """One aligned u64 *holder word* per job mutex, in a sidecar segment
    (``bf_<job>_holders``) next to the job segment — the native C struct
    is not extensible without recompiling shm_mailbox.cc.

    Word value is ``holder_rank + 1`` (0 = free), stamped by the winner
    right AFTER its raw acquire and cleared right BEFORE its release, so
    a nonzero word is only ever a rank that really holds (or held a
    heartbeat ago) the lock.  Like the trace sidecar the word is advisory
    and lock-free: a torn/stale read costs one wait mis-attribution,
    never correctness, so waiters sample it without synchronizing and
    ``bftpu-top`` mmaps it read-only from outside the job."""

    def __init__(self, job: str, nranks: int):
        self.nranks = int(nranks)
        path = os.path.join(_FALLBACK_DIR, seg_name(job, "holders")[1:])
        self._seg = _FallbackSegment(path, max(1, self.nranks) * 8)

    def set_holder(self, mutex_rank: int, holder_rank: int) -> None:
        if 0 <= int(mutex_rank) < self.nranks:
            struct.pack_into("<Q", self._seg._mm, int(mutex_rank) * 8,
                             (int(holder_rank) + 1) & 0xFFFFFFFFFFFFFFFF)

    def clear(self, mutex_rank: int,
              holder_rank: Optional[int] = None) -> None:
        """Zero a holder word; with ``holder_rank`` the clear is
        conditional (only if we are the recorded holder), so a release
        racing a ``mutex_break`` never erases the breaker's view."""
        if not 0 <= int(mutex_rank) < self.nranks:
            return
        off = int(mutex_rank) * 8
        if holder_rank is not None:
            cur = struct.unpack_from("<Q", self._seg._mm, off)[0]
            if cur != int(holder_rank) + 1:
                return
        struct.pack_into("<Q", self._seg._mm, off, 0)

    def holder(self, mutex_rank: int) -> Optional[int]:
        """Current holder rank of a mutex, or None when free/unknown."""
        if not 0 <= int(mutex_rank) < self.nranks:
            return None
        word = struct.unpack_from(
            "<Q", self._seg._mm, int(mutex_rank) * 8)[0]
        if word == 0 or word > self.nranks:
            return None
        return int(word) - 1

    def snapshot(self):
        """``{mutex_rank: holder_rank}`` for every currently-held word."""
        out = {}
        for r in range(self.nranks):
            h = self.holder(r)
            if h is not None:
                out[r] = h
        return out

    def close(self, unlink: bool = False) -> None:
        self._seg.close(unlink)


def _maybe_holder_board(job: str, nranks: int):
    """The job's holder board when introspection is on, else None (mutex
    waits fall back to owner-rank attribution)."""
    if not statuspage_enabled():
        return None
    try:
        return HolderBoard(job, nranks)
    except OSError:
        return None


class FallbackShmJob:
    """Barrier + mutexes + heartbeats over lockf.  Layout:
    [arrived u64][generation u64], one lock byte per rank (the mutex is
    the held lockf range), then one heartbeat u64 per rank."""

    def __init__(self, job: str, rank: int, nranks: int):
        self.rank = int(rank)
        self.nranks = nranks
        path = os.path.join(_FALLBACK_DIR, seg_name(job, "job")[1:])
        self._seg = _FallbackSegment(path, 16 + nranks + 8 * nranks)
        self._holders = _maybe_holder_board(job, nranks)
        self.last_wait_holder = None  # see NativeShmJob

    def _beat_off(self, rank: int) -> int:
        return 16 + self.nranks + 8 * rank

    def barrier(self, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        mm = self._seg._mm
        self._seg.lock(0, 16)
        gen = struct.unpack_from("<Q", mm, 8)[0]
        arrived = struct.unpack_from("<Q", mm, 0)[0] + 1
        if arrived == self.nranks:
            struct.pack_into("<Q", mm, 0, 0)
            struct.pack_into("<Q", mm, 8, gen + 1)
            self._seg.unlock(0, 16)
            return
        struct.pack_into("<Q", mm, 0, arrived)
        self._seg.unlock(0, 16)
        while True:
            self._seg.lock(8, 8)
            cur = struct.unpack_from("<Q", mm, 8)[0]
            self._seg.unlock(8, 8)
            if cur != gen:
                return
            if deadline is not None and time.monotonic() > deadline:
                # retract the arrival so later episodes stay consistent
                # (reset+bump are atomic under lock(0,16), so gen
                # unchanged here implies our arrival is still counted)
                self._seg.lock(0, 16)
                try:
                    if struct.unpack_from("<Q", mm, 8)[0] != gen:
                        return  # released while we were timing out
                    a = struct.unpack_from("<Q", mm, 0)[0]
                    struct.pack_into("<Q", mm, 0, max(0, a - 1))
                finally:
                    self._seg.unlock(0, 16)
                raise TimeoutError(
                    f"shm barrier timed out after {timeout:.3f}s "
                    f"(rank {self.rank} of {self.nranks})")
            time.sleep(0.0002)

    def heartbeat(self) -> None:
        struct.pack_into("<Q", self._seg._mm, self._beat_off(self.rank),
                         int(time.monotonic() * 1000.0))

    def liveness(self, rank: int) -> float:
        return struct.unpack_from(
            "<Q", self._seg._mm, self._beat_off(rank))[0] / 1000.0

    def mutex_acquire(self, rank: int,
                      timeout: Optional[float] = None) -> None:
        self.last_wait_holder = _timed_mutex_acquire(
            self._mutex_acquire_raw, rank, timeout,
            holders=self._holders, me=self.rank)

    def _mutex_acquire_raw(self, rank: int,
                           timeout: Optional[float]) -> None:
        if timeout is None:
            self._seg.lock(16 + rank, 1)
            return
        import fcntl

        deadline = time.monotonic() + timeout
        while True:
            try:
                fcntl.lockf(self._seg._fd, fcntl.LOCK_EX | fcntl.LOCK_NB,
                            1, 16 + rank)
                return
            except OSError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"shm mutex {rank} not acquired within "
                        f"{timeout:.3f}s") from None
                time.sleep(0.0005)

    def mutex_break(self, rank: int) -> None:
        # lockf ranges die with their holder process — nothing to break,
        # but the advisory holder word outlives the holder and must go
        if self._holders is not None:
            self._holders.clear(int(rank))

    def mutex_release(self, rank: int) -> None:
        if self._holders is not None:
            self._holders.clear(int(rank), self.rank)
        self._seg.unlock(16 + rank, 1)

    def mutex_holder(self, rank: int) -> Optional[int]:
        return None if self._holders is None else self._holders.holder(rank)

    def close(self, unlink: bool = False) -> None:
        self._seg.close(unlink)
        if self._holders is not None:
            self._holders.close(unlink)
            self._holders = None


class FallbackShmWindow:
    """Same slot geometry and op surface as the native window (including
    scaled writes and fused ``combine``); every op takes the slot's
    exclusive lock (no seqlock or chunking — simplicity over throughput;
    the chunk attributes exist only so benchmark/metadata consumers see a
    uniform interface)."""

    _HDR = 16  # per-slot: [version u64][p f64]

    supports_scale = True

    CAPS = _caps.TransportCaps(
        name="shm-fallback",
        fused_accumulate=True,
        fused_scale=True,        # == supports_scale
        fused_combine=True,      # locked two-pass combine()
        zero_copy_collect=False,  # collect memsets the payload
        chunked_streaming=False,  # whole-slot lockf, no chunk ring
        wire_quantization=False,
        resume=False,
    )

    def __init__(self, job: str, name: str, rank: int, nranks: int,
                 maxd: int, shape: Tuple[int, ...], dtype,
                 chunk: Optional[int] = None):
        self.rank = rank
        self.nranks = nranks
        self.maxd = max(maxd, 1)
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.nbytes = int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize
        self.chunk_bytes = int(chunk) if chunk else chunk_bytes()
        self.nchunks = max(1, -(-self.nbytes // self.chunk_bytes))
        self.pipeline_depth = min(pipeline_depth(), self.nchunks)
        self._stride = self._HDR + ((self.nbytes + 63) // 64) * 64
        nslots = nranks + nranks * self.maxd
        path = os.path.join(_FALLBACK_DIR, seg_name(job, f"win_{name}")[1:])
        self._seg = _FallbackSegment(path, nslots * self._stride)
        self._trace = _maybe_trace_sidecar(job, name, rank, nranks,
                                           self.maxd)

    def trace_stamp(self, dst: int, slot: int, word: int,
                    writer=None) -> None:
        del writer
        if self._trace is not None:
            self._trace.stamp(dst, slot, word)

    def trace_peek(self, slot: int, src=None) -> int:
        del src
        return self._trace.peek(slot) if self._trace is not None else 0

    def _off(self, index: int) -> int:
        return index * self._stride

    def _mail_index(self, d: int, k: int) -> int:
        return self.nranks + d * self.maxd + k

    def _read_slot(self, off: int):
        mm = self._seg._mm
        version, p = struct.unpack_from("<Qd", mm, off)
        a = np.frombuffer(
            mm, dtype=self.dtype,
            count=self.nbytes // self.dtype.itemsize,
            offset=off + self._HDR,
        ).reshape(self.shape).copy()
        return a, p, version

    def _locked(self, index: int):
        off = self._off(index)
        self._seg.lock(off, self._stride)
        return off

    def _unlock(self, index: int):
        self._seg.unlock(self._off(index), self._stride)

    def write(self, dst: int, slot: int, array, p: float = 1.0,
              accumulate: bool = False, writer=None,
              scale: float = 1.0) -> None:
        del writer
        if self.dtype not in _DTYPE_CODES:
            # same contract as the native path: accumulate/scale need a
            # float payload (raw dtypes are opaque bytes)
            if accumulate:
                raise TypeError(
                    f"accumulate unsupported for dtype {self.dtype}")
            if scale != 1.0:
                raise TypeError(f"scale unsupported for dtype {self.dtype}")
        a = _as_contiguous(array, self.dtype)
        if scale != 1.0:
            a = a * np.asarray(scale, dtype=self.dtype)
        idx = self._mail_index(dst, slot)
        off = self._locked(idx)
        try:
            mm = self._seg._mm
            version, cur_p = struct.unpack_from("<Qd", mm, off)
            if accumulate:
                cur, _, _ = self._read_slot(off)
                a = cur + a
                p = cur_p + p
            mm[off + self._HDR:off + self._HDR + self.nbytes] = a.tobytes()
            struct.pack_into("<Qd", mm, off, version + 1, p)
        finally:
            self._unlock(idx)
        reg = _telemetry.get_registry()
        if reg.enabled:
            _, dep, com = _deposit_counters(self, reg)
            dep.inc()
            com.inc()  # one whole-slot commit

    def read(self, slot: int, collect: bool = False, src=None, out=None):
        del src
        idx = self._mail_index(self.rank, slot)
        off = self._locked(idx)
        try:
            a, p, version = self._read_slot(off)
            if collect:
                mm = self._seg._mm
                mm[off + self._HDR:off + self._HDR + self.nbytes] = (
                    b"\x00" * self.nbytes
                )
                struct.pack_into("<Qd", mm, off, version, 0.0)
        finally:
            self._unlock(idx)
        if collect:
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.counter("shm.marker_drains").inc()
        if out is not None:
            np.copyto(out, a)
            a = out
        return a, p, version

    def combine(self, slot: int, acc: np.ndarray, weight: float = 1.0,
                collect: bool = False, src=None):
        """acc += weight * payload under the slot lock; returns (p,
        version).  Interface parity with the native fused combine (here it
        is two numpy passes over a view — no temporaries, but no fusion)."""
        del src
        if self.dtype not in _DTYPE_CODES:
            raise TypeError(f"combine unsupported for dtype {self.dtype}")
        idx = self._mail_index(self.rank, slot)
        off = self._locked(idx)
        try:
            mm = self._seg._mm
            version, p = struct.unpack_from("<Qd", mm, off)
            view = np.frombuffer(
                mm, dtype=self.dtype,
                count=self.nbytes // self.dtype.itemsize,
                offset=off + self._HDR,
            ).reshape(self.shape)
            flat_acc = acc.reshape(self.shape)
            flat_acc += np.asarray(weight, dtype=self.dtype) * view
            if collect:
                mm[off + self._HDR:off + self._HDR + self.nbytes] = (
                    b"\x00" * self.nbytes
                )
                struct.pack_into("<Qd", mm, off, version, 0.0)
        finally:
            self._unlock(idx)
        if collect:
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.counter("shm.marker_drains").inc()
        return p, version

    def put_dual(self, dst: int, slot: int, array, p: float = 1.0,
                 accumulate: bool = False, scale: float = 1.0,
                 expose_p: float = 1.0) -> None:
        """Interface parity with the native fused op: expose + deposit as
        two plain locked passes (nothing to fuse without chunking)."""
        if self.dtype not in _DTYPE_CODES:
            raise TypeError(f"put_dual unsupported for dtype {self.dtype}")
        self.expose(array, expose_p)
        self.write(dst, slot, array, p=p, accumulate=accumulate, scale=scale)

    def update_fused(self, slots, weights, self_data: np.ndarray,
                     self_weight: float, self_p: float, out: np.ndarray,
                     collect: bool = False, expose: int = 0) -> float:
        """Interface parity with the native fused sweep, composed from the
        per-slot combine (same drain atomicity per slot, no cross-slot
        fusion)."""
        if self.dtype not in _DTYPE_CODES:
            raise TypeError(
                f"update_fused unsupported for dtype {self.dtype}")
        flat = out.reshape(-1)
        np.multiply(self_data.reshape(-1),
                    np.asarray(self_weight, dtype=self.dtype), out=flat)
        p_acc = self_weight * self_p
        for s, w in zip(slots, weights):
            p, _ = self.combine(s, out, w, collect=collect)
            p_acc += w * p
        if expose:
            self.expose(out, p_acc if expose == 2 else self_p)
        return float(p_acc)

    def probe(self, src: np.ndarray, dst: np.ndarray, slot: int = 0,
              ring_depth: Optional[int] = None) -> None:
        """Self-edge roundtrip for the protocol-ceiling benchmark: a plain
        locked write + read (the fallback has no chunk ring to pipeline)."""
        del ring_depth
        self.write(self.rank, slot, src)
        a, _, _ = self.read(slot, collect=True)
        np.copyto(dst.reshape(self.shape), a)

    def read_version(self, slot: int, src=None) -> int:
        del src
        idx = self._mail_index(self.rank, slot)
        off = self._locked(idx)
        try:
            return int(struct.unpack_from("<Q", self._seg._mm, off)[0])
        finally:
            self._unlock(idx)

    def reset(self, slot: int, src=None) -> None:
        del src
        idx = self._mail_index(self.rank, slot)
        off = self._locked(idx)
        try:
            mm = self._seg._mm
            version = struct.unpack_from("<Q", mm, off)[0]
            mm[off + self._HDR:off + self._HDR + self.nbytes] = (
                b"\x00" * self.nbytes
            )
            struct.pack_into("<Qd", mm, off, version, 0.0)
        finally:
            self._unlock(idx)

    def force_drain(self, slot: int, src=None) -> None:
        """Dead-writer recovery.  lockf ranges die with their holder, so
        a dead writer cannot leave this slot locked — reset suffices."""
        self.reset(slot, src=src)
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("shm.force_drains").inc()

    def unlink_segments(self) -> None:
        if self.rank == 0:
            try:
                os.unlink(self._seg.path)
            except OSError:
                pass

    def expose(self, array, p: float = 1.0) -> None:
        a = _as_contiguous(array, self.dtype)
        off = self._locked(self.rank)
        try:
            mm = self._seg._mm
            version = struct.unpack_from("<Q", mm, off)[0]
            mm[off + self._HDR:off + self._HDR + self.nbytes] = a.tobytes()
            struct.pack_into("<Qd", mm, off, version + 1, p)
        finally:
            self._unlock(self.rank)

    def read_exposed(self, src: int):
        off = self._locked(src)
        try:
            return self._read_slot(off)
        finally:
            self._unlock(src)

    def close(self, unlink: bool = False) -> None:
        self._seg.close(unlink)
        if self._trace is not None:
            self._trace.close(unlink)
            self._trace = None


# ---------------------------------------------------------------------------
# factory
# ---------------------------------------------------------------------------


def make_shm_job(job: str, rank: int, nranks: int):
    """Shared-memory job segment: native when the .so is available, else
    the lockf fallback (no transport dispatch — used directly by the
    routed transport's intra-host leg)."""
    if get_lib() is not None and not _force_fallback():
        return NativeShmJob(job, rank, nranks)
    return FallbackShmJob(job, rank, nranks)


def make_shm_window(job: str, name: str, rank: int, nranks: int, maxd: int,
                    shape, dtype, chunk: Optional[int] = None):
    if get_lib() is not None and not _force_fallback():
        return NativeShmWindow(job, name, rank, nranks, maxd, shape, dtype,
                               chunk=chunk)
    return FallbackShmWindow(job, name, rank, nranks, maxd, shape, dtype,
                             chunk=chunk)


def make_job(job: str, rank: int, nranks: int):
    """Transport factory: hierarchical (shm intra-host + TCP inter-host)
    when a hostmap is configured, else TCP (cross-host/DCN) when selected,
    else pure shared memory."""
    hostmap = os.environ.get("BLUEFOG_ISLAND_HOSTMAP")
    if hostmap:
        from bluefog_tpu.native.routed_transport import RoutedJob, parse_hostmap

        hosts = parse_hostmap(hostmap, nranks)
        return RoutedJob(job, rank, nranks, hosts, _derived_coord(job))
    coord = _tcp_coord(job)
    if coord is not None:
        from bluefog_tpu.native.tcp_transport import TcpShmJob

        return TcpShmJob(job, rank, nranks, coord)
    return make_shm_job(job, rank, nranks)


def make_window(job: str, name: str, rank: int, nranks: int, maxd: int,
                shape, dtype):
    hostmap = os.environ.get("BLUEFOG_ISLAND_HOSTMAP")
    if hostmap:
        from bluefog_tpu.native.routed_transport import (
            RoutedWindow, parse_hostmap,
        )

        hosts = parse_hostmap(hostmap, nranks)
        return RoutedWindow(job, name, rank, nranks, maxd, shape, dtype,
                            hosts, _derived_coord(job))
    coord = _tcp_coord(job)
    if coord is not None:
        from bluefog_tpu.native.tcp_transport import TcpShmWindow

        return TcpShmWindow(job, name, rank, nranks, maxd, shape, dtype, coord)
    return make_shm_window(job, name, rank, nranks, maxd, shape, dtype)


def _force_fallback() -> bool:
    return os.environ.get("BLUEFOG_SHM_FALLBACK", "0") == "1"


def _derived_coord(job: str) -> str:
    """Explicit ``BLUEFOG_ISLAND_COORD`` or a job-deterministic localhost
    port, below the Linux ephemeral range (32768+) so a transient client
    socket never occupies it."""
    coord = os.environ.get("BLUEFOG_ISLAND_COORD")
    if coord:
        return coord
    import zlib

    port = 10000 + zlib.crc32(job.encode()) % 20000
    return f"127.0.0.1:{port}"


def island_transport() -> str:
    """The transport the island runtime will actually use for the current
    environment, mirroring ``make_job``/``make_window`` dispatch exactly:
    "routed" (hierarchical shm-intra/TCP-inter) when
    ``BLUEFOG_ISLAND_HOSTMAP`` is set, else "tcp" when
    ``BLUEFOG_ISLAND_COORD`` or ``BLUEFOG_ISLAND_TRANSPORT=tcp`` selects
    it, else "shm".  The single source of truth — benchmarks/labels must
    query this rather than re-reading the env vars."""
    if os.environ.get("BLUEFOG_ISLAND_HOSTMAP"):
        return "routed"
    if os.environ.get("BLUEFOG_ISLAND_COORD"):
        return "tcp"
    if os.environ.get("BLUEFOG_ISLAND_TRANSPORT", "").lower() == "tcp":
        return "tcp"
    return "shm"


def _tcp_coord(job: str) -> Optional[str]:
    """Coordinator address when the TCP (cross-host) transport is selected
    (see :func:`island_transport`): a job-deterministic localhost port for
    single-host testing, or derived from ``BLUEFOG_ISLAND_COORD``."""
    return _derived_coord(job) if island_transport() == "tcp" else None


def unlink_segment(job: str, suffix: str) -> None:
    """Best-effort unlink of one named segment (native object + fallback
    file); missing names are ignored."""
    _unlink_name(seg_name(job, suffix))


def poll_versions(win, pairs, seen):
    """Slots whose deposit count moved: ``[(slot, src, version)]`` for
    each ``(slot, src)`` in ``pairs`` whose ``read_version`` differs from
    ``seen[slot]``.  One lock-free word read per pair — the progress
    engine's idle prefetch uses this to re-read only edges with fresh
    deposits.  Transports without version words (or a slot torn down
    mid-poll) contribute nothing rather than raising."""
    moved = []
    for slot, src in pairs:
        try:
            ver = int(win.read_version(slot, src=src))
        except Exception:  # noqa: BLE001 - polling must never raise
            continue
        if ver != seen.get(slot):
            moved.append((slot, src, ver))
    return moved


# ---------------------------------------------------------------------------
# membership-epoch word (elastic membership; resilience/join.py)
# ---------------------------------------------------------------------------


def _epoch_word_path(job: str) -> str:
    # named like every other job segment ("bf_<job>_epoch"), so crashed-run
    # hygiene (unlink_all's prefix glob) reclaims it with the rest
    return os.path.join(_FALLBACK_DIR, seg_name(job, "epoch")[1:])


def membership_epoch(job: str) -> int:
    """The job's current membership-epoch word (0 = the launch view; a
    missing word reads as 0, so pre-elastic jobs are epoch 0 for free).

    One 8-byte little-endian file in the shm dir: readers see either the
    old or the new word (publish is an atomic rename), never a tear —
    the cheap "has membership moved?" probe incumbents poll at round
    barriers before touching the (heavier) membership board."""
    try:
        with open(_epoch_word_path(job), "rb") as f:
            raw = f.read(8)
    except OSError:
        return 0
    return struct.unpack("<Q", raw)[0] if len(raw) == 8 else 0


def publish_membership_epoch(job: str, epoch: int) -> None:
    """Atomically publish the membership-epoch word (monotone: a stale
    publish below the current word is dropped, mirroring the monotone
    dead-set contract on the shrink side)."""
    if int(epoch) <= membership_epoch(job):
        return
    path = _epoch_word_path(job)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(struct.pack("<Q", int(epoch)))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def unlink_all(job: str, window_names=()) -> None:
    """Best-effort cleanup of ALL of a job's segments (crashed-run hygiene).

    Globs ``/dev/shm`` (where shm_open objects appear as files on Linux) and
    the fallback dir for the job prefix, so window segments are reclaimed
    even when the caller no longer knows their names (a crashed run); the
    explicit ``window_names`` are unlinked too for non-Linux portability.
    """
    import glob as _glob

    lib = get_lib()
    prefix = seg_name(job, "")  # "/bf_<job>_"
    names = {seg_name(job, "job")}
    names.update(seg_name(job, f"win_{n}") for n in window_names)
    for d in {"/dev/shm", _FALLBACK_DIR}:
        for path in _glob.glob(os.path.join(d, prefix[1:] + "*")):
            names.add("/" + os.path.basename(path))
    for n in names:
        if lib is not None:
            try:
                lib.bf_shm_unlink(n.encode())
            except Exception:
                pass
        for d in {"/dev/shm", _FALLBACK_DIR}:
            try:
                os.unlink(os.path.join(d, n[1:]))
            except OSError:
                pass
