"""Shared-memory mailbox veneer (shm_mailbox.cc) + pure-Python fallback.

Process-to-process transport for the asynchronous island window ops
(:mod:`bluefog_tpu.islands`) — the TPU-native sibling of the reference's
passive-target MPI RMA windows (``MPI_Win_create/Put/Accumulate/lock`` in
``bluefog/common/mpi_controller.cc`` [U]).  The native path is a seqlock
mailbox in POSIX shm (readers wait-free, writers per-slot spinlocked, an
atomic read+zero ``collect`` for mass-conserving push-sum).  The fallback
implements the same interface over an mmap'd file with ``fcntl.lockf``
byte-range locks — slower, zero native deps, used when the .so is absent.

Both paths share slot geometry: per window, ``nranks`` exposed slots (the
owner-published tensor ``win_get`` reads) followed by ``nranks × maxd``
mailbox slots (slot ``(d, k)`` = last deposit from d's k-th in-neighbor).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import re
import struct
import time
from typing import Optional, Tuple

import numpy as np

from bluefog_tpu.native import get_lib

_DTYPE_CODES = {np.dtype(np.float32): 1, np.dtype(np.float64): 2}

# ---------------------------------------------------------------------------
# protocol specification (model-checked)
# ---------------------------------------------------------------------------
#
# The seqlock step orders below are the ground truth the static verifier's
# exhaustive interleaving model (bluefog_tpu/analysis/seqlock_model.py)
# mirrors; the model asserts its generated programs match these tuples, so
# a protocol change in shm_mailbox.cc must update BOTH this spec and the
# model — the checker cannot silently drift from the implementation.

#: slot_write() in shm_mailbox.cc: spinlock, seq -> odd, mutate payload,
#: seq -> even (release), unlock.  The odd phase is what makes concurrent
#: plain readers retry instead of copying a half-written payload.
SEQLOCK_WRITER_STEPS = (
    "acquire_lock",
    "seq_to_odd",
    "mutate_payload",
    "seq_to_even",
    "release_lock",
)

#: slot_read() in shm_mailbox.cc: wait-free w.r.t. writers — no lock;
#: retry until the same even seq brackets the whole copy.
SEQLOCK_READER_STEPS = (
    "read_seq_before_retry_if_odd",
    "copy_payload",
    "read_seq_after_retry_if_changed",
)

#: bf_shm_win_read(collect=1): the read AND the zero happen inside ONE
#: slot_write critical section — the push-sum mass-conservation primitive
#: (a deposit can never land between the read and the zero).
COLLECT_IS_ATOMIC = True

#: bf_shm_job_barrier(): sense-reversing — the last arriver must reset
#: ``arrived`` BEFORE bumping ``generation``; the opposite order loses the
#: arrival of a rank that races into the next episode (model-checked
#: lost-wakeup).
BARRIER_RESET_BEFORE_RELEASE = True


def seg_name(job: str, suffix: str) -> str:
    """Sanitized POSIX shm object name (leading slash, [A-Za-z0-9_.-])."""
    raw = f"bf_{job}_{suffix}"
    return "/" + re.sub(r"[^A-Za-z0-9_.-]", "_", raw)[:250]


def _as_contiguous(array, dtype) -> np.ndarray:
    a = np.asarray(array, dtype=dtype)
    return np.ascontiguousarray(a)


# ---------------------------------------------------------------------------
# native path
# ---------------------------------------------------------------------------


class NativeShmJob:
    """Job-scope segment: sense-reversing barrier + per-rank mutexes."""

    def __init__(self, job: str, rank: int, nranks: int):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._name = seg_name(job, "job")
        self._h = lib.bf_shm_job_create(self._name.encode(), rank, nranks)
        if not self._h:
            raise RuntimeError(f"could not create shm job segment {self._name}")

    def barrier(self) -> None:
        self._lib.bf_shm_job_barrier(self._h)

    def mutex_acquire(self, rank: int) -> None:
        self._lib.bf_shm_job_mutex_acquire(self._h, int(rank))

    def mutex_release(self, rank: int) -> None:
        self._lib.bf_shm_job_mutex_release(self._h, int(rank))

    def close(self, unlink: bool = False) -> None:
        if self._h:
            self._lib.bf_shm_job_destroy(self._h, 1 if unlink else 0)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeShmWindow:
    """One named window: exposed slots + per-in-neighbor mailbox slots."""

    def __init__(self, job: str, name: str, rank: int, nranks: int,
                 maxd: int, shape: Tuple[int, ...], dtype):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.rank = rank
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.nbytes = int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize
        self._code = _DTYPE_CODES.get(self.dtype, 0)
        self._name = seg_name(job, f"win_{name}")
        self._h = lib.bf_shm_win_create(
            self._name.encode(), rank, nranks, max(maxd, 1), self.nbytes,
            self._code,
        )
        if not self._h:
            raise RuntimeError(f"could not create shm window {self._name}")

    def write(self, dst: int, slot: int, array, p: float = 1.0,
              accumulate: bool = False, writer=None) -> None:
        del writer  # single-transport: routing is the RoutedWindow's job
        if accumulate and self._code == 0:
            raise TypeError(f"accumulate unsupported for dtype {self.dtype}")
        a = _as_contiguous(array, self.dtype)
        if a.nbytes != self.nbytes:
            raise ValueError(
                f"win_put payload has {a.nbytes} bytes but window "
                f"{self._name} expects {self.nbytes} (shape {self.shape})"
            )
        self._lib.bf_shm_win_write(
            self._h, int(dst), int(slot),
            a.ctypes.data_as(ctypes.c_void_p), float(p),
            1 if accumulate else 0,
        )

    def read(self, slot: int, collect: bool = False, src=None):
        del src
        out = np.empty(self.shape, dtype=self.dtype)
        p = ctypes.c_double(0.0)
        version = self._lib.bf_shm_win_read(
            self._h, int(slot), out.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(p), 1 if collect else 0,
        )
        return out, p.value, int(version)

    def read_version(self, slot: int, src=None) -> int:
        del src
        # metadata-only probe: NULL out pointer skips the payload copy
        return int(self._lib.bf_shm_win_read(self._h, int(slot), None, None, 0))

    def reset(self, slot: int, src=None) -> None:
        del src
        self._lib.bf_shm_win_reset(self._h, int(slot))

    def expose(self, array, p: float = 1.0) -> None:
        a = _as_contiguous(array, self.dtype)
        if a.nbytes != self.nbytes:
            raise ValueError(
                f"expose payload has {a.nbytes} bytes but window "
                f"{self._name} expects {self.nbytes} (shape {self.shape})"
            )
        self._lib.bf_shm_win_expose(
            self._h, a.ctypes.data_as(ctypes.c_void_p), float(p)
        )

    def read_exposed(self, src: int):
        out = np.empty(self.shape, dtype=self.dtype)
        p = ctypes.c_double(0.0)
        version = self._lib.bf_shm_win_read_exposed(
            self._h, int(src), out.ctypes.data_as(ctypes.c_void_p),
            ctypes.byref(p),
        )
        return out, p.value, int(version)

    def close(self, unlink: bool = False) -> None:
        if self._h:
            self._lib.bf_shm_win_destroy(self._h, 1 if unlink else 0)
            self._h = None

    def unlink_segments(self) -> None:
        """Name-based unlink by the designated (segment-rank-0) rank —
        the collective win_free teardown (call after close, between
        barriers)."""
        if self.rank == 0:
            _unlink_name(self._name)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _unlink_name(name: str) -> None:
    lib = get_lib()
    if lib is not None:
        try:
            lib.bf_shm_unlink(name.encode())
        except Exception:
            pass
    for d in {"/dev/shm", _FALLBACK_DIR}:
        try:
            os.unlink(os.path.join(d, name[1:]))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# pure-Python fallback (mmap + fcntl byte-range locks)
# ---------------------------------------------------------------------------

_FALLBACK_DIR = os.environ.get("BLUEFOG_SHM_DIR", "/dev/shm")


class _FallbackSegment:
    """mmap'd file; every slot guarded by an exclusive lockf range.

    Creation needs no handshake: all ranks ftruncate to the same size
    (idempotent, zero-fills) and zeros are a valid initial state.
    """

    def __init__(self, path: str, nbytes: int):
        self.path = path
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        os.ftruncate(self._fd, nbytes)
        self._mm = mmap.mmap(self._fd, nbytes)

    def lock(self, start: int, length: int):
        import fcntl

        fcntl.lockf(self._fd, fcntl.LOCK_EX, length, start)

    def unlock(self, start: int, length: int):
        import fcntl

        fcntl.lockf(self._fd, fcntl.LOCK_UN, length, start)

    def close(self, unlink: bool = False):
        if self._mm is not None:
            self._mm.close()
            os.close(self._fd)
            self._mm = None
            if unlink:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass


class FallbackShmJob:
    """Barrier + mutexes over lockf.  Layout: [arrived u64][generation u64]
    then one lock byte per rank (the mutex is the held lockf range)."""

    def __init__(self, job: str, rank: int, nranks: int):
        self.nranks = nranks
        path = os.path.join(_FALLBACK_DIR, seg_name(job, "job")[1:])
        self._seg = _FallbackSegment(path, 16 + nranks)

    def barrier(self) -> None:
        mm = self._seg._mm
        self._seg.lock(0, 16)
        gen = struct.unpack_from("<Q", mm, 8)[0]
        arrived = struct.unpack_from("<Q", mm, 0)[0] + 1
        if arrived == self.nranks:
            struct.pack_into("<Q", mm, 0, 0)
            struct.pack_into("<Q", mm, 8, gen + 1)
            self._seg.unlock(0, 16)
            return
        struct.pack_into("<Q", mm, 0, arrived)
        self._seg.unlock(0, 16)
        while True:
            self._seg.lock(8, 8)
            cur = struct.unpack_from("<Q", mm, 8)[0]
            self._seg.unlock(8, 8)
            if cur != gen:
                return
            time.sleep(0.0002)

    def mutex_acquire(self, rank: int) -> None:
        self._seg.lock(16 + rank, 1)

    def mutex_release(self, rank: int) -> None:
        self._seg.unlock(16 + rank, 1)

    def close(self, unlink: bool = False) -> None:
        self._seg.close(unlink)


class FallbackShmWindow:
    """Same slot geometry as the native window; every op takes the slot's
    exclusive lock (no seqlock — simplicity over read throughput)."""

    _HDR = 16  # per-slot: [version u64][p f64]

    def __init__(self, job: str, name: str, rank: int, nranks: int,
                 maxd: int, shape: Tuple[int, ...], dtype):
        self.rank = rank
        self.nranks = nranks
        self.maxd = max(maxd, 1)
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.nbytes = int(np.prod(self.shape, dtype=np.int64)) * self.dtype.itemsize
        self._stride = self._HDR + ((self.nbytes + 63) // 64) * 64
        nslots = nranks + nranks * self.maxd
        path = os.path.join(_FALLBACK_DIR, seg_name(job, f"win_{name}")[1:])
        self._seg = _FallbackSegment(path, nslots * self._stride)

    def _off(self, index: int) -> int:
        return index * self._stride

    def _mail_index(self, d: int, k: int) -> int:
        return self.nranks + d * self.maxd + k

    def _read_slot(self, off: int):
        mm = self._seg._mm
        version, p = struct.unpack_from("<Qd", mm, off)
        a = np.frombuffer(
            mm, dtype=self.dtype,
            count=self.nbytes // self.dtype.itemsize,
            offset=off + self._HDR,
        ).reshape(self.shape).copy()
        return a, p, version

    def _locked(self, index: int):
        off = self._off(index)
        self._seg.lock(off, self._stride)
        return off

    def _unlock(self, index: int):
        self._seg.unlock(self._off(index), self._stride)

    def write(self, dst: int, slot: int, array, p: float = 1.0,
              accumulate: bool = False, writer=None) -> None:
        del writer
        if accumulate and self.dtype not in _DTYPE_CODES:
            # same contract as the native path: accumulate needs a float
            # payload (raw dtypes are opaque bytes)
            raise TypeError(f"accumulate unsupported for dtype {self.dtype}")
        a = _as_contiguous(array, self.dtype)
        idx = self._mail_index(dst, slot)
        off = self._locked(idx)
        try:
            mm = self._seg._mm
            version, cur_p = struct.unpack_from("<Qd", mm, off)
            if accumulate:
                cur, _, _ = self._read_slot(off)
                a = cur + a
                p = cur_p + p
            mm[off + self._HDR:off + self._HDR + self.nbytes] = a.tobytes()
            struct.pack_into("<Qd", mm, off, version + 1, p)
        finally:
            self._unlock(idx)

    def read(self, slot: int, collect: bool = False, src=None):
        del src
        idx = self._mail_index(self.rank, slot)
        off = self._locked(idx)
        try:
            a, p, version = self._read_slot(off)
            if collect:
                mm = self._seg._mm
                mm[off + self._HDR:off + self._HDR + self.nbytes] = (
                    b"\x00" * self.nbytes
                )
                struct.pack_into("<Qd", mm, off, version, 0.0)
        finally:
            self._unlock(idx)
        return a, p, version

    def read_version(self, slot: int, src=None) -> int:
        del src
        idx = self._mail_index(self.rank, slot)
        off = self._locked(idx)
        try:
            return int(struct.unpack_from("<Q", self._seg._mm, off)[0])
        finally:
            self._unlock(idx)

    def reset(self, slot: int, src=None) -> None:
        del src
        idx = self._mail_index(self.rank, slot)
        off = self._locked(idx)
        try:
            mm = self._seg._mm
            version = struct.unpack_from("<Q", mm, off)[0]
            mm[off + self._HDR:off + self._HDR + self.nbytes] = (
                b"\x00" * self.nbytes
            )
            struct.pack_into("<Qd", mm, off, version, 0.0)
        finally:
            self._unlock(idx)

    def unlink_segments(self) -> None:
        if self.rank == 0:
            try:
                os.unlink(self._seg.path)
            except OSError:
                pass

    def expose(self, array, p: float = 1.0) -> None:
        a = _as_contiguous(array, self.dtype)
        off = self._locked(self.rank)
        try:
            mm = self._seg._mm
            version = struct.unpack_from("<Q", mm, off)[0]
            mm[off + self._HDR:off + self._HDR + self.nbytes] = a.tobytes()
            struct.pack_into("<Qd", mm, off, version + 1, p)
        finally:
            self._unlock(self.rank)

    def read_exposed(self, src: int):
        off = self._locked(src)
        try:
            return self._read_slot(off)
        finally:
            self._unlock(src)

    def close(self, unlink: bool = False) -> None:
        self._seg.close(unlink)


# ---------------------------------------------------------------------------
# factory
# ---------------------------------------------------------------------------


def make_shm_job(job: str, rank: int, nranks: int):
    """Shared-memory job segment: native when the .so is available, else
    the lockf fallback (no transport dispatch — used directly by the
    routed transport's intra-host leg)."""
    if get_lib() is not None and not _force_fallback():
        return NativeShmJob(job, rank, nranks)
    return FallbackShmJob(job, rank, nranks)


def make_shm_window(job: str, name: str, rank: int, nranks: int, maxd: int,
                    shape, dtype):
    if get_lib() is not None and not _force_fallback():
        return NativeShmWindow(job, name, rank, nranks, maxd, shape, dtype)
    return FallbackShmWindow(job, name, rank, nranks, maxd, shape, dtype)


def make_job(job: str, rank: int, nranks: int):
    """Transport factory: hierarchical (shm intra-host + TCP inter-host)
    when a hostmap is configured, else TCP (cross-host/DCN) when selected,
    else pure shared memory."""
    hostmap = os.environ.get("BLUEFOG_ISLAND_HOSTMAP")
    if hostmap:
        from bluefog_tpu.native.routed_transport import RoutedJob, parse_hostmap

        hosts = parse_hostmap(hostmap, nranks)
        return RoutedJob(job, rank, nranks, hosts, _derived_coord(job))
    coord = _tcp_coord(job)
    if coord is not None:
        from bluefog_tpu.native.tcp_transport import TcpShmJob

        return TcpShmJob(job, rank, nranks, coord)
    return make_shm_job(job, rank, nranks)


def make_window(job: str, name: str, rank: int, nranks: int, maxd: int,
                shape, dtype):
    hostmap = os.environ.get("BLUEFOG_ISLAND_HOSTMAP")
    if hostmap:
        from bluefog_tpu.native.routed_transport import (
            RoutedWindow, parse_hostmap,
        )

        hosts = parse_hostmap(hostmap, nranks)
        return RoutedWindow(job, name, rank, nranks, maxd, shape, dtype,
                            hosts, _derived_coord(job))
    coord = _tcp_coord(job)
    if coord is not None:
        from bluefog_tpu.native.tcp_transport import TcpShmWindow

        return TcpShmWindow(job, name, rank, nranks, maxd, shape, dtype, coord)
    return make_shm_window(job, name, rank, nranks, maxd, shape, dtype)


def _force_fallback() -> bool:
    return os.environ.get("BLUEFOG_SHM_FALLBACK", "0") == "1"


def _derived_coord(job: str) -> str:
    """Explicit ``BLUEFOG_ISLAND_COORD`` or a job-deterministic localhost
    port, below the Linux ephemeral range (32768+) so a transient client
    socket never occupies it."""
    coord = os.environ.get("BLUEFOG_ISLAND_COORD")
    if coord:
        return coord
    import zlib

    port = 10000 + zlib.crc32(job.encode()) % 20000
    return f"127.0.0.1:{port}"


def island_transport() -> str:
    """The transport the island runtime will actually use for the current
    environment, mirroring ``make_job``/``make_window`` dispatch exactly:
    "routed" (hierarchical shm-intra/TCP-inter) when
    ``BLUEFOG_ISLAND_HOSTMAP`` is set, else "tcp" when
    ``BLUEFOG_ISLAND_COORD`` or ``BLUEFOG_ISLAND_TRANSPORT=tcp`` selects
    it, else "shm".  The single source of truth — benchmarks/labels must
    query this rather than re-reading the env vars."""
    if os.environ.get("BLUEFOG_ISLAND_HOSTMAP"):
        return "routed"
    if os.environ.get("BLUEFOG_ISLAND_COORD"):
        return "tcp"
    if os.environ.get("BLUEFOG_ISLAND_TRANSPORT", "").lower() == "tcp":
        return "tcp"
    return "shm"


def _tcp_coord(job: str) -> Optional[str]:
    """Coordinator address when the TCP (cross-host) transport is selected
    (see :func:`island_transport`): a job-deterministic localhost port for
    single-host testing, or derived from ``BLUEFOG_ISLAND_COORD``."""
    return _derived_coord(job) if island_transport() == "tcp" else None


def unlink_segment(job: str, suffix: str) -> None:
    """Best-effort unlink of one named segment (native object + fallback
    file); missing names are ignored."""
    _unlink_name(seg_name(job, suffix))


def unlink_all(job: str, window_names=()) -> None:
    """Best-effort cleanup of ALL of a job's segments (crashed-run hygiene).

    Globs ``/dev/shm`` (where shm_open objects appear as files on Linux) and
    the fallback dir for the job prefix, so window segments are reclaimed
    even when the caller no longer knows their names (a crashed run); the
    explicit ``window_names`` are unlinked too for non-Linux portability.
    """
    import glob as _glob

    lib = get_lib()
    prefix = seg_name(job, "")  # "/bf_<job>_"
    names = {seg_name(job, "job")}
    names.update(seg_name(job, f"win_{n}") for n in window_names)
    for d in {"/dev/shm", _FALLBACK_DIR}:
        for path in _glob.glob(os.path.join(d, prefix[1:] + "*")):
            names.add("/" + os.path.basename(path))
    for n in names:
        if lib is not None:
            try:
                lib.bf_shm_unlink(n.encode())
            except Exception:
                pass
        for d in {"/dev/shm", _FALLBACK_DIR}:
            try:
                os.unlink(os.path.join(d, n[1:]))
            except OSError:
                pass
