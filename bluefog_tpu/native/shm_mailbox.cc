// Shared-memory mailbox transport for asynchronous "island" window ops.
//
// TPU-native sibling of the reference's passive-target MPI RMA layer
// (MPI_Win_create / MPI_Put / MPI_Accumulate / MPI_Win_lock in
// bluefog/common/mpi_controller.cc and mpi_context.cc [U]; SURVEY.md §2.4,
// §3.4).  The single-controller emulation in bluefog_tpu/windows.py realizes
// the synchronous schedule of asynchronous algorithms; THIS module supplies
// the missing piece — true one-sided deposits between independently-stepping
// OS processes ("islands"), each of which owns its own JAX controller and
// device set.  A writer deposits into its dedicated slot at the destination
// with NO participation by the receiver, exactly the reference's window
// model: one registered buffer per in-neighbor per named window, so
// concurrent writers never collide.
//
// Memory layout of a window segment (POSIX shm, /dev/shm):
//
//   Header  { magic, nranks, maxd, nbytes, dtype, init_done, attached }
//   Exposed [nranks]        — each rank's currently-exposed tensor
//   Mail    [nranks][maxd]  — slot (d, k): last deposit from d's k-th
//                             in-neighbor (ascending rank order)
//
// Every slot is a small header + 64-byte-aligned payload:
//
//   Slot { lock, seq, version, p, payload[nbytes] }
//
// Concurrency protocol (the part MPI gives the reference for free):
//   - writers (put / accumulate / reset / collect) take the slot spinlock,
//     then bump `seq` to odd, mutate, bump to even (seqlock publish);
//   - plain readers never lock: they spin on `seq` until they observe the
//     same even value before and after the copy — wait-free w.r.t. writers;
//   - `collect` (read + zero in one critical section) is the atomic drain
//     that makes asynchronous push-sum mass-conserving: a deposit can never
//     land between the read and the zero.
//
// A tiny per-job segment provides a sense-reversing barrier (init/teardown
// and tests only — the async hot loop never barriers) and per-rank mutexes
// implementing a REAL bf.win_mutex for island mode (the bulk-synchronous
// emulation's no-op shim is justified only when there are no concurrent
// writers; islands have them).
//
// C++17, no external deps; C-linkage ABI consumed by ctypes
// (bluefog_tpu/native/shm_native.py).

#include <atomic>
#include <cerrno>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x42464d41494c4258ull;  // "BFMAILBX"

inline int64_t align_up(int64_t v, int64_t a) { return (v + a - 1) / a * a; }

inline void cpu_relax() { sched_yield(); }

// ---------------------------------------------------------------------------
// shm segment plumbing
// ---------------------------------------------------------------------------

struct Segment {
  void* base = nullptr;
  int64_t bytes = 0;
  char name[256];
};

// Open-or-create a named segment of exactly `bytes`.  The winner of the
// O_EXCL race sizes + zeroes it and must later publish readiness itself via
// publish_init() — AFTER writing any header fields — so no attacher ever
// observes a half-initialized header; losers attach and spin on the flag at
// offset `init_off`.
bool segment_open(Segment* seg, const char* name, int64_t bytes,
                  int64_t init_off, bool* creator_out) {
  std::snprintf(seg->name, sizeof(seg->name), "%s", name);
  bool creator = false;
  int fd = shm_open(name, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd >= 0) {
    creator = true;
    if (ftruncate(fd, bytes) != 0) {
      close(fd);
      shm_unlink(name);
      return false;
    }
  } else {
    if (errno != EEXIST) return false;
    // attach; the creator may still be mid-ftruncate, so wait for full size
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return false;
    struct stat st;
    for (int spin = 0; ; ++spin) {
      if (fstat(fd, &st) != 0) { close(fd); return false; }
      if (st.st_size >= bytes) break;
      if (spin > 2000000) { close(fd); return false; }
      cpu_relax();
    }
  }
  void* base = mmap(nullptr, static_cast<size_t>(bytes),
                    PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return false;
  seg->base = base;
  seg->bytes = bytes;
  auto* flag = reinterpret_cast<std::atomic<uint64_t>*>(
      static_cast<char*>(base) + init_off);
  if (!creator) {
    for (int spin = 0; flag->load(std::memory_order_acquire) != 1; ++spin) {
      if (spin > 2000000) { munmap(base, bytes); return false; }
      cpu_relax();
    }
  }
  // creator: mapping of a fresh shm object is zero-filled; caller fills the
  // header then calls publish_init
  if (creator_out) *creator_out = creator;
  return true;
}

void publish_init(void* base, int64_t init_off) {
  reinterpret_cast<std::atomic<uint64_t>*>(static_cast<char*>(base) +
                                           init_off)
      ->store(1, std::memory_order_release);
}

void segment_close(Segment* seg, bool unlink_seg) {
  if (seg->base) munmap(seg->base, static_cast<size_t>(seg->bytes));
  if (unlink_seg) shm_unlink(seg->name);
  seg->base = nullptr;
}

// ---------------------------------------------------------------------------
// job segment: barrier + per-rank mutexes
// ---------------------------------------------------------------------------

struct JobHeader {
  std::atomic<uint64_t> init_done;
  int64_t nranks;
  std::atomic<uint64_t> arrived;
  std::atomic<uint64_t> generation;
  // nranks mutexes follow (one cache line each)
};

struct JobMutex {
  std::atomic<uint32_t> locked;
  char pad[60];
};

struct Job {
  Segment seg;
  int64_t rank = 0;
  int64_t nranks = 0;
  JobHeader* hdr() { return static_cast<JobHeader*>(seg.base); }
  JobMutex* mutexes() {
    return reinterpret_cast<JobMutex*>(static_cast<char*>(seg.base) +
                                       align_up(sizeof(JobHeader), 64));
  }
};

// ---------------------------------------------------------------------------
// window segment
// ---------------------------------------------------------------------------

struct WinHeader {
  uint64_t magic;
  std::atomic<uint64_t> init_done;
  int64_t nranks;
  int64_t maxd;
  int64_t nbytes;
  int32_t dtype;  // 0 raw bytes, 1 float32, 2 float64
};

struct SlotHeader {
  std::atomic<uint32_t> lock;  // writer spinlock
  uint32_t pad0;
  std::atomic<uint64_t> seq;   // seqlock: odd while a writer mutates
  uint64_t version;            // deposit count
  double p;                    // push-sum associated scalar
};

struct Window {
  Segment seg;
  int64_t rank = 0;
  int64_t nranks = 0;
  int64_t maxd = 0;
  int64_t nbytes = 0;
  int32_t dtype = 0;
  int64_t slot_stride = 0;
  int64_t slots_off = 0;  // exposed slots start; mail follows

  char* slot_at(int64_t index) {
    return static_cast<char*>(seg.base) + slots_off + index * slot_stride;
  }
  // exposed slot of rank r
  char* exposed(int64_t r) { return slot_at(r); }
  // mailbox slot (dst d, in-neighbor position k)
  char* mail(int64_t d, int64_t k) {
    return slot_at(nranks + d * maxd + k);
  }
};

inline char* payload_of(char* slot) {
  return slot + align_up(sizeof(SlotHeader), 64);
}

void slot_lock(SlotHeader* s) {
  uint32_t expected = 0;
  while (!s->lock.compare_exchange_weak(expected, 1,
                                        std::memory_order_acquire,
                                        std::memory_order_relaxed)) {
    expected = 0;
    cpu_relax();
  }
}

void slot_unlock(SlotHeader* s) {
  s->lock.store(0, std::memory_order_release);
}

// Mutate a slot under lock + seqlock publish.
template <typename F>
void slot_write(char* slot, F&& mutate) {
  auto* s = reinterpret_cast<SlotHeader*>(slot);
  slot_lock(s);
  uint64_t seq = s->seq.load(std::memory_order_relaxed);
  s->seq.store(seq + 1, std::memory_order_relaxed);  // odd: in progress
  // full fence: the payload stores must not become visible before the odd
  // seq store (store-store barrier — smp_wmb in the kernel's seqlock; a
  // release fence would NOT order the later plain stores on ARM)
  std::atomic_thread_fence(std::memory_order_seq_cst);
  mutate(s, payload_of(slot));
  // release store: all payload stores visible before seq turns even
  std::atomic_thread_fence(std::memory_order_release);
  s->seq.store(seq + 2, std::memory_order_release);
  slot_unlock(s);
}

// Seqlock read (no lock taken): retry until a stable even seq brackets the
// copy.  Returns the observed version.
int64_t slot_read(char* slot, void* out, int64_t nbytes, double* p_out) {
  auto* s = reinterpret_cast<SlotHeader*>(slot);
  for (;;) {
    uint64_t before = s->seq.load(std::memory_order_acquire);
    if (before & 1) { cpu_relax(); continue; }
    uint64_t version = s->version;
    double p = s->p;
    if (out) std::memcpy(out, payload_of(slot), static_cast<size_t>(nbytes));
    std::atomic_thread_fence(std::memory_order_acquire);
    uint64_t after = s->seq.load(std::memory_order_acquire);
    if (before == after) {
      if (p_out) *p_out = p;
      return static_cast<int64_t>(version);
    }
    cpu_relax();
  }
}

void accumulate_payload(char* dst, const void* src, int64_t nbytes,
                        int32_t dtype) {
  if (dtype == 1) {
    auto* d = reinterpret_cast<float*>(dst);
    auto* s = static_cast<const float*>(src);
    int64_t n = nbytes / static_cast<int64_t>(sizeof(float));
    for (int64_t i = 0; i < n; ++i) d[i] += s[i];
  } else if (dtype == 2) {
    auto* d = reinterpret_cast<double*>(dst);
    auto* s = static_cast<const double*>(src);
    int64_t n = nbytes / static_cast<int64_t>(sizeof(double));
    for (int64_t i = 0; i < n; ++i) d[i] += s[i];
  } else {
    std::memcpy(dst, src, static_cast<size_t>(nbytes));  // raw: overwrite
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

void* bf_shm_job_create(const char* name, int64_t rank, int64_t nranks) {
  auto* job = new Job;
  job->rank = rank;
  job->nranks = nranks;
  int64_t bytes = align_up(sizeof(JobHeader), 64) +
                  nranks * static_cast<int64_t>(sizeof(JobMutex));
  bool creator = false;
  if (!segment_open(&job->seg, name, bytes,
                    offsetof(JobHeader, init_done), &creator)) {
    delete job;
    return nullptr;
  }
  if (creator) {
    job->hdr()->nranks = nranks;
    publish_init(job->seg.base, offsetof(JobHeader, init_done));
  }
  return job;
}

void bf_shm_job_barrier(void* h) {
  auto* job = static_cast<Job*>(h);
  auto* hdr = job->hdr();
  uint64_t gen = hdr->generation.load(std::memory_order_acquire);
  uint64_t arrived = hdr->arrived.fetch_add(1, std::memory_order_acq_rel) + 1;
  if (arrived == static_cast<uint64_t>(job->nranks)) {
    hdr->arrived.store(0, std::memory_order_relaxed);
    hdr->generation.fetch_add(1, std::memory_order_acq_rel);
  } else {
    while (hdr->generation.load(std::memory_order_acquire) == gen) cpu_relax();
  }
}

void bf_shm_job_mutex_acquire(void* h, int64_t target_rank) {
  auto* job = static_cast<Job*>(h);
  auto& m = job->mutexes()[target_rank].locked;
  uint32_t expected = 0;
  while (!m.compare_exchange_weak(expected, 1, std::memory_order_acquire,
                                  std::memory_order_relaxed)) {
    expected = 0;
    cpu_relax();
  }
}

void bf_shm_job_mutex_release(void* h, int64_t target_rank) {
  auto* job = static_cast<Job*>(h);
  job->mutexes()[target_rank].locked.store(0, std::memory_order_release);
}

void bf_shm_job_destroy(void* h, int32_t unlink_seg) {
  auto* job = static_cast<Job*>(h);
  segment_close(&job->seg, unlink_seg != 0);
  delete job;
}

void* bf_shm_win_create(const char* name, int64_t rank, int64_t nranks,
                        int64_t maxd, int64_t nbytes, int32_t dtype) {
  auto* win = new Window;
  win->rank = rank;
  win->nranks = nranks;
  win->maxd = maxd < 1 ? 1 : maxd;
  win->nbytes = nbytes;
  win->dtype = dtype;
  win->slot_stride =
      align_up(sizeof(SlotHeader), 64) + align_up(nbytes, 64);
  win->slots_off = align_up(sizeof(WinHeader), 64);
  int64_t nslots = nranks + nranks * win->maxd;
  int64_t bytes = win->slots_off + nslots * win->slot_stride;
  bool creator = false;
  if (!segment_open(&win->seg, name, bytes,
                    offsetof(WinHeader, init_done), &creator)) {
    delete win;
    return nullptr;
  }
  auto* hdr = static_cast<WinHeader*>(win->seg.base);
  if (creator) {
    hdr->magic = kMagic;
    hdr->nranks = nranks;
    hdr->maxd = win->maxd;
    hdr->nbytes = nbytes;
    hdr->dtype = dtype;
    publish_init(win->seg.base, offsetof(WinHeader, init_done));
  } else if (hdr->magic != kMagic || hdr->nranks != nranks ||
             hdr->maxd != win->maxd || hdr->nbytes != nbytes ||
             hdr->dtype != dtype) {
    segment_close(&win->seg, false);
    delete win;
    return nullptr;
  }
  return win;
}

// Deposit into (dst, slot).  mode 0 = put (overwrite), 1 = accumulate.
// p rides along (overwritten or accumulated to match).
void bf_shm_win_write(void* h, int64_t dst, int64_t slot, const void* data,
                      double p, int32_t mode) {
  auto* win = static_cast<Window*>(h);
  slot_write(win->mail(dst, slot), [&](SlotHeader* s, char* payload) {
    if (mode == 1) {
      accumulate_payload(payload, data, win->nbytes, win->dtype);
      s->p += p;
    } else {
      std::memcpy(payload, data, static_cast<size_t>(win->nbytes));
      s->p = p;
    }
    s->version += 1;
  });
}

// Read my own mailbox slot `slot`.  collect != 0 drains it atomically
// (read + zero in one critical section — the push-sum mass-conservation
// primitive).  Returns the deposit count observed.
int64_t bf_shm_win_read(void* h, int64_t slot, void* out, double* p,
                        int32_t collect) {
  auto* win = static_cast<Window*>(h);
  char* sl = win->mail(win->rank, slot);
  if (!collect) return slot_read(sl, out, win->nbytes, p);
  int64_t version = 0;
  slot_write(sl, [&](SlotHeader* s, char* payload) {
    if (out) std::memcpy(out, payload, static_cast<size_t>(win->nbytes));
    if (p) *p = s->p;
    version = static_cast<int64_t>(s->version);
    std::memset(payload, 0, static_cast<size_t>(win->nbytes));
    s->p = 0.0;
  });
  return version;
}

// Overwrite a mailbox slot's payload+p without touching version — the
// owner-side reset (reference win_update(reset=True) zeroing its buffers).
void bf_shm_win_reset(void* h, int64_t slot) {
  auto* win = static_cast<Window*>(h);
  slot_write(win->mail(win->rank, slot), [&](SlotHeader* s, char* payload) {
    std::memset(payload, 0, static_cast<size_t>(win->nbytes));
    s->p = 0.0;
  });
}

// Publish my exposed tensor (what win_get by a neighbor observes).
void bf_shm_win_expose(void* h, const void* data, double p) {
  auto* win = static_cast<Window*>(h);
  slot_write(win->exposed(win->rank), [&](SlotHeader* s, char* payload) {
    std::memcpy(payload, data, static_cast<size_t>(win->nbytes));
    s->p = p;
    s->version += 1;
  });
}

// One-sided read of any rank's exposed tensor (the MPI_Get path).
int64_t bf_shm_win_read_exposed(void* h, int64_t src, void* out, double* p) {
  auto* win = static_cast<Window*>(h);
  return slot_read(win->exposed(src), out, win->nbytes, p);
}

void bf_shm_win_destroy(void* h, int32_t unlink_seg) {
  auto* win = static_cast<Window*>(h);
  segment_close(&win->seg, unlink_seg != 0);
  delete win;
}

void bf_shm_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
