// Shared-memory mailbox transport for asynchronous "island" window ops.
//
// TPU-native sibling of the reference's passive-target MPI RMA layer
// (MPI_Win_create / MPI_Put / MPI_Accumulate / MPI_Win_lock in
// bluefog/common/mpi_controller.cc and mpi_context.cc [U]; SURVEY.md §2.4,
// §3.4).  The single-controller emulation in bluefog_tpu/windows.py realizes
// the synchronous schedule of asynchronous algorithms; THIS module supplies
// the missing piece — true one-sided deposits between independently-stepping
// OS processes ("islands"), each of which owns its own JAX controller and
// device set.  A writer deposits into its dedicated slot at the destination
// with NO participation by the receiver, exactly the reference's window
// model: one registered buffer per in-neighbor per named window, so
// concurrent writers never collide.
//
// Memory layout of a window segment (POSIX shm, /dev/shm):
//
//   Header  { magic, nranks, maxd, nbytes, dtype, chunk_bytes, nchunks, .. }
//   Exposed [nranks]        — each rank's currently-exposed tensor
//   Mail    [nranks][maxd]  — slot (d, k): last deposit from d's k-th
//                             in-neighbor (ascending rank order)
//
// Every slot is a small header + a per-chunk seqlock array + 64-byte-aligned
// payload:
//
//   Slot { lock, wseq, version, drained, p, chunk_seq[nchunks],
//          payload[nbytes] }
//
// Chunked protocol (v2) — the chunk-ring transport:
//   - the payload is divided into fixed-size chunks (``chunk_bytes``), each
//     guarded by its OWN seqlock ``chunk_seq[c]``; a writer commits chunks
//     in ascending order (odd → mutate → release-fence → even), so a
//     pipelined consumer can follow the commit frontier: observing chunk c
//     committed at episode E implies every chunk < c is also at episode E
//     ("no reordered chunk commit" — model-checked);
//   - ``wseq`` is the slot-level seqlock bracketing whole-payload atomicity
//     for plain readers (same odd/even discipline as v1, now wrapping the
//     per-chunk commits);
//   - ``drained`` records the ``version`` at the last collect/reset.  When
//     ``drained == version`` the slot is LOGICALLY zero without any memset:
//     collect becomes a single copy-out pass + an O(1) marker store
//     (v1 paid a third full zeroing pass here), reset is O(1), and an
//     accumulate into a drained slot degrades to a plain scaled copy —
//     mass conservation is preserved because drained/version only move
//     under the slot lock (model-checked: no lost deposit);
//   - deposits take a ``scale`` factor applied in the copy loop (a put of
//     ``w * x`` is one pass, not a temporary + two);
//   - ``bf_shm_win_combine`` fuses the reader side the same way:
//     ``acc += weight * payload`` in one pass under the slot lock, so the
//     island win_update's weighted combine never materializes the payload;
//   - ``bf_shm_win_probe`` is the pipelined self-edge: it streams the
//     payload through a bounded ring of ``ring_depth`` chunk slots with the
//     full per-chunk seqlock protocol, writer deposit and reader drain
//     interleaved per chunk.  The ring stays cache-resident, so the
//     measured protocol ceiling approaches the single-pass memcpy bound
//     instead of v1's 1/3-of-memcpy three-pass floor.
//
// A tiny per-job segment provides a sense-reversing barrier (init/teardown
// and tests only — the async hot loop never barriers) and per-rank mutexes
// implementing a REAL bf.win_mutex for island mode.
//
// C++17, no external deps; C-linkage ABI consumed by ctypes
// (bluefog_tpu/native/shm_native.py).  ``bf_shm_abi_version`` returns 2;
// its absence from a stale .so triggers the loader's rebuild path.

#include <atomic>
#include <cerrno>
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x42464d41494c4232ull;  // "BFMAILB2"
constexpr int64_t kDefaultChunkBytes = 64 * 1024;

inline int64_t align_up(int64_t v, int64_t a) { return (v + a - 1) / a * a; }

inline void cpu_relax() { sched_yield(); }

// ---------------------------------------------------------------------------
// shm segment plumbing
// ---------------------------------------------------------------------------

struct Segment {
  void* base = nullptr;
  int64_t bytes = 0;
  char name[256];
};

// Open-or-create a named segment of exactly `bytes`.  The winner of the
// O_EXCL race sizes + zeroes it and must later publish readiness itself via
// publish_init() — AFTER writing any header fields — so no attacher ever
// observes a half-initialized header; losers attach and spin on the flag at
// offset `init_off`.
bool segment_open(Segment* seg, const char* name, int64_t bytes,
                  int64_t init_off, bool* creator_out) {
  std::snprintf(seg->name, sizeof(seg->name), "%s", name);
  bool creator = false;
  int fd = shm_open(name, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd >= 0) {
    creator = true;
    if (ftruncate(fd, bytes) != 0) {
      close(fd);
      shm_unlink(name);
      return false;
    }
  } else {
    if (errno != EEXIST) return false;
    // attach; the creator may still be mid-ftruncate, so wait for full size
    fd = shm_open(name, O_RDWR, 0600);
    if (fd < 0) return false;
    struct stat st;
    for (int spin = 0; ; ++spin) {
      if (fstat(fd, &st) != 0) { close(fd); return false; }
      if (st.st_size >= bytes) break;
      if (spin > 2000000) { close(fd); return false; }
      cpu_relax();
    }
  }
  void* base = mmap(nullptr, static_cast<size_t>(bytes),
                    PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return false;
  seg->base = base;
  seg->bytes = bytes;
  auto* flag = reinterpret_cast<std::atomic<uint64_t>*>(
      static_cast<char*>(base) + init_off);
  if (!creator) {
    for (int spin = 0; flag->load(std::memory_order_acquire) != 1; ++spin) {
      if (spin > 2000000) { munmap(base, bytes); return false; }
      cpu_relax();
    }
  }
  // creator: mapping of a fresh shm object is zero-filled; caller fills the
  // header then calls publish_init
  if (creator_out) *creator_out = creator;
  return true;
}

void publish_init(void* base, int64_t init_off) {
  reinterpret_cast<std::atomic<uint64_t>*>(static_cast<char*>(base) +
                                           init_off)
      ->store(1, std::memory_order_release);
}

void segment_close(Segment* seg, bool unlink_seg) {
  if (seg->base) munmap(seg->base, static_cast<size_t>(seg->bytes));
  if (unlink_seg) shm_unlink(seg->name);
  seg->base = nullptr;
}

// ---------------------------------------------------------------------------
// job segment: barrier + per-rank mutexes
// ---------------------------------------------------------------------------

struct JobHeader {
  std::atomic<uint64_t> init_done;
  int64_t nranks;
  std::atomic<uint64_t> arrived;
  std::atomic<uint64_t> generation;
  // nranks mutexes follow (one cache line each)
};

struct JobMutex {
  std::atomic<uint32_t> locked;
  char pad[60];
};

// Per-rank liveness word (one cache line each, after the mutex array).
// Each rank heartbeats its own word with a caller-supplied epoch stamp
// (CLOCK_MONOTONIC milliseconds — system-wide on Linux, so peers can
// compare a stamp against their own clock); a detector reads peers'
// words and declares any rank whose stamp is older than its timeout
// dead.  Plain shared-memory stores/loads with release/acquire — the
// detector only ever needs "stamp visible, eventually", not ordering
// against the mailbox payloads.
struct LiveWord {
  std::atomic<uint64_t> beat;
  char pad[56];
};

struct Job {
  Segment seg;
  int64_t rank = 0;
  int64_t nranks = 0;
  JobHeader* hdr() { return static_cast<JobHeader*>(seg.base); }
  JobMutex* mutexes() {
    return reinterpret_cast<JobMutex*>(static_cast<char*>(seg.base) +
                                       align_up(sizeof(JobHeader), 64));
  }
  LiveWord* live() {
    return reinterpret_cast<LiveWord*>(
        static_cast<char*>(seg.base) + align_up(sizeof(JobHeader), 64) +
        nranks * static_cast<int64_t>(sizeof(JobMutex)));
  }
};

int64_t monotonic_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

// ---------------------------------------------------------------------------
// window segment
// ---------------------------------------------------------------------------

struct WinHeader {
  uint64_t magic;
  std::atomic<uint64_t> init_done;
  int64_t nranks;
  int64_t maxd;
  int64_t nbytes;
  int32_t dtype;  // 0 raw bytes, 1 float32, 2 float64
  int32_t pad0;
  int64_t chunk_bytes;
  int64_t nchunks;
};

struct SlotHeader {
  std::atomic<uint32_t> lock;   // writer spinlock
  uint32_t pad0;
  std::atomic<uint64_t> wseq;   // slot seqlock: odd while a writer mutates
  uint64_t version;             // deposit count
  uint64_t drained;             // version at last collect/reset (O(1) drain)
  double p;                     // push-sum associated scalar
};

struct Window {
  Segment seg;
  int64_t rank = 0;
  int64_t nranks = 0;
  int64_t maxd = 0;
  int64_t nbytes = 0;
  int32_t dtype = 0;
  int64_t chunk_bytes = 0;
  int64_t nchunks = 0;
  int64_t payload_off = 0;  // within a slot: after header + chunk_seq array
  int64_t slot_stride = 0;
  int64_t slots_off = 0;  // exposed slots start; mail follows

  char* slot_at(int64_t index) {
    return static_cast<char*>(seg.base) + slots_off + index * slot_stride;
  }
  // exposed slot of rank r
  char* exposed(int64_t r) { return slot_at(r); }
  // mailbox slot (dst d, in-neighbor position k)
  char* mail(int64_t d, int64_t k) {
    return slot_at(nranks + d * maxd + k);
  }
  std::atomic<uint64_t>* chunk_seqs(char* slot) {
    return reinterpret_cast<std::atomic<uint64_t>*>(
        slot + align_up(sizeof(SlotHeader), 64));
  }
  char* payload(char* slot) { return slot + payload_off; }
  int64_t chunk_len(int64_t c) {
    int64_t off = c * chunk_bytes;
    int64_t n = nbytes - off;
    return n < chunk_bytes ? (n < 0 ? 0 : n) : chunk_bytes;
  }
};

void slot_lock(SlotHeader* s) {
  uint32_t expected = 0;
  while (!s->lock.compare_exchange_weak(expected, 1,
                                        std::memory_order_acquire,
                                        std::memory_order_relaxed)) {
    expected = 0;
    cpu_relax();
  }
}

void slot_unlock(SlotHeader* s) {
  s->lock.store(0, std::memory_order_release);
}

// One chunk of the deposit pass: scaled copy or scaled add, dtype-aware.
// ``scale`` is only meaningful for float payloads (dtype 1/2); the Python
// veneer rejects scale != 1 / add for raw windows.
void chunk_apply(char* dst, const char* src, int64_t n, int32_t dtype,
                 double scale, bool add) {
  if (dtype == 1) {
    auto* d = reinterpret_cast<float*>(dst);
    auto* s = reinterpret_cast<const float*>(src);
    int64_t k = n / static_cast<int64_t>(sizeof(float));
    float f = static_cast<float>(scale);
    if (add) {
      for (int64_t i = 0; i < k; ++i) d[i] += f * s[i];
    } else if (scale == 1.0) {
      std::memcpy(dst, src, static_cast<size_t>(n));
    } else {
      for (int64_t i = 0; i < k; ++i) d[i] = f * s[i];
    }
  } else if (dtype == 2) {
    auto* d = reinterpret_cast<double*>(dst);
    auto* s = reinterpret_cast<const double*>(src);
    int64_t k = n / static_cast<int64_t>(sizeof(double));
    if (add) {
      for (int64_t i = 0; i < k; ++i) d[i] += scale * s[i];
    } else if (scale == 1.0) {
      std::memcpy(dst, src, static_cast<size_t>(n));
    } else {
      for (int64_t i = 0; i < k; ++i) d[i] = scale * s[i];
    }
  } else {
    std::memcpy(dst, src, static_cast<size_t>(n));  // raw: overwrite
  }
}

// Chunked deposit under lock + slot seqlock.  ``mode`` 0 = put (scaled
// overwrite), 1 = accumulate (scaled add; degrades to a scaled copy when
// the slot is drained — the logical-zero fast path that replaces v1's
// eager memset).  Chunks commit IN ASCENDING ORDER, each bracketed by its
// own chunk_seq odd/even publish (the pipelined-consumer contract).
void slot_deposit(Window* win, char* slot, const char* data, double p,
                  int32_t mode, double scale) {
  auto* s = reinterpret_cast<SlotHeader*>(slot);
  slot_lock(s);
  bool add = (mode == 1) && (s->drained != s->version);
  uint64_t w = s->wseq.load(std::memory_order_relaxed);
  s->wseq.store(w + 1, std::memory_order_relaxed);  // odd: in progress
  // full fence: the payload stores must not become visible before the odd
  // seq store (store-store barrier — smp_wmb in the kernel's seqlock; a
  // release fence would NOT order the later plain stores on ARM)
  std::atomic_thread_fence(std::memory_order_seq_cst);
  auto* cs = win->chunk_seqs(slot);
  char* pay = win->payload(slot);
  for (int64_t c = 0; c < win->nchunks; ++c) {
    int64_t off = c * win->chunk_bytes;
    int64_t n = win->chunk_len(c);
    uint64_t q = cs[c].load(std::memory_order_relaxed);
    cs[c].store(q + 1, std::memory_order_relaxed);  // chunk odd
    std::atomic_thread_fence(std::memory_order_seq_cst);
    chunk_apply(pay + off, data + off, n, win->dtype, scale, add);
    // the commit fence: every chunk store is visible before the even
    // publish — dropping it is the seeded-bug fixture the verifier's
    // chunk-ring model must catch
    std::atomic_thread_fence(std::memory_order_release);
    cs[c].store(q + 2, std::memory_order_release);  // chunk even: committed
  }
  if (mode == 1) {
    s->p = add ? s->p + p : p;
  } else {
    s->p = p;
  }
  s->version += 1;
  std::atomic_thread_fence(std::memory_order_release);
  s->wseq.store(w + 2, std::memory_order_release);
  slot_unlock(s);
}

// Wait-free plain read: retry until a stable even wseq brackets the copy.
// A drained slot (drained == version) is LOGICALLY zero: the payload bytes
// are stale garbage by contract, so the copy-out is a memset and p reads 0.
int64_t slot_read(Window* win, char* slot, void* out, double* p_out) {
  auto* s = reinterpret_cast<SlotHeader*>(slot);
  for (;;) {
    uint64_t before = s->wseq.load(std::memory_order_acquire);
    if (before & 1) { cpu_relax(); continue; }
    uint64_t version = s->version;
    bool empty = (s->drained == version);
    double p = empty ? 0.0 : s->p;
    if (out) {
      if (empty) {
        std::memset(out, 0, static_cast<size_t>(win->nbytes));
      } else {
        std::memcpy(out, win->payload(slot),
                    static_cast<size_t>(win->nbytes));
      }
    }
    std::atomic_thread_fence(std::memory_order_acquire);
    uint64_t after = s->wseq.load(std::memory_order_acquire);
    if (before == after) {
      if (p_out) *p_out = p;
      return static_cast<int64_t>(version);
    }
    cpu_relax();
  }
}

// Metadata-only mutation under lock + slot seqlock (collect's marker
// store, reset).  The payload is untouched — O(1), no zeroing pass.
template <typename F>
void slot_mark(char* slot, F&& mutate) {
  auto* s = reinterpret_cast<SlotHeader*>(slot);
  slot_lock(s);
  uint64_t w = s->wseq.load(std::memory_order_relaxed);
  s->wseq.store(w + 1, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  mutate(s);
  std::atomic_thread_fence(std::memory_order_release);
  s->wseq.store(w + 2, std::memory_order_release);
  slot_unlock(s);
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// Protocol revision of this library.  The ctypes loader references this
// symbol while declaring the ABI, so a stale v1 .so (whole-payload
// protocol, narrower signatures) raises AttributeError and is rebuilt
// instead of being called with mismatched arguments.
int32_t bf_shm_abi_version(void) { return 2; }

void* bf_shm_job_create(const char* name, int64_t rank, int64_t nranks) {
  auto* job = new Job;
  job->rank = rank;
  job->nranks = nranks;
  int64_t bytes = align_up(sizeof(JobHeader), 64) +
                  nranks * static_cast<int64_t>(sizeof(JobMutex)) +
                  nranks * static_cast<int64_t>(sizeof(LiveWord));
  bool creator = false;
  if (!segment_open(&job->seg, name, bytes,
                    offsetof(JobHeader, init_done), &creator)) {
    delete job;
    return nullptr;
  }
  if (creator) {
    job->hdr()->nranks = nranks;
    publish_init(job->seg.base, offsetof(JobHeader, init_done));
  }
  return job;
}

void bf_shm_job_barrier(void* h) {
  auto* job = static_cast<Job*>(h);
  auto* hdr = job->hdr();
  uint64_t gen = hdr->generation.load(std::memory_order_acquire);
  uint64_t arrived = hdr->arrived.fetch_add(1, std::memory_order_acq_rel) + 1;
  if (arrived == static_cast<uint64_t>(job->nranks)) {
    hdr->arrived.store(0, std::memory_order_relaxed);
    hdr->generation.fetch_add(1, std::memory_order_acq_rel);
  } else {
    while (hdr->generation.load(std::memory_order_acquire) == gen) cpu_relax();
  }
}

// Timed sense-reversing barrier.  Returns 0 on release, -1 on timeout.
// On timeout the caller's arrival is RETRACTED (CAS decrement) so later
// barrier episodes are not corrupted; if the release races the retract,
// the retract is abandoned and the call reports success.  timeout_ms < 0
// waits forever (identical to bf_shm_job_barrier).
int32_t bf_shm_job_barrier_timeout(void* h, int64_t timeout_ms) {
  auto* job = static_cast<Job*>(h);
  auto* hdr = job->hdr();
  int64_t deadline = timeout_ms < 0 ? -1 : monotonic_ms() + timeout_ms;
  uint64_t gen = hdr->generation.load(std::memory_order_acquire);
  uint64_t arrived = hdr->arrived.fetch_add(1, std::memory_order_acq_rel) + 1;
  if (arrived == static_cast<uint64_t>(job->nranks)) {
    hdr->arrived.store(0, std::memory_order_relaxed);
    hdr->generation.fetch_add(1, std::memory_order_acq_rel);
    return 0;
  }
  while (hdr->generation.load(std::memory_order_acquire) == gen) {
    if (deadline >= 0 && monotonic_ms() > deadline) {
      // retract our arrival — unless the barrier released meanwhile, in
      // which case arrived may already have been reset (observing 0 with
      // gen unchanged means the last arriver is between its reset and its
      // bump: the release is imminent, keep waiting for it)
      uint64_t a = hdr->arrived.load(std::memory_order_relaxed);
      for (;;) {
        if (hdr->generation.load(std::memory_order_acquire) != gen) return 0;
        if (a == 0) {
          cpu_relax();
          a = hdr->arrived.load(std::memory_order_relaxed);
          continue;
        }
        if (hdr->arrived.compare_exchange_weak(a, a - 1,
                                               std::memory_order_acq_rel,
                                               std::memory_order_relaxed)) {
          return -1;
        }
      }
    }
    cpu_relax();
  }
  return 0;
}

// Stamp my liveness word.  epoch_ms should be CLOCK_MONOTONIC milliseconds
// (pass 0 to let the library stamp it).
void bf_shm_job_heartbeat(void* h, int64_t epoch_ms) {
  auto* job = static_cast<Job*>(h);
  uint64_t stamp = epoch_ms > 0 ? static_cast<uint64_t>(epoch_ms)
                                : static_cast<uint64_t>(monotonic_ms());
  job->live()[job->rank].beat.store(stamp, std::memory_order_release);
}

// Read a rank's last heartbeat stamp (0 if it never beat).
int64_t bf_shm_job_liveness(void* h, int64_t rank) {
  auto* job = static_cast<Job*>(h);
  return static_cast<int64_t>(
      job->live()[rank].beat.load(std::memory_order_acquire));
}

// Current CLOCK_MONOTONIC milliseconds — the clock heartbeats are stamped
// with, exported so the Python detector compares stamps against the same
// system-wide timebase.
int64_t bf_shm_monotonic_ms(void) { return monotonic_ms(); }

void bf_shm_job_mutex_acquire(void* h, int64_t target_rank) {
  auto* job = static_cast<Job*>(h);
  auto& m = job->mutexes()[target_rank].locked;
  uint32_t expected = 0;
  while (!m.compare_exchange_weak(expected, 1, std::memory_order_acquire,
                                  std::memory_order_relaxed)) {
    expected = 0;
    cpu_relax();
  }
}

// Timed mutex acquire: 0 on success, -1 on timeout.  timeout_ms < 0 waits
// forever.  A mutex held by a dead rank can be reclaimed by the detector
// via bf_shm_job_mutex_break.
int32_t bf_shm_job_mutex_acquire_timeout(void* h, int64_t target_rank,
                                         int64_t timeout_ms) {
  auto* job = static_cast<Job*>(h);
  auto& m = job->mutexes()[target_rank].locked;
  int64_t deadline = timeout_ms < 0 ? -1 : monotonic_ms() + timeout_ms;
  uint32_t expected = 0;
  while (!m.compare_exchange_weak(expected, 1, std::memory_order_acquire,
                                  std::memory_order_relaxed)) {
    expected = 0;
    if (deadline >= 0 && monotonic_ms() > deadline) return -1;
    cpu_relax();
  }
  return 0;
}

// Forcibly release a mutex (dead-holder recovery; caller must have
// established via the failure detector that the holder is gone).
void bf_shm_job_mutex_break(void* h, int64_t target_rank) {
  auto* job = static_cast<Job*>(h);
  job->mutexes()[target_rank].locked.store(0, std::memory_order_release);
}

void bf_shm_job_mutex_release(void* h, int64_t target_rank) {
  auto* job = static_cast<Job*>(h);
  job->mutexes()[target_rank].locked.store(0, std::memory_order_release);
}

void bf_shm_job_destroy(void* h, int32_t unlink_seg) {
  auto* job = static_cast<Job*>(h);
  segment_close(&job->seg, unlink_seg != 0);
  delete job;
}

void* bf_shm_win_create(const char* name, int64_t rank, int64_t nranks,
                        int64_t maxd, int64_t nbytes, int32_t dtype,
                        int64_t chunk_bytes) {
  auto* win = new Window;
  win->rank = rank;
  win->nranks = nranks;
  win->maxd = maxd < 1 ? 1 : maxd;
  win->nbytes = nbytes;
  win->dtype = dtype;
  win->chunk_bytes = chunk_bytes < 1 ? kDefaultChunkBytes : chunk_bytes;
  win->nchunks = (nbytes + win->chunk_bytes - 1) / win->chunk_bytes;
  if (win->nchunks < 1) win->nchunks = 1;
  win->payload_off = align_up(sizeof(SlotHeader), 64) +
                     align_up(win->nchunks * 8, 64);
  win->slot_stride = win->payload_off + align_up(nbytes, 64);
  win->slots_off = align_up(sizeof(WinHeader), 64);
  int64_t nslots = nranks + nranks * win->maxd;
  int64_t bytes = win->slots_off + nslots * win->slot_stride;
  bool creator = false;
  if (!segment_open(&win->seg, name, bytes,
                    offsetof(WinHeader, init_done), &creator)) {
    delete win;
    return nullptr;
  }
  auto* hdr = static_cast<WinHeader*>(win->seg.base);
  if (creator) {
    hdr->magic = kMagic;
    hdr->nranks = nranks;
    hdr->maxd = win->maxd;
    hdr->nbytes = nbytes;
    hdr->dtype = dtype;
    hdr->chunk_bytes = win->chunk_bytes;
    hdr->nchunks = win->nchunks;
    publish_init(win->seg.base, offsetof(WinHeader, init_done));
  } else if (hdr->magic != kMagic || hdr->nranks != nranks ||
             hdr->maxd != win->maxd || hdr->nbytes != nbytes ||
             hdr->dtype != dtype || hdr->chunk_bytes != win->chunk_bytes ||
             hdr->nchunks != win->nchunks) {
    segment_close(&win->seg, false);
    delete win;
    return nullptr;
  }
  return win;
}

// Deposit into (dst, slot).  mode 0 = put (overwrite), 1 = accumulate.
// ``scale`` multiplies the payload inside the copy loop (float dtypes; a
// scaled put is ONE pass, not a caller-side temporary + copy); p rides
// along (overwritten or accumulated to match).
void bf_shm_win_write(void* h, int64_t dst, int64_t slot, const void* data,
                      double p, int32_t mode, double scale) {
  auto* win = static_cast<Window*>(h);
  slot_deposit(win, win->mail(dst, slot), static_cast<const char*>(data),
               p, mode, scale);
}

// Read my own mailbox slot `slot`.  collect != 0 drains it atomically —
// ONE copy-out pass plus an O(1) ``drained = version`` marker store in the
// same critical section (v1 paid a full memset pass here; a drained slot
// reads back as zeros by contract).  Returns the deposit count observed.
int64_t bf_shm_win_read(void* h, int64_t slot, void* out, double* p,
                        int32_t collect) {
  auto* win = static_cast<Window*>(h);
  char* sl = win->mail(win->rank, slot);
  if (!collect) return slot_read(win, sl, out, p);
  auto* s = reinterpret_cast<SlotHeader*>(sl);
  int64_t version = 0;
  slot_mark(sl, [&](SlotHeader* sh) {
    bool empty = (sh->drained == sh->version);
    if (out) {
      if (empty) {
        std::memset(out, 0, static_cast<size_t>(win->nbytes));
      } else {
        std::memcpy(out, win->payload(sl),
                    static_cast<size_t>(win->nbytes));
      }
    }
    if (p) *p = empty ? 0.0 : sh->p;
    version = static_cast<int64_t>(sh->version);
    sh->drained = sh->version;  // the drain: no memset, just the marker
    sh->p = 0.0;
  });
  (void)s;
  return version;
}

// Fused weighted combine: acc += weight * slot_payload in ONE pass under
// the slot lock (float windows only; the caller's ``acc`` must match the
// window dtype).  ``collect`` drains the slot in the same critical section
// (atomic with respect to accumulating writers — mass conservation).  A
// drained slot contributes nothing and p_out = 0.  Returns the version.
int64_t bf_shm_win_combine(void* h, int64_t slot, void* acc, double weight,
                           int32_t collect, double* p_out) {
  auto* win = static_cast<Window*>(h);
  char* sl = win->mail(win->rank, slot);
  auto* s = reinterpret_cast<SlotHeader*>(sl);
  slot_lock(s);
  bool empty = (s->drained == s->version);
  if (!empty && acc) {
    const char* pay = win->payload(sl);
    if (win->dtype == 1) {
      auto* a = static_cast<float*>(acc);
      auto* v = reinterpret_cast<const float*>(pay);
      int64_t k = win->nbytes / static_cast<int64_t>(sizeof(float));
      float f = static_cast<float>(weight);
      for (int64_t i = 0; i < k; ++i) a[i] += f * v[i];
    } else if (win->dtype == 2) {
      auto* a = static_cast<double*>(acc);
      auto* v = reinterpret_cast<const double*>(pay);
      int64_t k = win->nbytes / static_cast<int64_t>(sizeof(double));
      for (int64_t i = 0; i < k; ++i) a[i] += weight * v[i];
    }
  }
  if (p_out) *p_out = empty ? 0.0 : s->p;
  int64_t version = static_cast<int64_t>(s->version);
  if (collect) {
    // marker ordering matters for concurrent lock-free readers: a reader
    // that observes the new ``drained`` reports the slot empty (p forced
    // to 0), one that observes the old value gets the intact pre-drain
    // payload — both are linearizable outcomes
    s->drained = s->version;
    s->p = 0.0;
  }
  slot_unlock(s);
  return version;
}

// Drain marker without reading — the owner-side reset (reference
// win_update(reset=True) zeroing its buffers).  O(1): no payload pass.
void bf_shm_win_reset(void* h, int64_t slot) {
  auto* win = static_cast<Window*>(h);
  slot_mark(win->mail(win->rank, slot), [&](SlotHeader* s) {
    s->drained = s->version;
    s->p = 0.0;
  });
}

// Dead-writer recovery: force mailbox slot ``slot`` (of MY rank) into a
// consistent drained state even if its writer died mid-deposit, leaving
// the slot lock held and the wseq / per-chunk seqlocks odd.  Safe to call
// ONLY after the failure detector has established the writer rank is gone
// (no live writer will ever touch this slot again — each mailbox slot has
// exactly one writer by construction).
//
// Mass conservation: ``slot_deposit`` advances ``p``/``version`` only
// AFTER every chunk write, under the slot lock — so a writer that died
// mid-deposit has committed ZERO mass; discarding the torn payload and
// storing ``drained = version`` conserves the committed-mass ledger
// exactly (model-checked: dead_writer_drain_model in
// analysis/seqlock_model.py).
void bf_shm_win_force_drain(void* h, int64_t slot) {
  auto* win = static_cast<Window*>(h);
  char* sl = win->mail(win->rank, slot);
  auto* s = reinterpret_cast<SlotHeader*>(sl);
  auto* cs = win->chunk_seqs(sl);
  for (int64_t c = 0; c < win->nchunks; ++c) {
    uint64_t q = cs[c].load(std::memory_order_relaxed);
    if (q & 1) cs[c].store(q + 1, std::memory_order_release);
  }
  s->drained = s->version;
  s->p = 0.0;
  std::atomic_thread_fence(std::memory_order_release);
  // even-ize the slot seqlock, advancing past any torn bracket so a
  // reader that sampled the odd value retries and sees the drained state
  uint64_t w = s->wseq.load(std::memory_order_relaxed);
  s->wseq.store((w | 1) + 1, std::memory_order_release);
  s->lock.store(0, std::memory_order_release);
}

// Publish my exposed tensor (what win_get by a neighbor observes).
void bf_shm_win_expose(void* h, const void* data, double p) {
  auto* win = static_cast<Window*>(h);
  slot_deposit(win, win->exposed(win->rank),
               static_cast<const char*>(data), p, 0, 1.0);
}

// One-sided read of any rank's exposed tensor (the MPI_Get path).
int64_t bf_shm_win_read_exposed(void* h, int64_t src, void* out, double* p) {
  auto* win = static_cast<Window*>(h);
  return slot_read(win, win->exposed(src), out, p);
}

// Pipelined self-edge probe: stream the window payload from ``src`` to
// ``dst`` through a bounded ring of ``ring_depth`` chunk slots of mailbox
// slot ``slot``, exercising the FULL per-chunk seqlock protocol — writer
// commit (odd / mutate / release-fence / even) immediately followed by the
// bracketed reader drain of the same chunk, per chunk.  The ring stays
// cache-resident, so this measures the chunk-ring transport's pipelined
// steady state (deposit overlapping drain) with no per-chunk ctypes
// overhead.  Returns 0 on success, -1 if any reader bracket failed
// (impossible single-threaded; checked anyway).
int32_t bf_shm_win_probe(void* h, int64_t slot, const void* src, void* dst,
                         int64_t ring_depth) {
  auto* win = static_cast<Window*>(h);
  char* sl = win->mail(win->rank, slot);
  auto* s = reinterpret_cast<SlotHeader*>(sl);
  if (ring_depth < 1) ring_depth = 1;
  if (ring_depth > win->nchunks) ring_depth = win->nchunks;
  auto* cs = win->chunk_seqs(sl);
  char* pay = win->payload(sl);
  const char* in = static_cast<const char*>(src);
  char* out = static_cast<char*>(dst);
  int32_t rc = 0;
  slot_lock(s);
  uint64_t w = s->wseq.load(std::memory_order_relaxed);
  s->wseq.store(w + 1, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  for (int64_t c = 0; c < win->nchunks; ++c) {
    int64_t ring = c % ring_depth;
    int64_t off = c * win->chunk_bytes;
    int64_t n = win->chunk_len(c);
    char* chunk = pay + ring * win->chunk_bytes;
    // writer leg: commit chunk c into ring slot `ring`
    uint64_t q = cs[ring].load(std::memory_order_relaxed);
    cs[ring].store(q + 1, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    std::memcpy(chunk, in + off, static_cast<size_t>(n));
    std::atomic_thread_fence(std::memory_order_release);
    cs[ring].store(q + 2, std::memory_order_release);
    // reader leg: bracketed drain of the chunk just committed
    uint64_t before = cs[ring].load(std::memory_order_acquire);
    std::memcpy(out + off, chunk, static_cast<size_t>(n));
    std::atomic_thread_fence(std::memory_order_acquire);
    if ((before & 1) || cs[ring].load(std::memory_order_acquire) != before) {
      rc = -1;
    }
  }
  // the ring overwrote the slot payload with the stream's tail: mark the
  // slot drained so subsequent reads see a logical zero, not garbage
  s->version += 1;
  s->drained = s->version;
  s->p = 0.0;
  std::atomic_thread_fence(std::memory_order_release);
  s->wseq.store(w + 2, std::memory_order_release);
  slot_unlock(s);
  return rc;
}

// Fused dual-target deposit: ONE read of ``data`` feeds BOTH my exposed
// slot (the win_put contract of refreshing the window tensor) and the
// mailbox slot at (dst, slot), chunk-interleaved so the source chunk is
// still cache-hot for its second store.  Replaces expose() + write() —
// two full passes over ``data`` — with one.  Lock order: my exposed lock,
// then the remote slot lock; exposed locks are only ever taken by their
// owner rank, so every wait chain terminates (no cycle).
void bf_shm_win_put_dual(void* h, int64_t dst, int64_t slot,
                         const void* data, double p, int32_t mode,
                         double scale, double expose_p) {
  auto* win = static_cast<Window*>(h);
  char* ex = win->exposed(win->rank);
  char* ml = win->mail(dst, slot);
  auto* es = reinterpret_cast<SlotHeader*>(ex);
  auto* ms = reinterpret_cast<SlotHeader*>(ml);
  const char* in = static_cast<const char*>(data);
  slot_lock(es);
  slot_lock(ms);
  bool add = (mode == 1) && (ms->drained != ms->version);
  uint64_t we = es->wseq.load(std::memory_order_relaxed);
  uint64_t wm = ms->wseq.load(std::memory_order_relaxed);
  es->wseq.store(we + 1, std::memory_order_relaxed);
  ms->wseq.store(wm + 1, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  auto* ecs = win->chunk_seqs(ex);
  auto* mcs = win->chunk_seqs(ml);
  char* epay = win->payload(ex);
  char* mpay = win->payload(ml);
  for (int64_t c = 0; c < win->nchunks; ++c) {
    int64_t off = c * win->chunk_bytes;
    int64_t n = win->chunk_len(c);
    uint64_t q = ecs[c].load(std::memory_order_relaxed);
    ecs[c].store(q + 1, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    std::memcpy(epay + off, in + off, static_cast<size_t>(n));
    std::atomic_thread_fence(std::memory_order_release);
    ecs[c].store(q + 2, std::memory_order_release);
    q = mcs[c].load(std::memory_order_relaxed);
    mcs[c].store(q + 1, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_seq_cst);
    chunk_apply(mpay + off, in + off, n, win->dtype, scale, add);
    std::atomic_thread_fence(std::memory_order_release);
    mcs[c].store(q + 2, std::memory_order_release);
  }
  es->p = expose_p;
  es->version += 1;
  es->drained = 0;  // an exposed slot is never logically empty once set
  if (mode == 1) {
    ms->p = add ? ms->p + p : p;
  } else {
    ms->p = p;
  }
  ms->version += 1;
  std::atomic_thread_fence(std::memory_order_release);
  es->wseq.store(we + 2, std::memory_order_release);
  ms->wseq.store(wm + 2, std::memory_order_release);
  slot_unlock(ms);
  slot_unlock(es);
}

// Fully fused win_update: out = self_weight * self_data + Σ w_i * slot_i
// in ONE chunked sweep, with the per-chunk partial staying cache-resident
// across the per-slot sub-passes; optionally drains the slots (atomic
// with accumulating writers — every slot lock is held for the whole
// combine) and republishes ``out`` as the exposed tensor chunk-by-chunk
// inside the same sweep (the expose pass rides the combine's cache
// locality instead of being a fourth full traversal).  Float windows
// only.  ``expose``: 0 = don't republish, 1 = republish with p = self_p
// (associated-p off: the exposed mass is untouched), 2 = republish with
// p = the combined mass (associated-p on).  Returns the combined scalar
// mass ``self_weight * self_p + Σ w_i * p_i`` (drained slots contribute 0).
// Locks are acquired in ascending slot index, exposed lock first —
// the same no-cycle argument as put_dual.
double bf_shm_win_update_fused(void* h, int64_t nslots,
                               const int64_t* slots, const double* weights,
                               const void* self_data, double self_weight,
                               double self_p, void* out, int32_t collect,
                               int32_t expose) {
  auto* win = static_cast<Window*>(h);
  if (nslots > 64) return 0.0;  // maxd ceiling; callers never exceed it
  char* ex = win->exposed(win->rank);
  auto* es = reinterpret_cast<SlotHeader*>(ex);
  // ascending-index lock order (slots may arrive in neighbor-rank order,
  // which is already ascending in practice; sort defensively)
  int64_t order[64];
  for (int64_t i = 0; i < nslots; ++i) order[i] = i;
  for (int64_t i = 1; i < nslots; ++i)
    for (int64_t j = i; j > 0 && slots[order[j]] < slots[order[j - 1]]; --j) {
      int64_t t = order[j]; order[j] = order[j - 1]; order[j - 1] = t;
    }
  char* epay = win->payload(ex);
  // out == nullptr selects the IN-PLACE form: the combine's destination
  // IS the exposed payload (the reference's window-buffer semantics —
  // win_update writes the memory neighbors read), eliminating both the
  // separate result buffer and the republish copy; the per-chunk seqlock
  // then brackets the whole chunk computation instead of a memcpy.
  char* dst = out ? static_cast<char*>(out) : epay;
  bool in_place = (dst == epay);
  if (in_place && !expose) expose = 1;
  if (expose) slot_lock(es);
  char* ml[64];
  SlotHeader* ms[64];
  bool empty[64];
  for (int64_t i = 0; i < nslots; ++i) {
    ml[i] = win->mail(win->rank, slots[i]);
    ms[i] = reinterpret_cast<SlotHeader*>(ml[i]);
  }
  for (int64_t i = 0; i < nslots; ++i) slot_lock(ms[order[i]]);
  double p_acc = self_weight * self_p;
  for (int64_t i = 0; i < nslots; ++i) {
    empty[i] = (ms[i]->drained == ms[i]->version);
    if (!empty[i]) p_acc += weights[i] * ms[i]->p;
  }
  uint64_t we = 0;
  auto* ecs = win->chunk_seqs(ex);
  if (expose) {
    we = es->wseq.load(std::memory_order_relaxed);
    es->wseq.store(we + 1, std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_seq_cst);
  }
  const char* self_in = static_cast<const char*>(self_data);
  for (int64_t c = 0; c < win->nchunks; ++c) {
    int64_t off = c * win->chunk_bytes;
    int64_t n = win->chunk_len(c);
    if (expose) {
      uint64_t q = ecs[c].load(std::memory_order_relaxed);
      ecs[c].store(q + 1, std::memory_order_relaxed);
      std::atomic_thread_fence(std::memory_order_seq_cst);
    }
    // self term first (alias-safe even when dst == self_data, including
    // the coherent-second-mapping case — full exact overlap means every
    // element/lane is read before it is overwritten)
    chunk_apply(dst + off, self_in + off, n, win->dtype, self_weight,
                /*add=*/false);
    for (int64_t i = 0; i < nslots; ++i) {
      if (empty[i]) continue;
      chunk_apply(dst + off, win->payload(ml[i]) + off, n, win->dtype,
                  weights[i], /*add=*/true);
    }
    if (expose) {
      if (!in_place)
        std::memcpy(epay + off, dst + off, static_cast<size_t>(n));
      std::atomic_thread_fence(std::memory_order_release);
      uint64_t q = ecs[c].load(std::memory_order_relaxed);
      ecs[c].store(q + 1, std::memory_order_release);
    }
  }
  if (collect) {
    for (int64_t i = 0; i < nslots; ++i) {
      ms[i]->drained = ms[i]->version;
      ms[i]->p = 0.0;
    }
  }
  if (expose) {
    es->p = (expose == 2) ? p_acc : self_p;
    es->version += 1;
    es->drained = 0;
    std::atomic_thread_fence(std::memory_order_release);
    es->wseq.store(we + 2, std::memory_order_release);
  }
  for (int64_t i = nslots - 1; i >= 0; --i) slot_unlock(ms[order[i]]);
  if (expose) slot_unlock(es);
  return p_acc;
}

// Byte offset of this rank's exposed payload within the segment file.
// Lets Python establish an independent coherent mapping of the exposed
// tensor (np view over its own mmap), so views returned to users stay
// valid after the window's native mapping is unmapped by win_destroy.
int64_t bf_shm_win_exposed_offset(void* h) {
  auto* win = static_cast<Window*>(h);
  return win->slots_off + win->rank * win->slot_stride + win->payload_off;
}

void bf_shm_win_destroy(void* h, int32_t unlink_seg) {
  auto* win = static_cast<Window*>(h);
  segment_close(&win->seg, unlink_seg != 0);
  delete win;
}

void bf_shm_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
