// Native chrome-trace timeline writer.
//
// TPU-native sibling of the reference's C++ timeline component
// (bluefog/common/timeline.h/.cc [U], SURVEY.md §5.1): a low-overhead,
// thread-safe span recorder with a background flush thread writing
// Chrome-tracing JSON.  The reference stamps per-tensor activity spans from
// its background communication loop; here spans come from the Python op
// veneers (dispatch-side timing; device-side timing lives in jax.profiler).
//
// C ABI (used from Python via ctypes — the environment has no pybind11):
//   bf_timeline_create(path) -> handle
//   bf_timeline_record(handle, name, ts_us, dur_us, tid)
//   bf_timeline_counter(handle, name, ts_us, value)
//   bf_timeline_flush(handle)
//   bf_timeline_destroy(handle)

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Event {
  std::string name;
  double ts_us;
  double dur_us;
  int64_t tid;
  bool is_counter;
  double value;
};

std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size() + 8);
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

class TimelineWriter {
 public:
  explicit TimelineWriter(const char* path)
      : path_(path), stop_(false), dirty_(false) {
    flusher_ = std::thread([this] { this->Loop(); });
  }

  ~TimelineWriter() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    if (flusher_.joinable()) flusher_.join();
    WriteFile();
  }

  void Record(const char* name, double ts_us, double dur_us, int64_t tid) {
    std::lock_guard<std::mutex> lk(mu_);
    events_.push_back(Event{name, ts_us, dur_us, tid, false, 0.0});
    dirty_ = true;
    cv_.notify_all();
  }

  void Counter(const char* name, double ts_us, double value) {
    std::lock_guard<std::mutex> lk(mu_);
    events_.push_back(Event{name, ts_us, 0.0, 0, true, value});
    dirty_ = true;
    cv_.notify_all();
  }

  void Flush() { WriteFile(); }

 private:
  void Loop() {
    // Periodic background flush, like the reference's writer thread [U]:
    // the trace survives a crashed run without per-event file I/O.
    std::unique_lock<std::mutex> lk(mu_);
    while (!stop_) {
      cv_.wait_for(lk, std::chrono::seconds(2),
                   [this] { return stop_ || dirty_; });
      if (stop_) break;
      if (!dirty_) continue;
      dirty_ = false;
      lk.unlock();
      WriteFile();
      lk.lock();
    }
  }

  void WriteFile() {
    std::vector<Event> snapshot;
    {
      std::lock_guard<std::mutex> lk(mu_);
      snapshot = events_;
    }
    std::string tmp = path_ + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "w");
    if (!f) return;
    std::fputs("{\"traceEvents\":[", f);
    bool first = true;
    char buf[512];
    for (const auto& e : snapshot) {
      if (!first) std::fputc(',', f);
      first = false;
      if (e.is_counter) {
        std::snprintf(buf, sizeof(buf),
                      "{\"name\":\"%s\",\"ph\":\"C\",\"ts\":%.3f,\"pid\":0,"
                      "\"args\":{\"value\":%.6g}}",
                      JsonEscape(e.name).c_str(), e.ts_us, e.value);
      } else {
        std::snprintf(buf, sizeof(buf),
                      "{\"name\":\"%s\",\"ph\":\"X\",\"ts\":%.3f,"
                      "\"dur\":%.3f,\"pid\":0,\"tid\":%lld}",
                      JsonEscape(e.name).c_str(), e.ts_us, e.dur_us,
                      static_cast<long long>(e.tid));
      }
      std::fputs(buf, f);
    }
    std::fputs("]}", f);
    std::fclose(f);
    std::rename(tmp.c_str(), path_.c_str());
  }

  std::string path_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::thread flusher_;
  std::vector<Event> events_;
  bool stop_;
  bool dirty_;
};

}  // namespace

extern "C" {

void* bf_timeline_create(const char* path) { return new TimelineWriter(path); }

void bf_timeline_record(void* h, const char* name, double ts_us, double dur_us,
                        int64_t tid) {
  static_cast<TimelineWriter*>(h)->Record(name, ts_us, dur_us, tid);
}

void bf_timeline_counter(void* h, const char* name, double ts_us,
                         double value) {
  static_cast<TimelineWriter*>(h)->Counter(name, ts_us, value);
}

void bf_timeline_flush(void* h) { static_cast<TimelineWriter*>(h)->Flush(); }

void bf_timeline_destroy(void* h) { delete static_cast<TimelineWriter*>(h); }

}  // extern "C"
