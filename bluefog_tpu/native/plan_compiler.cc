// Native comm-plan compiler: shift-class decomposition of a digraph.
//
// TPU-native sibling of the reference's graph-communicator construction
// (MPI_Dist_graph_create_adjacent in bluefog/common/mpi_context.cc [U] and
// the NCCL controller's grouped send/recv list building [U], SURVEY.md
// §2.4).  Python's plan.py performs the same decomposition; this native
// version is used when available (large graphs / frequent dynamic-topology
// compilation) and is verified against the Python fallback in tests.
//
// C ABI:
//   bf_plan_compile(size, n_edges, srcs, dsts,
//                   out_class_of_edge, out_slot_of_edge) -> n_classes
//     - out_class_of_edge[i]: shift-class index of edge i (classes ordered
//       by ascending shift (dst-src) mod size)
//     - out_slot_of_edge[i]: position of src in dst's ascending in-neighbor
//       list (drives neighbor_allgather placement)
//   Returns -1 on invalid input (self-edge, duplicate edge, out of range).

#include <algorithm>
#include <cstdint>
#include <map>
#include <set>
#include <vector>

extern "C" {

int64_t bf_plan_compile(int64_t size, int64_t n_edges, const int64_t* srcs,
                        const int64_t* dsts, int64_t* out_class_of_edge,
                        int64_t* out_slot_of_edge) {
  if (size <= 0 || n_edges < 0) return -1;
  std::vector<std::vector<int64_t>> in_neighbors(size);
  std::set<std::pair<int64_t, int64_t>> seen;
  for (int64_t i = 0; i < n_edges; ++i) {
    int64_t s = srcs[i], d = dsts[i];
    if (s < 0 || s >= size || d < 0 || d >= size || s == d) return -1;
    if (!seen.insert({s, d}).second) return -1;  // duplicate edge
    in_neighbors[d].push_back(s);
  }
  for (auto& v : in_neighbors) std::sort(v.begin(), v.end());

  // shift -> dense class index, ordered by ascending shift
  std::map<int64_t, int64_t> class_of_shift;
  for (int64_t i = 0; i < n_edges; ++i) {
    int64_t shift = ((dsts[i] - srcs[i]) % size + size) % size;
    class_of_shift.emplace(shift, 0);
  }
  int64_t idx = 0;
  for (auto& kv : class_of_shift) kv.second = idx++;

  for (int64_t i = 0; i < n_edges; ++i) {
    int64_t shift = ((dsts[i] - srcs[i]) % size + size) % size;
    out_class_of_edge[i] = class_of_shift[shift];
    const auto& nb = in_neighbors[dsts[i]];
    out_slot_of_edge[i] =
        std::lower_bound(nb.begin(), nb.end(), srcs[i]) - nb.begin();
  }
  return idx;
}

}  // extern "C"
