"""Transport capability records.

Every window transport (native shm, fallback shm, TCP, routed, sim)
declares ONE :class:`TransportCaps` record as a ``CAPS`` class attribute.
The record is the *only* thing a call site may branch on when it adapts
to a backend: the progress engine's fusion decision, islands' scaled
deposits, the wire-dtype selection, resume paths, and the routed tier
split all key off declared capabilities, never off transport class
identity (``analysis/transport_spec.py`` lints both sides — that each
declaration is honest against the class's actual surface, and that call
sites only probe capabilities).

The two ``future_*`` fields name the tiers ROADMAP item 1 adds next
(device-resident windows, an in-mesh collective transport); they exist
now so the lint and the capability matrix in ``docs/ANALYSIS.md`` do not
need a schema change when those tiers land.

This module imports nothing heavy (no numpy, no transports) so every
transport can import it without cycles.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

__all__ = ["TransportCaps", "CAP_FIELDS", "meet"]


@dataclasses.dataclass(frozen=True)
class TransportCaps:
    """What one window transport can do, as data.

    ``name`` identifies the tier (for reports); every other field is a
    boolean capability a call site may probe.
    """

    name: str
    #: ``write(..., accumulate=True)`` folds into the destination slot
    #: on the receiver side (push-sum deposits need no read-modify-write
    #: round trip at the caller).
    fused_accumulate: bool
    #: ``write(..., scale=w)`` applies the gossip weight inside the
    #: deposit pass (``supports_scale``); otherwise callers pre-multiply.
    fused_scale: bool
    #: ``combine()``/``update_fused()`` exist: read-side fused
    #: multiply-accumulate sweeps without per-slot temporaries.
    fused_combine: bool
    #: ``read(collect=True)`` drains without copying the payload (marker
    #: drain or buffer swap), so collect cost is O(1) + one consume.
    zero_copy_collect: bool
    #: deposits stream as per-chunk seqlocked (or credit-windowed)
    #: frames that overlap with readers; implies the ascending-commit
    #: and commit-fence rules of the chunk protocol apply.
    chunked_streaming: bool
    #: payloads may ride the wire quantized (``BFTPU_WIRE_DTYPE``) with
    #: an error-feedback residual keeping mass conservation exact.
    wire_quantization: bool
    #: a broken connection can resume a session and replay idempotent
    #: ops (and re-send uncommitted chunk streams) without double
    #: counting.
    resume: bool
    #: future tier (ROADMAP item 1): window memory is device-resident.
    device_resident: bool = False
    #: future tier: deposits ride an in-mesh collective, not a mailbox.
    in_mesh_collective: bool = False


#: The boolean capability fields, in declaration order (the lint and the
#: docs capability matrix iterate this — one source of truth).
CAP_FIELDS: Tuple[str, ...] = tuple(
    f.name for f in dataclasses.fields(TransportCaps) if f.name != "name")


def meet(a: TransportCaps, b: TransportCaps, name: str) -> TransportCaps:
    """Capability AND — what a composite transport (e.g. routed, which
    splits traffic between an shm leg and a TCP leg) may honestly claim:
    only what BOTH legs provide, since a caller cannot know which leg a
    given edge takes."""
    return TransportCaps(
        name=name,
        **{f: getattr(a, f) and getattr(b, f) for f in CAP_FIELDS},
    )
