"""ctypes wrapper over the native layout annealer (layout_optimizer.cc),
with a pure-Python fallback implementing the same search."""

from __future__ import annotations

import ctypes
import math
import random
from typing import List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.native import get_lib
from bluefog_tpu.parallel.ici_map import hop_distance as _hop


def anneal_layout(
    coords: Sequence[Sequence[int]],
    torus_shape: Sequence[int],
    edges: Sequence[Tuple[int, int]],
    weights: Optional[Sequence[float]] = None,
    *,
    init: Optional[Sequence[int]] = None,
    iters: int = 20000,
    seed: int = 0,
) -> Tuple[List[int], float]:
    """Best-found rank→position assignment and its weighted hop cost.

    ``coords[p]`` is candidate position p's torus coordinate; ranks and
    positions are both ``0..n-1``.  ``init`` seeds the search (identity by
    default).  Uses the native annealer when available, else the Python
    twin (same moves/cooling, deterministic for a given seed on each path).
    """
    n = len(coords)
    nd = len(torus_shape)
    if any(len(c) != nd for c in coords):
        raise ValueError("coords dimensionality does not match torus_shape")
    m = len(edges)
    w = [1.0] * m if weights is None else list(weights)
    if len(w) != m:
        raise ValueError(f"{m} edges but {len(w)} weights")
    assign = list(range(n)) if init is None else list(init)
    if sorted(assign) != list(range(n)):
        raise ValueError("init must be a permutation of 0..n-1")
    for s, d in edges:
        if not (0 <= s < n and 0 <= d < n) or s == d:
            raise ValueError(f"invalid edge ({s}, {d})")

    lib = get_lib()
    if lib is not None:
        c_coords = np.ascontiguousarray(coords, dtype=np.int64).reshape(n, nd)
        c_shape = np.ascontiguousarray(torus_shape, dtype=np.int64)
        c_src = np.ascontiguousarray([e[0] for e in edges], dtype=np.int64)
        c_dst = np.ascontiguousarray([e[1] for e in edges], dtype=np.int64)
        c_w = np.ascontiguousarray(w, dtype=np.float64)
        c_assign = np.ascontiguousarray(assign, dtype=np.int64)
        ip = lambda a: a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        cost = lib.bf_layout_anneal(
            n, nd, ip(c_coords), ip(c_shape), m, ip(c_src), ip(c_dst),
            c_w.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            iters, seed, ip(c_assign),
        )
        if cost < 0:
            raise ValueError("native annealer rejected the input")
        return c_assign.tolist(), float(cost)

    # ---- pure-Python twin ----
    pos = list(assign)
    inc: List[List[int]] = [[] for _ in range(n)]
    for e, (s, d) in enumerate(edges):
        inc[s].append(e)
        if d != s:
            inc[d].append(e)

    def edge_cost(e: int) -> float:
        s, d = edges[e]
        return w[e] * _hop(coords[pos[s]], coords[pos[d]], torus_shape)

    cost = sum(edge_cost(e) for e in range(m))
    best, best_cost = list(pos), cost
    if n < 2 or m == 0 or iters == 0:
        return best, best_cost

    rng = random.Random(seed)
    t0 = max(cost / max(m, 1), 1e-9)
    decay = (t0 * 1e-3 / t0) ** (1.0 / iters)
    temp = t0
    for _ in range(iters):
        r1, r2 = rng.randrange(n), rng.randrange(n)
        temp *= decay
        if r1 == r2:
            continue
        touched = inc[r1] + [
            e for e in inc[r2] if edges[e][0] != r1 and edges[e][1] != r1
        ]
        before = sum(edge_cost(e) for e in touched)
        pos[r1], pos[r2] = pos[r2], pos[r1]
        after = sum(edge_cost(e) for e in touched)
        delta = after - before
        if delta <= 0 or rng.random() < math.exp(-delta / temp):
            cost += delta
            if cost < best_cost:
                best_cost, best = cost, list(pos)
        else:
            pos[r1], pos[r2] = pos[r2], pos[r1]
    return best, best_cost
