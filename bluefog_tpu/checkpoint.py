"""Checkpoint/resume helpers.

The reference has no framework-level checkpointing (SURVEY.md §5.4):
examples use ``torch.save``/``load`` plus ``bf.broadcast_parameters`` /
``bf.broadcast_optimizer_state`` from rank 0 for consistent restarts.  The
TPU-native equivalent pairs orbax (the JAX checkpoint library) with the
same broadcast-on-restore idiom; ``save``/``restore`` here work on any
pytree (params, optimizer state, window state).

Decentralized nuance: ranks hold *different* parameters by design, so two
modes exist —
- ``mode="rank0"`` (the reference's idiom): persist rank 0's slice, restore
  broadcast to every rank;
- ``mode="all"``: persist the full rank-major array (exact training-state
  resume, including disagreement between ranks).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bluefog_tpu.core import basics

__all__ = ["save", "restore", "save_consensus", "restore_broadcast"]


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save(path: str, tree: Any, *, mode: str = "all") -> None:
    """Persist a (rank-major) pytree.  mode='rank0' stores only rank 0's
    slice (smaller, the reference's semantic); mode='all' stores everything.
    """
    if mode == "rank0":
        tree = jax.tree_util.tree_map(
            lambda a: np.asarray(a[0]) if getattr(a, "ndim", 0) >= 1 else np.asarray(a),
            tree,
        )
    else:
        tree = jax.tree_util.tree_map(np.asarray, tree)
    _ckptr().save(os.path.abspath(path), tree, force=True)


def restore(path: str) -> Any:
    """Load a pytree saved by :func:`save` (mode='all' layout)."""
    return _ckptr().restore(os.path.abspath(path))


def save_consensus(path: str, tree: Any) -> None:
    """Persist the rank-averaged model — the natural artifact of gossip
    training (all ranks converge to it)."""
    tree = jax.tree_util.tree_map(
        lambda a: np.asarray(jnp.mean(jnp.asarray(a), axis=0))
        if getattr(a, "ndim", 0) >= 1
        else np.asarray(a),
        tree,
    )
    _ckptr().save(os.path.abspath(path), tree, force=True)


def restore_broadcast(path: str, *, root_rank: int = 0) -> Any:
    """Restore a rank-0/consensus checkpoint and replicate it rank-major to
    every rank (the reference's ``load + broadcast_parameters`` restart
    idiom [U])."""
    single = _ckptr().restore(os.path.abspath(path))
    n = basics.size()
    return jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(jnp.asarray(a)[None], (n,) + jnp.asarray(a).shape)
        if np.asarray(a).ndim >= 1
        else jnp.asarray(a),
        single,
    )
