"""Checkpoint/resume helpers.

The reference has no framework-level checkpointing (SURVEY.md §5.4):
examples use ``torch.save``/``load`` plus ``bf.broadcast_parameters`` /
``bf.broadcast_optimizer_state`` from rank 0 for consistent restarts.  The
TPU-native equivalent pairs orbax (the JAX checkpoint library) with the
same broadcast-on-restore idiom; ``save``/``restore`` here work on any
pytree (params, optimizer state, window state).

Decentralized nuance: ranks hold *different* parameters by design, so two
modes exist —
- ``mode="rank0"`` (the reference's idiom): persist rank 0's slice, restore
  broadcast to every rank;
- ``mode="all"``: persist the full rank-major array (exact training-state
  resume, including disagreement between ranks).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bluefog_tpu.core import basics

__all__ = ["save", "restore", "restore_like", "save_consensus",
           "restore_broadcast"]


def _ckptr():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save(path: str, tree: Any, *, mode: str = "all") -> None:
    """Persist a (rank-major) pytree.  mode='rank0' stores only rank 0's
    slice (smaller, the reference's semantic); mode='all' stores everything.
    """
    if mode == "rank0":
        tree = jax.tree_util.tree_map(
            lambda a: np.asarray(a[0]) if getattr(a, "ndim", 0) >= 1 else np.asarray(a),
            tree,
        )
    else:
        tree = jax.tree_util.tree_map(np.asarray, tree)
    _ckptr().save(os.path.abspath(path), tree, force=True)


def restore(path: str) -> Any:
    """Load a pytree saved by :func:`save` (mode='all' layout)."""
    return _ckptr().restore(os.path.abspath(path))


def restore_like(path: str, like: Any) -> Any:
    """Restore a pytree and re-place every leaf with the sharding (and
    dtype) of the matching leaf in ``like`` — the exact-resume path for
    SHARDED training state (e.g. ``parallel.zero`` master/opt grids,
    where each chip must get back exactly its shard, not a replica)."""
    # restore INTO the template's structure (orbax item=): leaf pairing
    # is structural, not positional — a bare restore returns string-keyed
    # dicts for tuple nodes, whose lexicographic flatten order permutes
    # same-shaped leaves once a node has 10+ children
    skeleton = jax.tree_util.tree_map(lambda _: 0, like)
    restored = _ckptr().restore(os.path.abspath(path), item=skeleton)
    r_leaves = jax.tree_util.tree_leaves(restored)
    l_leaves, treedef = jax.tree_util.tree_flatten(like)
    if len(r_leaves) != len(l_leaves):
        raise ValueError(
            f"checkpoint has {len(r_leaves)} leaves, template has "
            f"{len(l_leaves)}"
        )
    out = []
    for r, l in zip(r_leaves, l_leaves):
        # cast on HOST: committing the full leaf to one device first
        # would OOM at exactly the sharded-8B scale this API serves
        arr = np.asarray(r, dtype=getattr(l, "dtype", None))
        sh = getattr(l, "sharding", None)
        out.append(jax.device_put(arr, sh) if sh is not None
                   else jnp.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out)


def save_consensus(path: str, tree: Any) -> None:
    """Persist the rank-averaged model — the natural artifact of gossip
    training (all ranks converge to it)."""
    tree = jax.tree_util.tree_map(
        lambda a: np.asarray(jnp.mean(jnp.asarray(a), axis=0))
        if getattr(a, "ndim", 0) >= 1
        else np.asarray(a),
        tree,
    )
    _ckptr().save(os.path.abspath(path), tree, force=True)


def restore_broadcast(path: str, *, root_rank: int = 0) -> Any:
    """Restore a rank-0/consensus checkpoint and replicate it rank-major to
    every rank (the reference's ``load + broadcast_parameters`` restart
    idiom [U])."""
    single = _ckptr().restore(os.path.abspath(path))
    n = basics.size()
    return jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(jnp.asarray(a)[None], (n,) + jnp.asarray(a).shape)
        if np.asarray(a).ndim >= 1
        else jnp.asarray(a),
        single,
    )
