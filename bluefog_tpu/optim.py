"""Decentralized optimizers — optax-native transforms + Bluefog-parity classes.

TPU-native sibling of the reference's ``bluefog/torch/optimizers.py`` [U]
(SURVEY.md §2.2, §3.3).  The reference hooks per-parameter backward callbacks
to overlap nonblocking gossip with backprop; under XLA the same overlap falls
out of putting the gossip *inside* the jitted train step — the compiler
schedules collectives concurrently with compute (SURVEY.md §3.3 TPU mapping),
so the whole hook/handle machinery dissolves into pure functions.

Two layers:

- **SPMD builders** (``*_spmd``): optax ``GradientTransformation`` factories
  parameterized by a comm function, for use inside user ``jit``/``shard_map``
  train steps — the idiomatic TPU path (used by the flagship benchmark).
- **Parity classes** (``DistributedAdaptThenCombineOptimizer`` etc.):
  eager, rank-major ``init``/``step`` mirroring the reference's usage shape,
  including ``CommunicationType`` and ``num_steps_per_communication``.

Algorithms (arXiv:2111.04287 §2):
  ATC  (adapt-then-combine):  w_{t+1} = W (w_t - α u_t)
  AWC  (adapt-with-combine):  w_{t+1} = W w_t - α u_t
  Gradient allreduce (Horovod-equivalent DP): u_t averaged globally.
  Win-put (async push-style): local adapt, deposit to out-neighbors'
  mailboxes, merge mailboxes — no global barrier semantics.
"""

from __future__ import annotations

import enum
import functools
from typing import Any, Callable, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from bluefog_tpu import ops, ops_spmd, windows
from bluefog_tpu.telemetry import registry as _telemetry
from bluefog_tpu.core import basics
from bluefog_tpu.core.basics import LOCAL_AXIS, MACHINES_AXIS, NODES_AXIS
from bluefog_tpu.core.plan import CommPlan
from bluefog_tpu.timeline import timeline_context

__all__ = [
    "CommunicationType",
    "adapt_then_combine_spmd",
    "adapt_with_combine_spmd",
    "gradient_allreduce_spmd",
    "DistributedAdaptThenCombineOptimizer",
    "DistributedAdaptWithCombineOptimizer",
    "DistributedGradientAllreduceOptimizer",
    "DistributedWinPutOptimizer",
    "one_peer_plan_schedule",
    "broadcast_parameters",
    "broadcast_optimizer_state",
]


class CommunicationType(enum.Enum):
    """Reference ``bf.CommunicationType`` [U]."""

    allreduce = "allreduce"
    neighbor_allreduce = "neighbor.allreduce"
    hierarchical_neighbor_allreduce = "hierarchical.neighbor.allreduce"
    empty = "empty"


CommFn = Callable[[Any], Any]  # pytree -> pytree, inside SPMD context


def make_spmd_comm_fn(
    comm_type: CommunicationType,
    plan: Optional[CommPlan] = None,
    machine_plan: Optional[CommPlan] = None,
    axis_name: str = NODES_AXIS,
    machines_axis: str = MACHINES_AXIS,
    local_axis: str = LOCAL_AXIS,
    fuse: bool = False,
) -> CommFn:
    """Build the in-SPMD communication function for a CommunicationType.

    ``fuse`` forwards to :func:`ops_spmd.neighbor_allreduce`'s fusion
    buffer (one ppermute per shift class per dtype group).  Default off
    for the training path: packing a large param tree materializes a
    params-sized pack+unpack per round, trading HBM bandwidth for
    collective count — the right side of that trade depends on leaf
    count and interconnect latency, so it is a measured knob, not a
    default (see docs/STATUS.md round-4 fusion-buffer entry; the exact
    methods in :mod:`bluefog_tpu.algorithms`, whose trees are small and
    carry an odd-shaped push-sum scalar, use it unconditionally)."""
    if fuse and comm_type != CommunicationType.neighbor_allreduce:
        # silently dropping the flag would poison an A/B (same rationale
        # as llama.py's --remat-policy guard): only the neighbor path
        # implements the fusion buffer today
        raise ValueError(
            f"fuse=True is only implemented for neighbor_allreduce, "
            f"not {comm_type}"
        )
    if comm_type == CommunicationType.empty:
        return lambda x: x
    if comm_type == CommunicationType.allreduce:
        return lambda x: ops_spmd.allreduce(x, axis_name, average=True)
    if comm_type == CommunicationType.neighbor_allreduce:
        if plan is None:
            raise ValueError("neighbor_allreduce needs a CommPlan")
        return lambda x: ops_spmd.neighbor_allreduce(x, plan, axis_name,
                                                     fuse=fuse)
    if comm_type == CommunicationType.hierarchical_neighbor_allreduce:
        if machine_plan is None:
            raise ValueError("hierarchical_neighbor_allreduce needs a machine CommPlan")
        return lambda x: ops_spmd.hierarchical_neighbor_allreduce(
            x, machine_plan, machines_axis, local_axis
        )
    raise ValueError(f"unknown communication type {comm_type}")


class GossipState(NamedTuple):
    base: Any
    step: jnp.ndarray  # int32 counter for num_steps_per_communication


def _every_k(comm_fn: CommFn, k: int) -> Callable[[Any, jnp.ndarray], Any]:
    """Communicate only on every k-th call (reference
    ``num_steps_per_communication`` [U]); k==1 avoids the cond entirely."""
    if k <= 1:
        return lambda x, step: comm_fn(x)

    def maybe(x, step):
        return jax.lax.cond((step + 1) % k == 0, comm_fn, lambda t: t, x)

    return maybe


def adapt_then_combine_spmd(
    base: optax.GradientTransformation,
    comm_fn: CommFn,
    num_steps_per_communication: int = 1,
) -> optax.GradientTransformation:
    """ATC as an optax transform: the returned updates satisfy
    ``params + updates == comm(params + base_updates)``.

    Mirrors ``DistributedAdaptThenCombineOptimizer`` [U]: local adapt first,
    then neighbor-combine the adapted parameters.
    """
    maybe_comm = _every_k(comm_fn, num_steps_per_communication)

    def init(params):
        return GossipState(base=base.init(params), step=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("ATC requires params")
        updates, base_state = base.update(grads, state.base, params)
        adapted = optax.apply_updates(params, updates)
        combined = maybe_comm(adapted, state.step)
        out = jax.tree_util.tree_map(lambda c, p: (c - p).astype(p.dtype), combined, params)
        return out, GossipState(base=base_state, step=state.step + 1)

    return optax.GradientTransformation(init, update)


def adapt_with_combine_spmd(
    base: optax.GradientTransformation,
    comm_fn: CommFn,
    num_steps_per_communication: int = 1,
) -> optax.GradientTransformation:
    """AWC: ``params + updates == comm(params) + base_updates`` — combine and
    adapt simultaneously (``DistributedAdaptWithCombineOptimizer`` [U])."""
    maybe_comm = _every_k(comm_fn, num_steps_per_communication)

    def init(params):
        return GossipState(base=base.init(params), step=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("AWC requires params")
        updates, base_state = base.update(grads, state.base, params)
        combined = maybe_comm(params, state.step)
        out = jax.tree_util.tree_map(
            lambda c, u, p: (c + u - p).astype(p.dtype), combined, updates, params
        )
        return out, GossipState(base=base_state, step=state.step + 1)

    return optax.GradientTransformation(init, update)


def gradient_allreduce_spmd(
    base: optax.GradientTransformation,
    axis_name: str = NODES_AXIS,
    num_steps_per_communication: int = 1,
) -> optax.GradientTransformation:
    """Horovod-equivalent synchronous DP: average gradients globally before
    the base update (``DistributedGradientAllreduceOptimizer`` [U])."""
    comm = _every_k(lambda g: ops_spmd.allreduce(g, axis_name, average=True),
                    num_steps_per_communication)

    def init(params):
        return GossipState(base=base.init(params), step=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        avg = comm(grads, state.step)
        updates, base_state = base.update(avg, state.base, params)
        return updates, GossipState(base=base_state, step=state.step + 1)

    return optax.GradientTransformation(init, update)


# --------------------------------------------------------------------------
# Parity classes — eager, rank-major
# --------------------------------------------------------------------------


def _state_specs(state, size, axis_spec):
    """Per-leaf partition specs for optimizer state: leaves mirroring
    rank-major params (leading dim == size) shard over ranks; scalars such
    as optax step counts stay replicated."""
    return jax.tree_util.tree_map(
        lambda x: axis_spec
        if getattr(x, "ndim", 0) >= 1 and x.shape[0] == size
        else P(),
        state,
    )


class _EagerDistributedOptimizer:
    """Shared machinery: jit-compiled rank-major step over the global mesh."""

    _mode = "atc"

    def __init__(
        self,
        base_optimizer: optax.GradientTransformation,
        communication_type: CommunicationType = CommunicationType.neighbor_allreduce,
        num_steps_per_communication: int = 1,
    ):
        self.base = base_optimizer
        self.communication_type = communication_type
        self.k = int(num_steps_per_communication)
        self._tx = None
        self._tx_key = None
        self._step_fns = {}

    def _transform(self) -> optax.GradientTransformation:
        ctx = basics.context()
        plan = ctx.plan
        mplan = (
            ctx.machine_plan
            if self.communication_type
            == CommunicationType.hierarchical_neighbor_allreduce
            else None
        )
        key = (plan, mplan)
        if self._tx_key != key:
            comm_fn = make_spmd_comm_fn(self.communication_type, plan, mplan)
            builder = {
                "atc": adapt_then_combine_spmd,
                "awc": adapt_with_combine_spmd,
            }[self._mode]
            self._tx = builder(self.base, comm_fn, self.k)
            self._tx_key = key
        return self._tx

    def _mesh_specs(self):
        ctx = basics.context()
        if (
            self.communication_type
            == CommunicationType.hierarchical_neighbor_allreduce
        ):
            return ctx.hier_mesh, P((MACHINES_AXIS, LOCAL_AXIS))
        return ctx.mesh, P(NODES_AXIS)

    def init(self, params):
        """params: rank-major pytree ([size, ...] leaves).

        Runs the init eagerly on the global arrays: standard optax inits are
        elementwise (zeros_like etc.), so rank-major params produce
        rank-major state and replicated scalars directly.
        """
        return self._transform().init(params)

    def step(self, params, grads, state, plan: "CommPlan" = None):
        """One distributed step: returns (new_params, new_state).

        ``plan`` overrides the installed topology's plan for this call —
        the reference's *dynamic topology* optimizer path (one-peer
        rotations etc.).  Rotating through a small set of plans (e.g. the
        log(n) exp-2 one-peer permutations) reuses cached compilations.
        """
        if plan is not None:
            if self.communication_type != CommunicationType.neighbor_allreduce:
                raise ValueError("per-step plan override requires neighbor_allreduce")
            world = basics.context().size
            if plan.size != world:
                raise ValueError(
                    f"plan is for {plan.size} ranks, mesh has {world}"
                )

            def build_tx():
                comm_fn = make_spmd_comm_fn(self.communication_type, plan)
                builder = {
                    "atc": adapt_then_combine_spmd,
                    "awc": adapt_with_combine_spmd,
                }[self._mode]
                return builder(self.base, comm_fn, self.k)

            tx_key = (plan,)
        else:
            build_tx = self._transform
            tx_key = self._tx_key
        mesh, spec = self._mesh_specs()
        ctx = basics.context()
        state_spec = _state_specs(state, ctx.size, spec)
        key = (tx_key, jax.tree_util.tree_structure(state))

        if key not in self._step_fns:
            tx = build_tx()

            def whole(params, grads, state):
                updates, new_state = tx.update(grads, state, params)
                return optax.apply_updates(params, updates), new_state

            self._step_fns[key] = jax.jit(
                jax.shard_map(
                    whole,
                    mesh=mesh,
                    in_specs=(spec, spec, state_spec),
                    out_specs=(spec, state_spec),
                )
            )
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter(
                "optim.steps", optimizer=self._mode,
                comm=self.communication_type.name).inc()
        # the whole fused step is one dispatch, so the step span is the
        # BLUEFOG_TIMELINE signal here (per-op spans exist only on the
        # eager op path)
        with timeline_context(
            f"optimizer_step_{self._mode}_{self.communication_type.name}"
        ):
            return self._step_fns[key](params, grads, state)


class DistributedAdaptThenCombineOptimizer(_EagerDistributedOptimizer):
    """Reference ``bf.DistributedAdaptThenCombineOptimizer`` [U]."""

    _mode = "atc"


class DistributedAdaptWithCombineOptimizer(_EagerDistributedOptimizer):
    """Reference ``bf.DistributedAdaptWithCombineOptimizer`` [U]."""

    _mode = "awc"


class DistributedGradientAllreduceOptimizer(_EagerDistributedOptimizer):
    """Reference ``bf.DistributedGradientAllreduceOptimizer`` [U]."""

    def __init__(
        self,
        base_optimizer: optax.GradientTransformation,
        num_steps_per_communication: int = 1,
    ):
        super().__init__(
            base_optimizer,
            communication_type=CommunicationType.allreduce,
            num_steps_per_communication=num_steps_per_communication,
        )

    def _transform(self) -> optax.GradientTransformation:
        return gradient_allreduce_spmd(self.base, NODES_AXIS, self.k)


class DistributedWinPutOptimizer:
    """Asynchronous win-put optimizer (reference
    ``bf.DistributedWinPutOptimizer`` [U]): each step does a local adapt,
    deposits parameters to out-neighbors via ``win_put``, and merges the
    mailbox with ``win_update`` — no global reduction.

    Uses the window emulation, so the realized schedule is the synchronous
    one (see :mod:`bluefog_tpu.windows` docstring).
    """

    def __init__(
        self,
        base_optimizer: optax.GradientTransformation,
        window_prefix: str = "winput_opt",
        num_steps_per_communication: int = 1,
        fuse: bool = True,
    ):
        self.base = base_optimizer
        self.prefix = window_prefix
        self.k = int(num_steps_per_communication)
        self.fuse = fuse
        self._step_count = 0
        self._created = False
        self._groups = None  # fused mode: [leaf_indices] per dtype group

    def init(self, params):
        leaves = jax.tree_util.tree_leaves(params)
        if self.fuse:
            # Tensor fusion, TPU-style: the reference coalesced small tensors
            # into its fusion buffer on the background thread
            # (BLUEFOG_FUSION_THRESHOLD, SURVEY.md §3.2); here all leaves of a
            # dtype pack into ONE rank-major window so a whole model's
            # win_put+win_update is two dispatches instead of 2 x num_leaves.
            by_dtype: Dict[Any, list] = {}
            for i, leaf in enumerate(leaves):
                by_dtype.setdefault(jnp.asarray(leaf).dtype, []).append(i)
            self._groups = []
            for g, (_, idxs) in enumerate(
                sorted(by_dtype.items(), key=lambda kv: str(kv[0]))
            ):
                # a LIST of leaves is a pytree: windows fuses it into one
                # packed window and packs/unpacks inside the compiled
                # exchange programs (no separate pack dispatches here)
                if not windows.win_create(
                    [leaves[i] for i in idxs], f"{self.prefix}.fused{g}"
                ):
                    raise RuntimeError(
                        f"window '{self.prefix}.fused{g}' already exists — "
                        f"two optimizers share window_prefix={self.prefix!r}, "
                        "or a prior instance was not win_free'd"
                    )
                self._groups.append(idxs)
        else:
            for i, leaf in enumerate(leaves):
                if not windows.win_create(leaf, f"{self.prefix}.{i}"):
                    raise RuntimeError(
                        f"window '{self.prefix}.{i}' already exists — two "
                        f"optimizers share window_prefix={self.prefix!r}, "
                        "or a prior instance was not win_free'd"
                    )
        self._created = True
        return self.base.init(params)

    def step(self, params, grads, state):
        ctx = basics.context()
        mesh = ctx.mesh

        def local(params, grads, state):
            updates, new_state = self.base.update(grads, state, params)
            return optax.apply_updates(params, updates), new_state

        key = ("local", jax.tree_util.tree_structure(state))
        if not hasattr(self, "_fns"):
            self._fns = {}
        if key not in self._fns:
            sspec = _state_specs(state, ctx.size, P(NODES_AXIS))
            self._fns[key] = jax.jit(
                jax.shard_map(
                    local,
                    mesh=mesh,
                    in_specs=(P(NODES_AXIS), P(NODES_AXIS), sspec),
                    out_specs=(P(NODES_AXIS), sspec),
                )
            )
        adapted, state = self._fns[key](params, grads, state)
        self._step_count += 1
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("optim.steps", optimizer="winput").inc()
        if self._step_count % self.k == 0:
            if reg.enabled:
                reg.counter("optim.gossip_rounds", optimizer="winput").inc()
            flat, treedef = jax.tree_util.tree_flatten(adapted)
            if self.fuse:
                for g, idxs in enumerate(self._groups):
                    name = f"{self.prefix}.fused{g}"
                    parts = windows.win_put_update(
                        [flat[i] for i in idxs], name
                    )
                    for i, part in zip(idxs, parts):
                        flat[i] = part
            else:
                for i, leaf in enumerate(flat):
                    name = f"{self.prefix}.{i}"
                    windows.win_put(leaf, name)  # also refreshes the exposure
                    flat[i] = windows.win_update(name)
            adapted = jax.tree_util.tree_unflatten(treedef, flat)
        return adapted, state

    def close(self):
        """API parity with the island optimizer's ``close()``: the
        emulation has no background pipeline to drain, so this is a
        documented no-op — teardown code written against the island
        surface (``finish``/``close``/``free``) runs unchanged here."""

    def finish(self, params):
        """Parity with the island optimizer: no overlap pipeline to
        apply, so the params come back unchanged (after ``close``)."""
        self.close()
        return params

    def free(self):
        self.close()
        if self._created:
            ctx = basics.context()
            for name in [n for n in ctx.windows if n.startswith(self.prefix + ".")]:
                windows.win_free(name)
            self._created = False


def one_peer_plan_schedule(size: int):
    """The exp-2 one-peer rotation as a list of CommPlans to cycle through
    (``opt.step(..., plan=plans[t % len(plans)])``) — the compiled-variant
    set SURVEY.md §7 prescribes for dynamic topologies (each plan is a
    single ppermute; log2(n) distinct compilations total)."""
    import math as _math

    from bluefog_tpu.core.plan import plan_from_neighbor_lists
    from bluefog_tpu.topology_util import GetDynamicOnePeerSendRecvRanks

    if size <= 1:
        return [plan_from_neighbor_lists(size, [[] for _ in range(size)])]
    nbits = max(1, int(_math.ceil(_math.log2(size))))
    gens = [GetDynamicOnePeerSendRecvRanks(size, r) for r in range(size)]
    return [
        plan_from_neighbor_lists(size, [next(g)[1] for g in gens])
        for _ in range(nbits)
    ]


# --------------------------------------------------------------------------
# Parameter/state broadcast helpers
# --------------------------------------------------------------------------


def broadcast_parameters(params, root_rank: int = 0):
    """Give every rank the root's parameters (reference
    ``bf.broadcast_parameters`` [U]) — consistent initialization."""
    return ops.broadcast(params, root_rank=root_rank)


def broadcast_optimizer_state(state, root_rank: int = 0):
    """Reference ``bf.broadcast_optimizer_state`` [U]."""
    return jax.tree_util.tree_map(
        lambda x: ops.broadcast(x, root_rank=root_rank)
        if hasattr(x, "shape") and getattr(x, "ndim", 0) >= 1
        else x,
        state,
    )
