"""Intra-step attribution tools (round-2 verdict missing #4).

The reference's timeline stamps per-tensor NEGOTIATING/COMMUNICATING
spans from its background loop (``bluefog/common/timeline.cc`` [U]);
under XLA one jitted step is one opaque span, so attribution works
differently: compare COMPILED COSTS between program variants, and time
program segments with the dispatch-amortized slope protocol.  This
module turns both hand-run techniques (docs/STATUS.md round 3: the
ResNet fwd/bwd/step decomposition, the peaks measurement) into tools.

- :func:`slope_time` — per-call wall time as the slope between two call
  counts (per-run sync RTT cancels; per-call dispatch is included — the
  honest number for step-level segments).
- :func:`slope_time_fused` — the microkernel form: iterations inside ONE
  jitted ``fori_loop``, so dispatch amortizes too (peaks methodology).
- :func:`segment_times` — slope-time a dict of named jitted segments
  (e.g. fwd / fwd+bwd / full step) in one sweep: the decomposition that
  pinned the ResNet ceiling.
- :func:`cost_summary` — XLA's compiled cost analysis (flops, bytes
  accessed) for a jitted fn.  NOTE: ``bytes accessed`` counts operand
  bytes per HLO op and OVERCOUNTS real HBM traffic under fusion — valid
  for program-to-program DELTAS, invalid as a roofline floor (that
  mistake is retracted in docs/STATUS.md).
- :func:`cost_delta` — the delta form: what did this change add/remove.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Mapping, Sequence, Tuple

import jax

from bluefog_tpu.ops import device_sync

__all__ = ["slope_time", "slope_time_fused", "segment_times",
           "cost_summary", "cost_delta"]


def slope_time(fn: Callable, args: Sequence = (), *, iters_lo: int = 3,
               iters_hi: int = 13, repeats: int = 2) -> float:
    """Per-call wall seconds of ``fn(*args)`` as the slope
    ``(T(iters_hi) - T(iters_lo)) / (iters_hi - iters_lo)``, each T the
    best of ``repeats`` timed runs (queued async calls, one
    ``device_sync`` at the end).

    What cancels: the per-RUN sync/fetch RTT (3.5–200 ms per session
    through the benched tunnel).  What does NOT cancel: the per-CALL
    dispatch cost (~1.8 ms marginal there) — each iteration is a real
    eager call, so the slope measures compute + per-call dispatch.  That
    is the honest number for step-level segments (a training step pays
    dispatch every call); for sub-ms MICROKERNELS it is dispatch-biased
    — use :func:`slope_time_fused`, which loops inside ONE jitted
    program (the benchmarks/peaks.py methodology).  Either way, size the
    span so the compute delta well exceeds per-run noise (a few ms)."""
    if iters_hi <= iters_lo:
        raise ValueError(f"iters_hi ({iters_hi}) must exceed iters_lo "
                         f"({iters_lo})")

    def timed(k: int) -> float:
        out = fn(*args)
        device_sync(out)  # compile + settle outside the timed region
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(k):
                out = fn(*args)
            device_sync(out)
            best = min(best, time.perf_counter() - t0)
        return best

    return (timed(iters_hi) - timed(iters_lo)) / (iters_hi - iters_lo)


def slope_time_fused(body: Callable, x, *, iters_lo: int = 4,
                     iters_hi: int = 24, repeats: int = 2) -> float:
    """Per-iteration seconds of ``x -> body(x)`` with the loop INSIDE one
    jitted ``lax.fori_loop`` — per-call dispatch amortizes to ~0, so this
    is the microkernel form (how benchmarks/peaks.py measures the chip's
    peaks).  ``body`` must be carry-compatible (same shape/dtype out)."""
    from jax import lax

    def make(k):
        @jax.jit
        def run(x):
            return lax.fori_loop(0, k, lambda _, y: body(y), x)

        return run

    lo = slope_time(make(iters_lo), (x,), iters_lo=1, iters_hi=2,
                    repeats=repeats)
    hi = slope_time(make(iters_hi), (x,), iters_lo=1, iters_hi=2,
                    repeats=repeats)
    return (hi - lo) / (iters_hi - iters_lo)


def segment_times(segments: Mapping[str, Tuple[Callable, Sequence]],
                  **slope_kwargs) -> Dict[str, float]:
    """Slope-time every named segment; returns {name: seconds}.

    The intra-step attribution recipe: pass e.g. ``{"fwd": (fwd_fn, a),
    "fwd_bwd": (grad_fn, a), "full_step": (step_fn, b)}`` and read the
    differences — optimizer+gossip+dispatch = full_step − fwd_bwd, etc.
    """
    return {name: slope_time(fn, args, **slope_kwargs)
            for name, (fn, args) in segments.items()}


def _compiled(fn: Callable, args: Sequence):
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    return jitted.lower(*args).compile()


def cost_summary(fn: Callable, args: Sequence = ()) -> Dict[str, float]:
    """XLA cost analysis of the compiled program: ``flops`` and
    ``bytes_accessed`` (operand-byte count — see the module docstring
    caveat), plus every other scalar XLA reports."""
    analysis = _compiled(fn, args).cost_analysis()
    if isinstance(analysis, (list, tuple)):  # older jax returns [dict]
        analysis = analysis[0]
    return {k: float(v) for k, v in analysis.items()
            if isinstance(v, (int, float))}


def cost_delta(fn_a: Callable, fn_b: Callable, args_a: Sequence = (),
               args_b: Sequence = ()) -> Dict[str, float]:
    """``cost_summary(fn_b) - cost_summary(fn_a)`` per key — the honest
    use of XLA's cost model: attribute what a CHANGE adds (a layer, a
    gossip edge, an optimizer), where the fusion overcount cancels to
    first order."""
    a = cost_summary(fn_a, args_a)
    b = cost_summary(fn_b, args_b)
    return {k: b.get(k, 0.0) - a.get(k, 0.0) for k in sorted(set(a) | set(b))}
