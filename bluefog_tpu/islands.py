"""Asynchronous islands — true one-sided window ops across processes.

The single-controller emulation (:mod:`bluefog_tpu.windows`) realizes the
*synchronous schedule* of asynchronous algorithms: all ranks live in one
process and deposits land at collective exchange points.  This module is the
documented stretch beyond that (SURVEY.md §7 stage 5): each rank is its own
OS process — an **island** with its own JAX controller and devices — and
window deposits travel through a native shared-memory mailbox
(``native/shm_mailbox.cc``) with genuine passive-target semantics: a
``win_put`` completes with NO participation by the receiver, ranks step at
their own pace, and staleness is whatever the wall clock makes it — exactly
the reference's MPI RMA model (``MPI_Win_lock/Put/flush`` in
``bluefog/common/mpi_controller.cc`` [U]; SURVEY.md §3.4).

Scope: islands cover the reference's *window* op family (the asynchronous
algorithms), plus ``barrier`` and a REAL ``win_mutex`` (shared-memory locks —
the emulation's no-op shim is only valid when there are no concurrent
writers; islands have them).  Synchronous collectives stay with the
single-controller SPMD path, which is strictly better for them.  On a
multi-host TPU pod each island is one host process (the deployment the
reference runs one MPI rank per GPU); shared memory is the intra-host
transport, and the same mailbox protocol over DCN is the documented
extension point.

API shape matches ``bluefog_tpu.windows`` rank-locally: tensors here are
THIS rank's tensor (no leading ``size`` axis), and weight arguments are
plain ``{rank: weight}`` dicts — the reference's per-process convention.

Mass conservation: ``win_accumulate`` + ``win_update_then_collect`` use the
transport's atomic read+zero ``collect``, so asynchronous push-sum conserves
Σx and Σp under ANY interleaving — the property the reference gets from MPI
atomicity and that makes x/p debiasing converge to the exact average.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import networkx as nx
import numpy as np

from bluefog_tpu import progress as _progress
from bluefog_tpu import topology_util
from bluefog_tpu.native import shm_native
from bluefog_tpu.resilience import adaptive as _adaptive
from bluefog_tpu.resilience import degraded as _degraded
from bluefog_tpu.resilience import healing as _healing
from bluefog_tpu.resilience import join as _join
from bluefog_tpu.resilience import quorum as _quorum
from bluefog_tpu.resilience.detector import (
    _EDGE_STATE_CODE,
    EDGE_ALIVE,
    FailureDetector,
)
from bluefog_tpu.resilience.quorum import OrphanedError
from bluefog_tpu.telemetry import registry as _telemetry
from bluefog_tpu.timeline import timeline_context
from bluefog_tpu.tracing import tracer as _tracing

__all__ = [
    "init",
    "shutdown",
    "initialized",
    "rank",
    "size",
    "barrier",
    "set_topology",
    "load_topology",
    "in_neighbor_ranks",
    "out_neighbor_ranks",
    "win_create",
    "win_free",
    "win_put",
    "win_accumulate",
    "win_get",
    "win_update",
    "win_put_async",
    "win_accumulate_async",
    "win_update_async",
    "progress_engine",
    "win_absorbed",
    "win_update_then_collect",
    "win_sync",
    "win_mutex",
    "win_associated_p",
    "win_set_exposed",
    "push_sum_round",
    "broadcast",
    "broadcast_parameters",
    "DistributedWinPutOptimizer",
    "get_win_version",
    "turn_on_win_ops_with_associated_p",
    "turn_off_win_ops_with_associated_p",
    "dead_ranks",
    "heal",
    "resilience_detector",
    "global_rank",
    "members",
    "membership_epoch",
    "join",
    "admit_pending",
    "adaptive_step",
    "adaptive_policy",
    "demoted_ranks",
    "OrphanedError",
    "is_orphaned",
    "merge_orphan",
    "serve_publish",
    "spawn",
]

WeightDict = Optional[Dict[int, float]]


class _IslandWindow:
    def __init__(self, name: str, tensor: np.ndarray, ctx: "_IslandContext",
                 zero_init: bool):
        topo = ctx.topology
        self.name = name
        self.in_neighbors: List[int] = sorted(topo.predecessors(ctx.rank))
        self.out_neighbors: List[int] = sorted(topo.successors(ctx.rank))
        # slot order at EVERY rank must be derivable by every writer: slot k
        # of rank d is d's k-th in-neighbor in ascending rank order (the
        # reference's per-writer registered-buffer model, SURVEY §2.4)
        self.slot_of: Dict[int, Dict[int, int]] = {
            d: {s: k for k, s in enumerate(sorted(topo.predecessors(d)))}
            for d in topo.nodes
        }
        maxd = max((len(v) for v in self.slot_of.values()), default=0)
        self.self_tensor = np.array(tensor, copy=True)
        self.p_self = 1.0
        self._scratch: Optional[np.ndarray] = None  # win_update staging
        self._tel_cache = None  # (registry, {key: metric handle}) memo
        # last trace-context word consumed per slot: a combine that finds
        # the word unchanged consumed no NEW deposit on that edge, so no
        # duplicate flow arrow is recorded
        self._trace_seen: Dict[int, int] = {}
        # adaptive edge-health probe state: slot -> (version, time the
        # version last CHANGED, miss already counted for this gap) — an
        # unchanged version past the edge deadline is ONE deadline miss
        # per gap (resilience/adaptive.py)
        self._edge_seen: Dict[int, Tuple[int, float, bool]] = {}
        # GLOBAL ranks the most recent combine dropped via the
        # round-local ABSORB (read back by win_absorbed: a synchronous
        # caller treats an absorbed edge as handled for this round)
        self._last_absorbed: Tuple[int, ...] = ()
        # writer-side deposit tally per destination, and the version the
        # creation seed left in each slot: together they let heal()
        # settle the ledger for a dead peer (adopt its lost writer-side
        # counts, write off deposits it will never combine)
        self._deposited_to: Dict[int, int] = {}
        self._seed_ver = 0 if zero_init else 1
        # progress-engine prefetch state: per-slot persistent warm buffer
        # + the slot version it holds.  The idle worker re-reads a slot
        # (read-only, no collect — zero semantic/mass effect) only when
        # its deposit count moved, leaving the mailbox pages cache-warm
        # for the caller's next combine.
        self._warm: Dict[int, np.ndarray] = {}
        self._warm_ver: Dict[int, int] = {}
        self.shm = shm_native.make_window(
            ctx.job, name, ctx.rank, ctx.size, maxd,
            tensor.shape, tensor.dtype,
        )
        # windows are created collectively (like MPI_Win_create): barrier so
        # every rank's segment view exists before anyone deposits.  Unless
        # zero_init, each rank seeds its OWN slots with its OWN tensor (the
        # reference initializes every in-neighbor buffer from the local
        # value so a pre-put win_update is a no-op average — see
        # windows._Window).
        self.shm.expose(self.self_tensor, self.p_self)
        if not zero_init:
            for k, s in enumerate(self.in_neighbors):
                self.shm.write(ctx.rank, k, tensor, p=1.0, writer=s)
        # mass-ledger bookkeeping (telemetry conservation invariant): slot
        # ``version`` is a monotone deposit count; ``_ledger_seen[slot]`` is
        # the last version this reader retired (collected/drained/pending).
        # The seed writes above are pre-retired — they are not deposits any
        # writer counted.
        self._ledger_seen: Dict[int, int] = {
            k: (0 if zero_init else 1)
            for k in range(len(self.in_neighbors))
        }
        ctx.shm_job.barrier()


class _IslandContext:
    def __init__(self, rank_: int, size_: int, job: str):
        self.rank = rank_
        self.size = size_
        self.job = job
        self.topology: nx.DiGraph = _default_topology(size_)
        self.windows: Dict[str, _IslandWindow] = {}
        self.created_names: set = set()  # for shm unlink at shutdown
        self.win_fusion: Dict[str, object] = {}  # name -> pytree pack meta
        self.associated_p = False
        self.shm_job = shm_native.make_job(job, rank_, size_)
        # resilience state: the detector heartbeats in the background on
        # transports exposing liveness words (shm native/fallback, tcp
        # leases); ``dead`` is the excised-rank set the degraded win ops
        # consult, populated by heal()
        self.detector = FailureDetector(self.shm_job, rank_, size_).start()
        self.dead: set = set()
        self.healed: Optional[_healing.HealedTopology] = None
        # quorum fencing (resilience/quorum.py): True once this rank
        # lost a strict-majority live view and quiesced — windows go
        # read-only, healing stops, merge_orphan() is the way back
        self.orphaned = False
        # elastic membership (resilience/join.py): epoch 0 is the launch
        # view, where local and global ranks coincide.  After an epoch
        # switch ``rank``/``size``/``job`` describe the CURRENT epoch's
        # dense world while these fields keep the stable identity.
        self.base_job = job
        self.epoch = 0
        self.global_rank = rank_
        self.members_global: Tuple[int, ...] = tuple(range(size_))
        # adaptive topology (resilience/adaptive.py): the edge-health
        # policy OUTLIVES epoch switches (it is keyed by global rank and
        # holds the hysteresis clocks), unlike the per-epoch detector.
        # ``demoted`` is the degree-capped global-rank set of the current
        # reweight record; ``base_edges`` the pre-demotion global edge
        # list a promote restores.
        self.adaptive: Optional[_adaptive.AdaptivePolicy] = (
            _adaptive.AdaptivePolicy() if _adaptive.adaptive_enabled()
            else None)
        self.demoted: set = set()
        self.base_edges: Optional[List[Tuple[int, int]]] = None
        _attach_edge_health(self)
        # live introspection plane (bluefog_tpu.introspect): the status
        # page and the trace-control poller are keyed by the STABLE
        # identity (base job + global rank), so an attached bftpu-top
        # survives the epoch switches adaptive demotions trigger
        self.statuspage = None
        self.tracectl = None
        self.op_rounds = 0
        # convergence observatory (bluefog_tpu.lab): per-window probes,
        # created lazily on the first win_update so the env decision is
        # made after spawn() has propagated the lab env keys to workers.
        # None = not yet checked, False = probe disabled, dict = live.
        self.lab_probes = None
        self.conv_err = -1.0
        self.conv_round = -1
        # per-rank background progress engine (bluefog_tpu.progress),
        # created lazily on the first *_async call so synchronous
        # programs never pay for the worker thread
        self.progress: Optional[_progress.ProgressEngine] = None
        # serving plane (bluefog_tpu.serve): the snapshot region this
        # rank publishes into (lazily created by serve_publish) and the
        # last committed version, mirrored onto the v5 status page
        self.serve_region = None
        self.serve_version = -1
        if shm_native.statuspage_enabled():
            from bluefog_tpu.introspect import statuspage as _statuspage

            try:
                self.statuspage = _statuspage.StatusPage(job, rank_)
                self.tracectl = _statuspage.TraceControl(job, rank_, size_)
            except OSError:
                self.statuspage = None  # read-only shm dir: run blind


def _trivial_graph() -> nx.DiGraph:
    g = nx.DiGraph()
    g.add_node(0)
    return g


def _default_topology(size_: int) -> nx.DiGraph:
    """The launch topology for an island fleet of ``size_``.

    Static default: exponential-2 (the paper's workhorse).  With
    ``BFTPU_LAB_AUTO_TOPOLOGY=1`` the choice is delegated to the lab's
    measured scaling laws (:func:`bluefog_tpu.lab.recommend`), sized by
    ``BFTPU_LAB_PAYLOAD_BYTES``; any failure there (no artifact, bad
    env) falls back to the static default — opting in to auto-topology
    must never be able to fail init."""
    if size_ <= 1:
        return _trivial_graph()
    if os.environ.get("BFTPU_LAB_AUTO_TOPOLOGY", "0").lower() in (
            "1", "true", "yes", "on"):
        try:
            from bluefog_tpu import lab as _lab

            payload = int(os.environ.get("BFTPU_LAB_PAYLOAD_BYTES",
                                         "1048576"))
            rec = _lab.recommend(size_, payload)
            return _lab.build_topology(rec["topology"], size_)
        except Exception:
            pass
    return topology_util.ExponentialTwoGraph(size_)


def _attach_edge_health(ctx: "_IslandContext") -> None:
    """Wire the (epoch-persistent) edge-health machine into the
    (per-epoch) failure detector, translating the detector's local
    ranks to the machine's global ids — death declarations must reach
    the machine (DEAD outranks SUSPECT, and is never floor-delayed)."""
    if ctx.adaptive is None:
        return
    members = ctx.members_global
    ctx.detector.edge_health = ctx.adaptive.health
    ctx.detector.to_peer = (
        lambda l: members[l] if 0 <= l < len(members) else l)


def _peer_global(ctx: "_IslandContext", local: int) -> int:
    m = ctx.members_global
    return m[local] if 0 <= local < len(m) else local


_context: Optional[_IslandContext] = None


def _ctx() -> _IslandContext:
    if _context is None:
        raise RuntimeError("islands not initialized; call islands.init() "
                           "(or launch via bftpu-run --islands N)")
    return _context


def init(rank_: Optional[int] = None, size_: Optional[int] = None,
         job: Optional[str] = None) -> None:
    """Join the island job.  Arguments default to the env the launcher sets
    (``BLUEFOG_ISLAND_RANK/SIZE/JOB``) — the analogue of ``bf.init()`` under
    ``bfrun`` reading MPI env [U]."""
    global _context
    if _context is not None:
        return
    if rank_ is None and os.environ.get("BLUEFOG_ISLAND_JOINER") == "1":
        # a launcher-spawned replacement/scale-out process (bftpu-run
        # --self-heal / --attach scale): rendezvous as a JOINER instead
        # of binding a launch rank — the script's init() call needs no
        # changes to run elastically
        join(job=job)
        return
    if rank_ is None or size_ is None:
        if "BLUEFOG_ISLAND_RANK" not in os.environ:
            raise RuntimeError(
                "islands.init() needs rank/size: either pass them explicitly "
                "or launch under `bftpu-run --islands N` (which sets "
                "BLUEFOG_ISLAND_RANK/SIZE/JOB), or use islands.spawn()"
            )
    r = int(os.environ["BLUEFOG_ISLAND_RANK"]) if rank_ is None else int(rank_)
    n = int(os.environ["BLUEFOG_ISLAND_SIZE"]) if size_ is None else int(size_)
    j = os.environ.get("BLUEFOG_ISLAND_JOB", "default") if job is None else job
    if not (0 <= r < n):
        raise ValueError(f"rank {r} out of range for size {n}")
    reg = _telemetry.get_registry()
    if reg.enabled:
        # spawn() passes rank/size/job as arguments, not env — point the
        # registry at the real identity so per-rank snapshot files do not
        # collide on the env-derived default (rank 0)
        reg.rank, reg.job = r, j
        reg.journal("island_init", size=n)
    tr = _tracing.get_tracer()
    if tr.enabled:
        # same identity handoff as telemetry, plus the SIGTERM flight-dump
        # handler and the per-rank flight ring at its final path
        tr.set_identity(r, n, j)
        tr.instant("island_init")
    _context = _IslandContext(r, n, j)
    try:
        # publish the elastic-membership board (idempotent, first writer
        # wins) so a later joiner can rendezvous; see resilience/join.py
        _join.MembershipBoard(j).ensure(n)
    except OSError:
        pass  # read-only shm dir: the job simply is not elastic
    _context.shm_job.barrier()


def shutdown(unlink: bool = False) -> None:
    """Leave the job; ``unlink=True`` (call on exactly one rank, after a
    barrier) removes the shm segments.

    Hierarchical transport: shared memory is only reachable from its own
    host, so each host group's leader additionally reclaims ITS host's
    segments regardless of ``unlink`` — a global rank cannot clean a
    remote /dev/shm.
    """
    global _context
    if _context is None:
        return
    ctx = _context
    if ctx.progress is not None:
        # the engine dies BEFORE the segments it deposits into: stop()
        # drains the remaining queue through the still-open windows
        ctx.progress.stop(drain=True)
        ctx.progress = None
    ctx.detector.stop()
    reg = _telemetry.get_registry()
    for w in ctx.windows.values():
        if reg.enabled:
            # windows still live at shutdown: whatever mass their slots
            # hold retires as "pending" (callers barrier before shutdown,
            # so on clean runs the deposits are all committed by now)
            _ledger_probe_pending(reg, w, ctx.rank)
        w.shm.close(unlink=False)
    names = list(ctx.created_names)
    ctx.windows.clear()
    ctx.shm_job.close(unlink=False)
    if ctx.statuspage is not None:
        ctx.statuspage.close(unlink=unlink)
        ctx.statuspage = None
    if ctx.serve_region is not None:
        ctx.serve_region.close(unlink=unlink)
        ctx.serve_region = None
    hostmap = os.environ.get("BLUEFOG_ISLAND_HOSTMAP")
    if hostmap:
        from bluefog_tpu.native.routed_transport import parse_hostmap

        hosts = parse_hostmap(hostmap, ctx.size)
        local = [r for r in range(ctx.size) if hosts[r] == hosts[ctx.rank]]
        if ctx.rank == local[0]:
            shm_native.unlink_all(f"{ctx.job}_h{hosts[ctx.rank]}", names)
    if unlink:
        shm_native.unlink_all(ctx.job, names)
    tr = _tracing.get_tracer()
    if tr.enabled:
        tr.write_buffer()
        tr.close()
    _context = None


def initialized() -> bool:
    return _context is not None


def rank() -> int:
    return _ctx().rank


def size() -> int:
    return _ctx().size


def barrier(timeout: Optional[float] = None) -> None:
    """Explicit global barrier (init/teardown/tests; the async hot loop
    never calls this — that is the point of islands).  With ``timeout``
    (seconds) the wait is bounded: TimeoutError if the barrier does not
    complete — the arrival is retracted, so a later barrier is unharmed.
    Raises TypeError on transports without timed-barrier support."""
    if timeout is None:
        _ctx().shm_job.barrier()
    else:
        _ctx().shm_job.barrier(timeout=timeout)


def set_topology(topo: nx.DiGraph) -> bool:
    """Install the virtual topology.  Must be called identically on every
    rank BEFORE creating windows (windows snapshot it, as upstream [U])."""
    ctx = _ctx()
    if ctx.windows:
        raise RuntimeError("set_topology with live windows: free them first "
                           "(windows snapshot their topology, as upstream)")
    ctx.topology = topo
    return True


def load_topology() -> nx.DiGraph:
    return _ctx().topology


def in_neighbor_ranks() -> List[int]:
    ctx = _ctx()
    return sorted(ctx.topology.predecessors(ctx.rank))


def out_neighbor_ranks() -> List[int]:
    ctx = _ctx()
    return sorted(ctx.topology.successors(ctx.rank))


# ---------------------------------------------------------------------------
# resilience: failure detection + topology healing (docs/RESILIENCE.md)
# ---------------------------------------------------------------------------


def resilience_detector() -> FailureDetector:
    """This rank's heartbeat failure detector (started at init on
    transports with liveness support)."""
    return _ctx().detector


def dead_ranks() -> set:
    """Ranks the failure detector currently considers dead (monotone:
    once declared, a rank stays dead for this job)."""
    return _ctx().detector.dead_ranks()


def is_orphaned() -> bool:
    """Whether this rank is in the ORPHAN quiesce (lost membership
    quorum; see docs/RESILIENCE.md "Orphan quiesce")."""
    return _ctx().orphaned


def _publish_orphan_page(ctx: "_IslandContext") -> None:
    """One final status-page publish carrying the ORPHAN flag — the
    page then freezes (the quiesced rank runs no more window ops), so
    an attached ``bftpu-top`` keeps showing the verdict."""
    page = ctx.statuspage
    if page is None:
        return
    from bluefog_tpu.introspect import statuspage as _statuspage

    reg = _telemetry.get_registry()
    try:
        page.publish(nranks=len(ctx.members_global), step=ctx.op_rounds,
                     epoch=ctx.epoch, op_id=ctx.op_rounds,
                     last_op="ORPHAN",
                     ledger=_ledger_totals(reg) if reg.enabled else None,
                     flags=_statuspage.FLAG_ORPHAN)
    except (OSError, ValueError):
        pass  # a reaped segment must never fail the quiesce itself


def _enter_orphan(ctx: "_IslandContext", live: int, total: int,
                  op: str) -> None:
    """The minority-side verdict: freeze instead of forking a second
    epoch lineage.  Idempotent — only the first denial transitions."""
    if ctx.orphaned:
        return
    ctx.orphaned = True
    reg = _telemetry.get_registry()
    if ctx.progress is not None:
        # park the engine exactly like an epoch switch does: the
        # in-flight op completes (or times out against the unreachable
        # side), queued ops stay queued until merge_orphan re-resolves
        # the world — no resume() until then
        try:
            ctx.progress.quiesce()
        except Exception:  # noqa: BLE001 - quiesce must not mask the verdict
            pass
    if reg.enabled:
        reg.counter("resilience.orphan_entered").inc()
        reg.journal("orphan_entered", epoch=ctx.epoch,
                    global_rank=ctx.global_rank, live=live, total=total,
                    op=op, **_ledger_totals(reg))
    tr = _tracing.get_tracer()
    if tr.enabled:
        tr.instant("orphan_entered", aux=live)
    _publish_orphan_page(ctx)


def _orphan_guard(ctx: "_IslandContext", op: str) -> None:
    """Raise the retriable :class:`OrphanedError` on any state-mutating
    window op while quiesced (reads of local state stay allowed)."""
    if ctx.orphaned:
        raise OrphanedError(
            f"{op}: this rank is ORPHANED (minority side of a "
            f"partition, membership epoch {ctx.epoch}); windows are "
            "read-only until merge_orphan() re-admits it",
            live=-1, total=len(ctx.members_global), epoch=ctx.epoch)


def _quorum_gate(ctx: "_IslandContext", dead: set, op: str) -> bool:
    """Quorum fence for heal/demote commits: True = the commit may
    proceed.  ``dead`` is the would-be local-rank dead set (this
    rank's view).  A denial enters the ORPHAN quiesce."""
    if not _quorum.quorum_enabled():
        return True
    total = len(ctx.members_global)
    live = total - len(set(ctx.dead) | set(dead))
    if _quorum.quorum_met(live, total):
        return True
    reg = _telemetry.get_registry()
    if reg.enabled:
        reg.counter("resilience.quorum_denied", op=op).inc()
        reg.journal("quorum_denied", op=op, live=live, total=total,
                    floor=_quorum.majority_floor(total), epoch=ctx.epoch)
    _enter_orphan(ctx, live, total, op)
    return False


def heal(dead=None, retiring=()):
    """Excise ``dead`` ranks (default: the detector's verdict) from the
    gossip: force-drain their mailbox slots (a writer that died
    mid-deposit committed zero mass — see DEPOSIT_COMMITS_AFTER_PAYLOAD),
    break any job mutex they held, and record them so every subsequent
    win op skips them and renormalizes its combine weights
    (mass-conserving degraded steps).  Returns the
    :class:`~bluefog_tpu.resilience.healing.HealedTopology` — survivor
    topology, doubly-stochastic W, and recompiled plan — or None when
    nothing is dead.

    ``retiring`` marks local ranks in ``dead`` whose PROCESS is alive —
    an orphan's abandoned identity, excised at merge-grant time
    (:func:`admit_pending`).  They are excised and drained like any
    corpse, but WITHOUT the crash-side ledger settlement: a crashed
    rank's registry died with it (so the survivor adopts its writer
    counts and writes off deposits it will never combine), while a
    retiring rank's registry lives on — it keeps its own writer counts
    and probes its quiesced inbox as pending in
    :func:`merge_orphan`, so settling its sides here would
    double-count both legs of the conservation identity.

    Idempotent and rank-local: every survivor calls it on its own
    schedule; no collective required (there is no one left to
    coordinate with — that is the failure mode being handled).

    Quorum-fenced (``BFTPU_QUORUM``, default ``majority``): the heal
    only commits when this rank still sees a strict majority of the
    membership epoch as live.  A minority view is a partition, not a
    mass death — the rank enters the ORPHAN quiesce instead and the
    call returns None (docs/RESILIENCE.md "Orphan quiesce").
    """
    ctx = _ctx()
    reg = _telemetry.get_registry()
    t0 = time.perf_counter_ns() if reg.enabled else 0
    dead = set(ctx.detector.dead_ranks() if dead is None else dead)
    if not dead:
        return ctx.healed
    if ctx.orphaned or not _quorum_gate(ctx, dead, "heal"):
        # quorum fence (BFTPU_QUORUM): a rank that cannot account for
        # a strict majority as live is the MINORITY side of a
        # partition, not a survivor — it must not excise "corpses"
        # that are actually healthy ranks across the cut.  No state
        # was mutated; merge_orphan() is the way back.
        return None
    for r in dead:
        ctx.detector.declare_dead(r)
    new = dead - ctx.dead
    ctx.dead |= dead
    for r in sorted(new):
        # a rank that died holding a mutex must not wedge win_mutex
        breaker = getattr(ctx.shm_job, "mutex_break", None)
        if breaker is not None:
            breaker(r)
    retiring = set(retiring)
    adopted = written_off = 0
    for win in ctx.windows.values():
        if reg.enabled:
            # the corpse's registry died with it, so BOTH sides of its
            # edges must be settled from the survivor side or the global
            # conservation identity (deposits == collected + drained +
            # pending over the live registries) breaks:
            # - edges corpse->me: ADOPT its lost writer-side count — the
            #   slot version is the monotone deposit count, minus the
            #   creation seed;
            # - edges me->corpse: WRITE OFF my deposits it will never
            #   combine — they leave live circulation as pending.
            # A RETIRING identity gets neither: its live registry keeps
            # the writer counts, and merge_orphan probes its inbox.
            rv = getattr(win.shm, "read_version", None)
            for s in win.in_neighbors:
                if s in new and s not in retiring and rv is not None:
                    try:
                        v = int(rv(win.slot_of[ctx.rank][s], src=s))
                    except Exception:  # noqa: BLE001 - accounting only
                        v = win._seed_ver
                    if v > win._seed_ver:
                        adopted += v - win._seed_ver
            for r in new:
                if r in retiring:
                    win._deposited_to.pop(r, None)
                else:
                    written_off += win._deposited_to.pop(r, 0)
        drain = getattr(win.shm, "force_drain", None)
        if drain is None:
            continue
        for s in win.in_neighbors:
            if s in new:
                slot = win.slot_of[ctx.rank][s]
                if reg.enabled:
                    _ledger_retire_probe(
                        reg, win, slot, s, _telemetry.LEDGER_DRAINED)
                drain(slot, src=s)
    if reg.enabled:
        if adopted:
            reg.counter(_telemetry.LEDGER_DEPOSITS).add(adopted)
        if written_off:
            reg.counter(_telemetry.LEDGER_PENDING).add(written_off)
    ctx.healed = _healing.heal_topology(ctx.topology, sorted(ctx.dead))
    tr = _tracing.get_tracer()
    if tr.enabled and new:
        for r in sorted(new):
            tr.instant("heal", aux=r)
    if reg.enabled and new:
        dt = (time.perf_counter_ns() - t0) / 1e9
        reg.counter("resilience.heals").inc()
        reg.histogram("resilience.heal_s").observe(dt)
        reg.journal("heal", new_dead=sorted(new), dead=sorted(ctx.dead),
                    duration_s=dt, ledger_adopted=adopted,
                    ledger_written_off=written_off)
    return ctx.healed


# ---------------------------------------------------------------------------
# elastic membership: rank join + epoch switch (resilience/join.py;
# docs/RESILIENCE.md "Elastic membership")
# ---------------------------------------------------------------------------


def global_rank() -> int:
    """This rank's stable global identity.  Equal to :func:`rank` in the
    launch epoch; after membership changes :func:`rank` is the dense
    epoch-local rank while the global rank never changes (and a dead
    rank's global id is never reissued)."""
    return _ctx().global_rank


def members() -> Tuple[int, ...]:
    """Sorted global ranks of the current membership epoch."""
    return tuple(_ctx().members_global)


def membership_epoch() -> int:
    """The membership epoch this rank is currently participating in."""
    return _ctx().epoch


def _ledger_totals(reg) -> Dict[str, float]:
    return {
        "deposits": reg.counter(_telemetry.LEDGER_DEPOSITS).value,
        "collected": reg.counter(_telemetry.LEDGER_COLLECTED).value,
        "drained": reg.counter(_telemetry.LEDGER_DRAINED).value,
        "pending": reg.counter(_telemetry.LEDGER_PENDING).value,
    }


def _live_global_graph(ctx: "_IslandContext") -> nx.DiGraph:
    """The current topology restricted to live members, in GLOBAL rank
    labels — the graph :func:`grow_topology` splices joiners into."""
    mapping = {l: ctx.members_global[l] for l in range(ctx.size)
               if l not in ctx.dead}
    G = nx.DiGraph()
    G.add_nodes_from(sorted(mapping.values()))
    for u, v in ctx.topology.edges:
        if u != v and u in mapping and v in mapping:
            G.add_edge(mapping[u], mapping[v])
    return G


def _windows_meta(ctx: "_IslandContext") -> List[dict]:
    return [{"name": n,
             "shape": [int(d) for d in ctx.windows[n].shm.shape],
             "dtype": str(np.dtype(ctx.windows[n].shm.dtype))}
            for n in sorted(ctx.windows)]


def _switch_epoch(ctx: "_IslandContext", rec: dict) -> None:
    """Member side of the epoch switch: retire outstanding mailbox mass,
    journal the ledger balance AT the switch (the membership-epoch
    audit point), close the old epoch's segments, and rebind into the
    epoch-suffixed namespace with the committed topology and windows.

    Old-epoch segments are left for crashed-run hygiene to reclaim (the
    designated unlink rank of the old epoch may be exactly the corpse
    being replaced); ``unlink_all``'s job-prefix glob catches every
    epoch's segments.
    """
    reg = _telemetry.get_registry()
    tr = _tracing.get_tracer()
    t0 = time.perf_counter_ns()
    if ctx.progress is not None:
        # park the progress engine FIRST: the in-flight op completes into
        # the old epoch's segments (its mass is then probed as pending or
        # already committed below), and queued ops survive the rebind —
        # they resolve their window by NAME at execution time, so after
        # resume() they land in the new epoch's segments.  No op is lost
        # or double-executed (the progress.queue-state-machine rule).
        ctx.progress.quiesce()
    if rec.get("reweight"):
        # QUIESCE before probing: an adaptive reweight switches a fleet
        # where every member is alive and mid-gossip — a deposit landing
        # after my pending-probe but before the peer switches would
        # vanish from the ledger.  Barriering the OLD epoch first orders
        # every member's last old-epoch write before every member's
        # probe, so the switch-point ledger balances deterministically.
        # (The join/death path cannot do this: its old epoch may contain
        # a corpse that will never arrive.)
        ctx.shm_job.barrier()
    saved: Dict[str, Tuple[np.ndarray, float]] = {}
    for name, w in ctx.windows.items():
        if reg.enabled:
            # deposits still sitting in slots cross the epoch boundary as
            # "pending" — never silently: the conservation identity
            # deposits == collected + drained + pending must hold AT the
            # switch (the resilience.membership-epoch rule checks it)
            _ledger_probe_pending(reg, w, ctx.rank)
        saved[name] = (np.array(w.self_tensor, copy=True), float(w.p_self))
    if reg.enabled:
        reg.journal("epoch_switch", old_epoch=ctx.epoch,
                    new_epoch=int(rec["epoch"]),
                    global_rank=ctx.global_rank,
                    joined=list(rec.get("joined", ())),
                    demoted=list(rec.get("demoted", ())),
                    **_ledger_totals(reg))
    ctx.detector.stop()
    for w in ctx.windows.values():
        w.shm.close(unlink=False)
    ctx.shm_job.close(unlink=False)

    new_members = tuple(int(m) for m in rec["members"])
    new_local = new_members.index(ctx.global_rank)
    m = len(new_members)
    ejob = _join.epoch_job(ctx.base_job, int(rec["epoch"]))
    ctx.rank = new_local
    ctx.size = m
    ctx.job = ejob
    ctx.epoch = int(rec["epoch"])
    ctx.members_global = new_members
    ctx.topology = _join.record_graph(rec)
    ctx.dead = set()
    ctx.healed = None
    # reweight records carry the adaptive state forward; any other kind
    # (a join grant re-splices the graph) resets it — the persistent
    # edge-health machine will simply re-demote a still-slow rank
    old_demoted = set(ctx.demoted)
    ctx.demoted = set(int(g) for g in rec.get("demoted", ()))
    if ctx.adaptive is not None and rec.get("reweight"):
        # start the commit floor for every peer whose standing changed,
        # and adopt the committer's promote verdicts: a non-anchor's
        # machine was starved of observations during the demotion and
        # would otherwise re-demote on its stale SUSPECT state
        changed = (old_demoted ^ ctx.demoted) \
            | set(int(g) for g in rec.get("promoted", ()))
        ctx.adaptive.note_epoch_change(changed)
        for g in rec.get("promoted", ()):
            if int(g) != ctx.global_rank:
                ctx.adaptive.health.absolve(int(g))
    ctx.base_edges = ([(int(u), int(v)) for u, v in rec["base_edges"]]
                      if rec.get("base_edges") else None)
    ctx.windows = {}
    ctx.created_names = set()
    ctx.shm_job = shm_native.make_job(ejob, new_local, m)
    ctx.detector = FailureDetector(ctx.shm_job, new_local, m).start()
    _attach_edge_health(ctx)
    ctx.shm_job.barrier()  # every new-epoch member (joiners included)
    for wmeta in sorted(rec["windows"], key=lambda w: w["name"]):
        name = wmeta["name"]
        t, p = saved[name]
        win = _IslandWindow(name, t, ctx, zero_init=True)
        ctx.windows[name] = win
        ctx.created_names.add(name)
        if p != 1.0:
            # carry this member's push-sum mass across the epoch: the
            # fresh window exposed (t, 1.0); restore the true (t, p)
            win.p_self = p
            win.shm.expose(win.self_tensor, p)
        # re-seed my own slots with the restored (t, p) — the creation
        # contract (pre-put win_update is a no-op average); zero slots
        # would bleed into the first post-switch combines and destroy
        # the consensus value admission is supposed to preserve
        for k, s in enumerate(win.in_neighbors):
            win.shm.write(ctx.rank, k, win.self_tensor,
                          p=win.p_self, writer=s)
            win._ledger_seen[k] = 1
        win._seed_ver = 1
    ctx.shm_job.barrier()  # every (t, p) exposure restored — joiners
    ctx.shm_job.barrier()  # ... finished their onboarding reads
    if ctx.progress is not None:
        ctx.progress.resume()
    if tr.enabled:
        tr.instant("epoch_switch", aux=ctx.epoch)
    if reg.enabled:
        reg.counter("resilience.epoch_switches").inc()
        reg.histogram("resilience.epoch_switch_s").observe(
            (time.perf_counter_ns() - t0) / 1e9)


def admit_pending(timeout: Optional[float] = None):
    """Admit any pending join requests and switch the job to the next
    membership epoch.  Call at a round barrier on EVERY member (the
    natural spot is right after a combine); returns the committed epoch
    record, or None when nobody is waiting to join.

    The sponsor — the lowest live global rank — grants all pending
    requests in one atomic board commit (fresh ranks, grown topology,
    window metadata); every other member waits for the commit, then all
    members switch together (see :func:`_switch_epoch`).  If the
    sponsor dies mid-admission, the next-lowest live rank takes over —
    the board commit is idempotent, so a raced double-grant resolves to
    the first record.
    """
    ctx = _ctx()
    if ctx.orphaned:
        return None  # an orphan neither sponsors nor switches epochs
    board = _join.MembershipBoard(ctx.base_job)
    rec = None
    if shm_native.membership_epoch(ctx.base_job) > ctx.epoch:
        rec = board.epoch_record(ctx.epoch + 1)
    if rec is None:
        pend = board.pending_requests()
        if not pend:
            return None
        # a merging orphan names the identity it abandoned: excise it
        # exactly like a detector-confirmed corpse BEFORE granting —
        # its heartbeats only stopped at the merge, so the detector may
        # not have flagged it yet, and a grown view that includes it
        # would wait forever on the new-epoch barrier
        g2l = {g: l for l, g in enumerate(ctx.members_global)}
        stale = {g2l[int(r["retiring"])] for r in pend
                 if int(r.get("retiring", -1)) in g2l} - ctx.dead
        if stale:
            # retiring identities are excised WITHOUT the crash-side
            # ledger settlement (their live process settles its own
            # sides at merge — see heal's ``retiring`` contract)
            heal(set(ctx.detector.dead_ranks()) | stale, retiring=stale)
        elif ctx.detector.dead_ranks() - ctx.dead:
            heal()  # the grown view must not include a corpse
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.journal("join_requested_seen", epoch=ctx.epoch)
        deadline = time.monotonic() + (
            _degraded.op_deadline_s() if timeout is None else timeout)
        while rec is None:
            live = [ctx.members_global[l] for l in range(ctx.size)
                    if l not in ctx.dead]
            if ctx.global_rank == min(live) and board.pending_requests():
                rec = board.grant(
                    ctx.global_rank, live, _live_global_graph(ctx),
                    _windows_meta(ctx), ctx.associated_p, ctx.epoch)
                if rec is not None and reg.enabled:
                    reg.counter("resilience.joins_admitted").inc(
                        len(rec["joined"]))
                    reg.journal("join_admitted",
                                joined=list(rec["joined"]),
                                epoch=int(rec["epoch"]),
                                sponsor=ctx.global_rank)
                break
            rec = board.epoch_record(ctx.epoch + 1)
            if rec is not None:
                break
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"epoch {ctx.epoch + 1} not committed within the "
                    "deadline (is the sponsor calling admit_pending?)")
            # the sponsor may itself be the next corpse: refresh the
            # verdict so sponsorship falls through to the next-lowest
            if ctx.detector.dead_ranks() - ctx.dead:
                heal()
            time.sleep(_join.join_poll_s())
    if rec is None:
        return None
    _switch_epoch(ctx, rec)
    return dict(rec)


def join(job: Optional[str] = None, timeout: Optional[float] = None,
         retiring: int = -1):
    """Join a LIVE island job as a brand-new rank (the elastic scale-out
    entry point; call INSTEAD of :func:`init`).  Blocks until some
    member admits this process via :func:`admit_pending`, then binds
    the new epoch's segments, receives every live window's state from
    the sponsor over the exposed-window (broadcast) path, and returns
    the :class:`~bluefog_tpu.resilience.join.JoinGrant`.

    The joiner enters each window with **unit push-sum mass at the
    sponsor's debiased estimate** — Σx/Σp over the grown membership is
    the same value the survivors agreed on, so admission neither
    creates nor destroys mass (journaled per window as
    ``join_mass_admitted``; counter ``MASS_JOIN_ADMITTED``).

    ``retiring`` names a global rank this process is abandoning —
    :func:`merge_orphan` re-enters under a fresh rank while its
    quiesced old identity may still look alive to the majority; the
    request carries it so :func:`admit_pending` excises the old
    identity before granting (dead ids are never reissued).
    """
    global _context
    if _context is not None:
        raise RuntimeError("join(): this process is already a member "
                           "(join replaces init for new processes)")
    j = job if job is not None else os.environ.get("BLUEFOG_ISLAND_JOB")
    if not j:
        raise RuntimeError("join() needs the job name: pass job= or set "
                           "BLUEFOG_ISLAND_JOB")
    board = _join.MembershipBoard(j)
    req = board.post_request(retiring=retiring)
    grant = board.wait_for_grant(req, timeout)
    rec = grant.record
    reg = _telemetry.get_registry()
    if reg.enabled:
        reg.rank, reg.job = grant.rank, j
        reg.journal("join_granted", epoch=grant.epoch,
                    sponsor=grant.sponsor,
                    members=list(grant.members))
    tr = _tracing.get_tracer()
    if tr.enabled:
        tr.set_identity(grant.rank, grant.size, j)
        tr.instant("join_granted", aux=grant.epoch)
    ejob = _join.epoch_job(j, grant.epoch)
    ctx = _IslandContext(grant.local_rank, grant.size, ejob)
    ctx.topology = _join.record_graph(rec)
    ctx.base_job = j
    ctx.epoch = grant.epoch
    ctx.global_rank = grant.rank
    ctx.members_global = grant.members
    ctx.associated_p = bool(rec.get("associated_p", False))
    if ctx.statuspage is not None:
        # the context constructor keyed the page by (epoch job, local
        # rank); re-key by the stable identity bftpu-top attaches under
        from bluefog_tpu.introspect import statuspage as _statuspage

        ctx.statuspage.close(unlink=True)
        try:
            ctx.statuspage = _statuspage.StatusPage(j, grant.rank)
            ctx.tracectl = _statuspage.TraceControl(j, grant.rank,
                                                   grant.size)
        except OSError:
            ctx.statuspage = None
    _context = ctx
    ctx.shm_job.barrier()  # aligns with _switch_epoch's first barrier
    sponsor_local = grant.sponsor_local
    for wmeta in sorted(rec["windows"], key=lambda w: w["name"]):
        name = wmeta["name"]
        dt = np.dtype(wmeta["dtype"])
        win = _IslandWindow(name, np.zeros(tuple(wmeta["shape"]), dt),
                            ctx, zero_init=True)
        ctx.windows[name] = win
        ctx.created_names.add(name)
    ctx.shm_job.barrier()  # members restored their true (t, p) exposures
    for name in sorted(ctx.windows):
        win = ctx.windows[name]
        # onboarding = the broadcast idiom: one one-sided read of the
        # sponsor's exposure, debiased so the joiner enters at the value
        # the survivors agree on, with unit push-sum mass of its own
        a, p, _ = win.shm.read_exposed(sponsor_local)
        x = np.asarray(a / p if (ctx.associated_p and p > 0.0) else a,
                       dtype=win.shm.dtype)
        win.self_tensor = x
        win.p_self = 1.0
        win.shm.expose(x, 1.0)
        # seed my own slots with the entry value (creation contract: a
        # pre-put combine is a no-op average, never a mix with zeros)
        for k, s in enumerate(win.in_neighbors):
            win.shm.write(ctx.rank, k, x, p=1.0, writer=s)
            win._ledger_seen[k] = 1
        win._seed_ver = 1
        if reg.enabled:
            reg.counter(_telemetry.MASS_JOIN_ADMITTED).add(1.0)
            reg.journal("join_mass_admitted", window=name, p=1.0,
                        epoch=grant.epoch)
    ctx.shm_job.barrier()  # sponsor's exposure survived until here
    if reg.enabled:
        # the joiner's switch-point ledger is trivially balanced (all
        # zeros) but journaled anyway: the membership-epoch rule audits
        # EVERY member of the new view, joiners included
        reg.journal("epoch_switch", old_epoch=None,
                    new_epoch=grant.epoch, global_rank=grant.rank,
                    joined=list(rec.get("joined", ())),
                    **_ledger_totals(reg))
    if tr.enabled:
        tr.instant("join_complete", aux=grant.epoch)
    return grant


def merge_orphan(timeout: Optional[float] = None):
    """Re-enter the fleet after an ORPHAN quiesce (call when
    connectivity has returned): tear down the quiesced context and come
    back through the standard join machinery — membership-board lease →
    sponsor grant → fresh global rank → epoch switch — **carrying this
    rank's debiased estimate** into the new epoch.

    The majority side long since healed this rank away, settling both
    ledger sides from its end; our side settles symmetrically here —
    deposits still sitting in the quiesced slots are probed as pending
    before teardown, so the conservation identity holds across
    partition → heal → merge.  The orphan re-enters each window with
    unit push-sum mass at its own debiased x̂ (the value it agreed on
    before the cut), so the merge neither creates nor destroys mass
    and gossip re-converges to the member-weighted average.

    Blocks until some majority member admits us via
    :func:`admit_pending`; returns the :class:`~bluefog_tpu.resilience.
    join.JoinGrant`.  The process keeps its telemetry/trace identity;
    its global rank changes (dead ids are never reissued).
    """
    global _context
    ctx = _ctx()
    if not ctx.orphaned:
        raise RuntimeError("merge_orphan(): this rank is not orphaned "
                           "(nothing to merge; did heal() deny quorum?)")
    reg = _telemetry.get_registry()
    est: Dict[str, np.ndarray] = {}
    for name, w in ctx.windows.items():
        x = np.array(w.self_tensor, copy=True)
        if ctx.associated_p and w.p_self > 0.0:
            x = np.asarray(x / w.p_self, dtype=x.dtype)
        est[name] = x
        if reg.enabled:
            _ledger_probe_pending(reg, w, ctx.rank)
    if reg.enabled:
        reg.counter("resilience.orphan_merged").inc()
        reg.journal("orphan_merged", epoch=ctx.epoch,
                    global_rank=ctx.global_rank,
                    windows=sorted(est), **_ledger_totals(reg))
    tr = _tracing.get_tracer()
    if tr.enabled:
        tr.instant("orphan_merge", aux=ctx.epoch)
    base_job = ctx.base_job
    old_identity = ctx.global_rank
    # teardown, mirroring _switch_epoch's close half: segments are left
    # for crashed-run hygiene (unlink_all's job glob), the frozen
    # status page is reclaimed so bftpu-top stops reporting ORPHAN
    ctx.detector.stop()
    if ctx.progress is not None:
        try:
            ctx.progress.stop()
        except Exception:  # noqa: BLE001 - a wedged worker must not block merge
            pass
    for w in ctx.windows.values():
        w.shm.close(unlink=False)
    ctx.shm_job.close(unlink=False)
    if ctx.statuspage is not None:
        ctx.statuspage.close(unlink=True)
        ctx.statuspage = None
    _context = None
    # the request names the abandoned identity so the majority excises
    # it before granting (it would never ack the new-epoch barrier)
    grant = join(base_job, timeout, retiring=old_identity)
    nctx = _ctx()
    for name, x in est.items():
        w = nctx.windows.get(name)
        if w is None:
            continue  # the window was freed on the majority side
        # overwrite the sponsor-onboarded value with the carried
        # estimate: mass stays the unit p the grant admitted, only the
        # value differs — slot seeds are version-fenced (seed_ver), so
        # no combine mixes the stale sponsor copy back in
        w.self_tensor = np.asarray(x, dtype=w.shm.dtype)
        w.shm.expose(w.self_tensor, w.p_self)
    return grant


# ---------------------------------------------------------------------------
# serving plane: fenced snapshot publication to the inference fleet
# (bluefog_tpu.serve; docs/SERVING.md)
# ---------------------------------------------------------------------------


def serve_publish(name: str, payload_cap: Optional[int] = None) -> int:
    """Publish my debiased estimate of window ``name`` as one committed
    serve snapshot for the job's replica fleet (docs/SERVING.md).

    The fence, in order: an ORPHAN quiesce raises immediately, and the
    quorum gate re-checks the current live view (detector verdict) at
    the publish boundary — a minority that has not yet healed enters the
    orphan quiesce HERE instead of publishing a split-brain snapshot.
    The progress engine (when running) is quiesced around the estimate
    read so no async deposit lands mid-snapshot; the snapshot itself is
    the push-sum debiased value x̂ = x/p — what the consensus agrees
    on — stamped with the membership epoch, so the publish is fenced at
    the epoch boundary replicas can reason about.

    Returns the committed version — strictly monotone for the job, even
    across publisher death and handoff (the region persists the word)."""
    from bluefog_tpu.serve.snapshot import SnapshotRegion

    ctx = _ctx()
    _orphan_guard(ctx, "serve_publish")
    if not _quorum_gate(ctx, set(ctx.detector.dead_ranks()),
                        "serve_publish"):
        _orphan_guard(ctx, "serve_publish")  # just quiesced: raise
    win = _win(name)
    reg = _telemetry.get_registry()
    t0 = time.monotonic()
    eng = ctx.progress
    if eng is not None:
        eng.quiesce()
    try:
        if ctx.associated_p and win.p_self > 0.0:
            est = np.asarray(win.self_tensor) / win.p_self
        else:
            est = np.array(win.self_tensor, copy=True)
    finally:
        if eng is not None:
            eng.resume()
    region = ctx.serve_region
    if region is None:
        cap = int(payload_cap) if payload_cap else max(1, est.nbytes)
        region = ctx.serve_region = SnapshotRegion(ctx.base_job, cap)
    version = region.publish(est, epoch=ctx.epoch, step=ctx.op_rounds)
    ctx.serve_version = version
    if reg.enabled:
        reg.counter("serve.published").inc()
        reg.gauge("serve.version").set(version)
        reg.histogram("serve.publish_s").observe(time.monotonic() - t0)
        reg.journal("serve_publish", win=name, version=version,
                    epoch=ctx.epoch, step=ctx.op_rounds,
                    nbytes=int(est.nbytes))
    _statuspage_tick(ctx, name, "serve_pub")
    return version


# ---------------------------------------------------------------------------
# adaptive topology: the straggler demote/promote control loop
# (resilience/adaptive.py; docs/RESILIENCE.md "Adaptive topology")
# ---------------------------------------------------------------------------


def adaptive_policy() -> Optional[_adaptive.AdaptivePolicy]:
    """This rank's adaptive edge-health policy, or None when
    ``BFTPU_ADAPTIVE`` is off."""
    return _ctx().adaptive


def demoted_ranks() -> Tuple[int, ...]:
    """Sorted global ranks currently demoted (degree-capped) by the
    adaptive topology — members, not corpses: they still gossip through
    their anchor edge."""
    return tuple(sorted(_ctx().demoted))


def _members_graph_global(ctx: "_IslandContext") -> nx.DiGraph:
    """The CURRENT epoch topology over ALL members (demoted included),
    in global rank labels — the base a demote caps or a promote
    restores."""
    G = nx.DiGraph()
    G.add_nodes_from(sorted(ctx.members_global))
    for u, v in ctx.topology.edges:
        if u != v:
            G.add_edge(_peer_global(ctx, u), _peer_global(ctx, v))
    return G


def _is_anchor(ctx: "_IslandContext", g: int) -> bool:
    """Whether this rank is ``g``'s anchor in the demoted topology —
    the ONLY member still observing g's edge, hence the only member
    whose edge-health machine can witness the recovery (everyone else
    stopped probing g when the demote dropped their edges)."""
    if g not in ctx.members_global:
        return False
    lg = ctx.members_global.index(g)
    nbrs = set(ctx.topology.successors(lg)) | set(ctx.topology.predecessors(lg))
    return ctx.rank in nbrs


def _commit_reweight(ctx: "_IslandContext", board, demote=(), promote=()):
    """Compute the deterministic reweight record and race it onto the
    board (first observer wins; the rest adopt the committed record).
    Quorum-fenced like :func:`heal`: a minority view may not commit a
    demote/promote epoch either — same split-brain, different door."""
    if ctx.orphaned or not _quorum_gate(ctx, set(), "reweight"):
        return None
    base = ctx.base_edges
    if base is None:
        G0 = _members_graph_global(ctx)
        base = sorted((int(u), int(v)) for u, v in G0.edges)
    baseG = nx.DiGraph()
    baseG.add_nodes_from(sorted(ctx.members_global))
    baseG.add_edges_from(base)
    new_demoted = (set(ctx.demoted) | set(demote)) - set(promote)
    if new_demoted:
        healed = _healing.demote_topology(baseG, sorted(new_demoted))
    else:
        # full restore: heal with an empty dead set re-symmetrizes and
        # MH re-weights the base graph through the same pipeline
        healed = _healing.heal_topology(baseG, [])
    reg = _telemetry.get_registry()
    rec = board.commit_reweight(
        committer=ctx.global_rank, prev_epoch=ctx.epoch,
        members=[int(m) for m in healed.to_global],
        edges=list(healed.topology.edges),
        windows=_windows_meta(ctx), associated_p=ctx.associated_p,
        demoted=sorted(new_demoted), promoted=sorted(promote),
        base_edges=base)
    if rec is not None and not rec.get("reweight"):
        return None  # a raced JOIN grant won this epoch; retry next tick
    if (rec is not None and reg.enabled
            and int(rec["sponsor"]) == ctx.global_rank):
        which = "demote" if demote else "promote"
        reg.counter(f"adaptive.{which}s_committed").inc()
        reg.journal(f"adaptive_{which}", epoch=int(rec["epoch"]),
                    demoted=list(rec.get("demoted", ())),
                    promoted=list(rec.get("promoted", ())),
                    committer=ctx.global_rank)
    return rec


def adaptive_step():
    """One tick of the adaptive-topology control loop: call at the
    round cadence on EVERY member (right after a combine is the natural
    spot).  No-op unless ``BFTPU_ADAPTIVE`` is on.

    Three things can happen, at most one per tick:

    1. a reweight epoch committed by another member is observed (cheap
       epoch-word probe) and this rank switches into it;
    2. an in-neighbor the edge-health machine holds SUSPECT is DEMOTED:
       any observer commits the deterministic degree-capped topology
       (:func:`~bluefog_tpu.resilience.healing.demote_topology`,
       first-wins) and switches;
    3. a demoted rank whose machine transitioned back to ALIVE — only
       its ANCHOR still observes it — is PROMOTED: the anchor commits
       the restored base topology and switches.

    Returns the epoch record switched through, or None.  Flapping
    cannot thrash epochs: the machine's hysteresis floor
    (``BFTPU_DEMOTE_FLOOR_S``) lower-bounds the time between its own
    transitions, and demote/promote commits only fire ON a transition's
    standing state.  Demotions are additionally capped to a MINORITY of
    the membership (longest-SUSPECT first) — every straggler needs a
    healthy anchor, and no misattribution cascade can demote the fleet
    out from under itself (at np=2 the cap is zero: ABSORB alone
    bounds the rounds there).
    """
    ctx = _ctx()
    pol = ctx.adaptive
    if pol is None or ctx.orphaned:
        return None
    board = _join.MembershipBoard(ctx.base_job)
    # 1. observe: someone committed an epoch I have not switched into
    if shm_native.membership_epoch(ctx.base_job) > ctx.epoch:
        rec = board.epoch_record(ctx.epoch + 1)
        if rec is not None and rec.get("reweight"):
            _switch_epoch(ctx, rec)
            return dict(rec)
        return None  # a join grant: admit_pending's business
    # 2. demote: a live, not-yet-demoted member gone SUSPECT
    suspects = pol.health.suspects()
    if suspects:
        cand = sorted(
            g for g in suspects
            if g in ctx.members_global and g not in ctx.demoted
            and g != ctx.global_rank
            and ctx.members_global.index(g) not in ctx.dead
            and pol.epoch_floor_open(g)
            # with the tracing feed live, demotion needs gap staleness
            # AND critical-path blame (pass-through when tracing is off)
            and pol.corroborated(g))
        if cand:
            # never demote past a minority: every straggler needs a
            # healthy anchor and a majority-healthy core keeps the
            # demoted graph mixing — this is also the terminal guard
            # against a convoy misattribution walking the fleet into
            # "every member is a straggler".  Longest-SUSPECT first:
            # under contention the persistently slow rank wins the slot
            # over a transient suspect.
            room = (len(ctx.members_global) - 1) // 2 - len(ctx.demoted)
            cand.sort(key=lambda g: -pol.health.time_in_state(g))
            cand = sorted(cand[:max(0, room)])
        if cand:
            rec = _commit_reweight(ctx, board, demote=cand)
            if rec is not None:
                _switch_epoch(ctx, rec)
                return dict(rec)
            return None
    # 3. promote: an anchored straggler proved itself ALIVE again
    if ctx.demoted:
        cand = sorted(
            g for g in ctx.demoted
            if pol.health.state(g) == EDGE_ALIVE and _is_anchor(ctx, g)
            and pol.epoch_floor_open(g))
        if cand:
            rec = _commit_reweight(ctx, board, promote=cand)
            if rec is not None:
                _switch_epoch(ctx, rec)
                return dict(rec)
    return None


# ---------------------------------------------------------------------------
# window ops
# ---------------------------------------------------------------------------


def _win(name: str) -> _IslandWindow:
    w = _ctx().windows.get(name)
    if w is None:
        raise KeyError(f"no window named {name!r}; call win_create first")
    return w


def _check_dst(win: _IslandWindow, dst_weights: WeightDict):
    """Destination ranks for a put/accumulate, validated against MY
    out-neighbors (a deposit lands in the slot keyed by the WRITER, so a
    non-out-neighbor target has no slot for us — fail with the real reason
    rather than a confusing slot KeyError)."""
    if dst_weights is None:
        return win.out_neighbors
    unknown = set(dst_weights) - set(win.out_neighbors)
    if unknown:
        raise KeyError(
            f"dst_weights for non-out-neighbor rank(s) {sorted(unknown)}; "
            f"out-neighbors of rank {_ctx().rank} are {win.out_neighbors}"
        )
    return dst_weights


def _to_host(tensor) -> np.ndarray:
    # jax.Array, torch.Tensor (cpu), or array-like → host numpy.  On the
    # progress-engine worker thread this is a zero-copy dlpack view when
    # the producer allows; synchronous callers get the historical copy
    # (progress/staging.py — the device→host staging-copy kill).
    return _progress.staging.stage(tensor)


class _IslandFusionMeta:
    """Pytree (fused) window metadata — one packed buffer per tree, the
    twin of windows._FusionMeta for the island (numpy/host) runtime."""

    __slots__ = ("treedef", "shapes", "sizes")

    def __init__(self, treedef, shapes, sizes):
        self.treedef = treedef
        self.shapes = shapes
        self.sizes = sizes


def _island_fusion_split(tensor):
    """(meta, packed 1-D array) for a pytree; (None, tensor) for an array."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tensor)
    if treedef == jax.tree_util.tree_structure(0):
        return None, tensor
    if not leaves:
        raise ValueError("win_create: empty pytree")
    if isinstance(tensor, (list, tuple)) and all(
        np.ndim(l) == 0 for l in leaves
    ):
        # nested-list-of-scalars spelling of a bare array
        return None, np.asarray(tensor)
    hosts = [_to_host(l) for l in leaves]
    dts = {h.dtype for h in hosts}
    if len(dts) > 1:
        raise ValueError(
            f"fused windows need a uniform leaf dtype, got "
            f"{sorted(map(str, dts))}; create one window per dtype group"
        )
    meta = _IslandFusionMeta(
        treedef,
        [h.shape for h in hosts],
        [int(h.size) for h in hosts],
    )
    return meta, np.concatenate([h.ravel() for h in hosts])


def _island_pack(name, tensor):
    meta = _ctx().win_fusion.get(name)
    if meta is None:
        return tensor
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tensor)
    if treedef == jax.tree_util.tree_structure(0):
        # already-packed array (internal callers like push_sum_round work
        # on the packed buffer) — accept iff it has the packed length
        t = _to_host(tensor)
        if t.shape == (sum(meta.sizes),):
            return t
        raise ValueError(
            f"window '{name}' is a fused pytree window; pass the tree "
            f"(or its packed [{sum(meta.sizes)}] buffer), got shape {t.shape}"
        )
    if treedef != meta.treedef:
        raise ValueError(
            f"pytree structure does not match window '{name}': {treedef} "
            f"vs {meta.treedef}"
        )
    hosts = [_to_host(l) for l in leaves]
    bad = [(h.shape, tuple(exp)) for h, exp in zip(hosts, meta.shapes)
           if h.shape != tuple(exp)]
    if bad:
        # same-size-different-shape leaves would pack without error and
        # unpack as silently corrupted data
        raise ValueError(
            f"leaf shapes do not match window '{name}': {bad[:4]}"
        )
    return np.concatenate([h.ravel() for h in hosts])


def _island_unpack(name, packed):
    meta = _ctx().win_fusion.get(name)
    if meta is None:
        return packed
    import jax

    out, off = [], 0
    for s, sz in zip(meta.shapes, meta.sizes):
        out.append(packed[off:off + sz].reshape(s))
        off += sz
    return jax.tree_util.tree_unflatten(meta.treedef, out)


def win_create(tensor, name: str, zero_init: bool = False) -> bool:
    """Collectively create a named window from THIS rank's tensor
    (reference ``bf.win_create`` [U]; collective like MPI_Win_create)."""
    ctx = _ctx()
    if name in ctx.windows:
        # already exists — e.g. this process JOINED and the window came
        # with the epoch record: adopt the caller's fusion meta so a
        # pytree window still unpacks correctly after the replayed call
        meta, _ = _island_fusion_split(tensor)
        if meta is not None and name not in ctx.win_fusion:
            ctx.win_fusion[name] = meta
        return False
    meta, tensor = _island_fusion_split(tensor)
    t = _to_host(tensor)
    ctx.windows[name] = _IslandWindow(name, t, ctx, zero_init)
    ctx.created_names.add(name)
    if meta is not None:
        ctx.win_fusion[name] = meta
    _note_op("win_create", name)
    return True


def win_free(name: Optional[str] = None) -> bool:
    """Free one window (all when ``name`` is None).  COLLECTIVE, like
    MPI_Win_free [U]: every rank must call it with the same name(s).  The
    segment is unlinked (rank 0, between two barriers) so a later
    ``win_create`` under the same name starts from a fresh segment instead
    of attaching to stale slots."""
    ctx = _ctx()
    names = [name] if name is not None else sorted(ctx.windows)
    ok = True
    reg = _telemetry.get_registry()
    if ctx.lab_probes:
        # flush + journal the convergence probe's batched tail before
        # the window goes away
        for n in names:
            pr = ctx.lab_probes.get(n)
            if pr is not None:
                pr.flush_pending()
                _drain_conv_journal(ctx, n, pr)
    eng = ctx.progress
    if eng is not None:
        # flush queued async ops into the still-live segments, then park
        # the worker: its idle prefetch must not touch a window whose
        # mapping the loop below is about to close
        for n in names:
            eng.drain(window=n, timeout=60.0)
        eng.quiesce()
        for n in names:
            eng.windows_seen.discard(n)
    try:
        ok = _win_free_inner(ctx, names, reg)
    finally:
        if eng is not None:
            eng.resume()
    return ok


def _win_free_inner(ctx: "_IslandContext", names, reg) -> bool:
    ok = True
    for n in names:
        w = ctx.windows.pop(n, None)
        if w is None:
            ok = False
            continue
        if reg.enabled:
            # ledger: account mass left in the slots as "pending" — but
            # only after every rank has entered this collective free (a
            # slower peer may still be mid-deposit), so barrier first.
            # BFTPU_TELEMETRY must be uniform across ranks (the launcher
            # forwards it), keeping the barrier schedule identical.
            ctx.shm_job.barrier()
            _ledger_probe_pending(reg, w, ctx.rank)
        w.shm.close(unlink=False)
        ctx.shm_job.barrier()  # all mappings closed
        # transport-aware designated unlink (plain shm: global rank 0;
        # hierarchical: each host group's segment-rank-0; tcp: no-op)
        w.shm.unlink_segments()
        ctx.shm_job.barrier()  # name gone everywhere before any re-create
        ctx.created_names.discard(n)
        ctx.win_fusion.pop(n, None)
        _note_op("win_free", n)
    return ok


def win_put(tensor, name: str, dst_weights: WeightDict = None) -> bool:
    """One-sided deposit of (optionally per-destination scaled) values into
    my slot at each out-neighbor — completes without receiver participation
    (reference ``bf.win_put`` → MPI_Put [U]).  Also refreshes my exposed
    tensor (upstream the window aliases the tensor's memory)."""
    with timeline_context("island_win_put"):
        ctx = _ctx()
        _orphan_guard(ctx, "win_put")
        win = _win(name)
        reg = _telemetry.get_registry()
        tr = _tracing.get_tracer()
        ttok = tr.begin("win_put", window=name) if tr.enabled else None
        emits = [] if ttok is not None else None
        t0 = time.perf_counter_ns() if reg.enabled else 0
        t = _to_host(_island_pack(name, tensor)).astype(win.shm.dtype, copy=False)
        # alias, don't copy: upstream the window aliases the user tensor's
        # memory, and the shm exposure below is already a stable snapshot
        win.self_tensor = t
        targets = _check_dst(win, dst_weights)
        if ctx.dead:
            # degraded step: a rank that died inside a fused combine holds
            # its own slot locks forever — depositing to it would spin
            targets = [d for d in targets if d not in ctx.dead]
        scaled = _scaled_transport(win)
        dual = getattr(win.shm, "put_dual", None) if scaled else None
        exposed = False
        for d in targets:
            wgt = 1.0 if dst_weights is None else float(dst_weights[d])
            if ttok is not None:
                # stamp BEFORE the deposit: the consumer must never see a
                # committed payload without its context word
                op_id = tr.next_op_id()
                win.shm.trace_stamp(
                    d, win.slot_of[d][ctx.rank],
                    _tracing.pack_ctx(tr.round, op_id, ctx.rank))
                emits.append({"dst": d, "op_id": op_id})
            if dual is not None and not exposed:
                # v2 transport: ONE read of t feeds both the exposed slot
                # and the first destination's mailbox, chunk-interleaved
                dual(d, win.slot_of[d][ctx.rank], t, p=win.p_self * wgt,
                     accumulate=False, scale=wgt, expose_p=win.p_self)
                exposed = True
            elif scaled:
                # the scale rides inside the deposit pass — no
                # per-destination ``t * wgt`` temporary
                win.shm.write(d, win.slot_of[d][ctx.rank], t,
                              p=win.p_self * wgt, accumulate=False,
                              scale=wgt)
            else:
                payload = t if wgt == 1.0 else t * wgt
                win.shm.write(d, win.slot_of[d][ctx.rank], payload,
                              p=win.p_self * wgt, accumulate=False)
        if not exposed:
            win.shm.expose(t, win.p_self)
        if reg.enabled:
            for d in targets:
                _edge_deposit(reg, win, "win_put", ctx.rank, d, t.nbytes)
            _op_hist(reg, win, "win_put").observe(
                (time.perf_counter_ns() - t0) / 1e9)
        if ttok is not None:
            tr.end(ttok, emit=emits)
        _note_op("win_put", name)
    return True


def _scaled_transport(win: _IslandWindow) -> bool:
    """Whether the window's transport fuses a scale factor into the deposit
    pass (protocol-v2 shm windows, float payloads only)."""
    return (getattr(win.shm, "supports_scale", False)
            and np.issubdtype(win.shm.dtype, np.floating))


def _note_op(op: str, name: str) -> None:
    """Record an island window op through the single telemetry event path
    (``telemetry.note_op``): bumps the ``win_ops.total`` counter and fans
    out to listeners — ``windows.record_win_ops()`` traces (and the
    verifier's epoch linter) subscribe there, so island-mode programs are
    covered without a parallel bookkeeping path (and without importing
    :mod:`bluefog_tpu.windows`, which would pull jax into every island
    worker)."""
    _telemetry.note_op(op, name)


def _lab_probe_tick(ctx: "_IslandContext", win: "_IslandWindow",
                    name: str) -> None:
    """Feed this round's post-combine tensor to the window's convergence
    probe (:mod:`bluefog_tpu.lab`) and stream the sample into telemetry.
    Off-path: when ``BFTPU_LAB_PROBE`` is unset the per-op cost is one
    attribute load and a falsy branch, same convention as tracing and
    the status page.  The enablement check is lazy (first win_update,
    not context init) so spawn() has already propagated the env.

    The probe batches its math over ``BFTPU_LAB_FLUSH`` rounds (the
    probe module's cost model: the tick runs cache-cold, so per-round
    numpy has a ~40 µs floor the < 2% gate can't afford), so the page's
    ``(conv_err, conv_round)`` pair and the journal trail advance in
    flush-sized bursts — every round's exact value still lands, each
    tagged with its own round index."""
    probes = ctx.lab_probes
    if probes is False:
        return
    if probes is None:
        from bluefog_tpu.lab import probe as _lab_probe

        if not _lab_probe.probe_enabled():
            ctx.lab_probes = False
            return
        probes = ctx.lab_probes = {}
    if name not in probes:
        from bluefog_tpu.lab import probe as _lab_probe

        probes[name] = _lab_probe.ConvergenceProbe(
            flush_every=_lab_probe.flush_every_env())
        probes[name]._journaled = 0  # history entries already journaled
    pr = probes[name]
    err = pr.observe(win.self_tensor,
                     win.p_self if ctx.associated_p else 1.0)
    if pr.last_round > 0:
        ctx.conv_round = pr.last_round
        ctx.conv_err = err if err == err else -1.0  # NaN first round
    _drain_conv_journal(ctx, name, pr)


def _drain_conv_journal(ctx: "_IslandContext", name: str, pr) -> None:
    """Journal the probe's newly computed (round, err) history entries.
    Called from the tick (after a flush lands a burst), from the
    ``win_conv_*`` accessors, and from win_free — so the batched tail
    (up to ``BFTPU_LAB_FLUSH - 1`` rounds) is never lost to the
    journal."""
    hist = pr.history
    done = getattr(pr, "_journaled", 0)
    if done >= len(hist):
        return
    reg = _telemetry.get_registry()
    if reg.enabled:
        for t, e in hist[done:]:
            if e == e:  # the round-1 NaN has no predecessor
                reg.gauge("lab.conv_err", win=name).set(e)
                reg.journal("conv", win=name, round=t, err=e,
                            epoch=ctx.epoch)
    pr._journaled = len(hist)


def _statuspage_tick(ctx: "_IslandContext", name: str,
                     op: str = "win_update") -> None:
    """Republish my live status page (one seqlocked mmap write, no
    locks/syscalls) and poll the trace-control word — the per-op
    heartbeat of the introspection plane (:mod:`bluefog_tpu.introspect`).
    No-op when ``BFTPU_STATUSPAGE=0``."""
    page = ctx.statuspage
    if page is None:
        return
    ctx.op_rounds += 1
    pol = ctx.adaptive
    deadline = (pol.gap_deadline_s() or 0.0) if pol is not None else 0.0
    edges = []
    for l, g in enumerate(ctx.members_global):
        if g == ctx.global_rank:
            continue
        code = (_EDGE_STATE_CODE.get(pol.health.state(g), 0)
                if pol is not None else 0)
        if l in ctx.dead:
            code = 2  # dead set outranks the edge machine's view
        elif g in ctx.demoted:
            code = 3
        edges.append((g, code, deadline))
    reg = _telemetry.get_registry()
    ledger = _ledger_totals(reg) if reg.enabled else None
    eng = ctx.progress
    qdepth, inflight = -1, ""
    if eng is not None:
        st = eng.stats()
        qdepth = int(st["queue_depth"])
        inflight = st["inflight"] or ""
    try:
        page.publish(nranks=len(ctx.members_global), step=ctx.op_rounds,
                     epoch=ctx.epoch, op_id=ctx.op_rounds,
                     last_op=f"{op}:{name}", ledger=ledger, edges=edges,
                     qdepth=qdepth, inflight=inflight,
                     conv_err=ctx.conv_err, conv_round=ctx.conv_round,
                     serve_version=ctx.serve_version,
                     serve_lag=0 if ctx.serve_version >= 0 else -1)
    except (OSError, ValueError):
        pass  # a reaped segment must never fail the op itself
    if ctx.tracectl is not None:
        ctx.tracectl.poll()


# ---------------------------------------------------------------------------
# telemetry helpers: per-edge traffic counters + the mailbox mass ledger.
# Every helper is called behind a ``reg.enabled`` guard, so the disabled
# path costs one attribute load and a falsy branch per op.
# ---------------------------------------------------------------------------


def _tel_table(reg, win: _IslandWindow) -> dict:
    """The window's memoized metric-handle table for ``reg``.  A labeled
    handle lookup (``reg.counter(name, **labels)``) costs ~2µs in label-key
    construction; an op touches several handles, which is visible next to a
    ~ms mailbox deposit.  Handles are stable objects, so the hot paths cache
    them per window, invalidating if telemetry is reset to a new registry."""
    cache = win._tel_cache
    if cache is None or cache[0] is not reg:
        win._tel_cache = cache = (reg, {})
    return cache[1]


def _edge_deposit(reg, win: _IslandWindow, op: str, src: int, dst: int,
                  nbytes: int) -> None:
    """Writer-side accounting for ONE mailbox deposit on edge src->dst."""
    tbl = _tel_table(reg, win)
    h = tbl.get(("e", op, src, dst))
    if h is None:
        h = tbl[("e", op, src, dst)] = (
            reg.counter("win.edge_ops", op=op, src=src, dst=dst),
            reg.counter("win.edge_bytes", op=op, src=src, dst=dst),
            reg.counter(_telemetry.LEDGER_DEPOSITS),
        )
    h[0].inc()
    h[1].add(int(nbytes))
    h[2].inc()
    win._deposited_to[dst] = win._deposited_to.get(dst, 0) + 1


def _op_hist(reg, win: _IslandWindow, op: str):
    """Memoized ``win.op_s`` latency histogram handle for ``op``."""
    tbl = _tel_table(reg, win)
    h = tbl.get(("h", op))
    if h is None:
        h = tbl[("h", op)] = reg.histogram("win.op_s", op=op)
    return h


def _ledger_retire(reg, win: _IslandWindow, slot: int, ver: int,
                   what: str) -> None:
    """Retire slot versions up to ``ver`` into ledger counter ``what``.
    Versions are monotone deposit counts, so retirement telescopes: the
    total ever retired equals the last version probed, regardless of how
    individual deposits were classified under concurrent writers."""
    seen = win._ledger_seen.get(slot, 0)
    if ver > seen:
        tbl = _tel_table(reg, win)
        c = tbl.get(("lc", what))
        if c is None:
            c = tbl[("lc", what)] = reg.counter(what)
        c.add(int(ver - seen))
        win._ledger_seen[slot] = int(ver)


def _ledger_retire_probe(reg, win: _IslandWindow, slot: int, src: int,
                         what: str) -> None:
    rv = getattr(win.shm, "read_version", None)
    if rv is None:
        return
    try:
        ver = rv(slot, src=src)
    except Exception:  # noqa: BLE001 - accounting must never break the op
        return
    _ledger_retire(reg, win, slot, int(ver), what)


def _ledger_probe_pending(reg, win: _IslandWindow, rank_: int) -> None:
    """Retire whatever each slot still holds as "pending" (window free /
    job shutdown: mass deposited but never combined)."""
    for s in win.in_neighbors:
        _ledger_retire_probe(reg, win, win.slot_of[rank_][s], s,
                             _telemetry.LEDGER_PENDING)


def win_accumulate(tensor, name: str, dst_weights: WeightDict = None) -> bool:
    """Like win_put but atomically ADDS into the destination slot (reference
    ``bf.win_accumulate`` → MPI_Accumulate [U]).  With associated-p enabled
    the scalar mass rides along, so Σ(x, p) over all slots + exposed tensors
    is invariant — the push-sum conservation law."""
    with timeline_context("island_win_accumulate"):
        ctx = _ctx()
        _orphan_guard(ctx, "win_accumulate")
        win = _win(name)
        reg = _telemetry.get_registry()
        tr = _tracing.get_tracer()
        ttok = tr.begin("win_accumulate", window=name) if tr.enabled else None
        emits = [] if ttok is not None else None
        t0 = time.perf_counter_ns() if reg.enabled else 0
        t = _to_host(_island_pack(name, tensor)).astype(win.shm.dtype, copy=False)
        targets = _check_dst(win, dst_weights)
        if ctx.dead:
            targets = [d for d in targets if d not in ctx.dead]
        scaled = _scaled_transport(win)
        for d in targets:
            wgt = 1.0 if dst_weights is None else float(dst_weights[d])
            if ttok is not None:
                # accumulating deposits overwrite the slot's word: the
                # flow records the LAST contributor (the sidecar word is
                # advisory, not a full contributor list)
                op_id = tr.next_op_id()
                win.shm.trace_stamp(
                    d, win.slot_of[d][ctx.rank],
                    _tracing.pack_ctx(tr.round, op_id, ctx.rank))
                emits.append({"dst": d, "op_id": op_id})
            if scaled:
                win.shm.write(d, win.slot_of[d][ctx.rank], t,
                              p=win.p_self * wgt, accumulate=True,
                              scale=wgt)
            else:
                payload = t if wgt == 1.0 else t * wgt
                win.shm.write(d, win.slot_of[d][ctx.rank], payload,
                              p=win.p_self * wgt, accumulate=True)
        if reg.enabled:
            for d in targets:
                _edge_deposit(reg, win, "win_accumulate", ctx.rank, d, t.nbytes)
            _op_hist(reg, win, "win_accumulate").observe(
                (time.perf_counter_ns() - t0) / 1e9)
        if ttok is not None:
            tr.end(ttok, emit=emits)
        _note_op("win_accumulate", name)
    return True


class _ProgressBackend:
    """Engine→transport adapter (the ``backend`` duck type in
    :mod:`bluefog_tpu.progress.engine`).  Ops re-enter the PUBLIC
    synchronous win ops, so telemetry, tracing, the mass ledger, and the
    degraded-mode dead-rank filtering apply identically on the async
    path; windows are resolved by NAME at execution time, which is what
    makes queued ops survive a membership-epoch rebind."""

    def execute(self, kind, window, payload, weights, kwargs):
        if kind == "put":
            return win_put(payload, window, dst_weights=weights)
        if kind == "accumulate":
            return win_accumulate(payload, window, dst_weights=weights)
        return win_update(window, **kwargs)

    def fuse(self, kind, window, payloads):
        # put deposits overwrite the slot: executing only the LAST of a
        # coalesced run is indistinguishable from executing all of them.
        # accumulate deposits add: the run deposits its (packed) sum
        # once — w·Σtᵢ == Σ(w·tᵢ), and the engine only fuses ops with
        # identical weights.
        if kind == "put":
            return payloads[-1]
        acc = np.array(_to_host(_island_pack(window, payloads[0])),
                       copy=True)
        for t in payloads[1:]:
            acc += _to_host(_island_pack(window, t))
        return acc

    def epoch(self) -> int:
        return _context.epoch if _context is not None else -1

    def prefetch(self, names) -> int:
        """Idle-time mailbox warming: one ``read_version`` word per
        in-edge, and a read-only bracketed copy into a persistent warm
        buffer for slots whose deposit count moved.  No collect, no
        mass movement, no semantic effect — the caller's next combine
        just runs over cache-warm pages."""
        ctx = _context
        if ctx is None:
            return 0
        n = 0
        for name in names:
            win = ctx.windows.get(name)
            if win is None:
                continue
            pairs = [(win.slot_of[ctx.rank][s], s)
                     for s in win.in_neighbors if s not in ctx.dead]
            for slot, src, ver in shm_native.poll_versions(
                    win.shm, pairs, win._warm_ver):
                buf = win._warm.get(slot)
                if (buf is None or buf.shape != win.shm.shape
                        or buf.dtype != win.shm.dtype):
                    buf = win._warm[slot] = np.empty(
                        win.shm.shape, dtype=win.shm.dtype)
                try:
                    win.shm.read(slot, collect=False, src=src, out=buf)
                except TypeError:  # transport without out= support
                    win.shm.read(slot, collect=False, src=src)
                win._warm_ver[slot] = ver
                n += 1
        return n


def progress_engine() -> Optional[_progress.ProgressEngine]:
    """This rank's background progress engine, creating it on first use.
    None when the engine is disabled (``BFTPU_PROGRESS=0``) — the async
    ops then run synchronously at the call site."""
    ctx = _ctx()
    if not _progress.enabled():
        return None
    eng = ctx.progress
    if eng is None or eng.stopped:
        eng = ctx.progress = _progress.ProgressEngine(
            _ProgressBackend(), name=f"{ctx.base_job}:{ctx.global_rank}")
    return eng


def _payload_nbytes(win: _IslandWindow) -> int:
    # deposits must match the window shape, so the fusion-budget estimate
    # never needs to stage the (possibly still-computing) payload
    return int(np.prod(win.shm.shape, dtype=np.int64)
               * np.dtype(win.shm.dtype).itemsize)


def win_put_async(tensor, name: str, dst_weights: WeightDict = None):
    """:func:`win_put` off the critical path: enqueue the deposit on the
    progress engine and return a
    :class:`~bluefog_tpu.progress.handles.WinHandle` immediately — the
    worker thread stages, fuses, and lands it while the caller's next
    train step computes.  ``tensor`` may be a zero-arg callable (a
    staging thunk materialized on the worker — where a blocking
    device→host transfer belongs).  CONTRACT: do not donate/delete the
    payload until the handle resolves."""
    win = _win(name)  # surface unknown-window errors at the call site
    _orphan_guard(_ctx(), "win_put_async")
    eng = progress_engine()
    if eng is None:
        t = tensor() if callable(tensor) else tensor
        return _progress.completed(win_put(t, name, dst_weights))
    return eng.submit("put", name, payload=tensor, weights=dst_weights,
                      nbytes=_payload_nbytes(win))


def win_accumulate_async(tensor, name: str,
                         dst_weights: WeightDict = None):
    """:func:`win_accumulate` through the progress engine — see
    :func:`win_put_async`.  Fused runs deposit their sum once; the mass
    ledger balance is unchanged because accumulation is additive."""
    win = _win(name)
    _orphan_guard(_ctx(), "win_accumulate_async")
    eng = progress_engine()
    if eng is None:
        t = tensor() if callable(tensor) else tensor
        return _progress.completed(win_accumulate(t, name, dst_weights))
    return eng.submit("accumulate", name, payload=tensor,
                      weights=dst_weights, nbytes=_payload_nbytes(win))


def win_update_async(name: str, self_weight: Optional[float] = None,
                     neighbor_weights: WeightDict = None,
                     reset: bool = False):
    """:func:`win_update` through the progress engine; the handle's
    ``result()`` is the combined tensor (or pytree).  The combine runs
    on the worker in submission order after any queued deposits to the
    same window — the per-window FIFO the verifier family checks.  The
    result is always an independent copy (``clone`` semantics): it must
    stay valid while later queued ops keep mutating the window."""
    _win(name)
    _orphan_guard(_ctx(), "win_update_async")
    eng = progress_engine()
    if eng is None:
        return _progress.completed(win_update(
            name, self_weight=self_weight,
            neighbor_weights=neighbor_weights, reset=reset, clone=True))
    return eng.submit("update", name, self_weight=self_weight,
                      neighbor_weights=neighbor_weights, reset=reset,
                      clone=True)


def win_get(name: str, src_weights: WeightDict = None) -> bool:
    """One-sided pull of in-neighbors' exposed tensors into my mailbox
    slots, optionally receiver-scaled (reference ``bf.win_get`` →
    MPI_Get [U])."""
    with timeline_context("island_win_get"):
        ctx = _ctx()
        _orphan_guard(ctx, "win_get")
        win = _win(name)
        reg = _telemetry.get_registry()
        t0 = time.perf_counter_ns() if reg.enabled else 0
        if src_weights is not None:
            unknown = set(src_weights) - set(win.in_neighbors)
            if unknown:
                raise KeyError(
                    f"src_weights for non-in-neighbor rank(s) {sorted(unknown)}; "
                    f"in-neighbors of rank {ctx.rank} are {win.in_neighbors}"
                )
        sources = win.in_neighbors if src_weights is None else src_weights
        if ctx.dead:
            sources = [s for s in sources if s not in ctx.dead]
        scaled = _scaled_transport(win)
        tr = _tracing.get_tracer()
        ttok = tr.begin("win_get", window=name) if tr.enabled else None
        emits = [] if ttok is not None else None
        for s in sources:
            wgt = 1.0 if src_weights is None else float(src_weights[s])
            a, p, _ = win.shm.read_exposed(s)
            if ttok is not None:
                # the pull deposits into MY slot: this rank is both the
                # emitting and (later, at win_update) the consuming side,
                # so origin is self — the edge s->me is recorded in args
                op_id = tr.next_op_id()
                win.shm.trace_stamp(
                    ctx.rank, win.slot_of[ctx.rank][s],
                    _tracing.pack_ctx(tr.round, op_id, ctx.rank),
                    writer=s)
                emits.append({"dst": ctx.rank, "op_id": op_id, "src": s})
            # writer-of-record is s: deposit and later read must agree on
            # which transport leg holds the slot (hierarchical routing)
            if scaled:
                win.shm.write(ctx.rank, win.slot_of[ctx.rank][s], a,
                              p=p * wgt, accumulate=False, writer=s,
                              scale=wgt)
            else:
                win.shm.write(ctx.rank, win.slot_of[ctx.rank][s], a * wgt,
                              p=p * wgt, accumulate=False, writer=s)
            if reg.enabled:
                # the pull deposits into MY slot on edge s->me; this rank
                # performed the write, so this rank counts the deposit
                _edge_deposit(reg, win, "win_get", s, ctx.rank, a.nbytes)
        if reg.enabled:
            _op_hist(reg, win, "win_get").observe(
                (time.perf_counter_ns() - t0) / 1e9)
        if ttok is not None:
            tr.end(ttok, emit=emits)
        _note_op("win_get", name)
    return True


def _adaptive_probe(ctx: "_IslandContext", win: _IslandWindow,
                    nbrs: Sequence[int]) -> Tuple[int, ...]:
    """Probe each in-edge's slot version (a monotone deposit count) and
    feed the edge-health policy: a changed version is a fresh deposit
    (clean observation + a gap sample for the pooled baseline), an
    unchanged one past the edge deadline is a miss.  Returns the local
    ranks whose edges missed — the combine absorbs them for this round.

    One ``read_version`` word per edge per combine; transports without
    the surface opt out (no probe, no misses)."""
    pol = ctx.adaptive
    rv = getattr(win.shm, "read_version", None)
    if rv is None:
        return ()
    now = time.monotonic()
    seen = win._edge_seen
    stale: List[int] = []
    for s in nbrs:
        slot = win.slot_of[ctx.rank][s]
        try:
            ver = int(rv(slot, src=s))
        except Exception:  # noqa: BLE001 - health probing must never break the op
            continue
        prev = seen.get(slot)
        if prev is None or ver != prev[0]:
            if prev is not None:
                # the completed gap is the observation unit: clean only
                # if it made the deadline (a missed gap already counted
                # its one miss mid-gap — prev[2])
                pol.note_fresh(_peer_global(ctx, s), now - prev[1],
                               clean=not prev[2])
            seen[slot] = (ver, now, False)
        else:
            d = pol.gap_deadline_s()
            age = now - prev[1]
            if d is None or age <= d:
                continue
            if not prev[2]:
                # ONE miss per stale gap, never one per poll: a
                # synchronous caller polling at ms cadence would turn a
                # single marginal gap into a full SUSPECT streak, and
                # the convoy behind a straggler (blocked ranks stop
                # depositing too) would demote innocents.  A persistent
                # straggler misses on EVERY gap and still builds the
                # streak; a rank silent forever is the heartbeat
                # detector's jurisdiction — ABSORB keeps the round
                # bounded meanwhile.
                pol.note_stale(_peer_global(ctx, s), age)
                seen[slot] = (prev[0], prev[1], True)
            stale.append(s)
    return tuple(stale)


def _resolve_update_weights(win: _IslandWindow, self_weight, neighbor_weights):
    nbrs = win.in_neighbors
    if neighbor_weights is not None:
        unknown = set(neighbor_weights) - set(nbrs)
        if unknown:
            raise KeyError(
                f"neighbor_weights for non-in-neighbor rank(s) {sorted(unknown)}; "
                f"in-neighbors of rank {_ctx().rank} are {nbrs}"
            )
        nw = {s: float(neighbor_weights.get(s, 0.0)) for s in nbrs}
        sw = (1.0 - sum(nw.values())) if self_weight is None else float(self_weight)
        dead = _ctx().dead
        if dead and not dead.isdisjoint(nw):
            # degraded combine, self-weight renormalization: drop dead
            # neighbors and let self absorb their weight — the row total
            # is unchanged, so a convex row stays convex and push-sum
            # collect rows (all-ones) keep their unit slot weights
            dropped = sum(w for s, w in nw.items() if s in dead)
            nw = {s: w for s, w in nw.items() if s not in dead}
            sw += dropped
            reg = _telemetry.get_registry()
            if reg.enabled and dropped:
                reg.counter("resilience.weight_absorbed").add(dropped)
    else:
        dead = _ctx().dead
        live = [s for s in nbrs if s not in dead] if dead else nbrs
        u = 1.0 / (len(live) + 1)
        nw = {s: u for s in live}
        sw = u if self_weight is None else float(self_weight)
    return sw, nw


def win_update(
    name: str,
    self_weight: Optional[float] = None,
    neighbor_weights: WeightDict = None,
    reset: bool = False,
    clone: bool = False,
):  # -> np.ndarray, or the window's pytree for fused windows
    """Local weighted combine of my exposed tensor with my mailbox slots
    (reference ``bf.win_update`` [U]; default uniform 1/(in_degree+1)).
    ``reset=True`` drains the slots atomically (collect) so in-flight
    deposits are never lost — the accumulate idiom."""
    with timeline_context("island_win_update"):
        ctx = _ctx()
        _orphan_guard(ctx, "win_update")
        win = _win(name)
        reg = _telemetry.get_registry()
        tr = _tracing.get_tracer()
        ttok = tr.begin("win_update", window=name) if tr.enabled else None
        t0 = time.perf_counter_ns() if reg.enabled else 0
        sw, nw = _resolve_update_weights(win, self_weight, neighbor_weights)
        # after healing, dead in-neighbors are absent from nw: their slots
        # were force-drained and must not be combined (or even locked)
        nbrs = [s for s in win.in_neighbors if s in nw]
        win._last_absorbed = ()
        if ctx.adaptive is not None:
            # the corroboration gate follows the tracer's LIVE state (it
            # can flip at runtime via bftpu-top): while tracing, demotion
            # additionally needs critical-path blame — see corroborated()
            ctx.adaptive.set_live_feed(tr.enabled)
        if ctx.adaptive is not None and nbrs:
            # round-local ABSORB on deadline-missed edges: a stale edge
            # is dropped from THIS combine only — its slot keeps its
            # mass (pending; collected once the straggler deposits), and
            # for a convex row the dropped weight moves to self so the
            # row total is unchanged.  Push-sum collect rows (all-ones)
            # are not convex: there the plain drop is the conserving
            # move (doubling the self share would mint mass).
            stale = _adaptive_probe(ctx, win, nbrs)
            if stale:
                convex = abs(sw + sum(nw.values()) - 1.0) <= 1e-6
                dropped = 0.0
                for s in stale:
                    dropped += nw.pop(s)
                if convex:
                    sw += dropped
                nbrs = [s for s in nbrs if s in nw]
                win._last_absorbed = tuple(
                    sorted(_peer_global(ctx, s) for s in stale))
                if tr.enabled:
                    # live critical-path attribution: a deadline-missed
                    # in-edge is by construction the op this round
                    # waited on — the rank-local form of the merged
                    # trace's rounds-lengthened-by-rank
                    for s in stale:
                        ctx.adaptive.note_round_blame(_peer_global(ctx, s))
                if reg.enabled:
                    reg.counter("adaptive.weight_absorbed").add(
                        dropped if convex else float(len(stale)))
        consumes = None
        if ttok is not None:
            # peek BEFORE the combine: collect (reset) may recycle the
            # slot to a new deposit under a racing writer.  An unchanged
            # word means no NEW deposit was consumed on that edge since
            # the last combine — skip it, or every later round would
            # re-draw the same flow arrow.
            consumes = []
            for s in nbrs:
                slot = win.slot_of[ctx.rank][s]
                word = win.shm.trace_peek(slot, src=s)
                if word and word != win._trace_seen.get(slot):
                    win._trace_seen[slot] = word
                    rnd, op_id, origin = _tracing.unpack_ctx(word)
                    consumes.append({"src": s, "origin": origin,
                                     "op_id": op_id, "round": rnd})
        wdt = (win.shm.dtype if np.issubdtype(win.shm.dtype, np.inexact)
               else np.float64)
        fused = (getattr(win.shm, "update_fused", None)
                 if wdt == win.shm.dtype else None)
        if fused is not None:
            # v2 transport: the entire update — self-scale, every weighted
            # neighbor combine, the atomic drain, AND the expose republish
            # — is one native chunked sweep; the per-chunk partial stays
            # cache-resident across sub-passes, so the round does ~one
            # traversal per payload instead of four.
            self_data = np.ascontiguousarray(win.self_tensor, dtype=wdt)
            slots = [win.slot_of[ctx.rank][s] for s in nbrs]
            wts = [nw[s] for s in nbrs]
            view_fn = getattr(win.shm, "exposed_view", None)
            if view_fn is not None:
                # in-place form: the combine's destination IS the exposed
                # payload (reference windows alias tensor memory — bf's
                # win_update writes the buffer neighbors read), so the
                # republish copy disappears entirely.  The returned tensor
                # is a view over an independent mapping of those pages and
                # stays readable after win_free unmaps the window.
                p_acc = fused(
                    slots, wts, self_data, sw, win.p_self, None,
                    collect=reset, expose=2 if ctx.associated_p else 1,
                )
                win.self_tensor = view_fn()
            else:
                if (win._scratch is None or win._scratch.dtype != wdt
                        or win._scratch.shape != win.self_tensor.shape):
                    win._scratch = np.empty(win.self_tensor.shape, dtype=wdt)
                out_buf = win._scratch
                p_acc = fused(
                    slots, wts, self_data, sw, win.p_self, out_buf,
                    collect=reset, expose=2 if ctx.associated_p else 1,
                )
                # the buffer IS the new window tensor; a subsequent
                # win_update reads it back as self_data, which the native
                # sweep handles alias-safely
                win.self_tensor = out_buf
            if ctx.associated_p:
                win.p_self = float(p_acc)
            if reg.enabled:
                if reset:
                    # the fused sweep drained the slots; the post-drain
                    # version probe retires exactly what it collected
                    for s in nbrs:
                        _ledger_retire_probe(
                            reg, win, win.slot_of[ctx.rank][s], s,
                            _telemetry.LEDGER_COLLECTED)
                _op_hist(reg, win, "win_update").observe(
                    (time.perf_counter_ns() - t0) / 1e9)
            if ttok is not None:
                tr.end(ttok, consume=consumes)
                tr.advance_round()
            _note_op("win_update", name)
            _lab_probe_tick(ctx, win, name)
            _statuspage_tick(ctx, name)
            out = win.self_tensor
            out = np.array(out, copy=True) if clone else out
            return _island_unpack(name, out)
        acc = np.multiply(win.self_tensor, sw, dtype=wdt)
        p_acc = sw * win.p_self
        combine = (getattr(win.shm, "combine", None)
                   if wdt == win.shm.dtype else None)
        if combine is not None:
            # v2 shm transport: the weighted combine is fused into ONE
            # native pass per neighbor under the slot lock — the slot
            # payload is never materialized on the Python side, and
            # collect (reset) happens in the same critical section.
            for s in nbrs:
                slot = win.slot_of[ctx.rank][s]
                p, ver = combine(slot, acc, nw[s], collect=reset, src=s)
                if reset and reg.enabled:
                    _ledger_retire(reg, win, slot, int(ver),
                                   _telemetry.LEDGER_COLLECTED)
                p_acc = p_acc + nw[s] * p
        else:
            # preallocated-scratch combine for the other transports: the
            # naive expression ``acc + w * a.astype(wdt)`` allocates three
            # payload-sized temporaries per neighbor (astype ALWAYS
            # copies), which dominates the gossip round on a 1-core host.
            # One fused multiply into a persistent scratch buffer + an
            # in-place add keeps it to two passes with zero allocations
            # after the first call.
            if (win._scratch is None or win._scratch.shape != acc.shape
                    or win._scratch.dtype != acc.dtype):
                win._scratch = np.empty_like(acc)
            scratch = win._scratch
            for s in nbrs:
                slot = win.slot_of[ctx.rank][s]
                a, p, ver = win.shm.read(slot, collect=reset, src=s)
                if reset and reg.enabled:
                    _ledger_retire(reg, win, slot, int(ver),
                                   _telemetry.LEDGER_COLLECTED)
                np.multiply(a, nw[s], out=scratch, casting="unsafe")
                np.add(acc, scratch, out=acc)
                p_acc = p_acc + nw[s] * p
        win.self_tensor = acc.astype(win.shm.dtype, copy=False)
        if ctx.associated_p:
            win.p_self = float(p_acc)
        win.shm.expose(win.self_tensor, win.p_self)
        if reg.enabled:
            _op_hist(reg, win, "win_update").observe(
                (time.perf_counter_ns() - t0) / 1e9)
        if ttok is not None:
            tr.end(ttok, consume=consumes)
            tr.advance_round()
        _note_op("win_update", name)
        _lab_probe_tick(ctx, win, name)
        _statuspage_tick(ctx, name)
        out = win.self_tensor
        out = np.array(out, copy=True) if clone else out
        return _island_unpack(name, out)


def win_update_then_collect(name: str, require_mutex: bool = False):
    # -> np.ndarray, or the window's pytree for fused windows
    """Self weight 1, every neighbor slot weight 1, atomic drain — the
    push-sum accumulate-and-drain idiom (reference
    ``bf.win_update_then_collect`` [U]).  ``require_mutex`` is honored with
    the REAL shared-memory mutex (unlike the bulk-synchronous shim)."""
    win = _win(name)
    ones = {s: 1.0 for s in win.in_neighbors if s not in _ctx().dead}
    cm = win_mutex(name, for_self=True) if require_mutex else contextlib.nullcontext()
    with cm:
        return win_update(name, self_weight=1.0, neighbor_weights=ones,
                          reset=True)


def win_absorbed(name: str) -> Tuple[int, ...]:
    """GLOBAL ranks whose edges the most recent :func:`win_update` on
    ``name`` dropped via the round-local ABSORB (deadline-missed
    in-edges).  A synchronous caller waiting for every in-edge to turn
    fresh treats an absorbed edge as handled for the round — that is
    exactly the bound the adaptive deadline buys."""
    return _win(name)._last_absorbed


def win_sync(name: str):
    """My current tensor (or pytree, for fused windows) without combining
    (reference ``bf.win_sync``-style read of the window copy [U])."""
    return _island_unpack(name, _win(name).self_tensor)


@contextlib.contextmanager
def win_mutex(name: str, for_self: bool = False,
              ranks: Optional[Sequence[int]] = None):
    """REAL cross-process mutual exclusion over shared-memory locks
    (reference ``bf.win_mutex`` — MPI lock-based [U]).  Default locks my
    out-neighbors (the ranks whose windows I am about to touch); always
    acquired in ascending rank order to prevent deadlock."""
    del name
    ctx = _ctx()
    targets = set(ranks) if ranks is not None else set(out_neighbor_ranks())
    targets -= ctx.dead  # a dead rank's window needs no exclusion
    if for_self:
        targets.add(ctx.rank)
    ordered = sorted(targets)
    acquired = []
    try:
        for r in ordered:
            _mutex_acquire_deadline(ctx, r)
            acquired.append(r)
        yield
    finally:
        for r in reversed(acquired):
            ctx.shm_job.mutex_release(r)


def _mutex_acquire_deadline(ctx: "_IslandContext", r: int) -> None:
    """Acquire rank ``r``'s job mutex under the op deadline.  A holder
    that died mid-critical-section wedges a plain acquire forever; the
    timed path re-consults the failure detector between attempts and
    heals (which breaks dead holders' mutexes) so the retry succeeds.
    Transports without timed acquire keep the unbounded wait."""

    def on_timeout():
        if ctx.detector.dead_ranks() - ctx.dead:
            heal()

    pol = ctx.adaptive
    t0 = time.monotonic() if pol is not None else 0.0
    try:
        _degraded.with_deadline(
            lambda budget: ctx.shm_job.mutex_acquire(r, timeout=budget),
            f"win_mutex acquire of rank {r}",
            on_timeout=on_timeout)
    except TypeError:
        ctx.shm_job.mutex_acquire(r)
    if pol is not None:
        # the convoy signal: a straggler asleep INSIDE its critical
        # section stalls this acquire long past the healthy-cadence
        # baseline (acquires are never CLEAN evidence — see adaptive.py).
        # Blame the rank that actually HELD the lock during the wait
        # (the transport's holder word) when available; the window
        # owner is the fallback attribution.
        blame = r
        h = getattr(ctx.shm_job, "last_wait_holder", None)
        if h is not None and 0 <= h < ctx.size:
            blame = h
        if blame != ctx.rank and blame not in ctx.dead:
            pol.note_acquire(_peer_global(ctx, blame),
                             time.monotonic() - t0)


def win_associated_p(name: str) -> float:
    return _win(name).p_self


def win_set_exposed(name: str, tensor, associated_p: Optional[float] = None) -> None:
    """Overwrite my exposed tensor (and optionally p) without a put — the
    push-sum debias-and-restart idiom (see windows.win_set_exposed)."""
    win = _win(name)
    t = _to_host(_island_pack(name, tensor)).astype(win.shm.dtype, copy=False)
    if t.shape != win.shm.shape:
        raise ValueError(f"shape {t.shape} != window shape {win.shm.shape}")
    win.self_tensor = t  # alias (reference windows alias the tensor [U])
    if associated_p is not None:
        win.p_self = float(associated_p)
    win.shm.expose(t, win.p_self)


def win_conv_error(name: str) -> Tuple[int, float]:
    """``(round, err)`` from the window's convergence probe
    (:mod:`bluefog_tpu.lab`): the round counter and the latest debiased
    consensus-error sample.  ``(-1, nan)`` when ``BFTPU_LAB_PROBE`` is
    off or no win_update has run yet; ``err`` is NaN on the first
    probed round (a successive difference needs a predecessor)."""
    ctx = _ctx()
    _win(name)  # raise KeyError on unknown windows, like the other accessors
    probes = ctx.lab_probes
    if not probes or name not in probes:
        return (-1, float("nan"))
    pr = probes[name]
    pr.flush_pending()  # reads want the batched stragglers computed
    _drain_conv_journal(ctx, name, pr)
    return (pr.rounds, pr.last_err)


def win_conv_history(name: str) -> List[Tuple[int, float]]:
    """The window's full probe history, ``[(round, err), ...]`` oldest
    first (empty when the probe is off) — what the lab sweep driver
    fits a contraction rate to."""
    ctx = _ctx()
    _win(name)
    probes = ctx.lab_probes
    if not probes or name not in probes:
        return []
    pr = probes[name]
    pr.flush_pending()
    _drain_conv_journal(ctx, name, pr)
    return list(pr.history)


def get_win_version(name: str) -> Dict[int, int]:
    """{in_neighbor: deposit_count} for MY slots (reference
    ``bf.get_win_version`` [U], rank-local view)."""
    ctx = _ctx()
    win = _win(name)
    return {
        s: win.shm.read_version(win.slot_of[ctx.rank][s], src=s)
        for s in win.in_neighbors
    }


def push_sum_round(name: str, dst_weights: WeightDict = None):
    # -> np.ndarray, or the window's pytree for fused windows
    """One mass-conserving asynchronous push-sum round (Kempe et al.; the
    algorithm the reference's ``win_accumulate`` + associated-p machinery
    exists for — ``examples/pytorch_optimization.py`` push-sum loops [U]).

    Splits my (x, p) mass into equal shares over {self} ∪ out-neighbors
    (or per ``dst_weights``, which must sum with the kept share to 1),
    deposits the neighbor shares atomically, keeps my share, then drains my
    mailbox.  Ordering matters: the deposit must read (x, p) BEFORE the kept
    share is written back, else the ride-along p is double-scaled.  Under
    any interleaving Σx and Σp over all ranks' (exposed + slots) are
    invariant, so ``win_sync(name) / win_associated_p(name)`` converges to
    the exact global average with NO synchronization.

    Requires associated-p mode; enables it if off.
    """
    ctx = _ctx()
    if not ctx.associated_p:
        ctx.associated_p = True
    win = _win(name)
    cur = win.self_tensor
    p = win.p_self
    live_out = [d for d in win.out_neighbors if d not in ctx.dead]
    if dst_weights is None:
        share = 1.0 / (len(live_out) + 1)
        dst_weights = {d: share for d in live_out}
        keep = share
    else:
        # shares aimed at dead ranks would be silently skipped by the
        # degraded win_accumulate — keep them instead (mass conservation)
        keep = 1.0 - sum(w for d, w in dst_weights.items()
                         if d not in ctx.dead)
    win_accumulate(cur, name, dst_weights=dst_weights)
    win_set_exposed(name, cur * keep, p * keep)
    return win_update_then_collect(name)


def turn_on_win_ops_with_associated_p() -> None:
    _ctx().associated_p = True


def turn_off_win_ops_with_associated_p() -> None:
    _ctx().associated_p = False


def broadcast(tensor, root: int = 0, name: Optional[str] = None):
    """Collective broadcast via the exposed-tensor region: ``win_create``
    already exposes every rank's tensor (and ends with a barrier), so the
    body is just a one-sided read of root's exposure (reference
    ``bf.broadcast`` [U]; the islands use-case is the consistent-start
    idiom).  All ranks must call it in the same order."""
    ctx = _ctx()
    t = _to_host(tensor)
    if name is None:
        n = getattr(ctx, "_bcast_counter", 0)
        ctx._bcast_counter = n + 1
        name = f"_bcast_auto{n}"  # same order on all ranks -> same name
    if not win_create(t, name, zero_init=True):
        raise ValueError(
            f"broadcast window name {name!r} collides with a live window"
        )
    try:
        out, _, _ = _win(name).shm.read_exposed(root)
        # every rank reads BEFORE anyone tears the window down (the TCP
        # store vanishes at close)
        barrier()
    finally:
        win_free(name)
    return out


def broadcast_parameters(params, root: int = 0):
    """Broadcast a pytree of parameters from ``root`` — the consistent
    initialization idiom (reference ``bf.broadcast_parameters`` [U]).
    Leaves are packed into ONE flat buffer per dtype (like the WinPut
    optimizer's fusion), so the coordination cost is a couple of window
    lifecycles regardless of leaf count.  Returns the tree with every leaf
    replaced by root's value, preserving leaf container kind (numpy vs
    jax) and dtype."""
    import jax
    import jax.numpy as jnp

    flat, treedef = jax.tree_util.tree_flatten(params)
    by_dtype: Dict = {}
    for i, leaf in enumerate(flat):
        by_dtype.setdefault(np.asarray(leaf).dtype, []).append(i)
    for dt, idxs in sorted(by_dtype.items(), key=lambda kv: str(kv[0])):
        packed = np.concatenate(
            [np.asarray(flat[i], dtype=dt).ravel() for i in idxs]
        )
        got = broadcast(packed, root=root)
        off = 0
        for i in idxs:
            leaf = flat[i]
            size = int(np.asarray(leaf).size)
            arr = got[off:off + size].reshape(np.shape(leaf))
            if isinstance(leaf, np.ndarray):
                flat[i] = arr.astype(leaf.dtype, copy=False)
            else:
                flat[i] = jnp.asarray(arr, dtype=leaf.dtype)
            off += size
    return jax.tree_util.tree_unflatten(treedef, flat)


# ---------------------------------------------------------------------------
# asynchronous WinPut optimizer (the reference's flagship async training API)
# ---------------------------------------------------------------------------


class DistributedWinPutOptimizer:
    """Asynchronous decentralized optimizer over island windows — the
    reference's ``bf.DistributedWinPutOptimizer`` [U] with TRUE async
    semantics: after each local update the parameters are deposited into
    out-neighbors' windows (one-sided) and combined with whatever the
    in-neighbors have deposited so far — no barrier, ranks step at their
    own pace (SURVEY.md §3.4, §2.3 "Asynchronous decentralized DP").

    Wraps any optax ``GradientTransformation``.  Leaves are packed into one
    window per dtype (the reference's tensor-fusion idea: two window ops
    per step instead of two per leaf).  ``num_steps_per_communication``
    mirrors the reference's local-SGD cadence knob.

    ``overlap=True`` runs the host side of each gossip round (device→host
    staging, deposits, combine) on a background thread while the caller
    computes the next gradients; the combine is applied one step later
    (AD-PSGD-style staleness — the reference's background-thread
    semantics).  CONTRACT: the params returned by ``step`` are handed to
    the background thread by reference, so the caller must NOT donate
    them to a jitted function before the next ``step``/``finish`` call
    (donation deletes the buffers under the in-flight staging copy).

    Usage (inside an island process)::

        opt = islands.DistributedWinPutOptimizer(optax.sgd(0.1))
        state = opt.init(params)          # collective: creates the windows
        params, state = opt.step(params, grads, state)   # async gossip
    """

    def __init__(self, base_optimizer, window_prefix: str = "island_winput",
                 num_steps_per_communication: int = 1,
                 overlap: bool = False):
        import optax  # local import: islands itself is numpy-only otherwise

        del optax
        self.base = base_optimizer
        self.prefix = window_prefix
        self.k = int(num_steps_per_communication)
        self.overlap = bool(overlap)
        self._step_count = 0
        self._groups = None  # [(leaf_indices, shapes, sizes, np_dtype)]
        # in-flight gossip round: [(put_handle, update_handle)] per group,
        # resolved by the rank's progress engine (bluefog_tpu.progress)
        self._pending = None

    def _pack(self, flat, idxs, dtype):
        return np.concatenate(
            [np.asarray(flat[i], dtype=dtype).ravel() for i in idxs]
        ) if idxs else np.zeros((0,), dtype)

    def init(self, params):
        import jax

        flat, _ = jax.tree_util.tree_flatten(params)
        by_dtype: Dict = {}
        for i, leaf in enumerate(flat):
            by_dtype.setdefault(np.asarray(leaf).dtype, []).append(i)
        self._groups = []
        for g, (dt, idxs) in enumerate(
            sorted(by_dtype.items(), key=lambda kv: str(kv[0]))
        ):
            shapes = [tuple(np.shape(flat[i])) for i in idxs]
            sizes = [int(np.prod(s, dtype=np.int64)) for s in shapes]
            packed = self._pack(flat, idxs, dt)
            if not win_create(packed, f"{self.prefix}.{g}"):
                raise RuntimeError(
                    f"window '{self.prefix}.{g}' already exists — two "
                    "optimizers share window_prefix (pass a distinct "
                    "prefix) or a previous instance was not freed"
                )
            self._groups.append((idxs, shapes, sizes, dt))
        return self.base.init(params)

    def _unpack_into(self, flat, combined, idxs, shapes, sizes):
        """Scatter a combined window buffer back into the leaves, keeping
        each leaf's container kind (numpy vs jax) and EXACT dtype — a bare
        jnp.asarray would silently drop x64."""
        import jax.numpy as jnp

        off = 0
        for i, shape, size in zip(idxs, shapes, sizes):
            arr = combined[off:off + size].reshape(shape)
            leaf = flat[i]
            if isinstance(leaf, np.ndarray):
                flat[i] = arr.astype(leaf.dtype, copy=False)
            else:
                flat[i] = jnp.asarray(arr, dtype=leaf.dtype)
            off += size

    # -- overlap machinery (round-3 verdict #5 / SURVEY §3.3: the
    # reference's background thread lands MPI_Put while the device keeps
    # computing; here the rank's progress engine runs the whole host side
    # of a gossip round — device→host staging, shm deposits, mailbox
    # combine — while the caller's NEXT forward/backward executes on
    # device) ------------------------------------------------------------

    def _submit_gossip_round(self, leaf_refs):
        """Enqueue one gossip round on the progress engine.  The put
        payload is a THUNK over the (possibly still-computing) device
        arrays: the engine worker materializes it, blocking on device
        completion there — the main thread has already returned and
        dispatched more work.  Returns [(put_handle, update_handle)] per
        group; with the engine disabled the round runs inline and the
        handles come back already resolved (same one-step-stale apply)."""
        pairs = []
        for g, (idxs, _, _, dt) in enumerate(self._groups):
            name = f"{self.prefix}.{g}"
            ph = win_put_async(
                lambda idxs=idxs, dt=dt: self._pack(leaf_refs, idxs, dt),
                name)
            pairs.append((ph, win_update_async(name)))
        return pairs

    def _apply_pending(self, params):
        """Wait for the in-flight gossip round (if any) and swap its
        combined values into ``params`` — the one-step-stale combine of
        AD-PSGD-style overlap."""
        import jax

        if self._pending is None:
            return params
        pending, self._pending = self._pending, None
        flat, treedef = jax.tree_util.tree_flatten(params)
        for g, (idxs, shapes, sizes, _) in enumerate(self._groups):
            put_h, upd_h = pending[g]
            put_h.result()  # surface deposit failures, not just combine's
            self._unpack_into(flat, upd_h.result(), idxs, shapes, sizes)
        return jax.tree_util.tree_unflatten(treedef, flat)

    def finish(self, params):
        """Drain the overlap pipeline: apply any in-flight combine, then
        release the overlap machinery (``close``).  Call after the
        training loop (before settle/evaluation/checkpoint)."""
        params = self._apply_pending(params)
        self.close()
        return params

    def close(self):
        """Release the overlap machinery (idempotent): drain and discard
        any in-flight round so repeated optimizer init/teardown leaks
        neither threads nor queued ops.  The progress engine itself is
        rank-global and stays up for other callers; historically this
        optimizer owned a private ThreadPoolExecutor that ``finish``
        never shut down — that leak is what this method retires."""
        pending, self._pending = self._pending, None
        for put_h, upd_h in pending or ():
            for h in (put_h, upd_h):
                try:
                    h.result(timeout=60.0)
                except Exception:  # noqa: BLE001 - draining, not applying
                    pass

    def step(self, params, grads, state):
        import jax
        import optax

        # fail BEFORE the local update: an orphaned rank's step must be
        # retriable as a unit once merge_orphan() re-admits it
        _orphan_guard(_ctx(), "DistributedWinPutOptimizer.step")
        if self.overlap:
            # combine-then-adapt on the freshest gossip: the in-flight
            # round deposited LAST step's params while the caller computed
            # ``grads`` (at those same params) — apply it first so the
            # local update lands on the combined point
            params = self._apply_pending(params)
        updates, state = self.base.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        self._step_count += 1
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("optim.steps", optimizer="island_winput").inc()
        if self._step_count % self.k != 0:
            return params, state
        if reg.enabled:
            reg.counter("optim.gossip_rounds",
                        optimizer="island_winput").inc()
        flat, treedef = jax.tree_util.tree_flatten(params)
        if self.overlap:
            # hand the DEVICE refs to the progress engine: its worker
            # blocks on device completion, then lands the shm round while
            # the caller's next step computes
            self._pending = self._submit_gossip_round(flat)
            return params, state
        for g, (idxs, shapes, sizes, dt) in enumerate(self._groups):
            name = f"{self.prefix}.{g}"
            win_put(self._pack(flat, idxs, dt), name)
            combined = win_update(name)
            self._unpack_into(flat, combined, idxs, shapes, sizes)
        return jax.tree_util.tree_unflatten(treedef, flat), state

    def settle(self, params, rounds: int = 1):
        """Barriered pure-gossip rounds: deposit, barrier, combine, barrier
        — every combine sees THIS round's deposits from all neighbors, so
        stragglers align deterministically.  Call after the async training
        loop (all ranks, same ``rounds``); returns the combined params."""
        import jax

        params = self._apply_pending(params)  # drain the overlap pipeline
        for _ in range(rounds):
            flat, treedef = jax.tree_util.tree_flatten(params)
            for g, (idxs, _, _, dt) in enumerate(self._groups):
                win_put(self._pack(flat, idxs, dt), f"{self.prefix}.{g}")
            barrier()
            for g, (idxs, shapes, sizes, _) in enumerate(self._groups):
                combined = win_update(f"{self.prefix}.{g}")
                self._unpack_into(flat, combined, idxs, shapes, sizes)
            barrier()
            params = jax.tree_util.tree_unflatten(treedef, flat)
        return params

    def free(self):
        """Collective: release the optimizer's windows (drains the overlap
        pipeline first — a deposit must not race the teardown barrier; a
        failed round must not skip the collective win_free, or siblings
        would block forever in its barrier)."""
        self.close()
        for g in range(len(self._groups or [])):
            win_free(f"{self.prefix}.{g}")


# ---------------------------------------------------------------------------
# process spawner (used by bftpu-run --islands and the tests)
# ---------------------------------------------------------------------------


def _spawn_worker(fn, r, nranks, job, args, q, tolerant=False):
    try:
        init(r, nranks, job)
        out = fn(r, nranks, *args)
    except Exception as e:  # noqa: BLE001 - report to parent
        import traceback

        tr = _tracing.get_tracer()
        if tr.enabled:
            # flight dump BEFORE reporting: the parent may reap siblings
            # (and us) as soon as the failure lands on the queue
            tr.dump_flight(f"fatal:{type(e).__name__}")
            tr.write_buffer()
        q.put((r, False, f"{e}\n{traceback.format_exc()}"))
        return
    # report BEFORE the teardown barrier: if a sibling died, the barrier
    # never completes and the parent reaps us after collecting results
    q.put((r, True, out))
    if tolerant:
        # chaos runs: a sibling may have been killed, so the teardown
        # barrier can never complete — bound it and proceed to shutdown
        try:
            barrier(timeout=_degraded.op_deadline_s())
        except TypeError:
            barrier()  # transport without timed barriers
        except TimeoutError:
            pass
    else:
        barrier()
    shutdown(unlink=(r == 0))


# distinguishes concurrent spawn() calls from one parent: pid alone is not
# enough (same fn name + nranks would collide on shm job/barrier segments)
_spawn_counter = itertools.count()


def spawn(fn, nranks: int, job: Optional[str] = None, timeout: float = 120.0,
          args: Tuple = (), method: str = "spawn",
          allow_failures: bool = False) -> List:
    """Run ``fn(rank, size, *args)`` in ``nranks`` processes, each
    auto-``init``-ed; returns the per-rank return values in rank order.  The
    miniature in-process ``bfrun``: tests and notebooks use this, production
    uses ``bftpu-run --islands`` (one process per host).

    ``method`` is the multiprocessing start method: the default "spawn" is
    safe after the parent has touched JAX (fresh interpreter per island —
    and an island owning its own runtime is the semantics anyway); "fork" is
    faster for JAX-free parents.  Under "spawn", ``fn`` must be a picklable
    top-level function.  Raises on any child failure.

    ``allow_failures=True`` is the chaos-test mode: ranks that die (or
    never report) yield ``None`` in the result list instead of raising,
    and workers bound their teardown barrier so survivors exit cleanly
    when a sibling was killed.
    """
    import multiprocessing as mp

    job = job or (
        f"spawn{os.getpid()}_{next(_spawn_counter)}_"
        f"{getattr(fn, '__name__', 'fn')[:32]}"
    )
    mp_ctx = mp.get_context(method)
    q = mp_ctx.Queue()
    procs = [
        mp_ctx.Process(target=_spawn_worker,
                       args=(fn, r, nranks, job, args, q, allow_failures))
        for r in range(nranks)
    ]
    for p in procs:
        p.start()
    results: Dict[int, object] = {}
    failures = []
    deadline = time.monotonic() + timeout
    while len(results) + len(failures) < nranks:
        try:
            r, ok, out = q.get(timeout=min(
                0.25 if allow_failures else timeout,
                max(0.05, deadline - time.monotonic())))
        except Exception:
            if time.monotonic() < deadline:
                if allow_failures and not any(p.is_alive() for p in procs):
                    break  # killed ranks never report; everyone has exited
                continue
            if not allow_failures:
                failures.append("timeout waiting for island results")
            break
        if ok:
            results[r] = out
        else:
            failures.append(f"rank {r}: {out}")
    if failures or (allow_failures and len(results) < nranks):
        # siblings of a failed rank may be stuck at the teardown barrier
        for p in procs:
            if p.is_alive():
                p.terminate()
    for p in procs:
        p.join(timeout=10)
        if p.is_alive():
            p.terminate()
            failures.append("child did not exit")
    # reclaim segments on EVERY path (spawn's children are on this host by
    # definition): rank 0's collective unlink normally already ran, but a
    # child terminated mid-teardown — e.g. under heavy machine load the
    # 10s join expired — must not leave /dev/shm litter behind
    shm_native.unlink_all(job, [])
    if (failures or len(results) < nranks) and _tracing.tracing_dir():
        # post-mortem: SIGKILLed ranks never ran their own dump — convert
        # their mmap flight rings (page cache survives the process) to JSON
        _tracing.convert_flight_rings(job)
    if failures:
        raise RuntimeError("island spawn failed:\n" + "\n".join(failures))
    # under allow_failures, killed ranks never reported: yield None
    return [results.get(r) for r in range(nranks)]
