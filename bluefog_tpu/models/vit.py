"""Vision Transformer for decentralized image classification.

Model-family breadth beyond the reference (which ships only the CNNs of
its examples — LeNet in ``examples/pytorch_mnist.py``, ResNets in
``examples/pytorch_benchmark.py``/``pytorch_cifar10_resnet.py`` [U]): a
standard ViT-B/16-style classifier that drops into the same decentralized
train step (``training.make_decentralized_train_step``) and benchmark
harness as the ResNets.  TPU-first choices: bf16 compute with fp32
LayerNorm/softmax/head (MXU-friendly, numerically safe), patchify as a
single strided conv (one big MXU matmul), static shapes throughout.

Reuses the BERT encoder block (``transformer._EncoderBlock``) so the
attention math lives in one place.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from bluefog_tpu.models.transformer import _EncoderBlock

__all__ = ["ViT", "ViT_S16", "ViT_B16"]


class ViT(nn.Module):
    """Vision Transformer classifier ([CLS]-token pooling)."""

    num_classes: int = 1000
    patch_size: int = 16
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    dff: int = 3072
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, images, train: bool = False):
        del train  # no dropout/batch-stats: keeps the step signature shared
        B = images.shape[0]
        # patchify = one strided conv: [B, H/P, W/P, hidden]
        x = nn.Conv(
            self.hidden_size,
            kernel_size=(self.patch_size, self.patch_size),
            strides=(self.patch_size, self.patch_size),
            padding="VALID",
            dtype=self.dtype,
            name="patch_embed",
        )(images)
        x = x.reshape(B, -1, self.hidden_size)
        cls = self.param(
            "cls", nn.initializers.zeros, (1, 1, self.hidden_size)
        )
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, self.hidden_size))
                             .astype(self.dtype), x], axis=1)
        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (1, x.shape[1], self.hidden_size),
        )
        x = x + pos.astype(self.dtype)
        for _ in range(self.num_layers):
            x = _EncoderBlock(self.num_heads, self.dff, self.dtype)(x, None)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x[:, 0])


def ViT_S16(num_classes: int = 1000, **kw) -> ViT:
    """ViT-Small/16 (22M params)."""
    return ViT(num_classes=num_classes, hidden_size=384, num_layers=12,
               num_heads=6, dff=1536, **kw)


def ViT_B16(num_classes: int = 1000, **kw) -> ViT:
    """ViT-Base/16 (86M params)."""
    return ViT(num_classes=num_classes, **kw)
