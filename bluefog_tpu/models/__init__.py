from bluefog_tpu.models.lenet import LeNet5
from bluefog_tpu.models.resnet import ResNet, ResNet18, ResNet50

__all__ = ["LeNet5", "ResNet", "ResNet18", "ResNet50"]
