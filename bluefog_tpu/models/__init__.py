from bluefog_tpu.models.lenet import LeNet5
from bluefog_tpu.models.resnet import ResNet, ResNet18, ResNet50
from bluefog_tpu.models.vit import ViT, ViT_S16, ViT_B16

__all__ = [
    "LeNet5", "ResNet", "ResNet18", "ResNet50", "ViT", "ViT_S16", "ViT_B16",
]
