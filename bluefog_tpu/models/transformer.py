"""Transformer model family for the BASELINE configs: a BERT-style encoder
(config #3: async push-sum fine-tune) and a Llama-style decoder LM
(config #5: decentralized pretraining).

The reference has no attention code at all (SURVEY.md §2.3/§5.7) — these
models exist because the rebuild's tracked configs name BERT-base and
Llama-3-8B as gossip-training workloads; the architectures are the standard
public ones, written TPU-first: bfloat16 matmul compute with float32
accumulation/norms, static shapes, and optional *ring-attention sequence
parallelism* (``bluefog_tpu.parallel.ring_attention``) so long contexts
shard across the mesh — composing with the gossip data parallelism.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

__all__ = [
    "BertEncoder",
    "LlamaLM",
    "dense_attention",
    "chunked_softmax_cross_entropy",
]


def dense_attention(q, k, v, *, causal: bool, dtype=jnp.float32):
    """Plain softmax attention, [B, T, H, D] layout; fp32 softmax."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


# --------------------------------------------------------------------------
# BERT-style encoder
# --------------------------------------------------------------------------


class _EncoderBlock(nn.Module):
    num_heads: int
    dff: int
    dtype: Any

    @nn.compact
    def __call__(self, x, mask):
        d = x.shape[-1]
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        qkv = nn.DenseGeneral(
            (3, self.num_heads, d // self.num_heads), dtype=self.dtype
        )(h)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        scale = 1.0 / math.sqrt(q.shape[-1])
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
        if mask is not None:
            scores = jnp.where(mask[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(self.dtype)
        att = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        att = att.reshape(att.shape[:2] + (d,))
        x = x + nn.Dense(d, dtype=self.dtype)(att)
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = nn.Dense(self.dff, dtype=self.dtype)(h)
        h = nn.gelu(h)
        x = x + nn.Dense(d, dtype=self.dtype)(h)
        return x


class BertEncoder(nn.Module):
    """BERT-style encoder with a classification head (the push-sum
    fine-tuning workload of BASELINE config #3)."""

    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    dff: int = 3072
    max_len: int = 512
    num_classes: int = 2
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, attention_mask=None):
        B, T = input_ids.shape
        tok = nn.Embed(self.vocab_size, self.hidden_size, dtype=self.dtype)(input_ids)
        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(0.02),
            (self.max_len, self.hidden_size),
        )
        x = tok + pos[None, :T].astype(self.dtype)
        for _ in range(self.num_layers):
            x = _EncoderBlock(self.num_heads, self.dff, self.dtype)(x, attention_mask)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        pooled = jnp.tanh(nn.Dense(self.hidden_size, dtype=jnp.float32)(x[:, 0]))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(pooled)


# --------------------------------------------------------------------------
# Llama-style decoder LM
# --------------------------------------------------------------------------


def _rotary(x, positions):
    """Rotary position embedding; x: [B, T, H, D], positions: [T]."""
    d = x.shape[-1]
    half = d // 2
    freqs = 1.0 / (10000.0 ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [T, half]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


class RMSNorm(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones_init(), (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6) * scale).astype(
            self.dtype
        )


class _DecoderBlock(nn.Module):
    num_heads: int
    dff: int
    dtype: Any
    attention_fn: Optional[Callable] = None  # (q, k, v) -> out, e.g. ring attn
    num_kv_heads: Optional[int] = None  # grouped-query attention (GQA)

    @nn.compact
    def __call__(self, x, positions):
        d = x.shape[-1]
        hd = d // self.num_heads
        kvh = self.num_kv_heads or self.num_heads
        if self.num_heads % kvh:
            raise ValueError(
                f"num_heads {self.num_heads} not divisible by "
                f"num_kv_heads {kvh}")
        h = RMSNorm(dtype=self.dtype)(x)
        q = nn.DenseGeneral((self.num_heads, hd), use_bias=False, dtype=self.dtype)(h)
        # GQA (Ainslie et al. 2023; Llama-3's 8-kv-head layout): k/v
        # project to kvh heads (the parameter/KV-cache saving), then
        # repeat up to num_heads for the attention math — correct for
        # every attention_fn (flash/ring/dense) at the cost of not
        # exploiting the smaller kv in the kernel's memory traffic
        k = nn.DenseGeneral((kvh, hd), use_bias=False, dtype=self.dtype)(h)
        v = nn.DenseGeneral((kvh, hd), use_bias=False, dtype=self.dtype)(h)
        q = _rotary(q, positions)
        k = _rotary(k, positions)
        if kvh != self.num_heads:
            rep = self.num_heads // kvh
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        if self.attention_fn is not None:
            att = self.attention_fn(q, k, v)
        else:
            att = dense_attention(q, k, v, causal=True, dtype=self.dtype)
        # named for remat_policy="attn" (save these ~B*T*d bf16 outputs,
        # recompute everything else — see _remat_block)
        att = checkpoint_name(att, "attn_out")
        att = att.reshape(att.shape[:2] + (d,))
        x = x + nn.Dense(d, use_bias=False, dtype=self.dtype)(att)
        h = RMSNorm(dtype=self.dtype)(x)
        gate = nn.Dense(self.dff, use_bias=False, dtype=self.dtype)(h)
        up = nn.Dense(self.dff, use_bias=False, dtype=self.dtype)(h)
        x = x + nn.Dense(d, use_bias=False, dtype=self.dtype)(nn.silu(gate) * up)
        return x


def _remat_block(policy_name):
    """``nn.remat`` over the decoder block with a named checkpoint policy.

    ``None``/"" = recompute everything (minimum memory, +~2N flops/token);
    "dots" = ``jax.checkpoint_policies.checkpoint_dots`` (save matmul
    outputs: recompute shrinks to elementwise/norm passes at the cost of
    O(layers·B·T·dff) saved activations); "dots_no_batch" =
    ``checkpoint_dots_with_no_batch_dims``, the PaLM-style middle ground;
    "attn" = save only the named attention outputs (cheapest; measured
    slower than full remat on the benched v5e — see the dict comment).
    """
    if not policy_name:
        return nn.remat(_DecoderBlock)
    policies = {
        "dots": jax.checkpoint_policies.checkpoint_dots,
        "dots_no_batch":
            jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        # save ONLY the named attention outputs (~layers*B*T*d bf16 —
        # 0.7 GB at the 1b preset).  Hypothesis was sparing the backward
        # the flash-forward recompute; MEASURED 6.8% SLOWER than full
        # remat at 1b same-window (14.0k -> 13.1k tok/s, r4): the flash
        # custom-vjp regenerates its residuals regardless, so the saved
        # output only displaces fusion.  Kept as a knob for hardware
        # where attention recompute dominates differently.
        "attn": jax.checkpoint_policies.save_only_these_names("attn_out"),
    }
    return nn.remat(_DecoderBlock, policy=policies[policy_name])


class _ScannedDecoderBlock(nn.Module):
    """nn.scan body adapter: carry = activations, no per-step outputs."""

    num_heads: int
    dff: int
    dtype: Any
    attention_fn: Optional[Callable] = None
    remat: bool = False
    remat_policy: Optional[str] = None
    num_kv_heads: Optional[int] = None
    act_constraint: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, positions):
        cls = (_remat_block(self.remat_policy) if self.remat
               else _DecoderBlock)
        x = cls(self.num_heads, self.dff, self.dtype, self.attention_fn,
                self.num_kv_heads)(x, positions)
        if self.act_constraint is not None:
            x = self.act_constraint(x)
        return x, None


@jax.custom_vjp
def _bf16_matmul_f32_acc(x, kernel):
    """bf16-input matmul with f32 accumulation IN BOTH DIRECTIONS.

    Without the custom VJP, jax differentiates the forward's
    ``dot(bf16, bf16, preferred=f32)`` into backward dots that mix the
    f32 cotangent with the bf16 operands — dtype promotion turns those
    back into f32 matmuls AND re-casts the operands per use (measured:
    a naive bf16 head was 6% SLOWER end to end than the f32 head at
    134M).  Here the cotangent is rounded to bf16 (the standard
    mixed-precision training contract: every matmul operand is bf16,
    every accumulator f32), so fwd, dx, and dW all run 1-pass at full
    MXU rate, with dW emerging f32 for the optimizer.

    Measured verdict on the v5e (docs/STATUS.md): even with this VJP the
    bf16 head is NEUTRAL at 1B and −3% at 134M vs the f32 head — XLA's
    default-precision f32 matmul already sustains 153–166 TF/s (~80% of
    the bf16 rate, `benchmarks/peaks.py`), so the rate gain cannot pay
    for the per-chunk operand casts.  f32 stays the default; the option
    exists for hardware where true-f32 matmul is actually slow.
    """
    y, _ = _bf16_matmul_f32_acc_fwd(x, kernel)
    return y


def _bf16_matmul_f32_acc_fwd(x, kernel):
    xb = x.astype(jnp.bfloat16)
    kb = kernel.astype(jnp.bfloat16)
    y = jax.lax.dot_general(
        xb, kb, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y, (xb, kb)


def _bf16_matmul_f32_acc_bwd(res, g):
    xb, kb = res
    gb = g.astype(jnp.bfloat16)
    nbatch = gb.ndim - 1
    # dx[..., d] = g[..., v] @ kernel[d, v]^T
    dx = jax.lax.dot_general(
        gb, kb, (((nbatch,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    # dW[d, v] = sum over batch dims of x[..., d] * g[..., v]
    batch_axes = tuple(range(nbatch))
    dw = jax.lax.dot_general(
        xb, gb, ((batch_axes, batch_axes), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dx, dw


_bf16_matmul_f32_acc.defvjp(_bf16_matmul_f32_acc_fwd, _bf16_matmul_f32_acc_bwd)


def _head_matmul(x, kernel, dtype):
    """Logits matmul with f32 ACCUMULATION/output regardless of ``dtype``.

    ``dtype=float32`` reproduces the ``nn.Dense(dtype=f32)`` head (XLA
    lowers default-precision f32 matmul onto the MXU at 153–166 TF/s on
    the v5e — near the bf16 rate).  ``dtype=bfloat16`` rounds matmul
    operands — including the backward cotangent, via the custom VJP
    above — to bf16; accumulators and logits stay f32, so the
    downstream logsumexp/CE numerics are intact.  See the VJP docstring
    for the measured (neutral-to-negative on v5e) verdict.
    """
    if dtype == jnp.bfloat16:
        return _bf16_matmul_f32_acc(x, kernel)
    return jax.lax.dot_general(
        x.astype(dtype), kernel.astype(dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def chunked_softmax_cross_entropy(hidden, kernel, labels, num_chunks,
                                  dtype=jnp.float32, onehot_targets=False,
                                  kernel_constraint=None):
    """Next-token cross-entropy WITHOUT materializing the full logits.

    The LM-head logits ``[B, T, vocab]`` in f32 are the single biggest
    activation of a small-vocab 1B model (1.05 GB at B=4/T=2048/V=32k;
    its backward cotangent doubles that) and are flatly infeasible at
    Llama-3-8B's 128k vocab.  This computes the shifted-LM loss
    ``mean(CE(logits[:, :-1], labels[:, 1:]))`` as a ``lax.scan`` over
    ``num_chunks`` sequence chunks with a ``jax.checkpoint`` body: the
    forward keeps only the running (sum, count) scalars, and the backward
    recomputes each chunk's ``[B, T/num_chunks, vocab]`` logits on the
    fly — peak logits memory drops by ``num_chunks``× at the cost of one
    extra head matmul (2·B·T·d·V flops, ~2% of a 1B model's 6N step).

    Equivalent to the full-logits loss to f32 roundoff
    (`tests/test_training.py::test_llama_head_chunks_matches_full`).

    Args:
      hidden: ``[B, T, d]`` final hidden states (any float dtype; logits
        are computed in f32, matching the full-logits head).
      kernel: ``[d, vocab]`` f32 head weight.
      labels: ``[B, T]`` int token ids; position t is scored against
        ``labels[:, t+1]``, the final position is masked out.
      num_chunks: number of sequence chunks; must divide T.
      onehot_targets: extract the target logit as ``sum(logits * onehot(y))``
        instead of ``take_along_axis`` — numerically identical, but a
        reduction GSPMD partitions cleanly over a VOCAB-SHARDED head
        kernel, where the gather forces it to replicate (the 8B FSDP
        compile measured full-batch f32 activation gathers from exactly
        this; see ``LlamaLM.spmd_vocab``).
      kernel_constraint: applied to ``kernel`` INSIDE the scan body, once
        per chunk.  Under FSDP this must be the SHARDING-ONLY per-read
        marker (``fsdp_param_io_constraint(...).sharding_only`` — no
        grad-dtype cast, or every chunk cotangent would round and the
        scan transpose would sum in bf16) and must sit inside the body:
        with the marker only outside, the transpose's accumulator is laid
        out replicated — measured as the largest single temps item of the
        8B compile (f32[4096,128k] ≈ 2.1 GB per buffer).
    """
    B, T, _ = hidden.shape
    if T % num_chunks:
        raise ValueError(f"num_chunks {num_chunks} must divide T {T}")
    # shift the targets left so every chunk scores positions uniformly;
    # the pad at T-1 carries weight 0 (the last token predicts nothing)
    y = jnp.concatenate([labels[:, 1:], labels[:, :1]], axis=1)
    w = jnp.concatenate(
        [jnp.ones((B, T - 1), jnp.float32), jnp.zeros((B, 1), jnp.float32)],
        axis=1,
    )
    tc = T // num_chunks
    xs = hidden.reshape(B, num_chunks, tc, hidden.shape[-1]).transpose(1, 0, 2, 3)
    ys = y.reshape(B, num_chunks, tc).transpose(1, 0, 2)
    ws = w.reshape(B, num_chunks, tc).transpose(1, 0, 2)

    @jax.checkpoint
    def body(carry, xyw):
        xc, yc, wc = xyw
        k = kernel if kernel_constraint is None else kernel_constraint(kernel)
        logits = _head_matmul(xc, k, dtype)  # [B, tc, V] — the peak
        lse = jax.nn.logsumexp(logits, axis=-1)
        if onehot_targets:
            tgt = jnp.sum(
                logits * jax.nn.one_hot(yc, logits.shape[-1],
                                        dtype=logits.dtype), axis=-1)
        else:
            tgt = jnp.take_along_axis(logits, yc[..., None], axis=-1)[..., 0]
        # per-chunk outputs instead of a scalar carry: under shard_map a
        # plain-zeros carry init would mismatch the body's varying-axes
        # type (jax vma rules); stacked outputs inherit it automatically
        return carry, (((lse - tgt) * wc).sum(), wc.sum())

    _, (tots, cnts) = jax.lax.scan(body, (), (xs, ys, ws))
    return tots.sum() / cnts.sum()


class _HeadKernel(nn.Module):
    """Owns the LM-head weight at the SAME pytree path (``Dense_0/kernel``,
    same lecun-normal init) as the ``nn.Dense`` head it replaces, so
    checkpoints and equivalence tests are unaffected — but exposes the raw
    kernel so the chunked-loss path can matmul per chunk."""

    vocab_size: int

    @nn.compact
    def __call__(self, d):
        return self.param(
            "kernel", nn.initializers.lecun_normal(), (d, self.vocab_size),
            jnp.float32,
        )


class LlamaLM(nn.Module):
    """Llama-style decoder-only LM: RMSNorm, rotary, SwiGLU, no biases.

    ``attention_fn`` plugs in sequence-parallel ring attention; when set,
    ``positions`` must be the device's global positions (the caller knows
    its sequence shard offset).
    """

    vocab_size: int = 32000
    hidden_size: int = 512
    num_layers: int = 4
    num_heads: int = 8
    dff: int = 1376
    dtype: Any = jnp.bfloat16
    attention_fn: Optional[Callable] = None
    remat: bool = False  # rematerialize each block: activations O(layers·B·T·d) -> O(B·T·d)
    remat_policy: Optional[str] = None  # _remat_block: None|"dots"|"dots_no_batch"|"attn"
    scan_layers: bool = False  # lax.scan over stacked layers: O(1)-size HLO
    num_kv_heads: Optional[int] = None  # GQA: kv heads < query heads
    head_chunks: int = 0  # >1: chunked LM loss, never materializes full logits
    head_dtype: Any = jnp.float32  # bf16: 1-pass MXU head, f32 accumulation
    # vocab-dim-sharded deployment mode (FSDP/ZeRO with the embedding and
    # head kernels sharded over their vocab axis): route every vocab-indexed
    # op through matmuls/reductions — one-hot-matmul embedding and one-hot
    # target extraction — instead of take/take_along_axis gathers.  GSPMD
    # partitions dots and reductions over a sharded vocab axis cleanly; the
    # gather lowering replicates the INDICES' batch axis instead, which the
    # 8B FSDP compile measured as full-batch f32 activations on every
    # device (~2.5 GB/layer of temps) and zero reduce-scatters.  Same
    # params, same math (tests/test_training.py::test_llama_spmd_vocab_
    # matches_default); the one-hot matmul is also the MXU-native lookup.
    # BEHAVIORAL DIFFERENCE on out-of-range token ids (only): gather-based
    # ``take``/``take_along_axis`` CLAMP the id to the vocab edge, so a
    # corrupt id silently embeds as (and extracts the logit of) the last
    # vocab entry; ``one_hot`` ZEROES — an out-of-range id embeds as the
    # zero vector and contributes -logsumexp (no target logit) to the
    # loss.  Neither mode validates ids; both are garbage-in, but the
    # garbage differs, so a dataset bug can shift metrics when toggling
    # this flag.  In-range ids are bit-identical between modes.
    spmd_vocab: bool = False
    # applied to the [B, T, d] hidden states after the embedding and after
    # every decoder block — the standard GSPMD FSDP recipe pins the
    # ACTIVATION layout (batch-sharded) at block boundaries, because with
    # weights sharded on their big dims, unconstrained propagation resolves
    # each x@W toward the locally-cheaper tensor-parallel layout (gather
    # the small activations, keep the big weight sharded) and the whole
    # model silently goes batch-replicated (measured on the 8B FSDP
    # compile: ~2.5 GB/layer of replicated f32 temps, zero
    # reduce-scatters).  See parallel/zero.py:fsdp_act_constraint.
    act_constraint: Optional[Callable] = None
    # applied to the one-hot embedding operand (``spmd_vocab`` path).  An
    # FSDP caller pins it VOCAB-sharded (parallel/zero.py:
    # fsdp_onehot_constraint) so the embedding dot partitions on its
    # contracting dim — partial [B,T,d] products + one small reduce —
    # instead of GSPMD's default resolution, which all-gathers the f32
    # table (measured 2.1 GB/device on the 8B compile).
    onehot_constraint: Optional[Callable] = None
    # applied (via nn.map_variables in the scan path) to each layer's
    # PARAM SLICES inside the scan body.  An FSDP caller passes
    # "replicate over the shard axis" — an explicit gather marker on a
    # loop-VARIANT value, which XLA cannot hoist out of the while loop.
    # Without it GSPMD gathers the whole stacked leaf outside the loop
    # (tests/test_hlo_contract.py::test_scan_stacked_leaves_gather_whole
    # pinned this; at 8B that is ~11 GB of stacked bf16 FFN gathers).
    weight_constraint: Optional[Callable] = None

    @nn.compact
    def __call__(self, input_ids, positions=None, labels=None):
        B, T = input_ids.shape
        if positions is None:
            positions = jnp.arange(T)
        embed = nn.Embed(self.vocab_size, self.hidden_size, dtype=self.dtype)
        if self.spmd_vocab:
            table = embed.embedding
            if self.weight_constraint is not None:
                table = self.weight_constraint(table)
            oh = jax.nn.one_hot(input_ids, self.vocab_size, dtype=self.dtype)
            if self.onehot_constraint is not None:
                oh = self.onehot_constraint(oh)
            x = oh @ table.astype(self.dtype)
        else:
            x = embed(input_ids)
        if self.act_constraint is not None:
            x = self.act_constraint(x)
        if self.scan_layers:
            # params gain a leading [num_layers] axis; the compiled program
            # contains ONE block body instead of num_layers copies — at 1B+
            # scale the unrolled HLO overwhelms compile services
            body_cls = _ScannedDecoderBlock
            if self.weight_constraint is not None:
                wc = self.weight_constraint
                body_cls = nn.map_variables(
                    _ScannedDecoderBlock, "params",
                    trans_in_fn=partial(jax.tree_util.tree_map, wc),
                    trans_out_fn=lambda vs: vs,
                    mutable=True, init=True,
                )
            scan = nn.scan(
                body_cls,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=self.num_layers,
                in_axes=nn.broadcast,
            )
            x, _ = scan(
                self.num_heads, self.dff, self.dtype, self.attention_fn,
                self.remat, self.remat_policy, self.num_kv_heads,
                self.act_constraint,
            )(x, positions)
        else:
            # remat selection for the scan path lives in _ScannedDecoderBlock
            block_cls = (_remat_block(self.remat_policy) if self.remat
                         else _DecoderBlock)
            if self.weight_constraint is not None:
                block_cls = nn.map_variables(
                    block_cls, "params",
                    trans_in_fn=partial(jax.tree_util.tree_map,
                                        self.weight_constraint),
                    trans_out_fn=lambda vs: vs,
                    mutable=True, init=True,
                )
            for _ in range(self.num_layers):
                x = block_cls(
                    self.num_heads, self.dff, self.dtype, self.attention_fn,
                    self.num_kv_heads,
                )(x, positions)
                if self.act_constraint is not None:
                    x = self.act_constraint(x)
        x = RMSNorm(dtype=jnp.float32)(x)
        kernel = _HeadKernel(self.vocab_size, name="Dense_0")(self.hidden_size)
        if self.weight_constraint is not None:
            # full marker once, OUTSIDE any chunk loop: grad_dtype rounding
            # must be one-shot on the accumulated head-kernel cotangent
            kernel = self.weight_constraint(kernel)
        if labels is None:
            return _head_matmul(x, kernel, self.head_dtype)  # f32 logits
        if self.head_chunks > 1:
            # sharding-only pin per chunk (keeps the scan-transpose
            # accumulator sharded); the cast already happened above
            wc = self.weight_constraint
            if wc is not None and not hasattr(wc, "sharding_only"):
                raise ValueError(
                    "head_chunks > 1 with a custom weight_constraint "
                    "requires a .sharding_only attribute (the per-chunk "
                    "pin without the grad-dtype cast, cf. parallel/zero."
                    "fsdp_param_io_constraint): passing the full "
                    "constraint would re-round the head-kernel cotangent "
                    "once per chunk instead of once on the accumulated "
                    "gradient"
                )
            return chunked_softmax_cross_entropy(
                x, kernel, labels, self.head_chunks, dtype=self.head_dtype,
                onehot_targets=self.spmd_vocab,
                kernel_constraint=getattr(wc, "sharding_only", wc),
            )
        logits = _head_matmul(x, kernel, self.head_dtype)
        lse = jax.nn.logsumexp(logits[:, :-1], axis=-1)
        if self.spmd_vocab:
            tgt = jnp.sum(
                logits[:, :-1] * jax.nn.one_hot(
                    labels[:, 1:], self.vocab_size, dtype=logits.dtype),
                axis=-1)
        else:
            tgt = jnp.take_along_axis(
                logits[:, :-1], labels[:, 1:, None], axis=-1
            )[..., 0]
        return (lse - tgt).mean()
